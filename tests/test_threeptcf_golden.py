"""3PCF validated against the Slepian–Eisenstein C++ code's output.

The reference repository ships a 1000-particle sample and the zeta
multipoles computed by Daniel Eisenstein's independent C++
implementation (nbodykit/algorithms/tests/test_threeptcf.py:10-13,
data/threeptcf_sim_{data,result}.dat) — a cross-implementation oracle
for ell = 0..10. The files are read from the reference tree (they are
third-party test data, not framework code); the test skips when the
tree is absent.
"""

import os

import numpy as np
import pytest

from nbodykit_tpu.lab import ArrayCatalog
from nbodykit_tpu.algorithms.threeptcf import SimulationBox3PCF

DATA_DIR = '/root/reference/nbodykit/algorithms/tests/data'


@pytest.mark.slow
def test_sim_3pcf_matches_eisenstein_code():
    fdata = os.path.join(DATA_DIR, 'threeptcf_sim_data.dat')
    fres = os.path.join(DATA_DIR, 'threeptcf_sim_result.dat')
    if not (os.path.exists(fdata) and os.path.exists(fres)):
        pytest.skip("reference golden data not available")

    BoxSize = 400.0
    raw = np.loadtxt(fdata)
    pos = raw[:, :3] * BoxSize
    w = raw[:, 3]

    nbins = 8
    edges = np.linspace(0, 200.0, nbins + 1)
    ells = list(range(0, 11))

    cat = ArrayCatalog({'Position': pos, 'Weight': w},
                       BoxSize=BoxSize, comm=None)
    r = SimulationBox3PCF(cat, ells, edges, BoxSize=BoxSize,
                          weight='Weight')

    truth = np.empty((nbins, nbins, len(ells)))
    with open(fres) as ff:
        for line in ff:
            fields = line.split()
            i, j = int(fields[0]), int(fields[1])
            truth[i, j, :] = [float(x) for x in fields[2:]]
            truth[j, i, :] = truth[i, j, :]

    for i, ell in enumerate(ells):
        x = np.asarray(r.poles['corr_%d' % ell])
        np.testing.assert_allclose(
            x * (4 * np.pi) ** 2 / (2 * ell + 1), truth[..., i],
            rtol=1e-3, err_msg='mismatch for ell=%d' % ell)
