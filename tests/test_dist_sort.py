"""Distributed sort tests (mpsort-replacement; SURVEY.md §2.2.4)."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.parallel.runtime import cpu_mesh
from nbodykit_tpu.parallel.sort import dist_sort


@pytest.mark.parametrize("N", [1000, 4096, 10001])
def test_dist_sort_matches_numpy(N):
    rng = np.random.RandomState(N)
    keys = rng.randint(0, 1_000_000, N).astype(np.int64)
    vals = rng.standard_normal((N, 2))
    ks, vs = dist_sort(jnp.asarray(keys), jnp.asarray(vals), cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))
    # values ride with their keys
    uniq, cnts = np.unique(keys, return_counts=True)
    got = dict(zip(np.asarray(ks).tolist(),
                   np.asarray(vs).tolist()))
    for k in uniq[cnts == 1][:64]:
        i = int(np.flatnonzero(keys == k)[0])
        np.testing.assert_allclose(got[int(k)], vals[i], rtol=1e-12)


def test_dist_sort_skewed_fallback():
    keys = np.zeros(5000, dtype=np.int64)
    keys[-7:] = np.arange(7)
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))


def test_dist_sort_floats():
    rng = np.random.RandomState(1)
    keys = rng.standard_normal(3000)
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_allclose(np.asarray(ks), np.sort(keys))


def test_catalog_sort_multi_device():
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.parallel.runtime import use_mesh
    rng = np.random.RandomState(2)
    with use_mesh(cpu_mesh()):
        cat = ArrayCatalog({'Mass': rng.uniform(size=4096),
                            'x': rng.uniform(size=4096)})
        s = cat.sort('Mass')
    m = np.asarray(s['Mass'])
    assert np.all(np.diff(m) >= 0)


def test_dist_sort_fast_path_engages():
    # balanced input must take the distributed path (no fallback)
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 1 << 30, 4096).astype(np.int64)
    dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    assert dist_sort._last_dropped == 0


def test_dist_sort_skew_handled_by_capacity_retry():
    # heavy duplication overflows the first-attempt buckets; the
    # grown-capacity retry must absorb it without the single-device
    # fallback (which _last_dropped > 0 would indicate)
    rng = np.random.RandomState(11)
    keys = np.concatenate([np.full(3000, 42, dtype=np.int64),
                           rng.randint(0, 1 << 20, 1096)])
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))
    assert dist_sort._last_dropped == 0
