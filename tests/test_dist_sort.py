"""Distributed sort tests (mpsort-replacement; SURVEY.md §2.2.4)."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.parallel.runtime import cpu_mesh
from nbodykit_tpu.parallel.sort import dist_sort


@pytest.mark.parametrize("N", [1000, 4096, 10001])
def test_dist_sort_matches_numpy(N):
    rng = np.random.RandomState(N)
    keys = rng.randint(0, 1_000_000, N).astype(np.int64)
    vals = rng.standard_normal((N, 2))
    ks, vs = dist_sort(jnp.asarray(keys), jnp.asarray(vals), cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))
    # values ride with their keys
    uniq, cnts = np.unique(keys, return_counts=True)
    got = dict(zip(np.asarray(ks).tolist(),
                   np.asarray(vs).tolist()))
    for k in uniq[cnts == 1][:64]:
        i = int(np.flatnonzero(keys == k)[0])
        np.testing.assert_allclose(got[int(k)], vals[i], rtol=1e-12)


def test_dist_sort_skewed_fallback():
    keys = np.zeros(5000, dtype=np.int64)
    keys[-7:] = np.arange(7)
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))


def test_dist_sort_floats():
    rng = np.random.RandomState(1)
    keys = rng.standard_normal(3000)
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_allclose(np.asarray(ks), np.sort(keys))


def test_catalog_sort_multi_device():
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.parallel.runtime import use_mesh
    rng = np.random.RandomState(2)
    with use_mesh(cpu_mesh()):
        cat = ArrayCatalog({'Mass': rng.uniform(size=4096),
                            'x': rng.uniform(size=4096)})
        s = cat.sort('Mass')
    m = np.asarray(s['Mass'])
    assert np.all(np.diff(m) >= 0)


def test_dist_sort_multi_payload():
    # a list payload: every array rides with its key
    rng = np.random.RandomState(5)
    keys = rng.randint(0, 1 << 20, 3000).astype(np.int64)
    a = np.arange(3000, dtype=np.int64)
    b = rng.standard_normal(3000)
    ks, outs = dist_sort(jnp.asarray(keys),
                         [jnp.asarray(a), jnp.asarray(b)], cpu_mesh())
    order = np.argsort(keys, kind='stable')
    np.testing.assert_array_equal(np.asarray(ks), keys[order])
    np.testing.assert_array_equal(np.asarray(outs[0]), a[order])
    np.testing.assert_allclose(np.asarray(outs[1]), b[order])


def test_dist_sort_stability():
    # many duplicate keys: payload order among equals must match the
    # original order (the LSD multi-key passes depend on this)
    rng = np.random.RandomState(6)
    keys = rng.randint(0, 8, 4096).astype(np.int64)
    tag = np.arange(4096, dtype=np.int64)
    ks, tg = dist_sort(jnp.asarray(keys), jnp.asarray(tag), cpu_mesh())
    order = np.argsort(keys, kind='stable')
    np.testing.assert_array_equal(np.asarray(ks), keys[order])
    np.testing.assert_array_equal(np.asarray(tg), tag[order])


def test_sortable_key_orderings():
    from nbodykit_tpu.parallel.sort import sortable_key
    rng = np.random.RandomState(8)
    for arr in [rng.standard_normal(512),
                rng.standard_normal(512).astype('f4'),
                rng.randint(-1000, 1000, 512),
                rng.randint(0, 1 << 40, 512).astype(np.int64)]:
        u = np.asarray(sortable_key(jnp.asarray(arr)))
        np.testing.assert_array_equal(np.argsort(u, kind='stable'),
                                      np.argsort(arr, kind='stable'))
        r = np.asarray(sortable_key(jnp.asarray(arr), reverse=True))
        np.testing.assert_array_equal(
            np.asarray(arr)[np.argsort(r, kind='stable')],
            np.sort(arr)[::-1])


def test_catalog_sort_multikey_reverse_multi_device():
    # multi-key + reverse on an 8-device mesh matches numpy lexsort
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.parallel.runtime import use_mesh
    rng = np.random.RandomState(9)
    a = rng.randint(0, 16, 4096).astype(np.int64)
    b = rng.standard_normal(4096)
    with use_mesh(cpu_mesh()):
        cat = ArrayCatalog({'a': a, 'b': b})
        s_fwd = cat.sort(['a', 'b'])
        s_rev = cat.sort(['a', 'b'], reverse=True)
    order = np.lexsort((b, a))
    np.testing.assert_array_equal(np.asarray(s_fwd['a']), a[order])
    np.testing.assert_allclose(np.asarray(s_fwd['b']), b[order])
    np.testing.assert_array_equal(np.asarray(s_rev['a']),
                                  a[order][::-1])
    np.testing.assert_allclose(np.asarray(s_rev['b']), b[order][::-1])


def test_dist_sort_fast_path_engages():
    # balanced input must take the distributed path (no fallback)
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 1 << 30, 4096).astype(np.int64)
    dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    assert dist_sort._last_dropped == 0


def test_dist_sort_skew_handled_by_capacity_retry():
    # heavy duplication overflows the first-attempt buckets; the
    # grown-capacity retry must absorb it without the single-device
    # fallback (which _last_dropped > 0 would indicate)
    rng = np.random.RandomState(11)
    keys = np.concatenate([np.full(3000, 42, dtype=np.int64),
                           rng.randint(0, 1 << 20, 1096)])
    ks = dist_sort(jnp.asarray(keys), mesh=cpu_mesh())
    np.testing.assert_array_equal(np.asarray(ks), np.sort(keys))
    assert dist_sort._last_dropped == 0
