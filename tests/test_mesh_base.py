"""MeshSource/CatalogMesh feature tests: apply kinds, interlacing,
resampling, preview, options, species meshes."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu import set_options, _global_options
from nbodykit_tpu.lab import (ArrayMesh, UniformCatalog, LinearMesh,
                              FFTPower, CatalogMesh)


def test_apply_wavenumber_and_index():
    rng = np.random.RandomState(0)
    field = rng.standard_normal((8, 8, 8))
    mesh = ArrayMesh(field, BoxSize=16.0)

    # k=0 passthrough: zeroing all k>0 leaves the mean
    def lowpass(k, v):
        k2 = sum(ki ** 2 for ki in k)
        return jnp.where(k2 == 0, v, 0.0)

    out = mesh.apply(lowpass, kind='wavenumber',
                     mode='complex').compute(mode='real')
    np.testing.assert_allclose(np.asarray(out.value), field.mean(),
                               rtol=1e-6, atol=1e-8)

    # index kind in real space: mask the first x-row
    def kill_row(i, v):
        return jnp.where(i[0] == 0, 0.0, v)

    out2 = mesh.apply(kill_row, kind='index',
                      mode='real').compute(mode='real')
    v2 = np.asarray(out2.value)
    np.testing.assert_allclose(v2[0], 0.0)
    np.testing.assert_allclose(v2[1:], field[1:], rtol=1e-6)


def test_interlacing_preserves_low_k():
    # interlacing changes high-k aliasing (and suppresses the aliased
    # part of the self-pair shot noise there — a known effect), but the
    # low-k signal must be identical to the plain paint
    cat = UniformCatalog(nbar=5e-3, BoxSize=64.0, seed=5)
    r_plain = FFTPower(cat.to_mesh(Nmesh=32, resampler='cic',
                                   compensated=True), mode='1d')
    r_inter = FFTPower(cat.to_mesh(Nmesh=32, resampler='cic',
                                   compensated=True, interlaced=True),
                       mode='1d')
    k = r_plain.power['k']
    low = (k > 0) & (k < 0.4 * np.nanmax(k))
    p0 = r_plain.power['power'].real[low]
    p1 = r_inter.power['power'].real[low]
    np.testing.assert_allclose(p1, p0, rtol=0.05)
    # high-k stays within a sane band of the shot-noise plateau (the
    # two estimators differ there only by aliasing treatment)
    high = k > 0.8 * np.nanmax(k)
    sn = r_plain.attrs['shotnoise']
    assert abs(np.nanmean(r_inter.power['power'].real[high]) / sn
               - 1) < 0.3


def test_mesh_resample_down():
    # resampling a smooth field down preserves the large-scale modes
    mesh = LinearMesh(lambda k: 50.0 * np.exp(-(k * 4) ** 2),
                      BoxSize=64.0, Nmesh=32, seed=11, dtype='f8')
    full = mesh.compute(mode='real')
    down = mesh.compute(mode='real', Nmesh=16)
    assert down.value.shape == (16, 16, 16)
    np.testing.assert_allclose(float(down.value.mean()),
                               float(full.value.mean()), atol=1e-6)


def test_preview_axes():
    rng = np.random.RandomState(2)
    field = rng.standard_normal((8, 8, 8))
    mesh = ArrayMesh(field, BoxSize=8.0)
    f = mesh.compute(mode='real')
    proj = f.preview(axes=(0, 1))
    np.testing.assert_allclose(proj, field.sum(axis=2), rtol=1e-6)
    full = f.preview()
    np.testing.assert_allclose(full, field, rtol=1e-6)


def test_set_options_context():
    default = _global_options['resampler']
    with set_options(resampler='tsc'):
        assert _global_options['resampler'] == 'tsc'
    assert _global_options['resampler'] == default
    with pytest.raises(KeyError):
        set_options(not_an_option=1)


def test_catalog_mesh_selection_column():
    rng = np.random.RandomState(3)
    pos = rng.uniform(0, 16.0, size=(500, 3))
    from nbodykit_tpu.lab import ArrayCatalog
    sel = np.zeros(500, dtype=bool)
    sel[:200] = True
    cat = ArrayCatalog({'Position': pos, 'Selection': sel},
                       BoxSize=16.0)
    mesh = cat.to_mesh(Nmesh=8, resampler='cic')
    f = mesh.to_real_field(normalize=False)
    np.testing.assert_allclose(float(f.value.sum()), 200.0, rtol=1e-6)
    assert f.attrs['N'] == 200


def test_value_column_weighting():
    # painting Value*Weight: momentum-like field
    from nbodykit_tpu.lab import ArrayCatalog
    rng = np.random.RandomState(4)
    pos = rng.uniform(0, 16.0, size=(300, 3))
    vx = rng.standard_normal(300)
    cat = ArrayCatalog({'Position': pos, 'Value': vx}, BoxSize=16.0)
    mesh = cat.to_mesh(Nmesh=8, resampler='cic')
    f = mesh.to_real_field(normalize=False)
    np.testing.assert_allclose(float(f.value.sum()), vx.sum(),
                               rtol=1e-5)


def test_meshsource_preview_downsample():
    """MeshSource.preview(axes, Nmesh) gathers a downsampled
    projection (reference base/mesh.py:340-383): projecting the
    Nmesh-resampled field must equal resample-then-project."""
    import jax.numpy as jnp
    from nbodykit_tpu.lab import ArrayCatalog

    rng = np.random.RandomState(3)
    pos = rng.uniform(0, 100.0, (5000, 3))
    mesh = ArrayCatalog({'Position': pos}, BoxSize=100.0).to_mesh(
        Nmesh=16, resampler='cic', compensated=False)

    full = mesh.preview(axes=(0, 1))
    assert full.shape == (16, 16)
    # total mass is preserved by projection
    np.testing.assert_allclose(full.sum(), 16 ** 3, rtol=1e-4)

    down = mesh.preview(axes=(0, 1), Nmesh=8)
    assert down.shape == (8, 8)
    want = mesh.compute(mode='real', Nmesh=8).preview(axes=(0, 1))
    np.testing.assert_allclose(down, want, rtol=1e-6)


def test_meshfilter_protocol_and_compensations():
    """MeshFilter instances carry their own kind/mode through apply
    (reference filter protocol), and the named Compensate* kernels
    (reference source/mesh/catalog.py:380-470) equal the built-in
    compensated=True pipeline."""
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.filters import Gaussian, TopHat
    from nbodykit_tpu.base.mesh import MeshFilter
    from nbodykit_tpu.source.mesh.catalog import (CompensateTSC,
                                                  CompensateTSCShotnoise)

    assert isinstance(Gaussian(2.0), MeshFilter)
    rng = np.random.RandomState(7)
    pos = rng.uniform(0, 50.0, (4000, 3))
    cat = ArrayCatalog({'Position': pos}, BoxSize=50.0)

    # filter smooths: small-scale power drops, mean preserved
    mesh = cat.to_mesh(Nmesh=16, resampler='cic', compensated=False)
    raw = np.asarray(mesh.compute(mode='real').value)
    sm = np.asarray(mesh.apply(Gaussian(5.0)).compute(mode='real').value)
    np.testing.assert_allclose(sm.mean(), raw.mean(), rtol=1e-4)
    assert sm.std() < raw.std()
    th = np.asarray(mesh.apply(TopHat(5.0)).compute(mode='real').value)
    np.testing.assert_allclose(th.mean(), raw.mean(), rtol=1e-4)

    # reference naming: the non-interlaced compensated=True pipeline
    # uses the *Shotnoise (eq.20) kernel (get_compensation,
    # nbodykit/source/mesh/catalog.py:436-451), while the PLAIN name is
    # the pure sinc^p (eq.18) kernel used under interlacing
    m1 = cat.to_mesh(Nmesh=16, resampler='tsc', compensated=True)
    m2 = cat.to_mesh(Nmesh=16, resampler='tsc', compensated=False) \
        .apply(CompensateTSCShotnoise, kind='circular', mode='complex')
    np.testing.assert_allclose(np.asarray(m1.compute(mode='real').value),
                               np.asarray(m2.compute(mode='real').value),
                               rtol=1e-5, atol=1e-8)
    m3 = cat.to_mesh(Nmesh=16, resampler='tsc', compensated=True,
                     interlaced=True)
    m4 = cat.to_mesh(Nmesh=16, resampler='tsc', compensated=False,
                     interlaced=True) \
        .apply(CompensateTSC, kind='circular', mode='complex')
    np.testing.assert_allclose(np.asarray(m3.compute(mode='real').value),
                               np.asarray(m4.compute(mode='real').value),
                               rtol=1e-5, atol=1e-8)


def test_file_catalog_factory(tmp_path):
    """FileCatalogFactory builds a reader class from a FileType
    (reference source/catalog/file.py:232)."""
    from nbodykit_tpu.source.catalog.file import FileCatalogFactory
    from nbodykit_tpu.io.csv import CSVFile

    MyCSV = FileCatalogFactory('MyCSV', CSVFile)
    path = str(tmp_path / 'factory_test.csv')
    with open(path, 'w') as f:
        for i in range(10):
            f.write('%d %d %d\n' % (i, i * 2, i * 3))
    cat = MyCSV(path, names=['a', 'b', 'c'])
    assert cat.size == 10
    np.testing.assert_array_equal(np.asarray(cat['b']),
                                  2 * np.arange(10))
