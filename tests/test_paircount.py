"""Pair counting + 2PCF tests: brute-force oracles, analytic randoms,
Landy-Szalay consistency (reference analog:
algorithms/pair_counters/tests, paircount_tpcf/tests)."""

import numpy as np
import pytest

from nbodykit_tpu.lab import ArrayCatalog, UniformCatalog
from nbodykit_tpu.algorithms.pair_counters import (SimulationBoxPairCount,
                                                   SurveyDataPairCount)
from nbodykit_tpu.algorithms.paircount_tpcf import (SimulationBox2PCF,
                                                    SurveyData2PCF)


def brute_pairs(pos, box, edges, weights=None, periodic=True):
    N = len(pos)
    if weights is None:
        weights = np.ones(N)
    npairs = np.zeros(len(edges) - 1)
    wpairs = np.zeros(len(edges) - 1)
    for i in range(N):
        d = pos[i] - pos
        if periodic:
            d -= np.round(d / box) * box
        r = np.sqrt((d ** 2).sum(axis=-1))
        r[i] = -1.0  # exclude self
        dig = np.digitize(r, edges)
        for j in np.flatnonzero((dig >= 1) & (dig <= len(edges) - 1)
                                & (r >= 0)):
            npairs[dig[j] - 1] += 1
            wpairs[dig[j] - 1] += weights[i] * weights[j]
    return npairs, wpairs


def test_paircount_1d_brute_force():
    rng = np.random.RandomState(0)
    pos = rng.uniform(0, 40.0, size=(200, 3))
    w = rng.uniform(0.5, 2.0, size=200)
    cat = ArrayCatalog({'Position': pos, 'Weight': w}, BoxSize=40.0)
    edges = np.linspace(0.5, 8.0, 9)
    r = SimulationBoxPairCount('1d', cat, edges)
    want_n, want_w = brute_pairs(pos, 40.0, edges, w)
    np.testing.assert_allclose(r.pairs['npairs'], want_n)
    np.testing.assert_allclose(r.pairs['wnpairs'], want_w, rtol=1e-10)


def test_paircount_cross():
    rng = np.random.RandomState(1)
    pos1 = rng.uniform(0, 30.0, size=(100, 3))
    pos2 = rng.uniform(0, 30.0, size=(150, 3))
    c1 = ArrayCatalog({'Position': pos1}, BoxSize=30.0)
    c2 = ArrayCatalog({'Position': pos2}, BoxSize=30.0)
    edges = np.linspace(0.5, 6.0, 7)
    r = SimulationBoxPairCount('1d', c1, edges, second=c2)
    # brute force cross
    want = np.zeros(6)
    for i in range(100):
        d = pos1[i] - pos2
        d -= np.round(d / 30.0) * 30.0
        rr = np.sqrt((d ** 2).sum(axis=-1))
        h, _ = np.histogram(rr, bins=edges)
        want += h
    np.testing.assert_allclose(r.pairs['npairs'], want)


def test_paircount_2d_mu_bins():
    rng = np.random.RandomState(2)
    pos = rng.uniform(0, 30.0, size=(150, 3))
    cat = ArrayCatalog({'Position': pos}, BoxSize=30.0)
    edges = np.linspace(0.5, 6.0, 5)
    r = SimulationBoxPairCount('2d', cat, edges, Nmu=4)
    r1 = SimulationBoxPairCount('1d', cat, edges)
    # mu bins partition the pairs
    np.testing.assert_allclose(r.pairs['npairs'].sum(axis=-1),
                               r1.pairs['npairs'])


def test_paircount_projected():
    rng = np.random.RandomState(3)
    pos = rng.uniform(0, 30.0, size=(120, 3))
    cat = ArrayCatalog({'Position': pos}, BoxSize=30.0)
    edges = np.linspace(0.5, 5.0, 5)
    r = SimulationBoxPairCount('projected', cat, edges, pimax=5)
    # oracle: direct rp/pi histogram
    want = np.zeros((4, 5))
    for i in range(120):
        d = pos[i] - pos
        d -= np.round(d / 30.0) * 30.0
        dpi = np.abs(d[:, 2])
        rp = np.sqrt(d[:, 0] ** 2 + d[:, 1] ** 2)
        sel = (dpi < 5) & ~np.all(d == 0, axis=-1)
        h, _, _ = np.histogram2d(rp[sel], dpi[sel],
                                 bins=[edges, np.arange(6)])
        want += h
    np.testing.assert_allclose(r.pairs['npairs'], want)


def test_2pcf_natural_uniform_is_zero():
    # uniform box: xi ~ 0 (within Poisson noise of the pair counts)
    cat = UniformCatalog(nbar=1.2e-2, BoxSize=50.0, seed=42)
    edges = np.linspace(2.0, 10.0, 7)
    r = SimulationBox2PCF('1d', cat, edges)
    npairs = r.D1D2.pairs['npairs']
    sigma = 3.0 / np.sqrt(np.maximum(npairs / 2, 1))
    assert np.all(np.abs(r.corr['corr']) < np.maximum(3 * sigma, 0.1))


def test_2pcf_landy_szalay_matches_natural():
    # with uniform randoms in the same box, LS ~ natural estimator
    cat = UniformCatalog(nbar=2e-3, BoxSize=50.0, seed=1)
    ran = UniformCatalog(nbar=8e-3, BoxSize=50.0, seed=2)
    edges = np.linspace(2.0, 12.0, 6)
    nat = SimulationBox2PCF('1d', cat, edges)
    ls = SimulationBox2PCF('1d', cat, edges, randoms1=ran)
    np.testing.assert_allclose(ls.corr['corr'], nat.corr['corr'],
                               atol=0.15)


def test_2pcf_clustered_signal():
    # plant pairs at separation ~3: xi large in that bin
    rng = np.random.RandomState(5)
    centers = rng.uniform(5, 45, size=(150, 3))
    offsets = rng.standard_normal((150, 3))
    offsets = 3.0 * offsets / np.linalg.norm(offsets, axis=-1,
                                             keepdims=True)
    pos = np.concatenate([centers, centers + offsets]) % 50.0
    cat = ArrayCatalog({'Position': pos}, BoxSize=50.0)
    edges = np.array([1.0, 2.5, 3.5, 5.0])
    r = SimulationBox2PCF('1d', cat, edges)
    xi = r.corr['corr']
    assert xi[1] > 5 * max(abs(xi[0]), abs(xi[2]))


def test_2pcf_projected_wp():
    cat = UniformCatalog(nbar=2e-3, BoxSize=50.0, seed=7)
    edges = np.linspace(1.0, 10.0, 6)
    r = SimulationBox2PCF('projected', cat, edges, pimax=10)
    assert hasattr(r, 'wp')
    assert np.nanmax(np.abs(r.wp['corr'])) < 4.0  # ~0 for uniform


def test_wedges_to_poles():
    cat = UniformCatalog(nbar=3e-3, BoxSize=50.0, seed=8)
    edges = np.linspace(1.0, 10.0, 6)
    r = SimulationBox2PCF('2d', cat, edges, Nmu=10)
    poles = r.corr.to_poles([0, 2])
    assert 'corr_0' in poles.variables
    # monopole of uniform data ~ 0
    assert np.nanmax(np.abs(poles['corr_0'])) < 0.3


def test_survey_paircount_angular():
    rng = np.random.RandomState(9)
    N = 200
    ra = rng.uniform(0, 360, N)
    dec = np.degrees(np.arcsin(rng.uniform(-1, 1, N)))
    cat = ArrayCatalog({'RA': ra, 'DEC': dec})
    edges = np.array([1.0, 5.0, 10.0, 20.0])
    r = SurveyDataPairCount('angular', cat, edges)
    # oracle: full angular separation histogram
    from nbodykit_tpu.transform import SkyToUnitSphere
    v = np.asarray(SkyToUnitSphere(ra, dec))
    cosang = np.clip(v @ v.T, -1, 1)
    ang = np.degrees(np.arccos(cosang))
    iu = np.triu_indices(N, k=1)
    h, _ = np.histogram(ang[iu], bins=edges)
    np.testing.assert_allclose(r.pairs['npairs'], 2 * h)


def test_survey_2pcf_runs():
    from nbodykit_tpu.cosmology import Planck15
    rng = np.random.RandomState(10)
    N = 150
    data = ArrayCatalog({
        'RA': rng.uniform(10, 30, N),
        'DEC': rng.uniform(-10, 10, N),
        'Redshift': rng.uniform(0.4, 0.6, N)})
    Nr = 400
    ran = ArrayCatalog({
        'RA': rng.uniform(10, 30, Nr),
        'DEC': rng.uniform(-10, 10, Nr),
        'Redshift': rng.uniform(0.4, 0.6, Nr)})
    edges = np.linspace(5.0, 50.0, 6)
    r = SurveyData2PCF('1d', data, ran, edges, cosmo=Planck15)
    assert np.isfinite(r.corr['corr']).any()


def test_2pcf_angular_analytic_randoms():
    """Angular natural estimator with analytic spherical-cap RR vs a
    brute-force oracle (VERDICT r2 missing #4): uniform points on the
    sphere, xi(theta) ~ 0, and the analytic RR matches the exact
    brute-force expectation including bins past 60 degrees where the
    chord-based cap formula breaks down."""
    from nbodykit_tpu.algorithms.paircount_tpcf.estimators import \
        analytic_random_pairs

    rng = np.random.RandomState(11)
    N = 500
    z = rng.uniform(-1, 1, N)
    phi = rng.uniform(0, 2 * np.pi, N)
    s = np.sqrt(1 - z * z)
    pos = np.stack([s * np.cos(phi), s * np.sin(phi), z], axis=1)
    cat = ArrayCatalog({'Position': pos}, BoxSize=1.0)

    edges = np.array([2.0, 10.0, 30.0, 60.0, 90.0, 120.0])
    r = SimulationBox2PCF('angular', cat, edges)

    # exact cap-ring fractions integrate to the sphere
    frac = analytic_random_pairs('angular', np.array([0.0, 180.0]),
                                 2, None) / 2.0
    np.testing.assert_allclose(frac, [1.0], rtol=1e-12)

    # brute-force oracle: ordered-pair fraction per bin / cap fraction
    cosang = np.clip(pos @ pos.T, -1, 1)
    ang = np.degrees(np.arccos(cosang))
    iu = np.triu_indices(N, k=1)
    h, _ = np.histogram(ang[iu], bins=edges)
    fDD = 2.0 * h / (N * (N - 1.0))
    fRR = analytic_random_pairs('angular', edges, 2, None) / 2.0
    xi_oracle = fDD / fRR - 1.0
    np.testing.assert_allclose(np.asarray(r.corr['corr']), xi_oracle,
                               rtol=1e-6, atol=1e-6)
    # uniform sphere points: no angular clustering
    assert np.nanmax(np.abs(xi_oracle)) < 0.2
