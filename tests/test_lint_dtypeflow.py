"""NBK7xx — the interprocedural precision-flow analysis: positive and
negative fixtures for every rule (NBK701-704), the --explain CLI
surface, and the whole-tree regression pinning the committed baseline
to zero unexplained NBK7xx entries.

Pure-host AST tests except the CLI subprocess checks.
"""

import json
import os
import subprocess
import sys
import textwrap

from nbodykit_tpu import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_str(src, select=None, memory_config=None):
    return lint.lint_source(
        'fixture.py', textwrap.dedent(src),
        project_constants={'AXIS': 'dev'}, select=select,
        memory_config=memory_config)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# NBK701 — silently demoted collective payload


def test_nbk701_bf16_psum_consumed_raw_positive():
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def reduce_field(x):
        y = jax.lax.psum(x.astype(jnp.bfloat16), 'dev')
        return y * 2
    """, select=['NBK701'])
    assert codes(fs) == ['NBK701']
    assert 'bfloat16' in fs[0].message


def test_nbk701_rewidened_negative():
    # the deliberate bf16-on-the-wire/f32-in-registers contract: the
    # result is immediately re-widened — clean
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def reduce_field(x):
        y = jax.lax.psum(x.astype(jnp.bfloat16),
                         'dev').astype(jnp.float32)
        return y * 2
    """, select=['NBK701'])
    assert codes(fs) == []


def test_nbk701_f32_payload_negative():
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def reduce_field(x):
        y = jax.lax.psum(x.astype(jnp.float32), 'dev')
        return y * 2
    """, select=['NBK701'])
    assert codes(fs) == []


def test_nbk701_interprocedural_payload_fact():
    # the narrow fact is born in a HELPER and flows through its return
    # summary into the collective's payload — the lattice is
    # interprocedural, not per-statement
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def compress(x):
        return x.astype(jnp.bfloat16)

    def reduce_field(x):
        small = compress(x)
        return jax.lax.psum(small, 'dev')
    """, select=['NBK701'])
    assert codes(fs) == ['NBK701']


def test_nbk701_a2a_bf16_rewidened_negative():
    # the dfft._a2a production idiom (ISSUE 13): all_to_all ships the
    # stacked re/im planes as bf16 and the literal astype on the
    # collective re-widens on arrival — bf16-on-wire/f32-out is the
    # documented contract, not a silent demotion
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def exchange(y, nsplit):
        planes = jnp.stack([jnp.real(y), jnp.imag(y)])
        planes = planes.astype(jnp.bfloat16)
        wide = jax.lax.all_to_all(
            planes, 'dev', 2, 1,
            tiled=False).astype(jnp.float32)
        return jax.lax.complex(wide[0], wide[1]).astype(y.dtype)
    """, select=['NBK701'])
    assert codes(fs) == []


def test_nbk701_a2a_bf16_consumed_raw_positive():
    # the same wire compression WITHOUT the re-widen: the narrow
    # payload leaks into downstream arithmetic — flagged
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def exchange(y, nsplit):
        planes = jnp.stack([jnp.real(y), jnp.imag(y)])
        planes = planes.astype(jnp.bfloat16)
        wide = jax.lax.all_to_all(planes, 'dev', 2, 1, tiled=False)
        return jax.lax.complex(wide[0], wide[1])
    """, select=['NBK701'])
    assert codes(fs) == ['NBK701']


# ---------------------------------------------------------------------------
# NBK702 — uncompensated narrow accumulation


def test_nbk702_bf16_accumulator_positive():
    fs = lint_str("""
    import jax.numpy as jnp

    def accumulate(xs):
        acc = jnp.zeros((8,), jnp.bfloat16)
        for x in xs:
            acc += x
        return acc
    """, select=['NBK702'])
    assert codes(fs) == ['NBK702']
    assert 'acc' in fs[0].message


def test_nbk702_f32_accumulator_negative():
    fs = lint_str("""
    import jax.numpy as jnp

    def accumulate(xs):
        acc = jnp.zeros((8,), jnp.float32)
        for x in xs:
            acc += x
        return acc
    """, select=['NBK702'])
    assert codes(fs) == []


def test_nbk702_compensated_idiom_negative():
    # the two-sum hi/lo residual split (ops/histogram.py's idiom):
    # narrow accumulation WITH compensation is the documented
    # technique, not a bug
    fs = lint_str("""
    import jax.numpy as jnp

    def accumulate(xs):
        acc = jnp.zeros((8,), jnp.bfloat16)
        err = jnp.zeros((8,), jnp.float32)
        for x in xs:
            acc += x
            lo = x - x.astype(jnp.bfloat16).astype(jnp.float32)
            err = err + lo
        return acc, err
    """, select=['NBK702'])
    assert codes(fs) == []


def test_nbk702_scatter_add_accumulator_positive():
    fs = lint_str("""
    import jax.numpy as jnp

    def deposit(idx, w):
        mesh = jnp.zeros((64, 64), jnp.bfloat16)
        mesh = mesh.at[idx].add(w)
        return mesh
    """, select=['NBK702'])
    assert codes(fs) == ['NBK702']


def test_nbk702_two_sum_deposit_negative():
    # the ops/paint.py bf16 replica idiom (ISSUE 13): each weight is
    # split hi/lo by a two-sum (lo assigned from a Sub of the
    # round-tripped hi) and both halves deposited narrow; the residual
    # makes the narrow accumulation compensated — clean
    fs = lint_str("""
    import jax.numpy as jnp

    def deposit(lin, w):
        flat = jnp.zeros(64, jnp.bfloat16)
        w32 = w.astype(jnp.float32)
        hi = w32.astype(jnp.bfloat16)
        lo = w32 - hi.astype(jnp.float32)
        flat = flat.at[lin].add(hi)
        flat = flat.at[lin].add(lo.astype(jnp.bfloat16))
        return flat
    """, select=['NBK702'])
    assert codes(fs) == []


def test_nbk702_narrow_deposit_no_residual_positive():
    # same deposit WITHOUT the lo residual: uncompensated narrow
    # scatter-accumulation — flagged
    fs = lint_str("""
    import jax.numpy as jnp

    def deposit(lin, w):
        flat = jnp.zeros(64, jnp.bfloat16)
        hi = w.astype(jnp.bfloat16)
        flat = flat.at[lin].add(hi)
        return flat
    """, select=['NBK702'])
    assert codes(fs) == ['NBK702']


# ---------------------------------------------------------------------------
# NBK703 — mixed-dtype arithmetic promoting a mesh-sized operand


def test_nbk703_bf16_field_times_f32_positive():
    fs = lint_str("""
    import jax.numpy as jnp

    def combine(pm, pos, w):
        field = pm.paint(pos)
        fb = field.astype(jnp.bfloat16)
        w32 = w.astype(jnp.float32)
        return fb * w32
    """, select=['NBK703'])
    assert codes(fs) == ['NBK703']
    assert 'bfloat16' in fs[0].message
    assert 'float32' in fs[0].message


def test_nbk703_same_width_negative():
    fs = lint_str("""
    import jax.numpy as jnp

    def combine(pm, pos, w):
        field = pm.paint(pos)
        f32 = field.astype(jnp.float32)
        w32 = w.astype(jnp.float32)
        return f32 * w32
    """, select=['NBK703'])
    assert codes(fs) == []


def test_nbk703_chunk_sized_narrow_negative():
    # the narrow side is NOT mesh-sized: the promotion is cheap and
    # the rule stays silent
    fs = lint_str("""
    import jax.numpy as jnp

    def combine(w, v):
        wb = w.astype(jnp.bfloat16)
        v32 = v.astype(jnp.float32)
        return wb * v32
    """, select=['NBK703'])
    assert codes(fs) == []


def test_nbk703_readout_rewiden_first_negative():
    # the pmesh._readout_impl contract (ISSUE 13): a bf16-stored field
    # is re-widened ONCE at entry, so all downstream interpolation
    # arithmetic is same-width f32 — no mesh-sized mixed promotion
    fs = lint_str("""
    import jax.numpy as jnp

    def readout(pm, pos, w):
        field = pm.paint(pos)
        real = field.astype(jnp.float32)
        w32 = w.astype(jnp.float32)
        return real * w32
    """, select=['NBK703'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# NBK704 — the value-range upgrade of the int32 flat-index rule


def test_nbk704_unbounded_chain_positive():
    fs = lint_str("""
    import jax.numpy as jnp

    def flatten(ci, n1, n2):
        flat = (ci[:, 0].astype(jnp.int32) * n1 + ci[:, 1]) * n2
        return flat + ci[:, 2]
    """, select=['NBK704'])
    assert codes(fs) == ['NBK704']
    assert 'no derivable static bound' in fs[0].message


def test_nbk704_dtype_fact_gate_positive():
    # the chain statement never SAYS int32 — the fact arrives through
    # the lattice from the astype two statements up.  The lexical
    # NBK302 gate would miss this entirely.
    fs = lint_str("""
    import jax.numpy as jnp

    def flatten(ci, n1, n2):
        idx = ci.astype(jnp.int32)
        lin = (idx * n1 + 1) * n2
        return lin
    """, select=['NBK704'])
    assert codes(fs) == ['NBK704']


def test_nbk704_provable_bound_negative():
    # N0/N1/N2 resolve to the declared --nmesh: the product is provably
    # inside int32, so the chain needs no guard and no pragma — the
    # upgrade over the shape-blind NBK302
    config = lint.make_config(128)
    fs = lint_str("""
    import jax.numpy as jnp

    def flat_cells(i):
        lin = i.astype(jnp.int32) + (N0 * N1 + N1) * N2
        return lin
    """, select=['NBK704'], memory_config=config)
    assert codes(fs) == []


def test_nbk704_provable_overflow_positive():
    # same shape, nmesh=4096: 4096^3 > 2**31 — the verdict hardens
    # from 'unbounded' to a definite overflow
    config = lint.make_config(4096)
    fs = lint_str("""
    import jax.numpy as jnp

    def flat_cells(i):
        lin = i.astype(jnp.int32) + (N0 * N1 + N1) * N2
        return lin
    """, select=['NBK704'], memory_config=config)
    assert codes(fs) == ['NBK704']
    assert 'guaranteed overflow' in fs[0].message


def test_nbk704_trace_time_guard_negative():
    # the paint.py pattern: an iinfo(int32) bound check that raises at
    # trace time makes the unbounded chain audited-safe
    fs = lint_str("""
    import numpy as np
    import jax.numpy as jnp

    def flatten(ci, n1, n2):
        if n1 * n2 > np.iinfo(np.int32).max:
            raise ValueError('int32 overflow')
        idx = ci.astype(jnp.int32)
        return (idx * n1 + 1) * n2
    """, select=['NBK704'])
    assert codes(fs) == []


def test_nbk704_non_i32_chain_negative():
    # no int32 fact anywhere near the chain: NBK704 has no opinion
    fs = lint_str("""
    def flatten(ci, n1, n2):
        return (ci * n1 + 1) * n2
    """, select=['NBK704'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# the --explain surface


def test_explain_renders_every_rule():
    from nbodykit_tpu.lint import explain, report
    for code in report.RULES:
        text = explain.render_explanation(code)
        assert code in text
        assert 'flagged:' in text
        assert 'fix pattern:' in text


def test_explain_cli():
    out = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint',
         '--explain', 'NBK704'],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert 'NBK704' in out.stdout
    assert 'flagged:' in out.stdout


def test_explain_cli_unknown_code():
    out = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint',
         '--explain', 'NBK999'],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
    assert 'NBK999' in out.stderr


# ---------------------------------------------------------------------------
# whole-tree regression


def test_tree_has_no_unexplained_nbk7_findings():
    # the full-tree NBK7xx sweep was triaged in-PR (two real fixes:
    # the paint.py _offset_terms trace-time guard and the subvolumes
    # grid guard; the rest carry audited pragmas).  The committed
    # baseline must hold ZERO grandfathered NBK7xx entries and a fresh
    # run must come back clean.
    with open(os.path.join(REPO, 'lint_baseline.json')) as f:
        baseline = json.load(f)
    assert not [e for e in baseline.get('findings', [])
                if e['code'].startswith('NBK7')]
    out = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--select', 'NBK7',
         os.path.join(REPO, 'nbodykit_tpu'),
         os.path.join(REPO, 'bench.py')],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
