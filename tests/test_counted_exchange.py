"""Two-pass counted exchange: eager count (pass 1) feeding a traced
paint's static all_to_all capacity (pass 2). Reference analog: the MPI
all-to-allv counts in pmesh.domain.GridND.decompose, consumed at
nbodykit/source/catalog ... mesh/catalog.py:271-284."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from nbodykit_tpu.pmesh import ParticleMesh, memory_plan
from nbodykit_tpu.parallel.runtime import cpu_mesh
from nbodykit_tpu.parallel.exchange import counted_capacity


def test_counted_capacity_is_exact_bound():
    nproc = 8
    rng = np.random.RandomState(5)
    dest = jnp.asarray(rng.randint(0, nproc, 10000), jnp.int32)
    cap = counted_capacity(nproc, dest, slack=1.0)
    # recompute the true max per (src, dst) pair under even sharding
    per = -(-10000 // nproc)
    src = np.arange(10000) // per
    pair_counts = np.bincount(src * nproc + np.asarray(dest),
                              minlength=nproc * nproc)
    assert cap >= pair_counts.max()
    assert cap <= pair_counts.max() + 8 + 1   # slack=1.0 + headroom


def test_traced_paint_with_counted_capacity_matches_eager():
    comm = cpu_mesh()
    pm = ParticleMesh(32, 100.0, dtype='f4', comm=comm)
    rng = np.random.RandomState(3)
    pos = jnp.asarray(rng.uniform(0, 100.0, (5000, 3)).astype('f4'))
    cap = pm.exchange_capacity(pos)
    # the counted bound must beat the traced ceil(N/P) fallback
    assert cap < 5000 // pm.nproc

    f_eager = pm.paint(pos, 1.0, resampler='cic')

    @jax.jit
    def step(p):
        return pm.paint(p, 1.0, resampler='cic', capacity=cap,
                        return_dropped=True)

    f_traced, dropped = step(pos)
    assert int(dropped) == 0
    np.testing.assert_allclose(np.asarray(f_traced),
                               np.asarray(f_eager), rtol=1e-6,
                               atol=1e-6)


def test_shifted_routing_counts_differently():
    """Interlaced paints route by the half-cell-shifted grid; the
    count must honor the same shift (round-5 review finding)."""
    comm = cpu_mesh()
    pm = ParticleMesh(32, 32.0, dtype='f4', comm=comm)
    # one source shard (40 slots) holds 20 particles at x=4.25 (slab 1)
    # and 20 at x=3.9 (slab 0): under shift=0.5 the first group routes
    # by x-0.5=3.75 -> slab 0 too, merging both into ONE (src, dst)
    # pair of 40 — the count must see it
    pos = np.zeros((320, 3), 'f4')
    pos[:20, 0] = 4.25
    pos[20:40, 0] = 3.9
    pos[40:, 0] = np.random.RandomState(0).uniform(8.0, 31.9, 280)
    pos[:, 1:] = np.random.RandomState(1).uniform(0, 32, (320, 2))
    cap0 = pm.exchange_capacity(jnp.asarray(pos), slack=1.0, shift=0.0)
    cap5 = pm.exchange_capacity(jnp.asarray(pos), slack=1.0, shift=0.5)
    assert cap5 >= 40 + 8
    assert cap5 > cap0  # merged routing -> strictly larger count


def test_memory_plan_counted_vs_ceil():
    pc = memory_plan(2048, int(1e9), 16)
    pf = memory_plan(2048, int(1e9), 16, exchange='ceil')
    assert pc['fits'] and not pf['fits']
    assert pc['exchange_buffers'] < pf['exchange_buffers'] / 5


def test_mxu_traced_requires_return_dropped():
    from nbodykit_tpu import set_options
    pm = ParticleMesh(16, 16.0, dtype='f4', comm=None)
    pos = jnp.asarray(np.random.RandomState(1)
                      .uniform(0, 16.0, (100, 3)).astype('f4'))
    with set_options(paint_method='mxu'):
        with pytest.raises(ValueError, match="return_dropped"):
            jax.jit(lambda p: pm.paint(p, 1.0))(pos)
        f, dropped = jax.jit(
            lambda p: pm.paint(p, 1.0, return_dropped=True))(pos)
        assert int(dropped) == 0
        assert abs(float(f.sum()) - 100) < 1e-3
