"""Reference-parity tests for the Cosmology class surface.

Ported from ``nbodykit/cosmology/tests/test_cosmology.py`` — the same
behaviors (parameter aliases, deprecated syntax, conflicts,
immutability, density relations, astropy-compat names, pickling), with
engine-backed spectra checks in the slow tier.
"""

import pickle
import warnings

import numpy as np
import pytest
from numpy.testing import assert_allclose, assert_array_equal

from nbodykit_tpu.cosmology import Cosmology, Planck15, WMAP9


def test_old_Omega_syntax():
    c1 = Cosmology(Omega_b=0.04)
    c2 = Cosmology(Omega0_b=0.04)
    assert c1.Omega0_b == c2.Omega0_b

    c1 = Cosmology(T_cmb=2.7)
    c2 = Cosmology(T0_cmb=2.7)
    assert c1.T0_cmb == c2.T0_cmb

    c1 = Cosmology(Omega0_k=0.05)
    c2 = Cosmology(Omega_k=0.05)
    assert c1.Omega0_k == c2.Omega0_k

    c1 = Cosmology(Omega0_lambda=0.7)
    c2 = Cosmology(Omega_lambda=0.7)
    c3 = Cosmology(Omega0_Lambda=0.7)
    assert c1.Omega0_lambda == c2.Omega0_lambda
    assert c1.Omega0_lambda == c3.Omega0_lambda


def test_deprecated_init():
    with pytest.warns(FutureWarning):
        c1 = Cosmology(H0=67.6, Om0=0.31, flat=True)
        c2 = Cosmology(H0=67.6, Om0=0.31, Ode0=0.7, flat=False, w0=-0.9)

    with pytest.raises(Exception):
        Cosmology(h=0.7, flat=True)

    with pytest.raises(Exception):
        Cosmology(0.7, flat=True)

    with pytest.raises(Exception):
        Cosmology(H0=70., flat=True, h=0.7)

    assert_allclose(c1.h, 0.676)
    assert_allclose(c2.h, 0.676)
    assert_allclose(c1.Om0, 0.31)
    assert_allclose(c2.Om0, 0.31)
    assert_allclose(c1.Ok0, 0.)
    assert_allclose(c2.Ode0, 0.7)
    assert_allclose(c2.w0_fld, -0.9)


def test_conflicts():
    with pytest.raises(Exception):
        Cosmology(h=0.7, H0=70)
    with pytest.raises(Exception):
        Cosmology(Omega0_b=0.04, Omega_b=0.04)
    with pytest.raises(Exception):
        Cosmology(Omega0_b=0.04, omega_b=0.02)


def test_unknown_params():
    with pytest.warns(UserWarning):
        Cosmology(unknown_paramter=100.)


def test_bad_input():
    with pytest.raises(ValueError):
        Cosmology(gauge='BAD')
    with pytest.raises(ValueError):
        Cosmology(Omega_Lambda=0.7, w0_fld=-0.9)


def test_massive_neutrinos():
    c = Cosmology(m_ncdm=0.6)
    assert c.N_ncdm == 1
    with pytest.raises(ValueError):
        Cosmology(m_ncdm=[0.6, 0.])


def test_no_massive_neutrinos():
    c = Cosmology(m_ncdm=None)
    assert c.has_massive_nu is False
    # N_ur default switches to 3.046 with no massive species
    assert_allclose(c.N_ur, 3.046)


def test_N_ur_inference():
    # reference docstring: 1 massive nu + default T_ncdm -> N_ur=2.0328
    c = Cosmology()
    assert c.N_ncdm == 1
    assert_allclose(c.N_ur, 2.0328)
    assert_allclose(c.Neff, 3.046, rtol=1e-2)


def test_from_file(tmp_path):
    f = tmp_path / "par.ini"
    f.write_text("H0=70\nomega_b = 0.0266691\nomega_cdm = 0.110616\n"
                 "T_cmb=2.7255\n")
    c = Cosmology.from_file(str(f))
    assert_allclose(c.Omega0_b * c.h ** 2, 0.0266691)
    assert_allclose(c.Omega0_cdm * c.h ** 2, 0.110616)

    c2 = c.clone(Omega0_b=0.04)
    assert_allclose(c2.Omega0_b, 0.04)

    s = pickle.dumps(c)
    c1 = pickle.loads(s)
    assert_allclose(c.Omega0_cdm, c1.Omega0_cdm)
    assert_allclose(c.Omega0_b, c1.Omega0_b)


def test_clone():
    c = Cosmology(gauge='synchronous')
    c2 = c.clone(Omega0_b=0.04)
    assert_allclose(c2.Omega0_b, 0.04)
    c2 = c2.clone()
    assert_allclose(c2.Omega0_b, 0.04)


def test_cosmology_sane():
    c = Cosmology(gauge='synchronous')
    assert_allclose(c.Omega_cdm(0), c.Omega0_cdm)
    assert_allclose(c.Omega_g(0), c.Omega0_g)
    assert_allclose(c.Omega_b(0), c.Omega0_b)
    assert_allclose(c.Omega_ncdm(0), c.Omega0_ncdm)
    assert_allclose(c.Omega_ur(0), c.Omega0_ur)
    assert_allclose(c.Omega_ncdm(0), c.Omega0_ncdm_tot)
    assert_allclose(c.Omega_pncdm(0), c.Omega0_pncdm)
    assert_allclose(c.Omega_m(0), c.Omega0_m)
    assert_allclose(c.Omega_r(0), c.Omega0_r)

    # total density in 1e10 Msun/h units (reference golden value)
    assert_allclose(c.rho_crit(0), 27.754999, rtol=1e-6)

    # conformal time in Mpc: the reference's classylss golden value
    assert_allclose(c.tau(1.0), 3396.158162, rtol=1e-4)
    assert_allclose(c.comoving_distance(1.0), c.tau(1.0) * c.h)

    assert_allclose(c.efunc(0), 1.)
    assert_allclose(c.efunc(0) - c.efunc(1 / 0.9999 - 1),
                    0.0001 * c.efunc_prime(0), rtol=1e-3)


def test_efunc_prime():
    epsilon = 1e-4
    z = np.linspace(0, 3, 100) + epsilon
    for cosmo in [WMAP9, Planck15]:
        d1 = cosmo.efunc_prime(z)
        d2 = (cosmo.efunc(z + epsilon)
              - cosmo.efunc(z - epsilon)) / (2 * epsilon) \
            * -(1 + z) ** 2
        assert_allclose(d1, d2, rtol=1e-3)


def test_cosmology_density():
    c = Cosmology(gauge='synchronous')
    z = [0, 1, 2, 5, 9, 99]
    assert_allclose(c.rho_cdm(z), c.Omega_cdm(z) * c.rho_crit(z))
    assert_allclose(c.rho_g(z), c.Omega_g(z) * c.rho_crit(z))
    assert_allclose(c.rho_ncdm(z), c.Omega_ncdm(z) * c.rho_crit(z))
    assert_allclose(c.rho_b(z), c.Omega_b(z) * c.rho_crit(z))
    assert_allclose(c.rho_m(z), c.Omega_m(z) * c.rho_crit(z))
    assert_allclose(c.rho_r(z), c.Omega_r(z) * c.rho_crit(z))
    assert_allclose(c.rho_ur(z), c.Omega_ur(z) * c.rho_crit(z))


def test_cosmology_vect():
    c = Cosmology(gauge='synchronous')
    assert_allclose(c.Omega_cdm([0]), c.Omega0_cdm)
    assert_array_equal(c.Omega_cdm([]).shape, [0])
    assert_array_equal(c.Omega_cdm([0]).shape, [1])
    assert_array_equal(c.Omega_cdm([[0]]).shape, [1, 1])
    assert_array_equal(c.rho_k([[0]]).shape, [1, 1])


def test_immutable():
    c = Cosmology()
    with pytest.raises(ValueError):
        c.A_s = 2e-9
    c.test = 'TEST'  # non-parameter attributes are allowed
    assert c.test == 'TEST'


def test_cosmology_dir():
    c = Cosmology()
    d = dir(c)
    assert "Background" in d
    assert "Spectra" in d
    assert "Omega0_m" in d


def test_cosmology_pickle():
    c = Cosmology()
    c1 = pickle.loads(pickle.dumps(c))
    assert c1.parameter_file == c.parameter_file


def test_parameter_file():
    c1 = Cosmology(gauge='newtonian')
    assert 'newtonian' in c1.parameter_file
    c2 = Cosmology(P_k_max=1.01234567)
    assert '1.01234567' in c2.parameter_file


def test_astropy_compat():
    c = Cosmology(gauge='synchronous', m_ncdm=[0.06])
    assert_allclose(c.Odm(0), c.Odm0)
    assert_allclose(c.Ogamma(0), c.Ogamma0)
    assert_allclose(c.Ob(0), c.Ob0)
    assert_allclose(c.Onu(0), c.Onu0)
    assert_allclose(c.Ok(0), c.Ok0)
    assert_allclose(c.Ode(0), c.Ode0)
    assert c.has_massive_nu is True


def test_wcdm():
    c = Cosmology(w0_fld=-0.9, wa_fld=0.1)
    assert c.Omega0_lambda == 0.0
    assert c.Omega0_fld > 0
    assert_allclose(c.Omega0_fld + c.Omega0_m + c.Omega0_r
                    + c.Omega0_k, 1.0, rtol=1e-8)
    # fld density evolves
    assert c.Omega_fld(1.0) != c.Omega0_fld


def test_match_omega():
    c = Cosmology().match(Omega0_cb=0.4)
    assert_allclose(c.Omega0_cb, 0.4)
    c = Cosmology().match(Omega0_m=0.4)
    assert_allclose(c.Omega0_m, 0.4)


def test_tau_reio_input():
    """tau_reio input inverts to z_reio (slow-ish root find)."""
    c = Cosmology(tau_reio=0.066)
    assert_allclose(c.tau_reio, 0.066, atol=2e-3)
    assert 5.0 < c.z_reio < 12.0


@pytest.mark.slow
def test_set_sigma8():
    c = Cosmology(P_k_max=2.0).match(sigma8=0.80)
    assert_allclose(c.sigma8, 0.80, rtol=1e-4)


@pytest.mark.slow
def test_sigma8_z():
    z = np.linspace(0, 1, 12)
    c = Cosmology(P_k_max=2.0)
    s8_z = c.sigma8_z(z)
    D_z = c.scale_independent_growth_factor(z)
    assert_allclose(s8_z, D_z * c.sigma8, rtol=5e-3)


@pytest.mark.slow
def test_cosmology_transfer():
    c = Cosmology(P_k_max=2.0)
    t = c.get_transfer(z=0)
    assert 'h_prime' in t.keys()
    assert 'k' in t.keys()
    assert 'd_cdm' in t.keys()


@pytest.mark.slow
def test_cosmology_get_pk():
    c = Cosmology(P_k_max=2.0)
    p = c.get_pk(z=0, k=0.1)
    p1 = c.Spectra.get_pk(z=0, k=0.1)
    assert_allclose(p, p1)
    # vectorized meshgrid form (reference test_cosmology_vect)
    k, z = np.meshgrid([0.05, 0.1], [0.01, 0.05, 0.1, 0.5],
                       sparse=True, indexing='ij')
    pk = c.get_pk(z=z, k=k)
    assert_array_equal(pk.shape, [2, 4])


@pytest.mark.slow
def test_linear_class_goldens():
    """Reference test_power.py::test_linear golden values (computed
    there with CLASS): velocity dispersion 5.898 Mpc/h at sigma8=0.82,
    and sigma_r(8) == sigma8 by normalization."""
    from nbodykit_tpu.cosmology import LinearPower
    c = Cosmology().match(sigma8=0.82)
    P = LinearPower(c, redshift=0, transfer='CLASS')
    assert_allclose(P.sigma_r(8.), c.sigma8, rtol=1e-4)
    assert_allclose(P.velocity_dispersion(), 5.898, rtol=0.015)


@pytest.mark.slow
def test_linear_norm_class():
    """Reference test_power.py::test_linear_norm on the CLASS path."""
    from nbodykit_tpu.cosmology import LinearPower
    c = Cosmology().match(sigma8=0.82)
    P = LinearPower(c, redshift=0, transfer='CLASS')
    k = np.logspace(-3, np.log10(0.99 * c.P_k_max), 100)
    Pk1 = P(k)
    P.sigma8 = 0.75
    Pk2 = P(k)
    assert_allclose(Pk1.max() / Pk2.max(), (0.82 / 0.75) ** 2,
                    rtol=1e-2)
    P.redshift = 0.55
    Pk3 = P(k)
    D2 = c.scale_independent_growth_factor(0.)
    D3 = c.scale_independent_growth_factor(0.55)
    assert_allclose(Pk2.max() / Pk3.max(), (D2 / D3) ** 2, rtol=1e-2)


@pytest.mark.slow
def test_large_scales_class():
    """Reference test_power.py::test_large_scales: linear == halofit ==
    zeldovich on very large scales."""
    from nbodykit_tpu.cosmology import (LinearPower, HalofitPower,
                                        ZeldovichPower)
    c = Cosmology()
    k = np.logspace(-5, -2, 100)
    Plin = LinearPower(c, redshift=0)
    Pnl = HalofitPower(c, redshift=0)
    Pzel = ZeldovichPower(c, redshift=0)
    assert_allclose(Plin(k), Pnl(k), rtol=1e-2)
    assert_allclose(Plin(k), Pzel(k), rtol=1e-2)
