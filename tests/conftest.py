"""Test configuration.

Tests run on CPU with 8 virtual devices so the multi-device code paths
(shard_map collectives, distributed FFT, halo exchange) are exercised
without TPU hardware — the analog of the reference CI running the same
suite under ``mpirun -n 4`` (reference .github/workflows/main.yaml:44-49).

The axon sitecustomize imports jax at interpreter startup (so env vars
like JAX_NUM_CPU_DEVICES set here would be too late) but does not
initialize backends; jax.config.update still works and is the reliable
way to get 8 CPU devices + CPU default + x64.
"""

import jax
import numpy as np  # noqa: F401
import pytest

# cpu-only: keeps the (possibly unreachable) axon TPU backend from even
# initializing — jax.devices() would otherwise block on its tunnel
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)

assert len(jax.devices("cpu")) == 8, \
    "multi-device test setup failed: expected 8 CPU devices"


@pytest.fixture(scope='session')
def cpu8():
    """An 8-device CPU mesh."""
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    return cpu_mesh()


# Parametrized ambient mesh: single device and the 8-device CPU mesh.
# Mirrors the reference's `@pytest.mark.parametrize("comm", [MPI.COMM_WORLD])`
# + 1-rank/4-rank CI matrix: the same test body must give device-count
# invariant results.
@pytest.fixture(params=['single', 'multi'])
def comm(request):
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    if request.param == 'single':
        return cpu_mesh(1)
    return cpu_mesh()
