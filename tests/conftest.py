"""Test configuration.

Tests run on CPU with 8 virtual devices so the multi-device code paths
(shard_map collectives, distributed FFT, halo exchange) are exercised
without TPU hardware — the analog of the reference CI running the same
suite under ``mpirun -n 4`` (reference .github/workflows/main.yaml:44-49).

The axon sitecustomize imports jax at interpreter startup (so env vars
like JAX_NUM_CPU_DEVICES set here would be too late) but does not
initialize backends; jax.config.update still works and is the reliable
way to get 8 CPU devices + CPU default + x64.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np  # noqa: F401
import pytest

# cpu-only: keeps the (possibly unreachable) axon TPU backend from even
# initializing — jax.devices() would otherwise block on its tunnel
jax.config.update("jax_platforms", "cpu")
# 8 virtual devices, robust to jax versions without the
# jax_num_cpu_devices config (falls back to XLA_FLAGS, which works
# because no backend has initialized yet)
from nbodykit_tpu._jax_compat import set_cpu_devices  # noqa: E402

set_cpu_devices(8)
jax.config.update("jax_enable_x64", True)

# Persistent XLA compile cache: the suite is compile-dominated on this
# 1-core box (~45 min cold); cached re-runs skip nearly all of it.
_cache_dir = os.environ.get(
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(__file__), '..', '.jax_cache'))
os.makedirs(_cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

assert len(jax.devices("cpu")) == 8, \
    "multi-device test setup failed: expected 8 CPU devices"


# ---------------------------------------------------------------------------
# fast/slow tiers. The box running CI has ONE core simulating 8 devices,
# so the suite is wall-clock dominated by shard_map programs. Tests
# measured >= ~2.5 s (see docs/COMPONENTS.md "test tiers") are marked
# slow centrally here; `pytest -m "not slow"` is the fast tier.
_SLOW = {
    "test_convpower.py::test_convpower_periodic_consistency",
    "test_coverage_extras.py::test_fftpower_dk_zero_unique_edges",
    "test_coverage_extras.py::test_paint_sort_method_end_to_end",
    "test_coverage_extras.py::test_readout_device_count_invariance",
    "test_dist_sort.py::test_catalog_sort_multi_device",
    "test_dist_sort.py::test_dist_sort_fast_path_engages",
    "test_dist_sort.py::test_dist_sort_floats",
    "test_dist_sort.py::test_dist_sort_matches_numpy[10001]",
    "test_dist_sort.py::test_dist_sort_matches_numpy[1000]",
    "test_dist_sort.py::test_dist_sort_matches_numpy[4096]",
    "test_dist_sort.py::test_dist_sort_skewed_fallback",
    "test_extras.py::test_demo_halo_catalog_and_populate",
    "test_fftpower.py::test_fftcorr_runs_and_integrates[multi]",
    "test_fftpower.py::test_fftpower_cross[multi]",
    "test_fftpower.py::test_fftpower_shotnoise_flat[multi]",
    "test_fftpower.py::test_fftpower_shotnoise_flat[single]",
    "test_fftpower.py::test_linear_mesh_recovers_power[multi]",
    "test_fof.py::test_fof_com_periodic",
    "test_forward.py::test_forward_served_end_to_end_with_shadow_verify",
    "test_forward.py::test_kdk_gradient_matches_fd_multi",
    "test_forward.py::test_recovery_beats_fftrecon_128",
    "test_forward.py::test_recovery_beats_fftrecon_small",
    "test_fof.py::test_fof_features_and_com",
    "test_fof.py::test_fof_matches_brute_force",
    "test_fof.py::test_fof_mean_separation_units",
    "test_fof.py::test_fof_periodic_wrap",
    "test_fof.py::test_fof_to_halos",
    "test_fof.py::test_fof_two_well_separated_clusters",
    "test_groups.py::test_fibercollisions_isolated",
    "test_groups.py::test_fibercollisions_pair",
    "test_ingest.py::test_cache_fits_predicate_prices_eviction",
    "test_ingest.py::test_cache_hit_bit_identical_and_zero_reads",
    "test_ingest.py::test_cache_misses_when_bytes_change",
    "test_ingest.py::test_eviction_under_shrunken_budget_reingests",
    "test_ingest.py::test_fault_mid_stream_resumes_without_repainting",
    "test_ingest.py::test_host_never_holds_the_catalog",
    "test_ingest.py::test_overlap_and_serial_paths_bit_identical",
    "test_ingest.py::test_resume_refuses_changed_catalog",
    "test_ingest.py::test_streamed_bit_identical_to_whole_load",
    "test_groups.py::test_fibercollisions_triplet_chain",
    "test_io.py::test_mesh_save_and_bigfile_mesh",
    "test_lognormal.py::test_lognormal_columns",
    "test_lognormal.py::test_lognormal_device_count_invariance",
    "test_lognormal.py::test_lognormal_power_recovery",
    "test_lognormal.py::test_unitary_amplitude_reduces_variance",
    "test_mesh_base.py::test_catalog_mesh_selection_column",
    "test_mesh_base.py::test_interlacing_preserves_low_k",
    "test_mesh_base.py::test_mesh_resample_down",
    "test_mesh_base.py::test_value_column_weighting",
    "test_misc_algorithms.py::test_3pcf_brute_force[0]",
    "test_misc_algorithms.py::test_3pcf_brute_force[1]",
    "test_misc_algorithms.py::test_3pcf_brute_force[2]",
    "test_misc_algorithms.py::test_3pcf_nonperiodic_no_double_count",
    "test_misc_algorithms.py::test_fftrecon_reduces_displacement",
    "test_misc_algorithms.py::test_fof_nonperiodic",
    "test_misc_algorithms.py::test_fof_peak_columns",
    "test_misc_algorithms.py::test_hod_populate",
    "test_misc_algorithms.py::test_hod_reproducible",
    "test_paircount.py::test_2pcf_clustered_signal",
    "test_paircount.py::test_2pcf_landy_szalay_matches_natural",
    "test_paircount.py::test_2pcf_natural_uniform_is_zero",
    "test_paircount.py::test_2pcf_projected_wp",
    "test_paircount.py::test_paircount_1d_brute_force",
    "test_paircount.py::test_paircount_2d_mu_bins",
    "test_paircount.py::test_paircount_cross",
    "test_paircount.py::test_paircount_projected",
    "test_paircount.py::test_survey_2pcf_runs",
    "test_paircount.py::test_survey_paircount_angular",
    "test_paircount.py::test_wedges_to_poles",
    "test_pmesh.py::test_dist_irfftn_roundtrip",
    "test_pmesh.py::test_paint_clustered_no_mass_loss",
    "test_pmesh.py::test_paint_device_count_invariance[cic]",
    "test_pmesh.py::test_paint_device_count_invariance[tsc]",
    "test_pmesh.py::test_paint_mass_conservation[multi-cic]",
    "test_pmesh.py::test_paint_mass_conservation[multi-nnb]",
    "test_pmesh.py::test_paint_mass_conservation[multi-pcs]",
    "test_pmesh.py::test_paint_mass_conservation[multi-tsc]",
    "test_pmesh.py::test_paint_nnb_is_histogram[multi]",
    "test_pmesh.py::test_paint_non_divisible_N[multi]",
    "test_pmesh.py::test_paint_non_divisible_N[single]",
    "test_pmesh.py::test_readout_constant_field[multi]",
    "test_pmesh.py::test_readout_constant_field[single]",
    "test_pmesh.py::test_readout_linear_gradient[multi]",
    "test_pmesh.py::test_readout_linear_gradient[single]",
    "test_pmesh.py::test_uniform_particle_grid[multi]",
    "test_pmesh.py::test_uniform_particle_grid[single]",
    "test_pmesh.py::test_whitenoise_unitary",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        key = "::".join(item.nodeid.split("/")[-1].split("::")[-2:])
        if key in _SLOW:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope='session')
def cpu8():
    """An 8-device CPU mesh."""
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    return cpu_mesh()


# Parametrized ambient mesh: single device and the 8-device CPU mesh.
# Mirrors the reference's `@pytest.mark.parametrize("comm", [MPI.COMM_WORLD])`
# + 1-rank/4-rank CI matrix: the same test body must give device-count
# invariant results.
@pytest.fixture(params=['single', 'multi'])
def comm(request):
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    if request.param == 'single':
        return cpu_mesh(1)
    return cpu_mesh()
