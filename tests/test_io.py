"""IO tests: round-trips through every reader, catalog/mesh save-load
(the reference's round-trip oracle style, SURVEY.md §4)."""

import numpy as np
import pytest

from nbodykit_tpu import io as nio
from nbodykit_tpu.lab import UniformCatalog, ArrayCatalog, LinearMesh
from nbodykit_tpu.source.catalog.file import (CSVCatalog, BinaryCatalog,
                                              BigFileCatalog, HDFCatalog,
                                              TPMBinaryCatalog)
from nbodykit_tpu.source.mesh.bigfile import BigFileMesh


@pytest.fixture
def data():
    rng = np.random.RandomState(0)
    return {
        'Position': rng.uniform(0, 100, size=(128, 3)),
        'Mass': rng.uniform(size=128),
    }


def test_bigfile_roundtrip(tmp_path, data):
    path = str(tmp_path / "cat.bf")
    with nio.BigFileWriter(path) as ff:
        ff.write_attrs('Header', {'BoxSize': np.array([100.0] * 3)})
        ff.write('Position', data['Position'], nfile=2)
        ff.write('Mass', data['Mass'])
    f = nio.BigFile(path)
    assert f.size == 128
    assert set(f.columns) == {'Position', 'Mass'}
    out = f.read(['Position', 'Mass'], 10, 50)
    np.testing.assert_array_equal(out['Position'],
                                  data['Position'][10:50])
    np.testing.assert_array_equal(out['Mass'], data['Mass'][10:50])
    np.testing.assert_array_equal(f.attrs['BoxSize'], [100.0] * 3)


def test_catalog_save_and_bigfile_catalog(tmp_path):
    cat = UniformCatalog(nbar=1e-3, BoxSize=64.0, seed=5)
    path = str(tmp_path / "uniform.bf")
    cat.save(path, columns=['Position', 'Velocity'])
    cat2 = BigFileCatalog(path)
    assert cat2.csize == cat.csize
    np.testing.assert_allclose(np.asarray(cat2['Position']),
                               np.asarray(cat['Position']), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cat2.attrs['BoxSize']),
                                  [64.0] * 3)


def test_mesh_save_and_bigfile_mesh(tmp_path):
    mesh = LinearMesh(lambda k: 10.0 * np.ones_like(k), BoxSize=32.0,
                      Nmesh=16, seed=3, dtype='f8')
    field = mesh.compute(mode='real')
    path = str(tmp_path / "mesh.bf")
    mesh.save(path)
    mesh2 = BigFileMesh(path)
    field2 = mesh2.compute(mode='real')
    np.testing.assert_allclose(np.asarray(field2.value),
                               np.asarray(field.value), rtol=1e-6)


def test_binary_file(tmp_path, data):
    path = str(tmp_path / "data.bin")
    with open(path, 'wb') as ff:
        data['Position'].astype('f8').tofile(ff)
        data['Mass'].astype('f8').tofile(ff)
    f = nio.BinaryFile(path, dtype=[('Position', ('f8', 3)),
                                    ('Mass', 'f8')])
    assert f.size == 128
    out = f.read(['Mass'], 0, 128)
    np.testing.assert_array_equal(out['Mass'], data['Mass'])
    cat = BinaryCatalog(path, dtype=[('Position', ('f8', 3)),
                                     ('Mass', 'f8')])
    np.testing.assert_allclose(np.asarray(cat['Position']),
                               data['Position'])


def test_csv_file(tmp_path):
    rng = np.random.RandomState(2)
    arr = rng.uniform(size=(64, 5))
    path = str(tmp_path / "data.csv")
    np.savetxt(path, arr)
    names = ['a', 'b', 'c', 'd', 'e']
    f = nio.CSVFile(path, names=names)
    assert f.size == 64
    out = f.read(['b', 'd'], 8, 32)
    np.testing.assert_allclose(out['b'], arr[8:32, 1])
    cat = CSVCatalog(path, names=names)
    np.testing.assert_allclose(np.asarray(cat['e']), arr[:, 4])


def test_hdf_file(tmp_path, data):
    h5py = pytest.importorskip('h5py')
    path = str(tmp_path / "data.h5")
    with h5py.File(path, 'w') as ff:
        g = ff.create_group('cat')
        g.create_dataset('Position', data=data['Position'])
        g.create_dataset('Mass', data=data['Mass'])
    f = nio.HDFFile(path, dataset='cat')
    assert f.size == 128
    out = f.read(['Position'], 0, 10)
    np.testing.assert_array_equal(out['Position'], data['Position'][:10])
    cat = HDFCatalog(path, dataset='cat')
    np.testing.assert_allclose(np.asarray(cat['Mass']), data['Mass'])


def test_tpm_file(tmp_path):
    rng = np.random.RandomState(3)
    N = 32
    pos = rng.uniform(size=(N, 3)).astype('f4')
    vel = rng.uniform(size=(N, 3)).astype('f4')
    ids = np.arange(N, dtype='u8')
    path = str(tmp_path / "tpm.bin")
    with open(path, 'wb') as ff:
        np.zeros(28, dtype='u1').tofile(ff)
        pos.tofile(ff)
        vel.tofile(ff)
        ids.tofile(ff)
    f = nio.TPMBinaryFile(path)
    assert f.size == N
    out = f.read(['Position', 'ID'], 0, N)
    np.testing.assert_array_equal(out['Position'], pos)
    np.testing.assert_array_equal(out['ID'], ids)
    cat = TPMBinaryCatalog(path)
    np.testing.assert_allclose(np.asarray(cat['Velocity']), vel)


def test_gadget_file(tmp_path):
    # synthesize a minimal Gadget-1 snapshot with ptype-1 particles
    rng = np.random.RandomState(4)
    N = 16
    pos = rng.uniform(size=(N, 3)).astype('f4')
    vel = rng.uniform(size=(N, 3)).astype('f4')
    ids = np.arange(N, dtype='u4')
    from nbodykit_tpu.io.gadget import DefaultHeaderDtype
    header = np.zeros(1, dtype=DefaultHeaderDtype)
    header['Npart'][0][1] = N
    path = str(tmp_path / "gadget.0")

    def record(ff, arr):
        n = np.array([arr.nbytes], dtype='i4')
        n.tofile(ff)
        arr.tofile(ff)
        n.tofile(ff)

    with open(path, 'wb') as ff:
        np.array([256], dtype='i4').tofile(ff)
        header.tofile(ff)
        np.zeros(256 - header.nbytes, dtype='u1').tofile(ff)
        np.array([256], dtype='i4').tofile(ff)
        record(ff, pos)
        record(ff, vel)
        record(ff, ids)

    f = nio.Gadget1File(path, ptype=1)
    assert f.size == N
    out = f.read(['Position', 'ID'], 0, N)
    np.testing.assert_array_equal(out['Position'], pos)
    np.testing.assert_array_equal(out['ID'], ids)


def test_file_stack(tmp_path, data):
    for i in range(3):
        path = str(tmp_path / ("part%d.bin" % i))
        with open(path, 'wb') as ff:
            (data['Mass'] + i).astype('f8').tofile(ff)
    stack = nio.FileStack(nio.BinaryFile, str(tmp_path / "part*.bin"),
                          dtype=[('Mass', 'f8')])
    assert stack.size == 3 * 128
    assert stack.nfiles == 3
    out = stack.read(['Mass'], 100, 300)
    want = np.concatenate([data['Mass'] + i for i in range(3)])[100:300]
    np.testing.assert_array_equal(out['Mass'], want)


def test_bigfile_on_disk_format(tmp_path):
    """Pin the real bigfile layout (rainwoodman/bigfile): ASCII block
    header with DTYPE/NMEMB/NFILE lines, hex-named raw data files, and
    attr-v2 'name dtype nmemb hex #HUMANE [...]' lines — so snapshots
    interchange with the C library (reference io/bigfile.py:16)."""
    import os
    from nbodykit_tpu.io.bigfile import BigFileWriter, BigFile

    path = str(tmp_path / 'snap')
    pos = np.arange(30, dtype='<f8').reshape(10, 3)
    pid = np.arange(10, dtype='<i8')
    with BigFileWriter(path) as ff:
        ff.write('Position', pos, nfile=2)
        ff.write('ID', pid)
        ff.write_attrs('Header', {
            'BoxSize': np.array([100.0, 100.0, 100.0]),
            'Label': 'hello',
            'Nested': {'a': 1},
        })

    # block header is the C library's exact text layout
    with open(os.path.join(path, 'Position', 'header')) as f:
        lines = f.read().splitlines()
    assert lines[0] == 'DTYPE: <f8'
    assert lines[1] == 'NMEMB: 3'
    assert lines[2] == 'NFILE: 2'
    assert lines[3].startswith('000000: 5 : ')
    assert lines[4].startswith('000001: 5 : ')
    # data files are hex-named raw little-endian bytes
    raw = open(os.path.join(path, 'Position', '000000'), 'rb').read()
    np.testing.assert_array_equal(
        np.frombuffer(raw, '<f8').reshape(5, 3), pos[:5])
    # checksum is the 32-bit byte sum
    want = int(np.frombuffer(raw, np.uint8).sum(dtype=np.uint64)
               & 0xFFFFFFFF)
    assert lines[3] == '000000: 5 : %d' % want

    # attr-v2: name dtype nmemb hex, trailing #HUMANE comment ignored
    with open(os.path.join(path, 'Header', 'attr-v2')) as f:
        attr_lines = f.read().splitlines()
    by_name = {l.split()[0]: l for l in attr_lines}
    name, dt, nmemb, hexdata = by_name['BoxSize'].split()[:4]
    assert (dt, nmemb) == ('<f8', '3')
    np.testing.assert_array_equal(
        np.frombuffer(bytes.fromhex(hexdata), '<f8'), 100.0)
    assert '#HUMANE' in by_name['BoxSize']

    # reader round-trip, including json:// decoding of nested attrs
    bf = BigFile(path)
    np.testing.assert_array_equal(bf.read(['Position'], 0, 10)['Position'], pos)
    np.testing.assert_array_equal(bf.read(['ID'], 2, 7)['ID'], pid[2:7])
    np.testing.assert_array_equal(bf.attrs['BoxSize'], [100.0] * 3)
    assert bf.attrs['Label'] == 'hello'
    assert bf.attrs['Nested'] == {'a': 1}


def test_bigfile_reads_foreign_snapshot(tmp_path):
    """A block written by hand following the published format (as the C
    library would) must load: the reader cannot depend on any quirk of
    our own writer."""
    import os
    from nbodykit_tpu.io.bigfile import BigFile

    root = str(tmp_path / 'fastpm_snap')
    bdir = os.path.join(root, '1', 'Position')
    os.makedirs(bdir)
    data = np.arange(12, dtype='<f4').reshape(4, 3)
    with open(os.path.join(bdir, '000000'), 'wb') as f:
        f.write(data[:1].tobytes())
    with open(os.path.join(bdir, '000001'), 'wb') as f:
        f.write(data[1:].tobytes())
    with open(os.path.join(bdir, 'header'), 'w') as f:
        f.write('DTYPE: <f4\nNMEMB: 3\nNFILE: 2\n'
                '000000: 1 : 0\n000001: 3 : 0\n')
    hdir = os.path.join(root, 'Header')
    os.makedirs(hdir)
    with open(os.path.join(hdir, 'header'), 'w') as f:
        f.write('DTYPE: <i8\nNMEMB: 1\nNFILE: 0\n')
    with open(os.path.join(hdir, 'attr-v2'), 'w') as f:
        f.write('Time <f8 1 %s #HUMANE [ 1.0 ]\n'
                % np.float64(1.0).tobytes().hex().upper())

    bf = BigFile(root, dataset='1', header='Header')
    got = bf.read(['Position'], 0, 4)['Position']
    np.testing.assert_array_equal(got, data)
    assert float(bf.attrs['Time']) == 1.0


def test_bigfile_native_reader_parity(tmp_path):
    """The C++ threaded part-file reader returns byte-identical data to
    the numpy loop across stripe boundaries (csrc/bigfile_io.cpp)."""
    from nbodykit_tpu.io.bigfile import BigFileWriter, BigFileDataset
    from nbodykit_tpu.io import _native

    if not _native.native_available():
        pytest.skip('native kernel unavailable: %s' % _native._lib_err)

    path = str(tmp_path / 'striped')
    data = np.arange(3000, dtype='f8').reshape(1000, 3)
    with BigFileWriter(path) as bf:
        bf.write('Position', data, nfile=7)  # uneven striping

    ds = BigFileDataset(path, 'Position')
    for start, stop in [(0, 1000), (0, 1), (999, 1000), (143, 857),
                        (500, 500)]:
        native = _native.read_block(ds.dir, ds.bounds, ds.dtype,
                                    ds.nmemb, start, stop)
        assert native is not None
        want = data[start:stop].reshape(-1)
        np.testing.assert_array_equal(native.reshape(-1), want)
    # and the public read() path (which prefers the native kernel)
    np.testing.assert_array_equal(ds.read(10, 990), data[10:990])


def test_bigfile_read_range_validated(tmp_path):
    """Out-of-range record requests raise instead of returning
    uninitialized memory."""
    from nbodykit_tpu.io.bigfile import BigFileWriter, BigFileDataset

    path = str(tmp_path / 'blk')
    with BigFileWriter(path) as bf:
        bf.write('X', np.arange(10.0), nfile=2)
    ds = BigFileDataset(path, 'X')
    with pytest.raises(IndexError):
        ds.read(0, 11)
    with pytest.raises(IndexError):
        ds.read(-1, 5)
    with pytest.raises(IndexError):
        ds.read(7, 3)


def test_bigfile_checksum_detects_corruption(tmp_path):
    """A flipped byte on disk raises ChecksumMismatch on the first
    read touching the file — naming the column and both checksums —
    instead of feeding rotten bytes to a catalog
    (docs/INTEGRITY.md)."""
    import nbodykit_tpu
    from nbodykit_tpu.io.bigfile import BigFileWriter, BigFileDataset

    path = str(tmp_path / 'rot')
    data = np.arange(300, dtype='f8').reshape(100, 3)
    with BigFileWriter(path) as bf:
        bf.write('Position', data, nfile=2)

    # corrupt one byte of the SECOND physical file
    fn = str(tmp_path / 'rot' / 'Position' / '000001')
    with open(fn, 'r+b') as ff:
        ff.seek(8)
        b = ff.read(1)
        ff.seek(8)
        ff.write(bytes([b[0] ^ 0xFF]))

    ds = BigFileDataset(path, 'Position')
    # rows wholly inside the intact first file read fine (lazy,
    # per-file verification)
    np.testing.assert_array_equal(ds.read(0, 10), data[:10])
    with pytest.raises(nio.ChecksumMismatch) as ei:
        ds.read(0, 100)
    assert ei.value.column == 'Position'
    assert ei.value.expected != ei.value.got
    assert 'io_verify_checksums' in str(ei.value)

    # explicit opt-out loads the bytes as-is (restore-and-inspect)
    with nbodykit_tpu.set_options(io_verify_checksums=False):
        ds2 = BigFileDataset(path, 'Position')
        out = ds2.read(0, 100)
    assert out.shape == data.shape
    assert not np.array_equal(out, data)


def test_bigfile_legacy_header_skips_verification(tmp_path):
    """Headers whose entries carry no checksum field (foreign writers)
    must load unverified rather than fail."""
    from nbodykit_tpu.io.bigfile import BigFileWriter, BigFileDataset

    path = str(tmp_path / 'legacy')
    data = np.arange(30, dtype='f8')
    with BigFileWriter(path) as bf:
        bf.write('X', data, nfile=1)
    hdr = str(tmp_path / 'legacy' / 'X' / 'header')
    with open(hdr) as ff:
        lines = ff.read().splitlines()
    # strip the checksum field from the per-file entries ('%06X: n :
    # cks' -> '%06X: n'), leaving DTYPE/NMEMB/NFILE untouched
    with open(hdr, 'w') as ff:
        for line in lines:
            parts = line.split(':')
            if len(parts) == 3 and parts[0].strip() not in (
                    'DTYPE', 'NMEMB', 'NFILE'):
                line = '%s: %s' % (parts[0], parts[1].strip())
            ff.write(line + '\n')
    ds = BigFileDataset(path, 'X')
    assert ds.checksums.get(0) is None
    np.testing.assert_array_equal(ds.read(0, 30), data)


def test_csv_reader_kwargs(tmp_path):
    """CSV variations the reference exercises (io/tests/test_csv.py):
    comma separator, comments, blank lines, usecols, skiprows,
    nrows."""
    from nbodykit_tpu.io import CSVFile

    fn = str(tmp_path / 'x.csv')
    with open(fn, 'w') as f:
        f.write("# header comment\n1,2,3\n4,5,6\n\n7,8,9\n10,11,12\n")

    ff = CSVFile(fn, names=['a', 'b', 'c'], sep=',', comment='#')
    assert ff.size == 4
    np.testing.assert_allclose(ff.read(['a'], 0, 4)['a'],
                               [1, 4, 7, 10])

    ff2 = CSVFile(fn, names=['a', 'b', 'c'], sep=',', comment='#',
                  usecols=['a', 'b'])
    assert set(ff2.dtype.names) == {'a', 'b'}

    # skiprows counts PHYSICAL lines (pandas semantics): line 0 is
    # the comment, lines 1-2 the first two data rows
    ff3 = CSVFile(fn, names=['a', 'b', 'c'], sep=',', comment='#',
                  skiprows=3, nrows=2)
    np.testing.assert_allclose(ff3.read(['a'], 0, ff3.size)['a'],
                               [7, 10])
    # partitioned read stays aligned across the comment/blank lines
    np.testing.assert_allclose(ff.read(['b'], 2, 4)['b'], [8, 11])
    # usecols selects labeled columns correctly (not positionally)
    np.testing.assert_allclose(ff2.read(['b'], 1, 3)['b'], [5, 8])


def test_csv_negative_step_and_mid_comments(tmp_path):
    """Partitioned reads stay aligned across mid-file comments; the
    slice contract supports negative steps and validates ranges."""
    from nbodykit_tpu.io import CSVFile

    fn = str(tmp_path / 'y.csv')
    with open(fn, 'w') as f:
        f.write("# c\n1,2\n3,4\n\n5,6\n# mid\n7,8\n9,10\n")
    ff = CSVFile(fn, names=['a', 'b'], sep=',', comment='#')
    assert ff.size == 5
    np.testing.assert_allclose(ff.read(['a'], 3, 5)['a'], [7, 9])
    np.testing.assert_allclose(ff[::-1]['a'], [9, 7, 5, 3, 1])
    np.testing.assert_allclose(ff.read(['a'], 0, 5, 2)['a'],
                               [1, 5, 9])
    with pytest.raises(IndexError):
        ff.read(['a'], -2, 2)
    # list-valued skiprows drops those physical lines
    f3 = CSVFile(fn, names=['a', 'b'], sep=',', comment='#',
                 skiprows=[2])
    np.testing.assert_allclose(f3.read(['a'], 0, f3.size)['a'],
                               [1, 5, 7, 9])


def _write_minimal_fits(path, cols):
    """Hand-roll a standards-conforming single-BINTABLE FITS file
    (2880-byte header blocks of 80-char cards + big-endian records)."""
    def card(key, val, quote=False):
        if quote:
            v = "'%s'" % val
        elif isinstance(val, bool):
            v = 'T' if val else 'F'
        else:
            v = str(val)
        return ('%-8s= %20s' % (key, v)).ljust(80).encode('ascii')

    def block(cards):
        raw = b''.join(cards) + b'END'.ljust(80, b' ')
        return raw.ljust(((len(raw) + 2879) // 2880) * 2880, b' ')

    fields = []
    for name, arr in cols:
        arr = np.asarray(arr)
        letter = {'f8': 'D', 'f4': 'E', 'i4': 'J', 'i8': 'K'}[
            arr.dtype.str[1:]]
        rep = arr.shape[1] if arr.ndim > 1 else 1
        fields.append((name, arr, '%d%s' % (rep, letter)))
    dt = np.dtype([(n, a.dtype.newbyteorder('>'),
                    (a.shape[1],) if a.ndim > 1 else ())
                   for n, a, _ in fields])
    nrows = len(fields[0][1])
    rec = np.zeros(nrows, dtype=dt)
    for n, a, _ in fields:
        rec[n] = a

    with open(path, 'wb') as f:
        f.write(block([card('SIMPLE', True), card('BITPIX', 8),
                       card('NAXIS', 0)]))
        hdr = [card('XTENSION', 'BINTABLE', quote=True),
               card('BITPIX', 8), card('NAXIS', 2),
               card('NAXIS1', dt.itemsize), card('NAXIS2', nrows),
               card('PCOUNT', 0), card('GCOUNT', 1),
               card('TFIELDS', len(fields))]
        for i, (n, _, tform) in enumerate(fields):
            hdr.append(card('TTYPE%d' % (i + 1), n, quote=True))
            hdr.append(card('TFORM%d' % (i + 1), tform, quote=True))
        f.write(block(hdr))
        raw = rec.tobytes()
        f.write(raw.ljust(((len(raw) + 2879) // 2880) * 2880, b'\0'))


def test_fits_native_reader(tmp_path):
    """The built-in BINTABLE parser reads numeric tables without
    astropy/fitsio (reference io/fits.py:8 requires the cfitsio
    binding)."""
    rng = np.random.RandomState(6)
    pos = rng.uniform(0, 100, size=(40, 3))
    mass = rng.uniform(size=40)
    ids = np.arange(40, dtype='i8')
    fn = str(tmp_path / 'cat.fits')
    _write_minimal_fits(fn, [('POS', pos), ('MASS', mass),
                             ('ID', ids)])

    f = nio.FITSFile(fn)
    assert f.size == 40
    assert set(f.dtype.names) == {'POS', 'MASS', 'ID'}
    out = f.read(['POS', 'ID'], 5, 25)
    np.testing.assert_allclose(out['POS'], pos[5:25])
    np.testing.assert_array_equal(out['ID'], ids[5:25])

    from nbodykit_tpu.source.catalog.file import FITSCatalog
    cat = FITSCatalog(fn)
    np.testing.assert_allclose(np.asarray(cat['MASS']), mass,
                               rtol=1e-6)
