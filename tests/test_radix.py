"""stable_key_order == stable argsort, across alphabet sizes, chunk
boundaries, and degenerate inputs."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.ops.radix import (stable_key_order, stable_digit_dest,
                                    _pass_rank_hist)


@pytest.mark.parametrize("n,D", [(1, 1), (17, 3), (1000, 7),
                                 (4096, 130), (5000, 130),
                                 (3000, 2000), (8191, 16513),
                                 (4000, 2_000_003)])
def test_matches_stable_argsort(n, D):
    rng = np.random.RandomState(n + D)
    key = rng.randint(0, D, n).astype(np.int32)
    order = np.asarray(stable_key_order(jnp.asarray(key), D, chunk=512))
    ref = np.argsort(key, kind='stable')
    np.testing.assert_array_equal(order, ref)


def test_all_equal_keys_identity():
    key = jnp.full((777,), 4, jnp.int32)
    order = np.asarray(stable_key_order(key, 9, chunk=64))
    np.testing.assert_array_equal(order, np.arange(777))


def test_rank_hist_exact():
    rng = np.random.RandomState(0)
    key = rng.randint(0, 5, 1000).astype(np.int32)
    rank, hist = _pass_rank_hist(jnp.asarray(key), 5, 128)
    rank, hist = np.asarray(rank), np.asarray(hist)
    np.testing.assert_array_equal(hist, np.bincount(key, minlength=5))
    # rank must equal the running per-key counter
    seen = np.zeros(5, int)
    for i, k in enumerate(key):
        assert rank[i] == seen[k]
        seen[k] += 1


def test_dest_is_permutation():
    rng = np.random.RandomState(3)
    key = rng.randint(0, 11, 500).astype(np.int32)
    dest = np.asarray(stable_digit_dest(jnp.asarray(key), 11, chunk=100))
    assert sorted(dest.tolist()) == list(range(500))


def test_empty():
    assert stable_key_order(jnp.zeros((0,), jnp.int32), 4).shape == (0,)


@pytest.mark.parametrize("D", [130, 16513])
def test_radix_under_shard_map(cpu8, D):
    """The bucketing runs inside shard_map in the distributed paint —
    the scan carry must be varying-axes clean on every path."""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    rng = np.random.RandomState(2)
    key = jnp.asarray(rng.randint(0, D, 8192).astype('i4'))
    g = jax.jit(shard_map(lambda k: stable_key_order(k, D),
                          mesh=cpu8, in_specs=P('dev'),
                          out_specs=P('dev')))
    out = np.asarray(g(key))
    npd = 8192 // cpu8.devices.size
    ref = np.concatenate(
        [np.argsort(np.asarray(key[i * npd:(i + 1) * npd]),
                    kind='stable')
         for i in range(cpu8.devices.size)])
    np.testing.assert_array_equal(out, ref)


def test_bucket_local_radix_matches_argsort(monkeypatch):
    """The TPU (rank-scatter) and CPU (argsort) exchange bucketing
    paths must produce identical buffers/valid/dropped."""
    import nbodykit_tpu.utils as utils
    from nbodykit_tpu.ops import radix
    from nbodykit_tpu.parallel import exchange as ex

    # the pallas rank engine needs real TPU hardware; pin the XLA one
    # (identical results by the tests above)
    monkeypatch.setattr(radix, 'DEFAULT_ENGINE', 'xla')

    rng = np.random.RandomState(7)
    n, nproc, cap = 1000, 8, 150
    dest = jnp.asarray(rng.randint(0, nproc, n).astype('i4'))
    pay = jnp.asarray(rng.uniform(size=(n, 3)).astype('f4'))
    live = jnp.asarray(rng.rand(n) > 0.1)

    outs = {}
    for forced, name in [(False, 'argsort'), (True, 'radix')]:
        monkeypatch.setattr(utils, 'is_mxu_backend', lambda f=forced: f)
        bufs, valid, dropped = ex._bucket_local(
            dest, [pay, jnp.ones(n, 'f4')], nproc, cap, live=live)
        outs[name] = ([np.asarray(b) for b in bufs], np.asarray(valid),
                      int(dropped))
    for a, b in zip(outs['argsort'][0], outs['radix'][0]):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(outs['argsort'][1], outs['radix'][1])
    assert outs['argsort'][2] == outs['radix'][2]

    # overflow accounting must agree too (tiny capacity)
    for forced in (False, True):
        monkeypatch.setattr(utils, 'is_mxu_backend', lambda f=forced: f)
        _, _, dropped = ex._bucket_local(dest, [pay], nproc, 10,
                                         live=live)
        if forced:
            assert int(dropped) == drop0
        else:
            drop0 = int(dropped)
    assert drop0 > 0


def test_devicehash_radix_order_matches(monkeypatch):
    """DeviceGridHash built with the counting order must equal the
    argsort-built one (both stable)."""
    import nbodykit_tpu.utils as utils
    from nbodykit_tpu.ops.devicehash import DeviceGridHash

    rng = np.random.RandomState(9)
    pos = jnp.asarray(rng.uniform(0, 100.0, (3000, 3)).astype('f4'))
    valid = jnp.asarray(rng.rand(3000) > 0.05)
    hashes = {}
    for forced in (False, True):
        monkeypatch.setattr(utils, 'is_mxu_backend', lambda f=forced: f)
        h = DeviceGridHash(pos, box=100.0, rmax=8.0, valid=valid)
        hashes[forced] = h
    np.testing.assert_array_equal(np.asarray(hashes[0].order),
                                  np.asarray(hashes[1].order))
    np.testing.assert_array_equal(np.asarray(hashes[0].flat_s),
                                  np.asarray(hashes[1].flat_s))


@pytest.mark.parametrize("n,D", [(1000, 7), (5000, 130), (4096, 512)])
def test_pallas_rank_pass_matches_xla(n, D):
    from nbodykit_tpu.ops.radix_pallas import pass_rank_hist_pallas
    rng = np.random.RandomState(5)
    d = jnp.asarray(rng.randint(0, D, n).astype('i4'))
    r1, h1 = _pass_rank_hist(d, D, 512)
    r2, h2 = pass_rank_hist_pallas(d, D, chunk=512, interpret=True)
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
