"""Tests for the observability plane (ISSUE 17): request-scoped trace
propagation across every owned thread hop (queue worker, vmap batch
leader, Supervisor retry, shadow-verify sub-mesh, region pacer,
singleflight follower), the per-request waterfall reconstruction with
orphan detection (diagnostics/analyze.py), SLO burn-rate monitoring
(diagnostics/slo.py), the live export plane — Prometheus text,
labelled gauges, the HTTP exporter, the flight recorder
(diagnostics/export.py) — and the regress/doctor ``slo`` posture."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import (REGISTRY, current_tracer,
                                      new_request_context,
                                      read_trace, request_report,
                                      span, trace_context,
                                      trace_files, trace_scope)
from nbodykit_tpu.diagnostics.export import (FLIGHT, TelemetryExporter,
                                             prometheus_text,
                                             register_source,
                                             stop_exporter)
from nbodykit_tpu.diagnostics.metrics import labelled, split_label
from nbodykit_tpu.diagnostics.slo import SLOTracker
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.resilience import reset_faults
from nbodykit_tpu.serve import (AnalysisRequest, AnalysisServer,
                                BatchPolicy, QoSPolicy, Region,
                                ResultCache, ServiceClass)


@pytest.fixture(autouse=True)
def _clean_state():
    """Registry, faults, options, exporter and flight ring are
    process-wide; every test sees (and leaves) a pristine copy."""
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    yield
    stop_exporter()
    REGISTRY.reset()
    reset_faults()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _one_worker_server(**kw):
    with use_mesh(cpu_mesh(1)):
        return AnalysisServer(per_task=1, **kw)


def _records(tracedir):
    out = []
    for path in trace_files(tracedir):
        recs, bad = read_trace(path)
        assert bad == 0
        out.extend(recs)
    return out


def _report(tracedir):
    from nbodykit_tpu.diagnostics.analyze import load_processes
    procs, torn = load_processes(tracedir)
    assert torn == 0
    return request_report(procs)


def _req(i, seed=None, prefix='obs', **kw):
    kw.setdefault('nmesh', 16)
    kw.setdefault('npart', 1000)
    kw.setdefault('deadline_s', 120.0)
    return AnalysisRequest(seed=seed if seed is not None else 100 + i,
                           request_id='%s-%03d' % (prefix, i), **kw)


# ---------------------------------------------------------------------------
# context + labelled metrics primitives

def test_request_context_is_deterministic_and_samplable():
    a = new_request_context('req-42')
    b = new_request_context('req-42')
    c = new_request_context('req-43')
    assert a.trace_id == b.trace_id and a.trace_id != c.trace_id
    assert len(a.trace_id) == 16
    # fraction 0 drops kernel spans for everyone, 1 keeps them all;
    # the draw is derived from the trace id, so it replays identically
    assert not new_request_context('req-42', fraction=0.0).sampled
    assert new_request_context('req-42', fraction=1.0).sampled
    assert trace_context() is None
    with trace_scope(a):
        assert trace_context() is a
    assert trace_context() is None


def test_labelled_metric_names_roundtrip_to_prometheus():
    assert labelled('serve.queue_depth', {'fleet': 'a'}) \
        == 'serve.queue_depth{fleet=a}'
    assert split_label('serve.queue_depth{fleet=a}') \
        == ('serve.queue_depth', {'fleet': 'a'})
    assert split_label('plain.name') == ('plain.name', {})
    from nbodykit_tpu.diagnostics import counter, gauge
    gauge('serve.queue_depth', fleet='a').set(3)
    counter('region.route.affinity').add(2)
    text = prometheus_text()
    assert 'serve_queue_depth{fleet="a"} 3' in text
    assert 'region_route_affinity_total 2' in text
    assert '# TYPE serve_queue_depth gauge' in text


def test_cross_thread_span_reparents_to_request_root(tmp_path):
    """A span opened on a foreign thread under trace_scope lands in
    the request's trace with ``rpar`` = the root span id — the
    mechanism every owned thread hop (worker, pacer, batcher,
    supervisor) rides."""
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        ctx = new_request_context('req-hop')
        with trace_scope(ctx), span('region.submit',
                                    request_id='req-hop') as root:
            ctx.span_id = root.span_id

            def hop():
                with trace_scope(ctx), span('serve.request',
                                            request_id='req-hop'):
                    pass
            t = threading.Thread(target=hop)
            t.start()
            t.join()
    recs = [r for r in _records(str(tmp_path)) if r['t'] == 'span']
    by_name = {r['name']: r for r in recs}
    assert by_name['serve.request']['trace'] == ctx.trace_id
    assert by_name['serve.request']['rpar'] \
        == by_name['region.submit']['id']
    assert 'rpar' not in by_name['region.submit']


def test_orphan_and_incomplete_waterfalls_are_detected():
    """A span whose rpar points at a span id absent from the trace is
    an orphan, and its trace must NOT count as complete."""
    good = [
        {'t': 'span', 'name': 'serve.submit', 'id': 1, 'par': 0,
         'ts': 1.0, 'dur': 0.1, 'trace': 'aaaa',
         'attrs': {'request_id': 'g'}},
        {'t': 'span', 'name': 'serve.request', 'id': 2, 'par': 0,
         'rpar': 1, 'ts': 1.1, 'dur': 0.5, 'trace': 'aaaa'},
        {'t': 'span', 'name': 'serve.deliver', 'id': 3, 'par': 0,
         'rpar': 1, 'ts': 1.6, 'dur': 0.0, 'trace': 'aaaa',
         'attrs': {'status': 'completed'}},
    ]
    orphan = [
        {'t': 'span', 'name': 'serve.submit', 'id': 4, 'par': 0,
         'ts': 2.0, 'dur': 0.1, 'trace': 'bbbb',
         'attrs': {'request_id': 'o'}},
        {'t': 'span', 'name': 'serve.request', 'id': 5, 'par': 0,
         'rpar': 999, 'ts': 2.1, 'dur': 0.5, 'trace': 'bbbb'},
        {'t': 'span', 'name': 'serve.deliver', 'id': 6, 'par': 0,
         'rpar': 4, 'ts': 2.6, 'dur': 0.0, 'trace': 'bbbb',
         'attrs': {'status': 'completed'}},
    ]
    rep = request_report({7: good + orphan})
    assert rep['traces'] == 2
    assert rep['complete'] == 1
    assert rep['orphan_spans'] == 1
    assert rep['incomplete'] == ['bbbb']


# ---------------------------------------------------------------------------
# serve-layer propagation

def test_serve_waterfall_queue_service_split_and_slo(tmp_path):
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0)) as srv:
            results = [srv.wait(srv.submit(_req(i)), timeout=180)
                       for i in range(3)]
            summary = srv.summary()
    assert [r.status for r in results] == ['completed'] * 3
    # the split rides each result AND the summary (old combined
    # fields stay)
    for r in results:
        assert r.queue_wait_s is not None and r.service_s is not None
        assert r.latency_s >= r.service_s
    assert summary['queue_p99_s'] is not None
    assert summary['service_p99_s'] is not None
    assert summary['p99_s'] is not None
    assert summary['slo']['verdict'] == 'OK'
    assert summary['slo']['classes']  # keyed by shape class
    rep = _report(str(tmp_path))
    assert rep['traces'] == 3
    assert rep['complete'] == 3 and rep['orphan_spans'] == 0
    stages = rep['stage_totals_s']
    assert 'queue' in stages and 'service' in stages


def test_batched_group_members_link_to_leader_trace(tmp_path):
    """vmap-batched followers get a zero-duration link span tying
    their trace to the leader's — no request vanishes into a batch."""
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with _one_worker_server(
                batch=BatchPolicy(max_batch=4,
                                  max_delay_s=0.25)) as srv:
            blocker = srv.submit(_req(0, seed=5))
            tickets = [srv.submit(_req(i, seed=5))
                       for i in range(1, 4)]
            results = [srv.wait(t, timeout=180)
                       for t in [blocker] + tickets]
    assert all(r.status == 'completed' for r in results)
    assert any(r.batch_size > 1 for r in results)
    recs = _records(str(tmp_path))
    links = [r for r in recs if r.get('name') == 'serve.batch.member']
    assert links, 'no batch link spans emitted'
    traces = {r['trace'] for r in recs if r.get('trace')}
    for link in links:
        assert link['attrs']['leader_trace'] in traces
        assert link['trace'] != link['attrs']['leader_trace']
    rep = _report(str(tmp_path))
    assert rep['complete'] == rep['traces'] \
        and rep['orphan_spans'] == 0


def test_supervisor_retry_lands_in_request_trace(tmp_path):
    from nbodykit_tpu.resilience import RetryPolicy
    with nbodykit_tpu.set_options(
            diagnostics=str(tmp_path),
            faults='serve.request.attempt@2:unavailable'):
        reset_faults()
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0),
                retry=RetryPolicy(max_retries=3,
                                  base_s=0.01)) as srv:
            results = [srv.wait(srv.submit(_req(i, nmesh=32,
                                                npart=20000)),
                                timeout=180) for i in range(3)]
    assert all(r.status == 'completed' for r in results)
    faulted = [r for r in results if r.event_count('retries')]
    assert len(faulted) == 1
    recs = _records(str(tmp_path))
    retry = [r for r in recs if r.get('name') == 'resilience.retry']
    assert retry, 'retry event did not land in the trace'
    # the retry is attributed to exactly the faulted request's trace
    req_root = [r for r in recs if r.get('name') == 'serve.submit'
                and (r.get('attrs') or {}).get('request_id')
                == faulted[0].request_id and r['t'] == 'span']
    assert retry[0]['trace'] == req_root[0]['trace']
    rep = _report(str(tmp_path))
    assert rep['complete'] == rep['traces'] \
        and rep['orphan_spans'] == 0


def test_shadow_verify_span_rides_request_trace(tmp_path):
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0),
                verify_fraction=1.0) as srv:
            r = srv.wait(srv.submit(_req(0)), timeout=180)
    assert r.status == 'completed'
    recs = _records(str(tmp_path))
    ver = [x for x in recs if x.get('name') == 'serve.shadow_verify'
           and x['t'] == 'span']
    assert ver, 'no shadow-verify span'
    root = [x for x in recs if x.get('name') == 'serve.submit'
            and x['t'] == 'span']
    assert ver[0]['trace'] == root[0]['trace']
    rep = _report(str(tmp_path))
    assert rep['complete'] == rep['traces'] \
        and rep['orphan_spans'] == 0
    assert 'verify' in rep['stage_totals_s']


# ---------------------------------------------------------------------------
# region-layer propagation

def _region(tmp, fleets=1, qos=None, cache=True):
    return Region(
        [('f%d' % i, _one_worker_server()) for i in range(fleets)],
        result_cache=ResultCache(os.path.join(tmp, 'rcache'))
        if cache else None,
        qos=qos)


def test_region_pacer_hold_span_propagates(tmp_path):
    """A ticket held by the fair-share pacer and dispatched from the
    pacer thread still renders one linked waterfall, with the hold
    visible as a ``region.qos.hold`` stage."""
    qos = QoSPolicy(
        classes=[ServiceClass('interactive'),
                 ServiceClass('bulk', rate=4.0, burst=1)],
        tenants={'sweep': 'bulk'}, default_class='interactive')
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        region = _region(str(tmp_path), qos=qos)
        t1 = region.submit(_req(0, seed=1), tenant='sweep')
        t2 = region.submit(_req(1, seed=2), tenant='sweep')
        r1 = region.wait(t1, timeout=180)
        r2 = region.wait(t2, timeout=180)
        region.shutdown()
    assert r1.status == 'completed' and r2.status == 'completed'
    recs = _records(str(tmp_path))
    holds = [x for x in recs if x.get('name') == 'region.qos.hold']
    assert holds, 'held ticket emitted no qos.hold span'
    roots = {x['trace']: x for x in recs
             if x.get('name') == 'region.submit' and x['t'] == 'span'}
    assert holds[0]['trace'] in roots
    assert holds[0]['rpar'] == roots[holds[0]['trace']]['id']
    rep = _report(str(tmp_path))
    assert rep['complete'] == rep['traces'] \
        and rep['orphan_spans'] == 0
    assert 'qos_hold' in rep['stage_totals_s']


def test_region_singleflight_follower_links_and_cache_spans(tmp_path):
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        region = _region(str(tmp_path))
        lead = region.submit(_req(0, seed=9))
        follow = region.submit(_req(1, seed=9))
        r1 = region.wait(lead, timeout=180)
        r2 = region.wait(follow, timeout=180)
        # a later identical request is a result-cache hit
        hit = region.submit(_req(2, seed=9))
        r3 = region.wait(hit, timeout=60)
        summary = region.summary()
        region.shutdown()
    assert all(r.status == 'completed' for r in (r1, r2, r3))
    assert summary['routed'].get('follower', 0) >= 1
    assert summary['routed'].get('result_cache', 0) >= 1
    recs = _records(str(tmp_path))
    by_name = {}
    for r in recs:
        by_name.setdefault(r.get('name'), []).append(r)
    links = by_name.get('region.singleflight.follower')
    assert links, 'no follower link span'
    lead_root = [x for x in by_name['region.submit']
                 if x['t'] == 'span' and (x.get('attrs') or {})
                 .get('request_id') == r1.request_id][0]
    assert links[0]['attrs']['leader_trace'] == lead_root['trace']
    assert by_name.get('region.cache.commit'), 'no commit span'
    assert by_name.get('region.cache.hit'), 'no cache-hit span'
    rep = _report(str(tmp_path))
    assert rep['complete'] == rep['traces'] \
        and rep['orphan_spans'] == 0


def test_region_slo_and_flight_record_terminal_verdicts(tmp_path):
    qos = QoSPolicy(
        classes=[ServiceClass('interactive'),
                 ServiceClass('bulk', rate=1.0, burst=1)],
        tenants={'sweep': 'bulk'}, default_class='interactive')
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        region = _region(str(tmp_path), qos=qos)
        ok = region.wait(region.submit(_req(0, seed=3, prefix='obs-flt')),
                         timeout=180)
        # warm consumes the burst token so the tight-deadline pair
        # below cannot slip through and die a (burning) deadline death
        warm = region.submit(_req(1, seed=4, prefix='obs-flt'),
                             tenant='sweep')
        # due-time past the deadline -> qos_throttled eviction, which
        # must shed (never burn the availability budget)
        t1 = region.submit(_req(2, seed=5, deadline_s=0.05,
                                prefix='obs-flt'),
                           tenant='sweep')
        t2 = region.submit(_req(3, seed=6, deadline_s=0.05,
                                prefix='obs-flt'),
                           tenant='sweep')
        shed = [region.wait(t1, timeout=60),
                region.wait(t2, timeout=60)]
        warm_r = region.wait(warm, timeout=180)
        summary = region.summary()
        region.shutdown()
    assert ok.status == 'completed' and warm_r.status == 'completed'
    assert all(r.status == 'evicted'
               and r.reason['code'] == 'qos_throttled' for r in shed)
    slo = summary['slo']
    assert slo['verdict'] == 'OK'   # shedding is not failure
    bulk = slo['classes']['bulk']
    assert bulk['shed'] == 2 and bulk['bad'] == 0
    # the region (context owner) recorded every terminal verdict.
    # FLIGHT is a bounded ring: once an earlier test fills it to
    # maxlen, appends rotate instead of growing and a len()-based
    # slice sees nothing -- select by this test's unique id prefix.
    mine = [e for e in FLIGHT.snapshot()
            if (e.get('request_id') or '').startswith('obs-flt-')]
    assert len(mine) >= 4
    assert {e['layer'] for e in mine} == {'region'}


# ---------------------------------------------------------------------------
# SLO burn math

def test_slo_burn_windows_and_verdicts():
    t0 = 1000.0
    tr = SLOTracker()
    for i in range(100):
        tr.observe('interactive', latency_s=0.1, t=t0 + i)
    assert tr.verdict() == 'OK'
    # 1 failure in 101 at three-nines: burn ~10 -> slow-window WARN,
    # under the 14.4 fast-page bar
    tr.observe('interactive', status='failed', t=t0 + 100)
    assert tr.verdict() == 'WARN'
    # 5 failures: burn ~47 -> fast-window FAIL
    for i in range(4):
        tr.observe('interactive', status='failed', t=t0 + 101 + i)
    snap = tr.snapshot()
    assert snap['verdict'] == 'FAIL'
    w = snap['classes']['interactive']['windows']
    assert w['fast']['burn'] >= 14.4

    # load shedding never burns
    tr2 = SLOTracker()
    tr2.observe('bulk', latency_s=0.1, t=t0)
    for i in range(50):
        tr2.observe('bulk', status='qos_throttled', t=t0 + i)
        tr2.observe('bulk', status='rejected', t=t0 + i)
    assert tr2.verdict() == 'OK'
    assert tr2.snapshot()['classes']['bulk']['shed'] == 100

    # latency over threshold burns the latency budget
    tr3 = SLOTracker()
    for i in range(50):
        tr3.observe('interactive', latency_s=31.0, t=t0 + i)
    assert tr3.verdict() == 'FAIL'


# ---------------------------------------------------------------------------
# export plane

def test_exporter_serves_metrics_slo_flight_and_health():
    from nbodykit_tpu.diagnostics import counter, gauge
    counter('serve.completed').add(7)
    gauge('serve.queue_depth', fleet='x').set(2)
    tr = SLOTracker()
    tr.observe('interactive', latency_s=0.2)
    register_source('test', tr.snapshot)
    FLIGHT.record({'request_id': 'exp-1', 'status': 'completed'})
    exp = TelemetryExporter(port=0)
    try:
        base = exp.url
        text = urllib.request.urlopen(base + '/metrics').read().decode()
        assert 'serve_completed_total 7' in text
        assert 'serve_queue_depth{fleet="x"} 2' in text
        health = urllib.request.urlopen(base + '/healthz').read()
        assert health == b'ok\n'
        slo = json.loads(urllib.request.urlopen(base + '/slo').read())
        assert slo['test']['classes']['interactive']['total'] == 1
        raw = json.loads(
            urllib.request.urlopen(base + '/metrics.json').read())
        assert raw['serve.completed']['value'] == 7
        fl = json.loads(
            urllib.request.urlopen(base + '/flight').read())
        assert any(e.get('request_id') == 'exp-1'
                   for e in fl['requests'])
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + '/nope')
    finally:
        exp.stop()


def test_exporter_option_singleton(tmp_path):
    from nbodykit_tpu.diagnostics.export import ensure_exporter
    assert ensure_exporter() is None    # option unset -> disabled
    with nbodykit_tpu.set_options(telemetry_port=0):
        exp = ensure_exporter()
        assert exp is not None and exp.port > 0
        assert ensure_exporter() is exp     # idempotent singleton
        out = urllib.request.urlopen(exp.url + '/healthz').read()
        assert out == b'ok\n'
    stop_exporter()


def test_flight_dump_on_preemption(tmp_path):
    """Preempting a server seals the flight ring next to the trace —
    the post-mortem artifact the smoke gate asserts on."""
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0)) as srv:
            r = srv.wait(srv.submit(_req(0)), timeout=180)
            assert r.status == 'completed'
            srv.preempt()
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith('flight-') and f.endswith('.json')]
    assert dumps, 'preempt sealed no flight dump'
    body = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    assert body['reason'].startswith('serve.preempt')
    assert any(e.get('request_id') == r.request_id
               for e in body['requests'])
    assert 'metrics' in body and 'sources' in body


# ---------------------------------------------------------------------------
# regress / doctor posture

def test_slo_summary_reads_round_and_doctor_renders_verdict(tmp_path):
    from nbodykit_tpu.diagnostics.regress import (build_history,
                                                  render_regress,
                                                  slo_summary)
    rec = {'metric': 'regiontrace_n24', 'unit': 's', 'value': 0.4,
           'requests': 24, 'lost': 0,
           'slo': {'verdict': 'OK', 'classes': {
               'interactive': {'verdict': 'OK', 'total': 20,
                               'shed': 0, 'bad': 0, 'p99_s': 0.4,
                               'windows': {
                                   'fast': {'burn': 0.0},
                                   'slow': {'burn': 0.0}}}}},
           'waterfalls': {'traces': 24, 'complete': 24,
                          'complete_fraction': 1.0,
                          'orphan_spans': 0},
           'trace_overhead': {'n': 24, 'overhead': 0.012,
                              'wall_on_s': 2.0, 'wall_off_s': 1.98},
           'measured_at': '2026-08-06T00:00:00Z'}
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'cmd': 'bench --region-trace 24', 'parsed': rec}))
    slo = slo_summary(str(tmp_path))
    assert slo['verdict'] == 'OK'
    assert slo['complete'] == 24 and slo['orphan_spans'] == 0
    assert slo['overhead'] == 0.012
    assert slo['classes']['interactive']['fast_burn'] == 0.0
    history = build_history(str(tmp_path), write=False)
    text = render_regress(history)
    line = next(l for l in text.splitlines()
                if l.strip().startswith('slo:'))
    assert '24/24 waterfall(s) complete' in line
    assert 'overhead 1.2%' in line

    import io
    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    out = io.StringIO()
    run_doctor(root=str(tmp_path), out=out)
    text = out.getvalue()
    line = next(l for l in text.splitlines() if l.startswith('slo '))
    assert 'OK' in line

    # an over-budget overhead or a burning fast window must FAIL
    rec2 = dict(rec, trace_overhead={'n': 24, 'overhead': 0.09,
                                     'wall_on_s': 2, 'wall_off_s': 1})
    (tmp_path / 'BENCH_r02.json').write_text(json.dumps(
        {'cmd': 'bench', 'parsed': rec2}))
    out = io.StringIO()
    rc = run_doctor(root=str(tmp_path), out=out)
    assert rc == 1
    assert 'slo          FAIL' in out.getvalue()
