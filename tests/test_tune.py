"""Tests for nbodykit_tpu.tune: cache roundtrip + atomicity,
nearest-shape-class fallback, deterministic trial plans,
infeasible-candidate handling via fault injection, and the 'auto'
resolution contract — cold cache falls back to today's defaults with
zero trial overhead, warm cache selects the measured winner (asserted
against the committed repo TUNE_CACHE.json on the 8-device CPU
mesh)."""

import json
import os

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY
from nbodykit_tpu.resilience import reset_faults
from nbodykit_tpu.tune import (Candidate, SearchSpace, TuneCache,
                               cache_summary, class_coords,
                               class_distance, device_signature,
                               entry_key, plan_spaces,
                               reset_cache_memo, resolve_exchange_slack,
                               resolve_fft_chunk_bytes, resolve_paint,
                               resolve_paint_deposit, run_space,
                               shape_class, tuned_snapshot,
                               validate_cache)
from nbodykit_tpu.tune.space import (_paint_runner, default_spaces,
                                     paint_space)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED = os.path.join(REPO, 'TUNE_CACHE.json')


@pytest.fixture(autouse=True)
def _clean_state():
    """Options, registry, fault counts and the cache mtime memo are
    process-wide; every test sees (and leaves) a pristine copy."""
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    reset_cache_memo()
    yield
    REGISTRY.reset()
    reset_faults()
    reset_cache_memo()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _counter(name):
    snap = REGISTRY.snapshot().get(name)
    return snap['value'] if snap else 0


def _entry(op='paint', sclass='mesh16-part1e3', winner=None,
           device_count=1, platform='cpu', device_kind='cpu',
           measured_at='2026-08-04T00:00:00Z', **extra):
    return dict({
        'platform': platform, 'device_kind': device_kind,
        'device_count': device_count, 'op': op, 'shape_class': sclass,
        'dtype': 'float32', 'winner': winner, 'winner_name':
        next(iter(winner.values())) if winner else None,
        'trials': {}, 'infeasible': [], 'measured_at': measured_at,
    }, **extra)


# ---------------------------------------------------------------------------
# shape classes

def test_shape_class_buckets():
    assert shape_class(64, 10_000) == 'mesh64-part1e4'
    assert shape_class(100, 9e4) == 'mesh128-part1e5'
    assert shape_class(512) == 'mesh512'
    assert shape_class(npart=1e7) == 'part1e7'
    with pytest.raises(ValueError):
        shape_class()


def test_class_coords_and_distance():
    assert class_coords('mesh64-part1e4') == (6.0, 4.0)
    assert class_coords('mesh512') == (9.0, None)
    assert class_coords('part1e7') == (None, 7.0)
    assert class_coords('nonsense') is None
    assert class_distance('mesh64-part1e4', 'mesh64-part1e4') == 0.0
    assert class_distance('mesh64', 'mesh256') == 2.0
    # different axes are not comparable
    assert class_distance('mesh64', 'part1e4') is None
    assert class_distance('mesh64', 'mesh64-part1e4') is None


# ---------------------------------------------------------------------------
# cache roundtrip / atomicity / fallback

def test_cache_roundtrip_and_atomic_commit(tmp_path):
    path = str(tmp_path / 'TC.json')
    tc = TuneCache(path)
    assert tc.entries() == {}          # cold cache is just empty
    key = tc.put(_entry(winner={'paint_method': 'sort'}))
    # a fresh instance reads the committed file, exact lookup hits
    tc2 = TuneCache(path)
    entry, match = tc2.lookup('cpu', 'cpu', 1, 'paint',
                              'mesh16-part1e3', 'f4')
    assert match == 'exact'
    assert entry['winner'] == {'paint_method': 'sort'}
    assert entry_key(entry) == key
    # tmp+rename discipline: no tmp siblings survive the commit
    assert [f for f in os.listdir(tmp_path) if 'tmp' in f] == []
    # a second put merges (and overwrites same-key entries)
    tc2.put(_entry(sclass='mesh64-part1e4',
                   winner={'paint_method': 'scatter'}))
    tc2.put(_entry(winner={'paint_method': 'scatter'}))
    entries = TuneCache(path).entries()
    assert len(entries) == 2
    entry, match = TuneCache(path).lookup('cpu', 'cpu', 1, 'paint',
                                          'mesh16-part1e3', 'f4')
    assert entry['winner'] == {'paint_method': 'scatter'}
    assert validate_cache(path) == []


def test_cache_corrupt_file_is_empty_and_invalid(tmp_path):
    path = str(tmp_path / 'TC.json')
    with open(path, 'w') as f:
        f.write('{"entries": {"k": ')       # torn write
    assert TuneCache(path).entries() == {}
    assert validate_cache(path)             # non-empty problem list
    # a well-formed file with a mis-keyed entry is caught too
    good = _entry(winner={'paint_method': 'sort'})
    with open(path, 'w') as f:
        json.dump({'version': 1, 'entries': {'wrong|key': good}}, f)
    problems = validate_cache(path)
    assert any('does not match' in p for p in problems)


def test_cache_nearest_fallback(tmp_path):
    tc = TuneCache(str(tmp_path / 'TC.json'))
    tc.put(_entry(sclass='mesh64-part1e4',
                  winner={'paint_method': 'sort'}))
    tc.put(_entry(sclass='mesh1024-part1e8',
                  winner={'paint_method': 'scatter'}))
    # miss on the exact class -> nearest (log-space) same-sig entry
    entry, match = tc.lookup('cpu', 'cpu', 1, 'paint',
                             'mesh128-part1e5', 'f4')
    assert match == 'nearest'
    assert entry['winner'] == {'paint_method': 'sort'}
    # other platform / device kind never matches
    assert tc.lookup('tpu', 'v5e', 1, 'paint', 'mesh64-part1e4',
                     'f4') == (None, 'miss')
    # same-count entries are preferred over closer other-count ones
    tc.put(_entry(sclass='mesh128-part1e5', device_count=8,
                  winner={'paint_method': 'mxu'}))
    entry, match = tc.lookup('cpu', 'cpu', 1, 'paint',
                             'mesh128-part1e5', 'f4')
    assert entry['device_count'] == 1 and match == 'nearest'
    # ...but an other-count entry is still reachable when it is all
    # there is
    entry, match = tc.lookup('cpu', 'cpu', 8, 'paint',
                             'mesh128-part1e5', 'f4')
    assert entry['winner'] == {'paint_method': 'mxu'}
    assert match == 'exact'


def test_winnerless_entries_never_steer(tmp_path):
    tc = TuneCache(str(tmp_path / 'TC.json'))
    tc.put(_entry(winner=None, infeasible=['scatter', 'sort']))
    assert tc.lookup('cpu', 'cpu', 1, 'paint', 'mesh16-part1e3',
                     'f4') == (None, 'miss')


# ---------------------------------------------------------------------------
# trial plans + infeasible handling

def test_trial_plan_deterministic():
    spaces = default_spaces()
    pairs = [(spaces['paint'], {'nmesh': 64, 'npart': 10_000,
                                'dtype': 'f4', 'seed': 7}),
             (spaces['fft'], {'nmesh': 64, 'dtype': 'f4', 'seed': 7})]
    sig = ('cpu', 'cpu', 8)
    p1 = plan_spaces(pairs, reps=2, signature=sig)
    p2 = plan_spaces(pairs, reps=2, signature=sig)
    assert p1 == p2
    assert p1[0]['key'] == 'cpu|cpu|8|paint|mesh64-part1e4|float32'
    assert 'scatter' in p1[0]['candidates']
    assert 'sort' in p1[0]['candidates']
    # the ISSUE 8 kernel families compete deterministically: both
    # segsum orders, and every stream count memory_plan admits at
    # this shape (all of {2,4,8} at mesh64/1e4)
    for name in ('segsum-argsort', 'segsum-radix',
                 'streams2', 'streams4', 'streams8'):
        assert name in p1[0]['candidates']


def _tiny_paint_space():
    """A two-candidate paint space small enough for tier-1."""
    return SearchSpace(
        'paint', ('paint_method', 'paint_chunk_size'),
        lambda ctx: [Candidate('scatter', {'paint_method': 'scatter'}),
                     Candidate('sort', {'paint_method': 'sort'})],
        _paint_runner)


def test_run_space_commits_measured_winner(tmp_path):
    tc = TuneCache(str(tmp_path / 'TC.json'))
    ctx = {'nmesh': 16, 'npart': 400, 'dtype': 'f4', 'seed': 7}
    entry = run_space(_tiny_paint_space(), ctx, cache=tc, reps=1)
    assert entry['winner_name'] in ('scatter', 'sort')
    assert entry['winner']['paint_method'] == entry['winner_name']
    assert entry['infeasible'] == []
    for rec in entry['trials'].values():
        assert rec['wall_s'] > 0 and rec['reps'] == 1
    assert _counter('tune.trials') == 2
    # and it landed in the cache, resolvable at this signature
    sig = device_signature(count=1)
    got, match = tc.lookup(sig[0], sig[1], 1, 'paint',
                           'mesh16-part1e3', 'f4')
    assert match == 'exact' and got['winner_name'] == entry['winner_name']


def test_infeasible_candidate_via_fault_injection(tmp_path):
    """An injected RESOURCE_EXHAUSTED at the first trial attempt (the
    same spec `NBKIT_FAULTS` carries into detached workers) condemns
    that candidate only; the tune run survives and the other
    candidate wins."""
    tc = TuneCache(str(tmp_path / 'TC.json'))
    nbodykit_tpu.set_options(
        faults='tune.trial.attempt@1:resource_exhausted')
    ctx = {'nmesh': 16, 'npart': 400, 'dtype': 'f4', 'seed': 7}
    entry = run_space(_tiny_paint_space(), ctx, cache=tc, reps=1)
    assert entry['infeasible'] == ['scatter']
    assert entry['trials']['scatter']['infeasible'] == 'oom'
    assert 'RESOURCE_EXHAUSTED' in entry['trials']['scatter']['error']
    assert entry['winner_name'] == 'sort'
    assert _counter('tune.infeasible') == 1
    assert _counter('tune.trials') == 1


def test_all_infeasible_commits_winnerless_entry(tmp_path):
    tc = TuneCache(str(tmp_path / 'TC.json'))
    nbodykit_tpu.set_options(
        faults='tune.trial.attempt@1:internal,'
               'tune.trial.attempt@2:internal')
    ctx = {'nmesh': 16, 'npart': 400, 'dtype': 'f4', 'seed': 7}
    entry = run_space(_tiny_paint_space(), ctx, cache=tc, reps=1)
    assert entry['winner'] is None
    assert sorted(entry['infeasible']) == ['scatter', 'sort']
    # the committed winner-less entry is posture, not guidance
    assert tc.lookup('cpu', 'cpu', 1, 'paint', 'mesh16-part1e3',
                     'f4') == (None, 'miss')


# ---------------------------------------------------------------------------
# 'auto' resolution

def test_auto_cold_cache_zero_trials(tmp_path):
    import jax.numpy as jnp
    nbodykit_tpu.set_options(
        tune_cache=str(tmp_path / 'ABSENT.json'),
        paint_method='auto', fft_chunk_bytes='auto')
    cfg = resolve_paint(nmesh=16, npart=500, nproc=1)
    assert cfg['paint_method'] == 'scatter'
    assert cfg['source'] == 'default'
    # the new knob resolves to its concrete fallback on a cold cache
    assert cfg['paint_streams'] == 4
    assert resolve_fft_chunk_bytes(shape=(16, 16, 16)) == 2 ** 31
    # resolution NEVER runs trials: cold cache == today's defaults
    assert _counter('tune.trials') == 0
    # end to end: an eager paint under 'auto' matches explicit scatter
    from nbodykit_tpu.pmesh import ParticleMesh
    pm = ParticleMesh(Nmesh=16, BoxSize=100.0, dtype='f4')
    pos = jnp.asarray(np.random.RandomState(0).uniform(
        0, 100, (300, 3)).astype('f4'))
    auto = pm.paint(pos, 1.0)
    with nbodykit_tpu.set_options(paint_method='scatter'):
        explicit = pm.paint(pos, 1.0)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(explicit))
    assert _counter('tune.trials') == 0
    assert _counter('tune.resolve.miss') > 0


def test_auto_warm_cache_selects_winner(tmp_path):
    import jax.numpy as jnp
    path = str(tmp_path / 'TC.json')
    TuneCache(path).put(_entry(winner={'paint_method': 'sort'}))
    nbodykit_tpu.set_options(tune_cache=path, paint_method='auto')
    cfg = resolve_paint(nmesh=16, npart=1000, nproc=1)
    assert cfg['paint_method'] == 'sort'
    assert cfg['source'] == 'cache'
    assert _counter('tune.resolve.hit') == 1
    # the tuned kernel actually runs: the sort paint's trace counter
    # bumps when the 'auto' paint executes
    from nbodykit_tpu.pmesh import ParticleMesh
    pm = ParticleMesh(Nmesh=16, BoxSize=100.0, dtype='f4')
    pos = jnp.asarray(np.random.RandomState(1).uniform(
        0, 100, (1000, 3)).astype('f4'))
    before = _counter('paint.trace.sort')
    out = pm.paint(pos, 1.0)
    np.testing.assert_allclose(float(out.sum()), 1000.0, rtol=1e-4)
    assert _counter('paint.trace.sort') == before + 1


def test_auto_explicit_options_never_overridden(tmp_path):
    path = str(tmp_path / 'TC.json')
    TuneCache(path).put(_entry(winner={'paint_method': 'mxu',
                                       'paint_order': 'radix'}))
    nbodykit_tpu.set_options(tune_cache=path, paint_method='auto',
                             paint_order='argsort')
    cfg = resolve_paint(nmesh=16, npart=1000, nproc=1)
    assert cfg['paint_method'] == 'mxu'       # asked: from the cache
    assert cfg['paint_order'] == 'argsort'    # explicit: untouched
    # a fully explicit call never consults the cache at all
    nbodykit_tpu.set_options(paint_method='scatter',
                             paint_order='auto')
    REGISTRY.reset()
    cfg = resolve_paint(nmesh=16, npart=1000, nproc=1)
    assert cfg['source'] == 'explicit'
    assert _counter('tune.resolve.hit') == 0
    assert _counter('tune.resolve.miss') == 0


def test_auto_mxu_winner_keeps_traced_contract(tmp_path):
    """A cached mxu winner must not impose the traced-overflow
    contract on an 'auto' caller inside jit: the call falls back to
    scatter instead of raising; an EXPLICIT mxu still raises."""
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.pmesh import ParticleMesh
    path = str(tmp_path / 'TC.json')
    TuneCache(path).put(_entry(winner={'paint_method': 'mxu'}))
    nbodykit_tpu.set_options(tune_cache=path, paint_method='auto')
    pm = ParticleMesh(Nmesh=16, BoxSize=100.0, dtype='f4')
    pos = jnp.asarray(np.random.RandomState(2).uniform(
        0, 100, (1000, 3)).astype('f4'))
    out = jax.jit(lambda p: pm.paint(p, 1.0))(pos)
    np.testing.assert_allclose(float(out.sum()), 1000.0, rtol=1e-4)
    with nbodykit_tpu.set_options(paint_method='mxu'):
        with pytest.raises(ValueError, match='return_dropped'):
            jax.jit(lambda p: pm.paint(p, 1.0))(pos)


def test_fft_chunk_bytes_auto(tmp_path):
    from nbodykit_tpu.parallel.dfft import _fft_chunk_bytes
    path = str(tmp_path / 'TC.json')
    TuneCache(path).put(_entry(op='fft', sclass='mesh16',
                               winner={'fft_chunk_bytes': 1 << 20}))
    nbodykit_tpu.set_options(tune_cache=path, fft_chunk_bytes='auto')
    assert _fft_chunk_bytes((16, 16, 16), 'f4') == 1 << 20
    # complex dtypes key by their real base: the c2r path sees the
    # same winner
    assert _fft_chunk_bytes((16, 16, 9), np.dtype('c8')) == 1 << 20
    # an explicit integer bypasses the cache entirely
    with nbodykit_tpu.set_options(fft_chunk_bytes=123):
        assert _fft_chunk_bytes((16, 16, 16), 'f4') == 123


def test_ladder_halves_auto_resolved_value(tmp_path):
    from nbodykit_tpu.resilience import default_ladder
    nbodykit_tpu.set_options(
        tune_cache=str(tmp_path / 'ABSENT.json'),
        fft_chunk_bytes='auto')
    lad = default_ladder()
    label, detail = lad.step()
    assert label == 'fft_chunk_bytes/2'
    assert detail == {'fft_chunk_bytes': 2 ** 30, 'was': 2 ** 31}
    # the rung PINNED the option to a concrete int
    assert _global_options['fft_chunk_bytes'] == 2 ** 30


def test_exchange_slack_and_deposit_resolution(tmp_path):
    path = str(tmp_path / 'TC.json')
    tc = TuneCache(path)
    tc.put(_entry(op='exchange', sclass='part1e5',
                  winner={'exchange_slack': 2.0}))
    tc.put(_entry(winner={'paint_method': 'mxu',
                          'paint_deposit': 'pallas'}))
    nbodykit_tpu.set_options(tune_cache=path)
    assert resolve_exchange_slack(npart=100_000, nproc=1) == 2.0
    assert resolve_paint_deposit(nmesh=16, npart=1000) == 'pallas'
    # cold fallbacks
    nbodykit_tpu.set_options(tune_cache=str(tmp_path / 'NONE.json'))
    reset_cache_memo()
    assert resolve_exchange_slack(npart=100_000, nproc=1) == 1.05
    assert resolve_paint_deposit(nmesh=16, npart=1000) == 'xla'


def test_tuned_snapshot_records_sources(tmp_path):
    nbodykit_tpu.set_options(
        tune_cache=str(tmp_path / 'ABSENT.json'),
        paint_method='auto', fft_chunk_bytes='auto')
    snap = tuned_snapshot(nmesh=16, npart=500, nproc=1)
    assert snap['paint_method'] == 'scatter'
    assert snap['paint_source'] == 'default'
    assert snap['fft_chunk_bytes'] == 2 ** 31
    assert snap['fft_source'] == 'auto'
    nbodykit_tpu.set_options(paint_method='scatter',
                             fft_chunk_bytes=2 ** 28)
    snap = tuned_snapshot(nmesh=16, npart=500, nproc=1)
    assert snap['paint_source'] == 'explicit'
    assert snap['fft_source'] == 'explicit'
    assert snap['fft_chunk_bytes'] == 2 ** 28


# ---------------------------------------------------------------------------
# posture: doctor / regression tracking

def test_tune_summary_in_bench_history(tmp_path):
    from nbodykit_tpu.diagnostics.regress import (build_history,
                                                  tune_summary)
    root = str(tmp_path)
    assert tune_summary(root) is None       # no cache file -> None
    tc = TuneCache(os.path.join(root, 'TUNE_CACHE.json'))
    tc.put(_entry(winner={'paint_method': 'sort'},
                  measured_at='2020-01-01T00:00:00Z'))   # stale
    tc.put(_entry(op='fft', sclass='mesh64', platform='tpu',
                  device_kind='v5e',
                  winner={'fft_chunk_bytes': 1 << 26},
                  infeasible=['chunk2g']))
    summary = tune_summary(root)
    assert summary['entries'] == 2
    assert summary['stale'] == 1
    assert summary['infeasible'] == 1
    assert summary['platforms'] == ['cpu/cpu', 'tpu/v5e']
    history = build_history(root, write=False)
    assert history['tune']['entries'] == 2
    # a malformed cache is reported, not raised
    with open(os.path.join(root, 'TUNE_CACHE.json'), 'w') as f:
        f.write('not json')
    reset_cache_memo()
    assert 'error' in tune_summary(root)


# ---------------------------------------------------------------------------
# CLI

def test_cli_dry_run_is_deterministic(tmp_path, capsys):
    from nbodykit_tpu.tune.__main__ import main
    args = ['--dry-run', '--devices', '8',
            '--cache', str(tmp_path / 'TC.json')]
    assert main(args) == 0
    out1 = json.loads(capsys.readouterr().out)
    assert main(args) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out1 == out2
    ops = [p['op'] for p in out1['plan']]
    assert ops.count('paint') == 2 and 'fft' in ops
    assert all('|' in p['key'] for p in out1['plan'])
    # every paint plan carries the stream/segsum families (the CLI's
    # default shapes are small enough for all stream counts to fit)
    for p in out1['plan']:
        if p['op'] == 'paint':
            for name in ('segsum-argsort', 'segsum-radix',
                         'streams2', 'streams4', 'streams8'):
                assert name in p['candidates']
    # dry-run touches nothing: no cache file, no trials
    assert not os.path.exists(str(tmp_path / 'TC.json'))
    assert _counter('tune.trials') == 0


def test_cli_validate_gate(tmp_path, capsys):
    from nbodykit_tpu.tune.__main__ import main
    absent = str(tmp_path / 'ABSENT.json')
    assert main(['--validate', '--cache', absent]) == 0
    capsys.readouterr()
    bad = str(tmp_path / 'BAD.json')
    with open(bad, 'w') as f:
        f.write('{"entries": []}')
    assert main(['--validate', '--cache', bad]) == 1


# ---------------------------------------------------------------------------
# acceptance: the committed database

def test_committed_cache_is_valid():
    assert os.path.exists(COMMITTED), \
        'the committed TUNE_CACHE.json is part of this PR'
    assert validate_cache(COMMITTED) == []
    summary = cache_summary(COMMITTED)
    paint_classes = {
        e['shape_class'] for e in TuneCache(COMMITTED).entries().values()
        if e['op'] == 'paint' and e['platform'] == 'cpu'
        and e['device_count'] == 8 and e['winner']}
    assert len(paint_classes) >= 2, \
        'committed cache must cover paint at two shape-classes on ' \
        'the 8-device CPU mesh: %s' % summary


def test_committed_cache_resolves_auto_on_cpu8(cpu8):
    """The acceptance path: on the 8-device CPU mesh,
    set_options(paint_method='auto') resolves through the committed
    TUNE_CACHE.json to the measured winner."""
    from nbodykit_tpu.parallel.runtime import use_mesh
    entries = [e for e in TuneCache(COMMITTED).entries().values()
               if e['op'] == 'paint' and e['platform'] == 'cpu'
               and e['device_count'] == 8 and e['winner']]
    assert entries
    entry = entries[0]
    ctx = entry['context']
    nbodykit_tpu.set_options(tune_cache=COMMITTED,
                             paint_method='auto')
    with use_mesh(cpu8):
        cfg = resolve_paint(nmesh=ctx['nmesh'], npart=ctx['npart'],
                            nproc=8)
    assert cfg['source'] == 'cache'
    assert cfg['paint_method'] == \
        entry['winner']['paint_method']
    assert _counter('tune.resolve.hit') == 1
