"""Tests for the Einstein-Boltzmann engine (cosmology/boltzmann.py).

Golden values are published Planck-chain / CLASS-derived numbers
(z_drag, r_drag, conformal distance), plus internal-consistency checks
(superhorizon curvature conservation, the 9/10 potential dip, gauge
suppression of the comoving density) that do not require CLASS.
"""

import numpy as np
import pytest

from nbodykit_tpu.cosmology import boltzmann as B


def _planckish(**kw):
    pars = dict(h=0.67556, T0_cmb=2.7255, Omega_b=0.0482754,
                Omega_cdm=0.263771, m_ncdm=[0.06], N_ur=2.0328)
    pars.update(kw)
    return B.Background(**pars)


@pytest.fixture(scope='module')
def bgth():
    bg = _planckish()
    return bg, B.Thermodynamics(bg)


def test_ncdm_density(bgth):
    bg, th = bgth
    # CLASS convention: omega_ncdm = m / 93.14 eV for T_ncdm/T = 0.71611
    assert np.isclose(bg.Omega_ncdm * bg.h ** 2, 0.06 / 93.14, rtol=2e-4)
    # relativistic limit at early times: rho -> (7/8) Tr^4 rho_g
    s = bg.ncdm[0]
    rel = (7.0 / 8) * B.T_NCDM_RATIO ** 4 * bg.Omega_g
    assert np.isclose(s.rho_over_rhocrit0(1e-6) * 1e-24, rel, rtol=1e-6)


def test_conformal_distance_golden(bgth):
    """chi(z=1) = 3396.16 Mpc: the reference's own golden value
    (nbodykit cosmology/tests/test_cosmology.py::test_cosmology_sane,
    c.tau(1.0) with classylss)."""
    bg, th = bgth
    chi = bg.tau(1.0) - bg.tau(0.5)
    assert np.isclose(chi, 3396.158162, rtol=5e-4)


def test_recombination_epochs(bgth):
    bg, th = bgth
    # Planck-chain values for essentially these parameters
    assert abs(th.z_drag - 1060.0) < 8.0
    assert abs(th.rs_drag - 147.2) < 1.5
    assert 1060 < th.z_rec < 1105
    assert th.xe(0.0) > 1.0           # reionized
    assert th.xe(500.0) < 1e-3        # dark ages
    assert 0.04 < th.tau_reio < 0.12
    assert th.Tb(0.0) > 0.0
    assert th.cs2_b(1.0) >= 0.0


def test_superhorizon_curvature_conservation(bgth):
    """R = phi + 2(phi'/Hc + psi)/(3(1+w)) conserved through equality
    and the classic phi_MD = (9/10) phi_RD dip (here with neutrinos)."""
    bg, th = bgth
    s = B.BoltzmannSolver(bg, th)
    lna_out = np.sort(np.log(1.0 / (1.0 + np.array([1e5, 50.0]))))
    out = s.solve_mode(1e-5, lna_out)
    phi_rd, phi_md = out['phi']
    # with R_nu ~ 0.41: phi_MD/phi_RD = (9/10)(1 + 4 R_nu/15)/(1 + 2 R_nu/5)
    rho_g = bg.Omega_g
    rho_nu = bg.Omega_ur + sum(sp._rel_density for sp in bg.ncdm)
    R_nu = rho_nu / (rho_g + rho_nu)
    expect = 0.9 * (1 + 4 * R_nu / 15) / (1 + 2 * R_nu / 5)
    assert np.isclose(phi_md / phi_rd, expect, rtol=0.015)
    # absolute normalization: R = 1 -> phi_RD = (2/3)(1 + 2Rnu/5)/(1 + 4Rnu/15)...
    psi_rd = 10.0 / (15.0 + 4.0 * R_nu)
    assert np.isclose(phi_rd, (1 + 2 * R_nu / 5) * psi_rd, rtol=0.01)


@pytest.mark.slow
def test_pk_shape_vs_eisenstein_hu():
    """P(k, z=0) shape within ~6% of the full EH transfer over the
    quasi-linear range (EH itself is a few-percent approximation and
    has no neutrino suppression)."""
    bg = _planckish()
    th = B.Thermodynamics(bg)
    eng = B.BoltzmannEngine(bg, th, A_s=2.215e-9, n_s=0.9667,
                            P_k_max=2.0, cache=False)
    from nbodykit_tpu.cosmology import Cosmology
    from nbodykit_tpu.cosmology.power.transfers import EisensteinHu
    c = Cosmology(h=0.67556, Omega0_b=0.0482754, Omega0_cdm=0.263771,
                  n_s=0.9667, A_s=2.215e-9, m_ncdm=0.06)
    T = EisensteinHu(c, 0.0)
    kh = np.logspace(-4, np.log10(1.5), 25)
    r = eng.get_pklin(kh, 0.0) / (kh ** 0.9667 * T(kh) ** 2)
    r = r / r[10]
    assert np.all(np.abs(r - 1.0) < 0.075), r
    # sigma8 in the Planck ballpark for this A_s
    assert 0.80 < eng.sigma8 < 0.86


@pytest.mark.slow
def test_growth_matches_background_ode():
    """Scale-independent growth from the Boltzmann solve matches the
    background growth ODE to ~1% (k = 0.15/Mpc is above the neutrino
    free-streaming scale, so the Boltzmann growth is physically ~1%
    lower than the all-matter ODE: free-streaming neutrinos do not
    cluster there but the ODE sources with the full Omega_m)."""
    bg = _planckish()
    th = B.Thermodynamics(bg)
    s = B.BoltzmannSolver(bg, th)
    zs = np.array([9.0, 1.0, 0.0])
    lna_out = np.sort(np.log(1 / (1 + zs)))
    out = s.solve_mode(0.15, lna_out)  # 1/Mpc
    g_boltz = out['d_cdm'][-1] / out['d_cdm'][0]     # D(0)/D(9)
    from nbodykit_tpu.cosmology import Cosmology
    c = Cosmology(h=0.67556, Omega0_b=0.0482754, Omega0_cdm=0.263771,
                  m_ncdm=0.06)
    g_ode = (c.scale_independent_growth_factor(0.0)
             / c.scale_independent_growth_factor(9.0))
    assert np.isclose(g_boltz, g_ode, rtol=0.02)
    # and the deficit has the free-streaming sign
    assert g_boltz < g_ode
