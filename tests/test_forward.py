"""Tests for nbodykit_tpu.forward: the differentiable forward model
(docs/FORWARD.md).

Finite-difference gradient checks for every adjoint in the pipeline —
paint (each kernel's contract), readout, the Poisson force, and the
full LPT+KDK+paint map on the 8-device mesh.  All FD probes run f8
with eps=1e-6: the CIC window is continuous but kinked, so larger eps
sits on the kink noise (1-10% apparent error for a CORRECT gradient)
while 1e-6 converges to ~1e-7 relative.  Multi-device pipelines are
always jitted — eager shard_map re-traces per call and is pathological.

Plus: 2LPT-vs-Zel'dovich displacement asymptotics, bit-identical
forward replay, field-level recovery beating the FFTRecon baseline
(the 128^3 toy is slow-tier), and the serve plane's Forward request
paths (validate / admit / degrade / reject / end-to-end with shadow
verification).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from nbodykit_tpu.forward import (ForwardModel, fftrecon_baseline,
                                  linear_init, lpt_init, make_loss,
                                  make_paint, mean_cross_correlation,
                                  normalized_amplitude, recover)
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.pmesh import ParticleMesh, memory_plan

requires_x64 = pytest.mark.skipif(
    not jax.config.jax_enable_x64,
    reason="finite-difference gradient checks need f8")


def _fd_vs_grad(loss, x, d, eps=1e-6):
    """(central finite difference, <grad, d>) along unit direction d."""
    d = d / jnp.sqrt(jnp.sum(d * d))
    g = jax.grad(loss)(x)
    fd = (float(loss(x + eps * d)) - float(loss(x - eps * d))) \
        / (2.0 * eps)
    return fd, float(jnp.sum(g * d))


def _assert_close(fd, dot, rtol):
    assert abs(fd - dot) <= rtol * max(abs(fd), abs(dot), 1e-10), \
        "FD %r vs grad %r (rel %.3g)" % (
            fd, dot, abs(fd - dot) / max(abs(fd), 1e-300))


# ---------------------------------------------------------------------------
# per-kernel paint adjoints (single device, eager — small and exact)

@requires_x64
@pytest.mark.parametrize('method',
                         ['scatter', 'sort', 'segsum', 'streams'])
def test_paint_adjoint_matches_fd(method):
    pm = ParticleMesh(Nmesh=8, BoxSize=100.0, dtype='f8')
    npart = 64
    paint, cfg = make_paint(pm, npart, 'cic', method=method)
    assert cfg['adjoint_mode'] == (
        'native' if method == 'scatter' else 'custom_vjp')
    rng = np.random.RandomState(42)
    pos = jnp.asarray(rng.uniform(0.0, 100.0, (npart, 3)))
    mass = jnp.asarray(1.0 + 0.5 * rng.random_sample(npart))
    tgt = jnp.asarray(rng.normal(size=pm.shape_real))

    fd, dot = _fd_vs_grad(
        lambda p: jnp.sum(tgt * paint(p, mass)), pos,
        jnp.asarray(rng.normal(size=(npart, 3))))
    _assert_close(fd, dot, 1e-5)
    fd, dot = _fd_vs_grad(
        lambda m: jnp.sum(tgt * paint(pos, m)), mass,
        jnp.asarray(rng.normal(size=npart)))
    _assert_close(fd, dot, 1e-5)


def test_make_paint_refuses_mxu_pin():
    pm = ParticleMesh(Nmesh=8, BoxSize=100.0, dtype='f8')
    with pytest.raises(ValueError, match='adjoint contract'):
        make_paint(pm, 64, 'cic', method='mxu')


@requires_x64
def test_readout_gradient_matches_fd():
    pm = ParticleMesh(Nmesh=8, BoxSize=100.0, dtype='f8')
    rng = np.random.RandomState(1)
    field = jnp.asarray(rng.normal(size=pm.shape_real))
    pos = jnp.asarray(rng.uniform(0.0, 100.0, (32, 3)))
    fd, dot = _fd_vs_grad(
        lambda p: jnp.sum(pm.readout(field, p) ** 2), pos,
        jnp.asarray(rng.normal(size=(32, 3))))
    _assert_close(fd, dot, 1e-5)


@requires_x64
def test_poisson_force_gradient_matches_fd():
    """paint -> k-space Poisson solve -> force readout, as one map."""
    model = ForwardModel(8, 64, BoxSize=100.0, pm_steps=1, dtype='f8')
    rng = np.random.RandomState(2)
    pos = jnp.asarray(rng.uniform(0.0, 100.0, (64, 3)))
    cot = jnp.asarray(rng.normal(size=(64, 3)))
    fd, dot = _fd_vs_grad(
        lambda p: jnp.sum(cot * model.gravity(p)), pos,
        jnp.asarray(rng.normal(size=(64, 3))))
    _assert_close(fd, dot, 1e-4)


# ---------------------------------------------------------------------------
# the full pipeline on the 8-device mesh (jitted; slow tier)

@requires_x64
def test_kdk_gradient_matches_fd_multi(cpu8):
    with use_mesh(cpu8):
        model = ForwardModel(16, 512, BoxSize=100.0, pm_steps=1,
                             dtype='f8')
        obs = jax.jit(model.density)(model.linear_modes(1))
        loss = make_loss(model, obs, noise_std=0.5)
        jloss = jax.jit(loss)
        w = model.lattice.c2r(
            model.lattice.generate_whitenoise(3)) * 0.2
        d = model.lattice.c2r(model.lattice.generate_whitenoise(5))
        d = d / jnp.sqrt(jnp.sum(d * d))
        g = jax.jit(jax.grad(loss))(w)
        eps = 1e-6
        fd = (float(jloss(w + eps * d)) - float(jloss(w - eps * d))) \
            / (2.0 * eps)
        dot = float(jnp.sum(g * d))
    _assert_close(fd, dot, 1e-4)


# ---------------------------------------------------------------------------
# LPT asymptotics + replay determinism

@requires_x64
def test_2lpt_correction_scales_linearly_vs_za():
    """The 2LPT term enters positions as D2 = -(3/7) a^2 against the
    Zel'dovich D1 = a, so rms(x_2lpt - x_za) / rms(x_za - q) must
    scale exactly linearly in a — and the momentum assembly must carry
    the matching -(6/7) a factor."""
    pm = ParticleMesh(Nmesh=16, BoxSize=100.0, dtype='f8')
    modes = pm.generate_whitenoise(7) * normalized_amplitude(
        pm, -2.5, 0.05)
    q = pm.generate_uniform_particle_grid(
        shift=0.0, dtype=pm.compute_dtype)

    def ratio(a):
        x1, p1 = lpt_init(pm, modes, a=a, order=1)
        x2, p2 = lpt_init(pm, modes, a=a, order=2)
        num = float(jnp.sqrt(jnp.mean((x2 - x1) ** 2)))
        den = float(jnp.sqrt(jnp.mean((x1 - q) ** 2)))
        assert num > 0      # 2LPT source must be nonzero
        # momentum: mom2 - mom1 = a^{3/2} (-6/7) a psi2
        #           pos2 - pos1 = (-3/7) a^2 psi2
        dp = float(jnp.sqrt(jnp.mean((p2 - p1) ** 2)))
        dx = float(jnp.sqrt(jnp.mean((x2 - x1) ** 2)))
        assert dp == pytest.approx(2.0 * a ** 0.5 * dx, rel=1e-10)
        return num / den

    r1, r2 = ratio(0.05), ratio(0.1)
    assert r2 / r1 == pytest.approx(2.0, rel=1e-10)


def test_growth_table_pins_lcdm_d1_and_eds_limit():
    """GrowthTable in the stepper's early-time gauge (D1 -> a):
    Omega_m=0.3 pays the textbook Lambda growth suppression at a=1,
    and Omega_m=1 reproduces the EdS closed forms."""
    from nbodykit_tpu.forward import GrowthTable, dkick, ddrift
    g = GrowthTable(0.3)
    assert g.D1(1.0) == pytest.approx(0.7789, abs=2e-3)
    assert 0.4 < g.f1(1.0) < 0.6          # ~ Omega_m(a=1)^0.55
    assert g.D2(1.0) < 0                  # EdS-sign convention
    e = GrowthTable(1.0)
    for a in (0.1, 0.33, 0.77, 1.0):
        assert e.D1(a) == pytest.approx(a, rel=1e-6)
        assert e.f1(a) == pytest.approx(1.0, abs=1e-5)
        assert e.D2(a) == pytest.approx(-3.0 / 7 * a * a, rel=1e-4)
        assert e.f2(a) == pytest.approx(2.0, abs=1e-4)
    for a0, a1 in ((0.1, 0.4), (0.5, 1.0)):
        assert e.dkick(a0, a1) == pytest.approx(dkick(a0, a1),
                                                rel=1e-12)
        assert e.ddrift(a0, a1) == pytest.approx(ddrift(a0, a1),
                                                 rel=1e-12)


@requires_x64
def test_lcdm_stepper_suppresses_growth_like_the_table():
    """Evolving the same tiny ZA displacement through the EdS and
    Omega_m=0.3 steppers: the ratio of the two growth factors must
    match D1_lcdm/D1_eds from the table (the mesh's CIC force
    softening cancels in the ratio to first order)."""
    def growth_ratio(omega_m):
        m = ForwardModel(8, pm_steps=8, order=1, omega_m=omega_m,
                         delta_rms=1e-4, dtype='f8', a_start=0.1)
        modes = m.linear_modes(3)
        pos0, _ = lpt_init(m.lattice, modes, a=0.1, order=1,
                           growth=m.growth)
        q = m.lattice.generate_uniform_particle_grid(shift=0.0)
        pos1, _ = m.evolve(modes)
        d0, d1 = np.asarray(pos0 - q), np.asarray(pos1 - q)
        return float(np.sum(d0 * d1) / np.sum(d0 * d0))

    from nbodykit_tpu.forward import GrowthTable
    g = GrowthTable(0.3)
    want = (g.D1(1.0) / g.D1(0.1)) / (1.0 / 0.1)
    got = growth_ratio(0.3) / growth_ratio(1.0)
    assert got == pytest.approx(want, rel=0.05)


def test_forward_replay_bit_identical():
    """Same modes -> same density, bit for bit (the contract shadow
    verification and result memoization stand on)."""
    model = ForwardModel(8, 64, BoxSize=100.0, pm_steps=2, dtype='f8')
    modes = model.linear_modes(9)
    dens = jax.jit(model.density)
    a = np.asarray(dens(modes))
    b = np.asarray(dens(modes))
    assert np.array_equal(a, b)
    # and through a fresh identically-configured model
    model2 = ForwardModel(8, 64, BoxSize=100.0, pm_steps=2, dtype='f8')
    c = np.asarray(jax.jit(model2.density)(model2.linear_modes(9)))
    assert np.array_equal(a, c)


# ---------------------------------------------------------------------------
# field-level recovery vs the classical baseline

@requires_x64
def test_recovery_beats_fftrecon_small():
    """32^3: linear-init Adam recovery of the initial field must beat
    FFTRecon (LGS) on whole-field cross-correlation with the truth."""
    model = ForwardModel(32, 32 ** 3, BoxSize=1000.0, pm_steps=2,
                         dtype='f8')
    truth = model.linear_modes(0)
    obs = jax.jit(model.density)(truth)
    w, losses = recover(model, obs, steps=80, lr=0.1, noise_std=0.1,
                        white0=linear_init(model, obs))
    assert losses[-1] < losses[0]
    lat = model.lattice
    r_rec = float(mean_cross_correlation(
        lat, model.modes_from_white(w), truth))
    pos, _ = model.evolve(truth)
    base = fftrecon_baseline(model, pos)
    r_base = float(mean_cross_correlation(lat, base, truth))
    assert r_rec > r_base, \
        "recovered r=%.4f does not beat FFTRecon r=%.4f" % (r_rec,
                                                            r_base)


@requires_x64
def test_recovery_beats_fftrecon_128():
    """The 128^3 toy, slow tier: same contract at production mesh
    resolution.  delta_rms scales the displacement regime of the 32^3
    toy (~1.8 cells rms) onto the bigger mesh — at delta_rms=1 the
    128^3 field moves ~5 cells and no plain gradient optimizer
    converges (docs/FORWARD.md 'Displacement per cell governs
    convergence')."""
    model = ForwardModel(128, 128 ** 3, BoxSize=1000.0, pm_steps=2,
                         delta_rms=0.36, dtype='f8')
    truth = model.linear_modes(0)
    obs = jax.jit(model.density)(truth)
    # lr shrinks with the mesh (0.1 at 32^3, 0.02 at 64^3): constant-
    # magnitude Adam steps inject white noise at every scale, and the
    # stable size falls as the k range grows
    w, losses = recover(model, obs, steps=40, lr=0.01, noise_std=0.1,
                        white0=linear_init(model, obs))
    assert losses[-1] < losses[0]
    lat = model.lattice
    r_rec = float(mean_cross_correlation(
        lat, model.modes_from_white(w), truth))
    pos, _ = model.evolve(truth)
    base = fftrecon_baseline(model, pos)
    r_base = float(mean_cross_correlation(lat, base, truth))
    assert r_rec > r_base, \
        "recovered r=%.4f does not beat FFTRecon r=%.4f" % (r_rec,
                                                            r_base)


def test_linear_init_requires_matching_meshes():
    model = ForwardModel(16, 8 ** 3, BoxSize=100.0, dtype='f8')
    with pytest.raises(ValueError, match='nmesh'):
        linear_init(model, jnp.ones(model.pm.shape_real))


# ---------------------------------------------------------------------------
# the serve plane: Forward as traffic

def test_forward_request_validation_and_program_key():
    from nbodykit_tpu.serve import AnalysisRequest
    r = AnalysisRequest(algorithm='Forward', nmesh=16, npart=4096,
                        pm_steps=2)
    assert r.pm_steps == 2
    assert r.program_key(1)[-1] == 2       # step count is program id
    r5 = AnalysisRequest(algorithm='Forward', nmesh=16, npart=4096)
    assert r5.pm_steps == 5                # default schedule
    assert r.program_key(1) != r5.program_key(1)
    with pytest.raises(ValueError, match='cube'):
        AnalysisRequest(algorithm='Forward', nmesh=16, npart=5000)
    with pytest.raises(ValueError, match='pm_steps'):
        AnalysisRequest(algorithm='FFTPower', nmesh=16, npart=4096,
                        pm_steps=3)
    with pytest.raises(ValueError, match='FFTPower only'):
        AnalysisRequest(algorithm='Forward', nmesh=16, npart=4096,
                        data_ref={'path': 'x', 'format': 'binary'})


def test_forward_admission_admit_degrade_reject():
    from nbodykit_tpu.serve import (ADMIT, DEGRADE, REJECT,
                                    AnalysisRequest, admit)
    # admit: small shape, priced with the reverse-pass branch
    d = admit(AnalysisRequest(algorithm='Forward', nmesh=16,
                              npart=8 ** 3, pm_steps=2), ndevices=1,
              hbm_bytes=16e9)
    assert d.status == ADMIT
    assert d.plan['workload'] == 'forward'
    assert d.plan['grad_residual_bytes'] > 0
    # degrade: 464^3 particles at nmesh=64 peak ~8.27 GB unchunked,
    # ~7.74 GB at paint_chunk 8M — a budget between the two admits
    # degraded through the scoped ladder
    d = admit(AnalysisRequest(algorithm='Forward', nmesh=64,
                              npart=464 ** 3, pm_steps=2,
                              paint_method='scatter'), ndevices=1,
              hbm_bytes=9.3e9)
    assert d.status == DEGRADE
    assert d.options.get('paint_chunk_size')
    assert [r[0] for r in d.rungs][-1] == 'paint_chunk_size/2'
    # reject over budget, structured
    d = admit(AnalysisRequest(algorithm='Forward', nmesh=64,
                              npart=464 ** 3, pm_steps=2,
                              paint_method='scatter'), ndevices=1,
              hbm_bytes=4e9)
    assert d.status == REJECT
    assert d.reason['code'] == 'over_budget'
    # reject indivisible particle lattice: ng=12 on 8 devices
    d = admit(AnalysisRequest(algorithm='Forward', nmesh=16,
                              npart=12 ** 3, pm_steps=2), ndevices=8)
    assert d.status == REJECT
    assert d.reason['code'] == 'indivisible'
    assert 'lattice' in d.reason['detail']


def test_forward_memory_plan_prices_reverse_pass():
    fwd = memory_plan(64, 32 ** 3, ndevices=1, dtype='f4',
                      workload='forward', pm_steps=5)
    base = memory_plan(64, 32 ** 3, ndevices=1, dtype='f4')
    assert fwd['workload'] == 'forward'
    assert fwd['pm_steps'] == 5
    assert fwd['grad_residual_bytes'] > 0
    assert fwd['peak_bytes'] > base['peak_bytes']
    # residuals grow with the step count
    deeper = memory_plan(64, 32 ** 3, ndevices=1, dtype='f4',
                         workload='forward', pm_steps=10)
    assert deeper['peak_bytes'] > fwd['peak_bytes']


def test_forward_served_end_to_end_with_shadow_verify():
    """A Forward request through the live server: admitted with the
    reverse-pass plan, completed, 0 lost — and when verify=True the
    shadow re-execution on a different sub-mesh agrees bit-identically
    (the counters, not faith, say so)."""
    from nbodykit_tpu.serve import (AnalysisRequest, AnalysisServer,
                                    BatchPolicy)
    with AnalysisServer(per_task=4,
                        batch=BatchPolicy(max_delay_s=0)) as srv:
        assert len(srv.meshes) >= 2, 'shadow needs two sub-meshes'
        res = srv.wait(srv.submit(AnalysisRequest(
            algorithm='Forward', nmesh=16, npart=8 ** 3, pm_steps=1,
            seed=3, deadline_s=600.0, verify=True)), timeout=600)
        summary = srv.summary()
    assert res.status == 'completed'
    assert summary['lost'] == 0
    assert summary['shadow_verified'] == 1
    assert summary['shadow_mismatch'] == 0
    y = np.asarray(res.y, dtype=np.float64)
    assert np.isfinite(y).all() and (np.abs(y) > 0).any()
