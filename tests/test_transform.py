"""Transform-module tests: sky<->cartesian round trips and column
helpers (reference: nbodykit/tests/test_transform.py — the astropy
cross-checks become self-consistency oracles here, since astropy is
not installed)."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu import transform
from nbodykit_tpu.cosmology import Planck15


def _random_sky(n, seed=0, zmax=1.5):
    rng = np.random.RandomState(seed)
    ra = rng.uniform(0.0, 360.0, n)
    dec = np.degrees(np.arcsin(rng.uniform(-0.99, 0.99, n)))
    z = rng.uniform(0.01, zmax, n)
    return ra, dec, z


def test_sky_to_unit_sphere_unit_norm():
    ra, dec, _ = _random_sky(500, seed=1)
    v = np.asarray(transform.SkyToUnitSphere(ra, dec))
    np.testing.assert_allclose(np.linalg.norm(v, axis=1), 1.0,
                               rtol=1e-6)
    # dec=+90 is the +z pole
    pole = np.asarray(transform.SkyToUnitSphere([10.0], [90.0]))
    np.testing.assert_allclose(pole[0], [0, 0, 1], atol=1e-6)


def test_sky_cartesian_round_trip():
    """CartesianToSky(SkyToCartesian(ra, dec, z)) == (ra, dec, z)."""
    ra, dec, z = _random_sky(300, seed=2)
    pos = transform.SkyToCartesian(ra, dec, z, Planck15)
    ra2, dec2, z2 = transform.CartesianToSky(pos, Planck15)
    np.testing.assert_allclose(np.mod(np.asarray(ra2), 360.0),
                               np.mod(ra, 360.0), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(dec2), dec, atol=1e-4)
    np.testing.assert_allclose(np.asarray(z2), z, rtol=1e-4)


def test_cartesian_to_equatorial_matches_sky():
    """CartesianToEquatorial agrees with the (ra, dec) of
    CartesianToSky for the same observer."""
    ra, dec, z = _random_sky(200, seed=3)
    pos = transform.SkyToCartesian(ra, dec, z, Planck15)
    ra_e, dec_e = transform.CartesianToEquatorial(pos)
    np.testing.assert_allclose(np.mod(np.asarray(ra_e), 360.0),
                               np.mod(ra, 360.0), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(dec_e), dec, atol=1e-4)


def test_cartesian_to_sky_with_velocity_rsd():
    """velocity= shifts the apparent redshift along the line of
    sight (reference transform.py:179 observed redshift)."""
    ra, dec, z = _random_sky(100, seed=4, zmax=0.5)
    pos = transform.SkyToCartesian(ra, dec, z, Planck15)
    vel = np.zeros((100, 3))
    _, _, z_norsd = transform.CartesianToSky(pos, Planck15)
    _, _, z_rsd = transform.CartesianToSky(pos, Planck15,
                                           velocity=vel)
    np.testing.assert_allclose(np.asarray(z_rsd),
                               np.asarray(z_norsd), rtol=1e-6)
    # outward radial velocity increases observed z
    unit = np.asarray(pos) / np.linalg.norm(np.asarray(pos),
                                            axis=1)[:, None]
    _, _, z_out = transform.CartesianToSky(pos, Planck15,
                                           velocity=300.0 * unit)
    assert (np.asarray(z_out) > np.asarray(z_norsd)).all()


def test_vector_projection():
    v = np.array([[1.0, 2.0, 3.0], [0.0, 1.0, 0.0]])
    proj = np.asarray(transform.VectorProjection(v, [0, 0, 1]))
    np.testing.assert_allclose(proj, [[0, 0, 3], [0, 0, 0]],
                               atol=1e-12)
    # projection + rejection reconstructs the vector
    rej = v - proj
    np.testing.assert_allclose(rej[:, 2], 0.0, atol=1e-12)


def test_stack_concatenate_constant():
    a = jnp.arange(4.0)
    b = jnp.arange(4.0) + 10
    st = np.asarray(transform.StackColumns(a, b))
    assert st.shape == (4, 2)
    np.testing.assert_allclose(st[:, 1], np.arange(4.0) + 10)

    c = np.asarray(transform.ConstantArray(3.5, 7))
    np.testing.assert_allclose(c, 3.5)
    assert len(c) == 7

    from nbodykit_tpu.lab import ArrayCatalog
    c1 = ArrayCatalog({'x': np.arange(3.0)})
    c2 = ArrayCatalog({'x': np.arange(5.0)})
    cc = transform.ConcatenateSources(c1, c2)
    assert cc.size == 8
    np.testing.assert_allclose(np.asarray(cc['x'])[3:],
                               np.arange(5.0))


def test_galactic_frame_roundtrip():
    """frame='galactic' must actually rotate (reference
    tests/test_transform.py:76 checks the astropy-backed version;
    here the standard IAU ICRS->galactic matrix)."""
    import pytest
    from nbodykit_tpu.cosmology import Planck15

    rng = np.random.RandomState(42)
    pos = jnp.asarray(rng.uniform(50.0, 300.0, (500, 3)))
    lon, lat, z = transform.CartesianToSky(pos, Planck15,
                                           frame='galactic')
    ra, dec, _ = transform.CartesianToSky(pos, Planck15)
    # a real rotation: galactic coords differ from equatorial
    assert float(jnp.abs(jnp.asarray(lon) - jnp.asarray(ra)).max()) > 1
    pos2 = transform.SkyToCartesian(lon, lat, z, Planck15,
                                    frame='galactic')
    np.testing.assert_allclose(np.asarray(pos2), np.asarray(pos),
                               rtol=1e-4)
    # the rotation matrix is orthonormal
    from nbodykit_tpu.transform import _ICRS_TO_GAL
    np.testing.assert_allclose(_ICRS_TO_GAL @ _ICRS_TO_GAL.T,
                               np.eye(3), atol=1e-12)
    # the galactic north pole is at (ra, dec) ~ (192.86, 27.13) deg:
    # its ICRS unit vector must map to lat = +90
    ngp = transform.SkyToUnitSphere(jnp.asarray([192.85948]),
                                    jnp.asarray([27.12825]))
    glon, glat = transform.CartesianToEquatorial(ngp, frame='galactic')
    assert abs(float(glat[0]) - 90.0) < 1e-3

    with pytest.raises(ValueError, match="frame"):
        transform.CartesianToSky(pos, Planck15, frame='fk5')


def test_halo_transforms_finite_and_scaling():
    """Reference tests/test_transform.py:145 exercises HaloRadius/
    HaloConcentration/HaloVelocityDispersion over random masses."""
    from nbodykit_tpu.cosmology import Planck15
    from nbodykit_tpu.transform import (HaloRadius, HaloConcentration,
                                        HaloVelocityDispersion)

    rng = np.random.RandomState(42)
    mass = jnp.asarray(rng.uniform(1e12, 1e14, 1000))
    zarr = jnp.asarray(rng.uniform(0.0, 1.0, 1000))
    for zz in (zarr, 0.0):
        r = HaloRadius(mass, Planck15, redshift=zz)
        c = HaloConcentration(mass, Planck15, redshift=zz)
        v = HaloVelocityDispersion(mass, Planck15, redshift=zz)
        for arr in (r, c, v):
            a = np.asarray(arr)
            assert np.isfinite(a).all() and (a > 0).all()
    # more massive halos are bigger and less concentrated
    m2 = jnp.asarray([1e12, 1e15])
    r2 = np.asarray(HaloRadius(m2, Planck15, redshift=0.0))
    c2 = np.asarray(HaloConcentration(m2, Planck15, redshift=0.0))
    assert r2[1] > r2[0] and c2[1] < c2[0]


def test_concatenate_invalid_column():
    import pytest
    from nbodykit_tpu.lab import UniformCatalog

    s1 = UniformCatalog(nbar=1e-4, BoxSize=100.0, seed=1)
    s2 = UniformCatalog(nbar=1e-4, BoxSize=100.0, seed=2)
    cat = transform.ConcatenateSources(s1, s2)
    assert cat.size == s1.size + s2.size
    with pytest.raises(ValueError):
        transform.ConcatenateSources(s1, s2, columns='InvalidColumn')
