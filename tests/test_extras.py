"""Tests for the remaining surface: FNLGalaxyPower, LinearNbody, halo
transforms, SubVolumesCatalog, meshtools, DemoHaloCatalog, HaloCatalog
population, catalog ops (sort/gslice/concat)."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import (ArrayCatalog, UniformCatalog,
                              Planck15, FNLGalaxyPower, LinearNbody,
                              SubVolumesCatalog, DemoHaloCatalog,
                              LinearPower)
from nbodykit_tpu import transform
from nbodykit_tpu.meshtools import SlabIterator


def test_fnl_galaxy_power():
    P0 = FNLGalaxyPower(Planck15, 0.5, b1=2.0, fnl=0.0, transfer='EisensteinHu')
    P1 = FNLGalaxyPower(Planck15, 0.5, b1=2.0, fnl=50.0, transfer='EisensteinHu')
    k = np.array([1e-3, 1e-2, 1e-1])
    # fnl=0: P = b1^2 Plin
    np.testing.assert_allclose(P0(k), 4.0 * P0.linear(k), rtol=1e-10)
    # fnl > 0 with b1 > p boosts large scales most
    boost = P1(k) / P0(k)
    assert boost[0] > boost[1] > boost[2]
    assert boost[0] > 1.5


def test_linear_nbody():
    ln = LinearNbody(Planck15)
    rng = np.random.RandomState(0)
    disp = rng.standard_normal((100, 3))
    vel = rng.standard_normal((100, 3))
    d2, v2 = ln.integrate(None, disp, vel, 0.5, 1.0)
    D = Planck15.scale_independent_growth_factor
    ratio = D(0.0) / D(1.0)  # a: 0.5 -> z=1; a=1 -> z=0
    np.testing.assert_allclose(np.asarray(d2) / disp, ratio, rtol=0.02)
    # forward then backward is identity
    d3, v3 = ln.integrate(None, d2, v2, 1.0, 0.5)
    np.testing.assert_allclose(np.asarray(d3), disp, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(v3), vel, rtol=1e-10)


def test_halo_transforms():
    M = np.array([1e12, 1e13, 1e14])
    R = np.asarray(transform.HaloRadius(M, Planck15, 0.0))
    assert np.all(np.diff(R) > 0)
    assert 0.1 < R[1] < 1.0  # ~0.3-0.5 Mpc/h for 1e13
    c = np.asarray(transform.HaloConcentration(M, Planck15, 0.0))
    assert np.all(np.diff(c) < 0)  # decreasing with mass
    sig = np.asarray(transform.HaloVelocityDispersion(M, Planck15, 0.0))
    assert np.all(np.diff(sig) > 0)


def test_subvolumes_catalog():
    cat = UniformCatalog(nbar=1e-3, BoxSize=64.0, seed=3)
    sub = SubVolumesCatalog(cat, domain=[2, 2, 2])
    assert sub.csize == cat.csize
    idx = np.asarray(sub['SubVolumeIndex'])
    assert np.all(np.diff(idx) >= 0)  # sorted by subvolume
    # particles in subvolume 0 live in the low corner
    pos = np.asarray(sub['Position'])
    first = pos[idx == 0]
    assert np.all(first < 32.0)


def test_demo_halo_catalog_and_populate():
    from nbodykit_tpu.source.catalog.halos import HaloCatalog
    demo = DemoHaloCatalog(seed=5)
    halos = HaloCatalog(demo, cosmo=Planck15, redshift=0.5)
    gals = halos.populate(seed=9)
    assert gals.csize > 0
    assert 'gal_type' in gals.columns


def test_slab_iterator():
    # coords of an 8^3 k-mesh, iterate slabs and accumulate mode count
    N = 8
    kx = np.fft.fftfreq(N, 1. / N).reshape(N, 1, 1)
    ky = np.fft.fftfreq(N, 1. / N).reshape(1, N, 1)
    # pmesh convention: the Nyquist frequency is stored negative so it
    # gets hermitian weight 1 (see reference meshtools.py:188)
    kz = np.array([0, 1, 2, 3, -4]).reshape(1, 1, N // 2 + 1)
    total = 0.0
    for slab in SlabIterator([kx, ky, kz], axis=0, symmetry_axis=2):
        w = slab.hermitian_weights
        total += np.sum(np.ones(slab.shape) * w)
    assert total == N ** 3


def test_catalog_sort_gslice_concat():
    rng = np.random.RandomState(4)
    cat = ArrayCatalog({'Mass': rng.uniform(size=50),
                        'Position': rng.uniform(0, 10, (50, 3))},
                       BoxSize=10.0)
    s = cat.sort('Mass')
    assert np.all(np.diff(np.asarray(s['Mass'])) >= 0)
    sl = cat.gslice(10, 20)
    assert sl.csize == 10
    np.testing.assert_allclose(np.asarray(sl['Mass']),
                               np.asarray(cat['Mass'])[10:20])
    both = transform.ConcatenateSources(cat, cat)
    assert both.csize == 100
    # boolean selection
    heavy = cat[np.asarray(cat['Mass']) > 0.5]
    assert heavy.csize == int((np.asarray(cat['Mass']) > 0.5).sum())
