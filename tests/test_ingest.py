"""Ingestion-plane tests (nbodykit_tpu.ingest, docs/INGEST.md).

The contracts under test, in order of importance:

- **bit-identity**: the painted mesh is defined by the chunked deposit
  order, and every route to it — cold streamed (overlap on or off),
  cache-hit replay, whole-resident catalog pushed through
  ``paint_chunks`` — produces the SAME bits;
- **bounded host**: the high-water mark of host-resident chunk bytes
  never approaches the catalog size (the whole point of streaming);
- **content addressing**: same bytes hit, changed bytes miss, eviction
  under a shrunken budget re-ingests correctly;
- **exact partition**: every reader's ``row_range``/``read_chunks``
  covers each row exactly once across ranks, uneven tails included;
- **resume**: a fault mid-stream + a CheckpointStore resumes by
  re-transferring (never re-painting) finished chunks, and a catalog
  that changed under the checkpoint is refused;
- **serving**: ``data_ref`` requests complete end-to-end, repeat
  requests ride the worker's on-device cache, unreadable paths get a
  structured reject.
"""

import json
import os

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import io as nio
from nbodykit_tpu.ingest import (ArraySource, CatalogCache, DataRef,
                                 IngestError, ingest_catalog,
                                 match_partition_rules, paint_cached,
                                 paint_chunks, probe_ref,
                                 resolve_partition_spec)
from nbodykit_tpu.pmesh import ParticleMesh

BOX = 100.0


@pytest.fixture(autouse=True)
def eight_device_mesh():
    """Every test here runs with the full 8-device mesh ambient — the
    regime the ingestion plane exists for."""
    from nbodykit_tpu.parallel.runtime import tpu_mesh, use_mesh
    with use_mesh(tpu_mesh()):
        yield


def _catalog(n, seed=0, box=BOX):
    rng = np.random.RandomState(seed)
    return (rng.uniform(0, box, size=(n, 3))).astype('f4')


def _write_binary(tmp_path, pos, name='cat.bin'):
    path = str(tmp_path / name)
    with open(path, 'wb') as ff:
        pos.astype('f4').tofile(ff)
    return DataRef(path, 'binary',
                   columns={'Position': 'Position'},
                   options={'dtype': [('Position', ('f4', 3))]})


def _pm(nmesh=32):
    return ParticleMesh(Nmesh=nmesh, BoxSize=BOX, dtype='f4')


# ---------------------------------------------------------------------------
# partition rules

def test_partition_rules_first_match_and_no_match():
    from nbodykit_tpu.ingest import DEFAULT_RULES, ROWS
    t = match_partition_rules(DEFAULT_RULES,
                              {'Position': 2, 'Weight': 1,
                               'Velocity': 2, 'Selection': 1})
    assert t['Position'] == (ROWS, None)
    assert t['Velocity'] == (ROWS, None)
    assert t['Weight'] == (ROWS,)
    assert t['Selection'] == (ROWS,)
    # the catch-all soaks up anything (Ellipsis widened to the rank)
    t2 = match_partition_rules(DEFAULT_RULES, {'Phi': 3})
    assert t2['Phi'][0] == ROWS
    with pytest.raises(ValueError):
        match_partition_rules(((r'^Position$', (ROWS, None)),),
                              {'Mass': 1})


def test_resolve_partition_spec_on_live_mesh():
    import jax
    from jax.sharding import NamedSharding

    from nbodykit_tpu.ingest import make_shard_and_gather_fns
    from nbodykit_tpu.parallel.runtime import CurrentMesh
    mesh = CurrentMesh.resolve(None)
    from nbodykit_tpu.ingest import DEFAULT_RULES
    templates = match_partition_rules(DEFAULT_RULES,
                                      {'Position': 2, 'Weight': 1})
    specs = {k: resolve_partition_spec(t, mesh)
             for k, t in templates.items()}
    shard_fns, gather_fns = make_shard_and_gather_fns(specs, mesh)
    pos = _catalog(64)
    dev = shard_fns['Position'](pos)
    assert isinstance(dev.sharding, NamedSharding)
    # leading axis sharded across the full device mesh
    ndev = len(jax.devices())
    assert dev.sharding.shard_shape(dev.shape)[0] == 64 // ndev
    np.testing.assert_array_equal(gather_fns['Position'](dev), pos)


# ---------------------------------------------------------------------------
# reader partition: every row exactly once, uneven tails included

@pytest.mark.parametrize('size,nranks', [
    (0, 1), (1, 8), (7, 8), (8, 8), (10007, 8), (128, 3), (13, 5)])
def test_row_range_exact_partition(size, nranks):
    from nbodykit_tpu.io.base import FileType
    f = FileType.__new__(FileType)
    f.size = size
    edges = [f.row_range(r, nranks) for r in range(nranks)]
    # contiguous, ordered, exactly covering [0, size)
    assert edges[0][0] == 0 and edges[-1][1] == size
    for (a, b), (c, d) in zip(edges, edges[1:]):
        assert b == c and a <= b and c <= d
    # balanced to within one row
    lens = [b - a for a, b in edges]
    assert max(lens) - min(lens) <= 1
    with pytest.raises(ValueError):
        f.row_range(nranks, nranks)


def _readers_with_uneven_rows(tmp_path):
    """(reader, position-column) pairs over the same 617-row catalog
    (617 is prime: every chunk_rows/nranks split has an uneven tail)."""
    n = 617
    pos = _catalog(n, seed=3)
    out = []

    path = str(tmp_path / 'u.bin')
    with open(path, 'wb') as ff:
        pos.tofile(ff)
    out.append((nio.BinaryFile(
        path, dtype=[('Position', ('f4', 3))]), 'Position'))

    csv = str(tmp_path / 'u.csv')
    np.savetxt(csv, pos)
    out.append((nio.CSVFile(csv, names=['x', 'y', 'z']), 'x'))

    try:
        import h5py
    except ImportError:
        h5py = None
    if h5py is not None:
        h5 = str(tmp_path / 'u.h5')
        with h5py.File(h5, 'w') as ff:
            ff.create_dataset('Position', data=pos)
        out.append((nio.HDFFile(h5, dataset='/'), 'Position'))

    bf = str(tmp_path / 'u.bf')
    with nio.BigFileWriter(bf) as ff:
        ff.write('Position', pos, nfile=3)
    out.append((nio.BigFile(bf), 'Position'))

    out.append((ArraySource({'Position': pos}), 'Position'))
    return out


def test_read_chunks_exact_partition_all_readers(tmp_path):
    """Concatenating read_chunks over all ranks reproduces the full
    column for EVERY reader, at chunk sizes that leave uneven tails
    both per-chunk and per-rank."""
    for f, col in _readers_with_uneven_rows(tmp_path):
        whole = f.read([col], 0, f.size)[col]
        for nranks in (1, 8):
            for chunk_rows in (100, 617, 1000):
                got, sizes = [], []
                for rank in range(nranks):
                    for chunk in f.read_chunks([col], chunk_rows,
                                               rank=rank,
                                               nranks=nranks):
                        got.append(chunk[col])
                        sizes.append(len(chunk))
                assert max(sizes) <= chunk_rows
                np.testing.assert_array_equal(
                    np.concatenate(got), whole,
                    err_msg='%s nranks=%d chunk_rows=%d'
                            % (type(f).__name__, nranks, chunk_rows))


# ---------------------------------------------------------------------------
# streaming: bit-identity + bounded host

# NOTE on shapes: every painting test below uses chunk_rows=512 with
# catalog sizes ≡ 8 (mod 512), so the whole file compiles exactly TWO
# chunk-paint programs per device mesh — (512, 3) and the (8, 3) tail.
# A novel chunk shape is a fresh XLA compile (~minutes on this 1-core
# box); keep new tests on these shapes.
CHUNK = 512


def test_streaming_contract_single_device(tmp_path):
    """The full contract — streamed == whole-load bits, cache hit ==
    cold bits, zero warm reads, bounded host — on a 1-device sub-mesh
    (a serve worker's regime).  This is the fast-tier guard; the
    8-device variants below are the slow tier."""
    from nbodykit_tpu.parallel.runtime import tpu_mesh, use_mesh
    n = 2 * CHUNK + 8
    pos = _catalog(n, seed=16)
    ref = _write_binary(tmp_path, pos)
    with use_mesh(tpu_mesh(1)):
        pm = _pm()
        cache = CatalogCache()
        cold_f, _, cold = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                         cache=cache)
        warm_f, _, warm = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                         cache=cache)
        chunks = [(pos[s:s + CHUNK],
                   np.ones(len(pos[s:s + CHUNK]), 'f4'),
                   min(CHUNK, n - s)) for s in range(0, n, CHUNK)]
        whole = paint_chunks(pm, chunks)
    assert cold['chunks'] == 3 and not cold['cache_hit']
    assert warm['cache_hit'] and warm['bytes'] == 0
    np.testing.assert_array_equal(np.asarray(cold_f),
                                  np.asarray(warm_f))
    np.testing.assert_array_equal(np.asarray(cold_f),
                                  np.asarray(whole))
    assert cold['host_peak_bytes'] <= 2 * CHUNK * 3 * 4
    assert abs(float(np.asarray(cold_f).sum()) - n) < 1e-3 * n


def test_streamed_bit_identical_to_whole_load(tmp_path):
    n = 8 * CHUNK + 8              # 8 full chunks + an uneven tail
    pos = _catalog(n, seed=1)
    ref = _write_binary(tmp_path, pos)
    pm = _pm()
    field, entry, stats = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                         overlap=True)
    assert stats['rows'] == n and stats['chunks'] == 9
    # whole catalog resident, pushed through the SAME canonical
    # chunked deposit -> bit-identical
    chunks = []
    for s in range(0, n, CHUNK):
        c = pos[s:s + CHUNK]
        chunks.append((c, np.ones(len(c), 'f4'), len(c)))
    whole = paint_chunks(pm, chunks)
    np.testing.assert_array_equal(np.asarray(field), np.asarray(whole))
    # total deposited mass is the particle count
    assert abs(float(np.asarray(field).sum()) - n) < 1e-3 * n


def test_overlap_and_serial_paths_bit_identical(tmp_path):
    pos = _catalog(4 * CHUNK + 8, seed=2)
    ref = _write_binary(tmp_path, pos)
    pm = _pm()
    f_on, _, s_on = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                   overlap=True)
    f_off, _, s_off = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                     overlap=False)
    assert s_on['overlap'] and not s_off['overlap']
    np.testing.assert_array_equal(np.asarray(f_on), np.asarray(f_off))


def test_host_never_holds_the_catalog(tmp_path):
    """The streaming bound: peak host-resident chunk bytes is the
    double buffer (<= 2 chunks), nowhere near the catalog."""
    n = 16 * CHUNK + 8
    ref = _write_binary(tmp_path, _catalog(n, seed=4))
    _, _, stats = ingest_catalog(ref, _pm(), chunk_rows=CHUNK,
                                 overlap=True)
    catalog_bytes = n * 3 * 4
    assert stats['bytes'] == catalog_bytes
    assert stats['host_peak_bytes'] <= 2 * CHUNK * 3 * 4
    assert stats['host_peak_bytes'] < catalog_bytes / 4


def test_empty_catalog_structured_error(tmp_path):
    path = str(tmp_path / 'empty.bin')
    open(path, 'wb').close()
    ref = DataRef(path, 'binary',
                  options={'dtype': [('Position', ('f4', 3))]})
    with pytest.raises(IngestError) as ei:
        ingest_catalog(ref, _pm())
    assert ei.value.code == 'empty_catalog'


# ---------------------------------------------------------------------------
# content-addressed cache

def test_cache_hit_bit_identical_and_zero_reads(tmp_path):
    ref = _write_binary(tmp_path, _catalog(4 * CHUNK + 8, seed=5))
    pm = _pm()
    cache = CatalogCache()
    cold_f, cold_e, cold = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                          cache=cache)
    warm_f, warm_e, warm = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                          cache=cache)
    assert not cold['cache_hit'] and warm['cache_hit']
    assert warm['bytes'] == 0          # no file, no wire
    assert warm_e is cold_e
    np.testing.assert_array_equal(np.asarray(cold_f),
                                  np.asarray(warm_f))
    st = cache.stats()
    assert st == {'entries': 1, 'resident_bytes': st['resident_bytes'],
                  'hits': 1, 'misses': 1, 'evictions': 0}
    # paint_cached replays the same bits once more
    np.testing.assert_array_equal(np.asarray(paint_cached(pm, cold_e)),
                                  np.asarray(cold_f))


def test_cache_misses_when_bytes_change(tmp_path):
    n = 2 * CHUNK + 8
    pos = _catalog(n, seed=6)
    ref = _write_binary(tmp_path, pos)
    pm = _pm()
    cache = CatalogCache()
    _, _, first = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                 cache=cache)
    # rewrite the file with different bytes (and bump mtime)
    with open(ref.path, 'wb') as ff:
        _catalog(n, seed=7).tofile(ff)
    os.utime(ref.path, (1, 1))
    _, _, second = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                  cache=cache)
    assert not second['cache_hit']
    assert second['digest'] != first['digest']


def test_eviction_under_shrunken_budget_reingests(tmp_path):
    """An entry evicted for room is gone — the next request for it
    re-ingests cold and lands back in the cache, bit-identically."""
    pm = _pm()
    n = 2 * CHUNK + 8
    ref_a = _write_binary(tmp_path, _catalog(n, seed=8), 'a.bin')
    ref_b = _write_binary(tmp_path, _catalog(n, seed=9), 'b.bin')
    one_entry = 16 * n             # pos (12 B/row) + mass (4 B/row)
    cache = CatalogCache(budget_bytes=int(one_entry * 1.5))
    f_a, _, _ = ingest_catalog(ref_a, pm, chunk_rows=CHUNK,
                               cache=cache)
    ingest_catalog(ref_b, pm, chunk_rows=CHUNK, cache=cache)
    assert cache.stats()['evictions'] == 1
    assert cache.stats()['entries'] == 1
    # A was the LRU victim: asking again is a miss + cold re-ingest
    f_a2, _, again = ingest_catalog(ref_a, pm, chunk_rows=CHUNK,
                                    cache=cache)
    assert not again['cache_hit'] and again['rows'] == n
    np.testing.assert_array_equal(np.asarray(f_a), np.asarray(f_a2))


def test_cache_fits_predicate_prices_eviction(tmp_path):
    """The memory_plan closure (not just the byte cap) drives
    eviction: a predicate that refuses any resident catalog evicts
    everything before the insert."""
    pm = _pm()
    n = CHUNK + 8
    ref = _write_binary(tmp_path, _catalog(n, seed=10))
    cache = CatalogCache()
    ingest_catalog(ref, pm, chunk_rows=CHUNK, cache=cache)
    assert cache.stats()['entries'] == 1
    ref2 = _write_binary(tmp_path, _catalog(n, seed=11), 'c2.bin')
    ingest_catalog(ref2, pm, chunk_rows=CHUNK, cache=cache,
                   fits=lambda resident: resident <= n * 16)
    st = cache.stats()
    assert st['evictions'] >= 1 and st['entries'] >= 1


# ---------------------------------------------------------------------------
# checkpoint / resume

def test_fault_mid_stream_resumes_without_repainting(tmp_path):
    from nbodykit_tpu.resilience import CheckpointStore
    from nbodykit_tpu.resilience.faults import reset_faults
    n = 4 * CHUNK + 8
    pos = _catalog(n, seed=12)
    ref = _write_binary(tmp_path, pos)
    pm = _pm()
    clean, _, _ = ingest_catalog(ref, pm, chunk_rows=CHUNK)

    store = CheckpointStore(str(tmp_path / 'ckpt'))
    with nbodykit_tpu.set_options(faults='ingest.chunk@3:unavailable'):
        reset_faults()
        with pytest.raises(Exception) as ei:
            ingest_catalog(ref, pm, chunk_rows=CHUNK,
                           checkpoint=store, ckpt_key='k',
                           ckpt_every=1)
        assert 'UNAVAILABLE' in str(ei.value)
    reset_faults()
    assert store.keys()            # partial progress on disk
    field, _, stats = ingest_catalog(ref, pm, chunk_rows=CHUNK,
                                     checkpoint=store, ckpt_key='k',
                                     ckpt_every=1)
    assert stats['resumed_chunks'] >= 1
    np.testing.assert_array_equal(np.asarray(field), np.asarray(clean))
    assert not store.keys()        # consumed on success


def test_resume_refuses_changed_catalog(tmp_path):
    from nbodykit_tpu.resilience import CheckpointStore
    from nbodykit_tpu.resilience.faults import reset_faults
    n = 4 * CHUNK + 8
    ref = _write_binary(tmp_path, _catalog(n, seed=13))
    pm = _pm()
    store = CheckpointStore(str(tmp_path / 'ckpt'))
    with nbodykit_tpu.set_options(faults='ingest.chunk@3:unavailable'):
        reset_faults()
        with pytest.raises(Exception):
            ingest_catalog(ref, pm, chunk_rows=CHUNK,
                           checkpoint=store, ckpt_key='k',
                           ckpt_every=1)
    reset_faults()
    # same shape, different bytes: the digests must catch it
    with open(ref.path, 'wb') as ff:
        _catalog(n, seed=14).tofile(ff)
    with pytest.raises(IngestError) as ei:
        ingest_catalog(ref, pm, chunk_rows=CHUNK, checkpoint=store,
                       ckpt_key='k', ckpt_every=1)
    assert ei.value.code == 'checkpoint_mismatch'


# ---------------------------------------------------------------------------
# memory_plan pricing

def test_memory_plan_prices_ingest():
    from nbodykit_tpu.pmesh import memory_plan
    base = memory_plan(Nmesh=64, npart=100000, ndevices=8)
    plan = memory_plan(Nmesh=64, npart=100000, ndevices=8,
                       ingest_chunk_rows=4096)
    assert 'catalog_bytes' in plan
    assert plan['ingest_chunk_buffers'] == 2 * 4 * 4 * 4096 / 8
    assert plan['peak_bytes'] > base['peak_bytes']
    # an explicit resident-cache total outprices the single entry
    big = memory_plan(Nmesh=64, npart=100000, ndevices=8,
                      ingest_chunk_rows=4096,
                      catalog_bytes=10 * 16 * 100000)
    assert big['catalog_bytes'] > plan['catalog_bytes']


# ---------------------------------------------------------------------------
# serving: data_ref end-to-end

def test_request_data_ref_validation():
    from nbodykit_tpu.serve import AnalysisRequest
    d = {'path': '/tmp/x.bin', 'format': 'binary'}
    r = AnalysisRequest(nmesh=32, data_ref=d)
    assert r.data_ref['format'] == 'binary'
    assert r.program_key(1)[-1] == 'data'
    plain = AnalysisRequest(nmesh=32)
    assert plain.program_key(1)[-1] != 'data'
    with pytest.raises(ValueError):
        AnalysisRequest(nmesh=32, algorithm='FFTCorr', data_ref=d)
    with pytest.raises(IngestError):
        AnalysisRequest(nmesh=32, data_ref={'path': 'x',
                                            'format': 'parquet'})


def test_probe_and_admission_reject_unreadable():
    from nbodykit_tpu.serve import REJECT, AnalysisRequest, admit
    ref = {'path': '/nonexistent/cat.bin', 'format': 'binary',
           'options': {'dtype': [('Position', ('f4', 3))]}}
    with pytest.raises(IngestError) as ei:
        probe_ref(ref)
    assert ei.value.code == 'unreadable_data_ref'
    dec = admit(AnalysisRequest(nmesh=32, data_ref=ref), ndevices=8,
                hbm_bytes=16 << 30)
    assert dec.status == REJECT
    assert dec.reason['code'] == 'unreadable_data_ref'


def test_serve_data_ref_end_to_end_and_cache_hit(tmp_path):
    """Two identical data_ref requests: both complete, the second
    rides the worker's CatalogCache, the spectra agree to the bit,
    and an unreadable path is REJECTED with a structured reason."""
    from nbodykit_tpu.serve import (COMPLETED, REJECTED,
                                    AnalysisRequest, AnalysisServer)
    n = 4 * CHUNK + 8
    ref = _write_binary(tmp_path, _catalog(n, seed=15))
    d = ref.to_dict()
    with nbodykit_tpu.set_options(ingest_chunk_rows=CHUNK), \
            AnalysisServer(per_task=1, max_queue=8) as srv:
        r1 = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, data_ref=d, deadline_s=600.0)))
        r2 = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, data_ref=d, deadline_s=600.0)))
        bad = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, deadline_s=600.0,
            data_ref={'path': str(tmp_path / 'missing.bin'),
                      'format': 'binary',
                      'options': d['options']})))
        summary = srv.summary()
    assert r1.status == COMPLETED and r2.status == COMPLETED
    np.testing.assert_array_equal(np.asarray(r1.y), np.asarray(r2.y))
    assert bad.status == REJECTED
    assert bad.reason['code'] == 'unreadable_data_ref'
    assert summary['ingest_requests'] == 2
    assert summary['ingest_cache_hits'] == 1
    assert summary['lost'] == 0
    # admission filled npart from the file
    assert summary['ingest_gb'] == round(n * 12 / 1e9, 6)


# ---------------------------------------------------------------------------
# posture plumbing: regress + doctor read the committed record

def test_ingest_summary_reads_committed_round(tmp_path):
    from nbodykit_tpu.diagnostics.regress import (build_history,
                                                  ingest_summary,
                                                  render_regress)
    rec = {'metric': 'ingest_n1000', 'unit': 'GB/s', 'value': 1.5,
           'rows': 1000, 'bytes': 12000, 'chunk_rows': 128,
           'cold_gbs': 1.5, 'warm_gbs': 3.0, 'serial_gbs': 1.2,
           'overlap_speedup': 1.25, 'host_peak_bytes': 3072,
           'cache_hits': 1, 'cache_evictions': 0,
           'serve_completed': 2, 'serve_cache_hits': 1,
           'serve_lost': 0,
           'measured_at': '2026-08-05T00:00:00Z'}
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'cmd': 'bench --ingest', 'rc': 0, 'parsed': rec}))
    ing = ingest_summary(str(tmp_path))
    assert ing['round'] == 'BENCH_r01.json'
    assert ing['cold_gbs'] == 1.5 and ing['overlap_speedup'] == 1.25
    history = build_history(str(tmp_path), write=False)
    assert history['ingest']['serve_cache_hits'] == 1
    text = render_regress(history)
    line = next(l for l in text.splitlines()
                if l.strip().startswith('ingest:'))
    assert '1.5' in line and 'cache-hit' in line
    assert ingest_summary(str(tmp_path / 'nowhere')) is None


def test_doctor_ingest_thrash_verdict(tmp_path):
    import io as _io

    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    rec = {'metric': 'ingest_n1000', 'unit': 'GB/s', 'value': 1.5,
           'rows': 1000, 'cold_gbs': 1.5, 'warm_gbs': 3.0,
           'overlap_speedup': 1.25, 'cache_hits': 1,
           'cache_evictions': 5, 'serve_completed': 2,
           'serve_cache_hits': 1, 'serve_lost': 0,
           'measured_at': '2026-08-05T00:00:00Z'}
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'cmd': 'bench --ingest', 'rc': 0, 'parsed': rec}))
    buf = _io.StringIO()
    run_doctor(trace=None, root=str(tmp_path), out=buf)
    text = buf.getvalue()
    line = next(l for l in text.splitlines()
                if l.startswith('ingest'))
    assert 'WARN' in line and 'thrash' in line
