"""Dynamic load balancing of the slab decomposition (the analog of the
reference's ``domain.loadbalance`` re-tiling, fof.py:399,
pair_counters/domain.py:256): clustered catalogs must spread evenly
over devices and give device-count-invariant results."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.parallel.runtime import cpu_mesh, mesh_size
from nbodykit_tpu.parallel.domain import (slab_route,
                                          balanced_slab_edges)


def _clustered_positions(n=4096, box=100.0, seed=3):
    """~97% of particles inside one uniform-slab width of an 8-device
    decomposition (dense blob, so linking/counting radii find pairs)."""
    rng = np.random.RandomState(seed)
    pos = rng.uniform(0, box, size=(n, 3))
    nclust = int(n * 0.97)
    pos[:nclust, 0] = rng.uniform(2.0, 9.0, size=nclust)  # slab 0 of 8
    pos[:nclust, 1] = rng.uniform(0.0, 20.0, size=nclust)
    pos[:nclust, 2] = rng.uniform(0.0, 20.0, size=nclust)
    return pos


def test_balanced_edges_even_counts():
    box = 100.0
    mesh = cpu_mesh()
    nproc = mesh_size(mesh)
    pos = _clustered_positions(box=box)
    x = jnp.asarray(pos[:, 0])
    edges = balanced_slab_edges(x, box, nproc, rmax=1.0)
    assert edges[0] == 0 and edges[-1] == box
    assert (np.diff(edges) >= 1.0 - 1e-9).all()  # min width respected
    counts = np.histogram(pos[:, 0], bins=edges)[0]
    even = len(pos) / nproc
    assert counts.max() <= 2.0 * even, counts
    # the uniform tiling would be catastrically skewed on this input
    ucounts = np.histogram(pos[:, 0],
                           bins=np.linspace(0, box, nproc + 1))[0]
    assert ucounts.max() > 5.0 * even


def test_balanced_route_bounded_capacity():
    box = 100.0
    mesh = cpu_mesh()
    nproc = mesh_size(mesh)
    pos = jnp.asarray(_clustered_positions(box=box))
    route, f, live = slab_route(pos, box, 1.0, mesh, ghosts='both',
                                balance=True)
    dest = np.asarray(route.dest)
    lv = np.asarray(live)
    per_dev = np.bincount(dest[lv], minlength=nproc)
    even = lv.sum() / nproc
    assert per_dev.max() <= 2.5 * even, per_dev


def test_fof_clustered_device_invariance():
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.algorithms.fof import FOF
    from nbodykit_tpu.parallel.runtime import use_mesh
    pos = _clustered_positions(n=2000)
    sizes = []
    for mesh in [cpu_mesh(1), cpu_mesh()]:
        with use_mesh(mesh):
            cat = ArrayCatalog({'Position': pos}, BoxSize=100.0)
            f = FOF(cat, linking_length=1.0, nmin=5, absolute=True)
            lab = np.asarray(f.labels)
        # compare the sorted multiset of group sizes (labels may be
        # numbered differently)
        cnt = np.bincount(lab[lab > 0])
        sizes.append(np.sort(cnt[cnt >= 5]))
    np.testing.assert_array_equal(sizes[0], sizes[1])


def test_paircount_clustered_device_invariance():
    from nbodykit_tpu.algorithms.pair_counters.core import (
        paircount, paircount_dist)
    pos = jnp.asarray(_clustered_positions(n=1500))
    redges = np.linspace(0.1, 3.0, 6)
    ref = paircount(pos, None, pos, None, 100.0, redges, mode='1d')
    got = paircount_dist(pos, None, pos, None, 100.0, redges,
                         cpu_mesh(), mode='1d')
    np.testing.assert_allclose(got['npairs'], ref['npairs'], rtol=1e-9)
    np.testing.assert_allclose(got['wnpairs'], ref['wnpairs'],
                               rtol=1e-9)
