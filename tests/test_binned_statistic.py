"""BinnedStatistic container tests (reference analog:
nbodykit/tests/test_binned_stat.py)."""

import numpy as np
import pytest

from nbodykit_tpu.binned_statistic import BinnedStatistic


def make_2d():
    kedges = np.linspace(0, 1, 11)
    muedges = np.linspace(-1, 1, 6)
    shape = (10, 5)
    rng = np.random.RandomState(0)
    data = np.empty(shape, dtype=[('k', 'f8'), ('mu', 'f8'),
                                  ('power', 'c16'), ('modes', 'f8')])
    data['k'] = 0.5 * (kedges[1:] + kedges[:-1])[:, None] * np.ones(shape)
    data['mu'] = 0.5 * (muedges[1:] + muedges[:-1])[None, :] * np.ones(shape)
    data['power'] = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    data['modes'] = rng.randint(1, 100, shape)
    return BinnedStatistic(['k', 'mu'], [kedges, muedges], data,
                           fields_to_sum=['modes'], attr1='hello')


def test_basic_properties():
    ds = make_2d()
    assert ds.shape == (10, 5)
    assert set(ds.variables) == {'k', 'mu', 'power', 'modes'}
    assert ds.dims == ['k', 'mu']
    assert ds.attrs['attr1'] == 'hello'
    assert 'power' in ds
    assert len(ds.coords['k']) == 10


def test_getitem_variable_and_slice():
    ds = make_2d()
    assert ds['power'].shape == (10, 5)
    sub = ds[['k', 'power']]
    assert set(sub.variables) == {'k', 'power'}
    sliced = ds[2:5]
    assert sliced.shape == (3, 5)
    np.testing.assert_allclose(sliced['power'], ds['power'][2:5])
    # reference Dataset semantics: an int index squeezes the dim, a
    # list keeps it, and single-element access raises
    col = ds[:, 0]
    assert col.shape == (10,) and col.dims == ['k']
    col2 = ds[:, [0]]
    assert col2.shape == (10, 1) and col2.dims == ['k', 'mu']
    both = ds[:, [0, -1]]
    assert both.shape == (10, 2)
    np.testing.assert_allclose(both['power'], ds['power'][:, [0, -1]])
    tup = ds[('k', 'power')]
    assert set(tup.variables) == {'k', 'power'}
    import pytest
    with pytest.raises(KeyError):
        ds[['k', 'nope']]
    with pytest.raises(IndexError):
        ds[0, 0]
    with pytest.raises(IndexError):
        ds[0, 0, 0]


def test_sel_and_squeeze():
    ds = make_2d()
    # scalar sel squeezes
    one = ds.sel(mu=ds.coords['mu'][0])
    assert one.dims == ['k']
    # slice sel keeps
    rng = ds.sel(k=slice(0.15, 0.55))
    assert rng.dims == ['k', 'mu']
    assert rng.shape[0] == 5
    # nearest method
    near = ds.sel(k=0.17, method='nearest')
    assert near.dims == ['mu']


def test_take():
    ds = make_2d()
    t = ds.take(k=ds.coords['k'] > 0.5)
    assert t.shape == (5, 5)
    t2 = ds.take(ds['modes'] > 0)
    assert t2.shape == ds.shape


def test_reindex_and_average():
    ds = make_2d()
    re = ds.reindex('k', 0.2)
    assert re.shape == (5, 5)
    # modes are summed, not averaged
    np.testing.assert_allclose(
        re['modes'], ds['modes'].reshape(5, 2, 5).sum(axis=1))
    av = ds.average('mu')
    assert av.dims == ['k']
    np.testing.assert_allclose(av['modes'], ds['modes'].sum(axis=1))


def test_json_roundtrip(tmp_path):
    ds = make_2d()
    fn = str(tmp_path / "ds.json")
    ds.to_json(fn)
    ds2 = BinnedStatistic.from_json(fn)
    assert ds2.dims == ds.dims
    np.testing.assert_allclose(ds2['power'].real, ds['power'].real)
    np.testing.assert_allclose(ds2['power'].imag, ds['power'].imag)
    np.testing.assert_allclose(ds2.edges['k'], ds.edges['k'])
    assert ds2.attrs['attr1'] == 'hello'


def test_rename_and_setitem():
    ds = make_2d()
    # in-place, like the reference (binned_statistic.py rename docs)
    ds.rename_variable('power', 'corr')
    assert 'corr' in ds.variables and 'power' not in ds.variables
    with pytest.raises(ValueError):
        ds.rename_variable('nope', 'x')
    ds['extra'] = np.ones(ds.shape)
    assert 'extra' in ds.variables
    with pytest.raises(ValueError):
        ds['bad'] = np.ones((3, 3))


def test_copy_independent():
    ds = make_2d()
    cp = ds.copy()
    cp['power'][...] = 0
    assert not np.allclose(ds['power'], 0)
