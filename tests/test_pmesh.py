"""Core ParticleMesh tests: FFT round-trip/correctness vs numpy, paint
mass conservation + cross-device-count invariance, readout consistency.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.parallel import dfft
from nbodykit_tpu.parallel.runtime import cpu_mesh


def test_dist_rfftn_matches_numpy(cpu8):
    rng = np.random.RandomState(42)
    x = rng.standard_normal((16, 24, 20))
    want = np.fft.rfftn(x).transpose(1, 0, 2)

    got1 = dfft.dist_rfftn(jnp.asarray(x), None)
    np.testing.assert_allclose(np.asarray(got1), want, rtol=1e-9, atol=1e-8)

    got8 = dfft.dist_rfftn(jnp.asarray(x), cpu8)
    np.testing.assert_allclose(np.asarray(got8), want, rtol=1e-9, atol=1e-8)


def test_dist_irfftn_roundtrip(cpu8):
    rng = np.random.RandomState(1)
    x = rng.standard_normal((16, 8, 12))
    y = dfft.dist_rfftn(jnp.asarray(x), cpu8)
    back = dfft.dist_irfftn(y, 12, cpu8)
    np.testing.assert_allclose(np.asarray(back), x, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize('shape', [(16, 24, 20), (12, 12, 12)])
@pytest.mark.parametrize('traced', [False, True])
def test_chunked_single_device_fft_matches_plain(shape, traced):
    # force the slab-chunked path on a tiny mesh and compare against
    # the one-shot rfftn (and the exact round-trip back). Eager calls
    # route through the Python-chunked lowmem driver; traced calls
    # through the in-jit fori_loop version — both must agree.
    import nbodykit_tpu
    rng = np.random.RandomState(7)
    x = rng.standard_normal(shape)
    want = np.fft.rfftn(x).transpose(1, 0, 2)
    fwd = (jax.jit(lambda v: dfft.dist_rfftn(v, None)) if traced
           else (lambda v: dfft.dist_rfftn(v, None)))
    inv = (jax.jit(lambda v: dfft.dist_irfftn(v, shape[2], None))
           if traced else (lambda v: dfft.dist_irfftn(v, shape[2], None)))
    with nbodykit_tpu.set_options(fft_chunk_bytes=1024):
        got = fwd(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-9, atol=1e-8)
        back = inv(got)
    np.testing.assert_allclose(np.asarray(back), x, rtol=1e-9, atol=1e-9)


def test_rfftn_single_lowmem_matches_plain():
    # the eager Python-chunked low-memory driver (bench >=1024 staged
    # path) must match the one-shot transform, and must consume its
    # one-element input box (ownership transfer)
    import nbodykit_tpu
    rng = np.random.RandomState(11)
    x = rng.standard_normal((8, 10, 12)).astype(np.float32)
    want = np.fft.rfftn(x.astype(np.float64)).transpose(1, 0, 2)
    with nbodykit_tpu.set_options(fft_chunk_bytes=1024):
        box = [jnp.asarray(x)]
        got = dfft.rfftn_single_lowmem(box)
    assert box == []
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=1e-4)


def test_irfftn_single_lowmem_roundtrip():
    import nbodykit_tpu
    rng = np.random.RandomState(13)
    x = rng.standard_normal((8, 10, 12)).astype(np.float32)
    with nbodykit_tpu.set_options(fft_chunk_bytes=1024):
        y = dfft.rfftn_single_lowmem([jnp.asarray(x)])
        box = [y]
        back = dfft.irfftn_single_lowmem(box, 12)
    assert box == []
    np.testing.assert_allclose(np.asarray(back), x, rtol=2e-4, atol=1e-4)


def test_chunked_c2c_matches_plain_and_roundtrips():
    import nbodykit_tpu
    rng = np.random.RandomState(5)
    x = (rng.standard_normal((10, 8, 6))
         + 1j * rng.standard_normal((10, 8, 6)))
    want = np.fft.fftn(x).transpose(1, 0, 2)
    with nbodykit_tpu.set_options(fft_chunk_bytes=512):
        got = dfft.dist_fftn_c2c(jnp.asarray(x), None)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-9, atol=1e-8)
        back = dfft.dist_fftn_c2c(got, None, inverse=True)
    np.testing.assert_allclose(np.asarray(back), x, rtol=1e-9, atol=1e-9)


def test_chunked_fft_norm_ortho_and_odd_rows():
    # odd leading axis exercises the divisor fallback; 'ortho' must
    # compose across the per-axis passes exactly like the one-shot
    import nbodykit_tpu
    rng = np.random.RandomState(3)
    x = rng.standard_normal((9, 6, 10))
    want = np.fft.rfftn(x, norm='ortho').transpose(1, 0, 2)
    with nbodykit_tpu.set_options(fft_chunk_bytes=512):
        got = dfft.dist_rfftn(jnp.asarray(x), None, norm='ortho')
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-9, atol=1e-8)


def test_r2c_normalization(comm):
    # pmesh convention: r2c divides by Ntot, so DC mode = mean of field
    pm = ParticleMesh(8, 1.0, dtype='f8', comm=comm)
    field = pm.create('real', value=3.0)
    c = pm.r2c(field)
    np.testing.assert_allclose(complex(c[0, 0, 0]), 3.0, rtol=1e-12)
    back = pm.c2r(c)
    np.testing.assert_allclose(np.asarray(back), 3.0, rtol=1e-6)


@pytest.mark.parametrize("resampler", ['nnb', 'cic', 'tsc', 'pcs'])
def test_paint_mass_conservation(comm, resampler):
    pm = ParticleMesh(32, 100.0, dtype='f8', comm=comm)
    rng = np.random.RandomState(7)
    pos = jnp.asarray(rng.uniform(0, 100.0, size=(1000, 3)))
    mass = jnp.asarray(rng.uniform(0.5, 1.5, size=1000))
    field = pm.paint(pos, mass, resampler=resampler)
    np.testing.assert_allclose(float(field.sum()), float(mass.sum()),
                               rtol=1e-10)


@pytest.mark.parametrize("resampler", ['cic', 'tsc'])
def test_paint_device_count_invariance(resampler):
    rng = np.random.RandomState(3)
    pos_np = rng.uniform(0, 50.0, size=(4096, 3))
    fields = []
    for comm in [cpu_mesh(1), cpu_mesh(2), cpu_mesh()]:
        pm = ParticleMesh(32, 50.0, dtype='f8', comm=comm)
        field = pm.paint(jnp.asarray(pos_np), 1.0, resampler=resampler)
        fields.append(np.asarray(field))
    np.testing.assert_allclose(fields[0], fields[1], rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(fields[0], fields[2], rtol=1e-10, atol=1e-12)


def test_paint_nnb_is_histogram(comm):
    pm = ParticleMesh(8, 8.0, dtype='f8', comm=comm)
    rng = np.random.RandomState(11)
    pos_np = rng.uniform(0, 8.0, size=(500, 3))
    field = np.asarray(pm.paint(jnp.asarray(pos_np), 1.0, resampler='nnb'))
    # nnb: each particle deposits into the nearest cell center => cell
    # index = floor(x + 0.5) mod N at unit cell size
    idx = np.floor(pos_np + 0.5).astype(int) % 8
    want = np.zeros((8, 8, 8))
    np.add.at(want, (idx[:, 0], idx[:, 1], idx[:, 2]), 1.0)
    np.testing.assert_array_equal(field, want)


def test_readout_constant_field(comm):
    # interpolating a constant field returns the constant for any window
    pm = ParticleMesh(32, 32.0, dtype='f8', comm=comm)
    field = pm.create('real', value=5.0)
    rng = np.random.RandomState(5)
    pos = jnp.asarray(rng.uniform(0, 32.0, size=(300, 3)))
    for resampler in ['cic', 'tsc', 'pcs']:
        vals = pm.readout(field, pos, resampler=resampler)
        np.testing.assert_allclose(np.asarray(vals), 5.0, rtol=1e-10)


def test_readout_linear_gradient(comm):
    # CIC exactly interpolates a linear function away from wrap edges
    pm = ParticleMesh(16, 16.0, dtype='f8', comm=comm)
    x = np.arange(16)
    field = jnp.asarray(np.broadcast_to(
        x[:, None, None], (16, 16, 16)).astype('f8'))
    rng = np.random.RandomState(9)
    pos_np = np.column_stack([
        rng.uniform(2, 13, 200),      # away from the periodic seam
        rng.uniform(0, 16, 200),
        rng.uniform(0, 16, 200)])
    vals = pm.readout(field, jnp.asarray(pos_np), resampler='cic')
    np.testing.assert_allclose(np.asarray(vals), pos_np[:, 0], rtol=1e-9)


def test_whitenoise_invariance_and_variance():
    for N in [16]:
        pms = [ParticleMesh(N, 1.0, dtype='f8', comm=c)
               for c in [cpu_mesh(1), cpu_mesh()]]
        etas = [np.asarray(pm.generate_whitenoise(seed=99)) for pm in pms]
        np.testing.assert_allclose(etas[0], etas[1], rtol=1e-10)
        # unit variance per complex mode (hermitian-sum over all modes)
        eta = etas[0]
        # total power sum_k |eta|^2 over the full (uncompressed) cube
        w = np.ones(N // 2 + 1) * 2.0
        w[0] = 1.0
        w[-1] = 1.0
        total = np.sum(np.abs(eta) ** 2 * w)
        assert abs(total / N ** 3 - 1.0) < 0.05


def test_whitenoise_unitary():
    pm = ParticleMesh(8, 1.0, dtype='f8')
    eta = np.asarray(pm.generate_whitenoise(seed=1, unitary=True))
    np.testing.assert_allclose(np.abs(eta), 1.0, rtol=1e-10)


def test_uniform_particle_grid(comm):
    pm = ParticleMesh(8, 16.0, dtype='f8', comm=comm)
    pos = np.asarray(pm.generate_uniform_particle_grid(shift=0.5))
    assert pos.shape == (512, 3)
    assert pos.min() == 1.0 and pos.max() == 15.0
    # paint back with nnb: exactly one particle per cell
    field = np.asarray(pm.paint(jnp.asarray(pos), 1.0, resampler='nnb'))
    np.testing.assert_array_equal(field, np.ones((8, 8, 8)))


def test_paint_clustered_no_mass_loss():
    # all particles in one slab: auto-capacity must still not drop any
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    pm = ParticleMesh(16, 16.0, dtype='f8', comm=cpu_mesh())
    rng = np.random.RandomState(0)
    pos_np = rng.uniform(0, 16.0, size=(4096, 3))
    pos_np[:, 0] = rng.uniform(0.0, 1.5, size=4096)  # clustered in x
    field = pm.paint(jnp.asarray(pos_np), 1.0, resampler='cic')
    np.testing.assert_allclose(float(field.sum()), 4096.0, rtol=1e-10)


def test_paint_non_divisible_N(comm):
    # N=501 not divisible by 8 devices: padding path
    pm = ParticleMesh(16, 16.0, dtype='f8', comm=comm)
    rng = np.random.RandomState(2)
    pos = jnp.asarray(rng.uniform(0, 16.0, size=(501, 3)))
    field = pm.paint(pos, 1.0, resampler='cic')
    np.testing.assert_allclose(float(field.sum()), 501.0, rtol=1e-10)


def test_halo_too_wide_raises():
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    pm = ParticleMesh(16, 16.0, dtype='f8', comm=cpu_mesh())  # n0 = 2
    pos = jnp.asarray(np.random.RandomState(1).uniform(0, 16.0, (64, 3)))
    with pytest.raises(ValueError, match="support"):
        pm.paint(pos, 1.0, resampler='tsc')  # support 3 > n0 2


def test_paint_sorted_max_collision_exact():
    """All particles in one cell: the sorted paint's doubling
    reduction must sum arbitrarily long runs exactly (f32-roundoff
    close to the f64 truth), and unused compaction slots must not
    corrupt neighboring cells."""
    from nbodykit_tpu.ops.paint import paint_local, paint_local_sorted

    pos = jnp.asarray(np.full((5000, 3), 3.3, dtype='f4'))
    for rs in ('cic', 'tsc', 'pcs'):
        truth = paint_local(pos.astype(jnp.float64), jnp.float64(1.0),
                            (8, 8, 8), resampler=rs)
        got = paint_local_sorted(pos, jnp.float32(1.0), (8, 8, 8),
                                 resampler=rs)
        scale = float(np.abs(np.asarray(truth)).max())
        err = np.abs(np.asarray(got, 'f8')
                     - np.asarray(truth)).max() / scale
        assert err < 1e-5, (rs, err)
        # total mass conserved
        assert abs(float(np.asarray(got, 'f8').sum()) - 5000) < 1.0


def test_paint_method_device_count_invariance(method='sort'):
    """The sort kernel produces device-count-invariant fields through
    the full exchange + halo path (the scatter kernel's invariance is
    test_paint_device_count_invariance above)."""
    from nbodykit_tpu import set_options

    rng = np.random.RandomState(13)
    pos_np = rng.uniform(0, 50.0, size=(3000, 3))
    fields = []
    with set_options(paint_method=method):
        for comm in [cpu_mesh(1), cpu_mesh()]:
            pm = ParticleMesh(32, 50.0, dtype='f8', comm=comm)
            field = pm.paint(jnp.asarray(pos_np), 1.0, resampler='tsc')
            fields.append(np.asarray(field))
    np.testing.assert_allclose(fields[0], fields[1], rtol=1e-10,
                               atol=1e-12)
    np.testing.assert_allclose(fields[0].sum(), 3000.0, rtol=1e-9)


def test_memory_plan_scale_claims():
    """The HBM arithmetic behind BASELINE.md: the v5e-16 stretch config
    fits per device, the single-chip 2048 does not, and small configs
    are comfortable (pmesh.memory_plan)."""
    from nbodykit_tpu.pmesh import memory_plan

    assert memory_plan(512, int(1e7), 1)['fits']
    assert not memory_plan(2048, int(1e9), 1)['fits']
    p16 = memory_plan(2048, int(1e9), 16)
    assert p16['fits'] and p16['peak_bytes'] < 10e9
    # monotonic in devices
    assert (memory_plan(1024, int(1e8), 8)['peak_bytes']
            < memory_plan(1024, int(1e8), 1)['peak_bytes'])
    # sort paint costs more than chunked scatter at large npart
    assert (memory_plan(1024, int(1e8), 1, paint_method='sort')
            ['paint_temporaries']
            > memory_plan(1024, int(1e8), 1)['paint_temporaries'])
