"""The shard-safety linter: per-family positives/negatives, the
suppression + baseline workflow, the package gate, and the acceptance
fixture (a seeded rank-dependent collective must be caught by both the
CLI and this pytest gate).

These tests are pure-host (AST only) — no jax computation — so the
whole module runs in well under a second apart from the package-wide
gate sweep.
"""

import json
import os
import subprocess
import sys
import textwrap

from nbodykit_tpu import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_str(src, select=None):
    return lint.lint_source(
        'fixture.py', textwrap.dedent(src),
        project_constants={'AXIS': 'dev'}, select=select)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# NBK1xx — collectives

SHARD_MAP_HEADER = """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
"""


def test_nbk101_axis_mismatch_detected():
    fs = lint_str(SHARD_MAP_HEADER + """
    def body(x):
        return jax.lax.psum(x, 'cols')

    f = jax.shard_map(body, mesh=None, in_specs=P('rows'),
                      out_specs=P('rows'))
    """)
    assert codes(fs) == ['NBK101']
    assert "'cols'" in fs[0].message and "'rows'" in fs[0].message
    assert fs[0].line == 7         # anchors on the psum call itself


def test_nbk101_matching_axis_is_clean():
    fs = lint_str(SHARD_MAP_HEADER + """
    def body(x):
        return jax.lax.psum(x, 'rows')

    f = jax.shard_map(body, mesh=None, in_specs=P('rows'),
                      out_specs=P('rows'))
    """)
    assert fs == []


def test_nbk101_axis_constant_resolves_across_modules():
    # AXIS resolves to 'dev' through the project constant table, so
    # AXIS-vs-'dev' comparisons match instead of false-firing
    fs = lint_str(SHARD_MAP_HEADER + """
    from nbodykit_tpu.parallel.runtime import AXIS

    def body(x):
        return jax.lax.psum(x, AXIS)

    f = jax.shard_map(body, mesh=None, in_specs=P('dev'),
                      out_specs=P())
    """)
    assert fs == []


def test_nbk101_unresolvable_axis_stays_silent():
    # dynamic axis expressions can't be judged statically — no finding
    fs = lint_str(SHARD_MAP_HEADER + """
    def make(ax):
        def body(x):
            return jax.lax.psum(x, ax)
        return jax.shard_map(body, mesh=None, in_specs=P('rows'),
                             out_specs=P('rows'))
    """, select=['NBK1'])
    assert fs == []


def test_nbk102_rank_gated_collective_detected():
    fs = lint_str(SHARD_MAP_HEADER + """
    def body(x):
        if jax.process_index() == 0:
            x = jax.lax.psum(x, 'dev')
        return x
    """, select=['NBK102'])
    assert codes(fs) == ['NBK102']


def test_nbk102_tainted_name_and_transitive_callee():
    # rank flows through an assignment, and the collective sits in a
    # helper the branch calls — both hops must be followed
    fs = lint_str(SHARD_MAP_HEADER + """
    def reduce_all(x):
        return jax.lax.psum(x, 'dev')

    def body(x):
        rank = jax.process_index()
        if rank == 0:
            x = reduce_all(x)
        return x
    """, select=['NBK102'])
    assert codes(fs) == ['NBK102']


def test_nbk102_data_dependent_branch_is_clean():
    fs = lint_str(SHARD_MAP_HEADER + """
    def body(x, flag):
        if flag:
            x = jax.lax.psum(x, 'dev')
        return x
    """, select=['NBK102'])
    assert fs == []


# ---------------------------------------------------------------------------
# NBK2xx — compile hygiene

def test_nbk201_jit_in_loop():
    fs = lint_str("""
    import jax

    def run(xs):
        out = []
        for x in xs:
            out.append(jax.jit(step)(x))
        return out

    def step(x):
        return x
    """, select=['NBK201'])
    assert codes(fs) == ['NBK201']


def test_nbk201_module_level_jit_clean():
    fs = lint_str("""
    import jax

    def step(x):
        return x

    fast_step = jax.jit(step)
    """, select=['NBK2'])
    assert fs == []


def test_nbk202_lambda_per_call():
    fs = lint_str("""
    import jax

    def run(x):
        f = jax.jit(lambda v: v * 2)
        return f(x)
    """, select=['NBK202'])
    assert codes(fs) == ['NBK202']


def test_nbk202_lru_cached_builder_is_the_fix():
    # the dfft.py pattern: a memoized builder constructs jits once per
    # config — that's the recommended fix, not a finding
    fs = lint_str("""
    import functools
    import jax

    @functools.lru_cache(maxsize=8)
    def programs(shape):
        return jax.jit(lambda v: v.reshape(shape))
    """, select=['NBK2'])
    assert fs == []


def test_nbk203_unhashable_static_args():
    fs = lint_str("""
    import jax

    def f(x, shape):
        return x.reshape(shape)

    fj = jax.jit(f, static_argnums=(1,))
    y = fj(data, [4, 4])
    """, select=['NBK203'])
    assert codes(fs) == ['NBK203']


def test_nbk203_tuple_static_arg_clean():
    fs = lint_str("""
    import jax

    def f(x, shape):
        return x.reshape(shape)

    fj = jax.jit(f, static_argnums=(1,))
    y = fj(data, (4, 4))
    """, select=['NBK203'])
    assert fs == []


# ---------------------------------------------------------------------------
# NBK3xx — precision

def test_nbk301_float64_in_traced_code():
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x + jnp.zeros(3, dtype=jnp.float64)
    """, select=['NBK301'])
    assert codes(fs) == ['NBK301']


def test_nbk301_host_numpy_f8_is_clean():
    # host-side numpy prep (gridhash.py style) legitimately uses f8
    fs = lint_str("""
    import numpy as np

    def prep(pos):
        return np.asarray(pos, dtype='f8')
    """, select=['NBK301'])
    assert fs == []


def test_nbk301_x64_guard_and_working_dtype_exempt():
    fs = lint_str("""
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.utils import working_dtype

    def f():
        a = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        b = jnp.zeros(3, dtype=a)
        c = jnp.zeros(3, dtype=working_dtype('f8'))
        return b, c
    """, select=['NBK301'])
    assert fs == []


def test_nbk302_int32_flat_index_chain():
    fs = lint_str("""
    import jax.numpy as jnp

    def flatten(ci, n1, n2):
        return (ci[:, 0].astype(jnp.int32) * n1 + ci[:, 1]) * n2 \\
            + ci[:, 2]
    """, select=['NBK302'])
    assert codes(fs) == ['NBK302']


def test_nbk302_single_multiply_clean():
    fs = lint_str("""
    import jax.numpy as jnp

    def pair(src, dest, nproc):
        return src.astype(jnp.int32) * nproc + dest
    """, select=['NBK302'])
    assert fs == []


# ---------------------------------------------------------------------------
# NBK4xx — trace safety

def test_nbk401_host_sync_in_traced_code():
    fs = lint_str("""
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        v = float(x)
        a = np.asarray(x)
        b = x.sum().item()
        return v, a, b
    """, select=['NBK401'])
    assert codes(fs) == ['NBK401'] * 3


def test_nbk401_shape_math_and_host_code_clean():
    fs = lint_str("""
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        n = int(x.shape[0])
        return x * n

    def host(y):
        return float(y)
    """, select=['NBK401'])
    assert fs == []


def test_nbk402_impure_host_op_in_trace():
    fs = lint_str("""
    import time
    import numpy as np
    import jax

    @jax.jit
    def f(x):
        return x + np.random.uniform() + time.time()
    """, select=['NBK402'])
    assert codes(fs) == ['NBK402'] * 2


def test_nbk402_host_randomness_clean():
    fs = lint_str("""
    import time
    import numpy as np

    def seed():
        return np.random.randint(0, 2 ** 31 - 1), time.time()
    """, select=['NBK402'])
    assert fs == []


# ---------------------------------------------------------------------------
# suppressions + baseline workflow

def test_inline_and_file_suppressions():
    src = """
    import jax

    def run(x):
        f = jax.jit(lambda v: v)  # nbkl: disable=NBK202
        # nbkl: disable=NBK202
        g = jax.jit(lambda v: v + 1)
        h = jax.jit(lambda v: v + 2)
        return f(x), g(x), h(x)
    """
    fs = lint_str(src, select=['NBK202'])
    assert len(fs) == 1 and fs[0].line == 8     # only h() fires

    fs = lint_str('# nbkl: disable-file=NBK202\n'
                  + textwrap.dedent(src), select=['NBK202'])
    assert fs == []


def test_baseline_roundtrip_survives_line_drift(tmp_path):
    src_v1 = textwrap.dedent("""
    import jax

    def run(x):
        return jax.jit(lambda v: v)(x)
    """)
    findings = lint.lint_source('pkg.py', src_v1, select=['NBK202'])
    assert len(findings) == 1
    sources = {'pkg.py': src_v1.splitlines()}
    doc = lint.build_baseline(findings, sources=sources)
    path = str(tmp_path / 'baseline.json')
    lint.write_baseline(doc, path)

    # same finding, shifted two lines down: still grandfathered
    src_v2 = '# new header\n# more header\n' + src_v1
    moved = lint.lint_source('pkg.py', src_v2, select=['NBK202'])
    assert moved[0].line == findings[0].line + 2
    new, grand, unused = lint.apply_baseline(
        moved, lint.load_baseline(path),
        sources={'pkg.py': src_v2.splitlines()})
    assert new == [] and len(grand) == 1 and unused == []

    # finding fixed: the stale baseline entry is reported for pruning
    new, grand, unused = lint.apply_baseline(
        [], lint.load_baseline(path), sources={})
    assert new == [] and grand == [] and len(unused) == 1


def test_malformed_baseline_raises(tmp_path):
    path = str(tmp_path / 'baseline.json')
    with open(path, 'w') as f:
        f.write('{"not": "a baseline"}')
    try:
        lint.load_baseline(path)
    except ValueError:
        pass
    else:
        raise AssertionError('malformed baseline must not load')


# ---------------------------------------------------------------------------
# the package gate: the committed baseline covers everything

def test_package_has_no_unbaselined_findings():
    new, grandfathered, unused = lint.run_lint(
        lint.default_targets(REPO),
        baseline_path=os.path.join(REPO, 'lint_baseline.json'))
    assert new == [], (
        'non-baselined lint findings — fix them or (if audited) add '
        'them to lint_baseline.json:\n'
        + lint.render_findings(new))
    assert unused == [], (
        'stale lint_baseline.json entries (the findings were fixed); '
        'prune them: %r' % unused)
    # the baseline exists and every grandfathered entry carries weight
    assert len(grandfathered) > 0


def test_jit_label_map_covers_instrumented_hot_paths():
    labels = lint.collect_jit_labels(lint.default_targets(REPO))
    assert 'fftpower.binning' in labels
    path, line = labels['fftpower.binning']
    assert path == 'nbodykit_tpu/algorithms/fftpower.py' and line > 0


# ---------------------------------------------------------------------------
# acceptance: a seeded rank-dependent collective is caught by the CLI
# and by the same API path this pytest gate uses

RANK_GATED_FIXTURE = textwrap.dedent("""
    import jax
    import jax.numpy as jnp

    def broken(x):
        if jax.process_index() == 0:
            x = jax.lax.psum(x, 'dev')
        return x
""")


def test_seeded_hazard_detected_by_pytest_gate(tmp_path):
    pkg = tmp_path / 'nbodykit_tpu'
    pkg.mkdir()
    (pkg / 'seeded.py').write_text(RANK_GATED_FIXTURE)
    new, _, _ = lint.run_lint([str(pkg)])
    # the rank-gated collective trips both detectors since nbkl v2:
    # NBK102 (collective under the branch) and NBK103 (the branch's
    # arms emit divergent collective sequences)
    assert sorted(f.code for f in new) == ['NBK102', 'NBK103']
    assert all(f.path == 'nbodykit_tpu/seeded.py' for f in new)


def test_seeded_hazard_detected_by_cli(tmp_path):
    fixture = tmp_path / 'seeded.py'
    fixture.write_text(RANK_GATED_FIXTURE)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert 'NBK102' in proc.stdout
    # with the hazard grandfathered the same invocation gates green
    bl = tmp_path / 'baseline.json'
    subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--write-baseline', str(bl)],
        capture_output=True, text=True, cwd=REPO, check=True)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--baseline', str(bl)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_and_rule_catalog(tmp_path):
    fixture = tmp_path / 'seeded.py'
    fixture.write_text(RANK_GATED_FIXTURE)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--json'],
        capture_output=True, text=True, cwd=REPO)
    data = json.loads(proc.stdout)
    assert data['summary']['by_code'] == {'NBK102': 1, 'NBK103': 1}
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--list-rules'],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    for code in ('NBK101', 'NBK102', 'NBK103', 'NBK201', 'NBK202',
                 'NBK203', 'NBK301', 'NBK302', 'NBK401', 'NBK402',
                 'NBK501', 'NBK502', 'NBK503',
                 'NBK601', 'NBK602', 'NBK603', 'NBK604',
                 'NBK701', 'NBK702', 'NBK703', 'NBK704'):
        assert code in proc.stdout


# ---------------------------------------------------------------------------
# doctor cross-link: compile misses + open NBK2xx finding on one line

def test_doctor_cross_links_compile_misses_to_nbk2(tmp_path, capsys):
    import shutil

    from nbodykit_tpu.diagnostics import REGISTRY, counter
    from nbodykit_tpu.diagnostics.__main__ import run_doctor

    # a throwaway root mirroring the repo's lint surface, so the
    # doctor's regress step writes its BENCH_HISTORY there, not here
    root = str(tmp_path)
    os.symlink(os.path.join(REPO, 'nbodykit_tpu'),
               os.path.join(root, 'nbodykit_tpu'))
    shutil.copy(os.path.join(REPO, 'lint_baseline.json'),
                os.path.join(root, 'lint_baseline.json'))
    counter('compile.fftpower.binning.misses').add(3)
    try:
        rc = run_doctor(trace=None, root=root)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert 'lint         OK' in out
        assert 'compile      WARN' in out
        assert "'fftpower.binning'" in out
        assert 'NBK202' in out and 'fftpower.py' in out
    finally:
        REGISTRY.reset()
