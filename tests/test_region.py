"""Tests for nbodykit_tpu.serve.region: the multi-fleet front door.

The load-bearing property test is result-key purity — the cache
address is EXACTLY ``(program_key, seed|catalog-digest, sorted(jit
options))``: every runtime-only field (priority, deadline_s, verify,
request_id, tenant) perturbs nothing, every jit-reaching option
perturbs the address.  Around it: torn-entry corruption (detected,
recomputed, never served), LRU eviction, router verdict grammar
(affinity / spill / rerouted_dead / catalog_home / no_fleet), the
singleflight follower path, QoS bucket determinism + the fair-share
and starvation ledgers + chaos at the admission gate, the
verified-stamp contract under the ``region.result.stamp`` corrupt
rule, elastic grow with ``reformed_from/to`` manifest stamps, the
``data_steal_grace_s`` satellite, region-trace synthesis, and the
regress posture plumbing."""

import json
import os
import time

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.resilience import reset_faults
from nbodykit_tpu.resilience.fleet import (FleetCheckpointStore,
                                           reassemble)
from nbodykit_tpu.serve import (COMPLETED, EVICTED, AnalysisRequest,
                                AnalysisServer, QoSPolicy, Region,
                                RegionRouter, RequestResult,
                                ResultCache, ServiceClass,
                                generate_region_trace, result_key)
from nbodykit_tpu.serve.region import (JIT_OPTIONS, Fleet,
                                       catalog_identity, grow,
                                       seal_join)
from nbodykit_tpu.serve.region.qos import _Bucket
from nbodykit_tpu.serve.scheduler import affinity


@pytest.fixture(autouse=True)
def _clean_state():
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    yield
    REGISTRY.reset()
    reset_faults()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


# ---------------------------------------------------------------------------
# fakes: just enough AnalysisServer surface for region mechanics

class _FakeTicket(object):
    def __init__(self, request, verify=False):
        self.request = request
        self.verify = verify


class _FakeServer(object):
    """Deterministic stand-in: completes (or evicts) instantly, with
    seed-dependent spectra so cached bytes are checkable."""

    def __init__(self, ndevices=1, status=COMPLETED, verify=False,
                 accepting=True, queued=0):
        self.ndevices = ndevices
        self.meshes = [None]
        self.status = status
        self.verify = verify
        self.accepting = accepting
        self.queued = queued
        self.submitted = []

    def load(self):
        return {'queued': self.queued, 'inflight': 0,
                'accepting': self.accepting, 'workers': 1}

    def submit(self, request):
        self.submitted.append(request)
        return _FakeTicket(request, verify=self.verify)

    def wait(self, ticket, timeout=None):
        req = ticket.request
        if self.status == COMPLETED:
            return RequestResult(
                req.request_id, COMPLETED, x=np.arange(4.0) + 0.5,
                y=np.arange(4.0) * (req.seed + 1),
                nmodes=np.ones(4, dtype=np.int64), latency_s=1e-4,
                algorithm=req.algorithm,
                shape_class=req.shape_class)
        return RequestResult(
            req.request_id, self.status, reason={'code': 'deadline'},
            latency_s=1e-4, algorithm=req.algorithm,
            shape_class=req.shape_class)

    def summary(self):
        return {'submitted': len(self.submitted), 'lost': 0}

    def shutdown(self, drain=True, timeout=None):
        self.accepting = False


# ---------------------------------------------------------------------------
# result-key purity (the satellite property test)

def test_result_key_runtime_fields_perturb_nothing():
    base = AnalysisRequest(nmesh=64, npart=100000, seed=5,
                           request_id='a')
    d0, text = result_key(base)
    # every runtime-only knob, together and separately
    twin = AnalysisRequest(nmesh=64, npart=100000, seed=5,
                           priority=2, deadline_s=0.125, verify=True,
                           request_id='completely-different')
    assert result_key(twin)[0] == d0
    # runtime-only OPTIONS perturb nothing either
    with nbodykit_tpu.set_options(
            diagnostics=None, tune_cache=None,
            io_verify_checksums=False, ingest_overlap=False,
            data_steal_grace_s=9.5,
            faults='region.qos.admit@99:internal'):
        assert result_key(base)[0] == d0
    # the canonical text carries no runtime field by name
    for forbidden in ('priority', 'deadline', 'verify', 'tenant',
                      'request_id'):
        assert forbidden not in text


def test_result_key_every_jit_option_perturbs():
    base = AnalysisRequest(nmesh=64, npart=100000, seed=5)
    d0, _ = result_key(base)
    perturb = {
        'mesh_dtype': 'bf16', 'a2a_compress': 'bf16',
        'resampler': 'tsc', 'paint_method': 'sort',
        'paint_chunk_size': 12345, 'paint_bucket_slack': 1.75,
        'paint_streams': 7, 'fft_chunk_bytes': 999,
        'fft_decomp': 'pencil', 'fft_pencil': (2, 4),
        'exchange_slack': 1.5, 'integrity': 'cheap',
        'ingest_chunk_rows': 4242,
    }
    assert sorted(perturb) == sorted(JIT_OPTIONS)
    digests = {d0}
    for key, value in perturb.items():
        with nbodykit_tpu.set_options(**{key: value}):
            d, _ = result_key(base)
        assert d != d0, 'jit option %r did not perturb' % key
        digests.add(d)
    # all distinct: no two options collide onto one address
    assert len(digests) == len(perturb) + 1
    # program identity and realization input perturb too
    assert result_key(base, ndevices=8)[0] != d0
    assert result_key(AnalysisRequest(nmesh=64, npart=100000,
                                      seed=6))[0] != d0
    assert result_key(AnalysisRequest(nmesh=32, npart=100000,
                                      seed=5))[0] != d0
    # request-scoped option overrides (the admission ladder) key too
    dov, _ = result_key(base, options={'mesh_dtype': 'bf16'})
    assert dov != d0
    # ... but a runtime-only override does not
    assert result_key(base, options={'diagnostics': '/tmp/x'})[0] \
        == d0


def test_catalog_identity_and_data_ref_keys(tmp_path):
    path = str(tmp_path / 'cat.bin')
    np.arange(12, dtype='f4').tofile(path)
    ref = {'path': path, 'format': 'binary',
           'columns': {'Position': 'Position'},
           'options': {'dtype': [('Position', ('f4', 3))]}}
    d0 = catalog_identity(ref)
    assert d0 == catalog_identity(dict(ref))
    # a data_ref request's seed is ignored, exactly as execution
    # ignores it
    r1 = AnalysisRequest(nmesh=32, data_ref=ref, seed=1)
    r2 = AnalysisRequest(nmesh=32, data_ref=ref, seed=999)
    assert result_key(r1)[0] == result_key(r2)[0]
    # rewriting the file mints a new address (size change)
    np.arange(24, dtype='f4').tofile(path)
    assert catalog_identity(ref) != d0
    # a different column map is a different catalog
    other = dict(ref, columns={'Position': 'pos'})
    assert catalog_identity(other) != catalog_identity(ref)


# ---------------------------------------------------------------------------
# the result cache on disk

def test_result_cache_roundtrip_bit_identity(tmp_path):
    cache = ResultCache(str(tmp_path))
    x = np.linspace(0.0, 1.0, 7)
    y = np.array([1e-300, -0.0, 3.141592653589793, 2.0 ** -1049,
                  1e308, -7.25, 0.1])
    nmodes = np.array([1, 2, 3, 4, 5, 6, 7], dtype=np.int64)
    assert cache.get('deadbeef') is None          # cold miss
    cache.put('deadbeef', 'key-text', x, y, nmodes, verified=True)
    got = cache.get('deadbeef')
    # bit-identical round trip, including the denormal and the -0.0
    assert got['x'].dtype == x.dtype
    assert np.array_equal(got['x'], x)
    assert np.array_equal(got['y'], y)
    assert np.array_equal(got['nmodes'], nmodes)
    assert got['y'].tobytes() == y.tobytes()
    assert got['verified'] is True and got['key'] == 'key-text'
    st = cache.stats()
    assert st['hits'] == 1 and st['misses'] == 1 \
        and st['commits'] == 1 and st['corrupt'] == 0
    # a second cache over the same root adopts the committed entries
    again = ResultCache(str(tmp_path))
    assert len(again) == 1 and again.get('deadbeef') is not None


def test_result_cache_torn_entry_never_served(tmp_path):
    cache = ResultCache(str(tmp_path))
    a = np.arange(4.0)
    cache.put('d1', 'k1', a, a, a, verified=True)
    path = cache._path('d1')
    # torn write: truncate mid-file
    data = open(path, 'rb').read()
    with open(path, 'wb') as f:
        f.write(data[:len(data) // 2])
    assert cache.get('d1') is None
    assert not os.path.exists(path), 'torn entry must be unlinked'
    assert cache.stats()['corrupt'] == 1
    # tampered write: valid JSON, flipped verified stamp, stale hash
    cache.put('d2', 'k2', a, a, a, verified=False)
    path = cache._path('d2')
    stored = json.load(open(path))
    stored['body']['verified'] = True
    with open(path, 'w') as f:
        json.dump(stored, f)
    assert cache.get('d2') is None, 'forged stamp must not be served'
    assert cache.stats()['corrupt'] == 2
    assert not os.path.exists(path)
    # recompute-and-recommit heals
    cache.put('d2', 'k2', a, a, a, verified=False)
    assert cache.get('d2')['verified'] is False


def test_result_cache_lru_under_byte_cap(tmp_path):
    cache = ResultCache(str(tmp_path), budget_bytes=1)
    a = np.arange(8.0)
    cache.put('old', 'k', a, a, a)
    cache.put('new', 'k', a, a, a)
    assert cache.get('old') is None, 'LRU entry must be evicted'
    assert cache.stats()['evictions'] == 1
    assert not os.path.exists(cache._path('old'))


# ---------------------------------------------------------------------------
# QoS: deterministic buckets, fair share, chaos at the gate

def test_qos_bucket_due_time_ladder():
    b = _Bucket(rate=1.0, burst=2.0)
    # two burst slots, then the Nth over-burst request waits N/rate
    assert [b.reserve(100.0) for _ in range(5)] \
        == [0.0, 0.0, 1.0, 2.0, 3.0]
    # refill: 2.5 s later two tokens are back (capped at burst)
    b2 = _Bucket(rate=2.0, burst=2.0)
    for _ in range(4):
        b2.reserve(50.0)
    assert b2.reserve(53.0) == pytest.approx(0.0)


def test_qos_policy_validation_and_mapping():
    with pytest.raises(ValueError):
        ServiceClass('bad', rate=0.0)
    with pytest.raises(ValueError):
        QoSPolicy(tenants={'t': 'nope'})
    with pytest.raises(ValueError):
        QoSPolicy(default_class='nope')
    qos = QoSPolicy(tenants={'sweep': 'bulk'})
    assert qos.service_class('sweep').name == 'bulk'
    # unmapped tenants fall to interactive and are never throttled
    assert qos.service_class('stranger').rate is None
    name, delay = qos.reserve('stranger', 0.0)
    assert (name, delay) == ('interactive', 0.0)


def test_qos_gate_chaos_is_structured_rejection():
    server = _FakeServer()
    with nbodykit_tpu.set_options(
            faults='region.qos.admit@1:internal'):
        region = Region([('a', server)], qos=QoSPolicy())
        t = region.submit(AnalysisRequest(nmesh=32, npart=1000),
                          tenant='x')
        res = region.wait(t, timeout=5)
        summary = region.summary()
        region.shutdown()
    assert res.status == 'rejected'
    assert res.reason['code'] == 'qos_unavailable'
    assert summary['lost'] == 0
    assert not server.submitted, 'broken gate must not leak through'


def test_region_fair_share_flood_holds():
    """A bulk tenant floods at self-declared priority 2; per-tenant
    fair share throttles THAT tenant (held to due-times, all still
    completing) and nobody starves, nothing is lost."""
    server = _FakeServer()
    qos = QoSPolicy(
        classes=[ServiceClass('interactive'),
                 ServiceClass('bulk', rate=400.0, burst=2)],
        tenants={'flood': 'bulk'})
    region = Region([('a', server)], qos=qos)
    tickets = [region.submit(
        AnalysisRequest(nmesh=32, npart=1000, seed=i, priority=2,
                        deadline_s=30.0), tenant='flood')
        for i in range(8)]
    tickets += [region.submit(
        AnalysisRequest(nmesh=32, npart=1000, seed=100 + i,
                        deadline_s=30.0), tenant='alice')
        for i in range(3)]
    assert region.drain(timeout=30)
    summary = region.summary()
    region.shutdown()
    assert summary['lost'] == 0
    assert summary['completed'] == 11
    assert summary['qos']['throttled'] == 6      # 8 bulk - burst 2
    assert summary['qos']['starved'] == 0
    assert summary['by_class']['interactive']['completed'] == 3
    assert summary['by_class']['bulk']['completed'] == 8
    for t in tickets:
        assert region.wait(t).ok


def test_qos_throttle_past_deadline_is_structured_eviction():
    server = _FakeServer()
    qos = QoSPolicy(
        classes=[ServiceClass('interactive'),
                 ServiceClass('bulk', rate=0.5, burst=1)],
        tenants={'flood': 'bulk'})
    region = Region([('a', server)], qos=qos)
    first = region.submit(AnalysisRequest(nmesh=32, npart=1000,
                                          deadline_s=1.0),
                          tenant='flood')
    second = region.submit(AnalysisRequest(nmesh=32, npart=1000,
                                           deadline_s=1.0),
                           tenant='flood')
    r1, r2 = region.wait(first, timeout=10), region.wait(second,
                                                         timeout=10)
    summary = region.summary()
    region.shutdown()
    assert r1.ok
    assert r2.status == EVICTED
    assert r2.reason['code'] == 'qos_throttled'
    assert r2.reason['would_wait_s'] == pytest.approx(2.0)
    # a fair-share eviction of a THROTTLED class is not starvation
    assert summary['qos']['starved'] == 0
    assert summary['lost'] == 0


def test_starvation_ledger_counts_unthrottled_deadline_deaths():
    """The failure mode QoS exists to prevent: an interactive
    (unthrottled / policy-free) request dying of old age counts as
    starved — the doctor's WARN number."""
    server = _FakeServer(status=EVICTED)
    region = Region([('a', server)])     # no QoS: the naive region
    t = region.submit(AnalysisRequest(nmesh=32, npart=1000,
                                      deadline_s=5.0))
    res = region.wait(t, timeout=10)
    summary = region.summary()
    region.shutdown()
    assert res.status == EVICTED
    assert summary['qos']['starved'] == 1
    assert summary['lost'] == 0


# ---------------------------------------------------------------------------
# the router verdict grammar

def _two_fleets(**kw):
    return [Fleet('f0', _FakeServer(**kw)),
            Fleet('f1', _FakeServer(**kw))]


def test_router_affinity_and_spill_verdicts():
    fleets = _two_fleets()
    router = RegionRouter(fleets, spill_depth=2)
    req = AnalysisRequest(nmesh=64, npart=100000, seed=1)
    ai = affinity(req, 1, 2)
    v = router.route(req)
    assert v == {'code': 'affinity', 'fleet': 'f%d' % ai, 'depth': 0}
    # pile queue onto the affinity fleet: structured spill to the
    # least-loaded one
    fleets[ai].server.queued = 10
    v = router.route(req)
    assert v['code'] == 'spill'
    assert v['fleet'] == 'f%d' % (1 - ai)
    assert v['from'] == 'f%d' % ai
    assert v['from_depth'] == 10 and v['depth'] == 0
    # both equally deep: no spill that doesn't help
    fleets[1 - ai].server.queued = 10
    assert router.route(req)['code'] == 'affinity'


def test_router_dead_fleet_and_no_fleet():
    fleets = _two_fleets()
    router = RegionRouter(fleets)
    req = AnalysisRequest(nmesh=64, npart=100000, seed=1)
    ai = affinity(req, 1, 2)
    fleets[ai].server.accepting = False
    v = router.route(req)
    assert v['code'] == 'rerouted_dead'
    assert v['fleet'] == 'f%d' % (1 - ai) and v['from'] == 'f%d' % ai
    fleets[1 - ai].server.accepting = False
    v = router.route(req)
    assert v['code'] == 'no_fleet' and v['fleets'] == 2


def test_router_catalog_home_stickiness(tmp_path):
    path = str(tmp_path / 'survey.bin')
    np.arange(12, dtype='f4').tofile(path)
    ref = {'path': path, 'format': 'binary',
           'columns': {'Position': 'Position'},
           'options': {'dtype': [('Position', ('f4', 3))]}}
    fleets = _two_fleets()
    router = RegionRouter(fleets, spill_depth=2)
    req = AnalysisRequest(nmesh=32, data_ref=ref)
    home = router.route(req)['fleet']
    # later data_ref requests follow the resident catalog even when
    # the home fleet is the deeper one (locality beats a re-ingest)
    router.get(home).server.queued = 50
    v = router.route(AnalysisRequest(nmesh=32, data_ref=ref))
    assert v == {'code': 'catalog_home', 'fleet': home}
    # a dead home falls back to hash placement (and re-homes)
    router.get(home).server.accepting = False
    v = router.route(AnalysisRequest(nmesh=32, data_ref=ref))
    assert v['code'] != 'catalog_home'
    assert v['fleet'] != home


# ---------------------------------------------------------------------------
# the region front door: memoization, followers, the verified stamp

def test_region_cache_hit_and_singleflight_follower(tmp_path):
    server = _FakeServer()
    region = Region([('a', server)],
                    result_cache=ResultCache(str(tmp_path)))
    req = AnalysisRequest(nmesh=32, npart=1000, seed=3,
                          request_id='lead')
    r1 = region.wait(region.submit(req), timeout=10)
    assert r1.ok and len(server.submitted) == 1
    # sequential repeat: a genuine disk hit, zero fleet submissions
    twin = AnalysisRequest(nmesh=32, npart=1000, seed=3,
                           request_id='repeat', priority=2)
    r2 = region.wait(region.submit(twin), timeout=10)
    assert r2.ok and len(server.submitted) == 1
    assert r2.events[0]['kind'] == 'result_cache'
    assert np.array_equal(np.asarray(r2.y), np.asarray(r1.y))
    summary = region.summary()
    assert summary['result_cache']['hits'] == 1
    assert summary['routed']['result_cache'] == 1
    # concurrent twins: followers ride the leader's single execution
    lead = region.submit(AnalysisRequest(nmesh=32, npart=1000,
                                         seed=77, request_id='c0'))
    follow = [region.submit(AnalysisRequest(nmesh=32, npart=1000,
                                            seed=77,
                                            request_id='c%d' % i))
              for i in (1, 2)]
    for t in [lead] + follow:
        assert region.wait(t, timeout=10).ok
    assert len(server.submitted) == 2, 'followers must not resubmit'
    summary = region.summary()
    region.shutdown()
    assert summary['routed']['follower'] == 2
    assert summary['lost'] == 0
    assert np.array_equal(np.asarray(region.results['c1'].y),
                          np.asarray(region.results['c0'].y))


def test_region_verified_stamp_contract(tmp_path):
    """verified=True on a hit means — and may ONLY mean — the
    committed execution was shadow-verified."""
    server = _FakeServer(verify=True)
    region = Region([('a', server)],
                    result_cache=ResultCache(str(tmp_path)))
    req = AnalysisRequest(nmesh=32, npart=1000, seed=1)
    assert region.wait(region.submit(req), timeout=10).ok
    hit = region.wait(region.submit(
        AnalysisRequest(nmesh=32, npart=1000, seed=1)), timeout=10)
    region.shutdown()
    assert hit.events[0] == {'kind': 'result_cache',
                             'digest': hit.events[0]['digest'],
                             'verified': True}
    # an unverified execution commits verified=False and serves as
    # such
    server2 = _FakeServer(verify=False)
    region2 = Region([('b', server2)],
                     result_cache=ResultCache(str(tmp_path / 'u')))
    assert region2.wait(region2.submit(
        AnalysisRequest(nmesh=32, npart=1000, seed=2)), timeout=10).ok
    hit2 = region2.wait(region2.submit(
        AnalysisRequest(nmesh=32, npart=1000, seed=2)), timeout=10)
    summary = region2.summary()
    region2.shutdown()
    assert hit2.events[0]['verified'] is False
    assert summary['result_cache']['unverified_as_verified'] == 0


def test_region_stamp_corruption_is_ledgered(tmp_path):
    """The chaos rule region.result.stamp flips an unverified hit's
    stamp to verified; the region must LEDGER the forgery
    (unverified_as_verified — the doctor's FAIL number), proving CI
    can catch a stamp-integrity bug."""
    server = _FakeServer(verify=False)
    with nbodykit_tpu.set_options(
            faults='region.result.stamp@1:corrupt'):
        region = Region([('a', server)],
                        result_cache=ResultCache(str(tmp_path)))
        assert region.wait(region.submit(
            AnalysisRequest(nmesh=32, npart=1000, seed=4)),
            timeout=10).ok
        hit = region.wait(region.submit(
            AnalysisRequest(nmesh=32, npart=1000, seed=4)),
            timeout=10)
        summary = region.summary()
        region.shutdown()
    assert hit.events[0]['verified'] is True         # the forgery
    assert summary['result_cache']['unverified_as_verified'] == 1


# ---------------------------------------------------------------------------
# elastic grow

def test_grow_repartitions_and_stamps_manifest(tmp_path):
    store = FleetCheckpointStore(str(tmp_path))
    full = np.arange(24.0).reshape(6, 4)
    for r, piece in enumerate(np.array_split(full, 2, axis=0)):
        store.save_shard('sim', 1, r, 2, {'rep': 7},
                         arrays={'field': piece})
    store.seal('sim', 1, nranks=2, rank=0)
    man0 = store.latest_manifest('sim')
    assert 'reformed_from' not in man0   # a plain seal is unstamped
    info = grow(store, 'sim', 3)
    assert info['reformed_from'] == 2 and info['reformed_to'] == 3
    man = store.latest_manifest('sim')
    assert man['nranks'] == 3
    assert man['reformed_from'] == 2 and man['reformed_to'] == 3
    # the grown shards reassemble to the exact original field, and
    # the carried user state survives
    shards = [store.store.load(store.shard_key('sim', man['seq'], r))
              for r in range(3)]
    assert all(s is not None for s in shards)
    assert np.array_equal(
        reassemble([arrays for _, arrays in shards])['field'], full)
    assert shards[0][0]['user'] == {'rep': 7}
    # the reformed stamps are hash-covered: forging one voids the
    # manifest
    path = store._manifest_path('sim', man['seq'])
    forged = json.load(open(path))
    forged['reformed_from'] = 99
    with open(path, 'w') as f:
        json.dump(forged, f)
    assert store.manifest('sim', man['seq']) is None
    # growing from nothing is a first seal, not a re-formation
    with pytest.raises(RuntimeError):
        grow(store, 'never-sealed', 4)


def test_region_join_seals_membership(tmp_path):
    store = FleetCheckpointStore(str(tmp_path))
    region = Region([('f0', _FakeServer()), ('f1', _FakeServer())],
                    checkpoint=store)
    info = region.join(_FakeServer(), name='f2')
    summary = region.summary()
    region.shutdown()
    assert info['reformed_from'] == 2 and info['reformed_to'] == 3
    assert summary['fleet_count'] == 3
    assert summary['elastic']['joins'] == 1
    man = store.latest_manifest('region')
    assert man['nranks'] == 3
    assert man['reformed_from'] == 2 and man['reformed_to'] == 3
    shard = store.store.load(store.shard_key('region', man['seq'], 0))
    assert shard[0]['user']['fleets'] == ['f0', 'f1', 'f2']
    # a second join stamps 3 -> 4 at the next seq
    assert seal_join(store, 'region', {'fleets': 4 * ['x']},
                     new_nranks=4,
                     reformed_from=3)['reformed_to'] == 4
    assert store.latest_manifest('region')['reformed_from'] == 3


def test_region_routes_around_dead_fleet_after_join():
    a, b = _FakeServer(), _FakeServer()
    region = Region([('f0', a), ('f1', b)])
    a.accepting = False
    t = region.submit(AnalysisRequest(nmesh=32, npart=1000, seed=9))
    res = region.wait(t, timeout=10)
    summary = region.summary()
    region.shutdown()
    assert res.ok
    assert b.submitted and not a.submitted
    assert summary['lost'] == 0


# ---------------------------------------------------------------------------
# the data_steal_grace_s satellite

def test_data_steal_grace_resolution(monkeypatch):
    from nbodykit_tpu.serve.server import _resolve_data_steal_grace
    monkeypatch.delenv('NBKIT_DATA_STEAL_GRACE_S', raising=False)
    assert _resolve_data_steal_grace('auto') \
        == AnalysisServer.DATA_STEAL_GRACE_S
    assert _resolve_data_steal_grace(0.25) == 0.25
    assert _resolve_data_steal_grace(0) == 0.0
    assert _resolve_data_steal_grace('2.5') == 2.5
    monkeypatch.setenv('NBKIT_DATA_STEAL_GRACE_S', '3.5')
    assert _resolve_data_steal_grace('auto') == 3.5
    assert _resolve_data_steal_grace(0.5) == 0.5   # option wins
    for bad in (-1.0, float('nan'), float('inf'), 'soon'):
        with pytest.raises(ValueError):
            _resolve_data_steal_grace(bad)
    monkeypatch.setenv('NBKIT_DATA_STEAL_GRACE_S', 'nonsense')
    with pytest.raises(ValueError):
        _resolve_data_steal_grace('auto')
    with pytest.raises(KeyError):
        nbodykit_tpu.set_options(data_steal_grace=1.0)  # typo'd name


def test_server_resolves_data_steal_grace_option():
    with nbodykit_tpu.set_options(data_steal_grace_s=0.125):
        with use_mesh(cpu_mesh(1)):
            srv = AnalysisServer(per_task=1)
    try:
        assert srv.data_steal_grace_s == 0.125
        assert srv.load()['accepting'] is True
    finally:
        srv.shutdown()
    assert srv.load()['accepting'] is False


# ---------------------------------------------------------------------------
# trace synthesis

def test_generate_region_trace_deterministic_with_repeats():
    a = generate_region_trace(120, seed=5, join_at=0.5)
    b = generate_region_trace(120, seed=5, join_at=0.5)
    assert len(a) == 121        # 120 items + the join event
    assert [sorted(i) for i in a] == [sorted(i) for i in b]
    assert sum(1 for i, x in enumerate(a) if 'event' in x) == 1
    assert a[60] == {'event': 'join'}
    reqs = [x for x in a if 'request' in x]
    ids = [x['request'].request_id for x in reqs]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    for x, y in zip(reqs, b[:60] + b[61:]):
        assert x['tenant'] == y['tenant']
        assert x['request'].to_dict() == y['request'].to_dict()
    # per-tenant repeat slices: some request re-issues an exact
    # earlier realization of the SAME tenant
    seen = {}
    repeats = 0
    for x in reqs:
        key = (x['request'].algorithm, x['request'].nmesh,
               x['request'].npart, x['request'].seed)
        repeats += key in seen and seen[key] == x['tenant']
        seen.setdefault(key, x['tenant'])
    assert repeats > 0
    # the bulk tenant self-declares priority 2 on every request
    bulk = [x for x in reqs if x['tenant'] == 'bulk-sweep']
    assert bulk and all(x['request'].priority == 2 for x in bulk)
    tenants = {x['tenant'] for x in reqs}
    assert tenants <= {'interactive-a', 'interactive-b',
                       'bulk-sweep'} and len(tenants) == 3


def test_replay_region_fires_join_event(tmp_path):
    from nbodykit_tpu.serve import replay_region
    region = Region([('f0', _FakeServer())],
                    result_cache=ResultCache(str(tmp_path)))
    trace = generate_region_trace(20, seed=2, deadline_s=30.0,
                                  join_at=0.4)
    joined = []
    tickets = replay_region(
        region, trace,
        on_join=lambda reg: joined.append(reg.join(_FakeServer())))
    summary = region.summary()
    region.shutdown()
    assert len(joined) == 1
    assert joined[0] == {'fleet': 'fleet-1', 'reformed_from': 1,
                         'reformed_to': 2,
                         'rehomed': joined[0]['rehomed']}
    assert len(tickets) == 20
    assert summary['lost'] == 0
    assert summary['resolved'] == 20
    assert summary['elastic']['joins'] == 1
    # the repeat slice produced real memoization traffic
    assert summary['result_cache']['hits'] \
        + summary['routed'].get('follower', 0) > 0


# ---------------------------------------------------------------------------
# one real end-to-end pass (everything above uses fakes)

def test_region_e2e_real_server_bit_identical_hit():
    with use_mesh(cpu_mesh(1)):
        srv = AnalysisServer(per_task=1, max_queue=8)
    import tempfile
    region = Region([('a', srv)],
                    result_cache=ResultCache(tempfile.mkdtemp()))
    req = AnalysisRequest(nmesh=32, npart=2000, seed=11,
                          deadline_s=600.0, request_id='real-0')
    r1 = region.wait(region.submit(req), timeout=300)
    assert r1 is not None and r1.ok, r1
    r2 = region.wait(region.submit(
        AnalysisRequest(nmesh=32, npart=2000, seed=11,
                        deadline_s=600.0, request_id='real-1')),
        timeout=60)
    summary = region.summary()
    region.shutdown()
    assert r2.ok and r2.events[0]['kind'] == 'result_cache'
    # the memoized spectrum is bit-identical to the computed one
    assert np.asarray(r2.y).tobytes() == np.asarray(r1.y).tobytes()
    assert np.asarray(r2.x).tobytes() == np.asarray(r1.x).tobytes()
    assert summary['result_cache']['hits'] == 1
    assert summary['lost'] == 0


# ---------------------------------------------------------------------------
# regress / doctor posture

def test_region_summary_reads_committed_round(tmp_path):
    from nbodykit_tpu.diagnostics.regress import (build_history,
                                                  region_summary,
                                                  render_regress)
    rec = {'metric': 'regiontrace_n40', 'unit': 's', 'value': 1.5,
           'requests': 40, 'fleets': 2, 'fleet_count': 3,
           'completed': 40, 'rejected': 0, 'evicted': 0, 'lost': 0,
           'result_hits': 9, 'hit_rate': 0.18, 'cache_corrupt': 0,
           'cache_bit_identical': True, 'unverified_as_verified': 0,
           'spills': 6, 'joins': 1, 'reformed_from': 2,
           'reformed_to': 3, 'throttled': 2, 'starved': 0,
           'interactive_p50_s': 1.1, 'interactive_p99_s': 1.5,
           'measured_at': '2026-08-06T00:00:00Z'}
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'cmd': 'bench --region-trace 40 2', 'parsed': rec}))
    reg = region_summary(str(tmp_path))
    assert reg is not None and reg['round'] == 'BENCH_r01.json'
    assert reg['lost'] == 0 and reg['result_hits'] == 9
    assert reg['reformed_from'] == 2 and reg['reformed_to'] == 3
    assert reg['unverified_as_verified'] == 0
    history = build_history(str(tmp_path), write=False)
    assert history['region']['metric'] == 'regiontrace_n40'
    line = next(l for l in render_regress(history).splitlines()
                if l.strip().startswith('region:'))
    assert '40 req over 3 fleet(s)' in line
    assert 'fleet re-formed 2 -> 3' in line
    assert '0 lost' in line


def test_region_summary_none_without_round(tmp_path):
    from nbodykit_tpu.diagnostics.regress import region_summary
    assert region_summary(str(tmp_path)) is None
