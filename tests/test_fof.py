"""FOF tests: brute-force oracle on small N, known cluster layouts,
halo property reductions (reference analog:
algorithms/tests/test_fof.py)."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import ArrayCatalog, UniformCatalog
from nbodykit_tpu.algorithms.fof import FOF


def brute_force_fof(pos, ll, box):
    """O(N^2) union-find oracle with periodic distances."""
    N = len(pos)
    parent = np.arange(N)

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i in range(N):
        for j in range(i + 1, N):
            d = pos[i] - pos[j]
            d -= np.round(d / box) * box
            if (d ** 2).sum() <= ll * ll:
                ri, rj = find(i), find(j)
                if ri != rj:
                    parent[ri] = rj
    return np.array([find(i) for i in range(N)])


def same_partition(a, b):
    """Two labelings describe the same partition?"""
    m = {}
    for x, y in zip(a, b):
        if x in m and m[x] != y:
            return False
        m[x] = y
    m = {}
    for x, y in zip(b, a):
        if x in m and m[x] != y:
            return False
        m[x] = y
    return True


def test_fof_matches_brute_force():
    rng = np.random.RandomState(0)
    pos = rng.uniform(0, 50.0, size=(300, 3))
    cat = ArrayCatalog({'Position': pos}, BoxSize=50.0)
    ll_abs = 3.0
    fof = FOF(cat, linking_length=ll_abs, nmin=1, absolute=True)
    want = brute_force_fof(pos, ll_abs, 50.0)
    got = np.asarray(fof.labels)
    assert same_partition(got, want)


def test_fof_two_well_separated_clusters():
    rng = np.random.RandomState(1)
    c1 = rng.normal(20, 0.5, size=(40, 3))
    c2 = rng.normal(70, 0.5, size=(25, 3))
    lone = np.array([[45.0, 45.0, 45.0]])
    pos = np.concatenate([c1, c2, lone])
    cat = ArrayCatalog({'Position': pos}, BoxSize=100.0)
    fof = FOF(cat, linking_length=3.0, nmin=5, absolute=True)
    labels = np.asarray(fof.labels)
    # two halos, ordered by size: cluster1 -> 1, cluster2 -> 2, lone -> 0
    assert set(labels[:40]) == {1}
    assert set(labels[40:65]) == {2}
    assert labels[65] == 0


def test_fof_periodic_wrap():
    # a cluster straddling the periodic boundary must be one group
    pos = np.array([[0.5, 10.0, 10.0],
                    [99.5, 10.0, 10.0],
                    [1.5, 10.0, 10.0],
                    [98.5, 10.0, 10.0]])
    cat = ArrayCatalog({'Position': pos}, BoxSize=100.0)
    fof = FOF(cat, linking_length=1.6, nmin=2, absolute=True)
    labels = np.asarray(fof.labels)
    assert len(set(labels)) == 1 and labels[0] == 1


def test_fof_features_and_com():
    rng = np.random.RandomState(2)
    center = np.array([10.0, 20.0, 30.0])
    cluster = center + rng.normal(0, 0.3, size=(50, 3))
    vel = np.ones((50, 3)) * 7.0
    cat = ArrayCatalog({'Position': cluster, 'Velocity': vel},
                       BoxSize=100.0)
    fof = FOF(cat, linking_length=2.0, nmin=5, absolute=True)
    halos = fof.find_features()
    assert halos['Length'][1] == 50
    np.testing.assert_allclose(np.asarray(halos['CMPosition'][1]),
                               cluster.mean(axis=0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(halos['CMVelocity'][1]), 7.0,
                               rtol=1e-6)


def test_fof_com_periodic():
    # center of mass of a boundary-straddling group is near the seam,
    # not the box center
    pos = np.array([[99.0, 5.0, 5.0], [1.0, 5.0, 5.0]])
    cat = ArrayCatalog({'Position': pos}, BoxSize=100.0)
    fof = FOF(cat, linking_length=3.0, nmin=2, absolute=True)
    halos = fof.find_features()
    cm = np.asarray(halos['CMPosition'][1])
    assert cm[0] > 99.0 or cm[0] < 1.0


def test_fof_to_halos():
    from nbodykit_tpu.cosmology import Planck15
    rng = np.random.RandomState(3)
    clusters = []
    for c in [15.0, 45.0, 80.0]:
        clusters.append(rng.normal(c, 0.4, size=(30, 3)))
    pos = np.concatenate(clusters)
    vel = rng.normal(0, 100.0, size=pos.shape)
    cat = ArrayCatalog({'Position': pos, 'Velocity': vel},
                       BoxSize=100.0)
    fof = FOF(cat, linking_length=2.0, nmin=10, absolute=True)
    halos = fof.to_halos(particle_mass=1e12, cosmo=Planck15, redshift=0.)
    assert halos.csize == 3
    np.testing.assert_allclose(np.asarray(halos['Mass']), 30 * 1e12)
    assert np.all(np.asarray(halos['Radius']) > 0)
    assert np.all(np.asarray(halos['Concentration']) > 1)


def test_fof_mean_separation_units():
    cat = UniformCatalog(nbar=1e-3, BoxSize=64.0, seed=9)
    fof = FOF(cat, linking_length=0.2, nmin=5)
    labels = np.asarray(fof.labels)
    assert labels.min() >= 0
    # most particles are isolated at this density
    assert (labels == 0).mean() > 0.5
