"""Direct unit tests for the _jax_compat shims.

Until now the backfills (jax.shard_map on 0.4.x, pvary/pcast/typeof,
set_cpu_devices, partitionable threefry) were exercised only
indirectly by whichever suite happened to hit them — a lint-driven
refactor could silently break the jax-0.4.37 path.  These pin the
contract explicitly on whatever jax the container bakes.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from nbodykit_tpu import _jax_compat


def test_apply_is_idempotent():
    before = (jax.shard_map, jax.lax.pvary, jax.lax.pcast, jax.typeof)
    _jax_compat.apply()
    _jax_compat.apply()
    after = (jax.shard_map, jax.lax.pvary, jax.lax.pcast, jax.typeof)
    assert before == after


def test_modern_names_exist():
    # the whole codebase uses ONE spelling; these must exist whether
    # native or backfilled
    assert callable(jax.shard_map)
    assert callable(jax.lax.pvary)
    assert callable(jax.lax.pcast)
    assert callable(jax.typeof)


def test_shard_map_psum_roundtrip(cpu8):
    # the backfilled (or native) jax.shard_map must run a real
    # collective: replicated sum over the 8-device mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    ndev = cpu8.shape[AXIS]
    x = jax.device_put(np.arange(ndev, dtype='f4'),
                       NamedSharding(cpu8, P(AXIS)))
    total = jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), AXIS), mesh=cpu8,
        in_specs=P(AXIS), out_specs=P()))(x)
    assert float(total) == float(np.arange(ndev).sum())


def test_shard_map_while_loop_carry(cpu8):
    # the reason the 0.4.x shim disables check_rep: while_loop carries
    # inside shard_map (the sort/paint kernels depend on this)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    ndev = cpu8.shape[AXIS]
    x = jax.device_put(np.ones(ndev, 'f4'),
                       NamedSharding(cpu8, P(AXIS)))

    def body(v):
        def step(state):
            i, acc = state
            return i + 1, acc + jnp.sum(v)
        _, acc = jax.lax.while_loop(lambda s: s[0] < 3, step,
                                    (jnp.int32(0), jnp.float32(0)))
        return jax.lax.psum(acc, AXIS)

    total = jax.jit(jax.shard_map(body, mesh=cpu8, in_specs=P(AXIS),
                                  out_specs=P()))(x)
    assert float(total) == 3.0 * ndev


def test_typeof_returns_aval():
    aval = jax.typeof(jnp.zeros((2, 3), jnp.float32))
    assert tuple(aval.shape) == (2, 3)
    assert aval.dtype == jnp.float32


def test_pvary_pcast_identity_shim(monkeypatch):
    # force the backfill path (even on modern jax) and pin the
    # identity contract the 0.4.x type system expects
    monkeypatch.delattr(jax.lax, 'pvary', raising=False)
    monkeypatch.delattr(jax.lax, 'pcast', raising=False)
    _jax_compat.apply()
    x = jnp.arange(3)
    assert jax.lax.pvary(x, axis_name='dev') is x
    assert jax.lax.pcast(x, axis_name='dev', to='varying') is x
    # monkeypatch restores the originals; re-apply puts the world back
    # for whatever jax version this is


def test_threefry_partitionable_enabled():
    # rng.py's device-count-invariant draw contract depends on it
    assert jax.config.jax_threefry_partitionable


def test_set_cpu_devices_env_fallback(monkeypatch):
    # simulate the 0.4.x surface: no jax_num_cpu_devices config ->
    # the XLA_FLAGS fallback must be used and reported as False
    class _NoConfig:
        def update(self, name, value):
            raise AttributeError(name)

    monkeypatch.setattr(_jax_compat.jax, 'config', _NoConfig())
    monkeypatch.setenv('XLA_FLAGS', '')
    assert _jax_compat.set_cpu_devices(3) is False
    assert '--xla_force_host_platform_device_count=3' in \
        os.environ['XLA_FLAGS']
    # idempotent: a second call must not duplicate the flag
    assert _jax_compat.set_cpu_devices(3) is False
    assert os.environ['XLA_FLAGS'].count(
        'xla_force_host_platform_device_count') == 1


def test_set_cpu_devices_config_path(monkeypatch):
    # simulate the modern surface: the config update is accepted
    calls = []

    class _Config:
        def update(self, name, value):
            calls.append((name, value))

    monkeypatch.setattr(_jax_compat.jax, 'config', _Config())
    assert _jax_compat.set_cpu_devices(5) is True
    assert calls == [('jax_num_cpu_devices', 5)]
    # NOTE the check_rep=False default the 0.4.x shard_map shim applies
    # is covered functionally by test_shard_map_while_loop_carry —
    # that program fails outright on 0.4.x with check_rep enabled
