"""Cosmology tests: astropy cross-checks for the background (the
reference's own oracle style, cosmology/tests/test_cosmology.py),
physical limits for the power spectra, FFTLog round-trips.
"""

import numpy as np
import pytest

from nbodykit_tpu.cosmology import (Cosmology, Planck15, LinearPower,
                                    HalofitPower, ZeldovichPower,
                                    CorrelationFunction, pk_to_xi,
                                    xi_to_pk)


def test_background_vs_astropy():
    ap = pytest.importorskip("astropy.cosmology")
    c = Planck15
    a = c.to_astropy()
    z = np.array([0.0, 0.5, 1.0, 2.0, 5.0])
    np.testing.assert_allclose(c.efunc(z), a.efunc(z), rtol=2e-3)
    # comoving distance in Mpc/h vs astropy Mpc
    ours = c.comoving_distance(z[1:])
    theirs = a.comoving_distance(z[1:]).value * c.h
    np.testing.assert_allclose(ours, theirs, rtol=3e-3)


def test_growth_matter_dominated_limit():
    # EdS: D = a exactly, f = 1
    c = Cosmology(h=0.7, Omega0_b=0.05, Omega0_cdm=0.95 - 1e-5,
                  N_ur=0.0, T0_cmb=1e-3)  # kill radiation
    z = np.array([0.0, 1.0, 3.0])
    D = c.scale_independent_growth_factor(z)
    np.testing.assert_allclose(D, 1.0 / (1 + z), rtol=1e-3)
    f = c.scale_independent_growth_rate(z)
    np.testing.assert_allclose(f, 1.0, rtol=1e-3)


def test_growth_rate_approximation():
    # f(z) ~ Omega_m(z)^0.55 for LCDM
    c = Planck15
    z = np.array([0.0, 0.5, 1.0])
    f = c.scale_independent_growth_rate(z)
    approx = c.Omega_m(z) ** 0.55
    np.testing.assert_allclose(f, approx, rtol=0.02)


def test_linear_power_sigma8_scaling():
    P = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    s8 = P.sigma8
    assert 0.5 < s8 < 1.2  # sane amplitude from A_s
    P.sigma8 = 0.8
    np.testing.assert_allclose(P.sigma8, 0.8, rtol=1e-10)
    # P scales as sigma8^2
    k = np.logspace(-2, 0, 10)
    p1 = P(k)
    P.sigma8 = 0.4
    np.testing.assert_allclose(P(k), p1 / 4, rtol=1e-10)


def test_linear_power_redshift_growth():
    P0 = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    P1 = LinearPower(Planck15, 1.0, transfer='EisensteinHu')
    D = Planck15.scale_independent_growth_factor(1.0)
    k = np.logspace(-2, 0, 8)
    np.testing.assert_allclose(P1(k) / P0(k), D ** 2, rtol=1e-4)


def test_transfer_normalization():
    from nbodykit_tpu.cosmology.power.transfers import (
        EisensteinHu, NoWiggleEisensteinHu)
    for cls in [EisensteinHu, NoWiggleEisensteinHu]:
        T = cls(Planck15)
        k = np.array([1e-7, 1e-6])
        np.testing.assert_allclose(T(k), 1.0, rtol=1e-3)
        # monotonically decreasing envelope at high k
        assert T(np.array([10.0]))[0] < 1e-2


def test_wiggle_vs_nowiggle():
    # the wiggly EH oscillates around the no-wiggle form within ~10%
    Pw = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    Pnw = LinearPower(Planck15, 0.0, transfer='NoWiggleEisensteinHu')
    Pnw.sigma8 = Pw.sigma8
    k = np.logspace(-2, 0, 256)
    ratio = Pw(k) / Pnw(k)
    assert np.all(np.abs(ratio - 1) < 0.12)
    assert np.std(ratio) > 5e-3  # wiggles exist


def test_halofit_enhances_small_scales():
    Pl = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    Pnl = HalofitPower(Planck15, 0.0, linear=Pl)
    k = np.logspace(-3, 1, 64)
    ratio = Pnl(k) / Pl(k)
    # linear on large scales
    assert abs(ratio[0] - 1) < 0.05
    # nonlinear boost at k ~ 1-10
    assert ratio[-1] > 2.0


def test_zeldovich_low_k_limit():
    Pz = ZeldovichPower(Planck15, 0.0, transfer='EisensteinHu')
    Pl = Pz.linear
    k = np.array([0.01, 0.02, 0.05])
    # ZA tracks linear to ~5% here (real BAO smearing + damping begins
    # by k ~ 0.05)
    np.testing.assert_allclose(Pz(k), Pl(k), rtol=0.07)
    # BAO damping: ZA < linear at k ~ 0.1-0.2
    k2 = np.array([0.2, 0.3])
    assert np.all(Pz(k2) < Pl(k2))


def test_pk_xi_roundtrip():
    P = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    k = np.logspace(-5, 2, 2048)
    xi = pk_to_xi(k, P(k))
    pk2 = xi_to_pk(np.logspace(-3, 3, 2048),
                   xi(np.logspace(-3, 3, 2048)))
    kt = np.logspace(-1.5, -0.5, 16)
    np.testing.assert_allclose(pk2(kt), P(kt), rtol=0.05)


def test_correlation_function_bao_peak():
    P = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    cf = CorrelationFunction(P)
    r = np.linspace(60, 140, 161)
    xi = cf(r)
    # BAO peak near ~100 Mpc/h: local max of r^2 xi in [85, 115]
    r2xi = r ** 2 * xi
    ipk = np.argmax(r2xi)
    assert 85 < r[ipk] < 115


def test_clone_and_match():
    c = Planck15
    c2 = c.clone(h=0.7)
    assert c2.h == 0.7 and c2.Omega0_b == c.Omega0_b
    c4 = c.match(Omega0_m=0.3)
    np.testing.assert_allclose(c4.Omega0_m, 0.3, rtol=1e-10)
