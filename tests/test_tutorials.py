"""Offline example-data store (reference analog:
nbodykit/tutorials/wget.py download_example_data/available_examples —
generated locally here, zero egress) and the demo halo catalog."""

import numpy as np
import pytest

from nbodykit_tpu.tutorials import (DemoHaloCatalog, available_examples,
                                    download_example_data)


def test_demo_halo_catalog():
    cat = DemoHaloCatalog()
    assert cat.size == 5000
    for col in ('Position', 'Velocity', 'Mass'):
        assert col in cat
    # reproducible
    cat2 = DemoHaloCatalog()
    np.testing.assert_array_equal(np.asarray(cat['Mass']),
                                  np.asarray(cat2['Mass']))


def test_examples_materialize_and_load(tmp_path):
    names = available_examples()
    assert len(names) >= 5
    download_example_data(names, download_dirname=str(tmp_path))

    from nbodykit_tpu.lab import (CSVCatalog, HDFCatalog, BigFileCatalog,
                                  BinaryCatalog, FITSCatalog)

    csv = CSVCatalog(str(tmp_path / 'csv-example.txt'),
                     names=['ra', 'dec', 'z', 'x', 'y', 'z_cart', 'w'])
    assert csv.size == 1024

    hdf = HDFCatalog(str(tmp_path / 'hdf-example.hdf5'), dataset='Data')
    assert hdf.size == 2048 and 'Position' in hdf

    big = BigFileCatalog(str(tmp_path / 'bigfile-example'))
    assert big.size == 2048
    np.testing.assert_array_equal(big.attrs['BoxSize'], [250.0] * 3)

    binc = BinaryCatalog(str(tmp_path / 'binary-example.bin'),
                         dtype=[('Position', ('f4', 3)),
                                ('Velocity', ('f4', 3))])
    assert binc.size == 1024

    fits = FITSCatalog(str(tmp_path / 'fits-example.fits'))
    assert fits.size == 512
    assert set(fits.columns) >= {'RA', 'DEC', 'Z'}
    assert float(np.asarray(fits['Z']).min()) >= 0.3


def test_download_errors(tmp_path):
    with pytest.raises(ValueError, match="no such example"):
        download_example_data('nope.dat')
    with pytest.raises(ValueError, match="not valid"):
        download_example_data('csv-example.txt',
                              download_dirname=str(tmp_path / 'missing'))


def test_deterministic_bytes(tmp_path):
    a, b = tmp_path / 'a', tmp_path / 'b'
    a.mkdir(), b.mkdir()
    download_example_data('binary-example.bin', str(a))
    download_example_data('binary-example.bin', str(b))
    assert (a / 'binary-example.bin').read_bytes() == \
        (b / 'binary-example.bin').read_bytes()
