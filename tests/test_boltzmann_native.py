"""Cross-checks of the native C++ Boltzmann kernel vs the Python BDF
reference path (csrc/boltzmann_kernel.cpp vs boltzmann.py)."""

import numpy as np
import pytest

from nbodykit_tpu.cosmology import boltzmann as B
from nbodykit_tpu.cosmology import _native


@pytest.fixture(scope='module')
def solver():
    bg = B.Background(h=0.67556, T0_cmb=2.7255, Omega_b=0.0482754,
                      Omega_cdm=0.263771, m_ncdm=[0.06], N_ur=2.0328)
    th = B.Thermodynamics(bg)
    return B.BoltzmannSolver(bg, th)


@pytest.fixture(scope='module')
def solver_nonu():
    bg = B.Background(h=0.7, T0_cmb=2.725, Omega_b=0.046,
                      Omega_cdm=0.24, m_ncdm=[], N_ur=3.046)
    th = B.Thermodynamics(bg)
    return B.BoltzmannSolver(bg, th)


def test_native_compiles():
    assert _native.native_available(), _native._lib_err


@pytest.mark.parametrize('k', [1e-4, 0.05, 0.6])
def test_native_matches_python(solver, k):
    lna_out = np.sort(np.log(1.0 / (1.0 + np.array([9.0, 1.0, 0.0]))))
    nat = _native.solve_mode_native(solver, k, lna_out)
    assert nat is not None
    py = solver._solve_mode_py(k, lna_out)
    for q in ('phi', 'psi', 'd_cdm', 'd_b', 't_b'):
        np.testing.assert_allclose(nat[q], py[q], rtol=2e-4,
                                   err_msg=q)
    # d_ncdm is free-streaming suppressed (tiny, f_nu-weighted in P);
    # the two integrators agree on it at the 1e-3 level
    np.testing.assert_allclose(nat['d_ncdm'], py['d_ncdm'], rtol=3e-3,
                               err_msg='d_ncdm')


def test_native_matches_python_nonu(solver_nonu):
    lna_out = np.array([0.0])
    for k in [0.01, 0.3]:
        nat = _native.solve_mode_native(solver_nonu, k, lna_out)
        py = solver_nonu._solve_mode_py(k, lna_out)
        np.testing.assert_allclose(nat['d_cdm'], py['d_cdm'],
                                   rtol=2e-4)


def test_python_fallback_flag(solver):
    """use_native=False forces the scipy path."""
    bg, th = solver.bg, solver.th
    s2 = B.BoltzmannSolver(bg, th, use_native=False)
    out = s2.solve_mode(0.05, np.array([0.0]))
    nat = solver.solve_mode(0.05, np.array([0.0]))
    np.testing.assert_allclose(out['d_cdm'], nat['d_cdm'], rtol=2e-4)
