"""Tests for nbkl v4: the NBK8xx host-concurrency engine.

Every rule gets at least one positive and one negative fixture — the
negatives matter as much as the positives, because a concurrency
linter that cries wolf gets pragma'd into silence.  The load-bearing
regression at the bottom pins the permanent zero-findings budget: the
repo's own threaded serve plane must stay NBK8xx-clean with ZERO
baselined entries, forever — concurrency findings are fixed or
explicitly pragma'd at the site, never grandfathered.

Alongside the static fixtures: 50-iteration stress loops proving the
two real shutdown races this engine's triage surfaced (the telemetry
exporter's stop-without-join, and the region replay harvester's
unbounded join on the exception path) stay fixed.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from nbodykit_tpu import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_str(src, select=('NBK8',)):
    return lint.lint_source('fixture.py', textwrap.dedent(src),
                            project_constants={'AXIS': 'dev'},
                            select=list(select))


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# NBK801: lock-order inversion

INVERSION = '''
    import threading
    A = threading.Lock()
    B = threading.Lock()
    def f():
        with A:
            with B:
                pass
    def g():
        with B:
            with A:
                pass
'''


def test_nbk801_same_module_inversion():
    fs = lint_str(INVERSION)
    # one witness per side of the inversion: the A->B path and the
    # B->A path are each reported, so the fix is visible at both ends
    assert codes(fs) == ['NBK801', 'NBK801']
    assert 'opposite order' in fs[0].message or \
        'inversion' in fs[0].message.lower()


def test_nbk801_consistent_order_is_clean():
    fs = lint_str('''
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:
                    pass
        def g():
            with A:
                with B:
                    pass
    ''')
    assert fs == []


def test_nbk801_interprocedural_across_two_modules(tmp_path):
    # the A-side of the inversion only exists through a call: outer()
    # holds A and calls inner_b() which takes B — the engine must
    # splice inner_b's acquire summary through the call site, and the
    # B->A order lives in a DIFFERENT module importing both locks
    (tmp_path / 'm1.py').write_text(textwrap.dedent('''
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def outer():
            with A:
                inner_b()
        def inner_b():
            with B:
                pass
    '''))
    (tmp_path / 'm2.py').write_text(textwrap.dedent('''
        from m1 import A, B
        def rev():
            with B:
                with A:
                    pass
    '''))
    new, grandfathered, _ = lint.run_lint([str(tmp_path)],
                                          select=['NBK8'])
    assert grandfathered == []
    got = sorted((f.code, os.path.basename(f.path)) for f in new)
    assert got == [('NBK801', 'm1.py'), ('NBK801', 'm2.py')]


# ---------------------------------------------------------------------------
# NBK802: shared mutable state from >= 2 thread roots, no common lock

def test_nbk802_two_thread_writers_without_lock():
    fs = lint_str('''
        import threading
        class S:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self.w1).start()
                threading.Thread(target=self.w2).start()
            def w1(self):
                self.n += 1
            def w2(self):
                self.n -= 1
    ''')
    assert codes(fs) == ['NBK802']
    assert 'S.n' in fs[0].message


def test_nbk802_common_lock_is_clean():
    fs = lint_str('''
        import threading
        class S:
            def __init__(self):
                self.lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self.w1).start()
                threading.Thread(target=self.w2).start()
            def w1(self):
                with self.lock:
                    self.n += 1
            def w2(self):
                with self.lock:
                    self.n -= 1
    ''')
    assert fs == []


# ---------------------------------------------------------------------------
# NBK803: blocking while holding a lock

def test_nbk803_join_and_collective_under_lock():
    fs = lint_str('''
        import threading
        import jax
        L = threading.Lock()
        def f(t):
            with L:
                t.join()
        def g(x):
            with L:
                return jax.lax.psum(x, AXIS)
    ''')
    assert codes(fs) == ['NBK803', 'NBK803']
    blob = ' '.join(f.message for f in fs)
    assert 'join()' in blob and 'collective' in blob


def test_nbk803_collective_reached_through_call_chain(tmp_path):
    # the callee's psum is not under any lock *locally* — it becomes
    # blocking-under-lock only through the caller's locked call site
    (tmp_path / 'c1.py').write_text(textwrap.dedent('''
        import threading
        import jax
        L = threading.Lock()
        def reduce_it(x):
            return jax.lax.psum(x, 'dev')
        def f(x):
            with L:
                return reduce_it(x)
    '''))
    new, _, _ = lint.run_lint([str(tmp_path)], select=['NBK8'])
    assert codes(new) == ['NBK803']
    assert 'collective' in new[0].message


def test_nbk803_timeout_or_unlocked_is_clean():
    fs = lint_str('''
        import threading
        L = threading.Lock()
        def f(t):
            with L:
                t.join(timeout=1.0)
            t.join()
    ''')
    assert fs == []


# ---------------------------------------------------------------------------
# NBK804: acquire() not released on the exception path

def test_nbk804_bare_acquire_without_try_finally():
    fs = lint_str('''
        import threading
        L = threading.Lock()
        def f():
            L.acquire()
            g()
            L.release()
        def g():
            pass
    ''')
    assert codes(fs) == ['NBK804']


def test_nbk804_with_statement_is_clean():
    fs = lint_str('''
        import threading
        L = threading.Lock()
        def f():
            with L:
                pass
    ''')
    assert fs == []


def test_nbk804_try_finally_release_is_clean():
    fs = lint_str('''
        import threading
        L = threading.Lock()
        def f():
            L.acquire()
            try:
                pass
            finally:
                L.release()
    ''')
    assert fs == []


# ---------------------------------------------------------------------------
# NBK805: thread spawn that drops the trace context

def test_nbk805_spawn_reaching_span_without_scope():
    fs = lint_str('''
        import threading
        from nbodykit_tpu.diagnostics import span
        def work():
            with span('x'):
                pass
        def main():
            threading.Thread(target=work).start()
    ''')
    assert codes(fs) == ['NBK805']
    assert 'trace_scope' in fs[0].hint or 'trace_scope' in fs[0].message


def test_nbk805_trace_scope_in_target_is_clean():
    fs = lint_str('''
        import threading
        from nbodykit_tpu.diagnostics import span, trace_scope
        def work():
            with trace_scope(None):
                with span('x'):
                    pass
        def main():
            threading.Thread(target=work).start()
    ''')
    assert fs == []


# ---------------------------------------------------------------------------
# the seeded inversion through BOTH gates: the CLI subprocess and the
# programmatic pytest gate

def test_cli_subprocess_detects_seeded_inversion(tmp_path):
    fixture = tmp_path / 'seeded.py'
    fixture.write_text(textwrap.dedent(INVERSION))
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--select',
         'NBK8', str(fixture)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert 'NBK801' in proc.stdout
    # baseline round-trip: grandfathering the seeded finding makes
    # the exit clean again (the mechanism the repo deliberately does
    # NOT use for NBK8xx — see the zero-budget test below)
    base = tmp_path / 'base.json'
    wb = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--select',
         'NBK8', '--write-baseline', str(base), str(fixture)],
        cwd=REPO, capture_output=True, text=True)
    assert wb.returncode == 0, wb.stdout + wb.stderr
    again = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--select',
         'NBK8', '--baseline', str(base), str(fixture)],
        cwd=REPO, capture_output=True, text=True)
    assert again.returncode == 0, again.stdout + again.stderr


def test_pytest_gate_detects_seeded_inversion(tmp_path):
    (tmp_path / 'seeded.py').write_text(textwrap.dedent(INVERSION))
    new, _, _ = lint.run_lint([str(tmp_path)], select=['NBK8'])
    assert 'NBK801' in codes(new)


# ---------------------------------------------------------------------------
# the reports

def test_lock_report_rows_and_rendering(tmp_path):
    (tmp_path / 'svc.py').write_text(textwrap.dedent('''
        import threading
        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                threading.Thread(target=self.worker,
                                 name='svc-worker').start()
            def worker(self):
                with self._cv:
                    self._cv.notify_all()
            def poke(self):
                with self._lock:
                    pass
    '''))
    project, parse_findings = lint.build_project([str(tmp_path)])
    assert parse_findings == []
    rows = lint.lock_report(project)
    assert len(rows) == 1
    row = rows[0]
    # the Condition collapses onto the lock it wraps — one identity,
    # with the alias on record
    assert row['lock'].endswith('svc.Server._lock')
    assert row['kind'] == 'lock'
    assert [a.endswith('svc.Server._cv') for a in row['aliases']] \
        == [True]
    assert 'thread:svc-worker' in row['threads']
    assert 'main' in row['threads']
    assert row['acquire_sites'] == 2
    text = lint.render_lock_report(rows)
    assert 'svc.Server._lock' in text
    assert 'aliased by' in text
    assert 'thread:svc-worker' in text


def test_threads_report_rows(tmp_path):
    (tmp_path / 'svc.py').write_text(textwrap.dedent('''
        import threading
        def helper():
            pass
        def worker():
            helper()
        def main():
            threading.Thread(target=worker,
                             name='bg-worker').start()
    '''))
    project, _ = lint.build_project([str(tmp_path)])
    rows = lint.threads_report(project)
    assert [r['root'] for r in rows] == ['thread:bg-worker']
    assert rows[0]['target'] == 'worker'
    # reach is transitive: the root covers the helper too
    assert set(rows[0]['reaches']) == {'worker', 'helper'}
    text = lint.render_threads_report(rows)
    assert 'thread:bg-worker' in text and 'worker()' in text


def test_cli_lock_report_runs_on_repo_tree(tmp_path, capsys):
    # the acceptance bar: every lock in the threaded serve plane
    # shows up with its acquiring threads
    rows = lint.run_lock_report([os.path.join(REPO, 'nbodykit_tpu')])
    out = capsys.readouterr().out
    names = {r['lock'] for r in rows}
    for expected in ('serve.server.AnalysisServer._lock',
                     'serve.region.router.Region._lock',
                     'diagnostics.trace.Tracer._wlock',
                     'resilience.faults._lock'):
        assert any(n.endswith(expected) for n in names), \
            (expected, sorted(names))
    # the serve-plane locks are touched by worker threads, not just
    # the submitting main thread
    region = [r for r in rows
              if r['lock'].endswith('region.router.Region._lock')][0]
    assert any(t.startswith('thread:') for t in region['threads'])
    assert 'host-concurrency lock report' in out


# ---------------------------------------------------------------------------
# the permanent zero-findings budget

def test_repo_tree_nbk8_budget_is_zero_forever():
    """The threaded serve plane stays NBK8xx-clean with ZERO baselined
    entries: a concurrency finding is fixed or pragma'd at the site
    with its justification, never grandfathered into the baseline."""
    baseline = os.path.join(REPO, 'lint_baseline.json')
    new, grandfathered, _ = lint.run_lint(
        lint.default_targets(REPO), baseline_path=baseline,
        select=['NBK8'])
    assert new == [], lint.render_findings(new)
    assert grandfathered == []
    doc = json.load(open(baseline))
    nbk8 = [e for e in doc.get('findings', ())
            if str(e.get('code', '')).startswith('NBK8')]
    assert nbk8 == []


def test_stats_has_host_concurrency_family_axis():
    # regress.py records family_stats into BENCH_HISTORY.json; the
    # NBK8 axis must exist (zeroed) even with no findings, so the
    # history gains the column the smoke gate reads
    from nbodykit_tpu.lint.report import FAMILIES, family_stats
    assert FAMILIES.get('NBK8') == 'host-concurrency'
    fams = family_stats([], [])
    assert fams['NBK8'] == {'new': 0, 'baselined': 0}


def test_explain_covers_all_five_codes():
    from nbodykit_tpu.lint.explain import EXAMPLES
    from nbodykit_tpu.lint.rules import RULES
    for code in ('NBK801', 'NBK802', 'NBK803', 'NBK804', 'NBK805'):
        assert code in RULES
        bad, good = EXAMPLES[code]
        assert bad.strip() and good.strip()


def test_pragma_suppresses_nbk8(tmp_path):
    fs = lint_str('''
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:  # nbkl: disable=NBK801 -- fixture
                    pass
        def g():
            with B:
                with A:  # nbkl: disable=NBK801 -- fixture
                    pass
    ''')
    assert fs == []


# ---------------------------------------------------------------------------
# doctor cross-link #3: the concurrency verdict line

def test_doctor_concurrency_ok_line(tmp_path, capsys):
    import shutil
    root = str(tmp_path)
    os.symlink(os.path.join(REPO, 'nbodykit_tpu'),
               os.path.join(root, 'nbodykit_tpu'))
    shutil.copy(os.path.join(REPO, 'lint_baseline.json'),
                os.path.join(root, 'lint_baseline.json'))
    from nbodykit_tpu.diagnostics import REGISTRY
    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    try:
        rc = run_doctor(trace=None, root=root)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert 'concurrency  OK: 0 open NBK8xx findings' in out
    finally:
        REGISTRY.reset()


def test_doctor_concurrency_warn_line_on_open_finding(tmp_path,
                                                      capsys):
    # a root whose package is just the seeded inversion: the doctor
    # must print the static finding on its own concurrency line, next
    # to (absent here) runtime wedge evidence
    root = str(tmp_path)
    pkg = os.path.join(root, 'nbodykit_tpu')
    os.makedirs(pkg)
    with open(os.path.join(pkg, 'seeded.py'), 'w') as f:
        f.write(textwrap.dedent(INVERSION))
    from nbodykit_tpu.diagnostics import REGISTRY
    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    try:
        rc = run_doctor(trace=None, root=root)
        out = capsys.readouterr().out
        assert rc != 0
        assert 'lint         FAIL' in out
        assert 'concurrency  WARN' in out
        assert 'NBK801' in out and 'seeded.py' in out
    finally:
        REGISTRY.reset()


# ---------------------------------------------------------------------------
# the shutdown races this engine's triage surfaced, pinned by stress

def test_exporter_stop_joins_serving_thread_stress():
    """stop() is a contract: when it returns, the serving thread is
    gone and the port is closed.  Before the join was added, this
    loop flaked — the successor exporter raced the half-dead
    predecessor for the socket."""
    from nbodykit_tpu.diagnostics.export import TelemetryExporter
    for _ in range(50):
        exp = TelemetryExporter(port=0)
        t = exp._thread
        exp.stop()
        assert not t.is_alive()
        exp.stop()              # idempotent: double stop is a no-op


class _StubTicket(object):
    def __init__(self, request):
        self.request = request
        self.done = threading.Event()
        self.done.set()


class _StubServer(object):
    ndevices = 1
    meshes = [None]

    def load(self):
        return {'queued': 0, 'inflight': 0, 'accepting': True,
                'workers': 1}

    def submit(self, request):
        return _StubTicket(request)

    def wait(self, ticket, timeout=None):
        return None

    def shutdown(self, drain=True, timeout=None):
        pass


def test_region_stop_pacer_idempotent_stress():
    """shutdown() joins the pacer and is safe to call repeatedly —
    before _stop_pacer was made idempotent, a drain()+shutdown()
    sequence could double-finish held tickets or leave the pacer
    running past shutdown's return."""
    from nbodykit_tpu.serve import Region
    for _ in range(50):
        region = Region([('a', _StubServer())])
        region.shutdown()
        assert not region._pacer.is_alive()
        region.shutdown()       # second shutdown: no raise, no hang
        assert region._stop_pacer() == []


def test_replay_region_exception_path_stops_harvester_stress():
    """An exception mid-submission must propagate promptly — the old
    finally-join waited on the harvester, which waited forever on the
    wedged ticket the exception left behind."""
    from nbodykit_tpu.serve.synth import replay_region

    class _WedgedTicket(object):
        def __init__(self):
            self.done = threading.Event()   # never set: wedged

    class _WedgedRegion(object):
        def submit(self, request, tenant='default'):
            return _WedgedTicket()

        def wait(self, ticket, timeout=None):
            return None

    for _ in range(50):
        def items():
            yield {'tenant': 'a', 'request': object()}
            raise RuntimeError('boom')
        with pytest.raises(RuntimeError, match='boom'):
            replay_region(_WedgedRegion(), items())
        assert not [t for t in threading.enumerate()
                    if t.name == 'region-replay-harvest'
                    and t.is_alive()]
