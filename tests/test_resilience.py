"""Tests for nbodykit_tpu.resilience: checkpoint atomicity (including
under SIGKILL replay, reusing the pattern from test_diagnostics.py),
error classification, supervised retry with backoff, OOM degradation
down the FFT/paint ladder, deterministic fault injection, and the
acceptance path — a bench rep killed mid-run resuming on relaunch
into one complete record with ``resumed: true``."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY, read_trace
from nbodykit_tpu.resilience import (DEADLINE, FATAL, OOM, TRANSIENT,
                                     CheckpointStore, DegradationLadder,
                                     RetryPolicy, Supervisor,
                                     classify_error, default_ladder,
                                     error_class, fault_point,
                                     parse_spec, reset_faults)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """Registry, tracer, fault counts and the degradable options are
    process-wide; every test sees (and leaves) a pristine copy."""
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    yield
    REGISTRY.reset()
    reset_faults()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _counter(name):
    snap = REGISTRY.snapshot().get(name)
    return snap['value'] if snap else 0


def _spans(path):
    records, _ = read_trace(str(path))
    return [r for r in records if r.get('t') == 'span']


# ---------------------------------------------------------------------------
# checkpoint store

def test_checkpoint_roundtrip(tmp_path):
    st = CheckpointStore(tmp_path)
    state = {'a': 1, 'b': [1.5, 'x'], 'nested': {'k': 2}}
    arrays = {'acc': np.arange(12.0).reshape(3, 4),
              'idx': np.array([3, 1, 2], np.int32)}
    st.save('bench.metric+1e_07', state, arrays=arrays)
    got = st.load('bench.metric+1e_07')
    assert got is not None
    got_state, got_arrays = got
    assert got_state == state
    np.testing.assert_array_equal(got_arrays['acc'], arrays['acc'])
    np.testing.assert_array_equal(got_arrays['idx'], arrays['idx'])
    assert got_arrays['idx'].dtype == np.int32
    assert st.keys() == ['bench.metric_1e_07']
    assert st.age_s('bench.metric+1e_07') >= 0
    assert st.oldest_age_s() >= 0
    st.delete('bench.metric+1e_07')
    assert st.load('bench.metric+1e_07') is None
    assert st.keys() == [] and st.oldest_age_s() is None


def test_checkpoint_overwrite_latest_wins(tmp_path):
    st = CheckpointStore(tmp_path)
    st.save('k', {'completed': 1})
    st.save('k', {'completed': 2})
    assert st.load('k')[0] == {'completed': 2}


def test_checkpoint_corrupt_state_detected(tmp_path):
    st = CheckpointStore(tmp_path)
    path = st.save('k', {'completed': 3})
    meta = json.load(open(path))
    meta['state']['completed'] = 4          # tampered, hash now stale
    with open(path, 'w') as f:
        json.dump(meta, f)
    assert st.load('k') is None
    assert _counter('resilience.checkpoint.corrupt') == 1
    # a torn metadata file (killed writer) is corrupt, not fatal
    with open(path, 'w') as f:
        f.write('{"v": 1, "state": {"comp')
    assert st.load('k') is None


def test_checkpoint_corrupt_array_detected(tmp_path):
    st = CheckpointStore(tmp_path)
    st.save('k', {'n': 1}, arrays={'x': np.ones(4)})
    apath = [os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
             if f.endswith('.npy')][0]
    with open(apath, 'wb') as f:
        np.save(f, np.zeros(4))             # bytes no longer match
    assert st.load('k') is None
    assert _counter('resilience.checkpoint.corrupt') == 1


def test_checkpoint_atomic_under_sigkill(tmp_path):
    """A SIGKILL mid-save (injected at the pre-commit fault point)
    must leave the PREVIOUS checkpoint intact and loadable — the
    atomic tmp+rename contract."""
    script = r"""
import os, sys
sys.path.insert(0, %r)
import nbodykit_tpu
from nbodykit_tpu.resilience import CheckpointStore
# the SECOND save of key 'k' dies between writing the tmp file and
# the commit rename
nbodykit_tpu.set_options(faults='ckpt.write.k@2:kill')
st = CheckpointStore(%r)
st.save('k', {'completed': 1, 'elapsed_s': 2.5})
st.save('k', {'completed': 2, 'elapsed_s': 5.0})   # SIGKILLed here
raise SystemExit('unreachable')
""" % (REPO, str(tmp_path))
    proc = subprocess.run([sys.executable, '-c', script],
                          capture_output=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    got = CheckpointStore(tmp_path).load('k')
    assert got is not None, 'checkpoint lost to a mid-save kill'
    assert got[0] == {'completed': 1, 'elapsed_s': 2.5}


# ---------------------------------------------------------------------------
# error classification

def test_classify_error():
    X = error_class()
    assert classify_error(X('UNAVAILABLE: socket closed')) == TRANSIENT
    assert classify_error(RuntimeError('DATA_LOSS: tunnel')) == TRANSIENT
    assert classify_error(
        X('RESOURCE_EXHAUSTED: Out of memory while trying to allocate '
          '4294967296 bytes.')) == OOM
    assert classify_error(MemoryError()) == OOM
    assert classify_error(
        X('DEADLINE_EXCEEDED: timed out')) == DEADLINE
    assert classify_error(ValueError('Nmesh must divide')) == FATAL
    assert classify_error(RuntimeError('INTERNAL: broken')) == FATAL


def test_retry_policy_backoff_bounded():
    p = RetryPolicy(max_retries=5, base_s=1.0, factor=2.0, max_s=4.0,
                    jitter=0.5, seed=3)
    delays = [p.backoff_s(i) for i in range(5)]
    # exponential then capped, jitter adds at most 50%
    assert 1.0 <= delays[0] <= 1.5
    assert 2.0 <= delays[1] <= 3.0
    assert all(4.0 <= d <= 6.0 for d in delays[2:])
    # deterministic for a fixed seed
    q = RetryPolicy(max_retries=5, base_s=1.0, factor=2.0, max_s=4.0,
                    jitter=0.5, seed=3)
    assert [q.backoff_s(i) for i in range(5)] == delays


# ---------------------------------------------------------------------------
# fault injection

def test_parse_spec():
    assert parse_spec('bench.rep@2:kill,p:unavailable') == [
        ('bench.rep', 2, 'kill'), ('p', 1, 'unavailable')]
    with pytest.raises(ValueError):
        parse_spec('p@2:explode')
    with pytest.raises(ValueError):
        parse_spec('justaname')


def test_fault_point_fires_on_nth_call_only():
    with nbodykit_tpu.set_options(faults='p@3:unavailable'):
        reset_faults()
        fault_point('p')
        fault_point('p')
        fault_point('other')                 # untargeted: never counted
        with pytest.raises(Exception, match='UNAVAILABLE'):
            fault_point('p')
        fault_point('p')                     # 4th call: rule spent
    assert _counter('resilience.faults.injected') == 1


def test_fault_point_raises_real_xla_error_class():
    with nbodykit_tpu.set_options(faults='q@1:resource_exhausted'):
        reset_faults()
        with pytest.raises(error_class()) as ei:
            fault_point('q')
        assert 'RESOURCE_EXHAUSTED' in str(ei.value)
        assert classify_error(ei.value) == OOM


# ---------------------------------------------------------------------------
# supervisor

def test_supervisor_retries_transient_with_backoff(tmp_path):
    """ISSUE acceptance: injected UNAVAILABLE is retried with backoff,
    then the call succeeds; the retry is a counter + a trace event."""
    diagnostics.configure(str(tmp_path))
    sleeps = []
    with nbodykit_tpu.set_options(faults='work.attempt@1:unavailable'):
        reset_faults()
        sup = Supervisor('work',
                         policy=RetryPolicy(max_retries=3, base_s=0.25,
                                            jitter=0.5, seed=7),
                         sleep=sleeps.append)
        assert sup.run(lambda: 'done') == 'done'
    diagnostics.configure(None)
    assert _counter('resilience.retries') == 1
    assert len(sleeps) == 1 and 0.25 <= sleeps[0] <= 0.375
    spans = _spans(tmp_path)
    retry = [s for s in spans if s['name'] == 'resilience.retry']
    assert len(retry) == 1
    assert retry[0]['attrs']['cls'] == TRANSIENT
    assert retry[0]['attrs']['task'] == 'work'
    assert 'UNAVAILABLE' in retry[0]['attrs']['error']
    backoff = [s for s in spans if s['name'] == 'resilience.backoff']
    assert len(backoff) == 1                 # the wait itself is a span


def test_supervisor_retry_budget_exhausted_reraises():
    with nbodykit_tpu.set_options(
            faults='w.attempt@1:unavailable,w.attempt@2:unavailable'):
        reset_faults()
        sup = Supervisor('w', policy=RetryPolicy(max_retries=1),
                         sleep=lambda s: None)
        with pytest.raises(Exception, match='UNAVAILABLE'):
            sup.run(lambda: 'never')
    assert _counter('resilience.retries') == 1


def test_supervisor_fatal_passes_through():
    sup = Supervisor('f', sleep=lambda s: None)
    with pytest.raises(ValueError, match='a real bug'):
        sup.run(lambda: (_ for _ in ()).throw(ValueError('a real bug')))
    assert _counter('resilience.retries') == 0


def test_supervisor_oom_steps_down_ladder(tmp_path):
    """ISSUE acceptance: injected RESOURCE_EXHAUSTED steps down the
    FFT/paint ladder (fft_chunk_bytes then paint_chunk_size halved)
    with each degradation recorded as a counter + trace event."""
    diagnostics.configure(str(tmp_path))
    fc0 = int(_global_options['fft_chunk_bytes'])
    pc0 = int(_global_options['paint_chunk_size'])

    def fn():
        # "OOMs" until BOTH knobs have stepped down one rung
        if _global_options['fft_chunk_bytes'] == fc0 or \
                _global_options['paint_chunk_size'] == pc0:
            raise error_class()('RESOURCE_EXHAUSTED: out of memory')
        return 'fits now'

    sup = Supervisor('big', ladder=default_ladder(),
                     sleep=lambda s: None)
    assert sup.run(fn) == 'fits now'
    diagnostics.configure(None)
    assert int(_global_options['fft_chunk_bytes']) == fc0 // 2
    assert int(_global_options['paint_chunk_size']) == pc0 // 2
    assert _counter('resilience.degradations') == 2
    degr = [s for s in _spans(tmp_path)
            if s['name'] == 'resilience.degrade']
    assert [d['attrs']['rung'] for d in degr] == \
        ['fft_chunk_bytes/2', 'paint_chunk_size/2']
    assert degr[0]['attrs']['detail']['fft_chunk_bytes'] == fc0 // 2


def test_supervisor_oom_without_ladder_reraises():
    sup = Supervisor('nl', sleep=lambda s: None)
    with pytest.raises(Exception, match='RESOURCE_EXHAUSTED'):
        sup.run(lambda: (_ for _ in ()).throw(
            error_class()('RESOURCE_EXHAUSTED: oom')))
    assert _counter('resilience.degradations') == 0


def test_supervisor_ladder_exhausted_reraises():
    ladder = DegradationLadder([('noop', lambda: {'step': 1})])
    sup = Supervisor('x', ladder=ladder, sleep=lambda s: None)
    with pytest.raises(Exception, match='RESOURCE_EXHAUSTED'):
        sup.run(lambda: (_ for _ in ()).throw(
            error_class()('RESOURCE_EXHAUSTED: oom')))
    assert _counter('resilience.degradations') == 1
    assert ladder.applied == [('noop', {'step': 1})]


def test_default_ladder_respects_floors():
    nbodykit_tpu.set_options(fft_chunk_bytes=1 << 24,
                             paint_chunk_size=1 << 18)
    ladder = default_ladder()
    while ladder.step() is not None:
        pass
    assert int(_global_options['fft_chunk_bytes']) == 1 << 24
    assert int(_global_options['paint_chunk_size']) == 1 << 18


def test_supervisor_resume_validate_rejects_mismatch(tmp_path):
    st = CheckpointStore(tmp_path)
    st.save('k', {'reps': 4, 'completed': 1})
    sup = Supervisor('v', checkpoint=st)
    assert sup.resume('k', validate=lambda s: s['reps'] == 2) is None
    assert _counter('resilience.resumes') == 0
    got = sup.resume('k', validate=lambda s: s['reps'] == 4)
    assert got[0]['completed'] == 1
    assert _counter('resilience.resumes') == 1
    sup.done('k')
    assert st.load('k') is None


# ---------------------------------------------------------------------------
# doctor / history posture

def test_resilience_summary_flags_pending_checkpoints(tmp_path):
    """A leftover checkpoint is an interrupted measurement awaiting
    relaunch: the regress history and the doctor must surface it."""
    from nbodykit_tpu.diagnostics.regress import resilience_summary
    res = resilience_summary(str(tmp_path))
    assert res == {'resumed_records': 0, 'pending_checkpoints': 0,
                   'oldest_checkpoint_hours': None}
    CheckpointStore(tmp_path / 'BENCH_CKPT').save(
        'bench.fftpower_x', {'completed': 1, 'reps': 2})
    with open(tmp_path / 'BENCH_STAGED.json', 'w') as f:
        json.dump({'results': {'m': {'metric': 'm', 'value': 1.0,
                                     'resumed': True}}}, f)
    res = resilience_summary(str(tmp_path))
    assert res['pending_checkpoints'] == 1
    assert res['resumed_records'] == 1
    assert res['oldest_checkpoint_hours'] is not None


def test_doctor_counts_resilience_events_from_trace(tmp_path):
    """Registry counters and trace events are merged per-key by max —
    a same-process doctor run must not double-count its own trace."""
    from nbodykit_tpu.diagnostics.__main__ import _resilience_counts
    tr = diagnostics.configure(str(tmp_path))
    tr.event('resilience.retry', {'task': 't'})
    tr.event('resilience.retry', {'task': 't'})
    tr.event('resilience.resume', {'key': 'k'})
    REGISTRY.counter('resilience.retries').add(2)
    diagnostics.configure(None)
    counts = _resilience_counts(str(tmp_path))
    assert counts['retries'] == 2
    assert counts['resumes'] == 1


# ---------------------------------------------------------------------------
# the OOM-ladder FFT rung (satellite): eager large c2c gets the
# tracer check + a Python-driven lowmem driver

def test_c2c_lowmem_matches_fftn():
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.parallel import dfft
    rng = np.random.RandomState(5)
    x = (rng.randn(8, 12, 10) + 1j * rng.randn(8, 12, 10)) \
        .astype('c16')
    ref = np.transpose(np.fft.fftn(x), (1, 0, 2))
    # direct driver call (chunked: tiny target)
    got = dfft.fftn_c2c_single_lowmem([jnp.asarray(x)], target=4096)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-12,
                               atol=1e-10)
    back = dfft.fftn_c2c_single_lowmem([jnp.asarray(got)],
                                       inverse=True, target=4096)
    np.testing.assert_allclose(np.asarray(back), x, rtol=1e-12,
                               atol=1e-12)
    with nbodykit_tpu.set_options(fft_chunk_bytes=4096):
        # eager dispatch goes through the lowmem driver...
        got2 = dfft.dist_fftn_c2c(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got2), ref, rtol=1e-12,
                                   atol=1e-10)
        # ...while a traced call takes the in-jit chunked branch (the
        # Tracer check: jitting must neither fail nor call back out)
        traced = jax.jit(lambda v: dfft.dist_fftn_c2c(v))(
            jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(traced), ref,
                                   rtol=1e-12, atol=1e-10)


def test_c2c_lowmem_emits_chunk_spans(tmp_path):
    import jax.numpy as jnp
    from nbodykit_tpu.parallel import dfft
    x = jnp.ones((8, 8, 8), jnp.complex64)
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        dfft.fftn_c2c_single_lowmem([x], target=2048)
    spans = _spans(tmp_path)
    names = [s['name'] for s in spans]
    assert 'fft.lowmem.c2c' in names
    assert any(s['name'] == 'fft.chunk' for s in spans)


# ---------------------------------------------------------------------------
# acceptance: a bench rep SIGKILLed mid-run resumes on relaunch

@pytest.mark.parametrize('nmesh,npart', [(32, 2000)])
def test_bench_rep_kill_then_resume(tmp_path, nmesh, npart):
    """bench.py --config, killed by the fault harness at the start of
    rep 2, relaunched without faults: the relaunch must RESUME (not
    restart), flush one complete record with ``resumed: true``, clean
    up its checkpoint, and leave the resume event in the trace."""
    env_base = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        BENCH_REPS='2', BENCH_PHASES='0',
        BENCH_STAGED_PATH=str(tmp_path / 'STAGED.json'),
        BENCH_DETAIL_PATH=str(tmp_path / 'DETAIL.json'),
        BENCH_CKPT_DIR=str(tmp_path / 'CKPT'),
        BENCH_TRACE_DIR=str(tmp_path / 'TRACE'),
    )
    env_base.pop('NBKIT_FAULTS', None)
    bench = os.path.join(REPO, 'bench.py')

    # run 1: rep 0 completes and checkpoints; the kill fires entering
    # rep 1
    env1 = dict(env_base, NBKIT_FAULTS='bench.rep@2:kill')
    p1 = subprocess.run([sys.executable, bench, '--config',
                         str(nmesh), str(npart)],
                        capture_output=True, timeout=560, env=env1)
    assert p1.returncode == -signal.SIGKILL, p1.stderr.decode()[-2000:]
    ckpts = os.listdir(tmp_path / 'CKPT')
    assert any(f.endswith('.ckpt.json') for f in ckpts), ckpts
    staged = json.load(open(tmp_path / 'STAGED.json'))['results']
    (partial,) = staged.values()
    assert partial['partial'] is True        # warmed record survived

    # run 2: no faults — resumes rep 1 from the checkpoint
    p2 = subprocess.run([sys.executable, bench, '--config',
                         str(nmesh), str(npart)],
                        capture_output=True, timeout=560, env=env_base)
    assert p2.returncode == 0, p2.stderr.decode()[-2000:]
    rec = json.loads(p2.stdout.decode().strip().splitlines()[-1])
    # one complete, doctor-clean record (regress.classify's shape
    # contract: metric + unit + positive value), marked resumed
    assert rec['resumed'] is True and rec['resumed_reps'] == 1
    assert rec['metric'] and rec['unit'] == 's' and rec['value'] > 0
    staged = json.load(open(tmp_path / 'STAGED.json'))['results']
    (final,) = staged.values()
    assert final['partial'] is False and final['stage'] == 'complete'
    assert final['resumed'] is True
    # checkpoint consumed; nothing left to resume
    assert not any(f.endswith('.ckpt.json')
                   for f in os.listdir(tmp_path / 'CKPT'))
    # the resume event is visible in the merged trace
    records, _ = read_trace(str(tmp_path / 'TRACE'))
    names = {r.get('name') for r in records if r.get('t') == 'span'}
    assert 'resilience.resume' in names
    assert 'ckpt.save' in names
