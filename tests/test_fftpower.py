"""FFTPower/FFTCorr/ProjectedFFTPower tests, mirroring the reference's
oracle styles (SURVEY.md §4): physical invariants (flat shot noise),
independent numpy implementations, device-count invariance, round-trips.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import (UniformCatalog, LinearMesh, ArrayMesh,
                              FFTPower, FFTCorr, ProjectedFFTPower,
                              FieldMesh, ArrayCatalog)
from nbodykit_tpu.base.mesh import Field
from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.parallel.runtime import cpu_mesh


def numpy_power_oracle(field_np, BoxSize, kedges, Nmu, los=[0, 0, 1]):
    """Independent numpy implementation of the (k, mu) binned power of a
    real field (hermitian double-counting, under/overflow bins, last mu
    bin inclusive)."""
    N = field_np.shape[0]
    c = np.fft.rfftn(field_np) / field_np.size
    p3 = (np.abs(c) ** 2) * np.prod(BoxSize)
    p3[0, 0, 0] = 0.0

    kf = 2 * np.pi / np.asarray(BoxSize)
    kx = np.fft.fftfreq(N, 1.0 / N)[:, None, None] * kf[0]
    ky = np.fft.fftfreq(N, 1.0 / N)[None, :, None] * kf[1]
    kz = np.arange(N // 2 + 1)[None, None, :] * kf[2]
    kk = np.sqrt(kx ** 2 + ky ** 2 + kz ** 2)
    with np.errstate(invalid='ignore'):
        mu = np.where(kk == 0, 0.0,
                      (kx * los[0] + ky * los[1] + kz * los[2]) / kk)

    w = np.full(c.shape, 2.0)
    w[..., 0] = 1.0
    if N % 2 == 0:
        w[..., -1] = 1.0

    muedges = np.linspace(-1, 1, Nmu + 1)
    dig_k = np.digitize(kk.ravel() ** 2, np.asarray(kedges) ** 2)
    dig_mu = np.digitize(mu.ravel(), muedges)
    idx = dig_k * (Nmu + 2) + dig_mu
    nb = (len(kedges) + 1) * (Nmu + 2)
    Psum = np.bincount(idx, weights=(w * p3).flat, minlength=nb)
    Nsum = np.bincount(idx, weights=w.flat, minlength=nb)
    Psum = Psum.reshape(len(kedges) + 1, Nmu + 2)
    Nsum = Nsum.reshape(len(kedges) + 1, Nmu + 2)
    Psum[:, -2] += Psum[:, -1]
    Nsum[:, -2] += Nsum[:, -1]
    with np.errstate(invalid='ignore', divide='ignore'):
        pk = (Psum / Nsum)[1:-1, 1:-1]
        modes = Nsum[1:-1, 1:-1]
    return pk, modes


def test_fftpower_matches_numpy_oracle(comm):
    # arbitrary real field -> power must match the independent oracle
    rng = np.random.RandomState(8)
    N, L = 16, 50.0
    field_np = rng.standard_normal((N, N, N))
    mesh = ArrayMesh(field_np, BoxSize=L, comm=comm)
    r = FFTPower(mesh, mode='2d', Nmu=4)
    kedges = r.power.edges['k']
    want, modes_want = numpy_power_oracle(field_np, [L] * 3, kedges, 4)
    got = r.power['power'].real
    np.testing.assert_allclose(r.power['modes'], modes_want)
    valid = modes_want > 0
    np.testing.assert_allclose(got[valid], want[valid], rtol=1e-9)


def test_fftpower_shotnoise_flat(comm):
    # reference oracle (test_fftpower.py:12-44): compensated paint of a
    # uniform catalog has flat power = shot noise, reduced chi2 < 1
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        cat = UniformCatalog(nbar=3e-3, BoxSize=100.0, seed=42)
        mesh = cat.to_mesh(Nmesh=32, resampler='cic', compensated=True)
        r = FFTPower(mesh, mode='1d')
    pk = r.power['power'].real
    sn = r.attrs['shotnoise']
    modes = r.power['modes']
    valid = (modes > 0) & (pk != 0)
    chi2 = np.sum(((pk[valid] - sn) / sn) ** 2 * modes[valid] / 2)
    assert chi2 / valid.sum() < 1.5


def test_fftpower_device_count_invariance():
    rng = np.random.RandomState(5)
    N, L = 16, 10.0
    field_np = rng.standard_normal((N, N, N))
    results = []
    for mesh in [cpu_mesh(1), cpu_mesh()]:
        r = FFTPower(ArrayMesh(field_np, BoxSize=L, comm=mesh),
                     mode='2d', Nmu=3, poles=[0, 2])
        results.append(r)
    np.testing.assert_allclose(results[0].power['power'].real,
                               results[1].power['power'].real,
                               rtol=1e-8, equal_nan=True)
    np.testing.assert_allclose(results[0].poles['power_2'].real,
                               results[1].poles['power_2'].real,
                               rtol=1e-8, equal_nan=True)


def test_fftpower_poles_consistency(comm):
    # P0 from poles == P(k) 1d (monopole == mu-average); reference
    # oracle test_fftpower.py:47-61
    rng = np.random.RandomState(3)
    field_np = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field_np, BoxSize=20.0, comm=comm)
    r = FFTPower(mesh, mode='1d', poles=[0])
    p1d = r.power['power'].real
    p0 = r.poles['power_0'].real
    valid = r.power['modes'] > 0
    np.testing.assert_allclose(p0[valid], p1d[valid], rtol=1e-8)


def test_fftpower_cross(comm):
    # cross power of a field with itself == auto power
    rng = np.random.RandomState(4)
    field_np = rng.standard_normal((8, 8, 8))
    m1 = ArrayMesh(field_np, BoxSize=10.0, comm=comm)
    m2 = ArrayMesh(field_np, BoxSize=10.0, comm=comm)
    auto = FFTPower(m1, mode='1d')
    cross = FFTPower(m1, mode='1d', second=m2)
    np.testing.assert_allclose(auto.power['power'].real,
                               cross.power['power'].real,
                               rtol=1e-9, equal_nan=True)


def test_fftpower_save_load(comm, tmp_path):
    rng = np.random.RandomState(6)
    field_np = rng.standard_normal((8, 8, 8))
    r = FFTPower(ArrayMesh(field_np, BoxSize=10.0, comm=comm),
                 mode='2d', Nmu=3, poles=[0, 2])
    fn = str(tmp_path / "power.json")
    r.save(fn)
    r2 = FFTPower.load(fn)
    np.testing.assert_allclose(r.power['power'].real,
                               r2.power['power'].real, equal_nan=True)
    np.testing.assert_allclose(r.poles['power_2'].real,
                               r2.poles['power_2'].real, equal_nan=True)
    assert r2.attrs['mode'] == '2d'


def test_linear_mesh_recovers_power(comm):
    # LinearMesh realization must recover the input P(k) within sample
    # variance; with unitary_amplitude the scatter shrinks drastically
    Plin = lambda k: 100.0 * np.ones_like(k)
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        mesh = LinearMesh(Plin, BoxSize=64.0, Nmesh=32, seed=7,
                          unitary_amplitude=True, dtype='f8')
        r = FFTPower(mesh, mode='1d')
    pk = r.power['power'].real
    modes = r.power['modes']
    valid = (modes > 0) & ~np.isnan(pk) & (pk != 0)
    np.testing.assert_allclose(pk[valid], 100.0, rtol=1e-6)


def test_fftcorr_runs_and_integrates(comm):
    # xi(r) of a white field: all power in the r=0 bin; elsewhere ~0
    rng = np.random.RandomState(9)
    field_np = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field_np, BoxSize=16.0, comm=comm)
    r = FFTCorr(mesh, mode='1d')
    xi = r.corr['corr'].real
    # white noise: xi(r>0) ~ 0 vs xi(0) ~ var
    assert abs(xi[0]) > 10 * np.nanmax(np.abs(xi[1:]))


def test_fftcorr_device_invariance():
    rng = np.random.RandomState(10)
    field_np = rng.standard_normal((16, 16, 16))
    rs = [FFTCorr(ArrayMesh(field_np, BoxSize=16.0, comm=m), mode='1d')
          for m in [cpu_mesh(1), cpu_mesh()]]
    np.testing.assert_allclose(rs[0].corr['corr'], rs[1].corr['corr'],
                               rtol=1e-8, equal_nan=True)


def _projected_power_oracle(field_np, boxsize, axes, dk, kmin=0.0):
    """Independent numpy computation of the projected power."""
    nd = len(axes)
    dropped = tuple(i for i in range(3) if i not in axes)
    proj = np.transpose(field_np.sum(axis=dropped),
                        [sorted(axes).index(a) for a in axes])
    c = np.fft.rfftn(proj) / field_np.size
    pk = (c * c.conj())
    pk.flat[0] = 0.0
    dims = [field_np.shape[i] for i in axes]
    lens = [boxsize] * nd
    kk = np.zeros(pk.shape)
    for j in range(nd):
        freq = (np.arange(pk.shape[-1]) if j == nd - 1
                else np.fft.fftfreq(dims[j], 1.0 / dims[j]))
        sh = [1] * nd
        sh[j] = freq.size
        kk = kk + (freq * 2 * np.pi / lens[j]).reshape(sh) ** 2
    kmag = np.sqrt(kk)
    w = np.full(pk.shape, 2.0)
    w[..., 0] = 1.0
    if dims[-1] % 2 == 0:
        w[..., -1] = 1.0
    kedges = np.arange(kmin, np.pi * min(dims) / max(lens) + dk / 2, dk)
    dig = np.digitize(kmag.reshape(-1), kedges)
    nb = len(kedges) + 1
    nsum = np.bincount(dig, weights=w.reshape(-1), minlength=nb)
    psum = np.bincount(dig, weights=(w * pk.real).reshape(-1),
                       minlength=nb)
    with np.errstate(invalid='ignore', divide='ignore'):
        return (psum / nsum)[1:-1] * np.prod(lens)


def test_projected_fftpower(comm):
    rng = np.random.RandomState(11)
    field_np = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field_np, BoxSize=16.0, comm=comm)
    r = ProjectedFFTPower(mesh, axes=(0, 1))
    assert 'power' in r.power.variables
    oracle = _projected_power_oracle(field_np, 16.0, (0, 1),
                                     dk=2 * np.pi / 16.0)
    np.testing.assert_allclose(r.power['power'].real, oracle,
                               rtol=1e-8, equal_nan=True)


def test_projected_fftpower_1d_axis(comm):
    rng = np.random.RandomState(13)
    field_np = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field_np, BoxSize=16.0, comm=comm)
    r = ProjectedFFTPower(mesh, axes=(2,))
    oracle = _projected_power_oracle(field_np, 16.0, (2,),
                                     dk=2 * np.pi / 16.0)
    np.testing.assert_allclose(r.power['power'].real, oracle,
                               rtol=1e-8, equal_nan=True)


def test_project_to_basis_chunked_multidevice(monkeypatch):
    # forcing tiny chunks on an 8-device mesh must reproduce the
    # unchunked single-device result exactly (the chunked path now
    # engages inside shard_map, round-2 VERDICT weak #4)
    import nbodykit_tpu.algorithms.fftpower as fp
    rng = np.random.RandomState(20)
    field_np = rng.standard_normal((16, 16, 16))
    r_one = FFTPower(ArrayMesh(field_np, BoxSize=16.0, comm=cpu_mesh(1)),
                     mode='2d', Nmu=5, poles=[0, 2])
    monkeypatch.setattr(fp, '_BIN_CHUNK_ELEMENTS', 16 * 9)
    r_many = FFTPower(ArrayMesh(field_np, BoxSize=16.0, comm=cpu_mesh()),
                      mode='2d', Nmu=5, poles=[0, 2])
    np.testing.assert_allclose(r_one.power['power'].real,
                               r_many.power['power'].real,
                               rtol=1e-10, equal_nan=True)
    np.testing.assert_allclose(r_one.poles['power_0'].real,
                               r_many.poles['power_0'].real,
                               rtol=1e-10, equal_nan=True)


def test_project_to_basis_mxu_binning(monkeypatch):
    # the MXU one-hot-matmul histogram is the production binning on
    # TPU; force it on CPU and compare against the exact bincount path
    import nbodykit_tpu.ops.histogram as hist
    rng = np.random.RandomState(21)
    field_np = rng.standard_normal((16, 16, 16))
    r_exact = FFTPower(ArrayMesh(field_np, BoxSize=16.0), mode='2d',
                       Nmu=5, poles=[0, 2, 4])
    monkeypatch.setattr(hist, '_default_method', lambda: 'mxu')
    r_mxu = FFTPower(ArrayMesh(field_np, BoxSize=16.0), mode='2d',
                     Nmu=5, poles=[0, 2, 4])
    np.testing.assert_allclose(r_mxu.power['power'].real,
                               r_exact.power['power'].real,
                               rtol=2e-5, equal_nan=True)
    np.testing.assert_allclose(r_mxu.poles['power_2'].real,
                               r_exact.poles['power_2'].real,
                               atol=2e-5 * np.nanmax(
                                   np.abs(r_exact.poles['power_2'].real)),
                               equal_nan=True)
    np.testing.assert_allclose(np.asarray(r_mxu.power['modes'], 'f8'),
                               np.asarray(r_exact.power['modes'], 'f8'))


def test_projected_fftpower_device_invariance():
    rng = np.random.RandomState(12)
    field_np = rng.standard_normal((16, 16, 16))
    rs = [ProjectedFFTPower(ArrayMesh(field_np, BoxSize=16.0, comm=m),
                            axes=(0, 1))
          for m in [cpu_mesh(1), cpu_mesh()]]
    np.testing.assert_allclose(rs[0].power['power'].real,
                               rs[1].power['power'].real,
                               rtol=1e-8, equal_nan=True)


def test_fftpower_anisotropic_box_and_mesh():
    """Anisotropic BoxSize triplet + anisotropic Nmesh: shot noise is
    V/N and the flat spectrum tracks it (reference supports 3-vector
    BoxSize/Nmesh throughout)."""
    rng = np.random.RandomState(0)
    box = np.array([100.0, 150.0, 80.0])
    pos = rng.uniform(0, 1, (20000, 3)) * box
    cat = ArrayCatalog({'Position': pos}, BoxSize=box)
    r = FFTPower(cat, mode='2d', Nmesh=[32, 48, 24], poles=[0, 2])
    V = float(np.prod(box))
    np.testing.assert_allclose(r.attrs['shotnoise'], V / 20000,
                               rtol=1e-6)
    p = np.asarray(r.power['power'].real)
    valid = np.asarray(r.power['modes']) > 0
    ratio = np.nanmean(p[valid] / r.attrs['shotnoise'])
    assert abs(ratio - 1) < 0.3
