"""CylindricalGroups and FiberCollisions tests."""

import numpy as np
import pytest

from nbodykit_tpu.lab import ArrayCatalog
from nbodykit_tpu.algorithms.cgm import CylindricalGroups
from nbodykit_tpu.algorithms.fibercollisions import FiberCollisions


def test_cgm_basic():
    # two "halos": a massive central with nearby satellites, plus an
    # isolated object
    pos = np.array([
        [50.0, 50.0, 50.0],   # massive central
        [50.5, 50.0, 50.0],   # satellite (dperp 0.5)
        [50.0, 50.4, 51.0],   # satellite (dperp 0.4, dpar 1.0)
        [20.0, 20.0, 20.0],   # isolated
    ])
    mass = np.array([10.0, 1.0, 1.0, 5.0])
    cat = ArrayCatalog({'Position': pos, 'Mass': mass}, BoxSize=100.0)
    cgm = CylindricalGroups(cat, rankby='Mass', rperp=1.0, rpar=2.0)
    types = np.asarray(cgm.groups['cgm_type'])
    hid = np.asarray(cgm.groups['cgm_haloid'])
    assert types[0] == 0            # central with satellites
    assert types[1] == 1 and hid[1] == 0
    assert types[2] == 1 and hid[2] == 0
    # isolated centrals are type 0 with no satellites (the reference
    # defines only types 0/1, cgm.py:133-134)
    assert types[3] == 0
    nsat = np.asarray(cgm.groups['num_cgm_sats'])
    assert nsat[0] == 2 and nsat[3] == 0


def test_cgm_rank_ordering():
    # the *more massive* of two close objects becomes the central
    pos = np.array([[10.0, 10.0, 10.0], [10.3, 10.0, 10.0]])
    mass = np.array([1.0, 2.0])
    cat = ArrayCatalog({'Position': pos, 'Mass': mass}, BoxSize=50.0)
    cgm = CylindricalGroups(cat, rankby='Mass', rperp=1.0, rpar=1.0)
    types = np.asarray(cgm.groups['cgm_type'])
    assert types[1] == 0 and types[0] == 1
    assert np.asarray(cgm.groups['cgm_haloid'])[0] == 1


def test_cgm_overlapping_cylinders_highest_priority():
    # a satellite whose cylinder contains TWO centrals joins the
    # higher-priority (more massive) one, even though the other is
    # nearer — the reference sorts candidate pairs by rank and keeps
    # the first (cgm.py:150+), it does not pick the nearest
    pos = np.array([
        [50.0, 50.0, 50.0],   # central A, highest mass, dperp 0.9
        [50.9, 50.0, 50.0],   # satellite, between the two centrals
        [51.5, 50.0, 50.0],   # central B, lower mass, dperp 0.6
    ])                        # A<->B 1.5 > rperp: both stay central
    mass = np.array([10.0, 1.0, 5.0])
    cat = ArrayCatalog({'Position': pos, 'Mass': mass}, BoxSize=100.0)
    cgm = CylindricalGroups(cat, rankby='Mass', rperp=1.0, rpar=1.0)
    types = np.asarray(cgm.groups['cgm_type'])
    hid = np.asarray(cgm.groups['cgm_haloid'])
    nsat = np.asarray(cgm.groups['num_cgm_sats'])
    assert list(types) == [0, 1, 0]
    assert hid[1] == 0              # joined A (priority), not B (near)
    assert nsat[0] == 1 and nsat[2] == 0


def test_fibercollisions_pair():
    # two objects within the collision radius: exactly one collided
    ra = np.array([10.0, 10.0 + 30. / 3600., 50.0])
    dec = np.array([0.0, 0.0, 20.0])
    fc = FiberCollisions(ra, dec, collision_radius=62. / 3600., seed=42)
    coll = np.asarray(fc.labels['Collided'])
    nid = np.asarray(fc.labels['NeighborID'])
    assert coll[:2].sum() == 1
    assert coll[2] == 0
    i = int(np.flatnonzero(coll[:2])[0])
    assert nid[i] == (i ^ 1)


def test_fibercollisions_triplet_chain():
    # three objects in a chain, spacing < radius: optimal assignment
    # collides only the middle one
    step = 40. / 3600.
    ra = np.array([10.0, 10.0 + step, 10.0 + 2 * step])
    dec = np.zeros(3)
    fc = FiberCollisions(ra, dec, collision_radius=62. / 3600., seed=1)
    coll = np.asarray(fc.labels['Collided'])
    assert coll.sum() == 1
    assert coll[1] == 1


def test_fibercollisions_isolated():
    rng = np.random.RandomState(3)
    ra = rng.uniform(0, 360, 50)
    dec = np.degrees(np.arcsin(rng.uniform(-0.5, 0.5, 50)))
    fc = FiberCollisions(ra, dec, seed=2)
    # at this sparsity nothing collides
    assert np.asarray(fc.labels['Collided']).sum() == 0
    assert np.all(np.asarray(fc.labels['NeighborID']) == -1)
