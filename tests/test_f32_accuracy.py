"""P(k) accuracy without float64 — the TPU reality check.

TPUs have no f64: the suite's global ``jax_enable_x64`` (conftest.py)
hides whether FFTPower survives f32 painting, FFT, and binning within
the 1e-4 relative target (BASELINE.json; round-2 VERDICT weak #3).
Here a subprocess runs the identical pipeline with x64 DISABLED and the
parent (x64) result is the truth.

What makes the f32 path hold the target (algorithms/fftpower.py):

- exact-integer lattice binning: bin decisions compare exact int32
  |i|^2 against host-f64-quantized edges, so no mode ever flips a k bin
  to f32 rounding;
- Kahan-compensated cross-chunk accumulation of the f32 histograms.

Bin edges here are deliberately incommensurate with the lattice
(dk != fundamental) so the f64 and f32 paths must agree on every
mode-to-bin assignment exactly; with edges ON the lattice (the dk
default) tie modes are rounding-decided in BOTH regimes.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))

NMESH = 256
NPART = 50_000
BOX = 1000.0
SEED = 42
# incommensurate edges: no |i|^2 integer sits within f32 ulp of an edge
KMIN = 0.31 * (2 * np.pi / BOX)
DK = 2.6718 * (2 * np.pi / BOX)

_CHILD = r"""
import json, os, sys
sys.path.insert(0, %(root)r)
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', False)   # the TPU regime
assert not jax.config.jax_enable_x64

from nbodykit_tpu.lab import ArrayCatalog
from nbodykit_tpu.algorithms.fftpower import FFTPower

NMESH, NPART, BOX, SEED, KMIN, DK = %(args)s
rng = np.random.RandomState(SEED)
pos = rng.uniform(0.0, BOX, size=(NPART, 3))
cat = ArrayCatalog({'Position': pos}, BoxSize=BOX)
r = FFTPower(cat, mode='1d', Nmesh=NMESH, poles=[0, 2],
             kmin=KMIN, dk=DK)
from nbodykit_tpu.algorithms.fftcorr import FFTCorr
# incommensurate dr (like DK) so both regimes agree on every bin
rc = FFTCorr(cat, mode='1d', Nmesh=NMESH, rmin=0.29 * BOX / NMESH,
             dr=2.6718 * BOX / NMESH)
out = {
    'k': np.asarray(r.power['k'], 'f8').tolist(),
    'power': np.asarray(r.power['power'].real, 'f8').tolist(),
    'modes': np.asarray(r.power['modes'], 'f8').tolist(),
    'p0': np.asarray(r.poles['power_0'].real, 'f8').tolist(),
    'p2': np.asarray(r.poles['power_2'].real, 'f8').tolist(),
    'shotnoise': float(r.attrs['shotnoise']),
    'corr_modes': np.asarray(rc.corr['modes'], 'f8').tolist(),
    'corr': np.asarray(rc.corr['corr'], 'f8').tolist(),
}
print(json.dumps(out))
"""


@pytest.mark.slow
def test_fftpower_f32_matches_f64_within_1e4(tmp_path):
    from nbodykit_tpu.lab import ArrayCatalog
    from nbodykit_tpu.algorithms.fftpower import FFTPower

    # f64 truth in this (x64-enabled) process
    rng = np.random.RandomState(SEED)
    pos = rng.uniform(0.0, BOX, size=(NPART, 3))
    cat = ArrayCatalog({'Position': pos}, BoxSize=BOX)
    truth = FFTPower(cat, mode='1d', Nmesh=NMESH, poles=[0, 2],
                     kmin=KMIN, dk=DK)

    script = tmp_path / 'child_f32.py'
    script.write_text(_CHILD % {
        'root': os.path.dirname(HERE),
        'args': repr([NMESH, NPART, BOX, SEED, KMIN, DK])})
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=HERE,
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = json.loads(proc.stdout.strip().splitlines()[-1])

    modes64 = np.asarray(truth.power['modes'], 'f8')
    # incommensurate edges: every mode must land in the same bin
    np.testing.assert_array_equal(np.asarray(got['modes']), modes64)

    p64 = np.asarray(truth.power['power'].real, 'f8')
    p32 = np.asarray(got['power'], 'f8')
    ok = np.isfinite(p64) & (modes64 > 0)
    # scale-relative: the uniform catalog's P(k) is shot noise
    scale = np.abs(p64[ok]).mean()
    err = np.abs(p32[ok] - p64[ok]) / scale
    assert err.max() < 1e-4, "max rel err %.3g" % err.max()

    k64 = np.asarray(truth.power['k'], 'f8')
    k32 = np.asarray(got['k'], 'f8')
    # the mean-k column carries f32 sqrt rounding (~4e-5); the 1e-4
    # pipeline target is the bar here too
    np.testing.assert_allclose(k32[ok], k64[ok], rtol=1e-4)

    # multipoles: P2 of uniform data ~ 0, compare at the P0 scale
    for name in ('p0', 'p2'):
        a64 = np.asarray(truth.poles['power_%s' % name[1]].real, 'f8')
        a32 = np.asarray(got[name], 'f8')
        m = np.isfinite(a64)
        assert (np.abs(a32[m] - a64[m]) / scale).max() < 1e-4, name

    # the real-field (separation-lattice) branch via FFTCorr: mode
    # counts exact, xi(r) within the same scale-relative budget
    from nbodykit_tpu.algorithms.fftcorr import FFTCorr
    truth_c = FFTCorr(cat, mode='1d', Nmesh=NMESH,
                      rmin=0.29 * BOX / NMESH, dr=2.6718 * BOX / NMESH)
    cm64 = np.asarray(truth_c.corr['modes'], 'f8')
    np.testing.assert_array_equal(np.asarray(got['corr_modes']), cm64)
    xi64 = np.asarray(truth_c.corr['corr'].real, 'f8')
    xi32 = np.asarray(got['corr'], 'f8')
    okc = np.isfinite(xi64) & (cm64 > 0)
    # yardstick: xi's dynamic range (the uniform catalog's xi is noise
    # around zero; measured f32 error is ~2e-6 abs vs a 0.046 range)
    xscale = max(np.abs(xi64[okc]).max(), 1e-30)
    assert (np.abs(xi32[okc] - xi64[okc]) / xscale).max() < 1e-4


_WARN_CHILD = r"""
import sys, warnings
sys.path.insert(0, %(root)r)
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', False)
import numpy as np
with warnings.catch_warnings(record=True) as caught:
    warnings.simplefilter('always')
    from nbodykit_tpu.lab import UniformCatalog, FFTPower
    from nbodykit_tpu.algorithms.pair_counters.simbox import \
        SimulationBoxPairCount
    cat = UniformCatalog(nbar=2e-3, BoxSize=64.0, seed=5)
    r = FFTPower(cat, mode='1d', Nmesh=32)
    pc = SimulationBoxPairCount('1d', cat, np.linspace(1.0, 8.0, 5))
trunc = [w for w in caught
         if 'truncated to dtype float32' in str(w.message)
         and 'nbodykit_tpu' in (w.filename or '')]
for w in trunc:
    print('TRUNCWARN %%s:%%d' %% (w.filename, w.lineno))
print('NWARN', len(trunc))
"""


@pytest.mark.slow
def test_no_truncation_warnings_x64_off(tmp_path):
    """The x64-off (TPU-regime) pipeline emits no f64-truncation
    warnings from package code — f8 requests are canonicalized up
    front (utils.working_dtype)."""
    script = tmp_path / 'child_warn.py'
    script.write_text(_WARN_CHILD % {'root': os.path.dirname(HERE)})
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=HERE,
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert lines[-1].startswith('NWARN'), proc.stdout[-500:]
    nwarn = int(lines[-1].split()[1])
    assert nwarn == 0, '\n'.join(lines)
