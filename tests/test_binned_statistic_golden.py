"""BinnedStatistic loaded from the reference's stored serializations.

The reference repository ships golden JSON and deprecated-plaintext
result files (nbodykit/tests/data/dataset_{1d,2d}*.{json,dat},
exercised at nbodykit/tests/test_binned_stat.py:20-59). Reading them
verifies on-disk format compatibility: a user's archived nbodykit
results must load unchanged. Files are read from the reference tree;
tests skip when it is absent.
"""

import os

import numpy as np
import pytest

from nbodykit_tpu.binned_statistic import BinnedStatistic

DATA_DIR = '/root/reference/nbodykit/tests/data'

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA_DIR), reason="reference data not available")


def test_from_json_1d():
    ds = BinnedStatistic.from_json(
        os.path.join(DATA_DIR, 'dataset_1d.json'))
    assert ds.dims == ['k']
    # the reference's stored 1d dataset holds multipole columns
    for var in ['power_0', 'power_2', 'power_4', 'modes']:
        assert var in ds.variables
    assert np.iscomplexobj(np.asarray(ds['power_0']))
    assert np.isfinite(np.asarray(ds['k'])[1:]).all()
    assert ds.shape[0] == len(ds.edges['k']) - 1


def test_from_json_2d():
    ds = BinnedStatistic.from_json(
        os.path.join(DATA_DIR, 'dataset_2d.json'))
    assert ds.dims == ['k', 'mu']
    assert 'power' in ds.variables
    # binned means lie inside their bin edges wherever defined
    k = np.asarray(ds['k'])
    ke = np.asarray(ds.edges['k'])
    ok = np.isfinite(k)
    assert ((k[ok] >= ke[0]) & (k[ok] <= ke[-1])).all()


def test_from_plaintext_1d():
    ds = BinnedStatistic.from_plaintext(
        ['k'], os.path.join(DATA_DIR, 'dataset_1d_deprecated.dat'))
    assert ds.dims == ['k']
    # wrong dimensionality must raise, mirroring the reference's
    # error contract (test_binned_stat.py:44)
    with pytest.raises(Exception):
        BinnedStatistic.from_plaintext(
            ['k', 'mu'],
            os.path.join(DATA_DIR, 'dataset_1d_deprecated.dat'))


def test_from_plaintext_2d():
    ds = BinnedStatistic.from_plaintext(
        ['k', 'mu'], os.path.join(DATA_DIR, 'dataset_2d_deprecated.dat'))
    assert ds.dims == ['k', 'mu']
    with pytest.raises(Exception):
        BinnedStatistic.from_plaintext(
            ['k'], os.path.join(DATA_DIR, 'dataset_2d_deprecated.dat'))
