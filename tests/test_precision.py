"""The halved-bytes accuracy gate: every compressed candidate vs the
full-width oracle (ISSUE 13).

``set_options(mesh_dtype='bf16')`` stores the painted mesh in bfloat16
(compute stays f32: weights, FFT, readout — pmesh.ParticleMesh splits
storage dtype from compute dtype, and ops/paint.py deposits with a
two-sum hi/lo split so the merge recovers f32-grade sums).
``set_options(a2a_compress='bf16'|'int16')`` keeps every FFT stage
f32 but halves the all_to_all wire payload (parallel/dfft._a2a):
bf16-on-wire/f32-out, or int16 quantized with per-shard scale factors
carried via all_gather.

The gate: each compressed posture's P(k) must match the full-width
pipeline (the oracle — f8 here since the suite enables x64, a strictly
tighter reference than the TPU-regime f32 it stands in for) on every
bin up to k_Nyquist/2, with IDENTICAL mode counts (compression must
never flip a bin assignment) and scale-relative error inside the
per-posture budget.  Measured errors (CPU, mesh64, 8 devices):
mesh-bf16 4.3e-3, a2a-bf16 1.9e-3, a2a-int16 9.0e-5; budgets sit
3-5x above.  Margins are committed to PRECISION.json
(diagnostics.regress.write_precision_margins) so the doctor can attest
any committed tune-cache winner running one of these postures.
"""

import os

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu.pmesh import ParticleMesh, memory_plan

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

NMESH = 64
NPART = 20_000
BOX = 200.0
SEED = 42
# incommensurate edges (test_f32_accuracy.py convention): no lattice
# |i|^2 sits within a ulp of a bin edge, so both regimes must agree on
# every mode-to-bin assignment exactly
KMIN = 0.31 * (2 * np.pi / BOX)
DK = 2.6718 * (2 * np.pi / BOX)
K_NYQ = np.pi * NMESH / BOX

# per-posture scale-relative P(k) error budgets up to k_Nyquist/2,
# 3-5x above the measured margins in the module docstring
BUDGETS = {
    'mesh-bf16': 2e-2,
    'a2a-bf16': 1e-2,
    'a2a-int16': 5e-4,
}


def _pk(**opts):
    """P(k) of the fixed uniform catalog on the 8-device mesh under
    ``set_options(**opts)`` (empty -> the full-width oracle)."""
    from nbodykit_tpu.lab import ArrayCatalog, FFTPower
    from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
    rng = np.random.RandomState(SEED)
    pos = rng.uniform(0.0, BOX, size=(NPART, 3))
    with use_mesh(cpu_mesh()):
        with nbodykit_tpu.set_options(**(opts or {'mesh_dtype': 'f4'})):
            cat = ArrayCatalog({'Position': pos}, BoxSize=BOX)
            r = FFTPower(cat, mode='1d', Nmesh=NMESH, kmin=KMIN, dk=DK)
    return (np.asarray(r.power['k'], 'f8'),
            np.asarray(r.power['power'].real, 'f8'),
            np.asarray(r.power['modes'], 'f8'))


@pytest.fixture(scope='module')
def oracle():
    return _pk()


@pytest.mark.slow
@pytest.mark.parametrize('posture,opts', [
    ('mesh-bf16', {'mesh_dtype': 'bf16'}),
    ('a2a-bf16', {'a2a_compress': 'bf16'}),
    ('a2a-int16', {'a2a_compress': 'int16'}),
])
def test_compressed_pk_within_budget(oracle, posture, opts):
    k0, p0, m0 = oracle
    k, p, m = _pk(**opts)

    # compression must never flip a mode's bin: wire payload precision
    # does not enter bin assignment (exact-integer lattice binning)
    np.testing.assert_array_equal(m, m0)

    sel = (m0 > 0) & np.isfinite(p0) & (k0 <= 0.5 * K_NYQ)
    assert sel.sum() >= 5, 'too few bins below k_Nyquist/2'
    scale = np.abs(p0[sel]).mean()
    err = float((np.abs(p[sel] - p0[sel]) / scale).max())
    budget = BUDGETS[posture]
    assert err < budget, \
        '%s: max P(k) rel err %.3e exceeds budget %.0e' \
        % (posture, err, budget)

    # commit the measured margin so the doctor can attest any
    # tune-cache winner running this posture (regress.precision_summary
    # WARNs on compressed winners with no margin on record)
    from nbodykit_tpu.diagnostics.regress import write_precision_margins
    write_precision_margins(
        {posture: {'max_rel_err': err, 'budget': budget}}, root=ROOT)


@pytest.mark.slow
def test_stacked_compression_within_mesh_budget(oracle):
    """bf16 mesh + bf16 wire stacked stays inside the mesh budget (the
    dominant term; wire error does not compound multiplicatively)."""
    k0, p0, m0 = oracle
    k, p, m = _pk(mesh_dtype='bf16', a2a_compress='bf16')
    np.testing.assert_array_equal(m, m0)
    sel = (m0 > 0) & np.isfinite(p0) & (k0 <= 0.5 * K_NYQ)
    scale = np.abs(p0[sel]).mean()
    err = float((np.abs(p[sel] - p0[sel]) / scale).max())
    assert err < BUDGETS['mesh-bf16'], 'stacked err %.3e' % err


def test_bf16_readout_rewidens():
    """NBK702 contract: readout of a bf16-stored mesh computes and
    returns f32 — the narrow storage never leaks into interpolation."""
    import jax.numpy as jnp
    pm = ParticleMesh(16, 32.0, dtype='bf16')
    assert pm.dtype == np.dtype(jnp.bfloat16)
    assert pm.compute_dtype == np.dtype('f4')
    pos = np.random.RandomState(0).uniform(0, 32.0, (100, 3))
    field = pm.paint(pos)
    assert field.dtype == np.dtype(jnp.bfloat16)
    vals = pm.readout(field, pos)
    assert vals.dtype == np.dtype('f4')
    # r2c re-widens before the transform: complex64, not a narrow type
    assert pm.r2c(field).dtype == np.dtype('c8')


def test_bf16_paint_conserves_mass():
    """The two-sum compensated deposit keeps total mass within bf16
    storage rounding of the particle count."""
    pm = ParticleMesh(32, 64.0, dtype='bf16')
    pos = np.random.RandomState(1).uniform(0, 64.0, (5000, 3))
    total = float(np.sum(np.asarray(pm.paint(pos), dtype='f8')))
    assert abs(total - 5000.0) / 5000.0 < 5e-3


def test_memory_plan_prices_bf16_at_half():
    plan4 = memory_plan(256, 10**6, ndevices=8, dtype='f4')
    plan2 = memory_plan(256, 10**6, ndevices=8, dtype='bf16')
    assert plan2['mesh_dtype'] == 'bfloat16'
    assert plan2['mesh_itemsize'] == 2
    assert plan4['mesh_itemsize'] == 4
    # the real mesh halves exactly; complex/FFT work stays f32-priced
    assert plan2['real_field'] * 2 == plan4['real_field']
    assert plan2['complex_field'] == plan4['complex_field']
    assert plan2['peak_bytes'] < plan4['peak_bytes']


def test_serve_admission_prices_bf16():
    """A bf16 request admits where the identical f4 request is priced
    strictly higher — admission sees the halved mesh (NBK503)."""
    from nbodykit_tpu.serve.request import AnalysisRequest
    from nbodykit_tpu.serve.admission import _plan
    req4 = AnalysisRequest(nmesh=256, npart=10**6, dtype='f4',
                           paint_method='scatter')
    req2 = AnalysisRequest(nmesh=256, npart=10**6, dtype='bf16',
                           paint_method='scatter')
    p4 = _plan(req4, ndevices=8, hbm_bytes=16e9)
    p2 = _plan(req2, ndevices=8, hbm_bytes=16e9)
    assert p2['real_field'] * 2 == p4['real_field']
    assert p2['peak_bytes'] < p4['peak_bytes']


def test_request_rejects_unknown_dtype():
    from nbodykit_tpu.serve.request import AnalysisRequest
    with pytest.raises(ValueError):
        AnalysisRequest(dtype='f2')


def test_tuner_registers_compressed_candidates():
    """Every compressed posture is a raced candidate with full-width
    cold-cache defaults (tune/space.py)."""
    from nbodykit_tpu.tune.space import paint_space, fft_space
    ctx = {'nmesh': 256, 'npart': 10**6, 'nproc': 8,
           'mesh_shape': (4, 2), 'dtype': 'f4'}
    paint = {c.name: c.options for c in paint_space().candidates(ctx)}
    fft = {c.name: c.options for c in fft_space().candidates(ctx)}
    assert 'scatter-bf16' in paint
    assert paint['scatter-bf16']['mesh_dtype'] == 'bf16'
    assert 'slab-a2a-bf16' in fft and 'slab-a2a-int16' in fft
    assert any(n.startswith('pencil') and n.endswith('a2a-bf16')
               for n in fft)
    # cold-cache defaults == today's behavior: plain candidates carry
    # the full-width posture explicitly so winners are unambiguous
    assert paint['scatter']['mesh_dtype'] == 'f4'
    assert all('a2a_compress' in o for o in fft.values())
    assert all(o['a2a_compress'] == 'none'
               for n, o in fft.items() if 'a2a' not in n)


def test_resolve_validates_postures():
    from nbodykit_tpu.tune.resolve import (resolve_mesh_dtype,
                                           resolve_a2a_compress)
    # explicit non-auto values pass through; cold cache falls back to
    # the full-width defaults
    assert resolve_mesh_dtype(nmesh=64) in ('f4', 'bf16')
    assert resolve_a2a_compress(shape=(64, 64, 64)) in \
        ('none', 'bf16', 'int16')


def test_precision_summary_attestation(tmp_path):
    """regress: a committed compressed winner without a margin is
    unattested; writing the margin attests it."""
    import json
    from nbodykit_tpu.diagnostics import regress
    root = str(tmp_path)
    cache = {'version': 1, 'entries': {'k': {
        'op': 'fft', 'shape_class': 'mesh256',
        'winner_name': 'slab-a2a-bf16',
        'winner': {'fft_decomp': 'slab', 'a2a_compress': 'bf16'},
        'trials': {'slab-a2a-bf16': {
            'options': {'fft_decomp': 'slab', 'a2a_compress': 'bf16'},
            'wall_s': 0.1}}}}}
    with open(os.path.join(root, 'TUNE_CACHE.json'), 'w') as f:
        json.dump(cache, f)
    p = regress.precision_summary(root)
    assert p['raced'] == ['slab-a2a-bf16']
    assert p['unattested'] == ['fft/mesh256=slab-a2a-bf16']
    regress.write_precision_margins(
        {'a2a-bf16': {'max_rel_err': 1.9e-3, 'budget': 1e-2}},
        root=root)
    p = regress.precision_summary(root)
    assert p['unattested'] == []
    assert 'a2a-bf16' in p['margins']
    # the render carries the posture line
    h = regress.build_history(root, write=False)
    assert 'precision:' in regress.render_regress(h)
