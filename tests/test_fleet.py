"""Tests for nbodykit_tpu.resilience.fleet — fleet survivability:
coordinated manifest-sealed checkpoints (all-or-nothing under injected
kills), the rank-scoped chaos-matrix fault grammar, SIGTERM preemption
inside a grace budget, the live heartbeat failure detector, and
shrink-to-survive shard repartitioning (8-rank state resumed on 4
ranks reproduces the FFTPower bit-for-bit).  The slow 2-process test
drives the full kill -> detect -> re-form -> resume choreography over
real gloo collectives."""

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY, read_trace
from nbodykit_tpu.resilience import (DEAD_RANK_EXIT, PREEMPTED_EXIT,
                                     FleetCheckpointStore, FleetMonitor,
                                     CheckpointStore, Preempted,
                                     check_preemption, clear_preemption,
                                     fault_point,
                                     install_preemption_handler,
                                     parse_spec, preemption_requested,
                                     reassemble, repartition,
                                     reset_faults, scan_liveness,
                                     uninstall_preemption_handler)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      '_multihost_worker.py')


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    """Registry, tracer, fault counts, preemption state and the fleet
    rank env are process-wide; every test sees (and leaves) a pristine
    copy."""
    saved = _global_options.copy()
    monkeypatch.delenv('NBKIT_FLEET_RANK', raising=False)
    monkeypatch.delenv('NBKIT_FLEET_SIZE', raising=False)
    REGISTRY.reset()
    reset_faults()
    clear_preemption()
    yield
    uninstall_preemption_handler()
    clear_preemption()
    REGISTRY.reset()
    reset_faults()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _counter(name):
    snap = REGISTRY.snapshot().get(name)
    return snap['value'] if snap else 0


# ---------------------------------------------------------------------------
# chaos-matrix fault grammar

def test_parse_spec_rank_scoped_rules():
    got = parse_spec('rank1@bench.rep:sigkill,'
                     'rank0@ckpt.manifest@2:sigterm,'
                     'bench.rep@2:kill')
    # rank-less rules keep their 3-tuple shape (back compat); rank-
    # scoped rules carry the rank as a 4th element
    assert got == [('bench.rep', 1, 'sigkill', 1),
                   ('ckpt.manifest', 2, 'sigterm', 0),
                   ('bench.rep', 2, 'kill')]
    with pytest.raises(ValueError):
        parse_spec('rank1@p@2:explode')


def test_rank_scoped_fault_fires_only_on_matching_rank(monkeypatch):
    """All ranks COUNT the targeted point (rank-uniform bookkeeping);
    only the matching rank acts — the collective sequence on survivors
    never branches."""
    monkeypatch.setenv('NBKIT_FLEET_RANK', '0')
    with nbodykit_tpu.set_options(faults='rank1@p@1:unavailable'):
        reset_faults()
        fault_point('p')                     # rank 0: counted, no fire
    monkeypatch.setenv('NBKIT_FLEET_RANK', '1')
    with nbodykit_tpu.set_options(faults='rank1@p@1:unavailable'):
        reset_faults()
        with pytest.raises(Exception, match='UNAVAILABLE'):
            fault_point('p')


def test_sigterm_fault_requests_preemption():
    """The ``sigterm`` action delivers a real SIGTERM to this process
    and RETURNS — the run continues to its next safe point, which
    raises :class:`Preempted` with grace still on the clock."""
    install_preemption_handler(grace_s=60.0)
    assert not preemption_requested()
    with nbodykit_tpu.set_options(faults='p@1:sigterm'):
        reset_faults()
        fault_point('p')                     # delivers + returns
    deadline = time.time() + 5.0
    while not preemption_requested() and time.time() < deadline:
        time.sleep(0.01)                     # handler is async-deferred
    assert preemption_requested()
    with pytest.raises(Preempted, match='grace left'):
        check_preemption('test.safe.point')
    assert _counter('resilience.preempted') == 1


# ---------------------------------------------------------------------------
# coordinated checkpoints: shard + manifest seal

def _save_ranked(store, key, seq, nranks, full):
    """Commit ``full`` split into ``nranks`` slabs as one sealed seq."""
    blocks = np.array_split(full, nranks, axis=0)
    for r in range(nranks):
        store.save_shard(key, seq, r, nranks,
                         {'completed': seq}, arrays={'f': blocks[r]})
    store.seal(key, seq, nranks=nranks, rank=0)


def test_fleet_save_seal_load_roundtrip(tmp_path):
    store = FleetCheckpointStore(tmp_path)
    full = np.arange(16.0 * 4 * 4, dtype='f4').reshape(16, 4, 4)
    _save_ranked(store, 'k', 1, 4, full)
    man = store.latest_manifest('k')
    assert man['seq'] == 1 and man['nranks'] == 4
    assert len(man['shards']) == 4
    # same rank count: the shard exactly as saved, no re-formation
    state, arrays, info = store.load('k', rank=2, nranks=4)
    assert state == {'completed': 1}
    np.testing.assert_array_equal(arrays['f'], full[8:12])
    assert info == {'seq': 1, 'nranks': 4, 'reformed': False}
    # full reassembly matches the original
    state, arrays, man2 = store.load_full('k')
    np.testing.assert_array_equal(arrays['f'], full)
    assert man2['seq'] == 1


def test_shrink_repartition_fftpower_equivalence(tmp_path):
    """ISSUE acceptance: an 8-rank sealed checkpoint resumed on 4
    ranks reassembles the identical field — the FFT power spectrum of
    the re-formed mesh matches the original bit-for-bit."""
    store = FleetCheckpointStore(tmp_path)
    rng = np.random.RandomState(42)
    full = rng.uniform(size=(16, 16, 16)).astype('f4')
    _save_ranked(store, 'fleet.k', 1, 8, full)
    parts = []
    for r in range(4):
        state, arrays, info = store.load('fleet.k', rank=r, nranks=4)
        assert info['reformed'] is True
        assert info['reformed_from'] == 8 and info['reformed_to'] == 4
        parts.append(arrays['f'])
    rebuilt = reassemble([{'f': p} for p in parts])['f']
    np.testing.assert_array_equal(rebuilt, full)
    # P(k) proxy: binned |FFT|^2 must agree exactly
    def power(field):
        c = np.fft.rfftn(field)
        return np.abs(c) ** 2
    np.testing.assert_array_equal(power(rebuilt), power(full))
    assert _counter('resilience.fleet.reformed') == 4


def test_repartition_uneven_and_identity():
    blocks = [np.arange(6.0).reshape(3, 2), np.arange(4.0).reshape(2, 2)]
    full = np.concatenate(blocks, axis=0)
    again = repartition([{'x': b} for b in blocks], 2)
    np.testing.assert_array_equal(
        np.concatenate([p['x'] for p in again], axis=0), full)
    solo = repartition([{'x': b} for b in blocks], 1)
    np.testing.assert_array_equal(solo[0]['x'], full)


def test_manifest_seal_atomic_under_sigkill(tmp_path):
    """A SIGKILL between shard commit and manifest seal (injected at
    the pre-rename ``ckpt.manifest`` fault point) leaves the PREVIOUS
    sealed manifest authoritative — all-or-nothing."""
    script = r"""
import os, sys
import numpy as np
sys.path.insert(0, %r)
import nbodykit_tpu
from nbodykit_tpu.resilience import FleetCheckpointStore
# the SECOND manifest write dies between the tmp write and the rename
nbodykit_tpu.set_options(faults='ckpt.manifest@2:kill')
st = FleetCheckpointStore(%r)
for seq in (1, 2):
    st.save_shard('k', seq, 0, 1, {'completed': seq},
                  arrays={'f': np.full(4, seq, 'f4')})
    st.seal('k', seq, nranks=1, rank=0)   # seq 2: SIGKILLed mid-seal
raise SystemExit('unreachable')
""" % (REPO, str(tmp_path))
    proc = subprocess.run([sys.executable, '-c', script],
                          capture_output=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    store = FleetCheckpointStore(tmp_path)
    man = store.latest_manifest('k')
    assert man is not None and man['seq'] == 1, \
        'previous sealed manifest lost to a mid-seal kill'
    state, arrays, _ = store.load_full('k')
    np.testing.assert_array_equal(arrays['f'], np.full(4, 1, 'f4'))
    sv = store.survey()
    assert sv['sealed'] == 1
    # seq 2's shards are visible as INCOMPLETE (kill debris), and a
    # relaunch never reuses the torn seq
    assert sv['families']['k']['incomplete'] == [2]
    assert store.next_seq('k') == 3


def test_seal_refuses_missing_shard(tmp_path):
    from nbodykit_tpu.resilience import FleetSealError
    store = FleetCheckpointStore(tmp_path)
    store.save_shard('k', 1, 0, 2, {'completed': 1},
                     arrays={'f': np.ones(2, 'f4')})
    # rank 1's shard never landed: the seal must refuse on every rank
    with pytest.raises(FleetSealError, match='rank 1'):
        store.seal('k', 1, nranks=2, rank=0)
    assert store.latest_manifest('k') is None
    assert _counter('resilience.fleet.seal_failed') == 1


# ---------------------------------------------------------------------------
# retention

def test_checkpoint_store_gc_tmp(tmp_path):
    st = CheckpointStore(tmp_path)
    st.save('k', {'completed': 1})
    orphan = os.path.join(tmp_path, 'k.ckpt.json.tmp.999')
    with open(orphan, 'w') as f:
        f.write('{"torn":')
    old = time.time() - 7200
    os.utime(orphan, (old, old))
    assert st.orphan_tmp(max_age_s=3600) == [orphan]
    assert st.gc_tmp(max_age_s=3600) == 1
    assert not os.path.exists(orphan)
    assert st.load('k') is not None          # real checkpoint untouched


def test_fleet_gc_keeps_last_k_and_drops_debris(tmp_path):
    store = FleetCheckpointStore(tmp_path, keep=2)
    full = np.arange(8.0, dtype='f4').reshape(4, 2)
    for seq in (1, 2, 3, 4):
        _save_ranked(store, 'k', seq, 2, full * seq)
    # unsealed debris OLDER than the newest seal (a torn seq a later
    # relaunch already superseded)
    store.save_shard('k', 3, 1, 2, {'junk': True},
                     arrays={'f': full[:2]})
    os.remove(os.path.join(tmp_path, 'k.m0003.manifest.json'))
    removed = store.gc()
    assert removed['manifests'] >= 1         # seqs 1 (and torn 3) gone
    sv = store.survey()
    assert sv['families']['k']['sealed'] == [2, 4]
    assert sv['families']['k']['incomplete'] == []
    # the newest sealed seq still loads in full
    state, arrays, man = store.load_full('k')
    assert man['seq'] == 4
    np.testing.assert_array_equal(arrays['f'], full * 4)


# ---------------------------------------------------------------------------
# live failure detection

def _write_trace(dirpath, pid, beats, iv=0.25, rank=None,
                 preempted_at=None):
    """A synthetic per-process trace file: meta + hb records (+ an
    optional clean preemption announcement)."""
    os.makedirs(dirpath, exist_ok=True)
    recs = [{'t': 'meta', 'version': 1, 'pid': pid, 'ts': beats[0],
             'heartbeat_s': iv,
             **({'rank': rank} if rank is not None else {})}]
    for ts in beats:
        recs.append({'t': 'hb', 'pid': pid, 'ts': ts, 'iv': iv,
                     **({'rank': rank} if rank is not None else {})})
    if preempted_at is not None:
        recs.append({'t': 'span', 'name': 'resilience.preempted',
                     'pid': pid, 'ts': preempted_at, 'dur': 0.0,
                     'depth': 0})
    with open(os.path.join(dirpath, 'trace-%d.jsonl' % pid), 'w') as f:
        for r in recs:
            f.write(json.dumps(r) + '\n')


def test_scan_liveness_thresholds(tmp_path):
    t0 = 1000.0
    d = str(tmp_path)
    # rank 0: beating until "now" — alive
    _write_trace(d, 101, [t0 + 0.25 * i for i in range(40)], rank=0)
    # rank 1: stopped 5 s ago — dead at any sane threshold
    _write_trace(d, 102, [t0 + 0.25 * i for i in range(20)], rank=1)
    # rank 2: stopped, but announced a clean preemption — never dead
    _write_trace(d, 103, [t0 + 0.25 * i for i in range(12)], rank=2,
                 preempted_at=t0 + 3.0)
    # rank 3: no heartbeats at all — no liveness claim
    _write_trace(d, 104, [t0], iv=0)
    now = t0 + 10.0
    by_pid = {e['pid']: e for e in scan_liveness(d, gap_s=1.5, now=now)}
    assert by_pid[101]['dead'] is False
    assert by_pid[102]['dead'] is True
    assert by_pid[102]['rank'] == 1
    assert by_pid[102]['gap_s'] == pytest.approx(10.0 - 4.75)
    assert by_pid[103]['dead'] is False
    assert by_pid[103]['preempted'] is True
    assert by_pid[104]['dead'] is None
    # below the threshold nobody is dead
    by_pid = {e['pid']: e
              for e in scan_liveness(d, gap_s=1.5, now=t0 + 5.5)}
    assert by_pid[102]['dead'] is False
    # default threshold = max(3*iv, 2 s)
    by_pid = {e['pid']: e for e in scan_liveness(d, now=now)}
    assert by_pid[102]['dead'] is True


def test_fleet_monitor_declares_once_and_calls_back(tmp_path):
    t0 = time.time()
    d = str(tmp_path)
    _write_trace(d, 201, [t0 - 5.0 + 0.25 * i for i in range(12)],
                 rank=1)
    deaths = []
    mon = FleetMonitor(d, gap_s=1.5, on_dead=deaths.append)
    mon._t0 = t0 - 10.0                       # rank died on our watch
    entries = mon.check_once(now=t0)
    assert [e['pid'] for e in mon.dead] == [201]
    assert deaths[0]['rank'] == 1
    assert _counter('resilience.fleet.dead_ranks') == 1
    # a second scan does not re-declare
    mon.check_once(now=t0 + 1.0)
    assert len(mon.dead) == 1
    assert any(e['pid'] == 201 for e in entries)


def test_fleet_monitor_ignores_stale_traces(tmp_path):
    """A trace file from an earlier incarnation (last record already
    older than start - gap when the monitor began) must not be
    declared — only deaths on this monitor's watch count."""
    t0 = time.time()
    d = str(tmp_path)
    _write_trace(d, 301, [t0 - 60.0 + 0.25 * i for i in range(4)])
    mon = FleetMonitor(d, gap_s=1.5)
    mon._t0 = t0                              # watch starts NOW
    mon.check_once(now=t0 + 2.0)
    assert mon.dead == []
    assert _counter('resilience.fleet.dead_ranks') == 0


# ---------------------------------------------------------------------------
# preempted-vs-silent in the post-mortem analyzer

def test_analyze_distinguishes_preempted_from_silent(tmp_path):
    from nbodykit_tpu.diagnostics.analyze import (heartbeat_report,
                                                  load_processes)
    t0 = 2000.0
    d = str(tmp_path)
    # pid 1: beats the whole window (defines the trace end)
    _write_trace(d, 1, [t0 + 0.25 * i for i in range(80)])
    # pid 2: silent death — heartbeats stop, no announcement
    _write_trace(d, 2, [t0 + 0.25 * i for i in range(10)])
    # pid 3: preempted — same gap, but announced cleanly
    _write_trace(d, 3, [t0 + 0.25 * i for i in range(10)],
                 preempted_at=t0 + 2.5)
    procs, _ = load_processes(d)
    hb = heartbeat_report(procs, {})
    assert hb['2']['silent'] is True and hb['2']['preempted'] is False
    assert hb['3']['silent'] is False and hb['3']['preempted'] is True
    from nbodykit_tpu.diagnostics.analyze import render_analysis, analyze
    text = render_analysis(analyze(d))
    assert 'PREEMPTED' in text
    assert re.search(r'SILENT.*\n.*\b2\b', text)


# ---------------------------------------------------------------------------
# serve: preemption drain with zero lost requests

def test_serve_preempt_drains_with_zero_lost():
    from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
    from nbodykit_tpu.resilience import fleet
    from nbodykit_tpu.serve import AnalysisRequest, AnalysisServer
    with use_mesh(cpu_mesh(1)):
        srv = AnalysisServer(per_task=1)
    tickets = [srv.submit(AnalysisRequest(nmesh=16, npart=500, seed=s))
               for s in range(3)]
    out = srv.preempt(grace_s=30.0)
    assert out['drained'] is True
    results = [srv.wait(t, timeout=5.0) for t in tickets]
    assert all(r is not None for r in results)
    summ = srv.summary()
    assert summ['lost'] == 0
    # every preemption eviction carries the structured verdict
    assert summ['preempted'] == sum(
        1 for r in results
        if (r.reason or {}).get('code') == 'preempted')
    # a submit AFTER the preemption notice is rejected as preempted,
    # not as a generic shutdown
    fleet._preempt['requested_at'] = time.time()
    try:
        late = srv.wait(srv.submit(
            AnalysisRequest(nmesh=16, npart=500, seed=9)), timeout=5.0)
    finally:
        clear_preemption()
    assert late.status == 'rejected'
    assert late.reason['code'] == 'preempted'
    srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# regress / doctor posture

def test_fleet_summary_counts_posture(tmp_path):
    from nbodykit_tpu.diagnostics.regress import fleet_summary
    out = fleet_summary(str(tmp_path))
    assert out['preempted_records'] == 0 and out['reformations'] == []
    with open(tmp_path / 'BENCH_STAGED.json', 'w') as f:
        json.dump({'results': {
            'a': {'metric': 'a', 'preempted': True},
            'b': {'metric': 'b', 'reformed_from': 8,
                  'reformed_to': 4}}}, f)
    store = FleetCheckpointStore(tmp_path / 'BENCH_CKPT')
    full = np.ones((4, 2), 'f4')
    _save_ranked(store, 'k', 1, 2, full)
    store.save_shard('k', 2, 0, 2, {'x': 1}, arrays={'f': full[:2]})
    out = fleet_summary(str(tmp_path))
    assert out['preempted_records'] == 1
    assert out['reformed_records'] == 1
    assert out['reformations'][0]['reformed_from'] == 8
    assert out['sealed_manifests'] == 1
    assert out['incomplete_seqs'] == 1


def test_doctor_fleet_line_warns_on_incomplete(tmp_path):
    import io
    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    store = FleetCheckpointStore(tmp_path / 'BENCH_CKPT')
    store.save_shard('k', 1, 0, 2, {'x': 1},
                     arrays={'f': np.ones(2, 'f4')})
    buf = io.StringIO()
    run_doctor(trace=None, root=str(tmp_path), out=buf,
               self_check_only=False)
    text = buf.getvalue()
    assert 'fleet        WARN' in text
    assert 'INCOMPLETE manifest' in text


def test_reform_decomposition_stamps():
    from nbodykit_tpu.parallel.runtime import reform_decomposition
    got = reform_decomposition(2, 1, ndev_per_rank=4)
    assert got['reformed_from'] == 2 and got['reformed_to'] == 1
    assert got['pencil_from'] == [2, 4] and got['pencil_to'] == [2, 2]


# ---------------------------------------------------------------------------
# acceptance: bench preempted by SIGTERM resumes with zero recomputed
# reps

def test_bench_preempt_then_resume_zero_recompute(tmp_path):
    """bench.py --config under ``bench.rep@2:sigterm``: the injected
    preemption notice lands entering rep 2; rep 1 is already sealed,
    so the run exits PREEMPTED_EXIT with a ``preempted`` staged record
    — and the relaunch resumes at rep 2 exactly (zero recomputed
    reps)."""
    env_base = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        BENCH_REPS='2', BENCH_PHASES='0',
        BENCH_PREEMPT_GRACE_S='60',
        BENCH_STAGED_PATH=str(tmp_path / 'STAGED.json'),
        BENCH_DETAIL_PATH=str(tmp_path / 'DETAIL.json'),
        BENCH_CKPT_DIR=str(tmp_path / 'CKPT'),
        BENCH_TRACE_DIR=str(tmp_path / 'TRACE'),
    )
    env_base.pop('NBKIT_FAULTS', None)
    bench = os.path.join(REPO, 'bench.py')
    env1 = dict(env_base, NBKIT_FAULTS='bench.rep@2:sigterm')
    p1 = subprocess.run([sys.executable, bench, '--config', '32',
                         '2000'], capture_output=True, timeout=560,
                        env=env1)
    assert p1.returncode == PREEMPTED_EXIT, p1.stderr.decode()[-2000:]
    staged = json.load(open(tmp_path / 'STAGED.json'))['results']
    (partial,) = staged.values()
    assert partial['stage'] == 'preempted'
    assert partial['preempted'] is True
    assert partial['completed_reps'] == 1
    # the announcement made it into the trace (preempted, not silent)
    records, _ = read_trace(str(tmp_path / 'TRACE'))
    names = {r.get('name') for r in records if r.get('t') == 'span'}
    assert 'resilience.preempted' in names

    p2 = subprocess.run([sys.executable, bench, '--config', '32',
                         '2000'], capture_output=True, timeout=560,
                        env=env_base)
    assert p2.returncode == 0, p2.stderr.decode()[-2000:]
    rec = json.loads(p2.stdout.decode().strip().splitlines()[-1])
    assert rec['resumed'] is True and rec['resumed_reps'] == 1
    assert rec['value'] > 0


# ---------------------------------------------------------------------------
# acceptance (slow): 2-process kill -> live detect -> shrink -> resume

@pytest.mark.slow
def test_fleet_kill_detect_reform_resume(tmp_path):
    """The full survivability choreography on a real 2-process gloo
    fleet: rank 1 is SIGKILLed entering rep 2 (after seq 1 sealed);
    rank 0's live monitor detects the dead peer within the gap
    threshold and exits DEAD_RANK_EXIT instead of wedging in the paint
    collective; the 1-process relaunch re-forms the mesh, repartitions
    the surviving shards, resumes at rep 2 — and the final power
    matches an uninterrupted single-process run."""
    trace = tmp_path / 'trace'
    ckpt = tmp_path / 'ckpt'
    record = tmp_path / 'rec.json'
    env = dict(os.environ,
               JAX_PLATFORMS='cpu',
               NBKIT_DIAGNOSTICS=str(trace),
               NBKIT_DIAGNOSTICS_HEARTBEAT='0.25',
               NBKIT_FLEET_DIR=str(ckpt),
               NBKIT_FLEET_RECORD=str(record),
               NBKIT_FLEET_GAP_S='1.5',
               NBKIT_FAULTS='rank1@bench.rep@2:sigkill')
    os.makedirs(ckpt)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, '127.0.0.1:12365', '2', str(i),
         'fleet'], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors='replace'))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    assert procs[1].returncode == -signal.SIGKILL, outs[1][-2000:]
    assert procs[0].returncode == DEAD_RANK_EXIT, outs[0][-2000:]
    # seq 1 sealed by both ranks before the kill
    store = FleetCheckpointStore(ckpt)
    man = store.latest_manifest('fleet.pipeline')
    assert man is not None and man['nranks'] == 2
    sealed_seq = man['seq']
    assert sealed_seq >= 1

    # shrink-to-survive relaunch: ONE process, no faults
    env2 = dict(env)
    env2.pop('NBKIT_FAULTS')
    p = subprocess.run([sys.executable, WORKER, 'none', '1', '0',
                        'fleet'], env=env2, capture_output=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr.decode()[-2000:]
    m = re.search(rb'FLEETRESULT (\d+) (\d+) (\S+) (\S+)', p.stdout)
    assert m, p.stdout[-2000:]
    total, p2v = float(m.group(3)), float(m.group(4))
    rec = json.load(open(record))
    assert rec['resumed'] is True
    assert rec['resumed_reps'] == sealed_seq
    assert rec['reformed_from'] == 2 and rec['reformed_to'] == 1

    # ...and the survivor's answer matches an uninterrupted run
    env3 = dict(env2, NBKIT_FLEET_DIR=str(tmp_path / 'ckpt-clean'),
                NBKIT_FLEET_RECORD=str(tmp_path / 'rec-clean.json'))
    os.makedirs(tmp_path / 'ckpt-clean')
    q = subprocess.run([sys.executable, WORKER, 'none', '1', '0',
                        'fleet'], env=env3, capture_output=True,
                       timeout=420)
    assert q.returncode == 0, q.stderr.decode()[-2000:]
    mq = re.search(rb'FLEETRESULT (\d+) (\d+) (\S+) (\S+)', q.stdout)
    np.testing.assert_allclose(total, float(mq.group(3)), rtol=1e-5)
    np.testing.assert_allclose(p2v, float(mq.group(4)), rtol=1e-4)

    # the dead rank is visible in rank 0's trace, with its rank stamp
    records, _ = read_trace(str(trace))
    dead = [r for r in records if r.get('t') == 'span'
            and r.get('name') == 'resilience.fleet.dead_rank']
    assert dead, 'no dead-rank event in the monitor trace'
    reform = [r for r in records if r.get('t') == 'span'
              and r.get('name') == 'resilience.fleet.reform']
    assert reform and reform[0]['attrs']['from'] == 2
