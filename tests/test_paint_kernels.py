"""Equivalence suite for every registered paint candidate.

The tuner can flip ``paint_method='auto'`` to ANY candidate in
tune/space.py, so each one must deposit exactly the same mesh as the
reference scatter kernel — across resamplers, wrap seams, halo/origin
offsets and the 8-device mesh. The candidate list here is the real
one (:func:`~nbodykit_tpu.tune.space.registered_paint_candidates`),
not a hand-kept copy: a new candidate is tested the moment it is
registered, or the parametrize list grows a hole.

Also the dropped-deposit observability contract (ISSUE 8): the eager
mxu bucket-overflow backoff must bump ``paint.dropped`` before it
heals, and the healed mesh must conserve mass.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import nbodykit_tpu
from nbodykit_tpu import _global_options
from nbodykit_tpu.diagnostics import REGISTRY
from nbodykit_tpu.ops.paint import (paint_local, paint_local_sorted,
                                    paint_local_segsum,
                                    paint_local_streams,
                                    paint_local_mxu)
from nbodykit_tpu.tune.space import registered_paint_candidates

# the real candidate list at the test shape (CPU process: no pallas
# candidate; all stream counts fit at mesh32)
CANDS = {c.name: c.options for c in registered_paint_candidates(32, 4000)}

# (n0l, N1, N2, p0, origin) — same geometry convention as
# tests/test_paint_mxu.py: interior block, origin-offset block, and a
# block whose halo-extended rows wrap the periodic boundary
GEOMETRIES = [
    (16, 16, 16, 16, 0),
    (12, 16, 16, 32, 5),
    (10, 24, 16, 64, 59),
]


@pytest.fixture(autouse=True)
def _clean_state():
    saved = _global_options.copy()
    REGISTRY.reset()
    yield
    REGISTRY.reset()
    _global_options.clear()
    _global_options.update(saved)


def _counter(name):
    snap = REGISTRY.snapshot().get(name)
    return snap['value'] if snap else 0


def _edge_positions(rng, n, n0l, p0, N1, N2, origin):
    """Positions slamming every hazard at once: x rows pinned to the
    block edges, the origin offset and the periodic seam (the
    n0l-boundary cases of ISSUE 8), y/z pinned to their wrap seams,
    plus a uniform fill."""
    pos = rng.uniform(0.0, p0, (n, 3))
    pos[:, 1] = rng.uniform(0.0, N1, n)
    pos[:, 2] = rng.uniform(0.0, N2, n)
    xedges = np.array([0.0, 0.3, p0 - 0.25, origin % p0,
                       (origin + 0.25) % p0,
                       (origin + n0l - 0.25) % p0,
                       (origin + n0l + 0.25) % p0])
    yedges = np.array([0.0, 0.25, N1 - 0.25])
    zedges = np.array([0.0, 0.25, N2 - 0.25])
    ne = min(n // 2, 7 * 8)
    pos[:ne, 0] = np.tile(xedges, -(-ne // len(xedges)))[:ne]
    pos[:ne, 1] = np.tile(yedges, -(-ne // len(yedges)))[:ne]
    pos[:ne, 2] = np.tile(zedges, -(-ne // len(zedges)))[:ne]
    return jnp.asarray(pos)


def _run_candidate(opts, pos, mass, shape, res, period, origin):
    """Invoke the LOCAL kernel a candidate's options select — with a
    non-default chunk where the candidate exercises a chunked loop, so
    the padded fori_loop paths are covered too."""
    method = opts['paint_method']
    args = (pos, mass, shape)
    kw = dict(resampler=res, period=period, origin=origin)
    if method == 'scatter':
        chunk = 97 if opts.get('paint_chunk_size') == 1024 * 1024 * 4 \
            else None
        return paint_local(*args, chunk=chunk, **kw)
    if method == 'sort':
        return paint_local_sorted(*args, **kw)
    if method == 'segsum':
        return paint_local_segsum(
            *args, order_method=opts.get('paint_order', 'argsort'),
            **kw)
    if method == 'streams':
        return paint_local_streams(
            *args, streams=opts['paint_streams'], chunk=101, **kw)
    if method == 'mxu':
        out, over = paint_local_mxu(
            *args, return_overflow=True,
            order_method=opts.get('paint_order', 'auto'),
            deposit='xla', **kw)
        assert int(over) == 0
        return out
    raise AssertionError('unknown candidate method %r' % method)


@pytest.mark.parametrize('res', ['cic', 'tsc'])
@pytest.mark.parametrize('name', sorted(CANDS))
def test_local_kernel_equivalence(name, res):
    rng = np.random.default_rng(42)
    for (n0l, N1, N2, p0, origin) in GEOMETRIES:
        shape, period = (n0l, N1, N2), (p0, N1, N2)
        pos = _edge_positions(rng, 400, n0l, p0, N1, N2, origin)
        mass = jnp.asarray(rng.uniform(0.5, 2.0, 400))
        ref = paint_local(pos, mass, shape, resampler=res,
                          period=period, origin=origin)
        got = _run_candidate(CANDS[name], pos, mass, shape, res,
                             period, origin)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12,
                                   err_msg='%s %s %r' % (name, res,
                                                         shape))


@pytest.mark.parametrize('name', sorted(CANDS))
def test_multi_device_equivalence(name, cpu8):
    """Every candidate, end to end through ``pm.paint`` on the
    8-device mesh: allclose to the scatter oracle, exact mass
    conservation, and bit-identical across repeated paints (the
    determinism claim a tuner A/B relies on)."""
    from nbodykit_tpu.pmesh import ParticleMesh
    rng = np.random.default_rng(7)
    n = 500
    pos = rng.uniform(0.0, 64.0, (n, 3))
    # pin a band to the inter-device slab boundaries (n0_cell = 4
    # cells per device at Nmesh=32 / box 64) and the periodic seam
    slab = 64.0 / 8
    edges = np.array([0.0, 0.01, slab, slab - 0.01, 3 * slab,
                      63.99, 5 * slab + 0.01, 7 * slab])
    pos[:len(edges) * 4, 0] = np.tile(edges, 4)
    spos = jnp.asarray(pos)
    pm = ParticleMesh(Nmesh=32, BoxSize=64.0, dtype='f8', comm=cpu8)

    # one jitted program per candidate: options are read at trace
    # time, and the persistent compile cache keeps re-runs cheap.
    # return_dropped satisfies the traced-mxu overflow contract; the
    # count must come back zero for every candidate here.
    def painted(options):
        with nbodykit_tpu.set_options(**options):
            fn = jax.jit(lambda p: pm.paint(p, 1.0,
                                            return_dropped=True))
            mesh, dropped = fn(spos)
            again, _ = fn(spos)
        assert int(dropped) == 0
        # bit-identical: same program, same inputs, same mesh
        np.testing.assert_array_equal(np.asarray(mesh),
                                      np.asarray(again))
        return mesh
    ref = painted({'paint_method': 'scatter'})
    got = painted(CANDS[name])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-12, atol=1e-12)
    assert np.isclose(float(jnp.sum(got)), n, rtol=1e-10)


def test_streams_candidates_capped_by_memory_plan():
    """Stream counts whose replica meshes blow the 0.85xHBM budget at
    the trial shape are EXCLUDED from the space (ISSUE 8 acceptance:
    the 1024^3 staged ladder must stay inside budget)."""
    from nbodykit_tpu.pmesh import memory_plan
    small = [c.name for c in registered_paint_candidates(64, 10_000)]
    assert {'streams2', 'streams4', 'streams8'} <= set(small)
    big = [c.name for c in registered_paint_candidates(1024, int(1e8))]
    assert 'scatter' in big and 'segsum-argsort' in big
    for name in big:
        if name.startswith('streams'):
            k = int(name[len('streams'):])
            assert memory_plan(1024, 1e8, paint_method='streams',
                               paint_streams=k)['fits']
    # at 16 GB HBM even k=2 replicas do not fit next to the 1024^3
    # field: every stream count is excluded there
    assert not memory_plan(1024, 1e8, paint_method='streams',
                           paint_streams=2)['fits']
    assert 'streams8' not in big


def test_mxu_dropped_counter_and_backoff():
    """Overflowing a tiny mxu Kcap eagerly: the backoff ladder heals
    the mesh, and each failed attempt lands in the ``paint.dropped``
    counter BEFORE the retry (the observability satellite of
    ISSUE 8)."""
    from nbodykit_tpu.pmesh import ParticleMesh
    rng = np.random.default_rng(3)
    n = 3000
    # every particle in one cell: one tile bucket holds all n, so a
    # slack of 0.01 makes Kcap provably too small on the first try
    pos = jnp.asarray(rng.uniform(4.0, 4.9, (n, 3)))
    pm = ParticleMesh(Nmesh=16, BoxSize=16.0, dtype='f8')
    with nbodykit_tpu.set_options(paint_method='mxu',
                                  paint_bucket_slack=0.01):
        out = pm.paint(pos, 1.0)
    assert _counter('paint.dropped') > 0
    assert np.isclose(float(jnp.sum(out)), n, rtol=1e-10)
