"""The interprocedural dataflow engine (nbkl v2): NBK103
collective-order deadlock detection and the NBK5xx static
HBM/donation analysis — seeded positives and negatives, the symbolic
peak model against the documented dfft buffer contracts, the baseline
roundtrip for the new codes, the --stats / --memory-report CLI
surfaces, and the doctor's NBK5xx <-> device-watermark cross-link.

Pure-host AST tests except the CLI subprocess and doctor checks.
"""

import json
import os
import subprocess
import sys
import textwrap

from nbodykit_tpu import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_str(src, select=None, memory_config=None):
    return lint.lint_source(
        'fixture.py', textwrap.dedent(src),
        project_constants={'AXIS': 'dev'}, select=select,
        memory_config=memory_config)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# NBK103 — collective-order deadlock detection

def test_nbk103_rank_divergent_sequences():
    # BOTH arms emit collectives, in different orders — NBK102 has no
    # opinion (no arm skips them), NBK103 must still flag the order
    fs = lint_str("""
    import jax

    def step(x):
        rank = jax.process_index()
        if rank == 0:
            x = jax.lax.psum(x, 'dev')
            x = jax.lax.all_gather(x, 'dev')
        else:
            x = jax.lax.all_gather(x, 'dev')
            x = jax.lax.psum(x, 'dev')
        return x
    """, select=['NBK103'])
    assert codes(fs) == ['NBK103']
    assert 'rank' in fs[0].message


def test_nbk103_exception_path_between_collectives():
    fs = lint_str("""
    import jax

    def pipeline(x, n):
        x = jax.lax.psum(x, 'dev')
        if n < 0:
            raise ValueError('bad shard')
        return jax.lax.all_to_all(x, 'dev', 0, 0)
    """, select=['NBK103'])
    assert codes(fs) == ['NBK103']
    assert 'strands its peers' in fs[0].message


def test_nbk103_matched_sequences_negative():
    # rank-dependent VALUES but identical collective sequences on
    # both arms: every rank emits the same program — clean
    fs = lint_str("""
    import jax

    def step(x):
        rank = jax.process_index()
        if rank == 0:
            x = jax.lax.psum(x * 2, 'dev')
        else:
            x = jax.lax.psum(x, 'dev')
        return x
    """, select=['NBK103'])
    assert fs == []


def test_nbk103_unconditional_raise_is_clean():
    # validation BEFORE the first collective is the recommended
    # pattern and must not fire
    fs = lint_str("""
    import jax

    def pipeline(x, n):
        if n < 0:
            raise ValueError('bad input')
        x = jax.lax.psum(x, 'dev')
        return jax.lax.all_to_all(x, 'dev', 0, 0)
    """, select=['NBK103'])
    assert fs == []


def test_nbk103_interprocedural_through_helper():
    # the collective hides in a helper: NBK103's summaries splice the
    # callee sequence into the rank-gated branch
    fs = lint_str("""
    import jax

    def reduce_all(x):
        return jax.lax.psum(x, 'dev')

    def run(x):
        rank = jax.process_index()
        if rank == 0:
            x = reduce_all(x)
        return x
    """, select=['NBK103'])
    assert codes(fs) == ['NBK103']


def test_nbk103_cross_module(tmp_path):
    # rank gate in one module, collective in another — beyond
    # NBK102's same-module reach
    pkg = tmp_path / 'pkg'
    pkg.mkdir()
    (pkg / 'helpers.py').write_text(textwrap.dedent("""
        import jax

        def reduce_all(x):
            return jax.lax.psum(x, 'dev')
    """))
    (pkg / 'driver.py').write_text(textwrap.dedent("""
        import jax
        from helpers import reduce_all

        def run(x):
            rank = jax.process_index()
            if rank == 0:
                x = reduce_all(x)
            return x
    """))
    fs = lint.lint_paths([str(pkg)], select=['NBK103'])
    assert codes(fs) == ['NBK103']
    assert fs[0].path.endswith('driver.py')


def test_nbk103_data_divergence_in_traced_code():
    fs = lint_str("""
    import jax

    @jax.jit
    def body(x):
        if x.sum() > 0:
            x = jax.lax.psum(x, 'dev')
        return x
    """, select=['NBK103'])
    assert codes(fs) == ['NBK103']
    assert 'traced-data' in fs[0].message


# ---------------------------------------------------------------------------
# NBK501/502 — donation analysis

_DONATION_HEADER = """
    import jax
    import jax.numpy as jnp

    def power(field):
        return jnp.abs(field) ** 2
"""


def test_nbk501_missed_donation():
    fs = lint_str(_DONATION_HEADER + """
    fast_power = jax.jit(power)

    def run(pm, pos):
        field = pm.paint(pos)
        p3 = fast_power(field)
        return p3.sum()
    """, select=['NBK5'])
    assert codes(fs) == ['NBK501']
    assert "'field'" in fs[0].message
    assert 'donate_argnums=(0,)' in fs[0].hint


def test_nbk501_silent_when_value_still_needed():
    # the field is read after the call: donation would be wrong, so
    # NBK501 must NOT ask for it
    fs = lint_str(_DONATION_HEADER + """
    fast_power = jax.jit(power)

    def run(pm, pos):
        field = pm.paint(pos)
        p3 = fast_power(field)
        return p3.sum() + field.sum()
    """, select=['NBK5'])
    assert fs == []


def test_nbk502_donated_but_held_live():
    fs = lint_str(_DONATION_HEADER + """
    fast_power = jax.jit(power, donate_argnums=(0,))

    def run(pm, pos):
        field = pm.paint(pos)
        p3 = fast_power(field)
        return p3.sum() + field.sum()
    """, select=['NBK5'])
    assert codes(fs) == ['NBK502']
    assert 'defeats the aliasing' in fs[0].message


def test_nbk502_loop_reuse_of_donated_buffer():
    # donated inside a loop while the buffer was built outside it:
    # iteration 2 reads a buffer iteration 1 donated away
    fs = lint_str(_DONATION_HEADER + """
    fast_power = jax.jit(power, donate_argnums=(0,))

    def run(pm, pos, reps):
        field = pm.paint(pos)
        out = []
        for _ in range(reps):
            out.append(fast_power(field))
        return out
    """, select=['NBK5'])
    assert codes(fs) == ['NBK502']


def test_nbk502_donated_accumulator_is_clean():
    # the dfft donated-accumulator idiom: y = upd(y, ...) rebinds the
    # handle every iteration — exactly one owner, no finding
    fs = lint_str("""
    import jax
    import jax.numpy as jnp

    def upd(dst, i):
        return dst.at[i].set(i)

    fast_upd = jax.jit(upd, donate_argnums=(0,))

    def run(pm, pos, n):
        y = pm.paint(pos)
        for i in range(n):
            y = fast_upd(y, i)
        return y
    """, select=['NBK5'])
    assert fs == []


def test_donation_clean_chain_negative():
    fs = lint_str(_DONATION_HEADER + """
    fast_power = jax.jit(power, donate_argnums=(0,))

    def run(pm, pos):
        field = pm.paint(pos)
        p3 = fast_power(field)
        return p3.sum()
    """, select=['NBK5'])
    assert fs == []


def test_labeled_taint_does_not_leak_through_timers():
    # a helper returning wall-clock floats must not inherit the mesh
    # size of its field argument (the labeled-taint regression that
    # motivated ret_params)
    fs = lint_str(_DONATION_HEADER + """
    import time

    def timeit(fn, arg):
        t0 = time.time()
        fn(arg)
        return time.time() - t0

    fast_power = jax.jit(power)

    def run(pm, pos):
        field = pm.paint(pos)
        dt = timeit(fast_power, field)
        dt2 = dt * 2
        return dt2
    """, select=['NBK5'])
    # 'dt' is not mesh-sized, so no donation findings are raised on
    # later uses of it; the field itself is consumed by an untracked
    # callee (timeit) so no NBK501 either
    assert fs == []


# ---------------------------------------------------------------------------
# NBK503 — symbolic peak vs the memory_plan budget

def test_nbk503_symbolic_peak_over_budget():
    config = lint.make_config(1024, dtype_bytes=4, hbm_bytes=16e9)
    fs = lint_str("""
    import jax.numpy as jnp

    def stage_chain(pm, pos):
        a = pm.paint(pos)
        b = pm.r2c(a)
        c = b * 2.0
        d = jnp.abs(c) ** 2
        return a.sum() + d.sum()
    """, select=['NBK503'], memory_config=config)
    assert codes(fs) == ['NBK503']
    assert 'memory_plan budget' in fs[0].message


def test_nbk503_silent_without_config_and_under_budget():
    src = """
    import jax.numpy as jnp

    def stage_chain(pm, pos):
        a = pm.paint(pos)
        b = pm.r2c(a)
        c = b * 2.0
        d = jnp.abs(c) ** 2
        return a.sum() + d.sum()
    """
    assert lint_str(src, select=['NBK503']) == []
    small = lint.make_config(256, dtype_bytes=4, hbm_bytes=16e9)
    assert lint_str(src, select=['NBK503'], memory_config=small) == []


def test_nbk503_shell_filtered_fields_are_mesh_taint():
    """ISSUE 20 satellite: each per-shell filtered field of the
    bispectrum estimator (algorithms/bispectrum.py) is a full real
    mesh, so ``shell_filtered_field`` must be a recognized producer.
    The fixture pair: the streaming triple-product (3 shell fields
    live — the memory_plan(workload='bispectrum') contract) FITS the
    declared budget; naively holding a field per shell EXCEEDS it —
    if the producer classification regresses, the second assertion
    catches the silent under-report."""
    src = """
    import jax.numpy as jnp

    def triple_streams(pm, cplx):
        d1 = shell_filtered_field(pm, cplx, 1, 4)
        d2 = shell_filtered_field(pm, cplx, 4, 9)
        d3 = shell_filtered_field(pm, cplx, 9, 16)
        return (d1 * d2 * d3).sum()

    def shells_exceed(pm, cplx):
        d0 = shell_filtered_field(pm, cplx, 1, 4)
        d1 = shell_filtered_field(pm, cplx, 4, 9)
        d2 = shell_filtered_field(pm, cplx, 9, 16)
        d3 = shell_filtered_field(pm, cplx, 16, 25)
        d4 = shell_filtered_field(pm, cplx, 25, 36)
        d5 = shell_filtered_field(pm, cplx, 36, 49)
        return (d0 * d1 * d2 * d3 * d4 * d5).sum()
    """
    # 1 unit = 4.29 GB; budget 0.85*28 GB = 23.8 GB: the streaming
    # triple (2 live + 3 internal = 5 units = 21.5 GB) fits, the
    # per-shell pile-up (5 live + 3 internal = 8 units = 34.4 GB)
    # does not
    config = lint.make_config(1024, dtype_bytes=4, hbm_bytes=28e9)
    fs = lint_str(src, select=['NBK503'], memory_config=config)
    assert codes(fs) == ['NBK503']
    assert 'shells_exceed' in fs[0].message
    assert 'triple_streams' not in ' '.join(f.message for f in fs)


def test_nbk503_grad_call_site_prices_the_backward_pass():
    """ISSUE 19 satellite: ``jax.grad(f)`` holds f's intermediates as
    residuals for the backward pass, so a grad call site must add f's
    internal peak once more.  The fixture pair: the forward-only
    pipeline FITS the declared budget; the identical pipeline under
    ``jax.grad`` EXCEEDS it — if the grad accounting regresses to
    zero, the second assertion catches the silent under-report."""
    src = """
    import jax
    import jax.numpy as jnp

    def loss(pm, x):
        a = pm.paint(x)
        b = pm.r2c(a)
        return jnp.abs(b).sum()

    def forward_fits(pm):
        w = pm.generate_whitenoise(0)
        return loss(pm, w)

    def grad_exceeds(pm):
        w = pm.generate_whitenoise(0)
        g = jax.grad(loss, argnums=1)(pm, w)
        return g.sum()
    """
    # 1 unit = 4.29 GB; budget 0.85*28 GB = 23.8 GB: the forward
    # pipeline (5 units = 21.5 GB) fits, the grad pipeline (forward
    # + residuals + live leaves = 10 units = 42.9 GB) does not
    config = lint.make_config(1024, dtype_bytes=4, hbm_bytes=28e9)
    fs = lint_str(src, select=['NBK503'], memory_config=config)
    assert codes(fs) == ['NBK503']
    assert 'grad_exceeds' in fs[0].message
    # the named-wrapper spelling (vg = jit(value_and_grad(f)); vg(x))
    # prices the same residuals — not only the immediate form
    named = """
    import jax
    import jax.numpy as jnp

    def loss(pm, x):
        a = pm.paint(x)
        b = pm.r2c(a)
        return jnp.abs(b).sum()

    def grad_named(pm):
        w = pm.generate_whitenoise(0)
        vg = jax.jit(jax.value_and_grad(loss, argnums=1))
        val, g = vg(pm, w)
        return g.sum()
    """
    fs2 = lint_str(named, select=['NBK503'], memory_config=config)
    assert codes(fs2) == ['NBK503']
    assert 'grad_named' in fs2[0].message
    # (11 units for the named form: the value_and_grad closure object
    # is a live leaf alongside the residuals)


# ---------------------------------------------------------------------------
# the symbolic peak model against the documented dfft buffer contracts

def _project_summaries(paths):
    from nbodykit_tpu.lint.sizes import analysis_for
    project, parse = lint.build_project(paths)
    assert parse == []
    an = analysis_for(project)
    out = {}
    import ast
    for ctx, fn in project.functions():
        if isinstance(fn, ast.Lambda):
            continue
        out[(ctx.canonical, fn.name)] = an.summary_of(fn)
    return out


def test_pencil_stages_summarize_cleanly():
    """ISSUE 9 satellite: the pencil drivers' inner/outer all_to_all
    pair must stay legible to the NBK103 dataflow engine — each stage
    closure of _pencil_programs (forward and inverse) summarizes to
    exactly one all_to_all token, nothing in dfft.py degrades to the
    VARIED sentinel, and the module lints clean for NBK103."""
    import ast
    from nbodykit_tpu.lint.collectives import analysis_for, VARIED
    path = os.path.join(REPO, 'nbodykit_tpu', 'parallel', 'dfft.py')
    project, parse = lint.build_project([path])
    assert parse == []
    an = analysis_for(project)
    stages = []
    for ctx, fn in project.functions():
        summ = an.summary_of(fn)
        name = getattr(fn, 'name', '<lambda>')
        assert summ is not VARIED, \
            '%s degraded to VARIED — the deadlock comparisons go ' \
            'silent over the pencil transposes' % name
        if name in ('stage1', 'stage2'):
            stages.append((name, summ))
    # two pencil programs (forward + inverse), two stages each, one
    # all_to_all per stage: the inner ('y') and outer ('x') transposes.
    # The integrity-guarded variant adds a psum (fold checksum) after
    # the wire — still one deterministic collective program per arm.
    allowed = (frozenset({('all_to_all',)}),
               frozenset({('all_to_all',), ('all_to_all', 'psum')}))
    assert len(stages) == 4
    for name, summ in stages:
        assert summ in allowed, (name, summ)
    findings = lint.lint_paths([path], select=['NBK103'])
    assert [f for f in findings if f.code == 'NBK103'] == []


def test_dfft_lowmem_contract_is_machine_checked():
    """PR 4 documented the lowmem drivers at ~2 full-mesh buffers and
    the dist_* entry points at ~3 (driver's 2 + the caller-held input
    ref, which the model books to the caller).  The symbolic peak
    model now derives those numbers from the source — the contract is
    machine-checked, not prose."""
    s = _project_summaries([os.path.join(REPO, 'nbodykit_tpu',
                                         'parallel', 'dfft.py')])
    dfft = 'nbodykit_tpu/parallel/dfft.py'
    for driver in ('rfftn_single_lowmem', 'irfftn_single_lowmem',
                   'fftn_c2c_single_lowmem'):
        assert s[(dfft, driver)].peak == 2.0, driver
    # entry points: 2 units internal; the caller's live input ref is
    # the documented third buffer (params are booked to callers)
    assert s[(dfft, 'dist_rfftn')].peak == 2.0
    assert s[(dfft, 'dist_irfftn')].peak == 2.0


def test_bench_staged_ladder_peak_vs_fused():
    """The acceptance check for the staged-ladder donation work: at
    the 1024-cubed config the donated staged chain (run_once /
    paint_fft) peaks at 2 full-mesh units — inside the memory_plan
    budget — while the fused pipeline (power3d) books 4+ units, which
    is exactly why bench.py gates Nmesh >= 512 to the staged path."""
    s = _project_summaries([os.path.join(REPO, 'bench.py'),
                            os.path.join(REPO, 'nbodykit_tpu',
                                         'parallel', 'dfft.py')])
    bench = {name: summ for (path, name), summ in s.items()
             if path == 'bench.py'}
    assert bench['run_once'].peak <= 2.0
    assert bench['paint_fft'].peak <= 2.0
    assert bench['power3d'].peak >= 4.0
    config = lint.make_config(1024)
    from nbodykit_tpu.lint.sizes import unit_bytes
    staged_bytes = bench['run_once'].peak * unit_bytes(config)
    assert staged_bytes <= config.budget_bytes       # fits v5e
    fused_bytes = bench['power3d'].peak * unit_bytes(config)
    assert fused_bytes > config.budget_bytes         # why staged exists


def test_memory_report_rows_and_budget():
    config = lint.make_config(1024)
    project, _ = lint.build_project(
        [os.path.join(REPO, 'bench.py')])
    report = lint.memory_report(project, config)
    rows = {r['function']: r for r in report['rows']}
    assert rows['power3d']['over_budget'] is True
    assert rows['run_once']['over_budget'] is False
    text = lint.render_memory_report(report)
    assert 'OVER BUDGET' in text and 'run_once' in text


# ---------------------------------------------------------------------------
# baseline roundtrip for the new codes

def test_baseline_line_drift_roundtrip_new_codes(tmp_path):
    src_v1 = textwrap.dedent("""
    import jax
    import jax.numpy as jnp

    def power(field):
        return jnp.abs(field) ** 2

    fast_power = jax.jit(power)

    def run(pm, pos, n):
        field = pm.paint(pos)
        x = jax.lax.psum(field, 'dev')
        if n < 0:
            raise ValueError('bad')
        x = jax.lax.all_to_all(x, 'dev', 0, 0)
        p3 = fast_power(field)
        return p3
    """)
    findings = lint.lint_source('pkg.py', src_v1,
                                select=['NBK103', 'NBK5'])
    assert sorted(codes(findings)) == ['NBK103', 'NBK501']
    sources = {'pkg.py': src_v1.splitlines()}
    doc = lint.build_baseline(findings, sources=sources)
    path = str(tmp_path / 'baseline.json')
    lint.write_baseline(doc, path)

    # three lines of drift above: both entries still grandfathered
    src_v2 = '# a\n# b\n# c\n' + src_v1
    moved = lint.lint_source('pkg.py', src_v2,
                             select=['NBK103', 'NBK5'])
    assert sorted(codes(moved)) == ['NBK103', 'NBK501']
    new, grand, unused = lint.apply_baseline(
        moved, lint.load_baseline(path),
        sources={'pkg.py': src_v2.splitlines()})
    assert new == [] and len(grand) == 2 and unused == []

    # both fixed: the stale entries surface for pruning
    new, grand, unused = lint.apply_baseline(
        [], lint.load_baseline(path), sources={})
    assert new == [] and grand == [] and len(unused) == 2


# ---------------------------------------------------------------------------
# acceptance: seeded deadlock + donation fixtures through the CLI
# subprocess AND the pytest-gate API path

SEEDED_FIXTURE = textwrap.dedent("""
    import jax
    import jax.numpy as jnp

    def power(field):
        return jnp.abs(field) ** 2

    fast_power = jax.jit(power, donate_argnums=(0,))

    def deadlock(x, n):
        x = jax.lax.psum(x, 'dev')
        if n < 0:
            raise ValueError('bad shard')
        return jax.lax.all_to_all(x, 'dev', 0, 0)

    def held(pm, pos):
        field = pm.paint(pos)
        p3 = fast_power(field)
        return p3.sum() + field.sum()
""")


def test_seeded_fixtures_detected_by_pytest_gate(tmp_path):
    pkg = tmp_path / 'nbodykit_tpu'
    pkg.mkdir()
    (pkg / 'seeded.py').write_text(SEEDED_FIXTURE)
    new, _, _ = lint.run_lint([str(pkg)])
    assert sorted(f.code for f in new) == ['NBK103', 'NBK502']
    assert all(f.path == 'nbodykit_tpu/seeded.py' for f in new)


def test_seeded_fixtures_detected_by_cli(tmp_path):
    fixture = tmp_path / 'seeded.py'
    fixture.write_text(SEEDED_FIXTURE)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert 'NBK103' in proc.stdout and 'NBK502' in proc.stdout
    # grandfathered, the same invocation gates green
    bl = tmp_path / 'baseline.json'
    subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--write-baseline', str(bl)],
        capture_output=True, text=True, cwd=REPO, check=True)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--baseline', str(bl)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_stats_json(tmp_path):
    fixture = tmp_path / 'seeded.py'
    fixture.write_text(SEEDED_FIXTURE)
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', str(fixture),
         '--stats'],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data['gate'] == 'FAIL'
    assert data['families']['NBK1']['new'] == 1
    assert data['families']['NBK5']['new'] == 1
    assert data['by_code']['new'] == {'NBK103': 1, 'NBK502': 1}
    assert data['total']['new'] == 2


def test_cli_memory_report(tmp_path):
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint',
         '--memory-report', '--nmesh', '1024', 'bench.py'],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'nmesh=1024' in proc.stdout
    assert 'run_once' in proc.stdout
    assert 'OVER BUDGET' in proc.stdout      # the fused pipeline
    # --memory-report without a config is a usage error
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint',
         '--memory-report', 'bench.py'],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2


def test_rule_catalog_lists_new_codes():
    proc = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--list-rules'],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    for code in ('NBK103', 'NBK501', 'NBK502', 'NBK503'):
        assert code in proc.stdout


# ---------------------------------------------------------------------------
# regress + doctor integration

def test_regress_records_per_family_counts(tmp_path):
    import shutil
    from nbodykit_tpu.diagnostics.regress import lint_summary

    root = str(tmp_path)
    os.symlink(os.path.join(REPO, 'nbodykit_tpu'),
               os.path.join(root, 'nbodykit_tpu'))
    for extra in ('bench.py',):
        shutil.copy(os.path.join(REPO, extra),
                    os.path.join(root, extra))
    shutil.copy(os.path.join(REPO, 'lint_baseline.json'),
                os.path.join(root, 'lint_baseline.json'))
    summ = lint_summary(root)
    assert summ['new'] == 0
    fams = summ['families']
    # every family axis is present so shrinkage is tracked per family
    for fam in ('NBK1', 'NBK2', 'NBK3', 'NBK4', 'NBK5'):
        assert fam in fams, fams
    # the audited NBK103 entries and the bench NBK202s are baselined
    assert fams['NBK1']['baselined'] >= 2
    assert fams['NBK2']['baselined'] >= 5


def test_doctor_cross_links_watermark_to_nbk5(tmp_path, capsys):
    from nbodykit_tpu.diagnostics import REGISTRY
    from nbodykit_tpu.diagnostics.metrics import REGISTRY as MREG
    from nbodykit_tpu.diagnostics.__main__ import run_doctor

    root = str(tmp_path)
    pkg = tmp_path / 'nbodykit_tpu'
    pkg.mkdir()
    (pkg / 'seeded.py').write_text(SEEDED_FIXTURE)
    # a watermark past half a v5e's HBM, as device_watermarks() would
    # record it after a hot run
    MREG.gauge('device.tpu:0.live_bytes').set(9.5e9)
    try:
        run_doctor(trace=None, root=root)
        out = capsys.readouterr().out
        assert 'memory       WARN' in out
        assert 'NBK502' in out and 'seeded.py' in out
        assert '9.50 GB' in out
    finally:
        REGISTRY.reset()


# ---------------------------------------------------------------------------
# regression: the pre-fix eager _fftn_c2c_single_chunked shape


def test_nbk503_would_have_caught_eager_chunked_fft():
    # dfft.py's _fftn_c2c_single_chunked originally allocated the FULL
    # complex result up front and fori_loop-wrote chunks into it —
    # peak = input + eager output + per-chunk FFT temporaries, a
    # multi-GB regression the 2-buffer rewrite removed.  This fixture
    # freezes that shape: the static peak model must flag it at the
    # documented 1024^3 complex config, and the same code must stay
    # silent where it genuinely fits (512^3).
    src = """
    import jax
    import jax.numpy as jnp

    def fftn_c2c_eager(v, shape_complex):
        x = to_complex_field(v)
        out = jnp.zeros(shape_complex, jnp.complex64)
        def body(i, acc):
            return acc.at[i].set(jnp.fft.fftn(x[i]))
        out = jax.lax.fori_loop(0, 8, body, out)
        return out
    """
    config = lint.make_config(1024, dtype_bytes=8, hbm_bytes=16e9)
    fs = lint_str(src, select=['NBK4', 'NBK5'], memory_config=config)
    assert 'NBK503' in codes(fs)
    assert 'full-mesh units at peak' in fs[0].message
    small = lint.make_config(512, dtype_bytes=8, hbm_bytes=16e9)
    assert lint_str(src, select=['NBK4', 'NBK5'],
                    memory_config=small) == []
