"""ConvolvedFFTPower / FKPCatalog tests (reference analog:
algorithms/convpower/tests/): Ylm addition theorem, periodic-box
consistency oracle, normalization/shotnoise identities, to_pkmu,
save/load.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import (UniformCatalog, LogNormalCatalog,
                              LinearPower, Planck15, FFTPower,
                              ConvolvedFFTPower, FKPCatalog,
                              FKPWeightFromNbar)
from nbodykit_tpu.algorithms.convpower import get_real_Ylm


def test_real_ylm_addition_theorem():
    # sum_m Ylm(a) Ylm(b) == (2l+1)/(4pi) P_l(a.b)
    rng = np.random.RandomState(0)
    a = rng.standard_normal(3)
    a /= np.linalg.norm(a)
    b = rng.standard_normal(3)
    b /= np.linalg.norm(b)
    from numpy.polynomial.legendre import legval
    for ell in [1, 2, 3, 4]:
        total = sum(
            float(get_real_Ylm(ell, m)(a[0], a[1], a[2]))
            * float(get_real_Ylm(ell, m)(b[0], b[1], b[2]))
            for m in range(-ell, ell + 1))
        coeffs = np.zeros(ell + 1)
        coeffs[ell] = 1.0
        want = (2 * ell + 1) / (4 * np.pi) * legval(float(a @ b), coeffs)
        np.testing.assert_allclose(total, want, rtol=1e-10)


def test_real_ylm_orthonormal():
    # numerical quadrature of Ylm * Yl'm' over the sphere
    nth, nph = 128, 256
    theta = (np.arange(nth) + 0.5) * np.pi / nth
    phi = (np.arange(nph) + 0.5) * 2 * np.pi / nph
    T, P = np.meshgrid(theta, phi, indexing='ij')
    x = np.sin(T) * np.cos(P)
    y = np.sin(T) * np.sin(P)
    z = np.cos(T)
    dA = np.sin(T) * (np.pi / nth) * (2 * np.pi / nph)
    y22 = np.asarray(get_real_Ylm(2, 2)(x, y, z))
    y20 = np.asarray(get_real_Ylm(2, 0)(x, y, z))
    np.testing.assert_allclose((y22 ** 2 * dA).sum(), 1.0, rtol=1e-3)
    np.testing.assert_allclose((y20 ** 2 * dA).sum(), 1.0, rtol=1e-3)
    assert abs((y22 * y20 * dA).sum()) < 1e-10


@pytest.fixture(scope='module')
def fkp_setup():
    Plin = LinearPower(Planck15, 0.55, transfer='EisensteinHu')
    Plin.sigma8 = 0.8
    data = LogNormalCatalog(Plin=Plin, nbar=5e-4, BoxSize=256., Nmesh=32,
                            bias=2.0, seed=11)
    ran = UniformCatalog(nbar=5e-3, BoxSize=256., seed=12)
    nbar_val = data.csize / 256. ** 3
    data['NZ'] = np.ones(data.csize) * nbar_val
    ran['NZ'] = np.ones(ran.csize) * nbar_val
    fkp = FKPCatalog(data, ran, BoxSize=270.0)
    mesh = fkp.to_mesh(Nmesh=32, resampler='cic', compensated=True)
    r = ConvolvedFFTPower(mesh, poles=[0, 2, 4], dk=0.02, kmin=0.02)
    return Plin, data, r


def test_convpower_periodic_consistency(fkp_setup):
    Plin, data, r = fkp_setup
    # full-box "survey" with constant n(z): P0 should track the
    # periodic-box FFTPower at the 30% level (window + noise)
    p0 = r.poles['power_0'].real - r.attrs['shotnoise']
    k = r.poles['k']
    mesh = data.to_mesh(Nmesh=32, BoxSize=256., resampler='cic',
                        compensated=True)
    rp = FFTPower(mesh, mode='1d', dk=0.02, kmin=0.02)
    pk_per = np.interp(k, rp.power['k'],
                       rp.power['power'].real - rp.attrs['shotnoise'])
    sel = (k > 0.05) & (k < 0.3)
    ratio = p0[sel] / pk_per[sel]
    assert abs(np.nanmean(ratio) - 1) < 0.3


def test_convpower_attrs(fkp_setup):
    _, data, r = fkp_setup
    # alpha ~ 1/10 by construction
    assert abs(r.attrs['alpha'] - 0.1) < 0.02
    # norms from data and randoms agree to 5% (enforced) and shotnoise
    # is near the V/N level
    assert abs(r.attrs['data.norm'] / r.attrs['randoms.norm'] - 1) < 0.05
    assert r.attrs['shotnoise'] > 0


def test_convpower_to_pkmu(fkp_setup):
    _, _, r = fkp_setup
    pkmu = r.to_pkmu(np.linspace(0, 1, 5), max_ell=4)
    assert pkmu.shape == (len(r.poles['k']), 4)
    # the mu-average of wedges reproduces the monopole
    recon = np.nanmean(pkmu['power'].real, axis=-1)
    valid = ~np.isnan(recon)
    np.testing.assert_allclose(recon[valid],
                               r.poles['power_0'].real[valid], rtol=0.15)


def test_convpower_save_load(fkp_setup, tmp_path):
    _, _, r = fkp_setup
    fn = str(tmp_path / "conv.json")
    r.save(fn)
    r2 = ConvolvedFFTPower.load(fn)
    np.testing.assert_allclose(r.poles['power_0'].real,
                               r2.poles['power_0'].real, equal_nan=True)
    assert r2.attrs['alpha'] == r.attrs['alpha']


def test_fkp_weight():
    nbar = np.array([1e-4, 1e-3])
    w = FKPWeightFromNbar(1e4, nbar)
    np.testing.assert_allclose(w, 1.0 / (1 + 1e4 * nbar))
    assert FKPWeightFromNbar(0, nbar) == 1.0


def test_multiple_species_basic():
    from nbodykit_tpu.lab import MultipleSpeciesCatalog
    c1 = UniformCatalog(nbar=1e-4, BoxSize=100., seed=1)
    c2 = UniformCatalog(nbar=1e-4, BoxSize=100., seed=2)
    cat = MultipleSpeciesCatalog(['a', 'b'], c1, c2)
    assert cat.csize == c1.csize + c2.csize
    assert 'a/Position' in cat.columns
    np.testing.assert_allclose(np.asarray(cat['a/Position']),
                               np.asarray(c1['Position']))
    cat['a/Extra'] = np.ones(c1.csize)
    assert 'Extra' in c1.columns


def test_convpower_odd_poles_c2c(fkp_setup):
    # requesting an odd pole switches to the full-complex spectrum; the
    # even poles must agree with the hermitian fast path
    _, data, r_even = fkp_setup
    mesh = r_even.first
    r_odd = ConvolvedFFTPower(mesh, poles=[0, 1, 2], dk=0.02, kmin=0.02)
    p0e = r_even.poles['power_0'].real
    p0o = r_odd.poles['power_0'].real
    sel = np.isfinite(p0e) & (np.abs(p0e) > 1)
    np.testing.assert_allclose(p0o[sel], p0e[sel], rtol=1e-10)
    # dipole of a (nearly) periodic box sample is tiny compared to P0
    p1 = r_odd.poles['power_1'].real
    assert np.nanmax(np.abs(p1[sel])) < 0.1 * np.nanmax(np.abs(p0e[sel]))


def test_convpower_no_monopole(comm):
    """poles without ell=0 still run (reference test_no_monopole)."""
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        d = UniformCatalog(nbar=3e-3, BoxSize=100.0, seed=12)
        r = UniformCatalog(nbar=3e-2, BoxSize=100.0, seed=13)
        d['NZ'] = 3e-3 * jnp.ones(d.size)
        r['NZ'] = 3e-3 * jnp.ones(r.size)
        mesh = FKPCatalog(d, r).to_mesh(Nmesh=32, resampler='tsc')
        p = ConvolvedFFTPower(mesh, poles=[2], dk=0.1, kmin=0.01)
    assert 'power_2' in p.poles.variables
    assert np.isfinite(np.asarray(p.poles['power_2'].real)).any()


def test_convpower_cross_equals_auto(comm):
    """second=same mesh reproduces the auto spectrum exactly
    (reference test_cross_corr)."""
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        d = UniformCatalog(nbar=3e-3, BoxSize=100.0, seed=12)
        r = UniformCatalog(nbar=3e-2, BoxSize=100.0, seed=13)
        d['NZ'] = 3e-3 * jnp.ones(d.size)
        r['NZ'] = 3e-3 * jnp.ones(r.size)
        fkp = FKPCatalog(d, r)
        mesh = fkp.to_mesh(Nmesh=32, resampler='tsc')
        # a DISTINCT second mesh of the same catalog: the cross branch
        # (separate second paint + A0*Aell' product) actually executes
        mesh2 = fkp.to_mesh(Nmesh=32, resampler='tsc')
        assert mesh2 is not mesh
        auto = ConvolvedFFTPower(mesh, poles=[0, 2], dk=0.1, kmin=0.01)
        cross = ConvolvedFFTPower(mesh, poles=[0, 2], second=mesh2,
                                  dk=0.1, kmin=0.01)
    np.testing.assert_allclose(
        np.asarray(auto.poles['power_0'].real),
        np.asarray(cross.poles['power_0'].real), rtol=1e-10)


def test_convpower_window_only(comm):
    """Zero data weight measures the window function without error
    (reference test_window_only)."""
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        d = UniformCatalog(nbar=3e-3, BoxSize=100.0, seed=14)
        r = UniformCatalog(nbar=3e-2, BoxSize=100.0, seed=13)
        d['NZ'] = 3e-3 * jnp.ones(d.size)
        r['NZ'] = 3e-3 * jnp.ones(r.size)
        d['Weight'] = jnp.zeros(d.size)
        mesh = FKPCatalog(d, r).to_mesh(Nmesh=32)
        p = ConvolvedFFTPower(mesh, poles=[0], dk=0.1)
    assert np.isfinite(np.asarray(p.poles['power_0'].real)).any()


@pytest.mark.slow
def test_convpower_with_zhist(comm):
    """Full survey flow: sky coords -> RedshiftHistogram n(z) ->
    interpolated NZ -> FKP multipoles (reference test_with_zhist)."""
    from nbodykit_tpu.lab import RandomCatalog, Planck15
    from nbodykit_tpu.algorithms.zhist import RedshiftHistogram
    from nbodykit_tpu import transform
    from nbodykit_tpu.parallel.runtime import use_mesh

    with use_mesh(comm):
        cats = []
        for i, n in enumerate((800, 8000)):
            cat = RandomCatalog(n, seed=11 + i)
            rng = np.random.RandomState(100 + i)
            ra = rng.uniform(0, 40, n)
            dec = rng.uniform(-10, 10, n)
            z = rng.uniform(0.2, 0.6, n)
            cat['RA'], cat['DEC'], cat['z'] = ra, dec, z
            cat['Position'] = transform.SkyToCartesian(ra, dec, z,
                                                       Planck15)
            cats.append(cat)
        data, randoms = cats
        zhist = RedshiftHistogram(randoms, 0.01, Planck15,
                                  redshift='z')
        alpha = 1.0 * data.csize / randoms.csize
        randoms['NZ'] = zhist.interpolate(randoms['z']) * alpha
        data['NZ'] = zhist.interpolate(data['z']) * alpha
        r = ConvolvedFFTPower(FKPCatalog(data, randoms).to_mesh(
            Nmesh=32), poles=[0, 2], dk=0.02)
    p0 = np.asarray(r.poles['power_0'].real)
    assert np.isfinite(p0).any()
    # data.csize-normalized alpha: shotnoise attr must be positive
    assert r.attrs['shotnoise'] > 0
