"""Tests for nbodykit_tpu.serve: the declarative request model,
memory-plan admission control (reject vs degrade, structured reasons),
the warm program cache (second identical-shape request compiles
nothing, proven by compile-miss counters), vmap batching
bit-equivalence, deadline eviction, queue bounding, per-request fault
isolation under injected faults (one request degrades, the fleet
survives), checkpoint resume, and graceful drain/shutdown — plus the
thread-safety satellites: the tune-cache mtime memo under concurrent
loaders, ``option_scope`` leak-proofing across reused worker threads,
and ``TaskManager.map`` exception propagation."""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.resilience import CheckpointStore, reset_faults
from nbodykit_tpu.serve import (ADMIT, DEGRADE, REJECT, AnalysisRequest,
                                AnalysisServer, BatchPolicy, admit,
                                generate_trace, replay)


@pytest.fixture(autouse=True)
def _clean_state():
    """Registry, fault counts and options are process-wide; every test
    sees (and leaves) a pristine copy."""
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    yield
    REGISTRY.reset()
    reset_faults()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _counter(name):
    snap = REGISTRY.snapshot().get(name)
    return snap['value'] if snap else 0


def _one_worker_server(**kw):
    """A server pinned to ONE 1-device worker (deterministic queueing
    tests need a single consumer)."""
    with use_mesh(cpu_mesh(1)):
        return AnalysisServer(per_task=1, **kw)


# ---------------------------------------------------------------------------
# request model

def test_request_validation_and_keys():
    r = AnalysisRequest(nmesh=64, npart=100000, seed=5, priority=2)
    assert r.request_id.startswith('req-')
    assert r.shape_class == 'mesh64-part1e5'
    # seed / deadline / priority are runtime inputs, never program id
    r2 = AnalysisRequest(nmesh=64, npart=100000, seed=99, priority=0)
    assert r.program_key(1) == r2.program_key(1)
    assert r.program_key(1) != r.program_key(8)
    rt = AnalysisRequest.from_dict(r.to_dict())
    assert rt.program_key(1) == r.program_key(1)
    assert rt.request_id == r.request_id
    with pytest.raises(ValueError):
        AnalysisRequest(algorithm='PairCount')
    with pytest.raises(ValueError):
        AnalysisRequest(dtype='f2')
    with pytest.raises(ValueError):
        AnalysisRequest(deadline_s=0)
    with pytest.raises(ValueError):
        AnalysisRequest(nmesh=2)


# ---------------------------------------------------------------------------
# admission control

def test_admission_admit_clean():
    d = admit(AnalysisRequest(nmesh=64, npart=10 ** 5), ndevices=1,
              hbm_bytes=16e9)
    assert d.status == ADMIT and d.admitted
    assert not d.options
    assert d.plan['fits']


def test_admission_reject_structured_over_budget():
    d = admit(AnalysisRequest(nmesh=2048, npart=10 ** 9), ndevices=1,
              hbm_bytes=16e9)
    assert d.status == REJECT and not d.admitted
    r = d.reason
    assert r['code'] == 'over_budget'
    assert r['peak_bytes'] > r['budget_bytes']
    assert r['rungs_tried']          # it tried the whole ladder
    assert 'ndevices' in r and 'detail' in r
    # machine-shape round trip
    assert json.loads(json.dumps(d.to_dict()))['reason']['code'] \
        == 'over_budget'


def test_admission_degrade_steps_scoped_ladder():
    # nmesh=64 / npart=1e8 / scatter: peak ~2.27 GB unchunked,
    # ~1.74 GB at paint_chunk 8M — budget between the two admits
    # degraded (and ONLY via per-request options, never set_options)
    before = dict(_global_options)
    d = admit(AnalysisRequest(nmesh=64, npart=10 ** 8,
                              paint_method='scatter'),
              ndevices=1, hbm_bytes=2.3e9)
    assert d.status == DEGRADE and d.admitted
    assert d.options.get('paint_chunk_size')
    assert [r[0] for r in d.rungs][-1] == 'paint_chunk_size/2'
    assert d.plan['fits']
    assert dict(_global_options) == before


def test_admission_reject_indivisible():
    d = admit(AnalysisRequest(nmesh=36, npart=1000), ndevices=8)
    assert d.status == REJECT
    assert d.reason['code'] == 'indivisible'


# ---------------------------------------------------------------------------
# the server: warm cache, batching, eviction, bounding

def test_serve_warm_cache_second_request_compiles_nothing():
    label = 'compile.serve.fftpower.mesh32-part1e4'
    with _one_worker_server(batch=BatchPolicy(max_delay_s=0)) as srv:
        r1 = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, npart=20000, seed=1)), timeout=180)
        assert r1.status == 'completed'
        miss0 = _counter(label + '.misses')
        build0 = _counter('serve.program.build')
        r2 = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, npart=20000, seed=2)), timeout=60)
        assert r2.status == 'completed'
        assert _counter(label + '.misses') == miss0     # ZERO recompile
        assert _counter(label + '.hits') >= 1
        assert _counter('serve.program.build') == build0
        assert _counter('serve.program.reuse') >= 1
        # tuned options resolved once per shape class, then memoized
        assert _counter('serve.tuned.resolve') == 1
        assert _counter('serve.tuned.reuse') >= 1


def test_serve_batched_bit_equal_to_sequential():
    seeds = [11, 12, 13, 14]
    with _one_worker_server(
            batch=BatchPolicy(max_batch=4, max_delay_s=1.0)) as srv:
        # 4 compatible requests submitted together: one vmap launch
        tickets = [srv.submit(AnalysisRequest(
            nmesh=32, npart=20000, seed=s)) for s in seeds]
        batched = [srv.wait(t, timeout=180) for t in tickets]
        assert all(r.status == 'completed' for r in batched)
        assert max(r.batch_size for r in batched) > 1
        # same seeds one at a time: sequential launches
        solo = [srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, npart=20000, seed=s)), timeout=60)
            for s in seeds]
    for rb, rs in zip(batched, solo):
        assert rs.batch_size == 1
        assert np.array_equal(np.asarray(rb.y), np.asarray(rs.y))
        assert np.array_equal(np.asarray(rb.nmodes),
                              np.asarray(rs.nmodes))


def test_serve_deadline_eviction_structured():
    with _one_worker_server(batch=BatchPolicy(max_delay_s=0)) as srv:
        # occupy the only worker, then submit an already-hopeless
        # deadline: it must be EVICTED with a verdict, not run late
        blocker = srv.submit(AnalysisRequest(nmesh=32, npart=20000,
                                             seed=100))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:   # blocker on the worker
            with srv._lock:
                if not srv._pending:
                    break
            time.sleep(0.005)
        doomed = srv.submit(AnalysisRequest(nmesh=32, npart=20000,
                                            seed=101, deadline_s=1e-3))
        rb = srv.wait(blocker, timeout=180)
        rd = srv.wait(doomed, timeout=60)
    assert rb.status == 'completed'
    assert rd.status == 'evicted'
    assert rd.reason['code'] == 'deadline'
    assert rd.reason['waited_s'] >= 0


def test_serve_queue_full_structured_reject():
    with _one_worker_server(max_queue=1,
                            batch=BatchPolicy(max_delay_s=0)) as srv:
        blocker = srv.submit(AnalysisRequest(nmesh=32, npart=20001,
                                             seed=0))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:     # wait until picked up
            with srv._lock:
                if not srv._pending:
                    break
            time.sleep(0.01)
        q1 = srv.submit(AnalysisRequest(nmesh=32, npart=20001, seed=1))
        q2 = srv.submit(AnalysisRequest(nmesh=32, npart=20001, seed=2))
        r2 = srv.wait(q2, timeout=10)
        assert r2.status == 'rejected'
        assert r2.reason['code'] == 'queue_full'
        assert r2.reason['max_queue'] == 1
        assert srv.wait(blocker, timeout=180).status == 'completed'
        assert srv.wait(q1, timeout=60).status == 'completed'


def test_serve_rejected_never_queued():
    with _one_worker_server() as srv:
        t = srv.submit(AnalysisRequest(nmesh=2048, npart=10 ** 9))
        r = srv.wait(t, timeout=5)
        assert r.status == 'rejected'
        assert r.reason['code'] == 'over_budget'
        assert srv.summary()['rejected'] == 1


# ---------------------------------------------------------------------------
# fault isolation

def test_serve_injected_fault_degrades_one_request_not_fleet():
    from nbodykit_tpu.resilience import RetryPolicy
    n = 4
    with nbodykit_tpu.set_options(
            faults='serve.request.attempt@2:unavailable'):
        reset_faults()
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0),
                retry=RetryPolicy(max_retries=3, base_s=0.01)) as srv:
            tickets = [srv.submit(AnalysisRequest(
                nmesh=32, npart=20000, seed=s)) for s in range(n)]
            results = [srv.wait(t, timeout=180) for t in tickets]
            summary = srv.summary()
    # the fleet survived: every request completed, nothing lost
    assert [r.status for r in results] == ['completed'] * n
    assert summary['lost'] == 0
    # and EXACTLY ONE request absorbed the injected tunnel death
    hit = [r for r in results if r.event_count('retries')]
    assert len(hit) == 1
    assert summary['retried'] == 1


def test_serve_fault_after_work_resumes_from_checkpoint(tmp_path):
    from nbodykit_tpu.resilience import RetryPolicy
    with nbodykit_tpu.set_options(
            faults='serve.request.work@1:unavailable'):
        reset_faults()
        with _one_worker_server(
                batch=BatchPolicy(max_delay_s=0),
                checkpoint=CheckpointStore(tmp_path),
                retry=RetryPolicy(max_retries=3, base_s=0.01)) as srv:
            r = srv.wait(srv.submit(AnalysisRequest(
                nmesh=32, npart=20000, seed=7)), timeout=180)
            summary = srv.summary()
    assert r.status == 'completed'
    # the kill landed AFTER the checkpoint: the retry resumed saved
    # results instead of recomputing
    assert r.event_count('resumes') == 1
    assert summary['resumed'] == 1
    assert summary['lost'] == 0


# ---------------------------------------------------------------------------
# lifecycle

def test_serve_graceful_drain_and_idempotent_shutdown():
    srv = _one_worker_server(batch=BatchPolicy(max_delay_s=0))
    tickets = [srv.submit(AnalysisRequest(nmesh=32, npart=20000,
                                          seed=s)) for s in range(3)]
    assert srv.drain(timeout=180)
    assert all(t.result is not None for t in tickets)
    srv.shutdown()
    srv.shutdown()                      # second call: no-op
    late = srv.submit(AnalysisRequest(nmesh=32, npart=20000))
    assert late.result.status == 'rejected'
    assert late.result.reason['code'] == 'shutting_down'
    s = srv.summary()
    assert s['lost'] == 0
    assert s['submitted'] == s['resolved']


def test_trace_generator_deterministic():
    a = [r.to_dict() for r in generate_trace(60, seed=3)]
    b = [r.to_dict() for r in generate_trace(60, seed=3)]
    assert a == b
    c = [r.to_dict() for r in generate_trace(60, seed=4)]
    assert a != c
    assert a[0]['request_id'] == 'trace-00000'
    # Zipf head: the hottest shape dominates
    algos = [d['algorithm'] for d in a]
    assert algos.count('FFTPower') > len(a) // 2


def test_serve_trace_replay_end_to_end():
    trace = generate_trace(12, seed=1, deadline_s=300.0)
    with _one_worker_server(
            batch=BatchPolicy(max_batch=4, max_delay_s=0.05)) as srv:
        tickets = replay(srv, trace, seed=1)
        assert all(t.result is not None for t in tickets)
        s = srv.summary()
    assert s['submitted'] == 12
    assert s['lost'] == 0
    assert s['completed'] + s['rejected'] + s['evicted'] \
        + s['failed'] == 12
    assert s['p99_s'] is not None and s['p50_s'] <= s['p99_s']


# ---------------------------------------------------------------------------
# satellites: thread safety

def test_tune_cache_memo_thread_safe(tmp_path):
    from nbodykit_tpu.tune import cache as tc
    path = str(tmp_path / 'TUNE_CACHE.json')
    cache = tc.TuneCache(path)
    cache.put({'platform': 'cpu', 'device_kind': 'cpu',
               'device_count': 1, 'op': 'paint',
               'shape_class': 'mesh32-part1e4', 'dtype': 'f4',
               'winner': 'scatter', 'candidates': {}})
    tc.reset_cache_memo()
    errs, results = [], []

    def load():
        try:
            for _ in range(200):
                results.append(len(tc._load_entries(path)))
        except Exception as e:          # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=load) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert set(results) == {1}


def test_option_scope_restores_and_cannot_leak_across_threads():
    import random

    def task(i):
        # each reused pool thread overrides, works, and MUST restore
        with nbodykit_tpu.option_scope(
                paint_chunk_size=1000 + i,
                fft_chunk_bytes=2000 + i):
            time.sleep(random.random() * 0.01)
            assert _global_options['paint_chunk_size'] == 1000 + i
        return _global_options['paint_chunk_size']

    baseline = _global_options['paint_chunk_size']
    with ThreadPoolExecutor(max_workers=4) as ex:
        out = list(ex.map(task, range(64)))
    # every task saw the default restored after its scope — including
    # on threads the pool reused across tasks
    assert set(out) == {baseline}
    assert _global_options['paint_chunk_size'] == baseline


def test_option_scope_restores_on_exception_and_rejects_bad_keys():
    baseline = _global_options['paint_chunk_size']
    with pytest.raises(RuntimeError):
        with nbodykit_tpu.option_scope(paint_chunk_size=7):
            raise RuntimeError('boom')
    assert _global_options['paint_chunk_size'] == baseline
    with pytest.raises(KeyError):
        with nbodykit_tpu.option_scope(not_an_option=1):
            pass


def test_taskmanager_map_propagates_original_exception(cpu8):
    from nbodykit_tpu.batch import TaskManager

    def work(i):
        if i == 2:
            raise ValueError('task two exploded')
        return i * i

    with use_mesh(cpu8):
        with TaskManager(cpus_per_task=4) as tm:     # 2 sub-meshes
            assert tm.map(lambda i: i * i, range(4)) == [0, 1, 4, 9]
            with pytest.raises(ValueError, match='task two exploded') \
                    as ei:
                tm.map(work, range(4))
    assert ei.value.task_index == 2


def test_taskmanager_injected_fault_surfaces_not_deadlocks(cpu8):
    from nbodykit_tpu.batch import TaskManager
    from nbodykit_tpu.resilience import fault_point

    def work(i):
        fault_point('batch.map.task')
        return i

    with nbodykit_tpu.set_options(faults='batch.map.task@3:internal'):
        reset_faults()
        with use_mesh(cpu8):
            with TaskManager(cpus_per_task=4) as tm:
                with pytest.raises(Exception) as ei:
                    tm.map(work, range(6))
    assert 'INTERNAL' in str(ei.value)
    assert hasattr(ei.value, 'task_index')


# ---------------------------------------------------------------------------
# CLI

def test_serve_cli_main(tmp_path):
    from nbodykit_tpu.serve.__main__ import main
    out = tmp_path / 'serve.json'
    with use_mesh(cpu_mesh(1)):
        rc = main(['--trace', '6', '--seed', '2', '--max-delay-ms',
                   '10', '--json', str(out)])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data['submitted'] == 6
    assert data['lost'] == 0
    assert len(data['verdicts']) == data['resolved']


# ---------------------------------------------------------------------------
# regress / doctor posture

def test_serve_summary_reads_committed_round(tmp_path):
    """serve_summary must read the FULL parsed record from the round
    file (load_rounds flattens it to the headline keys, which lose the
    lost/retried/degraded ledger) and render a posture line."""
    from nbodykit_tpu.diagnostics.regress import (build_history,
                                                  render_regress,
                                                  serve_summary)
    rec = {'metric': 'servetrace_n12', 'unit': 's', 'value': 0.5,
           'requests': 12, 'rps': 24.0, 'p50_s': 0.3, 'p99_s': 0.5,
           'completed': 11, 'rejected': 1, 'evicted': 0, 'failed': 0,
           'lost': 0, 'retried': 1, 'degraded': 0, 'resumed': 0,
           'admit_degraded': 0,
           'faults_injected': {'serve.request.attempt': 13},
           'measured_at': '2026-08-05T00:00:00Z'}
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'n': 1, 'cmd': 'bench --serve-trace 12', 'rc': 0,
         'tail': json.dumps(rec), 'parsed': rec}))
    srv = serve_summary(str(tmp_path))
    assert srv is not None
    assert srv['round'] == 'BENCH_r01.json'
    assert srv['lost'] == 0 and srv['retried'] == 1
    assert srv['faults_injected'] == {'serve.request.attempt': 13}
    history = build_history(str(tmp_path), write=False)
    assert history['serve']['metric'] == 'servetrace_n12'
    text = render_regress(history)
    line = next(l for l in text.splitlines()
                if l.strip().startswith('serve:'))
    assert '12 req @ 24.0 rps' in line
    assert 'faults injected at serve.request.attempt' in line
    assert '0 lost' in line


def test_serve_summary_none_without_round(tmp_path):
    from nbodykit_tpu.diagnostics.regress import serve_summary
    assert serve_summary(str(tmp_path)) is None
