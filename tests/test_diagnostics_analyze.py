"""Tests for the fleet-level diagnostics: multi-host trace merging
with clock alignment (analyze.py) — straggler tables, critical-path
attribution, hung-collective and heartbeat post-mortems — and the
bench regression tracker (regress.py) with injected regression, stale
cache replay, and malformed-record gating."""

import json
import os
import time

import pytest

from nbodykit_tpu.diagnostics import analyze as A
from nbodykit_tpu.diagnostics import regress as R
from nbodykit_tpu.diagnostics.__main__ import main as cli_main

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


# ---------------------------------------------------------------------------
# synthetic two-process traces

def _w(path, records):
    with open(path, 'w') as f:
        for r in records:
            f.write(json.dumps(r) + '\n')


def _span(pid, sid, name, ts, dur, depth=0, par=0, ok=True):
    return {'t': 'span', 'id': sid, 'par': par, 'name': name,
            'ts': ts, 'dur': dur, 'depth': depth, 'pid': pid, 'ok': ok}


def _begin(pid, sid, name, ts, depth=0, par=0):
    return {'t': 'b', 'id': sid, 'par': par, 'name': name,
            'ts': ts, 'depth': depth, 'pid': pid}


SKEW = 5.0          # pid 202's wall clock runs 5 s ahead of pid 101


def _two_process_trace(tmp_path):
    """Two workers, identical collective program, pid 202 with a +5 s
    wall-clock skew and consistently late into every collective."""
    t = 100.0
    p101 = [
        {'t': 'meta', 'version': 1, 'pid': 101, 'ts': t},
        _span(101, 1, 'barrier', t + 0.00, 0.30),
        _span(101, 2, 'paint', t + 1.0, 2.0),
        _span(101, 3, 'exchange', t + 1.5, 1.0, depth=1, par=2),
        _span(101, 4, 'fft.r2c', t + 3.0, 1.0),
        _span(101, 5, 'fftpower.binning', t + 4.0, 0.5),
        _span(101, 6, 'barrier', t + 5.0, 0.1),
    ]
    s = t + SKEW    # 202 records skewed timestamps, same true events
    p202 = [
        {'t': 'meta', 'version': 1, 'pid': 202, 'ts': s},
        _span(202, 1, 'barrier', s + 0.20, 0.10),        # in 0.2 late
        _span(202, 2, 'paint', s + 1.0, 1.0),
        _span(202, 3, 'exchange', s + 1.2, 0.5, depth=1, par=2),
        _span(202, 4, 'fft.r2c', s + 3.5, 0.5),          # in 0.5 late
        _span(202, 5, 'fftpower.binning', s + 4.0, 0.5),
        _span(202, 6, 'barrier', s + 4.8, 0.3),
    ]
    _w(str(tmp_path / 'trace-101.jsonl'), p101)
    _w(str(tmp_path / 'trace-202.jsonl'), p202)
    return str(tmp_path)


def test_clock_alignment_recovers_skew(tmp_path):
    res = A.analyze(_two_process_trace(tmp_path))
    assert res['nprocs'] == 2 and res['pids'] == [101, 202]
    assert res['clock_offsets']['101'] == 0.0
    # collective END times align, so 202's recovered offset is -SKEW
    assert res['clock_offsets']['202'] == pytest.approx(-SKEW,
                                                        abs=1e-6)
    assert res['unaligned_pids'] == []
    assert res['anchors_used'] >= 3          # 2 barriers + fft.r2c


def test_merged_timeline_is_time_ordered_across_pids(tmp_path):
    res = A.analyze(_two_process_trace(tmp_path))
    tl = res['timeline']
    assert {r['pid'] for r in tl} == {101, 202}
    assert [r['ts'] for r in tl] == sorted(r['ts'] for r in tl)
    # after alignment the two 'fftpower.binning' begins coincide
    bins = [r for r in tl if r['name'] == 'fftpower.binning']
    assert len(bins) == 2
    assert bins[0]['ts'] == pytest.approx(bins[1]['ts'], abs=1e-6)


def test_straggler_table(tmp_path):
    res = A.analyze(_two_process_trace(tmp_path))
    per_name = res['stragglers']['per_name']
    # pid 202 was last into the first barrier by 0.2 s...
    barrier = per_name['barrier']
    assert barrier['worst_straggler'] == '202'
    assert barrier['max_skew_s'] == pytest.approx(0.2, abs=1e-6)
    # ...and into the FFT by 0.5 s
    fft = per_name['fft.r2c']
    assert fft['worst_straggler'] == '202'
    assert fft['max_skew_s'] == pytest.approx(0.5, abs=1e-6)
    rows = res['stragglers']['per_collective']
    first_barrier = next(r for r in rows if r['name'] == 'barrier'
                         and r['occurrence'] == 0)
    assert first_barrier['straggler'] == 202


def test_critical_path_attribution(tmp_path):
    res = A.analyze(_two_process_trace(tmp_path))
    cp = res['critical_path']
    # nested exchange time is charged to exchange, not paint:
    # pid 101 painted 2.0 s of which 1.0 s was the exchange
    assert cp['per_process']['101']['paint'] == pytest.approx(1.0)
    assert cp['per_process']['101']['exchange'] == pytest.approx(1.0)
    # the breakdown takes the WORST process per phase
    assert cp['phases']['paint'] == pytest.approx(1.0)
    assert cp['phases']['dfft'] == pytest.approx(1.0)
    assert cp['phases']['binning'] == pytest.approx(0.5)
    # wall spans first begin to last end (aligned)
    assert cp['wall_s'] == pytest.approx(5.1, abs=1e-6)
    text = A.render_analysis(res)
    assert 'critical path' in text and 'straggler report' in text


def test_hung_collective_reported_not_crash(tmp_path):
    """One trace is missing the close event of a collective: the
    analyzer must name the hung span and the process stuck in it."""
    _w(str(tmp_path / 'trace-7.jsonl'), [
        _span(7, 1, 'paint', 10.0, 1.0),
        _begin(7, 2, 'exchange', 11.0),
        _span(7, 2, 'exchange', 11.0, 0.5),
        _span(7, 3, 'barrier', 12.0, 0.1),
    ])
    _w(str(tmp_path / 'trace-8.jsonl'), [
        _span(8, 1, 'paint', 10.0, 1.0),
        _begin(8, 2, 'exchange', 11.0),      # never closed: wedged
        _span(8, 3, 'barrier', 12.0, 0.1),
    ])
    res = A.analyze(str(tmp_path))
    hung = res['hangs']['hung_collectives']
    assert len(hung) == 1
    assert hung[0]['name'] == 'exchange'
    assert hung[0]['open_pid'] == 8
    assert hung[0]['closed_pids'] == [7]
    text = A.render_analysis(res)
    assert 'HUNG COLLECTIVES' in text and 'exchange' in text


def test_heartbeat_gap_flags_silent_process(tmp_path):
    hb7 = [{'t': 'hb', 'pid': 7, 'ts': 10.0 + i, 'iv': 1.0}
           for i in range(20)]
    hb9 = [{'t': 'hb', 'pid': 9, 'ts': 10.0 + i, 'iv': 1.0}
           for i in range(5)]                # falls silent at t=14
    _w(str(tmp_path / 'trace-7.jsonl'),
       [_span(7, 1, 'paint', 10.0, 1.0)] + hb7)
    _w(str(tmp_path / 'trace-9.jsonl'),
       [_span(9, 1, 'paint', 10.0, 1.0)] + hb9)
    res = A.analyze(str(tmp_path))
    assert res['heartbeat']['9']['silent'] is True
    assert res['heartbeat']['7']['silent'] is False
    assert 'SILENT PROCESSES' in A.render_analysis(res)


def test_analyze_empty_and_torn(tmp_path):
    assert A.analyze(str(tmp_path)).get('empty') is True
    with open(str(tmp_path / 'trace-1.jsonl'), 'w') as f:
        f.write(json.dumps(_span(1, 1, 'paint', 1.0, 1.0)) + '\n')
        f.write('{"t":"span","name":"torn')
    res = A.analyze(str(tmp_path))
    assert res['torn_lines'] == 1 and res['nspans'] == 1


def test_analyze_cli(tmp_path, capsys):
    _two_process_trace(tmp_path)
    assert cli_main(['--analyze', str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert 'merged timeline' in out
    assert '101' in out and '202' in out
    assert cli_main(['--analyze', str(tmp_path / 'nope')]) == 2


# ---------------------------------------------------------------------------
# bench regression tracking

NOW = time.time()


def _round(path, n, value, metric='fftpower_wallclock_nmesh256',
           rc=0, note=None, extra=None, parsed=True):
    data = {'n': n, 'rc': rc}
    if parsed:
        rec = {'metric': metric, 'value': value, 'unit': 's',
               'platform': 'tpu'}
        if note:
            rec['note'] = note
        if extra:
            rec.update(extra)
        data['parsed'] = rec
    with open(path, 'w') as f:
        json.dump(data, f)


def test_regress_flags_injected_regression_and_stale(tmp_path):
    root = str(tmp_path)
    _round(os.path.join(root, 'BENCH_r01.json'), 1, 1.00)
    _round(os.path.join(root, 'BENCH_r02.json'), 2, 2.00)  # 2x slower
    old = time.strftime('%Y-%m-%dT%H:%M:%SZ',
                        time.gmtime(NOW - 96 * 3600))
    _round(os.path.join(root, 'BENCH_r03.json'), 3, 1.00,
           note='live TPU run unavailable; reporting the most recent '
                'real-TPU measurement, taken at %s UTC '
                '(BENCH_TPU_CACHE.json)' % old,
           extra={'measured_at': old})
    history = R.build_history(root, now=NOW)
    by_file = {e['file']: e for e in history['rounds']}
    assert by_file['BENCH_r01.json']['verdict'] == 'ok'
    assert by_file['BENCH_r02.json']['verdict'] == 'regression'
    assert '+100%' in by_file['BENCH_r02.json']['why']
    assert by_file['BENCH_r03.json']['verdict'] == 'stale'
    assert by_file['BENCH_r03.json']['age_hours'] == pytest.approx(
        96.0, abs=0.2)
    # the history landed atomically next to the rounds
    with open(os.path.join(root, 'BENCH_HISTORY.json')) as f:
        on_disk = json.load(f)
    assert on_disk['summary']['regression'] == 1
    assert on_disk['summary']['stale'] == 1
    text = R.render_regress(history)
    assert 'STALE' in text and 'REGRESSION' in text
    assert 'WARN' in text
    # stale + regression warn loudly but do not fail the gate
    assert R.gate_rc(history) == 0


def test_regress_cache_age_hours_field_preferred(tmp_path):
    """bench.py's explicit cache_age_hours stamp wins over note
    parsing, and a fresh replay is 'replay', not 'stale'."""
    root = str(tmp_path)
    _round(os.path.join(root, 'BENCH_r01.json'), 1, 1.0,
           extra={'cache_age_hours': 2.0})
    _round(os.path.join(root, 'BENCH_r02.json'), 2, 1.0,
           extra={'cache_age_hours': 30.0})
    history = R.build_history(root, now=NOW, write=False)
    v = {e['file']: e['verdict'] for e in history['rounds']}
    assert v['BENCH_r01.json'] == 'replay'
    assert v['BENCH_r02.json'] == 'stale'


def test_regress_malformed_record_fails_gate(tmp_path, capsys):
    root = str(tmp_path)
    _round(os.path.join(root, 'BENCH_r01.json'), 1, 1.0)
    # rc=0 round whose record is missing value/unit: the smoke-gate
    # failure mode
    with open(os.path.join(root, 'BENCH_r02.json'), 'w') as f:
        json.dump({'n': 2, 'rc': 0, 'parsed': {'metric': 'm'}}, f)
    with open(os.path.join(root, 'BENCH_r03.json'), 'w') as f:
        f.write('{not json')
    history = R.build_history(root, now=NOW, write=False)
    v = {e['file']: e['verdict'] for e in history['rounds']}
    assert v['BENCH_r02.json'] == 'malformed'
    assert v['BENCH_r03.json'] == 'malformed'
    assert R.gate_rc(history) == 1
    assert cli_main(['--regress', root]) == 1
    assert 'FAIL' in capsys.readouterr().out


def test_regress_failed_rounds_are_no_result_not_malformed(tmp_path):
    root = str(tmp_path)
    _round(os.path.join(root, 'BENCH_r01.json'), 1, None, rc=124,
           parsed=False)
    _round(os.path.join(root, 'BENCH_r02.json'), 2, -1, rc=1,
           extra={'error': 'tunnel wedged'})
    history = R.build_history(root, now=NOW, write=False)
    assert all(e['verdict'] == 'no-result' for e in history['rounds'])
    assert R.gate_rc(history) == 0


def test_regress_committed_round5_is_stale():
    """ISSUE 2 acceptance: --regress over the repo's committed
    BENCH_r*.json flags the round-5 cache-replayed record as stale."""
    history = R.build_history(REPO, write=False)
    by_file = {e['file']: e for e in history['rounds']}
    r5 = by_file['BENCH_r05.json']
    assert r5['verdict'] == 'stale'
    assert r5['replay'] is True
    assert 'NOT a fresh number' in r5['why']
    # nothing committed may be malformed (the smoke gate runs this)
    assert history['summary']['malformed'] == 0
    assert R.gate_rc(history) == 0


# ---------------------------------------------------------------------------
# doctor

def test_doctor_self_check_only(capsys):
    assert cli_main(['--doctor', '--self-check-only']) == 0
    out = capsys.readouterr().out
    assert 'nbodykit-tpu doctor' in out
    assert 'self-check   OK' in out
    assert 'VERDICT: OK' in out


def test_doctor_full_block(tmp_path, capsys):
    _two_process_trace(tmp_path)
    root = str(tmp_path / 'bench')
    os.makedirs(root)
    _round(os.path.join(root, 'BENCH_r01.json'), 1, 1.0)
    rc = cli_main(['--doctor', '--trace', str(tmp_path),
                   '--root', root])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'analyze      OK' in out
    assert 'regress      OK' in out
    assert 'VERDICT: OK' in out


def test_doctor_fails_on_hung_collective(tmp_path, capsys):
    _w(str(tmp_path / 'trace-7.jsonl'),
       [_span(7, 1, 'exchange', 1.0, 0.5)])
    _w(str(tmp_path / 'trace-8.jsonl'),
       [_begin(8, 1, 'exchange', 1.0)])
    root = str(tmp_path / 'bench')
    os.makedirs(root)
    rc = cli_main(['--doctor', '--trace', str(tmp_path),
                   '--root', root])
    out = capsys.readouterr().out
    assert rc == 1
    assert 'hung' in out and 'VERDICT: FAIL' in out
