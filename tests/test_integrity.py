"""Tests for the end-to-end data-integrity layer
(nbodykit_tpu/resilience/integrity.py, docs/INTEGRITY.md).

The detection matrix is the core contract: with ``integrity='cheap'``
every clean program on the 8-device mesh reports ZERO violations
(including every registered paint candidate and both FFT
decompositions under every wire format), and every injected
``corrupt`` fault is caught by its OWNING guard — the corruption flows
through the real guarded surface, so the detector is what gets tested,
not the injector.  Tier 2 is covered end to end: Supervisor
retry-once-with-strike, two-strike quarantine into the sealed fleet
manifest, adoption + own-rank refusal on reload.  Tier 1 (shadow
verification) is covered in the serve tests below.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options, diagnostics
from nbodykit_tpu.diagnostics import REGISTRY
from nbodykit_tpu.parallel import dfft
from nbodykit_tpu.parallel.runtime import (cpu_mesh, pencil_mesh,
                                           use_mesh)
from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.resilience import (IntegrityError, RetryPolicy,
                                     Supervisor, checks_enabled,
                                     integrity_mode, reset_faults,
                                     reset_integrity, reset_suspects,
                                     shadow_margin, suspect_tracker,
                                     violation_counts)
from nbodykit_tpu.resilience.integrity import (check_a2a, check_close,
                                               check_mass,
                                               corrupt_host,
                                               flip_bits_value,
                                               violation)
from nbodykit_tpu.tune.space import registered_paint_candidates


@pytest.fixture(autouse=True)
def _clean_state():
    """Options, fault counts, the violation ledger and the suspect
    tracker are process-wide; every test sees (and leaves) a pristine
    copy."""
    saved = _global_options.copy()
    REGISTRY.reset()
    reset_faults()
    reset_integrity()
    reset_suspects()
    yield
    REGISTRY.reset()
    reset_faults()
    reset_integrity()
    reset_suspects()
    diagnostics.configure(None)
    _global_options.clear()
    _global_options.update(saved)


def _pos(n=2000, box=64.0, seed=3):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(0.0, box, (n, 3)), jnp.float32)


def _field(nmesh=32, seed=5):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((nmesh,) * 3), jnp.float32)


# ---------------------------------------------------------------------------
# the corruption primitive: catastrophic by construction

def test_flip_bits_catastrophic_for_any_finite_input():
    """The stuck-at-one exponent fault must land ANY finite input at a
    magnitude no rounding budget can absorb (or at inf/NaN, which the
    nonfinite tripwire owns) — detection never depends on the
    corrupted element's value."""
    for v in (0.0, -0.0, 1e-30, 1.0, -3.5, 1e20, -1e38):
        for nbits in (1, 2, 4, 8):
            got = float(flip_bits_value(v, nbits))
            assert not math.isfinite(got) or abs(got) >= 2.0 ** 64, \
                (v, nbits, got)


def test_corrupt_host_flips_exactly_one_element():
    arr = np.linspace(0.0, 1.0, 16, dtype=np.float32)
    out = corrupt_host(arr, 1)
    assert out.dtype == np.float32 and out.shape == arr.shape
    assert not math.isfinite(out[0]) or abs(out[0]) >= 2.0 ** 64
    np.testing.assert_array_equal(out[1:], arr[1:])
    # the input is untouched (a copy, not an in-place flip)
    assert arr[0] == 0.0


# ---------------------------------------------------------------------------
# the mode knob and the comparators

def test_integrity_option_resolution():
    assert integrity_mode() == 'off' and not checks_enabled()
    with nbodykit_tpu.set_options(integrity='cheap'):
        assert integrity_mode() == 'cheap' and checks_enabled()
    with nbodykit_tpu.set_options(integrity='off'):
        assert not checks_enabled()
    with nbodykit_tpu.set_options(integrity='bogus'):
        with pytest.raises(ValueError):
            integrity_mode()


def test_check_close_budget_and_tripwires():
    # inside budget: returns the delta, no ledger entry
    assert check_close('t.site', 1.0 + 1e-9, 1.0, 1e-6) <= 2e-9
    assert violation_counts()['violations'] == 0
    with pytest.raises(IntegrityError) as ei:
        check_close('t.site', 2.0, 1.0, 1e-6)
    assert ei.value.site == 't.site' and ei.value.delta == 1.0
    with pytest.raises(IntegrityError) as ei:
        check_close('t.site', float('nan'), 1.0, 1e-6)
    assert ei.value.site == 't.site.nonfinite'
    vc = violation_counts()
    assert vc['violations'] == 2
    assert vc['by_site'] == {'t.site': 1, 't.site.nonfinite': 1}


def test_check_mass_and_a2a_comparators():
    check_mass('paint.mass', 1000.0 + 1e-4, 1000.0, 1000.0,
               10 ** 6, 'f4')
    with pytest.raises(IntegrityError):
        check_mass('paint.mass', 1100.0, 1000.0, 1000.0, 10 ** 6, 'f4')
    check_a2a('a2a.t', 5.0, 5.0 + 1e-9, 1e-6)
    with pytest.raises(IntegrityError):
        check_a2a('a2a.t', 5.0, 6.0, 1e-6)
    with pytest.raises(IntegrityError) as ei:
        check_a2a('a2a.t', float('inf'), 6.0, 1e-6)
    assert ei.value.site == 'a2a.t.nonfinite'


def test_shadow_margin_from_options():
    assert shadow_margin({}) == 0.0
    assert shadow_margin({'a2a_compress': 'bf16'}) > 0.0
    assert shadow_margin({'a2a_compress': 'int16',
                          'mesh_dtype': 'bf16'}) > \
        shadow_margin({'a2a_compress': 'int16'})


# ---------------------------------------------------------------------------
# zero false positives: clean programs under integrity='cheap'

CANDS = {c.name: c.options for c in registered_paint_candidates(32,
                                                                4000)}


@pytest.mark.parametrize('name', sorted(CANDS))
def test_paint_candidates_clean_under_cheap(name, cpu8):
    """Every registered paint candidate, eager on the 8-device mesh
    with the guard armed: zero violations (the mass budget absorbs
    legitimate tree-reduction and bf16 storage rounding)."""
    pm = ParticleMesh(Nmesh=32, BoxSize=64.0, dtype='f4', comm=cpu8)
    opts = dict(CANDS[name], integrity='cheap')
    with nbodykit_tpu.set_options(**opts):
        out = pm.paint(_pos())
    assert np.isfinite(np.asarray(out)).all()
    assert violation_counts()['violations'] == 0


@pytest.mark.parametrize('case', ['slab', 'pencil', 'slab-bf16',
                                  'slab-int16', 'roundtrip'])
def test_fft_clean_under_cheap(case, cpu8):
    """Both decompositions and both compressed wire formats run the
    guarded eager FFT with zero violations — the a2a fold budgets
    absorb exactly the quantization each format implies."""
    x = _field()
    opts = {'integrity': 'cheap'}
    mesh = cpu8
    if case == 'pencil':
        mesh = pencil_mesh(px=4, py=2)
    elif case.startswith('slab-'):
        opts['a2a_compress'] = case.split('-')[1]
    with nbodykit_tpu.set_options(**opts):
        y = dfft.dist_rfftn(x, mesh)
        if case == 'roundtrip':
            back = dfft.dist_irfftn(y, x.shape[0], mesh)
            np.testing.assert_allclose(np.asarray(back),
                                       np.asarray(x), atol=1e-4)
    assert violation_counts()['violations'] == 0


def test_integrity_off_is_bit_identical(cpu8):
    """The acceptance bit-identity contract: integrity='off' compiles
    and executes the exact program shipped before this layer existed,
    and 'cheap' only ADDS reductions — the data path is unchanged."""
    pm = ParticleMesh(Nmesh=32, BoxSize=64.0, dtype='f4', comm=cpu8)
    pos, x = _pos(), _field()
    with nbodykit_tpu.set_options(integrity='off'):
        f_off = np.asarray(pm.paint(pos))
        y_off = np.asarray(dfft.dist_rfftn(x, cpu8))
    with nbodykit_tpu.set_options(integrity='cheap'):
        f_chk = np.asarray(pm.paint(pos))
        y_chk = np.asarray(dfft.dist_rfftn(x, cpu8))
    np.testing.assert_array_equal(f_off, f_chk)
    np.testing.assert_array_equal(y_off, y_chk)


# ---------------------------------------------------------------------------
# the detection matrix: every injected corruption caught by its
# owning guard

MATRIX = [
    ('paint', 'paint.accum@1:corrupt', {}, 'paint.mass'),
    ('slab-r2c', 'a2a.payload@1:corrupt', {}, 'a2a.slab.r2c'),
    ('slab-c2r', 'a2a.payload@1:corrupt', {}, 'a2a.slab.c2r'),
    ('pencil-stage1', 'a2a.payload@1:corrupt', {},
     'a2a.pencil.r2c.stage1'),
    ('pencil-stage2', 'a2a.payload@2:corrupt', {},
     'a2a.pencil.r2c.stage2'),
    ('slab-r2c', 'a2a.payload@1:corrupt', {'a2a_compress': 'bf16'},
     'a2a.slab.r2c'),
    ('slab-r2c', 'a2a.payload@1:corrupt', {'a2a_compress': 'int16'},
     'a2a.slab.r2c'),
]


@pytest.mark.parametrize('kind,spec,extra,owner', MATRIX)
def test_detection_matrix(kind, spec, extra, owner, cpu8):
    """One corrupt point at a time: the guard that owns the surface —
    and no other — must classify the corruption.  A saturated exponent
    may overflow the fold to inf, in which case the same guard's
    ``.nonfinite`` tripwire fires; both spell detection by the owner.
    """
    # the c2r case needs a clean spectrum BEFORE the rule arms — the
    # forward transform's own a2a would consume the injection first
    y = dfft.dist_rfftn(_field(), cpu8) if kind == 'slab-c2r' else None
    opts = dict(extra, integrity='cheap', faults=spec)
    with nbodykit_tpu.set_options(**opts):
        reset_faults()
        with pytest.raises(IntegrityError) as ei:
            if kind == 'paint':
                pm = ParticleMesh(Nmesh=32, BoxSize=64.0, dtype='f4',
                                  comm=cpu8)
                pm.paint(_pos())
            elif kind.startswith('pencil'):
                dfft.dist_rfftn(_field(), pencil_mesh(px=4, py=2))
            elif kind == 'slab-c2r':
                dfft.dist_irfftn(y, 32, cpu8)
            else:
                dfft.dist_rfftn(_field(), cpu8)
    assert ei.value.site.startswith(owner), ei.value.site
    assert 'DATA_CORRUPTION' in str(ei.value)
    assert violation_counts()['violations'] == 1


def test_corruption_undetected_when_integrity_off(cpu8):
    """integrity='off' must not pay for detection: the corrupt rule
    still fires (the injector is independent) but nothing raises —
    which is exactly why 'cheap' exists."""
    with nbodykit_tpu.set_options(faults='a2a.payload@1:corrupt'):
        reset_faults()
        y = dfft.dist_rfftn(_field(), cpu8)
    assert violation_counts()['violations'] == 0
    # the poisoned element really is in the spectrum
    assert not np.isfinite(np.asarray(y)).all() or \
        np.abs(np.asarray(y)).max() >= 2.0 ** 64


# ---------------------------------------------------------------------------
# tier 2: supervisor retry-once + strike, quarantine, sealed manifest

def test_supervisor_retries_integrity_exactly_once():
    state = {'n': 0}

    def task():
        state['n'] += 1
        if state['n'] == 1:
            raise violation('test.guard', rank=3, delta=42.0)
        return 'ok'

    sup = Supervisor('t', policy=RetryPolicy(max_retries=0))
    assert sup.run(task) == 'ok'
    kinds = [e['kind'] for e in sup.events]
    assert kinds == ['integrity_retries']
    assert suspect_tracker().strike_counts() == {3: 1}
    assert suspect_tracker().quarantined() == []


def test_supervisor_second_violation_reraises_and_quarantines():
    def task():
        raise violation('test.guard', rank=5, delta=1.0)

    sup = Supervisor('t', policy=RetryPolicy(max_retries=3,
                                             base_s=0.001))
    with pytest.raises(IntegrityError):
        sup.run(task)
    # one retry, then the re-raise; both strikes recorded -> K=2
    # quarantines the rank
    assert suspect_tracker().strike_counts() == {5: 2}
    assert suspect_tracker().quarantined() == [5]


def test_quarantine_rides_sealed_manifest_and_reload(tmp_path):
    from nbodykit_tpu.resilience import FleetCheckpointStore
    tr = suspect_tracker()
    tr.strike(1, site='a2a.slab.r2c', task='t')
    tr.strike(1, site='a2a.slab.r2c', task='t')
    assert tr.quarantined() == [1]

    st = FleetCheckpointStore(tmp_path)
    for r in range(2):
        st.save_shard('k', 1, r, 2, {'step': 7},
                      arrays={'x': np.arange(4.0) + r})
    st.seal('k', 1, nranks=2, rank=0)
    man = st.latest_manifest('k')
    assert man['quarantined'] == [1]

    # a fresh process adopting the sealed checkpoint inherits the list
    reset_suspects()
    state, arrays, info = st.load('k', rank=0, nranks=2)
    assert state == {'step': 7} and info['quarantined'] == [1]
    assert suspect_tracker().is_quarantined(1)

    # and the quarantined rank itself REFUSES to rejoin
    with pytest.raises(RuntimeError, match='quarantined'):
        st.load('k', rank=1, nranks=2)
    snap = REGISTRY.snapshot().get('resilience.fleet.'
                                   'quarantine_refused')
    assert snap and snap['value'] == 1


def test_manifest_without_quarantine_stays_backcompat(tmp_path):
    """An empty quarantine list must not change the sealed body — an
    old manifest keeps verifying, and a new one without strikes is
    byte-compatible with the pre-integrity format."""
    from nbodykit_tpu.resilience import FleetCheckpointStore
    st = FleetCheckpointStore(tmp_path)
    for r in range(2):
        st.save_shard('k', 1, r, 2, {'step': 1})
    st.seal('k', 1, nranks=2, rank=0)
    man = st.latest_manifest('k')
    assert man is not None and 'quarantined' not in man
    got = st.load('k', rank=0, nranks=2)
    # no strikes → the info dict too stays byte-compatible (no key)
    assert got is not None and 'quarantined' not in got[2]


# ---------------------------------------------------------------------------
# tier 1: shadow verification in serve

def _server(**kw):
    from nbodykit_tpu.serve import AnalysisServer, BatchPolicy
    kw.setdefault('batch', BatchPolicy(max_delay_s=0))
    kw.setdefault('retry', RetryPolicy(max_retries=1, base_s=0.01))
    return AnalysisServer(per_task=4, **kw)


def test_request_verify_flag_rules():
    from nbodykit_tpu.serve import AnalysisRequest
    r = AnalysisRequest(nmesh=32, npart=20000, seed=1, verify=True)
    assert r.verify and r.to_dict()['verify'] is True
    # verify is a scheduling attribute, not program identity
    plain = AnalysisRequest(nmesh=32, npart=20000, seed=1)
    assert r.program_key() == plain.program_key()
    with pytest.raises(ValueError, match='verify'):
        AnalysisRequest(nmesh=32, data_ref={'path': 'x',
                                            'format': 'binary'},
                        verify=True)


def test_shadow_verification_bit_identical_clean():
    from nbodykit_tpu.serve import AnalysisRequest
    with _server() as srv:
        assert len(srv.meshes) >= 2, 'shadow needs two sub-meshes'
        r = srv.wait(srv.submit(AnalysisRequest(
            nmesh=32, npart=20000, seed=3, verify=True)), timeout=300)
        summary = srv.summary()
    assert r.status == 'completed'
    assert summary['shadow_verified'] == 1
    assert summary['shadow_mismatch'] == 0
    assert summary['integrity_retried'] == 0


def test_shadow_catches_corrupted_result_and_retries():
    """serve.result corruption happens AFTER compute — no tier-0
    invariant can see it; only the shadow re-execution can.  The
    mismatch classifies as INTEGRITY, the supervisor strikes + retries
    once, the rule has burnt out, and the clean result is delivered.
    """
    from nbodykit_tpu.serve import AnalysisRequest
    with nbodykit_tpu.set_options(faults='serve.result@1:corrupt'):
        reset_faults()
        with _server() as srv:
            r = srv.wait(srv.submit(AnalysisRequest(
                nmesh=32, npart=20000, seed=3, verify=True)),
                timeout=300)
            summary = srv.summary()
    assert r.status == 'completed'
    assert r.event_count('integrity_retries') == 1
    assert summary['shadow_verified'] == 2
    assert summary['shadow_mismatch'] == 1
    assert summary['integrity_retried'] == 1
    assert np.isfinite(np.asarray(r.y, dtype=np.float64)).all()
    assert suspect_tracker().summary()['strikes'] == 1


# ---------------------------------------------------------------------------
# the posture: regress + doctor

def test_integrity_summary_and_doctor_fail_on_unacknowledged(tmp_path):
    from nbodykit_tpu.diagnostics.__main__ import run_doctor
    from nbodykit_tpu.diagnostics.regress import integrity_summary
    root = str(tmp_path)
    assert integrity_summary(root) is None
    with open(os.path.join(root, 'BENCH_r10.json'), 'w') as f:
        json.dump({'parsed': {
            'metric': 'integrity_nmesh64', 'value': 1.0, 'unit': 's',
            'integrity': {'violations': 1, 'retried': 1}}}, f)
    s = integrity_summary(root)
    assert s['stamped_records'] == 1 and s['violations'] == 1 \
        and s['retried'] == 1 and s['unacknowledged_mismatch'] == 0

    # a shadow mismatch nobody retried is the doctor's hard failure
    with open(os.path.join(root, 'BENCH_r11.json'), 'w') as f:
        json.dump({'parsed': {
            'metric': 'servetrace_n8', 'value': 0.5, 'unit': 's',
            'requests': 8, 'rps': 2.0, 'p99_s': 0.5, 'lost': 0,
            'shadow_verified': 3, 'shadow_mismatch': 2,
            'integrity_retried': 1}}, f)
    s = integrity_summary(root)
    assert s['unacknowledged_mismatch'] == 1
    import io as _io
    out = _io.StringIO()
    rc = run_doctor(root=root, out=out, self_check_only=False)
    text = out.getvalue()
    line = [ln for ln in text.splitlines()
            if ln.startswith('integrity')][0]
    assert rc == 1 and 'FAIL' in line and 'shadow' in line
    assert 'integrity' in text.split('VERDICT:')[1]
