"""Pencil-decomposed distributed FFT (parallel/dfft.py pencil path).

Equivalence oracles: the slab path and single-device jnp.fft on the
same 8-device CPU mesh, at every factorization of 8 — including the
degenerate 8x1 (== slab) — plus ragged shapes (exact fallback, never
zero-padded), r2c/c2r/c2c roundtrips, composition under an outer jit,
and bit-identical determinism.  Also units for the runtime helpers
(pencil_mesh / default_pencil_factor), dispatch-time decomp resolution
(resolve_decomp / dist_fft_plan / set_options), the factorization-
keyed tune-cache classes, and the memory_plan pencil branch.

x64 is on (conftest), so the jnp.fft oracle comparisons run at double
precision and the 1e-10 acceptance bar is meaningful.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu.parallel import dfft
from nbodykit_tpu.parallel.runtime import (cpu_mesh,
                                           default_pencil_factor,
                                           is_pencil, mesh_shape2d,
                                           pencil_mesh)

FACTORIZATIONS = [(4, 2), (2, 4), (8, 1), (1, 8)]


def _real(shape, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.standard_normal(shape), jnp.float64)


def _cplx(shape, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.standard_normal(shape)
                       + 1j * rng.standard_normal(shape),
                       jnp.complex128)


def _ref_rfftn(x):
    return np.transpose(np.fft.rfftn(np.asarray(x)), (1, 0, 2))


# ---------------------------------------------------------------- r2c

@pytest.mark.parametrize('pxpy', FACTORIZATIONS,
                         ids=['%dx%d' % f for f in FACTORIZATIONS])
def test_pencil_rfftn_matches_jnp_and_slab(pxpy):
    # N2=10 -> Nc=6: indivisible by py for 4 of the runs, so the
    # z-axis zero-pad + output slice path is exercised, not just the
    # pad=0 degenerate case
    x = _real((16, 16, 10), seed=1)
    pm = pencil_mesh(*pxpy)
    got = np.asarray(dfft.dist_rfftn(x, pm))
    np.testing.assert_allclose(got, _ref_rfftn(x), atol=1e-10)
    slab = np.asarray(dfft.dist_rfftn(x, cpu_mesh()))
    np.testing.assert_allclose(got, slab, atol=1e-10)


def test_pencil_rfftn_ortho_norm():
    x = _real((8, 8, 8), seed=2)
    pm = pencil_mesh(2, 4)
    got = np.asarray(dfft.dist_rfftn(x, pm, norm='ortho'))
    want = np.transpose(np.fft.rfftn(np.asarray(x), norm='ortho'),
                        (1, 0, 2))
    np.testing.assert_allclose(got, want, atol=1e-10)


@pytest.mark.parametrize('pxpy', FACTORIZATIONS,
                         ids=['%dx%d' % f for f in FACTORIZATIONS])
def test_pencil_roundtrip_r2c_c2r(pxpy):
    x = _real((16, 8, 12), seed=3)
    pm = pencil_mesh(*pxpy)
    y = dfft.dist_rfftn(x, pm)
    back = np.asarray(dfft.dist_irfftn(y, 12, pm))
    np.testing.assert_allclose(back, np.asarray(x), atol=1e-10)


def test_pencil_c2r_matches_slab():
    x = _real((16, 16, 10), seed=4)
    y = dfft.dist_rfftn(x, cpu_mesh())      # slab-produced spectrum
    pm = pencil_mesh(4, 2)
    got = np.asarray(dfft.dist_irfftn(y, 10, pm))
    want = np.asarray(dfft.dist_irfftn(y, 10, cpu_mesh()))
    np.testing.assert_allclose(got, want, atol=1e-10)
    np.testing.assert_allclose(got, np.asarray(x), atol=1e-10)


# ---------------------------------------------------------------- c2c

@pytest.mark.parametrize('pxpy', [(4, 2), (2, 4)],
                         ids=['4x2', '2x4'])
def test_pencil_c2c_forward_and_inverse(pxpy):
    x = _cplx((16, 16, 6), seed=5)
    pm = pencil_mesh(*pxpy)
    y = dfft.dist_fftn_c2c(x, pm)
    want = np.transpose(np.fft.fftn(np.asarray(x)), (1, 0, 2))
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-10)
    back = dfft.dist_fftn_c2c(y, pm, inverse=True)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=1e-10)


# ------------------------------------------------------- ragged shapes

def test_pencil_ragged_shape_is_exact():
    """A shape that does not factor into pencils falls back to exact
    semantics (never a zero-padded transform)."""
    from nbodykit_tpu.diagnostics import counter
    x = _real((10, 12, 8), seed=6)          # 10 % 4 != 0 on a 4x2 mesh
    pm = pencil_mesh(4, 2)
    before = counter('fft.pencil.fallback').value
    got = np.asarray(dfft.dist_rfftn(x, pm))
    assert counter('fft.pencil.fallback').value > before
    np.testing.assert_allclose(got, _ref_rfftn(x), atol=1e-10)
    back = np.asarray(dfft.dist_irfftn(jnp.asarray(got), 8, pm))
    np.testing.assert_allclose(back, np.asarray(x), atol=1e-10)


def test_pencil_ragged_n1_is_exact():
    x = _real((16, 10, 8), seed=7)          # 10 % 4 != 0 on a 2x4 mesh
    got = np.asarray(dfft.dist_rfftn(x, pencil_mesh(2, 4)))
    np.testing.assert_allclose(got, _ref_rfftn(x), atol=1e-10)


# --------------------------------------------- composition + determinism

def test_pencil_composes_under_jit():
    x = _real((16, 16, 10), seed=8)
    pm = pencil_mesh(2, 4)
    f = jax.jit(lambda v: dfft.dist_rfftn(v, pm))
    np.testing.assert_allclose(np.asarray(f(x)),
                               np.asarray(dfft.dist_rfftn(x, pm)),
                               atol=1e-10)


def test_pencil_bit_identical_determinism():
    x = _real((16, 16, 10), seed=9)
    pm = pencil_mesh(4, 2)
    a = np.asarray(dfft.dist_rfftn(x, pm))
    b = np.asarray(dfft.dist_rfftn(x, pm))
    assert np.array_equal(a, b)             # exact, not allclose
    rt1 = np.asarray(dfft.dist_irfftn(dfft.dist_rfftn(x, pm), 10, pm))
    rt2 = np.asarray(dfft.dist_irfftn(dfft.dist_rfftn(x, pm), 10, pm))
    assert np.array_equal(rt1, rt2)


# ----------------------------------------------------- runtime helpers

def test_default_pencil_factor():
    assert default_pencil_factor(8) == (2, 4)
    assert default_pencil_factor(4) == (2, 2)
    assert default_pencil_factor(6) == (2, 3)
    assert default_pencil_factor(12) == (3, 4)
    assert default_pencil_factor(7) == (1, 7)   # prime: degenerate
    assert default_pencil_factor(1) == (1, 1)


def test_pencil_mesh_construction():
    pm = pencil_mesh()                      # near-square default
    assert is_pencil(pm)
    assert mesh_shape2d(pm) == (2, 4)
    assert pm.axis_names == ('x', 'y')
    pm2 = pencil_mesh(4)                    # inferred py
    assert mesh_shape2d(pm2) == (4, 2)
    pm3 = pencil_mesh(py=8)
    assert mesh_shape2d(pm3) == (1, 8)
    with pytest.raises(ValueError):
        pencil_mesh(3, 2)                   # 6 != 8 devices
    assert not is_pencil(cpu_mesh())
    # flattened pencil device order == the 1-D slab mesh order, so
    # slab- and pencil-sharded fields interconvert without movement
    assert list(pm.devices.reshape(-1)) == \
        list(cpu_mesh().devices.reshape(-1))


# ------------------------------------------------- dispatch resolution

def test_resolve_decomp_defaults_and_overrides():
    # cold cache / default options -> slab, near-square factorization
    assert dfft.resolve_decomp(1) == ('slab', None)
    decomp, pxpy = dfft.resolve_decomp(8)
    assert decomp == 'slab' and pxpy == (2, 4)
    # explicit arguments win
    assert dfft.resolve_decomp(8, decomp='pencil') == ('pencil', (2, 4))
    assert dfft.resolve_decomp(8, pencil='8x1') == ('slab', (8, 1))
    # options drive the resolution when no explicit argument is given
    with nbodykit_tpu.set_options(fft_decomp='pencil',
                                  fft_pencil='4x2'):
        assert dfft.resolve_decomp(8) == ('pencil', (4, 2))
    with pytest.raises(ValueError):
        dfft.resolve_decomp(8, pencil='3x2')    # does not cover 8
    with pytest.raises(ValueError):
        dfft.resolve_decomp(8, decomp='banana')


def test_plan_dispatches_pencil_via_options():
    x = _real((16, 16, 12), seed=10)
    plan = dfft.dist_fft_plan((16, 16, 12), cpu_mesh())
    slab = np.asarray(plan.r2c(x))
    with nbodykit_tpu.set_options(fft_decomp='pencil'):
        pen = plan.r2c(x)
        np.testing.assert_allclose(np.asarray(pen), slab, atol=1e-10)
        back = np.asarray(plan.c2r(pen))
    np.testing.assert_allclose(back, np.asarray(x), atol=1e-10)


def test_plan_explicit_2d_mesh_wins():
    x = _real((16, 16, 12), seed=11)
    plan = dfft.dist_fft_plan((16, 16, 12), pencil_mesh(4, 2))
    np.testing.assert_allclose(np.asarray(plan.r2c(x)), _ref_rfftn(x),
                               atol=1e-10)


# ------------------------------------------- factorization-keyed cache

def test_shape_class_carries_factorization():
    from nbodykit_tpu.tune.cache import (class_distance,
                                         class_factorization,
                                         shape_class)
    assert shape_class(nmesh=64, mesh_shape=(4, 2)) == 'mesh64-g4x2'
    assert class_factorization('mesh64-g4x2') == (4, 2)
    assert class_factorization('mesh64') is None
    # winners never travel across device-mesh factorizations: a 4x2
    # measurement must not answer an 8x1 (or unfactorized) question
    assert class_distance('mesh64-g4x2', 'mesh64-g8x1') is None
    assert class_distance('mesh64-g4x2', 'mesh64') is None
    d = class_distance('mesh64-g4x2', 'mesh128-g4x2')
    assert d is not None and d > 0
    # committed suffix-less entries stay reachable for slab questions
    assert class_distance('mesh64', 'mesh128') is not None


def test_memory_plan_pencil_branch():
    from nbodykit_tpu.parallel.dfft import PENCIL_BUFFERS
    from nbodykit_tpu.pmesh import memory_plan
    plan = memory_plan(1024, int(1e8), ndevices=8,
                       fft_decomp='pencil')
    assert plan['fft_pencil'] == '2x4'
    assert plan['fft_pencil_buffers'] == PENCIL_BUFFERS == 2
    assert plan['fft_pencil_pad'] >= 1.0
    slab = memory_plan(1024, int(1e8), ndevices=8)
    # the pencil staging is the slab's 2 complex units scaled by the
    # z pad — never cheaper than slab, only padded
    assert plan['fft_workspace'] >= slab['fft_workspace']
    assert 'fft_pencil' not in slab
    # single device: the knob is meaningless, the slab model applies
    single = memory_plan(1024, int(1e8), fft_decomp='pencil')
    assert 'fft_pencil' not in single
