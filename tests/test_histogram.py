"""ops/histogram (the MXU (k,mu)-binning engine) and the bench.py
fused pipeline that uses it.

Oracles: exact numpy scatter-add histograms, and the production
FFTPower binning (itself verified against an independent numpy oracle
in test_fftpower.py).
"""

import sys
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from nbodykit_tpu.ops.histogram import (hist2d_mxu, hist2d_bincount,
                                        hist2d_weighted)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _ref_hist(a, b, ws, NA, NB):
    outs = []
    for w in ws:
        H = np.zeros((NA, NB))
        np.add.at(H, (np.asarray(a), np.asarray(b)), np.asarray(w, 'f8'))
        outs.append(H)
    return outs


@pytest.mark.parametrize("method", ["mxu", "bincount"])
def test_hist2d_matches_numpy(method):
    rng = np.random.RandomState(0)
    M, NA, NB = 40_000, 37, 12
    a = rng.randint(0, NA, M).astype('i4')
    b = rng.randint(0, NB, M).astype('i4')
    ws = [rng.uniform(0.5, 2.0, M), rng.standard_normal(M)]
    refs = _ref_hist(a, b, ws, NA, NB)
    got = hist2d_weighted(jnp.asarray(a), jnp.asarray(b),
                          [jnp.asarray(w) for w in ws], NA, NB,
                          method=method, chunk=8192)
    scale = max(np.abs(refs[1]).max(), 1.0)
    np.testing.assert_allclose(np.asarray(got[0]), refs[0], rtol=3e-6)
    np.testing.assert_allclose(np.asarray(got[1]) / scale,
                               refs[1] / scale, atol=3e-6)


def test_hist2d_mxu_chunk_tail():
    """M not divisible by chunk: the padded tail must not contribute."""
    rng = np.random.RandomState(1)
    M, NA, NB = 10_001, 9, 5
    a = rng.randint(0, NA, M).astype('i4')
    b = rng.randint(0, NB, M).astype('i4')
    w = rng.uniform(1.0, 2.0, M)
    (ref,) = _ref_hist(a, b, [w], NA, NB)
    (got,) = hist2d_mxu(jnp.asarray(a), jnp.asarray(b),
                        [jnp.asarray(w)], NA, NB, chunk=4096)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-6)
    assert float(np.asarray(got).sum()) == pytest.approx(w.sum(),
                                                         rel=1e-6)


def test_hist2d_under_jit():
    a = jnp.asarray([0, 1, 2, 1], jnp.int32)
    b = jnp.asarray([0, 0, 1, 1], jnp.int32)
    w = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    f = jax.jit(lambda a, b, w: hist2d_mxu(a, b, [w], 3, 2, chunk=2)[0])
    got = np.asarray(f(a, b, w))
    want = np.array([[1.0, 0.0], [2.0, 4.0], [0.0, 3.0]])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_bench_pipeline_matches_fftpower():
    """bench.py's fused paint->fft->bin program must agree with the
    production FFTPower(mode='2d') on the in-range bins."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'bench_mod', os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), 'bench.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.lab import FFTPower, ArrayCatalog

    Nmesh, Npart, L = 64, 20_000, 1000.0
    rng = np.random.RandomState(5)
    pos = rng.uniform(0, L, (Npart, 3)).astype('f4')

    nbodykit_tpu.set_options(paint_method='scatter')
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=L, dtype='f4')
    fused, _phases = bench._bench_fftpower_fn(pm, slab_chunks=8)
    fn = jax.jit(fused)
    Psum, Nsum = (np.asarray(x, 'f8') for x in fn(jnp.asarray(pos)))
    with np.errstate(invalid='ignore'):
        Pmu = Psum / Nsum

    # 1. mode counts must EXACTLY match the integer-lattice oracle
    # (the bench bins on integer norms: isq vs m^2, 25*iz^2 vs m^2*isq)
    ix = np.fft.fftfreq(Nmesh, d=1.0 / Nmesh).astype('i8')
    IX, IY, IZ = np.meshgrid(ix, ix, np.arange(Nmesh // 2 + 1,
                                               dtype='i8'),
                             indexing='ij')
    ISQ = IX ** 2 + IY ** 2 + IZ ** 2
    w = np.where((IZ > 0) & (IZ < Nmesh // 2), 2.0, 1.0)
    Nx = Nmesh // 2
    dig_k = np.searchsorted(np.arange(Nx + 1) ** 2, ISQ.ravel(),
                            side='right')
    dig_mu = sum((25 * IZ ** 2 >= (m * m) * ISQ).astype('i8')
                 for m in range(1, 6))
    dig_mu = (np.where(ISQ == 0, 0, dig_mu) + 6).ravel()
    NsumO = np.zeros((Nx + 2, 12))
    np.add.at(NsumO, (dig_k, dig_mu), w.ravel())
    np.testing.assert_array_equal(Nsum, NsumO)

    # 2. P values must match the production FFTPower on bins whose
    # counts agree (production digitizes float coordinates, so modes on
    # Pythagorean lattice edges may sit in the neighboring bin there)
    cat = ArrayCatalog({'Position': pos}, BoxSize=L, comm=None)
    mesh = cat.to_mesh(Nmesh=Nmesh, resampler='cic', compensated=True,
                       dtype='f4')
    r = FFTPower(mesh, mode='2d', dk=2 * np.pi / L, kmin=0.0, Nmu=10,
                 los=[0, 0, 1])
    Pref = np.asarray(r.power['power'].real)
    Nref = np.asarray(r.power['modes'], dtype='f8')

    # fold the internal mu==1 bin like the production path does
    PmuF = Psum.copy()
    NsumF = Nsum.copy()
    PmuF[:, -2] += PmuF[:, -1]
    NsumF[:, -2] += NsumF[:, -1]
    with np.errstate(invalid='ignore'):
        PmuF = PmuF / NsumF
    got = PmuF[1:-1, 1:-1][:Pref.shape[0], :]
    gotN = NsumF[1:-1, 1:-1][:Pref.shape[0], :]
    want = Pref[:got.shape[0]]
    wantN = Nref[:got.shape[0]]
    m = np.isfinite(got) & np.isfinite(want)
    # equal counts can still hide a swap of boundary modes with an
    # adjacent bin (one in, one out) — require the neighbors to agree
    # as well before comparing values
    eq = (gotN == wantN)
    same = m & eq
    for ax, sh in ((0, 1), (0, -1), (1, 1), (1, -1)):
        pad = np.ones_like(eq)
        sl_to = [slice(None)] * 2
        sl_from = [slice(None)] * 2
        if sh > 0:
            sl_to[ax] = slice(1, None); sl_from[ax] = slice(None, -1)
        else:
            sl_to[ax] = slice(None, -1); sl_from[ax] = slice(1, None)
        pad[tuple(sl_to)] = eq[tuple(sl_from)]
        same &= pad
    assert same.sum() > 25
    np.testing.assert_allclose(got[same], want[same], rtol=2e-4)


def test_project_to_basis_chunked_matches_unchunked(monkeypatch):
    """The slab-chunked binning reduction (active at Nmesh >= 1024 on
    one device) must agree exactly with the whole-array path — for both
    the transposed hermitian complex layout (leading axis = ky) and
    real fields (leading axis = rx)."""
    from nbodykit_tpu.algorithms import fftpower as fp
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.base.mesh import Field

    N, L = 32, 100.0
    pm = ParticleMesh(Nmesh=N, BoxSize=L, dtype='f8')
    rng = np.random.RandomState(7)
    field = jnp.asarray(rng.standard_normal((N, N, N)))
    cplx = pm.r2c(field)
    kedges = np.arange(0, np.pi * N / L + np.pi / L, 2 * np.pi / L)
    muedges = np.linspace(-1, 1, 6)

    for kind, val in (('complex', cplx), ('real', field)):
        y3d = Field(val, pm, kind=kind)
        ref2d, refp = fp.project_to_basis(y3d, [kedges, muedges],
                                          poles=[0, 2])
        monkeypatch.setattr(fp, '_BIN_CHUNK_ELEMENTS', 2 * N * N)
        got2d, gotp = fp.project_to_basis(y3d, [kedges, muedges],
                                          poles=[0, 2])
        monkeypatch.undo()
        for a, b in zip(ref2d, got2d):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-12, equal_nan=True)
        np.testing.assert_allclose(np.asarray(refp[1]),
                                   np.asarray(gotp[1]), rtol=1e-12,
                                   equal_nan=True)
