"""Distributed (device-mesh) irregular algorithms: FOF and pair
counting with sharded inputs — the reference's domain-decomposed
execution model (nbodykit/algorithms/fof.py:339-413,
pair_counters/domain.py:47-283) on the 8-device CPU mesh.

Oracles: the single-device implementations (themselves brute-force
tested in test_fof.py / test_paircount.py) — correctness here is
device-count invariance of the results, the reference CI's own
discipline (1-rank vs 4-rank runs of the same suite).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.parallel.runtime import cpu_mesh, shard_leading, use_mesh
from nbodykit_tpu.parallel.domain import (Route, slab_route,
                                          scatter_reduce_by_index,
                                          gather_by_index)
from nbodykit_tpu.algorithms.fof import (FOF, _fof_labels,
                                         _fof_labels_distributed)
from nbodykit_tpu.algorithms.pair_counters.core import (paircount,
                                                        paircount_dist)
from nbodykit_tpu.source.catalog.array import ArrayCatalog


def clustered_positions(N, box, nblob=40, sigma=0.7, seed=3):
    rng = np.random.RandomState(seed)
    centers = rng.uniform(0, box, (nblob, 3))
    half = N // 2
    pts = centers[rng.randint(0, nblob, half)] \
        + rng.normal(0, sigma, (half, 3))
    return np.concatenate([pts % box,
                           rng.uniform(0, box, (N - half, 3))])


def canon_partition(lab):
    """Canonical form: each group labeled by its first member index."""
    _, inv = np.unique(lab, return_inverse=True)
    first = np.full(inv.max() + 1, len(inv), dtype=int)
    np.minimum.at(first, inv, np.arange(len(inv)))
    return first[inv]


# ---------------------------------------------------------------- domain

def test_scatter_reduce_and_gather_by_index(cpu8):
    rng = np.random.RandomState(0)
    M, size = 5000, 1024
    idx = shard_leading(cpu8, jnp.asarray(
        rng.randint(0, size, M), jnp.int32))
    vals = shard_leading(cpu8, jnp.asarray(
        rng.randint(0, 1000, M), jnp.int32))
    got = np.asarray(scatter_reduce_by_index(idx, vals, size, cpu8,
                                             op='add'))[:size]
    want = np.zeros(size, dtype='i4')
    np.add.at(want, np.asarray(idx), np.asarray(vals))
    np.testing.assert_array_equal(got, want)

    table = shard_leading(cpu8, jnp.arange(size, dtype=jnp.int32) * 7)
    looked = np.asarray(gather_by_index(idx, table, cpu8))
    np.testing.assert_array_equal(looked, np.asarray(idx) * 7)


def test_route_realigns_payloads(cpu8):
    """Re-exchanging through the same Route aligns slots across calls."""
    rng = np.random.RandomState(1)
    n = 3000
    dest = shard_leading(cpu8, jnp.asarray(
        rng.randint(0, 8, n), jnp.int32))
    a = shard_leading(cpu8, jnp.arange(n, dtype=jnp.int32))
    route = Route(dest, cpu8)
    (a1,), ok1, _ = route.exchange([a])
    (a2,), ok2, _ = route.exchange([a * 2])
    np.testing.assert_array_equal(np.asarray(ok1), np.asarray(ok2))
    m = np.asarray(ok1)
    np.testing.assert_array_equal(np.asarray(a2)[m],
                                  np.asarray(a1)[m] * 2)


def test_slab_route_ghosts_cover_margins(cpu8):
    box, rmax, N, P = 80.0, 2.0, 2000, 8
    w = box / P
    rng = np.random.RandomState(2)
    pos_np = rng.uniform(0, box, (N, 3))
    pos = shard_leading(cpu8, jnp.asarray(pos_np))
    route, f, live = slab_route(pos, box, rmax, cpu8, ghosts='both')
    assert f == 3
    (p_r, lv), ok, dropped = route.exchange(
        [jnp.concatenate([pos] * f), live])
    assert int(dropped) == 0
    keep = np.asarray(ok & lv)
    p_all = np.asarray(p_r)
    slots_per_dev = p_all.shape[0] // P
    total_live = 0
    for d in range(P):
        sl = slice(d * slots_per_dev, (d + 1) * slots_per_dev)
        got_x = np.sort(p_all[sl][keep[sl]][:, 0])
        # expected: every particle within the slab extended by rmax
        # (periodic in x)
        lo, hi = d * w - rmax, (d + 1) * w + rmax
        x = pos_np[:, 0]
        m = ((x >= lo) & (x < hi)) | (x - box >= lo) | (x + box < hi)
        np.testing.assert_array_equal(got_x, np.sort(x[m]))
        total_live += m.sum()
    assert total_live > N  # ghosts exist


# ------------------------------------------------------------------- FOF

def test_distributed_fof_matches_single_device(cpu8):
    box = 100.0
    pos = clustered_positions(4000, box)
    ll = 0.9
    ref = np.asarray(_fof_labels(pos, np.ones(3) * box, ll,
                                 periodic=True))
    posj = shard_leading(cpu8, jnp.asarray(pos))
    got = np.asarray(_fof_labels_distributed(
        posj, np.ones(3) * box, ll, cpu8, periodic=True))
    np.testing.assert_array_equal(canon_partition(ref),
                                  canon_partition(got))


def test_distributed_fof_nonperiodic(cpu8):
    box = 60.0
    pos = clustered_positions(3000, box, seed=7)
    ll = 0.8
    ref = np.asarray(_fof_labels(pos, np.ones(3) * box, ll,
                                 periodic=False))
    posj = shard_leading(cpu8, jnp.asarray(pos))
    got = np.asarray(_fof_labels_distributed(
        posj, np.ones(3) * box, ll, cpu8, periodic=False))
    np.testing.assert_array_equal(canon_partition(ref),
                                  canon_partition(got))


@pytest.mark.slow
def test_distributed_fof_class_end_to_end(cpu8):
    """FOF class on a sharded catalog: halo count, size ordering and
    partition must match the single-device run."""
    box = 200.0
    pos = clustered_positions(30000, box, nblob=100, sigma=0.5, seed=5)
    with use_mesh(cpu8):
        cat = ArrayCatalog({'Position': pos}, BoxSize=box)
        f = FOF(cat, linking_length=0.2, nmin=8)
        lab_d = np.asarray(f.labels)
    cat1 = ArrayCatalog({'Position': pos}, BoxSize=box, comm=None)
    f1 = FOF(cat1, linking_length=0.2, nmin=8)
    lab_1 = np.asarray(f1.labels)

    assert f._halo_count == f1._halo_count
    # same size distribution, same partition on grouped particles
    s_d = np.sort(np.bincount(lab_d[lab_d > 0]))
    s_1 = np.sort(np.bincount(lab_1[lab_1 > 0]))
    np.testing.assert_array_equal(s_d, s_1)
    m = lab_d > 0
    np.testing.assert_array_equal(m, lab_1 > 0)
    np.testing.assert_array_equal(canon_partition(lab_d[m]),
                                  canon_partition(lab_1[m]))


@pytest.mark.slow
def test_distributed_fof_million_particles(cpu8):
    """N=1e6 sharded FOF — the scale the single-device path cannot
    reach without gathering (VERDICT round-1, missing #2)."""
    N = 1_000_000
    box = 1000.0
    rng = np.random.RandomState(11)
    pos = rng.uniform(0, box, (N, 3))
    ll = 1.0  # mean separation 10 -> sparse, few links
    posj = shard_leading(cpu8, jnp.asarray(pos))
    got = np.asarray(_fof_labels_distributed(
        posj, np.ones(3) * box, ll, cpu8, periodic=True))
    # oracle on a subsample window: brute-force pairs inside a small
    # sub-box must be grouped identically
    sel = np.all((pos > 100) & (pos < 112), axis=1)
    sub = pos[sel]
    subl = got[sel]
    d = sub[:, None, :] - sub[None, :, :]
    d -= np.round(d / box) * box
    adj = (d ** 2).sum(-1) <= ll * ll
    # particles linked directly must share a label
    ii, jj = np.nonzero(adj)
    assert np.all(subl[ii] == subl[jj]) if len(ii) else True
    # labels are min global index of the group: every label <= index
    assert np.all(got <= np.arange(N))


# ---------------------------------------------------------- pair counts

@pytest.mark.parametrize("mode,kw", [
    ('1d', {}),
    ('2d', dict(Nmu=5)),
    ('projected', dict(pimax=6.0)),
])
def test_paircount_dist_matches_single(cpu8, mode, kw):
    rng = np.random.RandomState(9)
    N = 6000
    box = np.ones(3) * 100.0
    pos = rng.uniform(0, 100, (N, 3))
    w = rng.uniform(0.5, 2.0, N)
    edges = np.linspace(0.5, 8.0, 9)
    ref = paircount(pos, w, pos, w, box, edges, mode=mode,
                    periodic=True, is_auto=True, **kw)
    pj = shard_leading(cpu8, jnp.asarray(pos))
    wj = shard_leading(cpu8, jnp.asarray(w))
    got = paircount_dist(pj, wj, pj, wj, box, edges, cpu8, mode=mode,
                         periodic=True, is_auto=True, **kw)
    np.testing.assert_allclose(got['npairs'], ref['npairs'], rtol=1e-12)
    np.testing.assert_allclose(got['wnpairs'], ref['wnpairs'],
                               rtol=1e-12)


def test_paircount_dist_cross_nonperiodic(cpu8):
    rng = np.random.RandomState(10)
    box = np.ones(3) * 100.0
    pos1 = rng.uniform(0, 100, (4000, 3))
    pos2 = rng.uniform(0, 100, (5000, 3))
    edges = np.linspace(0.5, 8.0, 9)
    ref = paircount(pos1, None, pos2, None, box, edges, mode='1d',
                    periodic=False, is_auto=False)
    got = paircount_dist(
        shard_leading(cpu8, jnp.asarray(pos1)), None,
        shard_leading(cpu8, jnp.asarray(pos2)), None,
        box, edges, cpu8, mode='1d', periodic=False, is_auto=False)
    np.testing.assert_allclose(got['npairs'], ref['npairs'], rtol=1e-12)


@pytest.mark.slow
def test_simbox_paircount_sharded_catalog(cpu8):
    """SimulationBoxPairCount with an ambient mesh routes through the
    distributed driver and must match the brute-force count."""
    rng = np.random.RandomState(4)
    N = 1500
    box = 40.0
    pos = rng.uniform(0, box, (N, 3))
    w = rng.uniform(0.5, 2.0, N)
    edges = np.linspace(0.5, 4.5, 6)
    from nbodykit_tpu.algorithms.pair_counters.simbox import \
        SimulationBoxPairCount
    with use_mesh(cpu8):
        cat = ArrayCatalog({'Position': pos, 'Weight': w}, BoxSize=box)
        r = SimulationBoxPairCount('1d', cat, edges)
    # brute force
    d = pos[:, None, :] - pos[None, :, :]
    d -= np.round(d / box) * box
    rr = np.sqrt((d ** 2).sum(-1))
    np.fill_diagonal(rr, -1.0)
    want_n = np.zeros(5)
    want_w = np.zeros(5)
    ww = w[:, None] * w[None, :]
    for b in range(5):
        m = (rr >= edges[b]) & (rr < edges[b + 1]) & (rr > 0)
        want_n[b] = m.sum()
        want_w[b] = ww[m].sum()
    np.testing.assert_allclose(r.pairs['npairs'], want_n)
    np.testing.assert_allclose(r.pairs['wnpairs'], want_w, rtol=1e-10)


# ------------------------------------------------------ overflow contract

def test_paint_overflow_retries_eagerly(cpu8):
    """An explicit too-small capacity must auto-retry (reference backoff
    loop, source/mesh/catalog.py:275-315), never silently drop mass."""
    from nbodykit_tpu.pmesh import ParticleMesh
    rng = np.random.RandomState(6)
    N = 4096
    pm = ParticleMesh(Nmesh=16, BoxSize=32.0, dtype='f8', comm=cpu8)
    # all particles in one slab -> per-(src,dst) load ~ N/8, far above
    # capacity=4
    pos = jnp.asarray(rng.uniform(0, 4.0, (N, 3)))
    pos = shard_leading(cpu8, pos)
    field = pm.paint(pos, 1.0, resampler='cic', capacity=4)
    np.testing.assert_allclose(float(field.sum()), N, rtol=1e-10)


def test_paint_overflow_traced_requires_return_dropped(cpu8):
    from nbodykit_tpu.pmesh import ParticleMesh
    import jax
    pm = ParticleMesh(Nmesh=16, BoxSize=32.0, dtype='f8', comm=cpu8)
    pos = shard_leading(cpu8, jnp.zeros((64, 3)) + 1.0)

    with pytest.raises(ValueError, match="return_dropped"):
        jax.jit(lambda p: pm.paint(p, 1.0, capacity=2))(pos)

    # with return_dropped=True the count is reported
    field, dropped = jax.jit(
        lambda p: pm.paint(p, 1.0, capacity=2, return_dropped=True))(pos)
    assert int(dropped) > 0
    # and with the default capacity nothing can drop
    field, dropped = jax.jit(
        lambda p: pm.paint(p, 1.0, return_dropped=True))(pos)
    assert int(dropped) == 0
    np.testing.assert_allclose(float(field.sum()), 64.0, rtol=1e-10)


def test_readout_overflow_retries_eagerly(cpu8):
    from nbodykit_tpu.pmesh import ParticleMesh
    rng = np.random.RandomState(8)
    pm = ParticleMesh(Nmesh=16, BoxSize=32.0, dtype='f8', comm=cpu8)
    field = pm.create('real', value=3.5)
    pos = shard_leading(cpu8, jnp.asarray(
        rng.uniform(0, 4.0, (2048, 3))))
    vals = pm.readout(field, pos, resampler='cic', capacity=4)
    np.testing.assert_allclose(np.asarray(vals), 3.5, rtol=1e-12)


@pytest.mark.slow
def test_fof_strongly_clustered_load_balance(cpu8):
    """A pathological density contrast: one blob holding half the
    particles plus a uniform background. The binary-search grid hash
    keeps cells exactly ll-sized, so the sweep cost tracks true local
    occupancy (SURVEY §2.2.3 load balancing; round-1 VERDICT missing
    #4) — this run must both terminate quickly and stay correct."""
    box = 100.0
    N = 20000
    rng = np.random.RandomState(13)
    blob = rng.normal(50.0, 0.4, (N // 2, 3)) % box
    bg = rng.uniform(0, box, (N - N // 2, 3))
    pos = np.concatenate([blob, bg])
    ll = 0.25
    ref = np.asarray(_fof_labels(pos, np.ones(3) * box, ll,
                                 periodic=True))
    posj = shard_leading(cpu8, jnp.asarray(pos))
    got = np.asarray(_fof_labels_distributed(
        posj, np.ones(3) * box, ll, cpu8, periodic=True))
    np.testing.assert_array_equal(canon_partition(ref),
                                  canon_partition(got))
    # sanity: the blob percolates into one giant group
    _, counts = np.unique(got, return_counts=True)
    assert counts.max() > N // 3


def test_paint_no_false_overflow_with_padding(cpu8):
    """N not divisible by the device count pads the exchange with dead
    entries; those must not count as dropped particles (they would
    trigger spurious retries and false alarms via return_dropped)."""
    import jax
    from nbodykit_tpu.pmesh import ParticleMesh
    rng = np.random.RandomState(12)
    N = 4001  # not divisible by 8
    pm = ParticleMesh(Nmesh=16, BoxSize=32.0, dtype='f8', comm=cpu8)
    pos = jnp.asarray(rng.uniform(0, 32.0, (N, 3)))
    field, dropped = jax.jit(
        lambda p: pm.paint(p, 1.0, return_dropped=True))(pos)
    assert int(dropped) == 0
    np.testing.assert_allclose(float(field.sum()), N, rtol=1e-10)


def test_current_mesh_inherited_by_threads(cpu8):
    """A user thread spawned under use_mesh must see the ambient mesh
    (regression: the thread-local stack fell back to single-device)."""
    from concurrent.futures import ThreadPoolExecutor
    from nbodykit_tpu.parallel.runtime import CurrentMesh, use_mesh
    with use_mesh(cpu8):
        with ThreadPoolExecutor(1) as ex:
            got = ex.submit(CurrentMesh.get).result()
    assert got is cpu8


def test_kddensity_distributed_matches_single(cpu8):
    """KDDensity on a sharded catalog must reproduce the single-device
    neighbor counts exactly (device-count invariance, the reference
    CI discipline; distributed path = slab ghosts + in-graph sweep)."""
    from nbodykit_tpu.algorithms.kdtree import KDDensity
    box = 50.0
    pos = clustered_positions(4096, box, nblob=20, sigma=0.6, seed=11)
    cat1 = ArrayCatalog({'Position': pos}, BoxSize=box, comm=None)
    kd1 = KDDensity(cat1, margin=1.0)
    with use_mesh(cpu8):
        cat = ArrayCatalog({'Position': pos}, BoxSize=box)
        kd = KDDensity(cat, margin=1.0)
    np.testing.assert_allclose(np.asarray(kd.density),
                               np.asarray(kd1.density), rtol=1e-6)


def test_3pcf_distributed_matches_single(cpu8):
    """SimulationBox3PCF on a sharded catalog: the psum'd SE zeta
    matrices must match the single-device sweep."""
    from nbodykit_tpu.algorithms.threeptcf import SimulationBox3PCF
    box = 40.0
    rng = np.random.RandomState(21)
    pos = rng.uniform(0, box, (800, 3))
    w = rng.uniform(0.5, 1.5, 800)
    edges = np.array([0.5, 2.0, 4.0])
    cat1 = ArrayCatalog({'Position': pos, 'Weight': w}, BoxSize=box,
                        comm=None)
    r1 = SimulationBox3PCF(cat1, poles=[0, 2], edges=edges)
    with use_mesh(cpu8):
        cat = ArrayCatalog({'Position': pos, 'Weight': w}, BoxSize=box)
        rd = SimulationBox3PCF(cat, poles=[0, 2], edges=edges)
    for ell in (0, 2):
        np.testing.assert_allclose(
            np.asarray(rd.poles['corr_%d' % ell]),
            np.asarray(r1.poles['corr_%d' % ell]), rtol=1e-8)


def test_kddensity_two_device_wraparound_ghosts(cpu8):
    """nproc=2 periodic: the lower and upper slab neighbor are the SAME
    device, so a particle within r of both faces must ghost only once
    (double-counted secondaries inflate the density proxy)."""
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    from nbodykit_tpu.algorithms.kdtree import KDDensity
    mesh2 = cpu_mesh(2)
    box = 10.0
    rng = np.random.RandomState(31)
    # concentrate particles in the face margins so wraparound ghosts
    # dominate: x in [0, 1) and [9, 10) with r ~ 1.08
    x = np.concatenate([rng.uniform(0, 1.0, 300),
                        rng.uniform(9.0, 10.0, 300)])
    pos = np.stack([x, rng.uniform(0, box, 600),
                    rng.uniform(0, box, 600)], axis=1)
    cat1 = ArrayCatalog({'Position': pos}, BoxSize=box, comm=None)
    kd1 = KDDensity(cat1, margin=0.5)
    with use_mesh(mesh2):
        cat = ArrayCatalog({'Position': pos}, BoxSize=box)
        kd = KDDensity(cat, margin=0.5)
    np.testing.assert_allclose(np.asarray(kd.density),
                               np.asarray(kd1.density), rtol=1e-6)


@pytest.mark.slow
def test_cgm_distributed_matches_single(cpu8):
    """CylindricalGroups on a sharded catalog: the fixpoint rounds with
    per-round ghost refresh must reproduce the single-device
    classification exactly."""
    from nbodykit_tpu.algorithms.cgm import CylindricalGroups
    box = 80.0
    rng = np.random.RandomState(17)
    pos = clustered_positions(1500, box, nblob=25, sigma=1.0, seed=17)
    mass = rng.uniform(1.0, 100.0, 1500)
    cat1 = ArrayCatalog({'Position': pos, 'Mass': mass}, BoxSize=box,
                        comm=None)
    g1 = CylindricalGroups(cat1, rankby='Mass', rperp=1.5, rpar=3.0)
    with use_mesh(cpu8):
        cat = ArrayCatalog({'Position': pos, 'Mass': mass},
                           BoxSize=box)
        gd = CylindricalGroups(cat, rankby='Mass', rperp=1.5, rpar=3.0)
    for col in ('cgm_type', 'cgm_haloid', 'num_cgm_sats'):
        np.testing.assert_array_equal(np.asarray(gd.groups[col]),
                                      np.asarray(g1.groups[col]))
