"""The bispectrum subsystem (ISSUE 20): the FFT Scoccimarro estimator
and the direct pairblock estimator against brute-force numpy oracles,
cross-path agreement on the multi-device mesh, bit-identical replay and
save/load, the MXU pairblock kernel, tuner integration, memory_plan
pricing, and the serve plane's Bispectrum requests.

Oracle conventions (docs/BISPECTRUM.md): the FFT path closes triangles
mod Nmesh (the aliased closure of the mesh product), so its oracle
wraps ``q3 = -(q1+q2)`` back into the fftfreq range; the direct path
uses TRUE closure over the enumerated integer lattice.  The two agree
wherever no wrapped triangle can occur — ``2 (nbins+1) <= Nmesh/2``.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import nbodykit_tpu
from nbodykit_tpu import _global_options
from nbodykit_tpu.algorithms import Bispectrum
from nbodykit_tpu.algorithms.bispectrum import (direct_bispectrum,
                                                fft_bispectrum,
                                                shell_modes,
                                                triangle_bins)
from nbodykit_tpu.lab import UniformCatalog
from nbodykit_tpu.ops.pairblock import lattice_kvecs, pairblock_sum
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.pmesh import ParticleMesh, memory_plan
from nbodykit_tpu.tune import TuneCache, reset_cache_memo
from nbodykit_tpu.tune.resolve import resolve_bispectrum


@pytest.fixture(autouse=True)
def _clean_options():
    saved = _global_options.copy()
    reset_cache_memo()
    yield
    reset_cache_memo()
    _global_options.clear()
    _global_options.update(saved)


# ---------------------------------------------------------------------------
# enumeration helpers

def test_triangle_bins_canonical_and_closable():
    tris = triangle_bins(4)
    for (i, j, l) in tris:
        assert i <= j <= l
        assert (l + 1) < (i + 2) + (j + 2)
    # the equilateral diagonal always closes
    for b in range(4):
        assert (b, b, b) in tris


def test_shell_modes_half_sphere():
    q, shell = shell_modes(3)
    assert q.shape == (shell.size, 3)
    seen = {tuple(v) for v in q}
    for v in q:
        assert tuple(-v) not in seen      # exactly one of q / -q
    isq = (q ** 2).sum(axis=1)
    assert np.all(isq >= (shell + 1) ** 2)
    assert np.all(isq < (shell + 2) ** 2)


# ---------------------------------------------------------------------------
# the MXU pairblock kernel

def test_pairblock_matches_numpy_and_is_device_invariant():
    rng = np.random.RandomState(11)
    pos = rng.uniform(0, 100.0, (300, 3))
    w = rng.uniform(0.5, 1.5, 300)
    q, _ = shell_modes(2)
    kv = lattice_kvecs(q, 100.0)
    want = (w[None, :] * np.exp(-1j * (kv @ pos.T))).sum(axis=1)
    got1 = np.asarray(pairblock_sum(jnp.asarray(pos), jnp.asarray(w),
                                    kv, tile=64))
    np.testing.assert_allclose(got1, want, rtol=1e-10, atol=1e-10)
    got8 = np.asarray(pairblock_sum(jnp.asarray(pos), jnp.asarray(w),
                                    kv, tile=64, comm=cpu_mesh()))
    np.testing.assert_allclose(got8, want, rtol=1e-10, atol=1e-10)


# ---------------------------------------------------------------------------
# FFT estimator vs the all-triangles oracle (aliased mod-N closure)

def test_fft_bispectrum_matches_all_triangle_oracle():
    N, L, nbins = 16, 100.0, 4
    pm = ParticleMesh(Nmesh=N, BoxSize=L, dtype='f8')
    rng = np.random.RandomState(42)
    real = rng.standard_normal((N, N, N))
    B, ntri = fft_bispectrum(pm, pm.r2c(jnp.asarray(real)), nbins)

    # oracle: full c2c spectrum, every mod-N-closed mode triangle
    dk = np.fft.fftn(real).reshape(-1) / N ** 3
    fx = np.fft.fftfreq(N, 1.0 / N).astype(int)
    qx, qy, qz = np.meshgrid(fx, fx, fx, indexing='ij')
    q = np.stack([qx, qy, qz], -1).reshape(-1, 3)
    isq = (q ** 2).sum(1)
    sh = np.floor(np.sqrt(isq.astype('f8'))).astype(int) - 1
    pos_of = {tuple(v): i for i, v in enumerate(q)}
    idx = {b: np.flatnonzero((isq >= 1) & (sh == b))
           for b in range(nbins)}
    So = np.zeros((nbins,) * 3, complex)
    No = np.zeros((nbins,) * 3)
    for b1 in range(nbins):
        for b2 in range(nbins):
            q2s, d2 = q[idx[b2]], dk[idx[b2]]
            for i1 in idx[b1]:
                q3 = (-(q[i1] + q2s) + N // 2) % N - N // 2
                for i2 in range(len(q2s)):
                    t = pos_of[tuple(q3[i2])]
                    b3 = sh[t]
                    if 0 <= b3 < nbins and isq[t] >= 1:
                        So[b1, b2, b3] += dk[i1] * d2[i2] * dk[t]
                        No[b1, b2, b3] += 1
    V = L ** 3
    Bo = np.where(No > 0, V * V * So.real / np.where(No > 0, No, 1),
                  np.nan)
    assert np.array_equal(np.nan_to_num(ntri, nan=0.0), No)
    assert np.array_equal(np.isnan(B), No == 0)
    both = No > 0
    np.testing.assert_allclose(B[both], Bo[both], rtol=1e-6)


# ---------------------------------------------------------------------------
# direct estimator vs the true-closure oracle

def test_direct_bispectrum_matches_true_closure_oracle():
    rng = np.random.RandomState(7)
    Np, L, nbins = 400, 100.0, 3
    pos = rng.uniform(0, L, (Np, 3))
    w = rng.uniform(0.5, 1.5, Np)
    B, ntri = direct_bispectrum(jnp.asarray(pos), jnp.asarray(w), L,
                                nbins, tile=128)

    q, sh = shell_modes(nbins)
    q = np.concatenate([q, -q])
    sh = np.concatenate([sh, sh])
    kv = q * (2 * np.pi / L)
    d = (w[None, :] * np.exp(-1j * (kv @ pos.T))).sum(1) / w.sum()
    pos_of = {tuple(v): i for i, v in enumerate(q)}
    S = np.zeros((nbins,) * 3, complex)
    No = np.zeros((nbins,) * 3)
    for i1 in range(len(q)):
        for i2 in range(len(q)):
            t = pos_of.get(tuple(-(q[i1] + q[i2])))
            if t is not None:
                S[sh[i1], sh[i2], sh[t]] += d[i1] * d[i2] * d[t]
                No[sh[i1], sh[i2], sh[t]] += 1
    V = L ** 3
    Bo = np.where(No > 0, V * V * S.real / np.where(No > 0, No, 1),
                  np.nan)
    assert np.array_equal(np.nan_to_num(ntri, nan=0.0), No)
    assert np.array_equal(np.isnan(B), No == 0)
    both = No > 0
    np.testing.assert_allclose(B[both], Bo[both], rtol=1e-10)


# ---------------------------------------------------------------------------
# cross-path agreement on the 8-device mesh

def _signal_catalog(L=100.0, seed=42):
    """A uniform catalog with a strong imprinted non-Gaussian weight
    field (a squared sum of low-|q| cosines): the bispectrum signal
    dominates shot noise, so the two estimators must agree instead of
    both measuring near-cancelling noise."""
    cat = UniformCatalog(nbar=1e-2, BoxSize=L, seed=seed)
    pos = np.asarray(cat['Position'])
    rng = np.random.RandomState(3)
    g = np.zeros(len(pos))
    for m in [(1, 0, 0), (0, 1, 0), (0, 0, 1), (1, 1, 0), (0, 1, 1),
              (1, 0, 1), (2, 0, 0), (1, 1, 1)]:
        ph = rng.uniform(0, 2 * np.pi)
        g += 0.4 * np.cos(2 * np.pi * (pos @ np.array(m)) / L + ph)
    cat['Weight'] = (1.0 + 0.5 * g) ** 2
    return cat


def test_fft_vs_direct_agreement_multi_device(cpu8):
    """nbins=3 at Nmesh=32: 2 (nbins+1) = 8 <= 16 = Nmesh/2, so no
    aliased triangle exists and the mod-N and true closures coincide —
    the two estimators measure the SAME statistic and must agree to
    estimator-difference tolerance (window/resolution only)."""
    with use_mesh(cpu8):
        cat = _signal_catalog()
        bf = Bispectrum(cat, nbins=3, Nmesh=32, method='fft')
        bd = Bispectrum(cat, nbins=3, method='direct', tile=256)
    Bf, Bd = bf.B['B'], bd.B['B']
    assert bf.attrs['method'] == 'fft'
    assert bd.attrs['method'] == 'direct'
    # identical closed-triangle counts, bit for bit
    assert np.array_equal(np.nan_to_num(bf.B['ntri'], nan=-1.0),
                          np.nan_to_num(bd.B['ntri'], nan=-1.0))
    m = ~np.isnan(Bf)
    assert np.array_equal(m, ~np.isnan(Bd))
    scale = np.abs(Bd[m]).max()
    assert np.allclose(Bf[m], Bd[m], rtol=2e-2, atol=2e-2 * scale)


def test_bispectrum_deterministic_and_roundtrip(tmp_path):
    cat = _signal_catalog()
    a = Bispectrum(cat, nbins=3, Nmesh=16, method='fft')
    b = Bispectrum(cat, nbins=3, Nmesh=16, method='fft')
    assert np.array_equal(np.nan_to_num(a.B['B'], nan=1.25),
                          np.nan_to_num(b.B['B'], nan=1.25))
    path = str(tmp_path / 'bspec.json')
    a.save(path)
    c = Bispectrum.load(path)
    assert np.array_equal(np.nan_to_num(a.B['B'], nan=1.25),
                          np.nan_to_num(c.B['B'], nan=1.25))
    assert c.attrs['nbins'] == 3 and c.attrs['method'] == 'fft'


def test_bispectrum_validates_method_and_sources():
    cat = UniformCatalog(nbar=2e-3, BoxSize=100.0, seed=1)
    with pytest.raises(ValueError):
        Bispectrum(cat, nbins=0, Nmesh=16)
    with pytest.raises(ValueError):
        Bispectrum(cat, nbins=2, Nmesh=16, method='exact')
    mesh = cat.to_mesh(Nmesh=16)
    with pytest.raises(ValueError):
        Bispectrum(mesh, nbins=2, method='direct')
    # 'auto' on a mesh source resolves to the FFT path
    r = Bispectrum(mesh, nbins=2)
    assert r.attrs['method'] == 'fft'


# ---------------------------------------------------------------------------
# tuner integration

def test_resolve_bispectrum_cold_cache_defaults(tmp_path):
    nbodykit_tpu.set_options(tune_cache=str(tmp_path / 'ABSENT.json'))
    cfg = resolve_bispectrum(nmesh=64, npart=10000, nproc=1)
    assert cfg['bspec_method'] == 'fft'
    assert cfg['pairblock_tile'] == 1024
    assert cfg['source'] == 'default'


def test_resolve_bispectrum_picks_up_cache_winner(tmp_path):
    path = str(tmp_path / 'TC.json')
    TuneCache(path).put({
        'platform': 'cpu', 'device_kind': 'cpu', 'device_count': 1,
        'op': 'bspec', 'shape_class': 'mesh16-part1e3',
        'dtype': 'float32',
        'winner': {'bspec_method': 'direct', 'pairblock_tile': 256},
        'winner_name': 'direct-tile256', 'trials': {},
        'infeasible': [], 'measured_at': '2026-08-04T00:00:00Z'})
    nbodykit_tpu.set_options(tune_cache=path)
    cfg = resolve_bispectrum(nmesh=16, npart=500, nproc=1)
    assert cfg['bspec_method'] == 'direct'
    assert cfg['pairblock_tile'] == 256
    assert cfg['source'] == 'cache'
    # an explicit option is never overridden by the cache
    nbodykit_tpu.set_options(bspec_method='fft')
    assert resolve_bispectrum(nmesh=16, npart=500,
                              nproc=1)['bspec_method'] == 'fft'


def test_tune_dry_run_lists_bspec_candidates(capsys):
    import json as _json
    from nbodykit_tpu.tune.__main__ import main
    assert main(['--dry-run', '--devices', '8']) == 0
    plan = _json.loads(capsys.readouterr().out)['plan']
    bspec = [p for p in plan if p['op'] == 'bspec']
    assert len(bspec) == 2            # one per default paint shape
    names = {c for p in bspec for c in p['candidates']}
    assert 'fft' in names
    assert 'direct-tile1024' in names


# ---------------------------------------------------------------------------
# memory_plan pricing

def test_memory_plan_bispectrum_fft_and_direct():
    fft = memory_plan(256, 10 ** 6, workload='bispectrum', nbins=4,
                      hbm_bytes=16e9)
    assert fft['workload'] == 'bispectrum'
    assert fft['bspec_method'] == 'fft'
    # the streaming contract: 3 shell fields, never nbins fields
    assert fft['shell_fields_bytes'] == pytest.approx(3 * 4 * 256 ** 3)
    assert fft['fits']
    big = memory_plan(2048, 10 ** 8, workload='bispectrum', nbins=8,
                      dtype='f8', hbm_bytes=16e9)
    assert not big['fits']

    d = memory_plan(256, 10 ** 6, workload='bispectrum', nbins=4,
                    bspec_method='direct', pairblock_tile=4096,
                    hbm_bytes=16e9)
    assert d['bspec_method'] == 'direct'
    assert d['pairblock_bytes'] == pytest.approx(4.0 * 4096 * 4096 * 4)
    assert d['fits']
    # the tile knob is the direct path's memory dial
    d2 = memory_plan(256, 10 ** 6, workload='bispectrum', nbins=4,
                     bspec_method='direct', pairblock_tile=256,
                     hbm_bytes=16e9)
    assert d2['peak_bytes'] < d['peak_bytes']


# ---------------------------------------------------------------------------
# the serve plane

def test_serve_bispectrum_admit_degrade_reject():
    from nbodykit_tpu.serve import AnalysisRequest, admit
    ok = admit(AnalysisRequest(algorithm='Bispectrum', nmesh=64,
                               npart=10000, nbins=4),
               ndevices=1, hbm_bytes=16e9)
    assert ok.status == 'admit'
    assert ok.plan['workload'] == 'bispectrum'
    # the paint phase dominates here (pos + unchunked scatter temps);
    # the scoped ladder's paint_chunk_size rung pulls it under budget
    mid = admit(AnalysisRequest(algorithm='Bispectrum', nmesh=64,
                                npart=10 ** 8, nbins=4,
                                paint_method='scatter'),
                ndevices=1, hbm_bytes=2.3e9)
    assert mid.status == 'degrade'
    assert mid.options.get('paint_chunk_size')
    bad = admit(AnalysisRequest(algorithm='Bispectrum', nmesh=1024,
                                npart=10 ** 7, nbins=8, dtype='f8'),
                ndevices=1, hbm_bytes=2e9)
    assert bad.status == 'reject'
    assert bad.reason['code'] == 'over_budget'
    # request-model validation: seeded only, Nyquist-bounded shells
    with pytest.raises(ValueError):
        AnalysisRequest(algorithm='Bispectrum', nmesh=16, nbins=9)
    with pytest.raises(ValueError):
        AnalysisRequest(algorithm='FFTPower', nbins=3)
    r = AnalysisRequest(algorithm='Bispectrum', nmesh=32, npart=1000)
    assert r.nbins == 4                    # the default shell count
    r3 = AnalysisRequest(algorithm='Bispectrum', nmesh=32, npart=1000,
                         nbins=3)
    assert r.program_key(1) != r3.program_key(1)


def test_serve_bispectrum_end_to_end_batched():
    from nbodykit_tpu.serve import (AnalysisRequest, AnalysisServer,
                                    BatchPolicy)
    with use_mesh(cpu_mesh(1)):
        srv = AnalysisServer(
            per_task=1, batch=BatchPolicy(max_batch=4, max_delay_s=1.0))
    with srv:
        tickets = [srv.submit(AnalysisRequest(
            algorithm='Bispectrum', nmesh=16, npart=5000, nbins=3,
            seed=s)) for s in (1, 2, 3)]
        batched = [srv.wait(t, timeout=240) for t in tickets]
        assert all(r.status == 'completed' for r in batched)
        assert max(r.batch_size for r in batched) > 1
        solo = srv.wait(srv.submit(AnalysisRequest(
            algorithm='Bispectrum', nmesh=16, npart=5000, nbins=3,
            seed=1)), timeout=120)
        # vmap-batched execution is bit-identical to solo
        assert np.array_equal(np.asarray(batched[0].y),
                              np.asarray(solo.y))
        assert np.array_equal(np.asarray(batched[0].nmodes),
                              np.asarray(solo.nmodes))
        y = np.asarray(batched[0].y, dtype='f8')
        assert np.isfinite(y).all()
        assert np.asarray(batched[0].nmodes).min() > 0
        summary = srv.summary()
    assert summary['lost'] == 0
    assert summary['completed'] == 4
