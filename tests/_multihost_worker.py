"""Worker process for the multi-host bootstrap test.

Run as:  python _multihost_worker.py <coordinator> <nprocs> <pid>

Connects to the coordination service, builds the world mesh spanning
both processes' CPU devices, runs the paint -> distributed rFFT
pipeline on a deterministic particle set, and prints two replicated
scalars every process must agree on.
"""

import functools
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# this worker's contract is 4 CPU devices per process; a forced device
# count inherited from the parent (conftest.py's set_cpu_devices(8)
# fallback exports XLA_FLAGS) would silently double the world size and
# break the slab-height checks — scrub it before jax initializes
_flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                os.environ.get('XLA_FLAGS', '')).strip()
if _flags:
    os.environ['XLA_FLAGS'] = _flags
else:
    os.environ.pop('XLA_FLAGS', None)

import jax

jax.config.update("jax_platforms", "cpu")
from nbodykit_tpu._jax_compat import set_cpu_devices  # noqa: E402

set_cpu_devices(4)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# every worker leaves a post-mortem span trace: multi-host failures are
# the recurring blind spot (a hung/killed worker used to leave nothing
# but a truncated stdout).  Per-process files (trace-<pid>.jsonl) under
# one directory; NBKIT_DIAGNOSTICS overrides the location, an empty
# value disables.  Read back with
# ``python -m nbodykit_tpu.diagnostics --report <dir>`` (one process)
# or ``--analyze <dir>`` (merged timeline, stragglers, hangs).
from nbodykit_tpu import diagnostics  # noqa: E402

diagnostics.configure_from_env(default='/tmp/nbodykit-tpu-multihost-trace')


# jitted barrier collective cached per mesh: re-wrapping the lambda
# inside _barrier recompiled the psum on every barrier tag (an NBK202
# finding of the shard-safety linter — the first bug it caught here)
@functools.lru_cache(maxsize=8)
def _allsum_for(mesh):
    from jax.sharding import PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    return jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), AXIS), mesh=mesh,
        in_specs=P(AXIS), out_specs=P()))


def _barrier(mesh, tag):
    """An explicit cross-process sync point wrapped in a ``barrier``
    span: a replicated-scalar psum over the whole mesh is a collective
    every process leaves together, so the analyzer
    (diagnostics/analyze.py) gets a guaranteed clock-alignment anchor
    per worker regardless of what the pipeline under test emits."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    ndev = len(jax.devices())
    x = jax.make_array_from_callback(
        (ndev,), NamedSharding(mesh, P(AXIS)),
        lambda idx: np.ones(ndev, 'f4')[idx])
    allsum = _allsum_for(mesh)
    with diagnostics.span('barrier', point=tag):
        total = float(allsum(x))
    assert total == ndev, (tag, total, ndev)


def main():
    coord, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else 'pipeline'
    from nbodykit_tpu.parallel.runtime import init_distributed, \
        world_mesh
    if nprocs > 1:
        try:
            # cross-process collectives on the CPU backend need the
            # gloo transport (else every multi-process computation
            # fails with "Multiprocess computations aren't implemented
            # on the CPU backend"); it requires the distributed client,
            # so only the multi-process path sets it
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:       # older jaxlib without the option
            pass
        assert init_distributed(coordinator_address=coord,
                                num_processes=nprocs, process_id=pid)
    if mode == 'batch':
        return main_batch()

    def pipeline():
        with diagnostics.span('multihost.pipeline', nprocs=nprocs,
                              proc=pid):
            mesh = world_mesh()
            ndev = len(jax.devices())
            _barrier(mesh, 'start')

            from nbodykit_tpu.pmesh import ParticleMesh
            pm = ParticleMesh(Nmesh=16, BoxSize=50.0, dtype='f4',
                              comm=mesh)

            N = 4096
            pos_np = np.random.RandomState(7).uniform(0, 50.0, (N, 3)) \
                .astype('f4')

            from jax.sharding import NamedSharding, PartitionSpec as P
            from nbodykit_tpu.parallel.runtime import AXIS
            sharding = NamedSharding(mesh, P(AXIS, None))

            def cb(index):
                return pos_np[index]

            pos = jax.make_array_from_callback((N, 3), sharding, cb)

            field = pm.paint(pos, 1.0, resampler='cic')
            total = float(jnp.sum(field.astype(jnp.float32)))
            c = pm.r2c(field)
            p2 = float(jnp.sum(jnp.abs(c) ** 2))
            _barrier(mesh, 'end')
        return ndev, total, p2

    # supervised (nbodykit_tpu.resilience): transient device loss is
    # retried with backoff, and every process given the same
    # $NBKIT_FAULTS spec injects/retries at the same logical step —
    # collective-consistent, so the retried pipeline re-enters its
    # barriers together. Retry/degrade events land in the per-process
    # trace the analyzer merges.
    from nbodykit_tpu.resilience import RetryPolicy, Supervisor
    sup = Supervisor('multihost.pipeline',
                     policy=RetryPolicy(max_retries=1, base_s=0.1))
    ndev, total, p2 = sup.run(pipeline)
    print("RESULT %d %.6e %.6e" % (ndev, total, p2), flush=True)


def main_batch():
    """Multi-host TaskManager farming: groups of one host each, five
    tasks round-robined, every process must return the full ordered
    result list (the reference's batch.py terminal allgather)."""
    from nbodykit_tpu.batch import TaskManager
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.parallel.runtime import CurrentMesh

    def work(seed):
        # a real sub-mesh pipeline: paint N particles on the group's
        # own mesh and return the mass total (deterministic per seed)
        mesh = CurrentMesh.get()
        pm = ParticleMesh(Nmesh=8, BoxSize=10.0, dtype='f4', comm=mesh)
        pos_np = np.random.RandomState(seed).uniform(0, 10.0, (257, 3))
        pos = jnp.asarray(pos_np, jnp.float32)
        field = pm.paint(pos, 1.0, resampler='cic')
        return round(float(jnp.sum(field.astype(jnp.float32))), 3)

    with diagnostics.span('multihost.batch'):
        with TaskManager(cpus_per_task=4) as tm:
            results = tm.map(work, list(range(11, 16)))
    print("BATCHRESULT %s" % ",".join("%.3f" % r for r in results),
          flush=True)


if __name__ == '__main__':
    main()
