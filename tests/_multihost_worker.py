"""Worker process for the multi-host bootstrap test.

Run as:  python _multihost_worker.py <coordinator> <nprocs> <pid>

Connects to the coordination service, builds the world mesh spanning
both processes' CPU devices, runs the paint -> distributed rFFT
pipeline on a deterministic particle set, and prints two replicated
scalars every process must agree on.
"""

import functools
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# this worker's contract is 4 CPU devices per process; a forced device
# count inherited from the parent (conftest.py's set_cpu_devices(8)
# fallback exports XLA_FLAGS) would silently double the world size and
# break the slab-height checks — scrub it before jax initializes
_flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                os.environ.get('XLA_FLAGS', '')).strip()
if _flags:
    os.environ['XLA_FLAGS'] = _flags
else:
    os.environ.pop('XLA_FLAGS', None)

import jax

jax.config.update("jax_platforms", "cpu")
from nbodykit_tpu._jax_compat import set_cpu_devices  # noqa: E402

set_cpu_devices(4)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# every worker leaves a post-mortem span trace: multi-host failures are
# the recurring blind spot (a hung/killed worker used to leave nothing
# but a truncated stdout).  Per-process files (trace-<pid>.jsonl) under
# one directory; NBKIT_DIAGNOSTICS overrides the location, an empty
# value disables.  Read back with
# ``python -m nbodykit_tpu.diagnostics --report <dir>`` (one process)
# or ``--analyze <dir>`` (merged timeline, stragglers, hangs).
from nbodykit_tpu import diagnostics  # noqa: E402

diagnostics.configure_from_env(default='/tmp/nbodykit-tpu-multihost-trace')


# jitted barrier collective cached per mesh: re-wrapping the lambda
# inside _barrier recompiled the psum on every barrier tag (an NBK202
# finding of the shard-safety linter — the first bug it caught here)
@functools.lru_cache(maxsize=8)
def _allsum_for(mesh):
    from jax.sharding import PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    return jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), AXIS), mesh=mesh,
        in_specs=P(AXIS), out_specs=P()))


def _barrier(mesh, tag):
    """An explicit cross-process sync point wrapped in a ``barrier``
    span: a replicated-scalar psum over the whole mesh is a collective
    every process leaves together, so the analyzer
    (diagnostics/analyze.py) gets a guaranteed clock-alignment anchor
    per worker regardless of what the pipeline under test emits."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS
    ndev = len(jax.devices())
    x = jax.make_array_from_callback(
        (ndev,), NamedSharding(mesh, P(AXIS)),
        lambda idx: np.ones(ndev, 'f4')[idx])
    allsum = _allsum_for(mesh)
    with diagnostics.span('barrier', point=tag):
        total = float(allsum(x))
    assert total == ndev, (tag, total, ndev)


def main():
    coord, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else 'pipeline'
    if mode == 'fleet':
        # stamp the fleet coordinates into the environment FIRST: the
        # chaos matrix's rank-scoped fault rules (resilience.faults)
        # and the tracer's heartbeat rank field both read these
        os.environ['NBKIT_FLEET_RANK'] = str(pid)
        os.environ['NBKIT_FLEET_SIZE'] = str(nprocs)
    from nbodykit_tpu.parallel.runtime import init_distributed, \
        world_mesh
    if nprocs > 1:
        try:
            # cross-process collectives on the CPU backend need the
            # gloo transport (else every multi-process computation
            # fails with "Multiprocess computations aren't implemented
            # on the CPU backend"); it requires the distributed client,
            # so only the multi-process path sets it
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:       # older jaxlib without the option
            pass
        assert init_distributed(coordinator_address=coord,
                                num_processes=nprocs, process_id=pid)
    if mode == 'batch':
        return main_batch()
    if mode == 'fleet':
        return main_fleet(nprocs, pid)

    def pipeline():
        with diagnostics.span('multihost.pipeline', nprocs=nprocs,
                              proc=pid):
            mesh = world_mesh()
            ndev = len(jax.devices())
            _barrier(mesh, 'start')

            from nbodykit_tpu.pmesh import ParticleMesh
            pm = ParticleMesh(Nmesh=16, BoxSize=50.0, dtype='f4',
                              comm=mesh)

            N = 4096
            pos_np = np.random.RandomState(7).uniform(0, 50.0, (N, 3)) \
                .astype('f4')

            from jax.sharding import NamedSharding, PartitionSpec as P
            from nbodykit_tpu.parallel.runtime import AXIS
            sharding = NamedSharding(mesh, P(AXIS, None))

            def cb(index):
                return pos_np[index]

            pos = jax.make_array_from_callback((N, 3), sharding, cb)

            field = pm.paint(pos, 1.0, resampler='cic')
            total = float(jnp.sum(field.astype(jnp.float32)))
            c = pm.r2c(field)
            p2 = float(jnp.sum(jnp.abs(c) ** 2))
            _barrier(mesh, 'end')
        return ndev, total, p2

    # supervised (nbodykit_tpu.resilience): transient device loss is
    # retried with backoff, and every process given the same
    # $NBKIT_FAULTS spec injects/retries at the same logical step —
    # collective-consistent, so the retried pipeline re-enters its
    # barriers together. Retry/degrade events land in the per-process
    # trace the analyzer merges.
    from nbodykit_tpu.resilience import RetryPolicy, Supervisor
    sup = Supervisor('multihost.pipeline',
                     policy=RetryPolicy(max_retries=1, base_s=0.1))
    ndev, total, p2 = sup.run(pipeline)
    print("RESULT %d %.6e %.6e" % (ndev, total, p2), flush=True)


def main_batch():
    """Multi-host TaskManager farming: groups of one host each, five
    tasks round-robined, every process must return the full ordered
    result list (the reference's batch.py terminal allgather)."""
    from nbodykit_tpu.batch import TaskManager
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.parallel.runtime import CurrentMesh

    def work(seed):
        # a real sub-mesh pipeline: paint N particles on the group's
        # own mesh and return the mass total (deterministic per seed)
        mesh = CurrentMesh.get()
        pm = ParticleMesh(Nmesh=8, BoxSize=10.0, dtype='f4', comm=mesh)
        pos_np = np.random.RandomState(seed).uniform(0, 10.0, (257, 3))
        pos = jnp.asarray(pos_np, jnp.float32)
        field = pm.paint(pos, 1.0, resampler='cic')
        return round(float(jnp.sum(field.astype(jnp.float32))), 3)

    with diagnostics.span('multihost.batch'):
        with TaskManager(cpus_per_task=4) as tm:
            results = tm.map(work, list(range(11, 16)))
    print("BATCHRESULT %s" % ",".join("%.3f" % r for r in results),
          flush=True)


def main_fleet(nprocs, pid):
    """Fleet-survivability pipeline: a checkpointed rep loop under the
    full resilience stack (nbodykit_tpu.resilience.fleet,
    docs/RESILIENCE.md).  Every rep paints a deterministic particle
    set into an accumulating density field and commits a coordinated
    checkpoint — per-rank shards sealed by a rank-0 manifest after a
    digest allgather.  A relaunch resumes from the newest SEALED
    manifest; a relaunch with fewer processes re-forms the mesh and
    repartitions the surviving shards (shrink-to-survive).  The chaos
    matrix drives it via ``$NBKIT_FAULTS`` rank-scoped rules
    (``rank1@bench.rep@2:sigkill``), and a live :class:`FleetMonitor`
    on every rank turns a dead peer into a prompt DEAD_RANK_EXIT
    instead of a wedged collective.

    Env contract: ``NBKIT_FLEET_DIR`` (checkpoint root, required),
    ``NBKIT_FLEET_RECORD`` (rank-0 record JSON path),
    ``NBKIT_FLEET_REPS`` (default 4), ``NBKIT_FLEET_GAP_S`` (detector
    threshold, default 1.5), ``NBKIT_FLEET_GRACE_S`` (preemption
    budget, default 10).  Prints ``FLEETRESULT ndev completed total
    p2`` on success."""
    import json

    from jax.sharding import NamedSharding, PartitionSpec as P
    from nbodykit_tpu.parallel.runtime import AXIS, world_mesh
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.resilience import (PREEMPTED_EXIT,
                                         FleetCheckpointStore,
                                         FleetMonitor, Preempted,
                                         check_preemption, fault_point,
                                         install_preemption_handler)

    root = os.environ['NBKIT_FLEET_DIR']
    record_path = os.environ.get('NBKIT_FLEET_RECORD', '')
    reps = int(os.environ.get('NBKIT_FLEET_REPS', '4') or 4)
    gap_s = float(os.environ.get('NBKIT_FLEET_GAP_S', '1.5') or 1.5)
    grace_s = float(os.environ.get('NBKIT_FLEET_GRACE_S', '10') or 10)
    install_preemption_handler(grace_s=grace_s)

    mesh = world_mesh()
    ndev = len(jax.devices())
    Nmesh = 16
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=50.0, dtype='f4', comm=mesh)
    store = FleetCheckpointStore(root)
    key = 'fleet.pipeline'
    sharding = NamedSharding(mesh, P(AXIS, None))

    rec = {'nranks': nprocs, 'ndev': ndev, 'reps': reps}

    # resume: this rank's slice of the newest SEALED manifest.  A
    # different rank count than the manifest's is the shrink path —
    # load() repartitions the surviving shards and info carries the
    # re-formation stamps
    start, block = 0, None
    got = store.load(key, rank=pid, nranks=nprocs)
    if got is not None:
        state, arrays, info = got
        start = int(state['completed'])
        block = arrays['field']
        rec['resumed'] = True
        rec['resumed_reps'] = start
        if info.get('reformed'):
            from nbodykit_tpu.parallel.runtime import \
                reform_decomposition
            rec.update(reform_decomposition(info['reformed_from'],
                                            info['reformed_to'],
                                            ndev_per_rank=4))

    # the accumulated field as a distributed array: row offset of this
    # rank's block is rank * (rows / nranks) — make_array only asks
    # the callback for this process's addressable slices, all of which
    # land inside the block
    full = np.zeros((Nmesh, Nmesh, Nmesh), 'f4')
    if block is not None:
        off = pid * (Nmesh // nprocs)
        full[off:off + block.shape[0]] = block
    field = jax.make_array_from_callback(
        (Nmesh, Nmesh, Nmesh), sharding, lambda idx: full[idx])

    def local_block(arr):
        """This process's contiguous slab rows, for the shard file."""
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards],
                              axis=0)

    monitor = None
    if nprocs > 1 and diagnostics.current_tracer() is not None:
        monitor = FleetMonitor(diagnostics.current_tracer().dir,
                               gap_s=gap_s, abort=True)
        monitor.start()

    N = 2048
    try:
        with diagnostics.span('fleet.pipeline', nprocs=nprocs,
                              proc=pid, start=start):
            if nprocs > 1:
                _barrier(mesh, 'fleet.start')
            for r in range(start, reps):
                fault_point('bench.rep')
                check_preemption('fleet.rep%d' % r)
                pos_np = np.random.RandomState(100 + r).uniform(
                    0, 50.0, (N, 3)).astype('f4')
                pos = jax.make_array_from_callback(
                    (N, 3), NamedSharding(mesh, P(AXIS, None)),
                    lambda idx: pos_np[idx])
                with diagnostics.span('fleet.rep', rep=r):
                    field = field + pm.paint(pos, 1.0, resampler='cic')
                    field.block_until_ready()
                store.save(key, {'completed': r + 1, 'reps': reps},
                           arrays={'field': local_block(field)},
                           mesh=mesh if nprocs > 1 else None,
                           seq=r + 1, rank=pid, nranks=nprocs)
            total = float(jnp.sum(field.astype(jnp.float32)))
            c = pm.r2c(field)
            p2 = float(jnp.sum(jnp.abs(c) ** 2))
            if nprocs > 1:
                _barrier(mesh, 'fleet.end')
    except Preempted:
        rec['preempted'] = True
        rec['completed'] = store.latest_manifest(key) or {}
        rec['completed'] = int(rec['completed'].get('seq', start))
        if pid == 0 and record_path:
            diagnostics.atomic_write(record_path, json.dumps(rec))
        if monitor is not None:
            monitor.stop()
        sys.exit(PREEMPTED_EXIT)
    if monitor is not None:
        monitor.stop()

    rec.update(completed=reps, total=round(total, 3),
               p2='%.6e' % p2)
    if pid == 0 and record_path:
        diagnostics.atomic_write(record_path, json.dumps(rec))
    print("FLEETRESULT %d %d %.6e %.6e" % (ndev, reps, total, p2),
          flush=True)


if __name__ == '__main__':
    main()
