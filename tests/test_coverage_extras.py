"""Small coverage tests: LOS variations, dk=0 unique-edge binning,
FFTCorr multipoles, readout device-count invariance."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import ArrayMesh, FFTPower, FFTCorr
from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.parallel.runtime import cpu_mesh


def test_fftpower_los_axes_equivalent():
    # an isotropic random field: P(k) must not depend on the los axis
    rng = np.random.RandomState(1)
    field = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field, BoxSize=32.0)
    rz = FFTPower(mesh, mode='2d', Nmu=3, los=[0, 0, 1])
    rx = FFTPower(ArrayMesh(field, BoxSize=32.0), mode='2d', Nmu=3,
                  los=[1, 0, 0])
    # 1d averages agree exactly (mu-binning differs, k-binning doesn't)
    pz = np.nansum(rz.power['power'].real * rz.power['modes'], axis=-1)
    px = np.nansum(rx.power['power'].real * rx.power['modes'], axis=-1)
    np.testing.assert_allclose(pz, px, rtol=1e-8)


def test_fftpower_dk_zero_unique_edges():
    rng = np.random.RandomState(2)
    field = rng.standard_normal((8, 8, 8))
    mesh = ArrayMesh(field, BoxSize=8.0)
    r = FFTPower(mesh, mode='1d', dk=0)
    # every bin holds modes of identical |k|: mean k equals the
    # coordinate value
    k = r.power['k']
    coords = r.power.coords['k']
    valid = r.power['modes'] > 0
    np.testing.assert_allclose(k[valid], coords[valid], rtol=1e-5)
    # first unique |k| is the fundamental mode
    np.testing.assert_allclose(coords[1], 2 * np.pi / 8.0, rtol=1e-6)


def test_find_unique_edges_complete_cubic():
    # brute-force every |k| on the 20^3 hermitian lattice: the dk=0
    # centers must hit EVERY unique modulus exactly (the round-2
    # device-unique version silently truncated beyond 2^20 uniques)
    from nbodykit_tpu.algorithms.fftpower import _find_unique_edges
    pm = ParticleMesh(20, 10.0, dtype='f8', comm=cpu_mesh(1))
    edges, fx = _find_unique_edges(pm, np.inf, kind='complex')
    kf = 2 * np.pi / 10.0
    ii = np.rint(np.fft.fftfreq(20, 1.0 / 20)).astype(int)
    iz = np.arange(11)
    isq = (ii[:, None, None] ** 2 + ii[None, :, None] ** 2
           + iz[None, None, :] ** 2)
    want = kf * np.sqrt(np.unique(isq).astype('f8'))
    np.testing.assert_allclose(np.sort(fx), want, rtol=1e-12)
    assert len(edges) == len(fx) + 1


def test_find_unique_edges_anisotropic():
    # anisotropic box: the fallback path must also enumerate all
    # moduli (up to its documented 0.05*kf quantum)
    from nbodykit_tpu.algorithms.fftpower import _find_unique_edges
    pm = ParticleMesh(8, (8.0, 12.0, 20.0), dtype='f8',
                      comm=cpu_mesh(1))
    edges, fx = _find_unique_edges(pm, np.inf, kind='complex')
    kf = 2 * np.pi / np.array([8.0, 12.0, 20.0])
    ii = np.rint(np.fft.fftfreq(8, 1.0 / 8)).astype(int)
    iz = np.arange(5)
    k2 = ((kf[0] * ii[:, None, None]) ** 2
          + (kf[1] * ii[None, :, None]) ** 2
          + (kf[2] * iz[None, None, :]) ** 2)
    quantum = kf.min() * 0.05
    want_q = np.unique((np.sqrt(k2.ravel()) / quantum + 0.5)
                       .astype('i8'))
    got_q = np.unique((np.sort(fx) / quantum + 0.5).astype('i8'))
    np.testing.assert_array_equal(got_q, want_q)


def test_fftcorr_poles():
    rng = np.random.RandomState(3)
    field = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field, BoxSize=16.0)
    r = FFTCorr(mesh, mode='1d', poles=[0, 2])
    assert 'corr_0' in r.poles.variables
    valid = r.corr['modes'] > 0
    np.testing.assert_allclose(r.poles['corr_0'].real[valid],
                               r.corr['corr'][valid], rtol=1e-8)


def test_readout_device_count_invariance():
    rng = np.random.RandomState(4)
    field_np = rng.standard_normal((16, 16, 16))
    pos_np = rng.uniform(0, 16.0, size=(999, 3))
    outs = []
    for comm in [cpu_mesh(1), cpu_mesh()]:
        pm = ParticleMesh(16, 16.0, dtype='f8', comm=comm)
        vals = pm.readout(jnp.asarray(field_np), jnp.asarray(pos_np),
                          resampler='cic')
        outs.append(np.asarray(vals))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-10)


def test_paint_sort_method_end_to_end():
    # the sort-based paint gives identical FFTPower results
    from nbodykit_tpu import set_options
    from nbodykit_tpu.lab import UniformCatalog
    cat = UniformCatalog(nbar=2e-3, BoxSize=32.0, seed=5)
    r1 = FFTPower(cat.to_mesh(Nmesh=16, resampler='cic',
                              compensated=True), mode='1d')
    with set_options(paint_method='sort'):
        r2 = FFTPower(cat.to_mesh(Nmesh=16, resampler='cic',
                                  compensated=True), mode='1d')
    np.testing.assert_allclose(r1.power['power'].real,
                               r2.power['power'].real, rtol=1e-5,
                               equal_nan=True)


def test_fftcorr_dr_zero_unique_edges(comm):
    """dr=0: one bin per unique lattice separation (reference
    fftcorr.py:167-171 + fftpower.py:732-769)."""
    from nbodykit_tpu.source.catalog.uniform import UniformCatalog
    from nbodykit_tpu.algorithms.fftcorr import FFTCorr
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        cat = UniformCatalog(nbar=2e-3, BoxSize=40.0, seed=42)
        r = FFTCorr(cat, mode='1d', Nmesh=16, dr=0, rmax=9.0)
    rcen = r.corr.coords['r']
    # true centers are unique |r| values on the 16^3 lattice (cell 2.5)
    seps = np.fft.fftfreq(16, d=1.0 / 16) * 2.5
    r2 = (seps[:, None, None] ** 2 + seps[None, :, None] ** 2
          + seps[None, None, :] ** 2).ravel()
    want = np.unique(np.round(np.sqrt(r2), 6))
    want = want[want < 9.0]
    np.testing.assert_allclose(np.sort(rcen), want, atol=1e-5)
    # every lattice mode lands in a bin: modes sum to Nmesh^3 over all
    # unique bins (each |r| is exact, no empty bins)
    assert (r.corr['modes'] > 0).all()


def test_binned_statistic_from_plaintext_1d(tmp_path):
    from nbodykit_tpu.binned_statistic import BinnedStatistic
    fn = str(tmp_path / 'meas1d.txt')
    with open(fn, 'w') as f:
        f.write("# k power modes\n")
        for i in range(4):
            f.write("%g %g %g\n" % (0.1 * (i + 0.5), 100.0 / (i + 1),
                                    10 * (i + 1)))
        f.write("# edges 5\n")
        for e in np.linspace(0, 0.4, 5):
            f.write("#%g\n" % e)
        f.write("# metadata 2\n")
        f.write("#BoxSize 100.0 float64\n")
        f.write("#N 512 int\n")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", FutureWarning)
        ds = BinnedStatistic.from_plaintext(['k'], fn)
    assert ds.shape == (4,)
    np.testing.assert_allclose(ds['power'],
                               [100.0, 50.0, 100 / 3.0, 25.0],
                               rtol=1e-5)
    np.testing.assert_allclose(ds.edges['k'], np.linspace(0, 0.4, 5))
    assert ds.attrs['BoxSize'] == 100.0
    assert ds.attrs['N'] == 512


def test_binned_statistic_from_plaintext_2d(tmp_path):
    from nbodykit_tpu.binned_statistic import BinnedStatistic
    fn = str(tmp_path / 'meas2d.txt')
    Nk, Nmu = 3, 2
    kedges = np.linspace(0, 0.3, Nk + 1)
    muedges = np.linspace(0, 1, Nmu + 1)
    with open(fn, 'w') as f:
        f.write("%d %d\n" % (Nk, Nmu))
        f.write("k mu power.real power.imag modes\n")
        v = 0
        for i in range(Nk):
            for j in range(Nmu):
                v += 1
                f.write("%g %g %g %g %d\n"
                        % (0.1 * (i + .5), 0.5 * (j + .5), 10.0 * v,
                           -1.0 * v, v))
        f.write("edges %d\n" % (Nk + 1))
        for e in kedges:
            f.write("%g\n" % e)
        f.write("edges %d\n" % (Nmu + 1))
        for e in muedges:
            f.write("%g\n" % e)
        f.write("metadata 1\n")
        f.write("volume 1000.0 float64\n")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", FutureWarning)
        ds = BinnedStatistic.from_plaintext(['k', 'mu'], fn)
    assert ds.shape == (3, 2)
    assert np.iscomplexobj(ds['power'])
    np.testing.assert_allclose(ds['power'].real,
                               10.0 * np.arange(1, 7).reshape(3, 2))
    np.testing.assert_allclose(ds['power'].imag,
                               -np.arange(1, 7).reshape(3, 2))
    np.testing.assert_allclose(ds.edges['mu'], muedges)
    assert ds.attrs['volume'] == 1000.0


def test_convpower_legacy_load(tmp_path):
    """pre-0.3.5 ConvolvedFFTPower files load via format='pre000305'
    (reference convpower/fkp.py:349-354,377-406)."""
    import json
    from nbodykit_tpu.algorithms.convpower.fkp import ConvolvedFFTPower
    from nbodykit_tpu.utils import JSONEncoder
    kedges = np.linspace(0, 0.3, 4)
    poles = np.empty(3, dtype=[('k', 'f8'), ('power_0', 'c16'),
                               ('modes', 'i8')])
    poles['k'] = 0.5 * (kedges[1:] + kedges[:-1])
    poles['power_0'] = [100 + 0j, 50 + 0j, 25 + 0j]
    poles['modes'] = [10, 20, 30]
    state = dict(edges=kedges, poles=poles,
                 attrs={'poles': [0], 'shotnoise': 12.0})
    fn = str(tmp_path / 'legacy.json')
    with open(fn, 'w') as f:
        json.dump(state, f, cls=JSONEncoder)
    r = ConvolvedFFTPower.load(fn, format='pre000305')
    np.testing.assert_allclose(r.poles['power_0'].real, [100, 50, 25])
    assert r.attrs['shotnoise'] == 12.0


@pytest.mark.slow
def test_fftcorr_matches_paircount_xi():
    """Cross-implementation oracle (SURVEY §4): xi(r) measured two
    fully independent ways — FFT of the painted/compensated mesh
    (FFTCorr) vs direct pair counting with analytic randoms
    (SimulationBox2PCF natural estimator) — must agree on a clustered
    lognormal realization. Measured agreement is 2-3% across
    6 < r < 27 (mesh cell 1.95); tolerance 8%."""
    from nbodykit_tpu.lab import LogNormalCatalog, LinearPower
    from nbodykit_tpu.algorithms.fftcorr import FFTCorr
    from nbodykit_tpu.algorithms.paircount_tpcf import SimulationBox2PCF
    from nbodykit_tpu.cosmology import Planck15

    Plin = LinearPower(Planck15, redshift=0.55, transfer='EisensteinHu')
    box, nmesh = 250.0, 128
    cat = LogNormalCatalog(Plin=Plin, nbar=1.5e-3, BoxSize=box,
                           Nmesh=nmesh, bias=2.0, seed=9)

    edges = np.linspace(6.0, 30.0, 9)
    xi_pc = np.asarray(SimulationBox2PCF('1d', cat, edges).corr['corr'])

    mesh = cat.to_mesh(Nmesh=nmesh, resampler='tsc', compensated=True)
    rc = FFTCorr(mesh, mode='1d', rmin=6.0, dr=3.0, rmax=30.0)
    xi_fft = np.asarray(rc.corr['corr'].real)

    n = len(xi_fft)
    np.testing.assert_allclose(xi_fft, xi_pc[:n], rtol=0.08)
    assert xi_pc[0] > 1.0  # genuinely clustered sample


def test_ylm_cache_complex_parity():
    """YlmCache returns complex Y_lm matching scipy's sph_harm_y
    (reference: sympy-backed YlmCache, threeptcf.py:393-505)."""
    try:
        from scipy.special import sph_harm_y
    except ImportError:    # scipy < 1.15: the old spelling/arg order
        from scipy.special import sph_harm

        def sph_harm_y(n, m, theta, phi):
            # sph_harm(m, n, azimuth, polar) == sph_harm_y(n, m,
            # polar, azimuth)
            return sph_harm(m, n, phi, theta)
    from nbodykit_tpu.lab import YlmCache

    cache = YlmCache([0, 1, 2, 3, 4, 5])
    rng = np.random.RandomState(11)
    v = rng.normal(size=(16, 3))
    v /= np.linalg.norm(v, axis=1)[:, None]
    x, y, z = v.T
    theta, phi = np.arccos(z), np.arctan2(y, x)
    out = cache(x + 1j * y, z)  # reference call form (xpyhat, zhat)
    assert set(out) == {(l, m) for l in range(6) for m in range(l + 1)}
    for (l, m), val in out.items():
        np.testing.assert_allclose(np.asarray(val),
                                   sph_harm_y(l, m, theta, phi),
                                   atol=1e-6)


def test_lab_api_surface_extras():
    """Reference-public names added for parity are importable from lab
    (reference nbodykit/lab.py + source/algorithms __all__)."""
    import nbodykit_tpu.lab as lab
    for name in ['FFTBase', 'FKPCatalogMesh', 'FileCatalogBase',
                 'FileCatalog', 'FileCatalogFactory',
                 'PopulatedHaloCatalog', 'WedgeBinnedStatistic',
                 'PairCountBase', 'YlmCache', 'IO', 'FKPPower']:
        assert hasattr(lab, name), name


def test_file_catalog_generic(tmp_path):
    """FileCatalog(filetype, path) reads like the factory classes
    (reference: source/catalog/file.py:202-231)."""
    import nbodykit_tpu.io as io
    from nbodykit_tpu.lab import FileCatalog

    path = str(tmp_path / 'data.csv')
    arr = np.arange(12, dtype='f8').reshape(4, 3)
    with open(path, 'w') as f:
        for row in arr:
            f.write(' '.join('%r' % float(v) for v in row) + '\n')
    cat = FileCatalog(io.CSVFile, path, names=['a', 'b', 'c'],
                      attrs={'tag': 1})
    assert cat.size == 4 and cat.attrs['tag'] == 1
    np.testing.assert_allclose(np.asarray(cat['b']), arr[:, 1])


def test_catalog_parity_methods(comm):
    """copy/persist/to_subvolumes/make_column/create_instance and
    MeshSource.view (reference base/catalog.py:193,223,474,754,1078;
    base/mesh.py:82)."""
    from nbodykit_tpu.source.catalog.uniform import UniformCatalog
    from nbodykit_tpu.base.catalog import CatalogSourceBase
    from nbodykit_tpu.parallel.runtime import use_mesh

    with use_mesh(comm):
        c = UniformCatalog(nbar=1e-3, BoxSize=100.0, seed=3)
    c2 = c.copy()
    assert c2.size == c.size
    c2.attrs['x'] = 1
    assert 'x' not in c.attrs  # attrs decoupled, unlike view

    p = c.persist(['Position'])
    np.testing.assert_allclose(np.asarray(p['Position']),
                               np.asarray(c['Position']))

    sv = c.to_subvolumes(domain=[2, 2, 2])
    assert sv.size == c.size and 'SubVolumeIndex' in sv.columns
    # subvolume ids are sorted, so the catalog is spatially grouped
    ids = np.asarray(sv['SubVolumeIndex'])
    assert (np.diff(ids) >= 0).all()

    assert c.make_column(np.arange(4)).shape == (4,)
    inst = CatalogSourceBase.create_instance(UniformCatalog)
    assert isinstance(inst, UniformCatalog)
    assert inst.attrs == {} and inst._columns == {}

    m = c.to_mesh(Nmesh=16)
    v = m.view()
    assert v.base is m and v.attrs == m.attrs


def test_utils_parity_functions(comm):
    """split_size_3d/get_data_bounds/Gather-ScatterArray/
    is_structured_array/captured_output (reference utils.py)."""
    import nbodykit_tpu.utils as U

    assert U.split_size_3d(12) == (2, 2, 3)
    assert U.split_size_3d(8) == (2, 2, 2)
    assert U.split_size_3d(7) == (1, 1, 7)

    lo, hi = U.get_data_bounds(np.arange(12.).reshape(4, 3))
    np.testing.assert_allclose(lo, [0, 1, 2])
    np.testing.assert_allclose(hi, [9, 10, 11])
    lo, hi = U.get_data_bounds(np.arange(12.).reshape(4, 3),
                               selection=np.array([1, 1, 0, 0], bool))
    np.testing.assert_allclose(hi, [3, 4, 5])

    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(comm):
        host = U.GatherArray(np.ones(16))
        dev = U.ScatterArray(host)
    assert dev.shape == (16,)

    assert U.is_structured_array(np.zeros(3, dtype=[('a', 'f8')]))
    assert not U.is_structured_array(np.zeros(3))
    with U.captured_output() as (out, err):
        print('hi')
    assert out.getvalue() == 'hi\n'


def test_style_module():
    """style.notebook loads as matplotlib rc params (reference:
    nbodykit/style)."""
    from nbodykit_tpu import style
    assert 'notebook' in style.__all__
    nb = style.notebook
    assert isinstance(nb, (dict, str)) or hasattr(nb, 'keys')
