"""Small coverage tests: LOS variations, dk=0 unique-edge binning,
FFTCorr multipoles, readout device-count invariance."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import ArrayMesh, FFTPower, FFTCorr
from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.parallel.runtime import cpu_mesh


def test_fftpower_los_axes_equivalent():
    # an isotropic random field: P(k) must not depend on the los axis
    rng = np.random.RandomState(1)
    field = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field, BoxSize=32.0)
    rz = FFTPower(mesh, mode='2d', Nmu=3, los=[0, 0, 1])
    rx = FFTPower(ArrayMesh(field, BoxSize=32.0), mode='2d', Nmu=3,
                  los=[1, 0, 0])
    # 1d averages agree exactly (mu-binning differs, k-binning doesn't)
    pz = np.nansum(rz.power['power'].real * rz.power['modes'], axis=-1)
    px = np.nansum(rx.power['power'].real * rx.power['modes'], axis=-1)
    np.testing.assert_allclose(pz, px, rtol=1e-8)


def test_fftpower_dk_zero_unique_edges():
    rng = np.random.RandomState(2)
    field = rng.standard_normal((8, 8, 8))
    mesh = ArrayMesh(field, BoxSize=8.0)
    r = FFTPower(mesh, mode='1d', dk=0)
    # every bin holds modes of identical |k|: mean k equals the
    # coordinate value
    k = r.power['k']
    coords = r.power.coords['k']
    valid = r.power['modes'] > 0
    np.testing.assert_allclose(k[valid], coords[valid], rtol=1e-5)
    # first unique |k| is the fundamental mode
    np.testing.assert_allclose(coords[1], 2 * np.pi / 8.0, rtol=1e-6)


def test_fftcorr_poles():
    rng = np.random.RandomState(3)
    field = rng.standard_normal((16, 16, 16))
    mesh = ArrayMesh(field, BoxSize=16.0)
    r = FFTCorr(mesh, mode='1d', poles=[0, 2])
    assert 'corr_0' in r.poles.variables
    valid = r.corr['modes'] > 0
    np.testing.assert_allclose(r.poles['corr_0'].real[valid],
                               r.corr['corr'][valid], rtol=1e-8)


def test_readout_device_count_invariance():
    rng = np.random.RandomState(4)
    field_np = rng.standard_normal((16, 16, 16))
    pos_np = rng.uniform(0, 16.0, size=(999, 3))
    outs = []
    for comm in [cpu_mesh(1), cpu_mesh()]:
        pm = ParticleMesh(16, 16.0, dtype='f8', comm=comm)
        vals = pm.readout(jnp.asarray(field_np), jnp.asarray(pos_np),
                          resampler='cic')
        outs.append(np.asarray(vals))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-10)


def test_paint_sort_method_end_to_end():
    # the sort-based paint gives identical FFTPower results
    from nbodykit_tpu import set_options
    from nbodykit_tpu.lab import UniformCatalog
    cat = UniformCatalog(nbar=2e-3, BoxSize=32.0, seed=5)
    r1 = FFTPower(cat.to_mesh(Nmesh=16, resampler='cic',
                              compensated=True), mode='1d')
    with set_options(paint_method='sort'):
        r2 = FFTPower(cat.to_mesh(Nmesh=16, resampler='cic',
                                  compensated=True), mode='1d')
    np.testing.assert_allclose(r1.power['power'].real,
                               r2.power['power'].real, rtol=1e-5,
                               equal_nan=True)
