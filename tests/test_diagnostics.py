"""Tests for nbodykit_tpu.diagnostics: span nesting + exception
safety, disabled-mode overhead (no file I/O, no span objects), JSONL
replay of a killed run, metric registry semantics, report/export
round-trips, and the end-to-end acceptance run (FFTPower on the
8-device CPU mesh leaves paint/FFT/exchange/binning spans with
byte/throughput metrics)."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import nbodykit_tpu
from nbodykit_tpu import diagnostics
from nbodykit_tpu.diagnostics import (NULL_SPAN, REGISTRY, counter,
                                      export_chrome_trace, gauge,
                                      histogram, read_trace, span)


@pytest.fixture(autouse=True)
def _clean_registry():
    """Metric registry + tracer reset between tests (the registry is
    process-wide by design; tests must not see each other's data)."""
    REGISTRY.reset()
    yield
    REGISTRY.reset()
    diagnostics.configure(None)


def _spans(path):
    records, bad = read_trace(path)
    return [r for r in records if r.get('t') == 'span'], bad


# ---------------------------------------------------------------------------
# tracer core

def test_disabled_mode_is_noop_singleton(tmp_path):
    # no tracer, no span objects, no file I/O
    assert diagnostics.current_tracer() is None
    assert span('a') is NULL_SPAN
    assert span('b', attr=1) is NULL_SPAN          # attrs don't allocate
    assert diagnostics.span_eager('c') is NULL_SPAN
    assert diagnostics.span_if(True, 'd') is NULL_SPAN
    with span('nested'):
        with span('inner'):
            pass
    assert os.listdir(tmp_path) == []              # nothing written
    assert diagnostics.current_trace_file() is None


def test_span_nesting_depth_and_parent(tmp_path):
    tr = diagnostics.configure(str(tmp_path))
    assert tr is not None
    with span('outer', phase='x'):
        with span('middle'):
            with span('inner'):
                pass
        with span('middle2'):
            pass
    diagnostics.configure(None)
    spans, bad = _spans(str(tmp_path))
    assert bad == 0
    by = {s['name']: s for s in spans}
    assert by['outer']['depth'] == 0
    assert by['middle']['depth'] == 1
    assert by['inner']['depth'] == 2
    assert by['inner']['par'] == by['middle']['id']
    assert by['middle']['par'] == by['outer']['id']
    assert by['middle2']['par'] == by['outer']['id']
    assert by['outer']['attrs'] == {'phase': 'x'}
    # children close before parents; durations nest
    assert by['outer']['dur'] >= by['middle']['dur'] >= by['inner']['dur']


def test_span_exception_safety(tmp_path):
    diagnostics.configure(str(tmp_path))
    with pytest.raises(ValueError, match='boom'):
        with span('will_fail'):
            raise ValueError('boom')
    # the tracer stack must be clean after the exception unwinds
    with span('after'):
        pass
    diagnostics.configure(None)
    spans, _ = _spans(str(tmp_path))
    by = {s['name']: s for s in spans}
    assert by['will_fail']['ok'] is False
    assert 'boom' in by['will_fail']['exc']
    assert by['after']['ok'] is True
    assert by['after']['depth'] == 0               # stack unwound


def test_span_set_attrs_and_decorator(tmp_path):
    diagnostics.configure(str(tmp_path))
    with span('s') as sp:
        sp.set(found=42)

    @diagnostics.traced('deco.span')
    def work(x):
        return x + 1

    assert work(1) == 2
    diagnostics.configure(None)
    spans, _ = _spans(str(tmp_path))
    by = {s['name']: s for s in spans}
    assert by['s']['attrs'] == {'found': 42}
    assert 'deco.span' in by


def test_replay_of_killed_run_truncated_line(tmp_path):
    diagnostics.configure(str(tmp_path))
    with span('complete1'):
        pass
    with span('complete2'):
        pass
    tf = diagnostics.current_trace_file()
    diagnostics.configure(None)
    # simulate a mid-line death: truncate the file inside its last line
    size = os.path.getsize(tf)
    with open(tf, 'r+b') as f:
        f.truncate(size - 7)
    spans, bad = _spans(tf)
    assert bad == 1                                # exactly the torn tail
    assert {s['name'] for s in spans} >= {'complete1'}
    # every surviving record is complete and well-formed
    for s in spans:
        assert 'dur' in s and 'ts' in s


def test_sigkill_leaves_completed_spans_readable(tmp_path):
    """A SIGKILLed process (no atexit, no flush-on-close) must leave
    every completed span on disk — the per-span fsync contract."""
    script = r"""
import os, sys
sys.path.insert(0, %r)
import nbodykit_tpu
from nbodykit_tpu import diagnostics
diagnostics.configure(%r)
with diagnostics.span('done1'):
    pass
with diagnostics.span('done2', n=7):
    pass
sp = diagnostics.span('inflight')
sp.__enter__()
os.kill(os.getpid(), 9)   # SIGKILL: no exit handlers run
""" % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
       str(tmp_path))
    proc = subprocess.run([sys.executable, '-c', script],
                          capture_output=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL
    records, bad = read_trace(str(tmp_path))
    spans = [r for r in records if r.get('t') == 'span']
    begins = [r for r in records if r.get('t') == 'b']
    assert {s['name'] for s in spans} == {'done1', 'done2'}
    # the in-flight span's begin event is visible post-mortem
    assert 'inflight' in {b['name'] for b in begins}


def test_heartbeat_records_written(tmp_path, monkeypatch):
    """The background heartbeat leaves periodic hb records so a wedged
    or SIGKILLed worker is distinguishable post-mortem (analyze.py
    flags the gap)."""
    import time
    monkeypatch.setenv('NBKIT_DIAGNOSTICS_HEARTBEAT', '0.05')
    tr = diagnostics.configure(str(tmp_path))
    assert tr.heartbeat_s == 0.05
    deadline = time.time() + 5.0
    while time.time() < deadline:
        records, _ = read_trace(str(tmp_path))
        if sum(1 for r in records if r.get('t') == 'hb') >= 2:
            break
        time.sleep(0.05)
    diagnostics.configure(None)
    records, _ = read_trace(str(tmp_path))
    hbs = [r for r in records if r.get('t') == 'hb']
    assert len(hbs) >= 2
    assert all(r['pid'] == os.getpid() and r['iv'] == 0.05
               for r in hbs)
    meta = next(r for r in records if r.get('t') == 'meta')
    assert meta['heartbeat_s'] == 0.05


def test_heartbeat_disabled(tmp_path, monkeypatch):
    import time
    monkeypatch.setenv('NBKIT_DIAGNOSTICS_HEARTBEAT', '0')
    diagnostics.configure(str(tmp_path))
    with span('s'):
        time.sleep(0.05)
    diagnostics.configure(None)
    records, _ = read_trace(str(tmp_path))
    assert not any(r.get('t') == 'hb' for r in records)


def test_emit_span_retroactive(tmp_path):
    """Out-of-band completed spans (compile telemetry) are normal
    records to every reader."""
    tr = diagnostics.configure(str(tmp_path))
    tr.emit_span('compile.backend', 123.0, 0.25, {'src': 'test'})
    diagnostics.configure(None)
    spans, _ = _spans(str(tmp_path))
    rec = next(s for s in spans if s['name'] == 'compile.backend')
    assert rec['ts'] == 123.0 and rec['dur'] == 0.25
    assert rec['depth'] == 0 and rec['attrs'] == {'src': 'test'}


# ---------------------------------------------------------------------------
# metrics

def test_metric_registry_counter_gauge_histogram():
    counter('c').add(2)
    counter('c').add(3)
    gauge('g').set(5)
    gauge('g').set(2)
    histogram('h').observe(1.0)
    histogram('h').observe(3.0)
    snap = REGISTRY.snapshot()
    assert snap['c'] == {'type': 'counter', 'value': 5}
    assert snap['g'] == {'type': 'gauge', 'value': 2, 'max': 5, 'min': 2}
    assert snap['h']['count'] == 2 and snap['h']['mean'] == 2.0
    assert snap['h']['min'] == 1.0 and snap['h']['max'] == 3.0
    with pytest.raises(TypeError):
        gauge('c')                                 # type clash is loud


def test_metric_registry_reset_between_tests_a():
    # the pair (a, b) relies on the autouse fixture: each sees a
    # pristine registry no matter the execution order
    assert len(REGISTRY) == 0
    counter('leak').add(1)


def test_metric_registry_reset_between_tests_b():
    assert len(REGISTRY) == 0
    counter('leak').add(1)


def test_instrumented_jit_compile_telemetry(tmp_path):
    """instrumented_jit attributes compiles to a label: miss + first
    call wall + a compile.<label> span on the first call, a hit
    counter on re-use."""
    import jax.numpy as jnp
    f = diagnostics.instrumented_jit(lambda x: x + 1, label='t.addone')
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        np.testing.assert_array_equal(
            np.asarray(f(jnp.zeros(4))), np.ones(4))
        f(jnp.zeros(4))                        # cached executable
    snap = REGISTRY.snapshot()
    assert snap['compile.t.addone.misses']['value'] == 1
    assert snap['compile.t.addone.hits']['value'] == 1
    assert snap['compile.t.addone.first_call_s']['count'] == 1
    spans, _ = _spans(str(tmp_path))
    comp = [s for s in spans if s['name'] == 'compile.t.addone']
    assert len(comp) == 1
    assert comp[0]['attrs'] == {'misses': 1}


def test_instrumented_jit_inside_outer_trace():
    """Under an outer jit the wrapper must pass straight through (no
    host-side bookkeeping while staging)."""
    import jax
    import jax.numpy as jnp
    inner = diagnostics.instrumented_jit(lambda x: x * 2,
                                         label='t.inner')

    @jax.jit
    def outer(x):
        return inner(x) + 1

    np.testing.assert_array_equal(np.asarray(outer(jnp.ones(3))),
                                  np.full(3, 3.0))
    snap = REGISTRY.snapshot()
    assert 'compile.t.inner.misses' not in snap
    assert 'compile.t.inner.hits' not in snap


# ---------------------------------------------------------------------------
# report + chrome export

def test_report_and_chrome_export(tmp_path):
    diagnostics.configure(str(tmp_path))
    with span('phase_one'):
        with span('sub'):
            pass
    counter('work.items').add(10)
    tr = diagnostics.current_tracer()
    paths = diagnostics.write_report(tracer=tr)
    chrome = export_chrome_trace(tr.path)
    diagnostics.configure(None)
    with open(paths[0]) as f:
        rep = json.load(f)
    assert rep['nspans'] == 2
    assert [p['name'] for p in rep['phases']] == ['phase_one']
    assert rep['spans']['sub']['count'] == 1
    assert rep['metrics']['work.items']['value'] == 10
    txt = open(paths[1]).read()
    assert 'phase_one' in txt and 'work.items' in txt
    with open(chrome) as f:
        ev = json.load(f)['traceEvents']
    assert {e['name'] for e in ev} == {'phase_one', 'sub'}
    assert all(e['ph'] == 'X' for e in ev)


def test_self_check_in_process(tmp_path):
    from nbodykit_tpu.diagnostics.__main__ import self_check
    assert self_check(str(tmp_path), verbose=False) == 0


# ---------------------------------------------------------------------------
# option plumbing + instrumented pipelines

def test_set_options_context_restores_disabled(tmp_path):
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        assert diagnostics.enabled()
        with span('inside'):
            pass
    assert not diagnostics.enabled()
    assert span('outside') is NULL_SPAN
    spans, _ = _spans(str(tmp_path))
    assert {s['name'] for s in spans} == {'inside'}


def test_timer_routes_through_tracer(tmp_path):
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with nbodykit_tpu.timer('existing_phase'):
            pass
    spans, _ = _spans(str(tmp_path))
    assert {s['name'] for s in spans} == {'timer.existing_phase'}


def test_fft_chunk_spans_lowmem(tmp_path):
    """The eager lowmem FFT driver emits per-chunk spans + the chunk
    wall histogram."""
    import jax.numpy as jnp
    from nbodykit_tpu.parallel.dfft import rfftn_single_lowmem
    x = jnp.zeros((16, 16, 16), jnp.float32)
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        out = rfftn_single_lowmem([x], target=16 * 16 * 9 * 8 * 2)
    assert out.shape == (16, 16, 9)
    spans, _ = _spans(str(tmp_path))
    names = [s['name'] for s in spans]
    assert 'fft.lowmem.r2c' in names
    chunk_spans = [s for s in spans if s['name'] == 'fft.chunk']
    assert len(chunk_spans) >= 2
    # chunks nest under the lowmem span
    low = next(s for s in spans if s['name'] == 'fft.lowmem.r2c')
    assert all(c['par'] == low['id'] for c in chunk_spans)
    snap = REGISTRY.snapshot()
    assert snap['fft.chunks']['value'] == len(chunk_spans)
    assert snap['fft.chunk_wall_s']['count'] == len(chunk_spans)


def test_fftpower_acceptance_trace(tmp_path, cpu8):
    """ISSUE acceptance: a full FFTPower run on the 8-virtual-device
    CPU mesh produces a JSONL trace containing paint, FFT, exchange,
    and binning spans with byte/throughput metrics."""
    from nbodykit_tpu.parallel.runtime import use_mesh
    from nbodykit_tpu.source.catalog.uniform import UniformCatalog
    from nbodykit_tpu.algorithms.fftpower import FFTPower
    with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
        with use_mesh(cpu8):
            cat = UniformCatalog(nbar=3e-3, BoxSize=32.0, seed=42)
            mesh = cat.to_mesh(Nmesh=16, resampler='cic')
            FFTPower(mesh, mode='2d', Nmu=5)
        snap = REGISTRY.snapshot()
    spans, bad = _spans(str(tmp_path))
    assert bad == 0
    names = {s['name'] for s in spans}
    assert {'paint', 'exchange', 'fft.r2c', 'fftpower.binning',
            'fftpower.run', 'mesh.compute'} <= names
    # byte + throughput metrics landed
    assert snap['exchange.bytes_sent']['value'] > 0
    assert snap['exchange.calls']['value'] >= 1
    assert snap['paint.scatter.mpart_per_s']['count'] >= 1
    # device watermarks were sampled for the 8 virtual devices
    assert snap['device.cpu:0.live_bytes']['max'] > 0
    # compile telemetry (ISSUE 2 acceptance): the binning program's
    # compile is attributed by label, and the jax.monitoring hook
    # timed the XLA compile stages
    assert snap['compile.fftpower.binning.misses']['value'] >= 1
    assert snap['compile.fftpower.binning.first_call_s']['count'] >= 1
    assert snap['xla.compile.backend_s']['count'] >= 1
    assert 'compile.fftpower.binning' in names
    # spans nest: the exchange happens inside the paint
    by = {s['name']: s for s in spans}
    assert by['exchange']['par'] == by['paint']['id']


def test_paint_results_identical_with_diagnostics(tmp_path, cpu8):
    """Tracing must not perturb numerics: same paint with and without
    diagnostics enabled."""
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.parallel.runtime import use_mesh
    with use_mesh(cpu8):
        pm = ParticleMesh(Nmesh=16, BoxSize=10.0, dtype='f8')
        pos = jax.random.uniform(jax.random.key(3), (999, 3),
                                 jnp.float64, 0.0, 10.0)
        ref = np.asarray(pm.paint(pos, 1.0, resampler='cic'))
        with nbodykit_tpu.set_options(diagnostics=str(tmp_path)):
            traced = np.asarray(pm.paint(pos, 1.0, resampler='cic'))
    np.testing.assert_array_equal(ref, traced)
    spans, _ = _spans(str(tmp_path))
    assert 'paint' in {s['name'] for s in spans}
