"""NBK6xx — the interprocedural sharding-flow analysis: positive and
negative fixtures for every rule (NBK601-604), the --shard-report CLI
surface, and the whole-tree regression pinning the committed baseline
to zero unexplained NBK6xx entries.

Pure-host AST tests except the CLI subprocess checks.
"""

import json
import os
import subprocess
import sys
import textwrap

from nbodykit_tpu import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_str(src, select=None):
    return lint.lint_source(
        'fixture.py', textwrap.dedent(src),
        project_constants={'AXIS': 'dev'}, select=select)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# NBK601 — implicit reshard at a shard_map boundary


def test_nbk601_spec_disagreement_positive():
    # produced under P('dev', None) by one boundary, consumed by a
    # second boundary declaring P(None, 'dev'): jax inserts the
    # all_to_all silently — NBK601 must not
    fs = lint_str("""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    from nbodykit_tpu.ops.paint import paint

    def body_a(pos):
        return paint(pos)

    def body_b(field):
        return field * 2

    apply_a = shard_map(body_a, mesh=cpu_mesh(),
                        in_specs=(P('dev'),), out_specs=P('dev', None))
    apply_b = shard_map(body_b, mesh=cpu_mesh(),
                        in_specs=(P(None, 'dev'),),
                        out_specs=P(None, 'dev'))

    def run(pos):
        y = apply_a(pos)
        return apply_b(y)
    """, select=['NBK601'])
    assert codes(fs) == ['NBK601']
    assert "P(dev)" in fs[0].message or "P(dev,None)" in fs[0].message
    assert "P(None,dev)" in fs[0].message


def test_nbk601_matching_specs_negative():
    # same plumbing, consumer declares the producer's spec (modulo
    # trailing-None normalization) — clean
    fs = lint_str("""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh
    from nbodykit_tpu.ops.paint import paint

    def body_a(pos):
        return paint(pos)

    def body_b(field):
        return field * 2

    apply_a = shard_map(body_a, mesh=cpu_mesh(),
                        in_specs=(P('dev'),), out_specs=P('dev', None))
    apply_b = shard_map(body_b, mesh=cpu_mesh(),
                        in_specs=(P('dev'),), out_specs=P('dev'))

    def run(pos):
        y = apply_a(pos)
        return apply_b(y)
    """, select=['NBK601'])
    assert codes(fs) == []


def test_nbk601_chunk_sized_crossing_negative():
    # spec disagreement on a value the size model cannot prove
    # mesh-sized: a cheap crossing, stays silent
    fs = lint_str("""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body_a(x):
        return x + 1

    def body_b(x):
        return x * 2

    apply_a = shard_map(body_a, mesh=cpu_mesh(),
                        in_specs=(P('dev'),), out_specs=P('dev', None))
    apply_b = shard_map(body_b, mesh=cpu_mesh(),
                        in_specs=(P(None, 'dev'),),
                        out_specs=P(None, 'dev'))

    def run(x):
        y = apply_a(x)
        return apply_b(y)
    """, select=['NBK601'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# NBK602 — mesh-sized output bound to replicated out_specs


def test_nbk602_replicated_output_positive():
    fs = lint_str("""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(field):
        return field * 2

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),), out_specs=P(None, None))

    def run(field):
        return g(field)
    """, select=['NBK602'])
    assert codes(fs) == ['NBK602']
    assert 'P(None,None)' in fs[0].message


def test_nbk602_reduced_output_negative():
    # the psum-reduced return REALLY is replicated — that contract is
    # correct and must stay silent
    fs = lint_str("""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(field):
        return jax.lax.psum(field, 'dev')

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),), out_specs=P(None, None))

    def run(field):
        return g(field)
    """, select=['NBK602'])
    assert codes(fs) == []


def test_nbk602_sharded_output_negative():
    fs = lint_str("""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(field):
        return field * 2

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),), out_specs=P('dev', None))

    def run(field):
        return g(field)
    """, select=['NBK602'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# NBK603 — in_specs/out_specs arity mismatch


def test_nbk603_in_specs_arity_positive():
    fs = lint_str("""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(x):
        return x + 1

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'), P('dev')), out_specs=P('dev'))
    """, select=['NBK603'])
    assert codes(fs) == ['NBK603']
    assert 'in_specs' in fs[0].message


def test_nbk603_out_specs_arity_positive():
    fs = lint_str("""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(x):
        return (x, x, x)

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),),
                  out_specs=(P('dev'), P('dev')))
    """, select=['NBK603'])
    assert codes(fs) == ['NBK603']
    assert 'out_specs' in fs[0].message


def test_nbk603_matching_arity_negative():
    fs = lint_str("""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(x, y):
        return (x + y, x - y)

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'), P('dev')),
                  out_specs=(P('dev'), P('dev')))
    """, select=['NBK603'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# NBK604 — collective naming an axis the mesh does not define


def test_nbk604_foreign_axis_positive():
    fs = lint_str("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(x):
        return jax.lax.psum(x, 'rows')

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),), out_specs=P(None,))
    """, select=['NBK604'])
    assert codes(fs) == ['NBK604']
    assert 'rows' in fs[0].message
    assert 'dev' in fs[0].message


def test_nbk604_pencil_axes_negative():
    # the pencil mesh defines BOTH 'x' and 'y' — collectives over
    # either are legal
    fs = lint_str("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import pencil_mesh

    def body(v):
        v = jax.lax.psum(v, 'x')
        return jax.lax.psum(v, 'y')

    g = shard_map(body, mesh=pencil_mesh(),
                  in_specs=(P('x', 'y'),), out_specs=P(None,))
    """, select=['NBK604'])
    assert codes(fs) == []


def test_nbk604_unresolved_mesh_negative():
    # mesh arrives as a parameter: axes unknown, the rule must stay
    # silent rather than guess
    fs = lint_str("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def body(x):
        return jax.lax.psum(x, 'rows')

    def build(mesh):
        return shard_map(body, mesh=mesh,
                         in_specs=(P('dev'),), out_specs=P(None,))
    """, select=['NBK604'])
    assert codes(fs) == []


# ---------------------------------------------------------------------------
# the --shard-report surface


def test_shard_report_lists_boundaries():
    from nbodykit_tpu.lint import callgraph, shardflow
    from nbodykit_tpu.lint.scopes import ModuleContext

    src = textwrap.dedent("""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from nbodykit_tpu.parallel.runtime import cpu_mesh

    def body(x):
        return x + 1

    g = shard_map(body, mesh=cpu_mesh(),
                  in_specs=(P('dev'),), out_specs=P('dev'))
    """)
    ctx = ModuleContext('fixture.py', src,
                        project_constants={'AXIS': 'dev'})
    project = callgraph.single_project(ctx)
    report = shardflow.shard_report(project)
    assert len(report['rows']) == 1
    row = report['rows'][0]
    assert row['function'] == 'body'
    assert row['in_specs'] == ['P(dev)']
    assert row['out_specs'] == ['P(dev)']
    assert row['mesh_axes'] == ['dev']
    text = shardflow.render_shard_report(report)
    assert 'body' in text and 'P(dev)' in text


def test_shard_report_cli():
    out = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--shard-report',
         os.path.join(REPO, 'nbodykit_tpu', 'parallel', 'dfft.py'),
         os.path.join(REPO, 'nbodykit_tpu', 'parallel', 'runtime.py')],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert 'shard_map boundaries' in out.stdout


# ---------------------------------------------------------------------------
# whole-tree regression


def test_tree_has_no_unexplained_nbk6_findings():
    # every NBK6xx finding in the repo was triaged in-PR: fixed or
    # pragma'd with an audit comment.  The committed baseline must
    # carry ZERO grandfathered NBK6xx entries, and a fresh tree run
    # must come back clean.
    with open(os.path.join(REPO, 'lint_baseline.json')) as f:
        baseline = json.load(f)
    assert not [e for e in baseline.get('findings', [])
                if e['code'].startswith('NBK6')]
    out = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.lint', '--select', 'NBK6',
         os.path.join(REPO, 'nbodykit_tpu'),
         os.path.join(REPO, 'bench.py')],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
