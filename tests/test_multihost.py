"""Multi-host (multi-process) bootstrap: the jax.distributed wiring of
SURVEY §2.2.7/M8 — two coordinated processes with 4 CPU devices each
must form one 8-device world mesh and agree on the full
paint -> distributed-rFFT pipeline, matching a single-process run
(the reference's whole execution model is N MPI processes over one
program; nersc/example-job.slurm:11)."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, '_multihost_worker.py')


def _run_single():
    r = subprocess.run(
        [sys.executable, WORKER, 'none', '1', '0'],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(HERE))
    assert r.returncode == 0, r.stderr[-2000:]
    m = re.search(r'RESULT (\d+) (\S+) (\S+)', r.stdout)
    assert m, r.stdout
    return int(m.group(1)), float(m.group(2)), float(m.group(3))


@pytest.mark.slow
def test_two_process_world_mesh_matches_single(tmp_path):
    port = 12357
    trace_dir = str(tmp_path / 'trace')
    env = dict(os.environ, NBKIT_DIAGNOSTICS=trace_dir)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, '127.0.0.1:%d' % port, '2',
             str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=os.path.dirname(HERE))
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-2000:]
        outs.append(out)

    results = []
    for out in outs:
        m = re.search(r'RESULT (\d+) (\S+) (\S+)', out)
        assert m, out
        results.append((int(m.group(1)), float(m.group(2)),
                        float(m.group(3))))

    # both processes saw the 8-device world and agree exactly
    assert results[0][0] == 8 and results[1][0] == 8
    assert results[0] == results[1]

    # and the multi-process pipeline reproduces the single-process run
    ndev1, total1, p21 = _run_single()
    assert ndev1 == 4
    np.testing.assert_allclose(results[0][1], total1, rtol=1e-5)
    np.testing.assert_allclose(results[0][2], p21, rtol=1e-4)

    # fleet analysis over the REAL 2-process trace directory: the
    # merged timeline must hold both worker pids with aligned clocks,
    # the explicit barrier spans must anchor a straggler table, and
    # the clean run must show no hung collectives
    from nbodykit_tpu.diagnostics.analyze import (analyze,
                                                  render_analysis)
    res = analyze(trace_dir)
    worker_pids = {p.pid for p in procs}
    assert set(res['pids']) == worker_pids
    assert res['nprocs'] == 2
    timeline_pids = {r['pid'] for r in res['timeline']}
    assert timeline_pids == worker_pids and res['timeline']
    assert res['anchors_used'] >= 2          # barrier pair at least
    assert 'barrier' in res['stragglers']['per_name']
    assert not res['hangs']['hung_collectives']
    cp = res['critical_path']
    assert cp['wall_s'] > 0 and 'paint' in cp['phases']
    text = render_analysis(res)
    assert 'straggler report' in text and 'critical path' in text

    # the CLI form the acceptance criterion names
    r = subprocess.run(
        [sys.executable, '-m', 'nbodykit_tpu.diagnostics',
         '--analyze', trace_dir],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(HERE))
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'merged timeline' in r.stdout
    for p in worker_pids:
        assert str(p) in r.stdout


@pytest.mark.slow
def test_two_process_taskmanager_farming():
    """Multi-host TaskManager (VERDICT r2 missing #5): two one-host
    groups, five tasks farmed round-robin, and both processes return
    the complete ordered result list."""
    port = 12361
    env = dict(os.environ)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, '127.0.0.1:%d' % port, '2',
             str(pid), 'batch'],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=os.path.dirname(HERE))
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-2000:]
        outs.append(out)

    parsed = []
    for out in outs:
        m = re.search(r'BATCHRESULT (\S+)', out)
        assert m, out
        parsed.append([float(x) for x in m.group(1).split(',')])

    # both processes hold all five results, in task order, identical
    assert len(parsed[0]) == 5
    assert parsed[0] == parsed[1]
    # every task painted all 257 particles
    np.testing.assert_allclose(parsed[0], [257.0] * 5, rtol=1e-5)
