"""MXU (tile-bucketed batched-matmul) paint kernel vs the scatter oracle.

The mxu kernel reformulates the deposit as per-tile matmuls
(ops/paint.py::paint_local_mxu); its semantics must match
``paint_local`` exactly on every geometry class: full mesh, periodic
wrap, halo-extended slab block (origin != 0, n0l < period), and the
wrapped-to-valid boundary strip. Reference behavior being reproduced:
pmesh's C paint consumed at nbodykit/source/mesh/catalog.py:287-296.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from nbodykit_tpu.ops.paint import paint_local, paint_local_mxu
from nbodykit_tpu.pmesh import ParticleMesh
from nbodykit_tpu.parallel.runtime import cpu_mesh

GEOMETRIES = [
    # (n0l, N1, N2, period0, origin): full, non-cubic, slab, far-wrap
    (16, 16, 16, 16, 0),
    (32, 16, 8, 32, 0),
    (12, 16, 16, 32, 5),
    (10, 24, 16, 64, 59),
]


def _random_particles(n, p0, N1, N2, seed=1):
    rng = np.random.default_rng(seed)
    pos = rng.uniform(0, p0, (n, 3))
    pos[:, 1] %= N1
    pos[:, 2] %= N2
    return jnp.asarray(pos), jnp.asarray(rng.uniform(0.5, 2.0, n))


@pytest.mark.parametrize('resampler', ['nnb', 'cic', 'tsc', 'pcs'])
def test_matches_scatter_all_geometries(resampler):
    for (n0l, N1, N2, p0, origin) in GEOMETRIES:
        pos, mass = _random_particles(3000, p0, N1, N2)
        ref = paint_local(pos, mass, (n0l, N1, N2), resampler=resampler,
                          period=(p0, N1, N2), origin=origin)
        got, over = paint_local_mxu(
            pos, mass, (n0l, N1, N2), resampler=resampler,
            period=(p0, N1, N2), origin=origin, rb=4, cb=4,
            return_overflow=True)
        assert int(over) == 0
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)


def test_default_tiles_and_out_accumulate():
    pos, mass = _random_particles(5000, 32, 32, 32, seed=3)
    base = jnp.full((32, 32, 32), 0.5, jnp.float64)
    ref = paint_local(pos, mass, (32, 32, 32), resampler='cic', out=base)
    got = paint_local_mxu(pos, mass, (32, 32, 32), resampler='cic',
                          out=base)  # default rb=cb=8
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-10, atol=1e-12)


def test_overflow_reported_and_bounded():
    """All particles in one cell: every bucket but one is empty, the
    full bucket overflows, the overflow count is exact, and the kept
    deposits still land correctly (no corruption from dropped slots)."""
    n = 4000
    pos = jnp.full((n, 3), 3.3, jnp.float64)
    got, over = paint_local_mxu(pos, jnp.float64(1.0), (16, 16, 16),
                                resampler='cic', rb=4, cb=4, slack=2.0,
                                return_overflow=True)
    kept = n - int(over)
    assert 0 < kept <= n
    # total deposited mass == kept particles (window sums to 1)
    assert abs(float(got.sum()) - kept) < 1e-6 * n
    # and a generous slack keeps everything
    got2, over2 = paint_local_mxu(
        pos, jnp.float64(1.0), (16, 16, 16), resampler='cic', rb=4,
        cb=4, slack=5000.0, return_overflow=True)
    assert int(over2) == 0
    ref = paint_local(pos, jnp.float64(1.0), (16, 16, 16),
                      resampler='cic')
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref),
                               rtol=1e-10, atol=1e-12)


def test_k_chunked_pieces_match_unchunked():
    """Force the per-stripe K-chunking (several pieces per bucket) and
    require bit-identical agreement with the single-piece path."""
    pos, mass = _random_particles(6000, 32, 32, 32, seed=11)
    one = paint_local_mxu(pos, mass, (32, 32, 32), resampler='cic')
    # tiny budget -> ck == 8 slots per bucket -> many pieces
    many = paint_local_mxu(pos, mass, (32, 32, 32), resampler='cic',
                           zchunk_bytes=1)
    np.testing.assert_allclose(np.asarray(many), np.asarray(one),
                               rtol=1e-12, atol=1e-13)
    ref = paint_local(pos, mass, (32, 32, 32), resampler='cic')
    np.testing.assert_allclose(np.asarray(many), np.asarray(ref),
                               rtol=1e-10, atol=1e-12)


def test_f32_precision_close_to_f64():
    pos64, mass64 = _random_particles(20000, 32, 32, 32, seed=5)
    truth = paint_local(pos64, mass64, (32, 32, 32), resampler='cic')
    got = paint_local_mxu(pos64.astype(jnp.float32),
                          mass64.astype(jnp.float32), (32, 32, 32),
                          resampler='cic')
    scale = float(jnp.abs(truth).max())
    assert float(jnp.abs(got.astype(jnp.float64) - truth).max()) \
        < 1e-5 * scale


def test_tiny_mesh_falls_back():
    """Meshes smaller than the wrap arithmetic allows delegate to the
    scatter kernel rather than mis-painting."""
    pos, mass = _random_particles(200, 4, 4, 4, seed=7)
    ref = paint_local(pos, mass, (4, 4, 4), resampler='pcs')
    got = paint_local_mxu(pos, mass, (4, 4, 4), resampler='pcs')
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-10, atol=1e-12)


@pytest.mark.slow
def test_pmesh_device_count_invariance_mxu():
    """The mxu kernel through the full exchange + halo + shard_map
    path: 1-device and 8-device paints agree to f64 roundoff."""
    from nbodykit_tpu import set_options

    rng = np.random.RandomState(13)
    pos_np = rng.uniform(0, 50.0, size=(3000, 3))
    fields = []
    with set_options(paint_method='mxu'):
        for comm in [cpu_mesh(1), cpu_mesh()]:
            pm = ParticleMesh(32, 50.0, dtype='f8', comm=comm)
            field = pm.paint(jnp.asarray(pos_np), 1.0, resampler='tsc')
            fields.append(np.asarray(field))
    np.testing.assert_allclose(fields[0], fields[1], rtol=1e-10,
                               atol=1e-12)
    np.testing.assert_allclose(fields[0].sum(), 3000.0, rtol=1e-9)
