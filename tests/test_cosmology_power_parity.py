"""Remaining portable cases from the reference cosmology suites
(cosmology/tests/test_power.py): error modes, deprecation shims, and
the large-scale agreement of the linear/nonlinear/Zeldovich spectra."""

import numpy as np
import pytest

from nbodykit_tpu.cosmology import (Cosmology, LinearPower, HalofitPower,
                                    ZeldovichPower, EHPower,
                                    NoWiggleEHPower)


def test_bad_transfer():
    with pytest.raises(ValueError):
        LinearPower(Cosmology(), redshift=0., transfer="BAD")


def test_deprecated_ehpower_shims():
    c = Cosmology()
    with pytest.warns(FutureWarning):
        P1 = EHPower(c, redshift=0)
    P2 = LinearPower(c, 0., transfer='EisensteinHu')
    np.testing.assert_allclose(P1(0.1), P2(0.1))

    with pytest.warns(FutureWarning):
        P1 = NoWiggleEHPower(c, redshift=0)
    P2 = LinearPower(c, 0., transfer='NoWiggleEisensteinHu')
    np.testing.assert_allclose(P1(0.1), P2(0.1))


def test_large_scales_agree():
    """On linear scales every spectrum reduces to the linear one
    (reference test_power.py:31)."""
    c = Cosmology()
    k = np.logspace(-5, -2, 50)
    Plin = LinearPower(c, redshift=0)
    Pnl = HalofitPower(c, redshift=0)
    Pzel = ZeldovichPower(c, redshift=0)
    np.testing.assert_allclose(np.asarray(Plin(k)), np.asarray(Pnl(k)),
                               rtol=1e-2)
    np.testing.assert_allclose(np.asarray(Plin(k)), np.asarray(Pzel(k)),
                               rtol=1e-2)


def test_scalar_and_array_calls_consistent():
    c = Cosmology()
    P = LinearPower(c, redshift=0.5)
    k = np.array([0.01, 0.1, 1.0])
    arr = np.asarray(P(k))
    for i, ki in enumerate(k):
        np.testing.assert_allclose(float(P(ki)), arr[i], rtol=1e-10)
