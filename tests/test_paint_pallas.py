"""Pallas deposit kernel == XLA deposit engine (interpret mode on CPU),
incl. slab blocks with origin offsets (the shard_map case) and the
end-to-end option plumbing."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.ops.paint import (paint_local, paint_local_mxu)


def _pos(rng, n, shape):
    scale = np.asarray(shape, 'f4')
    return jnp.asarray(rng.uniform(0, 1, (n, 3)).astype('f4') * scale)


@pytest.mark.parametrize("res", ['cic', 'tsc', 'pcs'])
def test_pallas_deposit_matches_xla(res):
    rng = np.random.RandomState(11)
    shape = (32, 32, 32)
    pos = _pos(rng, 4000, shape)
    ref, _ = paint_local_mxu(pos, 1.0, shape, resampler=res,
                             return_overflow=True, deposit='xla')
    got, over = paint_local_mxu(pos, 1.0, shape, resampler=res,
                                return_overflow=True, deposit='pallas')
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # and both agree with the scatter oracle
    sc = paint_local(pos, 1.0, shape, resampler=res)
    np.testing.assert_allclose(np.asarray(got), np.asarray(sc),
                               atol=1e-3)


def test_pallas_deposit_slab_block():
    """Slab block with origin offset + periodic wrap strip, weighted."""
    rng = np.random.RandomState(3)
    period = (32, 32, 32)
    n0l, origin = 8, 24          # top slab; rows wrap through 0
    shape = (n0l, 32, 32)
    pos = _pos(rng, 3000, period)
    w = jnp.asarray(rng.uniform(0.5, 2.0, 3000).astype('f4'))
    ref, _ = paint_local_mxu(pos, w, shape, resampler='tsc',
                             period=period, origin=origin,
                             return_overflow=True, deposit='xla')
    got, _ = paint_local_mxu(pos, w, shape, resampler='tsc',
                             period=period, origin=origin,
                             return_overflow=True, deposit='pallas')
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    sc = paint_local(pos, w, shape, resampler='tsc', period=period,
                     origin=origin)
    np.testing.assert_allclose(np.asarray(got), np.asarray(sc),
                               atol=1e-3)


def test_pallas_deposit_via_options():
    """set_options(paint_deposit='pallas') reaches the kernel through
    ParticleMesh.paint."""
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    rng = np.random.RandomState(5)
    pm = ParticleMesh(Nmesh=16, BoxSize=100.0, dtype='f4')
    pos = jnp.asarray(rng.uniform(0, 100.0, (2000, 3)).astype('f4'))
    with nbodykit_tpu.set_options(paint_method='mxu',
                                  paint_deposit='pallas'):
        f_pal = pm.paint(pos, 1.0, resampler='cic')
    with nbodykit_tpu.set_options(paint_method='mxu',
                                  paint_deposit='xla'):
        f_xla = pm.paint(pos, 1.0, resampler='cic')
    np.testing.assert_array_equal(np.asarray(f_pal), np.asarray(f_xla))
    assert abs(float(jnp.sum(f_pal)) - 2000.0) < 0.1
