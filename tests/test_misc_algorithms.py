"""Tests for the smaller algorithms: 3PCF (brute-force triplet oracle),
KDDensity, RedshiftHistogram, filters, HOD, TaskManager, FFTRecon."""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import (ArrayCatalog, UniformCatalog,
                              LogNormalCatalog, LinearPower, Planck15,
                              FFTPower)
from nbodykit_tpu.algorithms.threeptcf import SimulationBox3PCF
from nbodykit_tpu.algorithms.kdtree import KDDensity
from nbodykit_tpu.algorithms.zhist import RedshiftHistogram
from nbodykit_tpu.filters import TopHat, Gaussian
from nbodykit_tpu.hod import HODModel, Zheng07Model
from nbodykit_tpu.batch import TaskManager, split_ranks


def brute_zeta(pos, w, edges, ell, box):
    """Brute-force S_l(b1,b2) = sum_i w_i sum_{j in b1,k in b2} w_j w_k
    P_l(cos theta_jik), periodic distances."""
    from numpy.polynomial.legendre import legval
    N = len(pos)
    nb = len(edges) - 1
    out = np.zeros((nb, nb))
    c = np.zeros(ell + 1)
    c[ell] = 1.0
    for i in range(N):
        d = pos - pos[i]
        d -= np.round(d / box) * box
        r = np.sqrt((d ** 2).sum(axis=-1))
        sel = (r > 0) & (r >= edges[0]) & (r < edges[-1])
        idx = np.flatnonzero(sel)
        if len(idx) == 0:
            continue
        rv = d[idx] / r[idx][:, None]
        bins = np.digitize(r[idx], edges) - 1
        for a in range(len(idx)):
            for b in range(len(idx)):
                mu = np.clip(rv[a] @ rv[b], -1, 1)
                out[bins[a], bins[b]] += w[i] * w[idx[a]] * w[idx[b]] \
                    * legval(mu, c)
    return out


@pytest.mark.parametrize("ell", [0, 1, 2])
def test_3pcf_brute_force(ell):
    rng = np.random.RandomState(0)
    pos = rng.uniform(0, 20.0, size=(60, 3))
    w = rng.uniform(0.5, 1.5, size=60)
    cat = ArrayCatalog({'Position': pos, 'Weight': w}, BoxSize=20.0)
    edges = np.array([0.5, 4.0, 8.0])
    r = SimulationBox3PCF(cat, poles=[ell], edges=edges)
    want = brute_zeta(pos, w, edges, ell, 20.0) \
        * (2 * ell + 1) / (4 * np.pi) ** 2
    np.testing.assert_allclose(np.asarray(r.poles['corr_%d' % ell]),
                               want, rtol=1e-6, atol=1e-8)


def test_kddensity():
    rng = np.random.RandomState(1)
    sparse = rng.uniform(0, 50.0, size=(200, 3))
    cluster = 25.0 + rng.normal(0, 0.5, size=(200, 3))
    pos = np.concatenate([sparse, cluster])
    cat = ArrayCatalog({'Position': pos}, BoxSize=50.0)
    kd = KDDensity(cat, margin=1.0)
    rho = np.asarray(kd.density)
    # clustered particles must be far denser than the sparse field
    assert np.median(rho[200:]) > 10 * np.median(rho[:200])


def test_redshift_histogram():
    rng = np.random.RandomState(2)
    z = rng.normal(0.5, 0.1, size=5000).clip(0.01, 1.0)
    cat = ArrayCatalog({'Redshift': z})
    h = RedshiftHistogram(cat, fsky=0.1, cosmo=Planck15)
    assert h.nbar.shape == (len(h.bin_edges) - 1,)
    # counts integrate back to N
    np.testing.assert_allclose(h.hist['counts'].sum(), 5000)
    # interpolation peaks near z ~ 0.5
    zfine = np.linspace(0.05, 0.95, 181)
    assert abs(zfine[np.argmax(h.interpolate(zfine))] - 0.5) < 0.1


def test_filters_preserve_mean_and_smooth():
    from nbodykit_tpu.lab import ArrayMesh
    rng = np.random.RandomState(3)
    field = rng.standard_normal((32, 32, 32)) + 5.0
    mesh = ArrayMesh(field, BoxSize=32.0)
    for filt in [TopHat(2.0), Gaussian(2.0)]:
        sm = mesh.apply(filt, kind='wavenumber',
                        mode='complex').compute(mode='real')
        val = np.asarray(sm.value)
        np.testing.assert_allclose(val.mean(), field.mean(), rtol=1e-6)
        assert val.std() < field.std() * 0.5


def test_hod_populate():
    rng = np.random.RandomState(4)
    Nh = 500
    logM = rng.uniform(12.5, 15.0, Nh)
    halos = ArrayCatalog({
        'Mass': 10 ** logM,
        'Position': rng.uniform(0, 100.0, size=(Nh, 3)),
        'Velocity': rng.normal(0, 100, size=(Nh, 3))},
        BoxSize=100.0)
    model = HODModel(Zheng07Model(), seed=11)
    gals = model.populate(halos)
    assert gals.csize > Nh * 0.3
    types = np.asarray(gals['gal_type'])
    assert (types == 0).sum() > 0 and (types == 1).sum() > 0
    pos = np.asarray(gals['Position'])
    assert pos.min() >= 0 and pos.max() <= 100.0
    # occupation increases with halo mass
    occ = Zheng07Model()
    assert occ.mean_ncen(1e15) > 0.99
    assert occ.mean_ncen(1e12) < 0.05
    assert occ.mean_nsat(1e15) > occ.mean_nsat(1e14)


def test_hod_reproducible():
    rng = np.random.RandomState(5)
    halos = ArrayCatalog({
        'Mass': 10 ** rng.uniform(13, 15, 100),
        'Position': rng.uniform(0, 50.0, size=(100, 3)),
        'Velocity': np.zeros((100, 3))}, BoxSize=50.0)
    g1 = HODModel(seed=7).populate(halos)
    g2 = HODModel(seed=7).populate(halos)
    np.testing.assert_array_equal(np.asarray(g1['Position']),
                                  np.asarray(g2['Position']))


def test_split_ranks():
    groups = list(split_ranks(8, 3))
    assert groups[0] == (0, [0, 1, 2])
    assert groups[-1] == (2, [6, 7])


def test_task_manager():
    with TaskManager(cpus_per_task=2) as tm:
        results = tm.map(lambda x: x ** 2, range(5))
    assert results == [0, 1, 4, 9, 16]
    with TaskManager(cpus_per_task=1) as tm:
        acc = [t for t in tm.iterate(range(3))]
    assert acc == [0, 1, 2]


def test_fftrecon_reduces_displacement():
    # reconstruction should partially undo Zel'dovich displacements:
    # the reconstructed field's large-scale power moves toward linear
    from nbodykit_tpu.algorithms.fftrecon import FFTRecon
    Plin = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    Plin.sigma8 = 0.8
    data = LogNormalCatalog(Plin=Plin, nbar=2e-3, BoxSize=200.,
                            Nmesh=32, bias=1.5, seed=21)
    ran = UniformCatalog(nbar=8e-3, BoxSize=200., seed=22)
    recon = FFTRecon(data, ran, Nmesh=32, bias=1.5, R=15.0)
    field = recon.compute(mode='real')
    val = np.asarray(field.value)
    assert np.isfinite(val).all()
    # mean ~ 0 for an overdensity-difference field
    assert abs(val.mean()) < 0.05


def test_3pcf_nonperiodic_no_double_count():
    # regression: boundary cells in the non-periodic path must not
    # revisit aliased neighbor cells (SurveyData3PCF path)
    pos = np.array([[0.1, 0.1, 0.1], [1.0, 0.1, 0.1],
                    [9.9, 9.9, 9.9], [9.0, 9.9, 9.9]])
    w = np.ones(4)
    cat = ArrayCatalog({'Position': pos, 'Weight': w})
    # BoxSize absent -> non-periodic bbox path
    from nbodykit_tpu.algorithms.threeptcf import Base3PCF

    class Direct(Base3PCF):
        def __init__(self):
            self.attrs = dict(poles=[0], edges=np.array([0.5, 1.5]))
            self.poles = self._run(pos, w, np.array([0.5, 1.5]), [0],
                                   BoxSize=None, periodic=False)

    r = Direct()
    # each point has exactly one neighbor at separation ~0.9-1.0:
    # sum_i w_i * (1*1) * P_0 = 4, scaled by the reference corr
    # normalization (2l+1)/(4pi)^2
    np.testing.assert_allclose(np.asarray(r.poles['corr_0'])[0, 0],
                               4.0 / (4 * np.pi) ** 2, rtol=1e-6)


def test_fof_nonperiodic():
    from nbodykit_tpu.algorithms.fof import FOF
    pos = np.array([[0.1, 50.0, 50.0], [99.9, 50.0, 50.0],
                    [0.4, 50.0, 50.0]])
    cat = ArrayCatalog({'Position': pos}, BoxSize=100.0)
    f_per = FOF(cat, linking_length=0.5, nmin=1, absolute=True,
                periodic=True)
    f_non = FOF(cat, linking_length=0.5, nmin=1, absolute=True,
                periodic=False)
    lp = np.asarray(f_per.labels)
    ln = np.asarray(f_non.labels)
    assert lp[0] == lp[1] == lp[2]      # wraps: all one group
    assert ln[0] == ln[2] != ln[1]      # no wrap: boundary separated


def test_fof_peak_columns():
    from nbodykit_tpu.algorithms.fof import FOF
    rng = np.random.RandomState(6)
    c1 = rng.normal(20, 0.3, size=(20, 3))
    c2 = rng.normal(70, 0.3, size=(10, 3))
    pos = np.concatenate([c1, c2])
    dens = np.zeros(30)
    dens[3] = 10.0   # peak of cluster 1
    dens[25] = 7.0   # peak of cluster 2
    cat = ArrayCatalog({'Position': pos, 'Density': dens},
                       BoxSize=100.0)
    fof = FOF(cat, linking_length=2.0, nmin=5, absolute=True)
    feats = fof.find_features(peakcolumn='Density')
    pk = np.asarray(feats['PeakPosition'])
    np.testing.assert_allclose(pk[1], pos[3], rtol=1e-6)
    np.testing.assert_allclose(pk[2], pos[25], rtol=1e-6)


def test_task_manager_concurrent_submeshes(cpu8):
    """Tasks farm to disjoint sub-meshes on worker threads (reference
    master-worker farming, batch.py:172-267): each task must see a
    2-device ambient mesh, distinct groups must be used, and a real
    device computation must come back correct per task."""
    import threading
    from nbodykit_tpu.parallel.runtime import CurrentMesh, use_mesh
    from nbodykit_tpu.pmesh import ParticleMesh

    seen = []
    lock = threading.Lock()

    def task(seed):
        mesh = CurrentMesh.get()
        devs = tuple(d.id for d in np.asarray(mesh.devices).ravel())
        with lock:
            seen.append(devs)
        pm = ParticleMesh(Nmesh=8, BoxSize=10.0, dtype='f8', comm=mesh)
        rng = np.random.RandomState(seed)
        pos = jnp.asarray(rng.uniform(0, 10.0, (64, 3)))
        field = pm.paint(pos, 1.0, resampler='cic')
        return float(field.sum())

    with use_mesh(cpu8):
        with TaskManager(cpus_per_task=2) as tm:
            results = tm.map(task, range(6))

    # every task conserved mass on its sub-mesh
    np.testing.assert_allclose(results, 64.0, rtol=1e-12)
    # every ambient mesh had 2 devices; more than one distinct group ran
    assert all(len(d) == 2 for d in seen)
    assert len(set(seen)) > 1


def test_leauthaud11_occupation():
    """Native Leauthaud11 HOD (reference hod.py:191 exposes it via
    halotools): erf midpoint at the SHMR threshold mass, monotone
    occupations, satellite power law positive."""
    from nbodykit_tpu.hod import Leauthaud11Model

    m = Leauthaud11Model(threshold=10.5)
    M = np.logspace(11, 15, 200)
    ncen = m.mean_ncen(M)
    nsat = m.mean_nsat(M)
    assert np.all(np.diff(ncen) >= -1e-12) and ncen.max() <= 1.0
    assert np.all(nsat >= 0) and nsat[-1] > 1.0
    # <Ncen> = 1/2 exactly where f_SHMR(Mh) hits the threshold
    Mh_t = 10 ** m._log_mh_thresh
    np.testing.assert_allclose(m.mean_ncen(np.array([Mh_t]))[0], 0.5,
                               atol=1e-4)
    # SHMR grid inversion is self-consistent
    np.testing.assert_allclose(m._log_mstar(np.array([Mh_t]))[0], 10.5,
                               atol=1e-3)


def test_hearin15_decorated_hod():
    """Decorated HOD: the perturbation preserves the mass-binned mean,
    respects bounds, and populate() runs end-to-end."""
    from nbodykit_tpu.hod import (Hearin15Model, HODModel,
                                  mass_binned_percentile)

    M = np.full(1000, 1e13)
    pct = np.linspace(0, 1, 1000, endpoint=False)
    # mean preservation must hold at ANY split/strength, including the
    # asymmetric cases where the compensating branch hits its floor
    for split, strength in [(0.5, 0.8), (0.25, 1.0), (0.75, 1.0),
                            (0.25, -1.0)]:
        m = Hearin15Model(threshold=10.5, split=split,
                          assembias_strength=strength)
        ncen = m.mean_ncen(M, percentile=pct)
        base = m.mean_ncen(M)
        assert ncen.min() >= -1e-12 and ncen.max() <= 1.0 + 1e-12
        np.testing.assert_allclose(ncen.mean(), base.mean(), rtol=1e-9,
                                   err_msg="split=%s A=%s"
                                   % (split, strength))
        # and both branches actually moved (the decoration is active)
        if abs(strength) > 0:
            assert not np.allclose(ncen[pct >= split].mean(),
                                   ncen[pct < split].mean())
        nsat = m.mean_nsat(M, percentile=pct)
        np.testing.assert_allclose(nsat.mean(), m.mean_nsat(M).mean(),
                                   rtol=1e-9)
        assert nsat.min() >= -1e-12

    m = Hearin15Model(threshold=10.5, assembias_strength=0.8)
    ncen = m.mean_ncen(M, percentile=pct)
    base = m.mean_ncen(M)
    # high-percentile halos are boosted
    assert ncen[-1] > base[0] > ncen[0]

    # percentiles are uniform within mass bins
    rng = np.random.RandomState(2)
    Mr = 10 ** rng.uniform(12, 15, 2000)
    conc = 7.0 * (Mr / 1e13) ** -0.1 * rng.lognormal(0, 0.3, 2000)
    p = mass_binned_percentile(Mr, conc)
    assert 0.45 < p.mean() < 0.55 and p.min() >= 0 and p.max() < 1

    # end-to-end population with assembly bias (real secondary column)
    rng = np.random.RandomState(5)
    nh = 400
    halos = ArrayCatalog({
        'Position': rng.uniform(0, 100.0, (nh, 3)),
        'Velocity': np.zeros((nh, 3)),
        'Mass': 10 ** rng.uniform(12.5, 14.5, nh),
        'Concentration': rng.lognormal(2.0, 0.3, nh)}, BoxSize=100.0)
    cat = HODModel(occupation=m, seed=11).populate(halos)
    assert len(np.asarray(cat['Position'])) > 0
    assert set(np.unique(np.asarray(cat['gal_type']))) <= {0, 1}

    # without a Concentration column the decoration must NOT silently
    # run on the deterministic mass-scaling fallback (it would fake an
    # assembly-bias signal out of the mass rank); it warns and
    # populates undecorated instead
    bare = ArrayCatalog({
        'Position': np.asarray(halos['Position']),
        'Velocity': np.zeros((nh, 3)),
        'Mass': np.asarray(halos['Mass'])}, BoxSize=100.0)
    with pytest.warns(UserWarning, match="no 'Concentration'"):
        cat2 = HODModel(occupation=m, seed=11).populate(bare)
    assert len(np.asarray(cat2['Position'])) > 0


@pytest.mark.slow
def test_fftrecon_all_schemes():
    """LF2 and LRR schemes run and agree with LGS at large scales
    (reference fftrecon.py:172-215 scheme composition)."""
    from nbodykit_tpu.algorithms.fftrecon import FFTRecon

    Plin = LinearPower(Planck15, 0.0, transfer='EisensteinHu')
    data = LogNormalCatalog(Plin=Plin, nbar=2e-3, BoxSize=200.,
                            Nmesh=32, bias=1.5, seed=21)
    ran = UniformCatalog(nbar=8e-3, BoxSize=200., seed=22)
    fields = {}
    for scheme in ('LGS', 'LF2', 'LRR'):
        recon = FFTRecon(data, ran, Nmesh=32, bias=1.5, R=15.0,
                         scheme=scheme)
        val = np.asarray(recon.compute(mode='real').value)
        assert np.isfinite(val).all(), scheme
        assert abs(val.mean()) < 0.05, scheme
        fields[scheme] = val
    # exact scheme identity (reference fftrecon.py:194-199):
    # LF2 = 3/7 LGS + 4/7 LRR
    np.testing.assert_allclose(
        fields['LF2'], 3.0 / 7.0 * fields['LGS']
        + 4.0 / 7.0 * fields['LRR'], rtol=1e-4, atol=1e-5)
    # all schemes estimate the same underlying field: positively
    # correlated, but not identical
    for other in ('LF2', 'LRR'):
        rho = np.corrcoef(fields['LGS'].ravel(),
                          fields[other].ravel())[0, 1]
        assert rho > 0.5, (other, rho)
    assert not np.array_equal(fields['LGS'], fields['LF2'])


@pytest.mark.slow
def test_quickstart_cookbook():
    """The executable cookbook (tutorials/quickstart.py) runs every
    docs/EXAMPLES.md flow end-to-end with finite results."""
    from nbodykit_tpu.tutorials.quickstart import run_all

    out = run_all()
    assert len(out) >= 12
    for k, v in out.items():
        if isinstance(v, float):
            assert np.isfinite(v), (k, v)
    assert out['roundtrip_ok'] and out['bigfile_ok']
    # the populate(OccupationClass, **params) path must actually run
    assert out['n_halos'] > 0 and 'n_hod' in out
    assert out['farmed'] == 2
    assert abs(out['sigma8'] - 0.8159) < 0.01
