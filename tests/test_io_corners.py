"""IO corner cases ported from the reference suites
(nbodykit/io/tests/{test_base,test_csv,test_binary,test_hdf,
test_stack}.py) — the failure modes and selection semantics the happy
paths in test_io.py do not reach.
"""

import pickle

import numpy as np
import pytest

from nbodykit_tpu.io.csv import CSVFile
from nbodykit_tpu.io.binary import BinaryFile
from nbodykit_tpu.io.hdf import HDFFile
from nbodykit_tpu.io.stack import FileStack

try:
    import h5py
except ImportError:
    h5py = None


# ---------------------------------------------------------------------------
# FileType selection semantics (reference io/tests/test_base.py)

def _csv5(tmp_path, n=100, fmt='%.7e'):
    data = np.random.RandomState(0).uniform(size=(n, 5))
    path = str(tmp_path / 'data.txt')
    np.savetxt(path, data, fmt=fmt)
    return data, CSVFile(path, names=list('abcde'))


def test_getitem_semantics(tmp_path):
    data, f = _csv5(tmp_path)

    with pytest.raises(IndexError):
        f[[]]                       # empty column selection
    with pytest.raises(IndexError):
        f['a']['a']                 # cannot column-slice twice
    with pytest.raises(IndexError):
        f[['BAD1', 'BAD2']]         # unknown columns

    f2 = f[['a', 'b']]
    assert f2.columns == ['a', 'b']
    f3 = f2[['a']]
    assert f3.columns == ['a']
    with pytest.raises(IndexError):
        f2[['c']]                   # column outside the restricted view

    # a single-column view slices to a plain array
    np.testing.assert_allclose(f['a'][:], data[:, 0], rtol=1e-6)
    np.testing.assert_allclose(f['a'][10:20], data[10:20, 0], rtol=1e-6)

    # boolean mask and integer-list row selection
    valid = np.random.RandomState(1).choice([True, False], size=len(f))
    np.testing.assert_allclose(f[valid]['a'], data[valid, 0], rtol=1e-6)
    np.testing.assert_allclose(f[np.array([0, 1, 2])]['b'],
                               data[[0, 1, 2], 1], rtol=1e-6)


def test_asarray(tmp_path):
    data, f = _csv5(tmp_path)
    d = f.asarray()
    assert d.shape == (100, 5)
    np.testing.assert_allclose(d, data, rtol=1e-6)
    np.testing.assert_allclose(f[['a', 'b']].asarray(), data[:, :2],
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# CSV corner cases (reference io/tests/test_csv.py)

def test_csv_no_trailing_newline(tmp_path):
    path = str(tmp_path / 'nonewline.txt')
    with open(path, 'w') as ff:
        ff.write("1 1 1 1\n2 2 2 2")    # no trailing newline
    f = CSVFile(path, names=list('abcd'), dtype='i4')
    assert f.size == 2
    np.testing.assert_array_equal(
        f.asarray(), np.array([[1, 1, 1, 1], [2, 2, 2, 2]]))


def test_csv_leading_blank_lines(tmp_path):
    data = np.random.RandomState(2).uniform(size=(100, 5))
    path = str(tmp_path / 'blank.txt')
    with open(path, 'w') as ff:
        ff.write("\n\n\n")
        np.savetxt(ff, data, fmt='%.7e')
    f = CSVFile(path, names=list('abcde'))
    assert f.size == 100
    np.testing.assert_allclose(f['a'][:], data[:, 0], rtol=1e-6)


def test_csv_dtype_forms(tmp_path):
    data, _ = _csv5(tmp_path)
    path = str(tmp_path / 'data.txt')
    f = CSVFile(path, names=list('abcde'),
                dtype={'a': 'f4', 'b': 'i8', 'c': 'f8'})
    assert f.dtype['a'] == 'f4'
    assert f.dtype['b'] == 'i8'
    assert f.dtype['c'] == 'f8'
    f = CSVFile(path, names=list('abcde'), dtype='f4')
    assert all(f.dtype[c] == 'f4' for c in 'abcde')


def test_csv_wrong_names(tmp_path):
    data, _ = _csv5(tmp_path)
    path = str(tmp_path / 'data.txt')
    with pytest.raises(ValueError):
        CSVFile(path, names=['a', 'b', 'c'])   # 5 columns in the file


def test_csv_invalid_keywords(tmp_path):
    data, _ = _csv5(tmp_path)
    path = str(tmp_path / 'data.txt')
    for k, v in [('index_col', True), ('header', True),
                 ('skipfooter', True)]:
        with pytest.raises(ValueError):
            CSVFile(path, names=list('abcde'), **{k: v})


def test_csv_pickle(tmp_path):
    data, f = _csv5(tmp_path)
    f2 = pickle.loads(pickle.dumps(f))
    np.testing.assert_allclose(f2['a'][:], data[:, 0], rtol=1e-6)


# ---------------------------------------------------------------------------
# Binary corner cases (reference io/tests/test_binary.py)

def _binfile(tmp_path, header=0):
    rng = np.random.RandomState(3)
    pos = rng.uniform(size=(1024, 3))
    vel = rng.uniform(size=(1024, 3))
    path = str(tmp_path / 'data.bin')
    with open(path, 'wb') as ff:
        if header:
            np.arange(header // 8, dtype='i8').tofile(ff)
        pos.tofile(ff)
        vel.tofile(ff)
    dtype = [('Position', ('f8', 3)), ('Velocity', ('f8', 3))]
    return pos, vel, path, dtype


def test_binary_offsets(tmp_path):
    pos, vel, path, dtype = _binfile(tmp_path)
    f = BinaryFile(path, dtype, size=1024,
                   offsets={'Position': 0, 'Velocity': pos.nbytes})
    np.testing.assert_array_equal(
        f.read(['Velocity'], 0, 1024)['Velocity'], vel)
    with pytest.raises(ValueError):
        BinaryFile(path, dtype, size=1024, offsets={'Position': 0})
    with pytest.raises(TypeError):
        BinaryFile(path, dtype, size=1024, offsets=[('Position', 0)])


def test_binary_header_and_infer(tmp_path):
    pos, vel, path, dtype = _binfile(tmp_path, header=80)
    f = BinaryFile(path, dtype, header_size=80)
    assert f.size == 1024        # inferred through the header
    np.testing.assert_array_equal(
        f.read(['Position'], 0, 1024)['Position'], pos)
    with pytest.raises(ValueError):
        BinaryFile(path, dtype, header_size=79)   # misaligned payload


def test_binary_pickle(tmp_path):
    pos, vel, path, dtype = _binfile(tmp_path)
    f = BinaryFile(path, dtype, size=1024)
    f2 = pickle.loads(pickle.dumps(f))
    np.testing.assert_array_equal(
        f2.read(['Position'], 10, 20)['Position'], pos[10:20])


# ---------------------------------------------------------------------------
# HDF corner cases (reference io/tests/test_hdf.py)

@pytest.mark.skipif(h5py is None, reason="h5py not installed")
def test_hdf_nonzero_root_and_exclude(tmp_path):
    path = str(tmp_path / 'data.h5')
    rng = np.random.RandomState(4)
    pos = rng.uniform(size=(64, 3))
    mass = rng.uniform(size=64)
    with h5py.File(path, 'w') as ff:
        ff.create_dataset('X/Position', data=pos)
        g = ff.create_group('Y')
        g.create_dataset('Position', data=pos)
        g.create_dataset('Mass', data=mass)

    f = HDFFile(path, dataset='Y')
    assert sorted(f.columns) == ['Mass', 'Position']
    with pytest.raises(ValueError):
        HDFFile(path, dataset='Z')

    f = HDFFile(path, dataset='Y', exclude=['Mass'])
    assert f.columns == ['Position']
    with pytest.raises(ValueError):
        HDFFile(path, dataset='Y', exclude=['Nope'])


@pytest.mark.skipif(h5py is None, reason="h5py not installed")
def test_hdf_size_mismatch_and_empty(tmp_path):
    path = str(tmp_path / 'mismatch.h5')
    rng = np.random.RandomState(5)
    with h5py.File(path, 'w') as ff:
        ff.create_dataset('Mass', data=rng.uniform(size=512))
        ff.create_dataset('Position', data=rng.uniform(size=(1024, 3)))
    with pytest.raises(ValueError):
        HDFFile(path)
    f = HDFFile(path, exclude=['Mass'])
    assert f.size == 1024

    empty = str(tmp_path / 'empty.h5')
    with h5py.File(empty, 'w') as ff:
        ff.create_group('G')
    with pytest.raises(ValueError):
        HDFFile(empty, dataset='G')


# ---------------------------------------------------------------------------
# Stack corner cases (reference io/tests/test_stack.py)

def test_stack_single_and_bad_path(tmp_path):
    pos, vel, path, dtype = _binfile(tmp_path)
    s = FileStack(BinaryFile, path, dtype, size=1024)
    assert s.nfiles == 1 and s.size == 1024
    with pytest.raises(FileNotFoundError):
        FileStack(BinaryFile, str(tmp_path / 'nope.*'), dtype)
