"""LogNormalCatalog / mockmaker tests (reference analog:
source/catalog/tests/test_lognormal.py): power recovery vs b^2 P_lin,
device-count invariance, velocity scaling.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from nbodykit_tpu.lab import (LogNormalCatalog, LinearPower, Planck15,
                              FFTPower)
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh


@pytest.fixture(scope='module')
def plin():
    P = LinearPower(Planck15, redshift=0.55, transfer='EisensteinHu')
    P.sigma8 = 0.8
    return P


def test_lognormal_power_recovery(plin):
    cat = LogNormalCatalog(Plin=plin, nbar=3e-4, BoxSize=512., Nmesh=64,
                           bias=2.0, seed=42)
    # sane size
    expected_N = 3e-4 * 512. ** 3
    assert abs(cat.csize - expected_N) / expected_N < 0.05

    mesh = cat.to_mesh(Nmesh=64, resampler='cic', compensated=True)
    r = FFTPower(mesh, mode='1d', dk=0.01, kmin=0.01)
    pk = r.power['power'].real - r.attrs['shotnoise']
    k = r.power['k']
    sel = (k > 0.02) & (k < 0.1)
    ratio = pk[sel] / (4.0 * plin(k[sel]))
    assert abs(np.nanmean(ratio) - 1.0) < 0.2


def test_lognormal_device_count_invariance(plin):
    cats = []
    for comm in [cpu_mesh(1), cpu_mesh()]:
        with use_mesh(comm):
            cat = LogNormalCatalog(Plin=plin, nbar=1e-4, BoxSize=256.,
                                   Nmesh=32, bias=2.0, seed=7)
            cats.append(np.asarray(cat['Position']))
    assert cats[0].shape == cats[1].shape
    np.testing.assert_allclose(cats[0], cats[1], rtol=1e-5, atol=1e-4)


def test_lognormal_columns(plin):
    cat = LogNormalCatalog(Plin=plin, nbar=1e-4, BoxSize=256., Nmesh=32,
                           bias=2.0, seed=3)
    assert 'Position' in cat.columns
    assert 'Velocity' in cat.columns
    assert 'VelocityOffset' in cat.columns
    pos = np.asarray(cat['Position'])
    assert pos.min() >= 0 and pos.max() <= 256.0
    # velocity = voff * 100 E(z)/(1+z)
    z = cat.attrs['redshift']
    E = float(Planck15.efunc(z))
    np.testing.assert_allclose(
        np.asarray(cat['Velocity']),
        np.asarray(cat['VelocityOffset']) * 100 * E / (1 + z),
        rtol=1e-5, atol=1e-5)


def test_unitary_amplitude_reduces_variance(plin):
    # unitary realizations have (nearly) no large-scale sample variance
    powers = []
    for seed in [1, 2, 3]:
        cat = LogNormalCatalog(Plin=plin, nbar=5e-4, BoxSize=256.,
                               Nmesh=32, bias=1.0, seed=seed,
                               unitary_amplitude=True)
        mesh = cat.to_mesh(resampler='cic', compensated=True)
        r = FFTPower(mesh, mode='1d', dk=0.02, kmin=0.02)
        powers.append(r.power['power'].real[:3])
    spread = np.std(powers, axis=0) / np.mean(powers, axis=0)
    assert np.all(spread < 0.2)
