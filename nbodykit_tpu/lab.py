"""The single import surface (reference: nbodykit/lab.py):

    from nbodykit_tpu.lab import *
"""

from . import set_options, setup_logging, timer  # noqa: F401
from .parallel.runtime import (CurrentMesh, use_mesh, cpu_mesh,  # noqa: F401
                               tpu_mesh)
from .pmesh import ParticleMesh  # noqa: F401
from .binned_statistic import BinnedStatistic  # noqa: F401
from .base.catalog import CatalogSource  # noqa: F401
from .base.mesh import MeshSource, FieldMesh  # noqa: F401
from .source.catalog import ArrayCatalog, RandomCatalog, UniformCatalog  # noqa: F401
from .source.mesh import CatalogMesh, LinearMesh, ArrayMesh  # noqa: F401
from .algorithms import (FFTPower, ProjectedFFTPower, FFTCorr,  # noqa: F401
                         FFTBase, Bispectrum, project_to_basis)
from . import transform  # noqa: F401
from .source.catalog import LogNormalCatalog  # noqa: F401,E402
from . import cosmology  # noqa: F401,E402
from .cosmology import (Cosmology, Planck13, Planck15,  # noqa: F401,E402
                        WMAP5, WMAP7, WMAP9, LinearPower, HalofitPower,
                        ZeldovichPower, CorrelationFunction)
from .algorithms import ConvolvedFFTPower, FKPCatalog, FKPWeightFromNbar  # noqa: F401,E402
from .algorithms.convpower.catalogmesh import FKPCatalogMesh  # noqa: F401,E402
FKPPower = ConvolvedFFTPower  # reference alias (algorithms/__init__.py:7)
from .source.catalog.species import MultipleSpeciesCatalog  # noqa: F401,E402
from .source.mesh.species import MultipleSpeciesCatalogMesh  # noqa: F401,E402
from .source.catalog.file import (CSVCatalog, BinaryCatalog,  # noqa: F401,E402
                                  BigFileCatalog, HDFCatalog, FITSCatalog,
                                  TPMBinaryCatalog, Gadget1Catalog,
                                  FileCatalogBase, FileCatalog,
                                  FileCatalogFactory)
from .source.mesh.bigfile import BigFileMesh  # noqa: F401,E402
from .algorithms.fftrecon import FFTRecon  # noqa: F401,E402
from . import io  # noqa: F401,E402
IO = io  # reference alias (lab.py:18 imports io as IO)
from .algorithms.fof import FOF  # noqa: F401,E402
from .source.catalog.halos import HaloCatalog  # noqa: F401,E402
from .algorithms.pair_counters import (SimulationBoxPairCount,  # noqa: F401,E402
                                       SurveyDataPairCount)
from .algorithms.pair_counters.base import PairCountBase  # noqa: F401,E402
from .algorithms.paircount_tpcf import (SimulationBox2PCF,  # noqa: F401,E402
                                        SurveyData2PCF)
from .algorithms.paircount_tpcf.estimators import WedgeBinnedStatistic  # noqa: F401,E402
from .algorithms.threeptcf import (SimulationBox3PCF, SurveyData3PCF,  # noqa: F401,E402
                                   YlmCache)
from .algorithms.kdtree import KDDensity  # noqa: F401,E402
from .algorithms.zhist import RedshiftHistogram  # noqa: F401,E402
from .algorithms.cgm import CylindricalGroups  # noqa: F401,E402
from .algorithms.fibercollisions import FiberCollisions  # noqa: F401,E402
from . import filters  # noqa: F401,E402
from .filters import TopHat, Gaussian  # noqa: F401,E402
from .hod import (HODModel, Zheng07Model, Leauthaud11Model,  # noqa: F401,E402
                  Hearin15Model, HODModelFactory, PopulatedHaloCatalog)
from .batch import TaskManager  # noqa: F401,E402
from .source.catalog.subvolumes import SubVolumesCatalog  # noqa: F401,E402
from .cosmology import FNLGalaxyPower, LinearNbody  # noqa: F401,E402
from .tutorials import DemoHaloCatalog  # noqa: F401,E402
from . import meshtools  # noqa: F401,E402
