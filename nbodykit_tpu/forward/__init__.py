"""Differentiable forward model: LPT initial conditions, a symplectic
PM stepper, and field-level inference — ROADMAP item 3.

Everything here is a pure function of the linear modes, built from ops
the analysis plane already trusts (paint/readout, dist_rfftn, the
Poisson-solve kernels), so ``jax.grad`` flows through the whole
pipeline.  Layering:

  lpt.py      Zel'dovich + 2LPT displacements from the mockmaker linear
              field, via spectral gradient-of-inverse-Laplacian.
  adjoint.py  grad-safe paint: native reverse mode where the tuned
              winner supports it, an analytic ``jax.custom_vjp``
              (scatter's adjoint IS readout) where it does not.
  pm.py       kick-drift-kick PM stepper; ``ForwardModel`` is the
              modes -> density map the serve plane runs as traffic.
  infer.py    Gaussian field-level posterior + gradient-descent
              recovery of the initial field, FFTRecon as baseline.

See docs/FORWARD.md for the stepper math and the adjoint contract.
"""

from .lpt import (linear_amplitude, linear_modes, modes_from_white,
                  lpt_displacements, lpt_init)
from .adjoint import resolve_forward_paint, make_paint
from .pm import (ForwardModel, GrowthTable, dkick, ddrift,
                 power_law, normalized_amplitude)
from .infer import (binned_power, cross_correlation,
                    mean_cross_correlation, make_loss, linear_init,
                    recover, fftrecon_baseline)

__all__ = [
    'linear_amplitude', 'linear_modes', 'modes_from_white',
    'lpt_displacements', 'lpt_init',
    'resolve_forward_paint', 'make_paint',
    'ForwardModel', 'GrowthTable', 'dkick', 'ddrift', 'power_law',
    'normalized_amplitude',
    'binned_power', 'cross_correlation', 'mean_cross_correlation',
    'make_loss', 'linear_init', 'recover', 'fftrecon_baseline',
]
