"""Lagrangian perturbation theory initial conditions (ZA + 2LPT).

Generates particle positions and momenta at a starting scale factor
from the linear density modes, via the spectral displacement recipe the
mockmaker already uses (gradient of the inverse Laplacian through
``dist_rfftn``):

  ZA:    psi1_i(k) = i k_i / k^2 * delta_k
  2LPT:  S2 = sum_{i<j} [phi_{,ii} phi_{,jj} - phi_{,ij}^2],
         phi_{,ij}(k) = k_i k_j / k^2 * delta_k,
         psi2_i(k) = i k_i / k^2 * S2(k)

with Einstein-de-Sitter growth (Omega_m = 1, the gauge the KDK stepper
in pm.py integrates):

  x(q, a) = q + D1 psi1 + D2 psi2,    D1 = a,  D2 = -(3/7) a^2
  p(q, a) = a^{3/2} (dD1/dlna psi1 + dD2/dlna psi2) / a^{1/2}
          = a^{3/2} (psi1 - (6/7) a psi2)

where the momentum convention p = a^2 dx/dt (t in units with H0 = 1)
matches the stepper's kick/drift factors — at linear order the
Zel'dovich flow is an EXACT solution of the discrete KDK operators up
to the O(da^3) integrator error, which is what the asymptotics test in
tests/test_forward.py checks.

Every function is jit-pure and differentiable with respect to the
modes; particles live on the mesh lattice (one per cell, shift=0) so
psi-at-particle is a raster reshape — no readout, no interpolation
error in the ICs, and reverse mode through them is a reshape too.
"""

import numpy as np
import jax.numpy as jnp


def _k_inv_k2(pm):
    """k-vectors and the zero-safe 1/k^2 on the transposed complex
    layout, in the mesh compute dtype."""
    kx, ky, kz = pm.k_list()
    k2 = kx ** 2 + ky ** 2 + kz ** 2
    inv = jnp.where(k2 == 0, 0.0, 1.0 / jnp.where(k2 == 0, 1.0, k2))
    return (kx, ky, kz), inv


def linear_amplitude(pm, linear_power):
    """sqrt(P(k)/V) on the complex mesh — the scaling that turns a
    unit-variance hermitian whitenoise field into linear density modes
    (mockmaker recipe, mockmaker.py gaussian_complex_fields).

    ``linear_power`` is P(k) in box units, callable on |k|.  The DC
    mode is zeroed (and P is never evaluated at k=0, so power laws
    with negative spectral index are safe).
    """
    kx, ky, kz = pm.k_list()
    k2 = kx ** 2 + ky ** 2 + kz ** 2
    kmag = jnp.sqrt(jnp.where(k2 == 0, 1.0, k2))
    V = float(np.prod(pm.BoxSize))
    power = jnp.where(k2 == 0, 0.0, linear_power(kmag))
    return jnp.sqrt(jnp.maximum(power, 0.0) / V)


def linear_modes(pm, linear_power, seed):
    """Gaussian linear density modes delta_k for a power spectrum —
    ``generate_whitenoise`` scaled by :func:`linear_amplitude`.
    Device-count invariant (the whitenoise draw is a function of
    (seed, global cell index) only)."""
    eta = pm.generate_whitenoise(seed)
    return eta * linear_amplitude(pm, linear_power)


def modes_from_white(pm, white, amp):
    """Differentiable map from a REAL whitenoise field (the inference
    parametrization, one real number per mesh cell) to linear modes.

    ``pm.r2c`` is forward-normalized (divides by Ntot); multiplying by
    sqrt(Ntot) restores unit variance per complex mode so ``amp``
    (from :func:`linear_amplitude`) gives the same mode statistics as
    :func:`linear_modes`.  Parametrizing by a real field keeps the
    optimization leaf real-valued — no Wirtinger bookkeeping in
    jax.grad — and the prior is an iid unit normal on the leaf.
    """
    return pm.r2c(white) * np.sqrt(pm.Ntot) * amp


def lpt_displacements(pm, delta_k, order=2):
    """ZA (and optionally 2LPT) displacement fields on the mesh.

    Returns (psi1, psi2): lists of three real fields each (psi2 is
    None for order=1).  Spectral throughout — six c2r per order-2
    off-diagonal/diagonal Hessian component plus one r2c for the 2LPT
    source, all through the sharded ``dist_rfftn`` drivers.
    """
    if order not in (1, 2):
        raise ValueError("order must be 1 (ZA) or 2 (2LPT)")
    kv, inv = _k_inv_k2(pm)
    psi1 = [pm.c2r(1j * kv[i] * inv * delta_k) for i in range(3)]
    if order == 1:
        return psi1, None
    # phi_{,ij}(k) = k_i k_j / k^2 delta_k; S2 = sum_{i<j} (d_ii d_jj - d_ij^2)
    diag = [pm.c2r(kv[i] * kv[i] * inv * delta_k) for i in range(3)]
    src = (diag[0] * diag[1] + diag[0] * diag[2] + diag[1] * diag[2])
    for i, j in ((0, 1), (0, 2), (1, 2)):
        od = pm.c2r(kv[i] * kv[j] * inv * delta_k)
        src = src - od * od
    src_k = pm.r2c(src)
    psi2 = [pm.c2r(1j * kv[i] * inv * src_k) for i in range(3)]
    return psi1, psi2


def lpt_init(pm, delta_k, a=0.1, order=2, growth=None):
    """Particle (positions, momenta) at scale factor ``a`` from linear
    modes, one particle per mesh cell (box units).

    The lattice is ``generate_uniform_particle_grid(shift=0)`` whose
    x-fastest raster order matches ``field.reshape(-1)``, so the
    displacement at each particle is a reshape of the displacement
    field — exact and trivially differentiable.

    ``growth`` is None (the EdS closed forms above, bit-for-bit) or a
    :class:`~.pm.GrowthTable`, generalizing to a LCDM background:

      x = q + D1 psi1 + D2 psi2
      p = a^2 E(a) (f1 D1 psi1 + f2 D2 psi2)

    (EdS ``D1 = a, f1 = 1, D2 = -(3/7) a^2, f2 = 2, E = a^{-3/2}``
    recovers the hardcoded factors).
    """
    psi1, psi2 = lpt_displacements(pm, delta_k, order=order)
    cdt = jnp.dtype(pm.compute_dtype)
    q = pm.generate_uniform_particle_grid(shift=0.0, dtype=cdt)
    d1 = jnp.stack([p.reshape(-1).astype(cdt) for p in psi1], axis=-1)
    if growth is not None:
        af = float(a)
        D1, f1 = growth.D1(af), growth.f1(af)
        pre = af ** 2 * growth.E(af)
        pos = q + D1 * d1
        mom = pre * f1 * D1 * d1
        if psi2 is not None:
            d2 = jnp.stack([p.reshape(-1).astype(cdt) for p in psi2],
                           axis=-1)
            D2, f2 = growth.D2(af), growth.f2(af)
            pos = pos + D2 * d2
            mom = mom + pre * f2 * D2 * d2
        return pos, mom
    a = jnp.asarray(a, cdt)
    pos = q + a * d1
    mom = a ** 1.5 * d1
    if psi2 is not None:
        d2 = jnp.stack([p.reshape(-1).astype(cdt) for p in psi2], axis=-1)
        pos = pos + (-3.0 / 7.0) * a ** 2 * d2
        mom = mom + a ** 1.5 * (-6.0 / 7.0) * a * d2
    return pos, mom
