"""Field-level inference: Gaussian posterior over the linear modes,
optimized with jax.grad through the full forward model.

The posterior is the standard field-level setup (e.g. 1609.00349 for
the spectral-analysis view): a unit-normal prior on the REAL
whitenoise leaf g (one number per lattice cell; modes = r2c(g) *
sqrt(Ntot) * amp, lpt.py) and a Gaussian likelihood comparing the
modeled density to the observed painted field,

  -log P(g | obs) = 0.5 ||density(modes(g)) - obs||^2 / sigma^2
                  + 0.5 ||g||^2  (+ const).

Every optimizer step is one forward+backward pipeline — exactly the
work a serve-plane ``Forward`` request performs per SBI sample.
FFTRecon (standard BAO reconstruction) is the classical baseline the
recovered field must beat on cross-correlation with the truth.
"""

import numpy as np
import jax
import jax.numpy as jnp


def _shells(pm):
    """Integer-lattice shell index + hermitian weights on the
    compressed complex mesh (same binning convention as the serve
    scheduler's _binned_power: shell = round(|k|/kf), nmesh//2 bins,
    DC in shell 0 which callers drop)."""
    kx, ky, kz = pm.k_list()
    kf = 2.0 * np.pi / np.asarray(pm.BoxSize, 'f8')
    n = jnp.sqrt((kx / kf[0]) ** 2 + (ky / kf[1]) ** 2
                 + (kz / kf[2]) ** 2)
    nbins = int(pm.Nmesh[0]) // 2
    idx = jnp.clip(jnp.floor(n + 0.5).astype(jnp.int32), 0, nbins)
    w = jnp.full(pm.shape_complex, 2.0, n.dtype)
    w = w.at[..., 0].set(1.0)
    if int(pm.Nmesh[2]) % 2 == 0:
        w = w.at[..., -1].set(1.0)
    return idx, w, nbins, float(kf[0])


def _shell_sum(idx, nbins, vals):
    return jnp.zeros(nbins + 1, vals.dtype).at[idx.reshape(-1)].add(
        vals.reshape(-1))


def binned_power(pm, c):
    """Shell-averaged P(k) of complex modes ``c`` (hermitian-weighted,
    DC dropped).  Returns (k, P, nmodes)."""
    idx, w, nbins, kf = _shells(pm)
    p = w * jnp.abs(c) ** 2
    psum = _shell_sum(idx, nbins, p)[1:]
    nsum = _shell_sum(idx, nbins, w)[1:]
    V = float(np.prod(pm.BoxSize))
    k = kf * jnp.arange(1, nbins + 1, dtype=p.dtype)
    P = jnp.where(nsum > 0, psum / jnp.maximum(nsum, 1) * V, 0.0)
    return k, P, nsum


def cross_correlation(pm, a, b):
    """Per-shell cross-correlation coefficient r(k) between two mode
    sets on the same mesh: r = P_ab / sqrt(P_aa P_bb).  Returns
    (k, r, nmodes); r is clipped to the defined shells (nmodes > 0)."""
    if a.shape != b.shape:
        raise ValueError("cross_correlation needs same-mesh modes")
    idx, w, nbins, kf = _shells(pm)
    ab = _shell_sum(idx, nbins, w * (a * jnp.conj(b)).real)[1:]
    aa = _shell_sum(idx, nbins, w * jnp.abs(a) ** 2)[1:]
    bb = _shell_sum(idx, nbins, w * jnp.abs(b) ** 2)[1:]
    nsum = _shell_sum(idx, nbins, w)[1:]
    denom = jnp.sqrt(jnp.maximum(aa * bb, 1e-300))
    k = kf * jnp.arange(1, nbins + 1, dtype=ab.dtype)
    r = jnp.where(nsum > 0, ab / denom, 0.0)
    return k, r, nsum


def mean_cross_correlation(pm, a, b, kmax=None):
    """One scalar: hermitian-weighted whole-field cross-correlation
    sum(Re a b*) / sqrt(sum|a|^2 sum|b|^2) over modes with |k| <= kmax
    (all modes when None).  The headline recovery metric — the number
    the bench stamps and the CI compares against the FFTRecon
    baseline."""
    if a.shape != b.shape:
        raise ValueError("mean_cross_correlation needs same-mesh modes")
    kx, ky, kz = pm.k_list()
    k2 = kx ** 2 + ky ** 2 + kz ** 2
    w = jnp.full(pm.shape_complex, 2.0, k2.dtype)
    w = w.at[..., 0].set(1.0)
    if int(pm.Nmesh[2]) % 2 == 0:
        w = w.at[..., -1].set(1.0)
    mask = w * (k2 > 0)
    if kmax is not None:
        mask = mask * (k2 <= float(kmax) ** 2)
    ab = jnp.sum(mask * (a * jnp.conj(b)).real)
    aa = jnp.sum(mask * jnp.abs(a) ** 2)
    bb = jnp.sum(mask * jnp.abs(b) ** 2)
    return ab / jnp.sqrt(jnp.maximum(aa * bb, 1e-300))


def make_loss(model, obs, noise_std=0.1):
    """Negative log posterior over the real whitenoise leaf (module
    docstring).  ``obs`` is an observed 1+delta field on model.pm."""
    obs = jnp.asarray(obs, jnp.dtype(model.pm.compute_dtype))
    inv = 1.0 / float(noise_std)

    def loss(white):
        d = model.density(model.modes_from_white(white))
        r = (d - obs) * inv
        return 0.5 * jnp.sum(r * r) + 0.5 * jnp.sum(white * white)
    return loss


def linear_init(model, obs):
    """Linear-theory initialization of the whitenoise leaf: treat the
    observed overdensity as if it were linear and invert the
    modes-from-white map, white = c2r(r2c(obs - 1) / (sqrt(Ntot) amp))
    (amp-zero modes drop to zero).  Starting Adam here instead of at
    zero skips the slow large-scale assembly phase — the optimizer
    only has to undo the nonlinear displacement, which is what the
    gradient is good at.  Requires the inference lattice to BE the
    force mesh (npart == nmesh^3) so the observed modes map one-to-one
    onto the lattice modes."""
    lat = model.lattice
    if lat is not model.pm:
        raise ValueError('linear_init needs npart == nmesh^3 (the '
                         'lattice must be the force mesh; got ng=%d '
                         'on nmesh=%d)' % (int(lat.Nmesh[0]),
                                           int(model.pm.Nmesh[0])))
    cdt = jnp.dtype(lat.compute_dtype)
    dk = lat.r2c(jnp.asarray(obs, cdt) - 1.0)
    amp = model.amp
    inv = jnp.where(amp > 0,
                    1.0 / (np.sqrt(lat.Ntot)
                           * jnp.maximum(amp, 1e-300)), 0.0)
    return lat.c2r(dk * inv)


def recover(model, obs, steps=30, lr=0.05, noise_std=0.1, white0=None):
    """Adam-optimize the whitenoise leaf against ``obs``.  Each step is
    one jitted value_and_grad of the full LPT+KDK+paint pipeline.
    Returns (white, losses)."""
    loss_fn = make_loss(model, obs, noise_std)
    # one jit per recover() call, reused for every optimizer step —
    # the cache outlives the loop it serves  # nbkl: disable=NBK202
    vg = jax.jit(jax.value_and_grad(loss_fn))
    w = model.white_guess() if white0 is None else white0
    m = jnp.zeros_like(w)
    v = jnp.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    losses = []
    for t in range(1, int(steps) + 1):
        val, g = vg(w)
        losses.append(float(val))
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * g * g
        mh = m / (1.0 - b1 ** t)
        vh = v / (1.0 - b2 ** t)
        w = w - lr * mh / (jnp.sqrt(vh) + eps)
    return w, losses


def fftrecon_baseline(model, pos, R=20.0, bias=1.0, ran_seed=12345):
    """Classical baseline: FFTRecon (LGS) of the evolved particles,
    returned as linear-field-estimate modes on the particle lattice so
    it is directly cross-correlatable with the truth modes.

    ``pos`` are the evolved positions (model.evolve output); the
    randoms are a uniform random catalog of the same size.  The
    reconstructed overdensity is the classical estimate of the linear
    field the gradient-based recovery must beat.
    """
    from ..algorithms.fftrecon import FFTRecon
    from ..source.catalog.array import ArrayCatalog

    lat = model.lattice
    box = np.asarray(lat.BoxSize, 'f8')
    data = ArrayCatalog({'Position': np.asarray(pos)},
                        comm=lat.comm, BoxSize=box)
    rng = np.random.RandomState(ran_seed)
    ran_pos = rng.uniform(0.0, 1.0, size=(model.npart, 3)) * box
    ran = ArrayCatalog({'Position': ran_pos.astype('f8')},
                       comm=lat.comm, BoxSize=box)
    recon = FFTRecon(data, ran, Nmesh=int(lat.Nmesh[0]), bias=bias,
                     R=R, BoxSize=box, scheme='LGS',
                     resampler=model.resampler)
    field = recon.run()
    return lat.r2c(jnp.asarray(field.value, lat.dtype))
