"""Grad-safe paint: the adjoint contract per paint kernel.

The compensated paint/readout pair is an adjoint pair — the VJP of
scatter-add IS readout — so the backward pass of painting needs no new
kernels.  What differs per tuned paint method is whether JAX's native
reverse mode can trace the FORWARD:

  scatter          natively differentiable (.at[].add has a transpose
                   rule; the halo exchange is psum/ppermute, also
                   transposable).  Used as-is.
  sort / segsum /  forward is fine under jit but reverse mode either
  streams          fails to trace (sort's while_loop) or materializes
                   absurd residuals.  Wrapped in ``jax.custom_vjp``:
                   winner kernel forward, analytic readout backward.
  mxu              its traced overflow contract requires
                   return_dropped, which cannot live inside a silent
                   custom_vjp forward — demoted via
                   ``resolve_paint(differentiable=True)`` (source tag
                   'grad-fallback', counter ``tune.grad_fallback``).

The analytic backward, for out = paint(pos, mass) and cotangent g:

  d/dmass  = readout(g, pos)                       (the classic adjoint)
  d/dpos_d = mass * readout(g, pos, grad_axis=d) * Nmesh_d / Box_d

where ``grad_axis`` readout uses the derivative window dW/dx (cell
units, ops/window.py window_weights_grad), hence the Nmesh/Box factor
to return box-unit gradients.  window_weights_grad matches the a.e.
derivative of the native path, so both modes agree wherever defined —
asserted against finite differences in tests/test_forward.py.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .. import option_scope
from ..tune.resolve import (resolve_paint, DIFFERENTIABLE_PAINT,
                            GRAD_WRAPPED_PAINT)


def resolve_forward_paint(pm, npart):
    """Tuned paint config for a grad workload plus its adjoint mode.

    Returns (cfg, mode) with mode in {'native', 'custom_vjp'}:
    'native' lets JAX reverse mode trace the kernel, 'custom_vjp'
    means :func:`make_paint` installs the analytic readout backward.
    Cached winners without either story demote through the resolver's
    grad fallback (never a trace error deep inside ``jax.grad``).
    """
    kw = dict(nmesh=int(pm.Nmesh[0]), npart=int(npart),
              dtype=str(np.dtype(pm.dtype)), nproc=pm.nproc)
    cfg = resolve_paint(**kw)
    method = cfg.get('paint_method', 'scatter')
    if method in DIFFERENTIABLE_PAINT:
        return cfg, 'native'
    if method in GRAD_WRAPPED_PAINT:
        return cfg, 'custom_vjp'
    # mxu or unknown: ask the resolver for the grad-mode fallback.
    cfg = resolve_paint(differentiable=True, **kw)
    return cfg, 'native'


def make_paint(pm, npart, resampler='cic', method=None):
    """Build a differentiable ``paint(pos, mass=1.0) -> mesh`` over
    ``pm`` for ``npart`` particles, pinned to the tuned kernel.

    The resolved paint options are captured eagerly and re-applied via
    ``option_scope`` around every call, so resolution inside a
    ``jax.grad``/``jit`` trace is deterministic regardless of ambient
    options.  Returns (paint_fn, cfg); cfg['adjoint_mode'] records the
    contract chosen by :func:`resolve_forward_paint`.

    ``method`` pins a specific paint kernel instead of consulting the
    tuner (tests use this to exercise the custom_vjp path directly);
    a method with no adjoint story ('mxu') is a ValueError here —
    only the RESOLVER may silently demote.
    """
    if method is not None:
        cfg = dict(resolve_paint(nmesh=int(pm.Nmesh[0]),
                                 npart=int(npart),
                                 dtype=str(np.dtype(pm.dtype)),
                                 nproc=pm.nproc),
                   paint_method=method, source='explicit')
        if method in DIFFERENTIABLE_PAINT:
            mode = 'native'
        elif method in GRAD_WRAPPED_PAINT:
            mode = 'custom_vjp'
        else:
            raise ValueError(
                "paint method %r has no adjoint contract; use the "
                "resolver (method=None) for the grad fallback" % method)
    else:
        cfg, mode = resolve_forward_paint(pm, npart)
    cfg = dict(cfg, adjoint_mode=mode)
    opts = {k: cfg[k] for k in
            ('paint_method', 'paint_chunk_size', 'paint_streams')
            if k in cfg and cfg[k] is not None}
    cdt = jnp.dtype(pm.compute_dtype)

    def _run(pos, mass):
        with option_scope(**opts):
            return pm.paint(pos, mass, resampler=resampler)

    if mode == 'native':
        def paint_fn(pos, mass=1.0):
            return _run(pos, jnp.broadcast_to(
                jnp.asarray(mass, cdt), pos.shape[:1]))
        return paint_fn, cfg

    # box-units -> cell-units position gradient scale, per axis
    scale = jnp.asarray(np.asarray(pm.Nmesh, 'f8')
                        / np.asarray(pm.BoxSize, 'f8'), cdt)

    @jax.custom_vjp
    def _painted(pos, mass):
        return _run(pos, mass)

    def _fwd(pos, mass):
        return _run(pos, mass), (pos, mass)

    def _bwd(res, cot):
        pos, mass = res
        g = cot.astype(cdt)
        dmass = pm.readout(g, pos, resampler=resampler)
        dpos = jnp.stack(
            [pm.readout(g, pos, resampler=resampler, grad_axis=d)
             * scale[d] for d in range(3)], axis=-1)
        dpos = dpos * mass[:, None]
        return dpos.astype(pos.dtype), dmass.astype(mass.dtype)

    _painted.defvjp(_fwd, _bwd)

    def paint_fn(pos, mass=1.0):
        return _painted(pos, jnp.broadcast_to(
            jnp.asarray(mass, cdt), pos.shape[:1]))
    return paint_fn, cfg
