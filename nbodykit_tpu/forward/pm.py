"""Symplectic kick-drift-kick PM stepper — a pure, differentiable
function of the linear modes.

Gauge and units (Einstein-de-Sitter, Omega_m = 1, H0 = 1, positions in
box units): with canonical momentum p = a^2 dx/dt the equations of
motion separate into

  dx/da = p * a^{-3/2}           (drift)
  dp/da = F(x) * a^{-1/2}        (kick)

where F is the PM force, F_i(k) = 1.5 Omega_m * i k_i / k^2 * delta_k
read out at the particle positions.  The second-order KDK integrator
uses the EXACT time integrals of the prefactors over each interval
(Quinn et al. 1997 convention):

  dkick(a0, a1)  = int a^{-1/2} da = 2 (sqrt(a1) - sqrt(a0))
  ddrift(a0, a1) = int a^{-3/2} da = 2 (1/sqrt(a0) - 1/sqrt(a1))

so the Zel'dovich flow x = q + a psi, p = a^{3/2} psi (lpt.py) is an
exact solution of the discrete operators at linear order up to the
O(da^3) midpoint error — the property the 2LPT-vs-ZA asymptotics test
leans on.

``ForwardModel`` packages lattice + force mesh + tuned grad-safe paint
(adjoint.make_paint) into the modes -> density map the serve plane
runs as a ``Forward`` request; ``jax.grad`` through
``ForwardModel.density`` is the backward pass every field-level
inference sample pays, priced by ``pmesh.memory_plan(
workload='forward', pm_steps=...)``.
"""

import numpy as np
import jax.numpy as jnp

from ..pmesh import ParticleMesh
from .lpt import _k_inv_k2, lpt_init, linear_amplitude, modes_from_white
from .adjoint import make_paint


def dkick(a0, a1):
    """Exact kick prefactor integral int_{a0}^{a1} a^{-1/2} da (EdS)."""
    return 2.0 * (np.sqrt(a1) - np.sqrt(a0))


def ddrift(a0, a1):
    """Exact drift prefactor integral int_{a0}^{a1} a^{-3/2} da (EdS)."""
    return 2.0 * (1.0 / np.sqrt(a0) - 1.0 / np.sqrt(a1))


def power_law(A=1.0, n=-2.5):
    """A pure power-law linear spectrum P(k) = A k^n (box units)."""
    def P(k):
        return A * k ** n
    return P


def normalized_amplitude(pm, n=-2.5, delta_rms=1.0):
    """:func:`~.lpt.linear_amplitude` for a power-law spectrum,
    rescaled so the linear field at a=1 has real-space rms
    ``delta_rms`` on this mesh.

    The variance implied by an amplitude field is the hermitian-
    weighted sum of amp^2 over the compressed modes (forward-normalized
    convention: Var[delta(x)] = sum_k P(k)/V), computed exactly here so
    tests and serve get a box- and mesh-independent normalization.
    """
    amp = linear_amplitude(pm, power_law(1.0, n))
    w = jnp.full(pm.shape_complex, 2.0, amp.dtype)
    w = w.at[..., 0].set(1.0)
    if int(pm.Nmesh[2]) % 2 == 0:
        w = w.at[..., -1].set(1.0)
    var = jnp.sum(w * amp * amp)
    return amp * (delta_rms / jnp.sqrt(var))


class ForwardModel:
    """LPT ICs + KDK PM evolution + paint, as one differentiable map.

    Parameters
    ----------
    nmesh : force/analysis mesh cells per side
    npart : total particles; must be a cube ng^3 with ng divisible by
        the device count (defaults to nmesh^3, one per force-mesh cell)
    pm_steps : number of KDK steps from ``a_start`` to ``a_end``
    order : 1 (Zel'dovich) or 2 (2LPT) initial conditions
    linear_power : P(k) callable; default is a power-law spectrum
        normalized to ``delta_rms`` via :func:`normalized_amplitude`
    dtype : mesh dtype ('f8' for gradient-check work, 'f4' for serve)

    The model owns two meshes: ``lattice`` (ng^3, where the linear
    modes and the inference parametrization live) and ``pm`` (nmesh^3,
    where forces are solved and the observed density is painted).  All
    public maps (:meth:`evolve`, :meth:`density`) are pure functions of
    the modes — jit/grad/shard_map composable, bit-identically
    replayable.
    """

    def __init__(self, nmesh, npart=None, BoxSize=1000.0, pm_steps=5,
                 a_start=0.1, a_end=1.0, order=2, resampler='cic',
                 linear_power=None, spectral_index=-2.5, delta_rms=1.0,
                 omega_m=1.0, dtype='f8', comm=None):
        if npart is None:
            npart = int(nmesh) ** 3
        ng = int(round(float(npart) ** (1.0 / 3.0)))
        if ng ** 3 != int(npart):
            raise ValueError("npart=%d is not a cube; the particle "
                             "lattice needs ng^3" % npart)
        if int(pm_steps) < 1:
            raise ValueError("pm_steps must be >= 1")
        self.pm = ParticleMesh(nmesh, BoxSize, dtype, comm)
        self.lattice = self.pm if ng == int(self.pm.Nmesh[0]) \
            else ParticleMesh(ng, BoxSize, dtype, self.pm.comm)
        self.npart = int(npart)
        self.pm_steps = int(pm_steps)
        self.a_start = float(a_start)
        self.a_end = float(a_end)
        self.order = int(order)
        self.resampler = resampler
        self.omega_m = float(omega_m)
        self.paint_fn, self.paint_cfg = make_paint(
            self.pm, self.npart, resampler)
        if linear_power is not None:
            self.amp = linear_amplitude(self.lattice, linear_power)
        else:
            self.amp = normalized_amplitude(
                self.lattice, spectral_index, delta_rms)

    # -- parametrizations -------------------------------------------------

    def linear_modes(self, seed):
        """Truth linear modes for ``seed`` (device-count invariant)."""
        return self.lattice.generate_whitenoise(seed) * self.amp

    def white_guess(self):
        """The zero-initialized real whitenoise leaf for inference."""
        return jnp.zeros(self.lattice.shape_real,
                         jnp.dtype(self.lattice.compute_dtype))

    def modes_from_white(self, white):
        """Differentiable real-leaf -> linear-modes map (lpt.py)."""
        return modes_from_white(self.lattice, white, self.amp)

    # -- dynamics ---------------------------------------------------------

    def gravity(self, pos):
        """PM force at ``pos``: paint -> k-space Poisson -> readout x3.
        Returns (npart, 3) box-unit accelerations (the dkick integral
        supplies the remaining a-dependence)."""
        pm = self.pm
        cdt = jnp.dtype(pm.compute_dtype)
        rho = self.paint_fn(pos)
        nbar = self.npart / pm.Ntot
        delta_k = pm.r2c(rho.astype(cdt) / nbar - 1.0)
        kv, inv = _k_inv_k2(pm)
        acc = [pm.readout(
            pm.c2r(1.5 * self.omega_m * 1j * kv[d] * inv * delta_k),
            pos, resampler=self.resampler) for d in range(3)]
        return jnp.stack(acc, axis=-1)

    def kdk_step(self, pos, mom, a0, a1):
        """One kick-drift-kick step from a0 to a1 (geometric midpoint
        for the kick split, matching the exact-integral prefactors)."""
        ah = np.sqrt(a0 * a1)
        mom = mom + self.gravity(pos) * dkick(a0, ah)
        pos = pos + mom * ddrift(a0, a1)
        mom = mom + self.gravity(pos) * dkick(ah, a1)
        return pos, mom

    def evolve(self, modes):
        """Evolve linear modes to (positions, momenta) at ``a_end``:
        LPT ICs at ``a_start`` then ``pm_steps`` KDK steps.  Pure in
        ``modes``; the step schedule is static (unrolled under jit)."""
        pos, mom = lpt_init(self.lattice, modes, a=self.a_start,
                            order=self.order)
        aa = np.linspace(self.a_start, self.a_end, self.pm_steps + 1)
        for a0, a1 in zip(aa[:-1], aa[1:]):
            pos, mom = self.kdk_step(pos, mom, float(a0), float(a1))
        return pos, mom

    def density(self, modes):
        """The observable: evolved particles painted on the force mesh,
        normalized to 1 + delta.  jax.grad of a scalar of this output
        with respect to the modes (or the white leaf upstream) is the
        field-level inference backward pass."""
        pos, _ = self.evolve(modes)
        rho = self.paint_fn(pos)
        return rho.astype(jnp.dtype(self.pm.compute_dtype)) \
            * (self.pm.Ntot / self.npart)
