"""Symplectic kick-drift-kick PM stepper — a pure, differentiable
function of the linear modes.

Gauge and units (Einstein-de-Sitter, Omega_m = 1, H0 = 1, positions in
box units): with canonical momentum p = a^2 dx/dt the equations of
motion separate into

  dx/da = p * a^{-3/2}           (drift)
  dp/da = F(x) * a^{-1/2}        (kick)

where F is the PM force, F_i(k) = 1.5 Omega_m * i k_i / k^2 * delta_k
read out at the particle positions.  The second-order KDK integrator
uses the EXACT time integrals of the prefactors over each interval
(Quinn et al. 1997 convention):

  dkick(a0, a1)  = int a^{-1/2} da = 2 (sqrt(a1) - sqrt(a0))
  ddrift(a0, a1) = int a^{-3/2} da = 2 (1/sqrt(a0) - 1/sqrt(a1))

so the Zel'dovich flow x = q + a psi, p = a^{3/2} psi (lpt.py) is an
exact solution of the discrete operators at linear order up to the
O(da^3) midpoint error — the property the 2LPT-vs-ZA asymptotics test
leans on.

The same equations hold for a general matter + Lambda background with
``E(a) = H(a)/H0``: the prefactor integrals become

  dkick(a0, a1)  = int da / (a^2 E(a))
  ddrift(a0, a1) = int da / (a^3 E(a))

(EdS ``E = a^{-3/2}`` recovers the closed forms above) and the LPT
initial conditions use the tabulated growth factors D1(a)/D2(a) and
rates f1/f2 from the :mod:`..cosmology.background` ODE solver instead
of the EdS ``D1 = a``, ``D2 = -(3/7) a^2``.  :class:`GrowthTable`
packages exactly that — solved once at model build, interpolated on a
host-side table, so the traced program still sees static per-step
prefactors.  ``ForwardModel(omega_m=1)`` (the default) keeps the EdS
closed forms bit-for-bit.

``ForwardModel`` packages lattice + force mesh + tuned grad-safe paint
(adjoint.make_paint) into the modes -> density map the serve plane
runs as a ``Forward`` request; ``jax.grad`` through
``ForwardModel.density`` is the backward pass every field-level
inference sample pays, priced by ``pmesh.memory_plan(
workload='forward', pm_steps=...)``.
"""

import numpy as np
import jax.numpy as jnp

from ..pmesh import ParticleMesh
from .lpt import _k_inv_k2, lpt_init, linear_amplitude, modes_from_white
from .adjoint import make_paint


def dkick(a0, a1):
    """Exact kick prefactor integral int_{a0}^{a1} a^{-1/2} da (EdS)."""
    return 2.0 * (np.sqrt(a1) - np.sqrt(a0))


def ddrift(a0, a1):
    """Exact drift prefactor integral int_{a0}^{a1} a^{-3/2} da (EdS)."""
    return 2.0 * (1.0 / np.sqrt(a0) - 1.0 / np.sqrt(a1))


# Gauss-Legendre nodes for the LCDM prefactor integrals: the
# integrands 1/(a^2 E) and 1/(a^3 E) are smooth on any step interval,
# so 64 points are exact to machine precision
_GL_X, _GL_W = np.polynomial.legendre.leggauss(64)


class GrowthTable:
    """Tabulated LCDM growth for the forward stepper.

    Solves the first- and second-order growth ODEs once
    (:class:`~nbodykit_tpu.cosmology.background.MatterDominated`,
    matter + Lambda + curvature, radiation ignored) and rescales the
    solution to the *early-time gauge* the stepper and LPT use:
    ``D1(a) -> a`` as ``a -> 0`` (so EdS reduces to ``D1 = a``,
    ``D2 = -(3/7) a^2`` identically, and ``D1(a=1) ~= 0.779`` for
    ``Omega0_m = 0.3`` — the growth suppression a Lambda background
    pays relative to EdS).

    All evaluations are host-side floats interpolated in ``log a`` on
    a dense table — the KDK schedule is static under jit, so per-step
    growth factors enter the traced program as constants, exactly like
    the EdS closed forms they generalize.
    """

    def __init__(self, omega_m, omega_k=0.0, na=8192):
        from ..cosmology.background import MatterDominated
        self.omega_m = float(omega_m)
        self.omega_k = float(omega_k)
        P = MatterDominated(self.omega_m, Omega0_k=self.omega_k)
        # the solver normalizes D1(a_normalize=1) = 1; undo it via the
        # early-time limit D1_raw(a) -> a (Lambda is negligible at
        # a = 1e-4 to ~1e-12), restoring the stepper's gauge
        a_ref = 1e-4
        scale = a_ref / float(P.D1(a_ref))
        self._P = P
        self._lna = np.log(np.geomspace(1e-3, 1.5, int(na)))
        a = np.exp(self._lna)
        self._D1 = np.asarray(P.D1(a), dtype='f8') * scale
        self._f1 = np.asarray(P.f1(a), dtype='f8')
        self._D2 = np.asarray(P.D2(a), dtype='f8') * scale ** 2
        self._f2 = np.asarray(P.f2(a), dtype='f8')

    def _interp(self, tab, a):
        out = np.interp(np.log(np.asarray(a, dtype='f8')),
                        self._lna, tab)
        return float(out) if np.ndim(a) == 0 else out

    def D1(self, a):
        """First-order growth factor (early-time gauge D1 -> a)."""
        return self._interp(self._D1, a)

    def f1(self, a):
        """First-order growth rate dlnD1/dlna."""
        return self._interp(self._f1, a)

    def D2(self, a):
        """Second-order growth factor (EdS limit -(3/7) a^2)."""
        return self._interp(self._D2, a)

    def f2(self, a):
        """Second-order growth rate dlnD2/dlna."""
        return self._interp(self._f2, a)

    def E(self, a):
        """Dimensionless Hubble rate H(a)/H0 (closed form)."""
        out = self._P.efunc(a)
        return float(out) if np.ndim(a) == 0 else out

    def _quad(self, f, a0, a1):
        mid, half = 0.5 * (a0 + a1), 0.5 * (a1 - a0)
        a = mid + half * _GL_X
        return float(np.sum(_GL_W * f(a)) * half)

    def dkick(self, a0, a1):
        """Kick prefactor integral int_{a0}^{a1} da / (a^2 E(a))."""
        return self._quad(lambda a: 1.0 / (a * a * self.E(a)), a0, a1)

    def ddrift(self, a0, a1):
        """Drift prefactor integral int_{a0}^{a1} da / (a^3 E(a))."""
        return self._quad(lambda a: 1.0 / (a ** 3 * self.E(a)),
                          a0, a1)


def power_law(A=1.0, n=-2.5):
    """A pure power-law linear spectrum P(k) = A k^n (box units)."""
    def P(k):
        return A * k ** n
    return P


def normalized_amplitude(pm, n=-2.5, delta_rms=1.0):
    """:func:`~.lpt.linear_amplitude` for a power-law spectrum,
    rescaled so the linear field at a=1 has real-space rms
    ``delta_rms`` on this mesh.

    The variance implied by an amplitude field is the hermitian-
    weighted sum of amp^2 over the compressed modes (forward-normalized
    convention: Var[delta(x)] = sum_k P(k)/V), computed exactly here so
    tests and serve get a box- and mesh-independent normalization.
    """
    amp = linear_amplitude(pm, power_law(1.0, n))
    w = jnp.full(pm.shape_complex, 2.0, amp.dtype)
    w = w.at[..., 0].set(1.0)
    if int(pm.Nmesh[2]) % 2 == 0:
        w = w.at[..., -1].set(1.0)
    var = jnp.sum(w * amp * amp)
    return amp * (delta_rms / jnp.sqrt(var))


class ForwardModel:
    """LPT ICs + KDK PM evolution + paint, as one differentiable map.

    Parameters
    ----------
    nmesh : force/analysis mesh cells per side
    npart : total particles; must be a cube ng^3 with ng divisible by
        the device count (defaults to nmesh^3, one per force-mesh cell)
    pm_steps : number of KDK steps from ``a_start`` to ``a_end``
    order : 1 (Zel'dovich) or 2 (2LPT) initial conditions
    linear_power : P(k) callable; default is a power-law spectrum
        normalized to ``delta_rms`` via :func:`normalized_amplitude`
    dtype : mesh dtype ('f8' for gradient-check work, 'f4' for serve)

    The model owns two meshes: ``lattice`` (ng^3, where the linear
    modes and the inference parametrization live) and ``pm`` (nmesh^3,
    where forces are solved and the observed density is painted).  All
    public maps (:meth:`evolve`, :meth:`density`) are pure functions of
    the modes — jit/grad/shard_map composable, bit-identically
    replayable.
    """

    def __init__(self, nmesh, npart=None, BoxSize=1000.0, pm_steps=5,
                 a_start=0.1, a_end=1.0, order=2, resampler='cic',
                 linear_power=None, spectral_index=-2.5, delta_rms=1.0,
                 omega_m=1.0, dtype='f8', comm=None):
        if npart is None:
            npart = int(nmesh) ** 3
        ng = int(round(float(npart) ** (1.0 / 3.0)))
        if ng ** 3 != int(npart):
            raise ValueError("npart=%d is not a cube; the particle "
                             "lattice needs ng^3" % npart)
        if int(pm_steps) < 1:
            raise ValueError("pm_steps must be >= 1")
        self.pm = ParticleMesh(nmesh, BoxSize, dtype, comm)
        self.lattice = self.pm if ng == int(self.pm.Nmesh[0]) \
            else ParticleMesh(ng, BoxSize, dtype, self.pm.comm)
        self.npart = int(npart)
        self.pm_steps = int(pm_steps)
        self.a_start = float(a_start)
        self.a_end = float(a_end)
        self.order = int(order)
        self.resampler = resampler
        self.omega_m = float(omega_m)
        # omega_m != 1 switches the stepper to the tabulated LCDM
        # growth gauge; the default EdS path keeps the closed-form
        # prefactors bit-for-bit
        self.growth = None if self.omega_m == 1.0 \
            else GrowthTable(self.omega_m)
        self.paint_fn, self.paint_cfg = make_paint(
            self.pm, self.npart, resampler)
        if linear_power is not None:
            self.amp = linear_amplitude(self.lattice, linear_power)
        else:
            self.amp = normalized_amplitude(
                self.lattice, spectral_index, delta_rms)

    # -- parametrizations -------------------------------------------------

    def linear_modes(self, seed):
        """Truth linear modes for ``seed`` (device-count invariant)."""
        return self.lattice.generate_whitenoise(seed) * self.amp

    def white_guess(self):
        """The zero-initialized real whitenoise leaf for inference."""
        return jnp.zeros(self.lattice.shape_real,
                         jnp.dtype(self.lattice.compute_dtype))

    def modes_from_white(self, white):
        """Differentiable real-leaf -> linear-modes map (lpt.py)."""
        return modes_from_white(self.lattice, white, self.amp)

    # -- dynamics ---------------------------------------------------------

    def gravity(self, pos):
        """PM force at ``pos``: paint -> k-space Poisson -> readout x3.
        Returns (npart, 3) box-unit accelerations (the dkick integral
        supplies the remaining a-dependence)."""
        pm = self.pm
        cdt = jnp.dtype(pm.compute_dtype)
        rho = self.paint_fn(pos)
        nbar = self.npart / pm.Ntot
        delta_k = pm.r2c(rho.astype(cdt) / nbar - 1.0)
        kv, inv = _k_inv_k2(pm)
        acc = [pm.readout(
            pm.c2r(1.5 * self.omega_m * 1j * kv[d] * inv * delta_k),
            pos, resampler=self.resampler) for d in range(3)]
        return jnp.stack(acc, axis=-1)

    def _dkick(self, a0, a1):
        return dkick(a0, a1) if self.growth is None \
            else self.growth.dkick(a0, a1)

    def _ddrift(self, a0, a1):
        return ddrift(a0, a1) if self.growth is None \
            else self.growth.ddrift(a0, a1)

    def kdk_step(self, pos, mom, a0, a1):
        """One kick-drift-kick step from a0 to a1 (geometric midpoint
        for the kick split, matching the exact-integral prefactors)."""
        ah = np.sqrt(a0 * a1)
        mom = mom + self.gravity(pos) * self._dkick(a0, ah)
        pos = pos + mom * self._ddrift(a0, a1)
        mom = mom + self.gravity(pos) * self._dkick(ah, a1)
        return pos, mom

    def evolve(self, modes):
        """Evolve linear modes to (positions, momenta) at ``a_end``:
        LPT ICs at ``a_start`` then ``pm_steps`` KDK steps.  Pure in
        ``modes``; the step schedule is static (unrolled under jit)."""
        pos, mom = lpt_init(self.lattice, modes, a=self.a_start,
                            order=self.order, growth=self.growth)
        aa = np.linspace(self.a_start, self.a_end, self.pm_steps + 1)
        for a0, a1 in zip(aa[:-1], aa[1:]):
            pos, mom = self.kdk_step(pos, mom, float(a0), float(a1))
        return pos, mom

    def density(self, modes):
        """The observable: evolved particles painted on the force mesh,
        normalized to 1 + delta.  jax.grad of a scalar of this output
        with respect to the modes (or the white leaf upstream) is the
        field-level inference backward pass."""
        pos, _ = self.evolve(modes)
        rho = self.paint_fn(pos)
        return rho.astype(jnp.dtype(self.pm.compute_dtype)) \
            * (self.pm.Ntot / self.npart)
