"""Atomic, content-hashed checkpoint/restore of pipeline state.

Round 5 lost the north-star TPU record because nothing of a run
survived a mid-run fault: the tunnel died mid-timing and the partial
measurement vaporized with the process.  This module is the durable
half of the resilience story (the reference nbodykit inherits
restartability from MPI batch schedulers, SURVEY §L0 — here it has to
be built in): small host-side pipeline state — staged jit'd programs'
host inputs, partial bench reps, partial lowmem-FFT passes, FFTPower
binned accumulators — is written to disk after every unit of progress
so a relaunch resumes instead of restarting.

Discipline (same as :mod:`..diagnostics.report`):

- **atomic**: every file is written to a tmp sibling and committed
  with one ``os.replace`` — a SIGKILL mid-save leaves the *previous*
  checkpoint intact, never a torn one.  Array payloads are committed
  before the metadata file, so the metadata rename is the single
  commit point.
- **content-hashed**: the metadata records a sha256 over the
  canonical JSON state and over each array's raw bytes; :meth:`load`
  re-verifies everything and returns ``None`` (plus a
  ``resilience.checkpoint.corrupt`` counter bump) on any mismatch —
  a half-written or bit-rotted checkpoint is detected, not replayed.

Checkpoints are named by a caller-chosen key; the bench keys on the
config metric (``bench.fftpower_wallclock_...``), so concurrent
workers (the TPU + forced-CPU pair) never collide.  Fault-injection
points (:mod:`.faults`) fire around the commit so the atomicity claim
is testable: ``ckpt.write.<key>`` before the metadata rename,
``ckpt.<key>`` after it.
"""

import hashlib
import json
import os
import time

from ..diagnostics import counter, span

_META_SUFFIX = '.ckpt.json'


def _safe(name):
    """Filesystem-safe checkpoint/array name (keys carry metric names
    with ``+`` etc.)."""
    return ''.join(c if c.isalnum() or c in '._-' else '_'
                   for c in str(name))


def _canonical(obj):
    """Canonical JSON text of a state payload: the hashed form and the
    stored form are byte-identical because both pass through one
    serialization with sorted keys."""
    return json.dumps(obj, sort_keys=True, separators=(',', ':'),
                      default=str)


def _sha(text):
    if isinstance(text, str):
        text = text.encode('utf-8')
    return hashlib.sha256(text).hexdigest()


def _atomic_bytes(path, data):
    tmp = '%s.tmp.%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        f.write(data)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:         # pragma: no cover - exotic fs
            pass
    os.replace(tmp, path)


class CheckpointStore(object):
    """Checkpoints under one directory, one ``<key>.ckpt.json`` (plus
    optional ``<key>.<name>.npy`` array payloads) per key."""

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def _meta_path(self, key):
        return os.path.join(self.root, _safe(key) + _META_SUFFIX)

    def _array_path(self, key, name):
        return os.path.join(self.root,
                            '%s.%s.npy' % (_safe(key), _safe(name)))

    def keys(self):
        """Keys with a committed metadata file, sorted."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(f[:-len(_META_SUFFIX)] for f in names
                      if f.endswith(_META_SUFFIX))

    # -- save / load ------------------------------------------------------

    def save(self, key, state, arrays=None):
        """Commit ``state`` (a JSON-serializable dict) plus optional
        named numpy ``arrays`` under ``key``.  Returns the metadata
        path.  The metadata rename is the commit point; a death at any
        earlier moment leaves the previous checkpoint loadable."""
        from .faults import fault_point
        with span('ckpt.save', key=str(key)):
            # tuples etc. must hash the way they re-load: round-trip
            # the state through JSON before hashing
            state = json.loads(_canonical(state))
            arr_meta = {}
            if arrays:
                import numpy as np
                for name, arr in sorted(arrays.items()):
                    data = np.ascontiguousarray(np.asarray(arr))
                    apath = self._array_path(key, name)
                    tmp = '%s.tmp.%d' % (apath, os.getpid())
                    with open(tmp, 'wb') as f:
                        np.save(f, data)
                        f.flush()
                        try:
                            os.fsync(f.fileno())
                        except OSError:  # pragma: no cover
                            pass
                    os.replace(tmp, apath)
                    arr_meta[str(name)] = {
                        'file': os.path.basename(apath),
                        'sha256': _sha(data.tobytes()),
                        'dtype': str(data.dtype),
                        'shape': list(data.shape),
                    }
            body = _canonical({'state': state, 'arrays': arr_meta})
            meta = {
                'v': 1, 'key': str(key),
                'saved_at': round(time.time(), 6),
                'sha256': _sha(body),
                'state': state, 'arrays': arr_meta,
            }
            path = self._meta_path(key)
            # the pre-commit fault point: a kill here proves the
            # previous checkpoint survives a death mid-save
            fault_point('ckpt.write.%s' % key)
            _atomic_bytes(path, json.dumps(meta, indent=1,
                                           default=str).encode('utf-8'))
            counter('resilience.checkpoint.saves').add(1)
            fault_point('ckpt.%s' % key)
            return path

    def load(self, key):
        """``(state, arrays)`` for ``key``, or ``None`` when absent or
        failing any content-hash check (corrupt checkpoints are
        counted, never trusted)."""
        path = self._meta_path(key)
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            if os.path.exists(path):
                counter('resilience.checkpoint.corrupt').add(1)
            return None
        body = _canonical({'state': meta.get('state'),
                           'arrays': meta.get('arrays', {})})
        if _sha(body) != meta.get('sha256'):
            counter('resilience.checkpoint.corrupt').add(1)
            return None
        arrays = {}
        for name, am in (meta.get('arrays') or {}).items():
            import numpy as np
            apath = os.path.join(self.root, am.get('file', ''))
            try:
                data = np.load(apath)
            except (OSError, ValueError):
                counter('resilience.checkpoint.corrupt').add(1)
                return None
            if _sha(np.ascontiguousarray(data).tobytes()) \
                    != am.get('sha256'):
                counter('resilience.checkpoint.corrupt').add(1)
                return None
            arrays[name] = data
        counter('resilience.checkpoint.restores').add(1)
        return meta.get('state'), arrays

    def delete(self, key):
        """Remove ``key``'s metadata + array payloads (metadata first,
        so a death mid-delete leaves only harmless orphan arrays)."""
        meta = self._meta_path(key)
        names = []
        try:
            with open(meta) as f:
                names = [am.get('file') for am in
                         (json.load(f).get('arrays') or {}).values()]
        except (OSError, ValueError):
            pass
        for path in [meta] + [os.path.join(self.root, n)
                              for n in names if n]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- retention --------------------------------------------------------

    def orphan_tmp(self, max_age_s=0.0, now=None):
        """Paths of ``*.tmp.<pid>`` siblings at least ``max_age_s`` old
        — debris a kill mid-commit leaves behind (the rename never
        happened, so they are invisible to load; they only waste
        disk)."""
        now = time.time() if now is None else now
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for f in names:
            if '.tmp.' not in f:
                continue
            path = os.path.join(self.root, f)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age >= max_age_s:
                out.append(path)
        return sorted(out)

    def gc_tmp(self, max_age_s=3600.0, now=None):
        """Remove stale tmp orphans; returns the count removed.  The
        default age spares a concurrent writer's in-flight tmp."""
        n = 0
        for path in self.orphan_tmp(max_age_s=max_age_s, now=now):
            try:
                os.remove(path)
                n += 1
            except OSError:
                pass
        return n

    # -- freshness --------------------------------------------------------

    def saved_at(self, key):
        """Epoch seconds of ``key``'s commit, or None."""
        try:
            with open(self._meta_path(key)) as f:
                return float(json.load(f).get('saved_at'))
        except (OSError, ValueError, TypeError):
            return None

    def age_s(self, key, now=None):
        """Seconds since ``key`` was committed, or None."""
        ts = self.saved_at(key)
        if ts is None:
            return None
        return (time.time() if now is None else now) - ts

    def oldest_age_s(self, now=None):
        """Age of the oldest committed checkpoint, or None when the
        store is empty — the doctor's last-checkpoint-age signal."""
        ages = [self.age_s(k, now=now) for k in self.keys()]
        ages = [a for a in ages if a is not None]
        return max(ages) if ages else None
