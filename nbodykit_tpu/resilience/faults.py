"""Deterministic fault injection: make every recovery path testable.

The faults this subsystem exists for — ``UNAVAILABLE`` tunnel deaths,
``RESOURCE_EXHAUSTED`` OOMs, SIGKILLed workers — only occur on the
real TPU fleet, which tier-1 never touches.  This harness injects
them *deterministically* on the CPU mesh so the retry / degrade /
resume machinery (:mod:`.supervise`, :mod:`.checkpoint`) is exercised
by ordinary tests instead of waiting for the hardware to misbehave.

Spec format (``set_options(faults=...)`` or ``$NBKIT_FAULTS``):

    [rankR@]point[@N]:action[,...]

``point`` names a fault point (a host-side call site instrumented
with :func:`fault_point` — e.g. ``bench.rep``, ``ckpt.write.<key>``,
``ckpt.manifest``, ``<supervisor>.attempt``), ``N`` is the 1-based
call count at which the rule fires (default 1), and ``action`` is one
of:

- ``unavailable`` / ``resource_exhausted`` / ``deadline`` /
  ``internal`` — raise a real ``XlaRuntimeError`` (the class jax's
  runtime raises; a plain RuntimeError subclass when jax is absent)
  whose message carries the canonical gRPC status prefix, so error
  classification sees exactly what the fleet produces;
- ``kill`` / ``sigkill`` — ``SIGKILL`` this process on the spot (no
  atexit, no flush): the checkpoint-atomicity and resume paths see a
  true mid-run death;
- ``sigterm`` — deliver a real SIGTERM to this process and *return*:
  with the preemption handler installed (:mod:`.fleet`) execution
  continues to the next safe point exactly as under a preemptible
  scheduler; without one the default disposition terminates.
- ``corrupt[:bits]`` — a DATA action, not an error: at a named
  data-injection point (``a2a.payload``, ``paint.accum``,
  ``serve.result``) the site consults :func:`corrupt_spec` and, when
  the rule fires, applies a deterministic stuck-at-one fault to the
  top ``bits`` (default 1) of one payload word's exponent
  (:func:`integrity.flip_bits_value` — catastrophic by construction,
  so detection never depends on the corrupted element's value).  This is how every silent-data-corruption
  detector (:mod:`.integrity`, docs/INTEGRITY.md) is exercised in CI
  without real hardware faults: the corruption flows through the
  guarded surface and the guard — not the injector — must catch it.

The optional ``rankR@`` prefix scopes a rule to one fleet rank
(``rank1@bench.rep:sigkill`` kills only rank 1), which is how the
chaos matrix kills chosen ranks of a multi-process fleet.  Call
*counting* stays rank-uniform — every process counts every targeted
point — so all ranks agree on the call index a rule names.

Each rule fires exactly once (the call count passes ``N`` once per
process).  Calls to points no rule targets cost one string lookup.
Counting is per-process and deterministic, so a multi-process fleet
given the same spec injects the same fault at the same logical step
everywhere — collective-consistent by construction.
"""

import os
import re
import signal
import threading

from ..diagnostics import counter

_lock = threading.Lock()
_counts = {}
_parsed = None          # (source_spec, rules)

_STATUS_MESSAGES = {
    'unavailable': 'UNAVAILABLE: injected fault at %s (call %d); '
                   'socket closed',
    'resource_exhausted': 'RESOURCE_EXHAUSTED: injected fault at %s '
                          '(call %d); out of memory while allocating',
    'deadline': 'DEADLINE_EXCEEDED: injected fault at %s (call %d)',
    'internal': 'INTERNAL: injected fault at %s (call %d)',
}
ACTIONS = tuple(_STATUS_MESSAGES) + ('kill', 'sigkill', 'sigterm',
                                     'corrupt')

_RANK_RE = re.compile(r'^rank(\d+)$')


class InjectedFault(RuntimeError):
    """Raised for injected faults when jax's XlaRuntimeError is not
    importable (diagnostics-only environments)."""


def error_class():
    """The exception class injected errors are raised as: the real
    ``XlaRuntimeError`` when jax is present (classification and any
    caller except-clauses see the genuine article)."""
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError
    except Exception:
        return InjectedFault


def _spec():
    try:
        from .. import _global_options
    except ImportError:     # pragma: no cover - interpreter teardown
        return None
    try:
        return _global_options['faults']
    except KeyError:
        return None


def parse_spec(spec):
    """``[(point, nth, action), ...]`` for a spec string — rank-scoped
    rules (``rankR@point[@N]:action``) parse to 4-tuples ``(point,
    nth, action, rank)``; raises ValueError on malformed rules (a
    typo'd spec must not silently inject nothing)."""
    rules = []
    for part in str(spec).split(','):
        part = part.strip()
        if not part:
            continue
        name, _, action = part.rpartition(':')
        if not name:
            raise ValueError('fault rule %r: expected point@N:action'
                             % part)
        action = action.strip().lower()
        if name.lower().endswith(':corrupt') and action.isdigit():
            # 'point:corrupt:3' — the bits suffix landed in rpartition's
            # tail; fold it back into a single 'corrupt:N' action
            name = name[:-len(':corrupt')]
            action = 'corrupt:' + action
        base = action.partition(':')[0]
        if base not in ACTIONS or (base != 'corrupt' and base != action):
            raise ValueError('fault rule %r: unknown action %r '
                             '(choose %s)' % (part, action,
                                              '/'.join(ACTIONS)))
        if base == 'corrupt':
            bits = action.partition(':')[2]
            if bits and (not bits.isdigit() or not 1 <= int(bits) <= 30):
                raise ValueError('fault rule %r: corrupt bit count %r '
                                 'must be an integer in [1, 30]'
                                 % (part, bits))
        point, at, nth = name.partition('@')
        rank = None
        m = _RANK_RE.match(point.strip())
        if m is not None and at:
            rank = int(m.group(1))
            point, at, nth = nth.partition('@')
        try:
            n = int(nth) if at else 1
        except ValueError:
            raise ValueError('fault rule %r: call count %r is not an '
                             'integer' % (part, nth))
        point = point.strip()
        rules.append((point, n, action) if rank is None
                     else (point, n, action, rank))
    return rules


def _rules():
    global _parsed
    spec = _spec()
    if not spec:
        return ()
    cached = _parsed
    if cached is not None and cached[0] == spec:
        return cached[1]
    rules = tuple(parse_spec(spec))
    with _lock:
        _parsed = (spec, rules)
    return rules


def reset_faults():
    """Clear per-process call counts + the parsed-spec cache (test
    isolation; the spec itself lives in the options/env)."""
    global _parsed
    with _lock:
        _counts.clear()
        _parsed = None


def fault_counts():
    """Snapshot of per-point call counts (observability for tests)."""
    with _lock:
        return dict(_counts)


def fault_point(name):
    """Declare a named fault point.  Free when no spec is configured
    or no rule targets ``name``; otherwise counts the call and fires
    any rule matching (name, count) — rank-scoped rules only on their
    fleet rank, though every rank counts the call."""
    rules = _rules()
    if not rules:
        return
    mine = [r for r in rules if r[0] == name]
    if not mine:
        return
    with _lock:
        n = _counts[name] = _counts.get(name, 0) + 1
    for rule in mine:
        nth, action = rule[1], rule[2]
        if nth != n or action.startswith('corrupt'):
            # corrupt rules are DATA actions consumed by corrupt_spec
            # at the injection site, never raised from a fault point
            continue
        if len(rule) > 3:
            from .fleet import fleet_rank
            if fleet_rank() != rule[3]:
                continue
        if action in ('kill', 'sigkill'):
            # no flush, no atexit: the genuine mid-run death
            os.kill(os.getpid(), signal.SIGKILL)
        counter('resilience.faults.injected').add(1)
        if action == 'sigterm':
            # the real signal, then return: the preemption handler
            # sees exactly what a preemptible scheduler sends and the
            # run continues to its next safe point
            os.kill(os.getpid(), signal.SIGTERM)
            continue
        raise error_class()(_STATUS_MESSAGES[action] % (name, n))


def corrupt_spec(name):
    """Declare a named DATA-injection point: the number of payload
    bits to flip at this call (0 almost always).

    The query form of :func:`fault_point` for ``corrupt`` rules: the
    site calls this once per logical payload, and when a rule matches
    (name, call count) it returns the rule's bit count — the site then
    flips that many top bits of one payload word itself (the
    corruption must flow through the guarded surface so the DETECTOR
    is what gets tested, not the injector).  Counting shares
    :func:`fault_point`'s per-process table and stays rank-uniform;
    rank-scoped rules return 0 everywhere but their fleet rank (every
    rank still counts the call, so all ranks agree on indices).  Each
    rule fires once.  Free when no rule targets ``name``."""
    rules = _rules()
    if not rules:
        return 0
    mine = [r for r in rules if r[0] == name]
    if not mine:
        return 0
    with _lock:
        n = _counts[name] = _counts.get(name, 0) + 1
    for rule in mine:
        nth, action = rule[1], rule[2]
        if nth != n or not action.startswith('corrupt'):
            continue
        if len(rule) > 3:
            from .fleet import fleet_rank
            if fleet_rank() != rule[3]:
                continue
        counter('resilience.faults.injected').add(1)
        bits = action.partition(':')[2]
        return int(bits) if bits else 1
    return 0
