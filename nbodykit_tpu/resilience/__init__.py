"""nbodykit_tpu.resilience — checkpointed, retrying, fault-injectable
execution for flaky TPU fleets.

Round 5's verdict: after five rounds the north-star config has zero
recorded TPU evidence — not because the code is slow, but because
nothing survives a mid-run fault (the 1024³ record died
``UNAVAILABLE`` mid-timing; the FKP proof and ``--prim`` died
``RESOURCE_EXHAUSTED``).  The reference nbodykit inherits
restartability from MPI batch schedulers (SURVEY §L0); a
production-scale jax_graft system has to build the moral equivalent
in.  Three pieces:

- :mod:`.checkpoint` — :class:`CheckpointStore`: atomic (tmp+rename),
  content-hashed (sha256 over state + array bytes) checkpoint/restore
  of host-side pipeline state.  A SIGKILL mid-save leaves the
  previous checkpoint intact; corruption is detected, never replayed.
- :mod:`.supervise` — :class:`Supervisor`: classifies raised errors
  (``UNAVAILABLE``/device loss vs ``RESOURCE_EXHAUSTED``/OOM vs
  deadline) and applies per-class policy — bounded exponential-backoff
  retries for transients, *graceful degradation* down the existing
  FFT/paint memory ladder (:func:`default_ladder`) for OOM, immediate
  re-raise for real bugs.
- :mod:`.faults` — deterministic fault injection
  (``set_options(faults='point@N:action')`` / ``$NBKIT_FAULTS``):
  raise a real ``XlaRuntimeError`` of a chosen status at the Nth call
  to a named :func:`fault_point`, or SIGKILL at a named checkpoint —
  every recovery path is testable on the CPU mesh in tier-1.  Rank-
  scoped rules (``rank1@bench.rep:sigkill``) and signal actions form
  the fleet chaos matrix.
- :mod:`.fleet` — fleet survivability on top of the three:
  coordinated manifest-sealed multi-rank checkpoints
  (:class:`FleetCheckpointStore`), SIGTERM preemption handling inside
  a grace budget (:func:`install_preemption_handler` /
  :class:`Preempted`), a live heartbeat failure detector
  (:class:`FleetMonitor`), and shrink-to-survive shard repartitioning
  for relaunches with fewer processes.
- :mod:`.integrity` — the silent-data-corruption defense the four
  above cannot provide (they handle *loud* failures): tier-0
  on-device invariants (``set_options(integrity='cheap')`` — paint
  mass conservation, FFT Parseval, a2a fold checksums, NaN/Inf
  tripwires), classified :class:`IntegrityError` attribution, and the
  tier-2 :class:`SuspectTracker` quarantine in :mod:`.fleet`.  The
  fault grammar's ``corrupt[:bits]`` action makes every detector
  testable in CI.  Full guide: docs/INTEGRITY.md.

Wired in: ``bench.py``'s measurement reps checkpoint after every rep
and resume on relaunch (records carry ``resumed: true``); the
multi-host test worker runs its pipeline under a Supervisor.  Every
retry / degradation / resume lands as a ``resilience.*`` span +
counter (:mod:`..diagnostics`) and in the doctor's verdict block.
Full guide: docs/RESILIENCE.md.
"""

from .checkpoint import CheckpointStore  # noqa: F401
from .faults import (ACTIONS, InjectedFault, corrupt_spec,  # noqa: F401
                     error_class, fault_counts, fault_point,
                     parse_spec, reset_faults)
from .fleet import (DEAD_RANK_EXIT, PREEMPTED_EXIT,  # noqa: F401
                    FleetCheckpointStore, FleetMonitor, FleetSealError,
                    Preempted, SuspectTracker, check_preemption,
                    clear_preemption, fleet_barrier, fleet_rank,
                    fleet_size, install_preemption_handler,
                    preemption_requested, reassemble, repartition,
                    reset_suspects, scan_liveness, suspect_tracker,
                    uninstall_preemption_handler)
from .integrity import (IntegrityError, checks_enabled,  # noqa: F401
                        integrity_mode, precision_margins,
                        reset_integrity, shadow_margin,
                        violation_counts)
from .supervise import (DEADLINE, FATAL, INTEGRITY, OOM,  # noqa: F401
                        TRANSIENT, DegradationLadder, RetryPolicy,
                        Supervisor, classify_error, default_ladder,
                        scoped_ladder)
