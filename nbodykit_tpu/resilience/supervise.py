"""The Supervisor: classify faults, retry transients, degrade on OOM.

Round 5's three losses map to three error classes with three correct
responses, and nothing in the stack applied any of them:

- ``UNAVAILABLE`` (tunnel/device loss) is *transient*: the correct
  response is a bounded retry with exponential backoff + jitter;
- ``RESOURCE_EXHAUSTED`` (HBM OOM) is *deterministic for a given
  program shape* — retrying the identical program is futile, but the
  codebase already exposes a memory ladder nothing selects adaptively:
  the FFT dispatch steps in-jit → chunked → eager lowmem as
  ``fft_chunk_bytes`` shrinks (parallel/dfft.py), and the paint
  bounds its live set via ``paint_chunk_size`` (ops/paint.py,
  pmesh.py).  The correct response is to *step down that ladder* and
  re-run;
- ``DEADLINE_EXCEEDED`` is retried like a transient (the axon tunnel
  surfaces wedge-then-recover as deadlines);
- anything else is *fatal* and re-raised untouched — a real bug must
  never be retried into flakiness.

Every retry / degradation is emitted as a ``resilience.*`` span and
counter (:mod:`..diagnostics`), so the merged fleet trace shows what
the supervisor did and the doctor surfaces the totals.
"""

import random
import time

from ..diagnostics import counter, current_tracer, span
from .faults import fault_point

# error classes
TRANSIENT = 'transient'
OOM = 'oom'
DEADLINE = 'deadline'
INTEGRITY = 'integrity'
FATAL = 'fatal'

# gRPC-status / runtime substrings, checked in order: OOM first, since
# an allocator message can mention the device that was lost
_OOM_MARKERS = ('RESOURCE_EXHAUSTED', 'RESOURCE EXHAUSTED',
                'Out of memory', 'out of memory', 'OOM')
_DEADLINE_MARKERS = ('DEADLINE_EXCEEDED', 'Deadline Exceeded',
                     'deadline exceeded')
_TRANSIENT_MARKERS = ('UNAVAILABLE', 'DATA_LOSS', 'socket closed',
                      'connection reset', 'failed to connect',
                      'device lost')


def classify_error(exc):
    """One of TRANSIENT / OOM / DEADLINE / INTEGRITY / FATAL for a
    raised error.

    Classification is by message substring — the runtime's gRPC status
    prefixes (``UNAVAILABLE: ...``) survive every re-wrap the stack
    applies, while the exception *types* do not (XlaRuntimeError covers
    all of them).  ``MemoryError`` is OOM regardless of text;
    integrity violations carry the ``DATA_CORRUPTION:`` prefix
    (resilience/integrity.py) through the same discipline."""
    if isinstance(exc, MemoryError):
        return OOM
    text = str(exc)
    if 'DATA_CORRUPTION' in text:
        return INTEGRITY
    for marker in _OOM_MARKERS:
        if marker in text:
            return OOM
    for marker in _DEADLINE_MARKERS:
        if marker in text:
            return DEADLINE
    for marker in _TRANSIENT_MARKERS:
        if marker in text:
            return TRANSIENT
    return FATAL


class RetryPolicy(object):
    """Bounded exponential backoff with deterministic jitter.

    ``backoff_s(attempt)`` is ``base * factor**attempt`` capped at
    ``max_s``, plus up to ``jitter`` of itself from a policy-local RNG
    (seeded, so tests and multi-process fleets are reproducible)."""

    def __init__(self, max_retries=3, base_s=0.5, factor=2.0,
                 max_s=30.0, jitter=0.5, seed=0):
        self.max_retries = int(max_retries)
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def backoff_s(self, attempt):
        d = min(self.base_s * self.factor ** attempt, self.max_s)
        return d * (1.0 + self.jitter * self._rng.random())


class DegradationLadder(object):
    """Ordered rungs of graceful degradation.  Each rung is a
    ``(label, apply)`` pair; ``apply()`` performs the step (typically
    a ``set_options`` change) and returns a detail dict for the
    record.  :meth:`step` applies the next rung, or returns None when
    exhausted."""

    def __init__(self, rungs):
        self.rungs = list(rungs)
        self.applied = []

    def step(self):
        i = len(self.applied)
        if i >= len(self.rungs):
            return None
        label, apply = self.rungs[i]
        detail = apply() or {}
        self.applied.append((label, detail))
        return label, detail


def _halve_option(option, floor):
    """A ladder rung halving a global option (not below ``floor``).
    An ``'auto'`` option halves from its tune-cache-resolved effective
    value — and the rung pins it to the concrete result, so every
    later attempt in this degraded run stays below the OOM point
    instead of re-resolving back up."""
    def apply():
        import nbodykit_tpu
        from .. import _global_options
        from ..tune.resolve import effective_int_option
        cur = _global_options[option]
        if isinstance(cur, bool) or not isinstance(cur, (int, float)):
            cur = effective_int_option(option)
        cur = int(cur)
        new = max(int(floor), cur // 2)
        nbodykit_tpu.set_options(**{option: new})
        return {option: new, 'was': cur}
    return apply


def default_ladder():
    """The FFT/paint memory ladder the codebase already exposes,
    as supervisor rungs.

    Halving ``fft_chunk_bytes`` pulls single-device FFTs out of the
    one-shot in-jit program into the chunked / eager-lowmem drivers
    (parallel/dfft.py dispatches on output bytes vs this target, for
    r2c, c2r and the c2c path convpower's odd multipoles use) with
    ever-smaller slabs; halving ``paint_chunk_size`` halves the paint
    batch the host-streaming path keeps live (pmesh.py).  Rungs
    alternate so one OOM doesn't collapse both knobs at once."""
    return DegradationLadder([
        ('fft_chunk_bytes/2', _halve_option('fft_chunk_bytes', 1 << 24)),
        ('paint_chunk_size/2',
         _halve_option('paint_chunk_size', 1 << 18)),
        ('fft_chunk_bytes/2', _halve_option('fft_chunk_bytes', 1 << 24)),
        ('paint_chunk_size/2',
         _halve_option('paint_chunk_size', 1 << 18)),
    ])


def _halve_scoped(opts, option, floor):
    """A ladder rung halving an option INSIDE a caller-owned mapping
    (not below ``floor``).  The first step seeds from the mapping's
    current value when present, else from the tune-cache-resolved
    effective value — same pinning discipline as :func:`_halve_option`
    but with zero writes to the process-wide options."""
    def apply():
        from ..tune.resolve import effective_int_option
        cur = opts.get(option)
        if cur is None or isinstance(cur, bool) \
                or not isinstance(cur, (int, float)):
            cur = effective_int_option(option)
        cur = int(cur)
        new = max(int(floor), cur // 2)
        opts[option] = new
        return {option: new, 'was': cur}
    return apply


def scoped_ladder(opts):
    """:func:`default_ladder` writing into ``opts`` (a caller-owned
    dict) instead of the process-wide options.

    This is the multi-tenant form: one request's OOM response must
    reconfigure THAT request, not every other tenant sharing the
    process.  The serving layer steps this ladder at admission
    (:mod:`nbodykit_tpu.serve.admission`) and at runtime, then applies
    the accumulated ``opts`` with :func:`nbodykit_tpu.option_scope`
    around just that request's execution."""
    return DegradationLadder([
        ('fft_chunk_bytes/2',
         _halve_scoped(opts, 'fft_chunk_bytes', 1 << 24)),
        ('paint_chunk_size/2',
         _halve_scoped(opts, 'paint_chunk_size', 1 << 18)),
        ('fft_chunk_bytes/2',
         _halve_scoped(opts, 'fft_chunk_bytes', 1 << 24)),
        ('paint_chunk_size/2',
         _halve_scoped(opts, 'paint_chunk_size', 1 << 18)),
    ])


class Supervisor(object):
    """Run callables under per-error-class policy.

    Parameters
    ----------
    name : str — names the supervisor's fault point
        (``<name>.attempt``, fired before every attempt) and labels
        its spans/events.
    policy : RetryPolicy — transient/deadline retry budget + backoff.
    ladder : DegradationLadder or None — OOM response; None re-raises
        the first OOM (no silent degradation unless asked for).
    checkpoint : CheckpointStore or None — enables :meth:`save` /
        :meth:`resume`.
    sleep : injectable for tests (defaults to ``time.sleep``).
    """

    def __init__(self, name, policy=None, ladder=None, checkpoint=None,
                 sleep=time.sleep):
        self.name = str(name)
        self.policy = policy if policy is not None else RetryPolicy()
        self.ladder = ladder
        self.checkpoint = checkpoint
        self.sleep = sleep
        self.events = []

    # -- event plumbing ---------------------------------------------------

    # counter name (plural) -> trace event span name
    _EVENT_SPANS = {'retries': 'resilience.retry',
                    'degradations': 'resilience.degrade',
                    'resumes': 'resilience.resume',
                    'integrity_retries': 'resilience.integrity_retry'}

    def _event(self, kind, **attrs):
        attrs['task'] = self.name
        self.events.append(dict(attrs, kind=kind))
        counter('resilience.%s' % kind).add(1)
        tr = current_tracer()
        if tr is not None:
            tr.event(self._EVENT_SPANS[kind], attrs)

    # -- checkpoint conveniences ------------------------------------------

    def save(self, key, state, arrays=None):
        """Checkpoint progress (no-op without a store)."""
        if self.checkpoint is None:
            return None
        return self.checkpoint.save(key, state, arrays=arrays)

    def resume(self, key, validate=None):
        """``(state, arrays)`` from the last checkpoint, or None.  A
        hit is a *resume*: counted and visible in the trace.  An
        optional ``validate(state) -> bool`` rejects a checkpoint
        written for a different unit of work (wrong rep target, stale
        config) WITHOUT emitting a resume event."""
        if self.checkpoint is None:
            return None
        got = self.checkpoint.load(key)
        if got is None:
            return None
        if validate is not None and not validate(got[0]):
            return None
        self._event('resumes', key=str(key))
        return got

    def done(self, key):
        """Drop ``key``'s checkpoint (the unit of work completed)."""
        if self.checkpoint is not None:
            self.checkpoint.delete(key)

    # -- the run loop -----------------------------------------------------

    def run(self, fn, *args, **kwargs):
        """Call ``fn(*args, **kwargs)`` under the per-class policy:
        bounded backoff retries for TRANSIENT/DEADLINE, ladder
        degradation for OOM, exactly-one retry for INTEGRITY (a
        transient bit flip heals on re-execution; a sick chip fails
        again, and every strike lands in the fleet's SuspectTracker
        either way), immediate re-raise for FATAL (and for exhausted
        budgets/ladders)."""
        retries = 0
        integrity_retried = False
        while True:
            try:
                # inside the try: injected faults at the attempt point
                # go through the same classification as real ones
                fault_point('%s.attempt' % self.name)
                return fn(*args, **kwargs)
            except Exception as e:
                kind = classify_error(e)
                if kind == INTEGRITY:
                    # attribution first: the strike is recorded whether
                    # or not the retry heals, so a chip that corrupts
                    # once per K tasks still accumulates toward
                    # quarantine (resilience/fleet.py)
                    from .fleet import suspect_tracker
                    rank = getattr(e, 'rank', None)
                    site = getattr(e, 'site', 'unknown')
                    suspect_tracker().strike(rank, site=site,
                                             task=self.name)
                    if integrity_retried:
                        raise
                    integrity_retried = True
                    self._event('integrity_retries', site=site,
                                rank=rank, error=str(e)[:200])
                    continue
                if kind == OOM:
                    rung = self.ladder.step() if self.ladder is not None \
                        else None
                    if rung is None:
                        raise
                    label, detail = rung
                    self._event('degradations', rung=label,
                                detail=detail, error=str(e)[:200])
                    continue
                if kind in (TRANSIENT, DEADLINE):
                    if retries >= self.policy.max_retries:
                        raise
                    from .fleet import preemption_requested
                    if preemption_requested():
                        # a SIGTERM'd process must spend its grace
                        # budget sealing a checkpoint, not sleeping in
                        # backoff — surface the error and let the safe
                        # point raise Preempted
                        raise
                    delay = self.policy.backoff_s(retries)
                    retries += 1
                    self._event('retries', attempt=retries,
                                delay_s=round(delay, 3), cls=kind,
                                error=str(e)[:200])
                    # the wait itself is a span: visible dead time in
                    # the merged timeline, attributed to resilience
                    with span('resilience.backoff', task=self.name,
                              attempt=retries, delay_s=round(delay, 3)):
                        self.sleep(delay)
                    continue
                raise
