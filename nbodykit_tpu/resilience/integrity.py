"""Silent-data-corruption defense: tripwires, budgets, attribution.

Every other piece of :mod:`nbodykit_tpu.resilience` handles *loud*
failures — crashes, OOMs, preemptions, dead ranks.  Nothing before
this module could detect a *wrong answer*: a flipped bit in an
``all_to_all`` payload, a corrupted HBM line under a paint scatter, a
degraded chip that silently skews P(k) for every tenant of the serve
layer.  The defense is tiered (docs/INTEGRITY.md):

**Tier 0 — cheap on-device invariants** (``set_options(
integrity='cheap')``), priced as near-free reductions:

- *mass conservation*: the deposit windows (CIC/TSC/PCS) distribute
  each particle's mass over cells with weights summing to one, so
  ``sum(field) == sum(mass)`` up to a compute-dtype rounding budget —
  checked after every eager paint, for every registered kernel
  including the bf16 streams path (whose storage rounding widens the
  budget by the storage dtype's eps);
- *Parseval*: for the unnormalized DFT,
  ``sum(w*|X|^2) == Ntot * sum(x^2)`` with Hermitian weights ``w`` on
  the compressed z axis — checked bracketing every eager
  ``dist_rfftn``/``dist_irfftn`` (slab, pencil and single-device
  alike, since the bracket sits at the public entry);
- *NaN/Inf tripwires*: both invariants above are reductions, so a
  non-finite mesh-sized intermediate poisons the reduced scalar and
  trips the same check at zero extra cost;
- *a2a fold checksums*: an ``all_to_all`` permutes a global payload
  without changing its elements, so the globally-psummed fold
  ``sum(|Re| + |Im|)`` is invariant across the wire.  Each of the 8
  ``_a2a`` sites (parallel/dfft.py) compares the pre-wire fold
  against the post-wire fold inside the shard_map — two extra psums,
  identical on every rank (NBK103 by construction) — and the eager
  driver raises on a mismatch.  The compressed wire formats are
  checked *pre-quantization vs dequantized* against a budget the
  format itself implies (bf16: mantissa width; int16: the per-shard
  scale, psummed alongside).

**Tier 1 — shadow verification** lives in :mod:`nbodykit_tpu.serve`:
a completed request re-executes on a *different* sub-mesh worker and
the results are compared — bit-identical for uncompressed postures,
margin-gated (PRECISION.json) for compressed ones.

**Tier 2 — attribution and quarantine**: every violation raises a
classified :class:`IntegrityError` carrying (site, rank, delta).  The
Supervisor (:mod:`.supervise`) retries it exactly once — a transient
bit flip heals, a sick chip doesn't — and each strike lands in the
:class:`~nbodykit_tpu.resilience.fleet.SuspectTracker`, which
quarantines a rank after K strikes into the sealed fleet manifest.

``integrity='off'`` (the default) adds ZERO ops — every guard
resolves the mode at closure-build/dispatch time and compiles or
executes nothing when off, so results are bit-identical to a build
without this module.
"""

import math
import os
import threading

from ..diagnostics import counter, current_tracer

_lock = threading.Lock()
_violations = []


class IntegrityError(RuntimeError):
    """A detected integrity violation, classified for attribution.

    Parameters
    ----------
    site : str — the guard that fired (``paint.mass``, ``fft.parseval``,
        ``a2a.checksum``, ``serve.shadow``, ``*.nonfinite``)
    rank : int or None — the fleet rank the violation was observed on
    delta : float or None — the invariant's residual (absolute)
    detail : str or None — extra context for the record
    """

    def __init__(self, site, rank=None, delta=None, detail=None):
        self.site = str(site)
        self.rank = rank
        self.delta = delta
        msg = 'DATA_CORRUPTION: integrity violation at %s' % self.site
        if rank is not None:
            msg += ' (rank %d)' % int(rank)
        if delta is not None:
            msg += ' delta=%.6g' % float(delta)
        if detail:
            msg += ': %s' % detail
        super(IntegrityError, self).__init__(msg)


def integrity_mode():
    """The resolved ``integrity`` option: 'off' or 'cheap'."""
    try:
        from .. import _global_options
        v = _global_options['integrity']
    except Exception:        # pragma: no cover - interpreter teardown
        return 'off'
    if v in (None, False, '', 'off'):
        return 'off'
    if v in (True, 'on', 'cheap'):
        return 'cheap'
    raise ValueError("integrity must be 'off' or 'cheap' (got %r)" % v)


def checks_enabled():
    """Whether tier-0 guards should run (the one call every guarded
    surface makes at dispatch time — False compiles/executes nothing)."""
    return integrity_mode() != 'off'


# ---------------------------------------------------------------------------
# the violation ledger

def violation(site, rank=None, delta=None, detail=None):
    """Record a violation (ledger + counter + trace event) and return
    the classified :class:`IntegrityError` for the caller to raise."""
    if rank is None:
        from .fleet import fleet_rank
        rank = fleet_rank()
    rec = {'site': str(site), 'rank': int(rank),
           'delta': None if delta is None else float(delta)}
    if detail:
        rec['detail'] = str(detail)[:200]
    with _lock:
        _violations.append(rec)
    counter('integrity.violations').add(1)
    tr = current_tracer()
    if tr is not None:
        tr.event('integrity.violation', rec)
    return IntegrityError(site, rank=rank, delta=delta, detail=detail)


def violation_counts():
    """Snapshot: total violations plus a per-site breakdown."""
    with _lock:
        recs = list(_violations)
    by_site = {}
    for r in recs:
        by_site[r['site']] = by_site.get(r['site'], 0) + 1
    return {'violations': len(recs), 'by_site': by_site,
            'records': recs}


def reset_integrity():
    """Clear the violation ledger (test isolation)."""
    with _lock:
        del _violations[:]


# ---------------------------------------------------------------------------
# budgets

def rel_budget(dtype, n):
    """The compute-dtype-derived relative tolerance for a reduction
    over ``n`` terms: ``64 * eps * sqrt(n)`` (floored at 8 terms) —
    the random-walk rounding model with a wide safety factor.  Bit
    flips in sign/exponent shift a reduced scalar by orders of
    magnitude; this budget is what separates them from legitimate
    tree-reduction reordering noise."""
    import numpy as np
    eps = float(np.finfo(np.dtype(dtype)).eps)
    return 64.0 * eps * max(8.0, math.sqrt(float(max(int(n), 1))))


def mass_budget(n, compute_dtype, storage_dtype=None):
    """Relative budget for the paint mass-conservation check: the
    compute-dtype reduction budget, widened by the storage dtype's eps
    when the mesh stores narrow (bf16 deposits round each term once —
    a deterministic, bounded, non-cancelling error the guard must
    tolerate while still catching corruption)."""
    import numpy as np
    b = rel_budget(compute_dtype, n)
    if storage_dtype is not None:
        from ..utils import is_narrow_float
        if is_narrow_float(storage_dtype):
            import jax.numpy as jnp
            b += 8.0 * float(jnp.finfo(jnp.dtype(storage_dtype)).eps)
    return b


_MARGIN_FALLBACK = {'a2a-bf16': 0.01, 'a2a-int16': 0.0005,
                    'mesh-bf16': 0.02}
_margins_cache = None


def precision_margins():
    """The committed PRECISION.json accuracy margins (budget per
    compressed posture), falling back to the documented defaults when
    the file is absent (installed package, detached worker)."""
    global _margins_cache
    if _margins_cache is not None:
        return _margins_cache
    out = dict(_MARGIN_FALLBACK)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), 'PRECISION.json')
    try:
        import json
        with open(path) as f:
            data = json.load(f)
        for k, v in (data.get('margins') or {}).items():
            if isinstance(v, dict) and 'budget' in v:
                out[k] = float(v['budget'])
    except Exception:
        pass
    _margins_cache = out
    return out


def shadow_margin(options=None):
    """The result-comparison margin for tier-1 shadow verification: 0
    (bit-identical required) for uncompressed postures, else the sum
    of the PRECISION.json budgets of every compressed knob in play."""
    from .. import _global_options
    opts = dict(_global_options.copy())
    opts.update(options or {})
    m = precision_margins()
    margin = 0.0
    if str(opts.get('a2a_compress') or 'none') == 'bf16':
        margin += m['a2a-bf16']
    elif str(opts.get('a2a_compress') or 'none') == 'int16':
        margin += m['a2a-int16']
    if str(opts.get('mesh_dtype') or 'f4') == 'bf16':
        margin += m['mesh-bf16']
    return margin


# ---------------------------------------------------------------------------
# tier-0 checks (host-side, eager — called with concrete floats)

def check_close(site, got, want, budget_rel, rank=None, detail=None):
    """The shared invariant comparator: raises a recorded
    :class:`IntegrityError` when ``|got - want|`` exceeds the relative
    budget, or when either side is non-finite (the NaN/Inf tripwire —
    a poisoned mesh-sized intermediate reduces to a poisoned scalar)."""
    got, want = float(got), float(want)
    if not (math.isfinite(got) and math.isfinite(want)):
        raise violation(site + '.nonfinite', rank=rank,
                        detail='got=%r want=%r' % (got, want))
    delta = abs(got - want)
    if delta > max(abs(want), 1.0) * float(budget_rel):
        raise violation(
            site, rank=rank, delta=delta,
            detail='got=%.9g want=%.9g budget_rel=%.3g%s'
                   % (got, want, budget_rel,
                      ' (%s)' % detail if detail else ''))
    return delta


def check_mass(site, total, expected, scale, n, compute_dtype,
               storage_dtype=None):
    """Paint mass conservation: the deposited field's global sum must
    equal the global deposited mass within :func:`mass_budget`.
    ``scale`` is the absolute-mass fold ``sum(|mass|)`` the rounding
    budget scales with — signed weights (FKP) can cancel in
    ``expected`` while the rounding error cannot."""
    total, expected = float(total), float(expected)
    if not (math.isfinite(total) and math.isfinite(expected)):
        raise violation(site + '.nonfinite',
                        detail='total=%r expected=%r'
                               % (total, expected))
    budget = max(abs(float(scale)), 1.0) * mass_budget(
        n, compute_dtype, storage_dtype)
    delta = abs(total - expected)
    if delta > budget:
        raise violation(site, delta=delta,
                        detail='total=%.9g expected=%.9g budget=%.3g'
                               % (total, expected, budget))
    return delta


def check_a2a(site, pre, post, budget_abs, rank=None):
    """All_to_all fold-checksum: the globally-psummed fold of the
    payload must be wire-invariant within the format's own budget
    (computed in-graph alongside the folds — see dfft._a2a_checked)."""
    pre, post, budget = float(pre), float(post), float(budget_abs)
    if not (math.isfinite(pre) and math.isfinite(post)):
        raise violation(site + '.nonfinite', rank=rank,
                        detail='pre=%r post=%r' % (pre, post))
    delta = abs(pre - post)
    if delta > max(budget, 1e-30):
        raise violation(site, rank=rank, delta=delta,
                        detail='pre=%.9g post=%.9g budget=%.3g'
                               % (pre, post, budget))
    return delta


# ---------------------------------------------------------------------------
# deterministic payload corruption (the testable SDC stand-in)

def flip_bits_value(x, nbits):
    """Apply a stuck-at-one fault to the top ``nbits`` bits below the
    sign of one float32 word — the deterministic corruption the
    ``corrupt[:bits]`` fault action injects.  The mask always covers
    the exponent's top two bits, so ANY finite input (including 0.0)
    lands at magnitude >= 2**65: an XOR flip of a near-zero or large
    element can move a fold checksum by *less* than its legitimate
    rounding budget, which would make detection input-dependent — a
    stuck-at-one exponent fault is catastrophic by construction, so
    the detection matrix is deterministic.  Works under trace and
    eagerly (pure jnp)."""
    import jax
    import jax.numpy as jnp
    word = jax.lax.bitcast_convert_type(
        jnp.asarray(x, jnp.float32), jnp.uint32)
    nbits = max(1, min(int(nbits), 30))
    mask = jnp.uint32((((1 << nbits) - 1) << (31 - nbits))
                      | 0x60000000)
    return jax.lax.bitcast_convert_type(word | mask, jnp.float32)


def corrupt_real(arr, nbits):
    """Flip bits in element [0, ...] of a real array (eager or traced);
    returns the corrupted array in the input dtype."""
    import jax.numpy as jnp
    flat = arr.reshape(-1)
    bad = flip_bits_value(flat[0], nbits).astype(flat.dtype)
    return flat.at[0].set(bad).reshape(arr.shape)


def corrupt_host(arr, nbits):
    """The numpy form of :func:`corrupt_real` for host-side results
    (the ``serve.result`` injection point flips a delivered spectrum
    AFTER compute, so only tier-1 shadow verification can catch it).
    Returns a float32 copy with element [0] stuck-at-one faulted."""
    import numpy as np
    out = np.array(arr, dtype=np.float32, copy=True)
    nbits = max(1, min(int(nbits), 30))
    mask = np.uint32((((1 << nbits) - 1) << (31 - nbits)) | 0x60000000)
    flat = out.reshape(-1)
    word = flat[:1].view(np.uint32)
    word |= mask
    return out


def corrupt_complex(y, nbits):
    """Flip bits in the real part of element [0, ...] of a complex
    payload (used on the a2a wire)."""
    import jax
    import jax.numpy as jnp
    r, i = jnp.real(y), jnp.imag(y)
    return jax.lax.complex(corrupt_real(r, nbits).astype(r.dtype),
                           i).astype(y.dtype)
