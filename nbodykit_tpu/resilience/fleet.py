"""Fleet survivability: coordinated checkpoints, preemption, live
failure detection, and shrink-to-survive resume.

The single-process resilience story (:mod:`.checkpoint`,
:mod:`.supervise`, :mod:`.faults`) keeps ONE interpreter's progress
safe.  The hardware campaign (ROADMAP item 1) runs on preemptible
multi-host slices, where the failure model is the classic MPI one: any
rank dying takes the whole collective program with it — and today it
takes the recorded evidence too.  Four pieces close that gap:

- **Coordinated checkpoints** (:class:`FleetCheckpointStore`): every
  rank commits its shard through the atomic tmp+rename+sha256
  machinery of :class:`.checkpoint.CheckpointStore`, then the fleet
  rendezvouses — an allgather of shard-hash digests proves every rank
  landed — and only then does rank 0 atomically write a *manifest*
  that seals the sequence number.  A checkpoint without a sealed
  manifest does not exist: a kill anywhere mid-commit leaves the
  previous manifest authoritative.
- **Preemption handling**: :func:`install_preemption_handler` turns
  SIGTERM (what preemptible schedulers send) into a request honored at
  the next safe point (:func:`check_preemption` raises
  :class:`Preempted`) inside a grace budget; at the deadline a daemon
  timer force-exits with :data:`PREEMPTED_EXIT` so the scheduler never
  has to escalate to SIGKILL.  The request is announced as a
  ``resilience.preempted`` event + counter, which is also how the
  post-mortem analyzer distinguishes a clean preemption from a silent
  death.
- **Live failure detection** (:class:`FleetMonitor`): a daemon thread
  tails the per-process ``hb`` heartbeat records (diagnostics/trace.py)
  that were previously post-mortem-only, declares a peer dead after a
  configurable gap, and — because the main thread is typically wedged
  inside a gloo/ICI collective the dead peer will never enter —
  aborts the process (:data:`DEAD_RANK_EXIT`) instead of hanging until
  the distributed runtime's own multi-minute timeout.
- **Shrink-to-survive resume**: :meth:`FleetCheckpointStore.load`
  repartitions the surviving manifest's per-rank shards onto a new
  (smaller or larger) rank count — concatenate along the slab axis,
  re-slice — so a relaunch with fewer processes re-forms a valid mesh
  and resumes instead of restarting.

Everything here is host-side and importable without jax (the
collective rendezvous imports jax lazily); the chaos matrix that
drives the end-to-end test lives in :mod:`.faults` (rank-scoped rules
like ``rank1@bench.rep:sigkill``).  Full guide: docs/RESILIENCE.md.
"""

import functools
import json
import os
import re
import signal
import sys
import threading
import time

from ..diagnostics import counter, current_tracer, span
from .checkpoint import CheckpointStore, _atomic_bytes, _canonical, \
    _safe, _sha

# distinct exit codes so launchers/relaunch loops can tell a clean
# preemption (resume and continue) from a detected dead peer (re-form
# the fleet) from an ordinary crash.  75/76 follow the BSD sysexits
# "temporary failure" neighborhood without colliding with shell or
# signal codes (128+N).
PREEMPTED_EXIT = 75
DEAD_RANK_EXIT = 76


class Preempted(RuntimeError):
    """Raised at a safe point after SIGTERM requested preemption."""


class FleetSealError(RuntimeError):
    """A coordinated checkpoint failed its seal rendezvous: some rank's
    shard is missing or hash-divergent.  FATAL to classification — a
    torn fleet checkpoint must not be retried blindly."""


# ---------------------------------------------------------------------------
# fleet identity

def fleet_rank():
    """This process's fleet rank: ``$NBKIT_FLEET_RANK``, else
    ``$JAX_PROCESS_ID``, else ``jax.process_index()`` when jax is
    already imported, else 0.  Environment first, so host-side tools
    (and fault rules evaluated before jax initializes) agree with the
    launcher."""
    for var in ('NBKIT_FLEET_RANK', 'JAX_PROCESS_ID'):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    jax = sys.modules.get('jax')
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


def fleet_size():
    """Number of processes in the fleet (same resolution order as
    :func:`fleet_rank`; 1 when nothing says otherwise)."""
    for var in ('NBKIT_FLEET_SIZE', 'JAX_NUM_PROCESSES'):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    jax = sys.modules.get('jax')
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:
            pass
    return 1


# ---------------------------------------------------------------------------
# tier-2 integrity attribution: suspect ranks and quarantine
# (docs/INTEGRITY.md).  The Supervisor records a strike here for every
# classified IntegrityError; after K strikes the rank is quarantined —
# the quarantine list rides the sealed fleet manifest (hash-covered),
# is adopted by any process that loads the checkpoint, and a
# quarantined rank refuses to rejoin at load time, so shrink-to-
# survive re-formation proceeds without the suspect chip.

class SuspectTracker(object):
    """Count integrity strikes per fleet rank; quarantine after K.

    ``strikes_to_quarantine`` defaults to 2 (one retry heals a
    transient bit flip; a second violation on the same rank is a
    pattern) and is overridable via ``$NBKIT_INTEGRITY_STRIKES``.
    Thread-safe; process-local, with :meth:`adopt` merging the sealed
    manifest's quarantine list on fleet re-formation."""

    def __init__(self, strikes=None):
        if strikes is None:
            strikes = os.environ.get('NBKIT_INTEGRITY_STRIKES') or 2
        self.strikes_to_quarantine = max(1, int(strikes))
        self._lock = threading.Lock()
        self._strikes = {}
        self._quarantined = set()

    def strike(self, rank=None, site=None, task=None):
        """Record one integrity strike against ``rank`` (default: this
        process's fleet rank).  Returns the rank's strike count; the
        K-th strike quarantines the rank and emits the
        ``resilience.fleet.quarantined`` counter + trace event."""
        rank = fleet_rank() if rank is None else int(rank)
        with self._lock:
            recs = self._strikes.setdefault(rank, [])
            recs.append({'site': site, 'task': task,
                         'at': round(time.time(), 3)})
            n = len(recs)
            newly = (n >= self.strikes_to_quarantine
                     and rank not in self._quarantined)
            if newly:
                self._quarantined.add(rank)
        counter('resilience.fleet.strikes').add(1)
        if newly:
            counter('resilience.fleet.quarantined').add(1)
            tr = current_tracer()
            if tr is not None:
                tr.event('resilience.fleet.quarantined',
                         {'rank': rank, 'strikes': n,
                          'site': site, 'task': task})
        return n

    def adopt(self, ranks):
        """Merge an externally-recorded quarantine list (the sealed
        manifest's) into this process's view."""
        with self._lock:
            self._quarantined.update(int(r) for r in (ranks or ()))

    def quarantined(self):
        with self._lock:
            return sorted(self._quarantined)

    def is_quarantined(self, rank):
        with self._lock:
            return int(rank) in self._quarantined

    def strike_counts(self):
        with self._lock:
            return {r: len(v) for r, v in self._strikes.items()}

    def summary(self):
        """Posture dict for regress/doctor: strikes + quarantine."""
        with self._lock:
            return {'strikes': sum(len(v)
                                   for v in self._strikes.values()),
                    'by_rank': {str(r): len(v)
                                for r, v in self._strikes.items()},
                    'quarantined': sorted(self._quarantined)}

    def reset(self):
        with self._lock:
            self._strikes.clear()
            self._quarantined.clear()


_suspects = SuspectTracker()


def suspect_tracker():
    """The process-wide :class:`SuspectTracker` singleton."""
    return _suspects


def reset_suspects():
    """Clear strikes + quarantine (test isolation)."""
    _suspects.reset()


# ---------------------------------------------------------------------------
# preemption: SIGTERM -> safe-point Preempted inside a grace budget

_preempt_lock = threading.Lock()
_preempt = {'prev_handler': None, 'grace_s': 30.0,
            'exit_code': PREEMPTED_EXIT, 'requested_at': None,
            'deadline': None, 'announced': False}


def install_preemption_handler(grace_s=30.0, exit_code=PREEMPTED_EXIT):
    """Install the SIGTERM handler (main thread only, per the signal
    module's contract).  Idempotent; re-installing updates the grace
    budget.  The handler itself only records the request and arms the
    grace-deadline force-exit — the checkpoint/seal work happens at the
    next :func:`check_preemption` safe point, in ordinary context."""
    with _preempt_lock:
        _preempt['grace_s'] = float(grace_s)
        _preempt['exit_code'] = int(exit_code)
        if _preempt['prev_handler'] is None:
            prev = signal.signal(signal.SIGTERM, _on_sigterm)
            _preempt['prev_handler'] = prev if prev is not None \
                else signal.SIG_DFL


def uninstall_preemption_handler():
    """Restore the previous SIGTERM disposition and clear any pending
    request (test isolation)."""
    with _preempt_lock:
        prev = _preempt['prev_handler']
        _preempt['prev_handler'] = None
        _preempt.update(requested_at=None, deadline=None,
                        announced=False)
    if prev is not None:
        signal.signal(signal.SIGTERM, prev)


def _on_sigterm(signum, frame):
    # Runs as a deferred Python-level handler on the main thread, which
    # may be interrupted INSIDE tracer/file locks — so no emitting
    # here.  The announce runs on its own thread; the grace timer is
    # the force-exit backstop the scheduler's kill would otherwise be.
    now = time.time()
    with _preempt_lock:
        if _preempt['requested_at'] is not None:
            return
        _preempt['requested_at'] = now
        grace = _preempt['grace_s']
        _preempt['deadline'] = now + grace
    threading.Thread(target=_announce_preemption, daemon=True,
                     name='nbkit-preempt-announce').start()
    t = threading.Timer(grace, _grace_expired)
    t.daemon = True
    t.start()


def _announce_preemption():
    """Emit the ``resilience.preempted`` counter + trace event exactly
    once per request (the analyzer keys the preempted-vs-silent
    distinction on this event)."""
    with _preempt_lock:
        if _preempt['announced'] or _preempt['requested_at'] is None:
            return
        _preempt['announced'] = True
        grace = _preempt['grace_s']
        deadline = _preempt['deadline']
    counter('resilience.preempted').add(1)
    tr = current_tracer()
    if tr is not None:
        tr.event('resilience.preempted',
                 {'grace_s': grace, 'deadline': round(deadline, 3)})
    # seal the flight recorder while there is still grace left: the
    # post-mortem gets the last N request waterfalls even if the
    # grace timer force-exits before any server drains
    from ..diagnostics.export import FLIGHT
    FLIGHT.dump('preempt.sigterm')


def _grace_expired():
    with _preempt_lock:
        if _preempt['requested_at'] is None:
            return
        code = _preempt['exit_code']
    counter('resilience.preempt_forced').add(1)
    from ..diagnostics.export import FLIGHT
    FLIGHT.dump('preempt.grace_expired')
    tr = current_tracer()
    if tr is not None:
        tr.event('resilience.preempt_forced', {'exit_code': code})
        tr.close()
    os._exit(code)


def preemption_requested():
    """True once SIGTERM arrived (checked lock-free on hot paths)."""
    return _preempt['requested_at'] is not None


def preemption_deadline():
    """Epoch seconds of the grace deadline, or None."""
    return _preempt['deadline']


def clear_preemption():
    """Forget a pending request (test isolation; the handler stays)."""
    with _preempt_lock:
        _preempt.update(requested_at=None, deadline=None,
                        announced=False)


def check_preemption(label=None):
    """The safe point: raise :class:`Preempted` when a SIGTERM arrived.
    Call where progress has just been checkpointed — between bench
    reps, between serve requests — so the exit loses nothing."""
    if _preempt['requested_at'] is None:
        return
    _announce_preemption()
    left = (_preempt['deadline'] or 0) - time.time()
    raise Preempted('preemption requested at %s (%.1f s of grace left)'
                    % (label or 'safe point', max(left, 0.0)))


# ---------------------------------------------------------------------------
# collective rendezvous (jax imported lazily; single-process callers
# pass mesh=None and never touch it)

@functools.lru_cache(maxsize=8)
def _allgather_for(mesh, width):
    import jax
    from jax.sharding import PartitionSpec as P
    from ..parallel.runtime import leading_axes
    lead = leading_axes(mesh)
    return jax.jit(jax.shard_map(
        lambda v: jax.lax.all_gather(v, lead, axis=0, tiled=True),
        mesh=mesh, in_specs=P(lead), out_specs=P()))


@functools.lru_cache(maxsize=8)
def _allsum_for(mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..parallel.runtime import leading_axes
    lead = leading_axes(mesh)
    return jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), lead), mesh=mesh,
        in_specs=P(lead), out_specs=P()))


def _device_rows(mesh, row):
    """Place ``row`` (one int32 vector, identical across this
    process's devices) as a device-sharded (ndev, width) array."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.runtime import leading_axes
    row = np.ascontiguousarray(row, 'int32').ravel()
    ndev = int(mesh.devices.size)
    full = np.tile(row, (ndev, 1))
    sh = NamedSharding(mesh, P(leading_axes(mesh)))
    # the callback only materializes THIS process's shards, so every
    # process contributes its own row without seeing the others'
    return jax.make_array_from_callback((ndev, row.size), sh,
                                        lambda idx: full[idx])


def fleet_allgather(mesh, row):
    """All-gather one small int32 row per process over ``mesh``;
    returns the rows ordered by process index (one per process, the
    duplicate per-device copies collapsed).  This is the seal
    rendezvous primitive: every process calls it unconditionally, so
    the fleet's collective order stays rank-uniform."""
    import numpy as np
    arr = _device_rows(mesh, row)
    out = np.asarray(_allgather_for(mesh, arr.shape[1])(arr))
    rows = {}
    for i, d in enumerate(mesh.devices.flatten()):
        rows.setdefault(int(d.process_index), out[i])
    return [rows[p] for p in sorted(rows)]


def fleet_barrier(mesh, tag):
    """An explicit fleet-wide sync point wrapped in a ``barrier`` span
    (the analyzer's clock-alignment anchor): a replicated psum every
    process leaves together."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.runtime import leading_axes
    ndev = int(mesh.devices.size)
    sh = NamedSharding(mesh, P(leading_axes(mesh)))
    ones = np.ones((ndev,), 'f4')
    x = jax.make_array_from_callback((ndev,), sh, lambda idx: ones[idx])
    allsum = _allsum_for(mesh)
    with span('barrier', point=str(tag)):
        total = float(allsum(x))
    assert total == ndev, (tag, total, ndev)
    return total


# ---------------------------------------------------------------------------
# coordinated checkpoints: per-rank shards + rank-0 sealed manifest

def reassemble(per_rank_arrays):
    """Concatenate per-rank array dicts (rank order) along axis 0 —
    the slab convention every fleet shard follows."""
    import numpy as np
    if not per_rank_arrays:
        return {}
    names = sorted(per_rank_arrays[0])
    return {name: np.concatenate([np.asarray(d[name])
                                  for d in per_rank_arrays], axis=0)
            for name in names}


def repartition(per_rank_arrays, new_nranks):
    """Re-slice per-rank shard arrays onto ``new_nranks`` ranks: the
    shrink-to-survive transform.  Returns a list of array dicts, one
    per new rank (slab re-slice; a pencil relaunch re-factorizes its
    device mesh separately via ``default_pencil_factor``)."""
    import numpy as np
    full = reassemble(per_rank_arrays)
    new_nranks = int(new_nranks)
    out = [dict() for _ in range(new_nranks)]
    for name, arr in full.items():
        for r, piece in enumerate(np.array_split(arr, new_nranks,
                                                 axis=0)):
            out[r][name] = piece
    return out


class FleetCheckpointStore(object):
    """Coordinated multi-rank checkpoints over one directory.

    Layout (all names pass through the base store's ``_safe``):

    - ``<key>.m<seq>.rank<r>.ckpt.json`` (+ ``.npy`` payloads) — rank
      ``r``'s shard for sequence ``seq``, committed atomically by
      :class:`CheckpointStore`.
    - ``<key>.m<seq>.manifest.json`` — the rank-0 seal: per-rank shard
      hashes + decomposition, content-hashed itself, written only
      after the allgather rendezvous proved every shard landed.  Its
      rename is the fleet-wide commit point; :meth:`latest_manifest`
      only ever trusts a verifying manifest whose shards verify too,
      so a kill mid-commit leaves the previous seq authoritative.

    ``seq`` must be rank-uniform (callers use the rep number)."""

    _SHARD_RE = re.compile(
        r'^(?P<fam>.+)\.m(?P<seq>\d+)\.rank(?P<rank>\d+)\.ckpt\.json$')
    _MANIFEST_RE = re.compile(
        r'^(?P<fam>.+)\.m(?P<seq>\d+)\.manifest\.json$')

    def __init__(self, root, keep=3):
        self.store = CheckpointStore(root)
        self.root = self.store.root
        self.keep = int(keep)

    # -- naming -----------------------------------------------------------

    def shard_key(self, key, seq, rank):
        return '%s.m%04d.rank%d' % (key, int(seq), int(rank))

    def _manifest_path(self, key, seq):
        return os.path.join(self.root, '%s.m%04d.manifest.json'
                            % (_safe(key), int(seq)))

    # -- shard commit ------------------------------------------------------

    def save_shard(self, key, seq, rank, nranks, state, arrays=None):
        """Commit this rank's shard (atomic via the base store).  The
        user state is wrapped with the fleet coordinates so a shard
        can never be replayed under the wrong decomposition."""
        wrapped = {'fleet': {'key': str(key), 'seq': int(seq),
                             'rank': int(rank), 'nranks': int(nranks)},
                   'user': state}
        skey = self.shard_key(key, seq, rank)
        self.store.save(skey, wrapped, arrays=arrays)
        return skey

    def _shard_sha(self, key, seq, rank):
        """The committed shard's content hash (metadata ``sha256``),
        or None when the shard has not landed."""
        path = self.store._meta_path(self.shard_key(key, seq, rank))
        try:
            with open(path) as f:
                return json.load(f).get('sha256')
        except (OSError, ValueError):
            return None

    @staticmethod
    def _sha_words(sha_hex):
        """The first 64 hash bits as two non-negative int32 words —
        the form that rides the seal allgather."""
        return (int(sha_hex[:8], 16) & 0x7fffffff,
                int(sha_hex[8:16], 16) & 0x7fffffff)

    def _digest_row(self, key, seq, rank, nranks):
        sha = self._shard_sha(key, seq, rank)
        w0, w1 = self._sha_words(sha) if sha else (-1, -1)
        return [int(seq), int(rank), int(nranks), w0, w1]

    def _verify_rows(self, key, seq, nranks, rows):
        """None when every rank's shard landed and the wire digests
        match the on-disk hashes; else the reason string.  Never
        raises — the caller sequences the raise AFTER the seal barrier
        so the fleet's collective order stays rank-uniform."""
        seen = {}
        for row in rows:
            vals = [int(v) for v in row]
            seen[vals[1]] = vals
        for r in range(int(nranks)):
            vals = seen.get(r)
            if vals is None:
                return 'rank %d missing from the seal rendezvous' % r
            if vals[0] != int(seq):
                return 'rank %d rendezvoused seq %d, expected %d' \
                    % (r, vals[0], int(seq))
            if vals[2] != int(nranks):
                return 'rank %d sees %d ranks, expected %d' \
                    % (r, vals[2], int(nranks))
            sha = self._shard_sha(key, seq, r)
            if sha is None:
                return 'rank %d shard not committed' % r
            if tuple(vals[3:5]) != self._sha_words(sha):
                return 'rank %d shard hash diverges from its ' \
                    'rendezvous digest' % r
        return None

    #: Manifest keys a seal may stamp via ``extra`` — hash-covered,
    #: present only when set, so old manifests keep verifying.  The
    #: reformed pair records an elastic re-formation boundary (shrink
    #: OR grow): this seq's shards were repartitioned from a fleet of
    #: ``reformed_from`` ranks into ``reformed_to``.
    _EXTRA_KEYS = ('reformed_from', 'reformed_to')

    def _write_manifest(self, key, seq, nranks, decomp, extra=None):
        shards = {}
        for r in range(int(nranks)):
            skey = self.shard_key(key, seq, r)
            shards[str(r)] = {
                'key': skey,
                'file': os.path.basename(self.store._meta_path(skey)),
                'sha256': self._shard_sha(key, seq, r),
            }
        payload = {'key': str(key), 'seq': int(seq),
                   'nranks': int(nranks), 'decomp': decomp,
                   'shards': shards}
        # the quarantine list rides the SEALED body (hash-covered):
        # a re-formed fleet adopting this checkpoint inherits which
        # ranks are suspect.  Only present when non-empty, so every
        # previously-sealed manifest keeps verifying unchanged.
        quarantined = suspect_tracker().quarantined()
        if quarantined:
            payload['quarantined'] = quarantined
        for k in self._EXTRA_KEYS:
            if extra is not None and k in extra:
                payload[k] = int(extra[k])
        body = _canonical(payload)
        man = dict(payload, v=1, sealed_at=round(time.time(), 6),
                   sha256=_sha(body))
        path = self._manifest_path(key, seq)
        from .faults import fault_point
        # pre-commit fault points: a kill here proves the previous
        # manifest stays authoritative (chaos rule ckpt.manifest)
        fault_point('ckpt.manifest')
        fault_point('ckpt.manifest.%s' % key)
        _atomic_bytes(path, json.dumps(man, indent=1,
                                       default=str).encode('utf-8'))
        counter('resilience.fleet.manifests_sealed').add(1)
        fault_point('ckpt.manifest.sealed')
        return path

    def seal(self, key, seq, nranks=None, mesh=None, rank=None,
             decomp=None, extra=None):
        """Seal sequence ``seq``: rendezvous (allgather of shard
        digests over ``mesh``), verify every rank landed, rank 0
        writes the manifest, then a fleet barrier so no rank runs
        ahead of an unsealed checkpoint.  All-or-nothing: any missing
        or divergent shard raises :class:`FleetSealError` on every
        rank — after the barrier, so the collective order never
        branches.  ``mesh=None`` verifies against the shared
        filesystem alone (single-process fleets, tests)."""
        rank = fleet_rank() if rank is None else int(rank)
        nranks = fleet_size() if nranks is None else int(nranks)
        with span('fleet.seal', key=str(key), seq=int(seq),
                  nranks=nranks):
            if mesh is None:
                rows = [self._digest_row(key, seq, r, nranks)
                        for r in range(nranks)]
                err = self._verify_rows(key, seq, nranks, rows)
                if err is None and rank == 0:
                    self._write_manifest(key, seq, nranks, decomp,
                                         extra=extra)
            else:
                row = self._digest_row(key, seq, rank, nranks)
                rows = fleet_allgather(mesh, row)
                err = self._verify_rows(key, seq, nranks, rows)
                if err is None and rank == 0:
                    self._write_manifest(key, seq, nranks, decomp,
                                         extra=extra)
                fleet_barrier(mesh, 'fleet.seal')
        if err is not None:
            counter('resilience.fleet.seal_failed').add(1)
            raise FleetSealError('fleet seal %s.m%04d: %s'
                                 % (key, int(seq), err))
        return int(seq)

    def save(self, key, state, arrays=None, mesh=None, seq=None,
             rank=None, nranks=None, decomp=None):
        """Shard commit + seal in one call.  ``seq`` defaults to
        :meth:`next_seq` — fine on one process; multi-rank callers
        must pass a rank-uniform ``seq`` (the rep number)."""
        rank = fleet_rank() if rank is None else int(rank)
        nranks = fleet_size() if nranks is None else int(nranks)
        if seq is None:
            seq = self.next_seq(key)
        self.save_shard(key, seq, rank, nranks, state, arrays=arrays)
        self.seal(key, seq, nranks=nranks, mesh=mesh, rank=rank,
                  decomp=decomp)
        return int(seq)

    # -- manifests ---------------------------------------------------------

    def manifest_seqs(self, key):
        """Sequence numbers with a manifest file on disk, ascending
        (verification happens at :meth:`manifest` time)."""
        rx = re.compile(r'^%s\.m(\d+)\.manifest\.json$'
                        % re.escape(_safe(key)))
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(int(m.group(1)) for f in names
                      for m in [rx.match(f)] if m)

    def next_seq(self, key):
        """1 + the highest seq any manifest OR shard file mentions, so
        a relaunch never reuses a seq that has kill debris."""
        fam = _safe(key)
        seqs = set(self.manifest_seqs(key))
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for f in names:
            m = self._SHARD_RE.match(f)
            if m and m.group('fam') == fam:
                seqs.add(int(m.group('seq')))
        return (max(seqs) + 1) if seqs else 1

    def manifest(self, key, seq):
        """The verified manifest dict for ``seq``, or None (missing,
        torn, or content-hash mismatch — counted as corrupt)."""
        try:
            with open(self._manifest_path(key, seq)) as f:
                man = json.load(f)
        except (OSError, ValueError):
            return None
        payload = {'key': man.get('key'), 'seq': man.get('seq'),
                   'nranks': man.get('nranks'),
                   'decomp': man.get('decomp'),
                   'shards': man.get('shards')}
        if 'quarantined' in man:
            payload['quarantined'] = man['quarantined']
        for k in self._EXTRA_KEYS:
            if k in man:
                payload[k] = man[k]
        body = _canonical(payload)
        if _sha(body) != man.get('sha256'):
            counter('resilience.checkpoint.corrupt').add(1)
            return None
        return man

    def latest_manifest(self, key):
        """The newest verifying manifest, or None.  A seq whose
        manifest is torn (kill mid-seal) is skipped — the previous
        sealed seq stays authoritative."""
        for seq in reversed(self.manifest_seqs(key)):
            man = self.manifest(key, seq)
            if man is not None:
                return man
        return None

    # -- restore -----------------------------------------------------------

    def load_full(self, key):
        """``(state, arrays, manifest)`` — the newest sealed checkpoint
        reassembled across ranks (arrays concatenated along axis 0 in
        rank order; state from rank 0, rank-uniform by construction).
        None without a verifying manifest or with any corrupt shard."""
        man = self.latest_manifest(key)
        if man is None:
            return None
        per_rank = []
        for r in range(int(man['nranks'])):
            got = self.store.load(self.shard_key(key, man['seq'], r))
            if got is None:
                return None
            per_rank.append(got)
        state = (per_rank[0][0] or {}).get('user')
        return state, reassemble([a for _, a in per_rank]), man

    def load(self, key, rank=None, nranks=None):
        """This rank's slice of the newest sealed checkpoint as
        ``(state, arrays, info)``, or None.  Same rank count as the
        manifest → the shard exactly as saved; a different count →
        the shrink-to-survive repartition (``info`` carries
        ``reformed_from``/``reformed_to`` for the record stamps)."""
        rank = fleet_rank() if rank is None else int(rank)
        nranks = fleet_size() if nranks is None else int(nranks)
        man = self.latest_manifest(key)
        if man is None:
            return None
        old = int(man['nranks'])
        seq = int(man['seq'])
        quarantined = [int(r) for r in man.get('quarantined') or ()]
        if quarantined:
            # the sealed quarantine list is authoritative: adopt it,
            # and a quarantined rank REFUSES to rejoin — the launcher
            # must re-form the fleet without the suspect chip (the
            # shrink-to-survive path below handles the smaller count)
            suspect_tracker().adopt(quarantined)
            if rank in quarantined:
                counter('resilience.fleet.quarantine_refused').add(1)
                raise RuntimeError(
                    'fleet rank %d is quarantined in the sealed '
                    'manifest %s.m%04d (integrity strikes); re-form '
                    'the fleet without it' % (rank, key, seq))
        if nranks == old:
            got = self.store.load(self.shard_key(key, seq, rank))
            if got is None:
                return None
            wrapped, arrays = got
            info = {'seq': seq, 'nranks': old, 'reformed': False}
            if quarantined:
                # mirror the manifest policy: the key appears only
                # when there is something to report, so pre-integrity
                # callers comparing info dicts never see it
                info['quarantined'] = quarantined
            return ((wrapped or {}).get('user'), arrays, info)
        per_rank = []
        for r in range(old):
            got = self.store.load(self.shard_key(key, seq, r))
            if got is None:
                return None
            per_rank.append(got)
        state = (per_rank[0][0] or {}).get('user')
        mine = repartition([a for _, a in per_rank], nranks)[rank]
        counter('resilience.fleet.reformed').add(1)
        tr = current_tracer()
        if tr is not None:
            tr.event('resilience.fleet.reform',
                     {'key': str(key), 'from': old, 'to': nranks})
        info = {'seq': seq, 'nranks': nranks, 'reformed': True,
                'reformed_from': old, 'reformed_to': nranks}
        if quarantined:
            info['quarantined'] = quarantined
        return (state, mine, info)

    # -- retention / observability ----------------------------------------

    def survey(self):
        """Inventory for the doctor/regress posture: per family the
        sealed seqs and the *incomplete* ones (shards without a
        manifest — kill debris), plus in-flight ``*.tmp.*`` files."""
        fams, tmp = {}, 0
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for f in names:
            if '.tmp.' in f:
                tmp += 1
                continue
            m = self._MANIFEST_RE.match(f)
            if m:
                fam = fams.setdefault(m.group('fam'),
                                      {'sealed': set(), 'shards': {}})
                fam['sealed'].add(int(m.group('seq')))
                continue
            m = self._SHARD_RE.match(f)
            if m:
                fam = fams.setdefault(m.group('fam'),
                                      {'sealed': set(), 'shards': {}})
                fam['shards'].setdefault(int(m.group('seq')),
                                         set()).add(int(m.group('rank')))
        families = {}
        sealed = incomplete = 0
        for name, info in sorted(fams.items()):
            inc = sorted(s for s in info['shards']
                         if s not in info['sealed'])
            families[name] = {'sealed': sorted(info['sealed']),
                              'incomplete': inc,
                              'shards': {s: sorted(r) for s, r
                                         in info['shards'].items()}}
            sealed += len(info['sealed'])
            incomplete += len(inc)
        return {'families': families, 'sealed': sealed,
                'incomplete': incomplete, 'orphan_tmp': tmp}

    def gc(self, keep=None, tmp_age_s=3600.0, now=None):
        """Retention: keep the newest ``keep`` sealed manifests per
        family; drop superseded manifests + their shards, unsealed
        shard seqs older than the newest seal (kill debris), and stale
        ``*.tmp.*`` orphans.  Returns removal counts — the campaign's
        BENCH_CKPT/ stops growing without bound."""
        keep = self.keep if keep is None else int(keep)
        keep = max(keep, 1)
        sv = self.survey()
        removed = {'manifests': 0, 'shards': 0, 'tmp': 0}
        for fam, info in sv['families'].items():
            sealed = info['sealed']
            drop = set(sealed[:-keep])
            newest = sealed[-1] if sealed else None
            debris = set(s for s in info['incomplete']
                         if newest is not None and s < newest)
            for seq in sorted(drop):
                try:
                    os.remove(self._manifest_path(fam, seq))
                    removed['manifests'] += 1
                except OSError:
                    pass
            for seq in sorted(drop | debris):
                for r in info['shards'].get(seq, ()):
                    self.store.delete(self.shard_key(fam, seq, r))
                    removed['shards'] += 1
        removed['tmp'] = self.store.gc_tmp(max_age_s=tmp_age_s,
                                           now=now)
        total = sum(removed.values())
        if total:
            counter('resilience.fleet.gc_removed').add(total)
        return removed

    def delete(self, key):
        """Remove every manifest + shard of ``key``'s family."""
        fam = _safe(key)
        info = self.survey()['families'].get(fam)
        if info is None:
            return
        for seq in info['sealed']:
            try:
                os.remove(self._manifest_path(fam, seq))
            except OSError:
                pass
        for seq, ranks in info['shards'].items():
            for r in ranks:
                self.store.delete(self.shard_key(fam, seq, r))


# ---------------------------------------------------------------------------
# live failure detection

def scan_liveness(path, gap_s=None, now=None, exclude_pids=()):
    """Per-process liveness from a LIVE trace directory.

    Unlike ``analyze.heartbeat_report`` (post-mortem: gaps measured
    against the trace end) this compares each process's last record
    against the wall clock *now* — same-host clocks, which is what the
    CPU fleet and per-host monitors see.  A process with a
    ``resilience.preempted`` event is never ``dead`` (it announced a
    clean exit); one traced without heartbeats makes no claim
    (``dead: None``).  ``gap_s`` defaults to max(3·interval, 2 s).
    """
    from ..diagnostics.analyze import load_processes
    procs, _ = load_processes(path)
    now = time.time() if now is None else float(now)
    skip = set(exclude_pids)
    out = []
    for pid in sorted(procs):
        if pid in skip:
            continue
        last, iv, count, rank, preempted = None, None, 0, None, False
        for r in procs[pid]:
            ts = r.get('ts')
            if ts is not None:
                ts = float(ts)
                last = ts if last is None else max(last, ts)
            t = r.get('t')
            if t == 'hb':
                count += 1
                iv = float(r.get('iv', 0)) or iv
                if r.get('rank') is not None:
                    rank = int(r['rank'])
            elif t == 'meta':
                if r.get('heartbeat_s'):
                    iv = float(r['heartbeat_s'])
                if r.get('rank') is not None:
                    rank = int(r['rank'])
            elif t == 'span' and r.get('name') == 'resilience.preempted':
                preempted = True
        gap = None if last is None else now - last
        thresh = float(gap_s) if gap_s else \
            (max(3.0 * iv, 2.0) if iv else None)
        if preempted:
            dead = False
        elif iv and gap is not None and thresh is not None:
            dead = gap > thresh
        else:
            dead = None
        out.append({'pid': pid, 'rank': rank, 'last_seen': last,
                    'gap_s': None if gap is None else round(gap, 6),
                    'hb_count': count, 'hb_interval_s': iv,
                    'preempted': preempted, 'dead': dead})
    return out


class FleetMonitor(object):
    """Daemon thread declaring peers dead from their heartbeat gaps —
    live, while this process may be wedged inside a collective the
    dead peer will never enter.

    With ``abort=True`` a detection flushes the tracer and
    ``os._exit(exit_code)``s (default :data:`DEAD_RANK_EXIT`): the
    only way out of a blocked gloo/ICI collective, and minutes faster
    than the distributed runtime's own heartbeat timeout at any sane
    ``gap_s``.  Only processes seen alive on this monitor's watch
    (last record no older than start − gap) are ever declared — stale
    trace files from an earlier incarnation are ignored.  The monitor
    never raises into the watched process: scan errors are swallowed.
    """

    def __init__(self, path, gap_s=2.0, poll_s=None, on_dead=None,
                 abort=False, exit_code=DEAD_RANK_EXIT,
                 exclude_pids=()):
        self.path = str(path)
        self.gap_s = float(gap_s)
        self.poll_s = float(poll_s) if poll_s \
            else max(self.gap_s / 4.0, 0.05)
        self.on_dead = on_dead
        self.abort = abort
        self.exit_code = int(exit_code)
        self.exclude = set(exclude_pids)
        self.exclude.add(os.getpid())
        self.dead = []
        self._reported = set()
        self._stop = threading.Event()
        self._thread = None
        self._t0 = time.time()

    def start(self):
        self._t0 = time.time()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name='nbkit-fleet-monitor')
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception:   # monitoring must never kill a healthy run
                pass

    def _emit(self, name, attrs):
        """A detection event must survive even when the tracer is
        already gone — a dead peer usually errors the main thread's
        collective FIRST, and by the time the heartbeat gap confirms
        the death the interpreter may be deep in teardown (tracer
        closed by atexit, main thread blocked in the distributed
        runtime's shutdown).  Fall back to appending the span record
        directly into the watched directory, where the post-mortem
        analyzer merges it like any per-process trace file."""
        tr = current_tracer()
        if tr is not None:
            try:
                f = getattr(tr, '_f', None)
                if f is not None and not f.closed:
                    tr.event(name, attrs)
                    return
            except Exception:
                pass
        if not os.path.isdir(self.path):
            return
        rec = {'t': 'span', 'name': name, 'ts': round(time.time(), 6),
               'dur': 0.0, 'depth': 0, 'pid': os.getpid(), 'ok': True,
               'attrs': attrs}
        try:
            with open(os.path.join(
                    self.path, 'monitor-%d.jsonl' % os.getpid()),
                    'a') as f:
                f.write(json.dumps(rec) + '\n')
                f.flush()
        except OSError:
            pass

    def check_once(self, now=None):
        """One scan; declares (and with ``abort``, acts on) fresh
        deaths.  Split out for tests.  Returns the scan entries."""
        now = time.time() if now is None else now
        entries = scan_liveness(self.path, gap_s=self.gap_s, now=now,
                                exclude_pids=self.exclude)
        fresh = []
        for e in entries:
            if not e['dead'] or e['pid'] in self._reported:
                continue
            if e['last_seen'] is not None and \
                    e['last_seen'] < self._t0 - self.gap_s:
                continue        # died before our watch began
            self._reported.add(e['pid'])
            self.dead.append(e)
            fresh.append(e)
            counter('resilience.fleet.dead_ranks').add(1)
            self._emit('resilience.fleet.dead_rank',
                       {'pid': e['pid'], 'rank': e['rank'],
                        'gap_s': e['gap_s']})
            if self.on_dead is not None:
                try:
                    self.on_dead(e)
                except Exception:
                    pass
        if fresh and self.abort:
            self._abort(fresh)
        return entries

    def _abort(self, entries):
        self._emit('resilience.fleet.abort',
                   {'pids': [e['pid'] for e in entries],
                    'exit_code': self.exit_code})
        tr = current_tracer()
        if tr is not None:
            try:
                tr.close()
            except Exception:
                pass
        os._exit(self.exit_code)
