"""MeshSource: the distributed 3-D field abstraction.

Reference: ``nbodykit/base/mesh.py:6``. A MeshSource is *a recipe for a
field*: it can produce a real-space or Fourier-space view of itself
(``compute``), with a queue of deferred ``apply`` actions (window
compensation, smoothing filters, transfer functions) composed on top.

TPU-native redesign: the action queue is function composition that jit
traces through — paint, FFTs, and every queued transfer fuse into one
XLA program. Fields are :class:`Field` wrappers around global sharded
jnp arrays (value + attrs), registered as pytrees so they flow through
jax transforms.

Complex fields use the transposed hermitian layout of
:mod:`nbodykit_tpu.parallel.dfft`; ``apply(kind=...)`` passes
coordinate arrays matching the reference's kinds
(wavenumber/circular/index for complex, relative/index for real;
reference base/mesh.py:132-176).
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..pmesh import ParticleMesh
from ..parallel.runtime import CurrentMesh
from ..utils import as_numpy
from ..diagnostics import device_watermarks, enabled, span_eager

logger = logging.getLogger('MeshSource')


@jax.tree_util.register_pytree_node_class
class Field(object):
    """A mesh field: a global (possibly sharded) jnp array + metadata.

    Replaces pmesh's RealField/ComplexField at the API surface consumed
    by the reference's algorithms (r2c/c2r/apply/csum/readout...).
    """

    def __init__(self, value, pm, kind=None, attrs=None):
        self.value = value
        self.pm = pm
        # kind: 'real' or 'complex'; inferred when not given
        if kind is None:
            kind = 'complex' if jnp.iscomplexobj(value) else 'real'
        self.kind = kind
        self.attrs = {} if attrs is None else attrs

    # pytree protocol: value is the leaf, the rest rides along
    def tree_flatten(self):
        return (self.value,), (self.pm, self.kind, self.attrs)

    @classmethod
    def tree_unflatten(cls, aux, children):
        pm, kind, attrs = aux
        return cls(children[0], pm, kind=kind, attrs=attrs)

    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    def r2c(self):
        assert self.kind == 'real'
        with span_eager('mesh.r2c', shape=[int(s) for s in self.shape]):
            return Field(self.pm.r2c(self.value), self.pm, 'complex',
                         self.attrs)

    def c2r(self):
        assert self.kind == 'complex'
        with span_eager('mesh.c2r', shape=[int(s) for s in self.shape]):
            return Field(self.pm.c2r(self.value), self.pm, 'real',
                         self.attrs)

    def apply(self, func, kind=None):
        """Apply ``func(coords, value) -> value`` immediately with the
        coordinate arrays implied by ``kind`` (see
        :meth:`MeshSource.apply` for the deferred version)."""
        if kind is None and isinstance(func, MeshFilter):
            kind = func.kind
        if kind is None:
            kind = 'wavenumber' if self.kind == 'complex' else 'relative'
        coords = _coords_for(self.pm, self.kind, kind)
        return Field(func(coords, self.value), self.pm, self.kind,
                     self.attrs)

    def csum(self):
        """Collective sum (global — a plain sum over the global array)."""
        return self.value.sum()

    def cmean(self):
        return self.value.mean()

    def readout(self, pos, resampler=None):
        assert self.kind == 'real'
        return self.pm.readout(self.value, pos, resampler=resampler)

    def preview(self, axes=None):
        """Project the (real) field onto ``axes`` by summing the others;
        returns host numpy (reference: base/mesh.py:340)."""
        v = self.value
        if axes is None:
            return as_numpy(v)
        axes = tuple(axes) if np.iterable(axes) else (axes,)
        other = tuple(i for i in range(3) if i not in axes)
        return as_numpy(v.sum(axis=other))

    def numpy(self):
        return as_numpy(self.value)


def _coords_for(pm, field_kind, coord_kind):
    """Coordinate arrays for an apply action (reference kinds at
    base/mesh.py:132-176)."""
    if field_kind == 'complex':
        if coord_kind == 'wavenumber':
            return pm.k_list()
        if coord_kind == 'circular':
            return pm.k_list(circular=True)
        if coord_kind == 'index':
            return pm.i_list_complex()
        raise ValueError("invalid coord kind %r for a complex field "
                         "(wavenumber|circular|index)" % coord_kind)
    else:
        if coord_kind in ('relative', 'untransformed'):
            return pm.x_list()
        if coord_kind == 'index':
            N0, N1, N2 = pm.shape_real
            return [jnp.arange(N0).reshape(N0, 1, 1),
                    jnp.arange(N1).reshape(1, N1, 1),
                    jnp.arange(N2).reshape(1, 1, N2)]
        raise ValueError("invalid coord kind %r for a real field "
                         "(relative|index)" % coord_kind)


class MeshFilter(object):
    """Base class for named mesh filters (reference base/mesh.py
    MeshFilter): subclasses declare the coordinate ``kind`` and field
    ``mode`` they operate in and implement ``filter(coords, value)``;
    instances can then be passed to :meth:`MeshSource.apply` /
    :meth:`Field.apply` without repeating kind/mode at the call
    site."""

    kind = None
    mode = None

    def filter(self, coords, value):
        raise NotImplementedError

    def __call__(self, coords, value):
        return self.filter(coords, value)


class MeshSource(object):
    """Base class: a recipe for a distributed 3-D field.

    Subclasses implement ``to_real_field()`` or ``to_complex_field()``;
    users call :meth:`compute` (alias :meth:`paint`) with
    ``mode='real'|'complex'``, optionally after queueing transfer
    functions with :meth:`apply`.
    """

    def __init__(self, Nmesh, BoxSize, dtype='f4', comm=None):
        comm = CurrentMesh.resolve(comm)
        self.comm = comm
        self.pm = ParticleMesh(Nmesh, BoxSize, dtype=dtype, comm=comm)
        if not hasattr(self, 'attrs'):
            self.attrs = {}
        self.attrs['Nmesh'] = self.pm.Nmesh.copy()
        self.attrs['BoxSize'] = self.pm.BoxSize.copy()
        self._actions = []

    @property
    def actions(self):
        """The queue of deferred (mode, func, kind) transfer actions."""
        return self._actions

    def view(self):
        """A view MeshSource whose computation is owned by ``self``
        (reference base/mesh.py:82)."""
        import copy
        view = copy.copy(self)
        view.attrs = self.attrs.copy()
        view.base = self
        return view

    def apply(self, func, kind='wavenumber', mode='complex'):
        """Return a *view* of this mesh with ``func`` appended to the
        action queue (reference base/mesh.py:118-176). ``func`` takes
        ``(coords, value)`` and returns the new value; it runs on the
        ``mode``-space field with ``kind`` coordinates. A
        :class:`MeshFilter` instance carries its own kind/mode."""
        import copy
        if isinstance(func, MeshFilter):
            kind = func.kind if func.kind is not None else kind
            mode = func.mode if func.mode is not None else mode
        view = copy.copy(self)
        view.attrs = self.attrs.copy()
        view._actions = self._actions + [(mode, func, kind)]
        return view

    # subclasses implement one of these -----------------------------------

    def to_real_field(self):
        return NotImplemented

    def to_complex_field(self):
        return NotImplemented

    def to_field(self, mode='real'):
        if mode == 'real':
            real = self.to_real_field()
            if real is NotImplemented:
                real = self.to_complex_field().c2r()
            return real
        elif mode == 'complex':
            cplx = self.to_complex_field()
            if cplx is NotImplemented:
                cplx = self.to_real_field().r2c()
            return cplx
        raise ValueError("mode must be 'real' or 'complex'")

    def compute(self, mode='real', Nmesh=None):
        """Produce the field, running the action pipeline (alternating
        r2c/c2r as needed) and optionally resampling to ``Nmesh``
        (reference paint pipeline, base/mesh.py:246-338)."""
        if mode not in ('real', 'complex'):
            raise ValueError("mode must be 'real' or 'complex'")

        with span_eager('mesh.compute', mode=mode,
                        cls=type(self).__name__,
                        nactions=len(self.actions)):
            # decide the starting representation: prefer the native one
            native_real = (type(self).to_real_field
                           is not MeshSource.to_real_field)
            field = self.to_field('real' if native_real else 'complex')

            for amode, func, kind in self.actions:
                if amode == 'real' and field.kind != 'real':
                    field = field.c2r()
                elif amode == 'complex' and field.kind != 'complex':
                    field = field.r2c()
                field = field.apply(func, kind=kind)

            if Nmesh is not None and any(
                    np.atleast_1d(Nmesh) != self.pm.Nmesh):
                field = self._resample(field, Nmesh)

            if mode == 'real' and field.kind != 'real':
                field = field.c2r()
            elif mode == 'complex' and field.kind != 'complex':
                field = field.r2c()
            if enabled():
                # per-device live-buffer watermarks at the end of each
                # compute phase: the gauge maxima answer "what was HBM
                # holding when it OOMed" post-mortem
                device_watermarks()
            return field

    paint = compute

    def preview(self, axes=None, Nmesh=None, root=0):
        """Project the (optionally ``Nmesh``-downsampled) real field
        onto ``axes`` and return host numpy (reference
        base/mesh.py:340-383). ``root`` is accepted for signature
        parity; global arrays make the result identical on every
        process, so no broadcast is needed."""
        return self.compute(mode='real', Nmesh=Nmesh).preview(axes=axes)

    def _resample(self, field, Nmesh):
        """Fourier-space resample to a new mesh size: mode truncation
        (down) or zero-padding (up), reference base/mesh.py:320-330."""
        if field.kind != 'complex':
            field = field.r2c()
        pm2 = self.pm.reshape(Nmesh)
        src, dst = self.pm, pm2
        a = field.value
        # build the destination spectrum by gathering the overlapping
        # modes; operate on host-safe index arithmetic with jnp.take
        sN0, sN1, sN2 = src.shape_real
        dN0, dN1, dN2 = dst.shape_real
        n1 = min(sN1, dN1)
        n0 = min(sN0, dN0)
        nz = min(sN2 // 2 + 1, dN2 // 2 + 1)

        def modes(n_dst, n_src, count):
            # signed mode index list of the destination's first `count`
            # positive + matching negative frequencies in source ordering
            half = (count + 1) // 2
            pos = jnp.arange(half)
            neg = jnp.arange(-(count - half), 0) % n_src
            return jnp.concatenate([pos, neg])

        i1 = modes(dN1, sN1, n1)
        i0 = modes(dN0, sN0, n0)
        sub = jnp.take(jnp.take(a[:, :, :nz], i1, axis=0), i0, axis=1)
        out = jnp.zeros(dst.shape_complex, dtype=a.dtype)
        o1 = modes(dN1, dN1, n1)
        o0 = modes(dN0, dN0, n0)
        out = out.at[jnp.ix_(o1, o0, jnp.arange(nz))].set(sub)
        f2 = Field(out, pm2, 'complex', field.attrs)
        return f2

    def save(self, output, dataset='Field', mode='real'):
        """Persist the computed field (+ attrs) to disk; see
        :mod:`nbodykit_tpu.io.bigfile` for the format. Reference:
        base/mesh.py:367-412."""
        from ..io.bigfile import BigFileWriter
        field = self.compute(mode=mode)
        with BigFileWriter(output, create=True) as ff:
            attrs = dict(self.attrs)
            attrs['ndarray.shape'] = np.asarray(field.shape)
            ff.write(dataset, as_numpy(field.value).reshape(-1), attrs=attrs)

    def to_mesh(self):
        return self

    def __len__(self):
        return 0


class FieldMesh(MeshSource):
    """Wrap an existing field (array or Field) as a MeshSource
    (reference: nbodykit/source/mesh/field.py:6)."""

    def __init__(self, field, BoxSize=None, comm=None):
        if isinstance(field, Field):
            pm = field.pm
            self.attrs = dict(field.attrs)
            MeshSource.__init__(self, pm.Nmesh, pm.BoxSize,
                                dtype=pm.dtype.str, comm=pm.comm)
            self._field = field
        else:
            field = jnp.asarray(field)
            if BoxSize is None:
                raise ValueError("BoxSize is required when wrapping a "
                                 "plain array")
            if jnp.iscomplexobj(field):
                raise ValueError("pass complex fields as Field objects "
                                 "(the layout is ambiguous)")
            MeshSource.__init__(self, field.shape, BoxSize,
                                dtype=field.dtype.str, comm=comm)
            self._field = Field(field, self.pm, 'real')

    def to_real_field(self):
        f = self._field
        return f if f.kind == 'real' else f.c2r()

    def to_complex_field(self):
        f = self._field
        return f if f.kind == 'complex' else f.r2c()
