"""Distributed data containers: CatalogSource (particle tables) and
MeshSource (3-D fields) — the L2 layer of SURVEY.md §1, re-designed for
global sharded jax.Arrays instead of rank-local MPI blocks."""

from .catalog import CatalogSource, CatalogSourceBase, column
from .mesh import MeshSource, Field, FieldMesh

__all__ = ['CatalogSource', 'CatalogSourceBase', 'column',
           'MeshSource', 'Field', 'FieldMesh']
