"""CatalogSource: the distributed particle-table abstraction.

Reference: ``nbodykit/base/catalog.py:168,875``. A catalog is a table of
particle columns with metadata; the reference implements it as rank-local
dask arrays over MPI. Here a column is a *global* jax.Array (sharded over
the device mesh on its leading axis when one is active), so collective
sizes/slices/sorts are ordinary jnp ops and XLA inserts the collectives.

Laziness: the reference's dask-lazy columns become (a) hardcolumns
declared with the ``@column`` decorator — computed on first access and
cached — and (b) whatever jit fusion downstream consumers apply. The
``attrs`` reproducibility convention carries over verbatim.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..parallel.runtime import CurrentMesh, shard_leading, mesh_size
from ..utils import as_numpy


def column(name=None):
    """Decorator declaring a hardcolumn on a CatalogSource subclass
    (reference: base/catalog.py:97). The method computes the column on
    first access; the result is cached."""
    def wrapper(func):
        func.column_name = name or func.__name__
        return func
    if callable(name):
        func, name = name, name.__name__
        return wrapper(func)
    return wrapper


def find_columns(cls):
    """Collect hardcolumn methods from a class hierarchy (reference's
    ColumnFinder metaclass, base/catalog.py:127)."""
    hard = {}
    for klass in reversed(cls.__mro__):
        for value in vars(klass).values():
            if callable(value) and hasattr(value, 'column_name'):
                hard[value.column_name] = value
    return hard


class CatalogSourceBase(object):
    """Dict-like base: column get/set, attrs, views, mesh conversion."""

    logger = logging.getLogger('CatalogSource')

    def __init__(self, comm=None):
        self.comm = CurrentMesh.resolve(comm)
        if not hasattr(self, 'attrs'):
            self.attrs = {}
        self._columns = {}     # explicitly set columns
        self._cache = {}       # evaluated hardcolumns

    # -- column access ----------------------------------------------------

    @property
    def hardcolumns(self):
        return sorted(find_columns(type(self)))

    @property
    def columns(self):
        return sorted(set(self.hardcolumns) | set(self._columns))

    def __contains__(self, col):
        return col in self.columns

    def __getitem__(self, sel):
        if isinstance(sel, str):
            if sel in self._columns:
                return self._columns[sel]
            if sel in self._cache:
                return self._cache[sel]
            hard = find_columns(type(self))
            if sel in hard:
                val = hard[sel](self)
                val = self._promote(val)
                self._cache[sel] = val
                return val
            raise KeyError("column '%s' not found; available: %s"
                           % (sel, self.columns))
        # boolean-mask or slice selection -> new catalog view
        return self._select(sel)

    def __setitem__(self, col, value):
        value = self._promote(value, col=col)
        self._columns[col] = value

    def __delitem__(self, col):
        if col in self._columns:
            del self._columns[col]
        elif col in self.hardcolumns:
            raise ValueError("cannot delete hardcolumn '%s'" % col)
        else:
            raise KeyError(col)

    def _promote(self, value, col=None):
        """Coerce a column value to a global device array of length
        self.size (scalars broadcast)."""
        size = len(self)
        if np.isscalar(value):
            value = jnp.full((size,), value)
        else:
            value = jnp.asarray(value)
        if value.shape[0] != size:
            raise ValueError(
                "size mismatch setting column%s: got %d, catalog has %d"
                % ('' if col is None else " '%s'" % col, value.shape[0],
                   size))
        nproc = mesh_size(self.comm) if self.comm is not None else 1
        if nproc > 1 and size % nproc == 0:
            # evenly shard over the device mesh; ragged sizes stay on the
            # default device until a paint/readout exchange distributes
            # them (exchange_by_dest pads internally)
            value = shard_leading(self.comm, value)
        return value

    def compute(self, *args):
        """Materialize columns (the reference's dask barrier,
        base/catalog.py:705); arrays are already concrete, so this just
        resolves names."""
        out = [self[a] if isinstance(a, str) else a for a in args]
        return out[0] if len(out) == 1 else out

    def get_hardcolumn(self, col):
        return self[col]

    # -- views / selection -------------------------------------------------

    def _select(self, sel):
        """Boolean-mask / slice selection returning an ArrayCatalog-like
        view with all columns materialized and sliced."""
        from ..source.catalog.array import ArrayCatalog
        if isinstance(sel, (slice, np.ndarray, jnp.ndarray, list)):
            data = {}
            for col in self.columns:
                data[col] = self[col][sel]
            cat = ArrayCatalog(data, comm=self.comm, **self.attrs)
            return cat
        raise KeyError("invalid catalog selection %r" % (sel,))

    def view(self, type=None):
        """A re-typed view sharing column *data* (reference
        base/catalog.py:727). The column dicts are shallow-copied so
        adding derived columns on the view does not pollute the base."""
        type = type or self.__class__
        obj = object.__new__(type)
        obj.__dict__.update(self.__dict__)
        obj._columns = dict(self._columns)
        obj._cache = dict(self._cache)
        obj._size = len(self)
        obj.base = self
        return obj

    def __finalize__(self, other):
        self.attrs.update(getattr(other, 'attrs', {}))
        return self

    @staticmethod
    def make_column(array):
        """Convert an array-like to a column array (reference
        base/catalog.py:193 returns a dask array; columns here are
        global device arrays)."""
        return jnp.asarray(array)

    @staticmethod
    def create_instance(cls, comm=None):
        """A bare, empty instance of ``cls`` with only the base state
        initialized (reference base/catalog.py:223)."""
        obj = object.__new__(cls)
        CatalogSourceBase.__init__(obj, comm)
        return obj

    def copy(self):
        """A shallow copy holding references to all current columns,
        with a decoupled ``attrs`` (reference base/catalog.py:474)."""
        toret = CatalogSourceBase.create_instance(self.__class__,
                                                  comm=self.comm)
        toret._size = len(self)
        toret.__finalize__(self)
        for col in self.columns:
            toret[col] = self[col]
        toret.attrs = dict(self.attrs)
        return toret

    def persist(self, columns=None):
        """An ArrayCatalog with the selected columns materialized
        (reference base/catalog.py:1078; columns here are already
        device-resident, so this just snapshots them)."""
        from ..source.catalog.array import ArrayCatalog
        cols = {key: self[key] for key in (columns or self.columns)}
        c = ArrayCatalog(cols, comm=self.comm)
        c.attrs.update(self.attrs)
        return c

    def to_subvolumes(self, domain=None, position='Position',
                      columns=None):
        """Spatially domain-decomposed copy of this catalog (reference
        base/catalog.py:754 -> SubVolumesCatalog)."""
        from ..source.catalog.subvolumes import SubVolumesCatalog
        return SubVolumesCatalog(self, domain=domain,
                                 position=position, columns=columns)

    # -- conversion --------------------------------------------------------

    def to_mesh(self, Nmesh=None, BoxSize=None, dtype=None, interlaced=False,
                compensated=False, resampler='cic', position='Position',
                weight='Weight', value='Value', selection='Selection'):
        """Make a CatalogMesh that paints this catalog (reference
        base/catalog.py:787-873)."""
        from ..source.mesh.catalog import CatalogMesh
        from .. import _global_options

        if Nmesh is None:
            Nmesh = self.attrs.get('Nmesh', None)
            if Nmesh is None:
                raise ValueError("cannot infer Nmesh; pass it to to_mesh "
                                 "or set attrs['Nmesh']")
        if BoxSize is None:
            BoxSize = self.attrs.get('BoxSize', None)
            if BoxSize is None:
                raise ValueError("cannot infer BoxSize; pass it to "
                                 "to_mesh or set attrs['BoxSize']")
        if dtype is None:
            dtype = _global_options['mesh_dtype']
            if dtype == 'auto':
                # the tune cache's measured storage winner for this
                # mesh class, else 'f4' (resolve.py cold-cache rule)
                from ..tune.resolve import resolve_mesh_dtype
                dtype = resolve_mesh_dtype(nmesh=Nmesh)
        return CatalogMesh(self, Nmesh=Nmesh, BoxSize=BoxSize, dtype=dtype,
                           interlaced=interlaced, compensated=compensated,
                           resampler=resampler, position=position,
                           weight=weight, value=value, selection=selection)

    def save(self, output, columns=None, dataset=None, datasets=None,
             header='Header'):
        """Persist columns + attrs (reference base/catalog.py:562 writes
        bigfile; same format here via io.bigfile)."""
        from ..io.bigfile import BigFileWriter
        if columns is None:
            columns = self.columns
        if datasets is None:
            datasets = columns
        with BigFileWriter(output, create=True) as ff:
            ff.write_attrs(header, self.attrs)
            for col, ds in zip(columns, datasets):
                ff.write(ds, as_numpy(self[col]))

    def read(self, columns):
        return [self[col] for col in columns]


class CatalogSource(CatalogSourceBase):
    """A catalog with a definite global size and the default
    Selection/Weight/Value columns (reference base/catalog.py:875)."""

    def __init__(self, size, comm=None):
        CatalogSourceBase.__init__(self, comm=comm)
        self._size = int(size)

    def __len__(self):
        return self._size

    @property
    def size(self):
        return self._size

    @property
    def csize(self):
        """Collective size == global size (columns are global arrays)."""
        return self._size

    def __repr__(self):
        return "%s(size=%d)" % (self.__class__.__name__, self._size)

    # default columns (reference base/catalog.py:1166-1216)

    @column
    def Selection(self):
        return jnp.ones(self._size, dtype=bool)

    @column
    def Weight(self):
        return jnp.ones(self._size)

    @column
    def Value(self):
        return jnp.ones(self._size)

    @column
    def Index(self):
        return jnp.arange(self._size, dtype=jnp.int64)

    # -- global ops --------------------------------------------------------

    def gslice(self, start, stop, step=1):
        """Global slice (reference base/catalog.py:1013)."""
        return self._select(slice(start, stop, step))

    def sort(self, keys, reverse=False, usecols=None):
        """Globally sort by one or more columns (reference
        base/catalog.py:1100 via mpsort).

        On a multi-device mesh every combination of multi-key and
        ``reverse`` runs through the distributed sample sort
        (parallel/sort.py): columns map to order-preserving unsigned
        keys (bit-flipped for descending), and multiple keys resolve
        via least-significant-first stable passes that carry the
        not-yet-sorted keys and the permutation as all_to_all payload —
        no global argsort of a gathered key ever appears in the
        compiled program. Ties keep their original catalog order (also
        under ``reverse``, where the reference's gather-argsort-flip
        would invert them)."""
        if isinstance(keys, str):
            keys = [keys]
        cols = usecols or self.columns
        from ..source.catalog.array import ArrayCatalog
        if self.comm is not None and mesh_size(self.comm) > 1:
            from ..parallel.sort import dist_sort, sortable_key
            cur = [sortable_key(self[k], reverse) for k in keys]
            perm = jnp.arange(self._size)
            for j in range(len(cur) - 1, -1, -1):
                payload = cur[:j] + [perm]
                _, out = dist_sort(cur[j], payload, self.comm)
                cur, perm = out[:j], out[j]
            order = perm
        else:
            order = jnp.argsort(self[keys[-1]])
            for key in reversed(keys[:-1]):
                order = order[jnp.argsort(self[key][order],
                                          stable=True)]
            if reverse:
                order = order[::-1]
        data = {c: self[c][order] for c in cols}
        return ArrayCatalog(data, comm=self.comm, **self.attrs)

    def concatenate(self, *others):
        from ..transform import ConcatenateSources
        return ConcatenateSources(self, *others)
