"""Small shared utilities (reference analog: nbodykit/utils.py).

The distributed-collective helpers of the reference (GatherArray/
ScatterArray, utils.py:128,249) are unnecessary here — global jax.Arrays
already are the gathered view — but JSON encoding of numpy-laden attrs
dicts (utils.py:381-489) and a few array helpers carry over.
"""

import json

import numpy as np
import jax


def is_mxu_backend():
    """True on MXU hardware (TPU, incl. the axon tunnel's platform
    name) — the shared dispatch predicate for kernels with a
    TPU-shaped and a CPU-shaped implementation (histogram, paint
    bucketing, radix ordering, exchange routing)."""
    try:
        return jax.default_backend() in ('tpu', 'axon')
    except Exception:
        return False


def working_dtype(dt='f8'):
    """The widest available dtype no wider than ``dt``: the 64-bit
    float/complex/int types when x64 is enabled, else their 32-bit
    counterparts — *without* the per-callsite "requested dtype float64
    ... truncated" warning that a direct ``jnp.asarray(x, jnp.float64)``
    emits on TPU (no f64 hardware). Use for 'compute in the best
    precision we have' sites."""
    import jax
    dt = np.dtype(dt)
    if dt.itemsize == 8 * (2 if dt.kind == 'c' else 1) \
            and dt.kind in 'fciu' and not jax.config.jax_enable_x64:
        return np.dtype({'f': 'f4', 'c': 'c8', 'i': 'i4',
                         'u': 'u4'}[dt.kind])
    return dt


def mesh_storage_dtype(dt='f4'):
    """Resolve a mesh-buffer STORAGE dtype token, including the
    ``'bf16'`` half-storage request that ``np.dtype`` cannot parse.

    ``'bf16'``/``'bfloat16'`` resolves to the ml_dtypes-registered
    bfloat16 (itemsize 2 — half the f4 mesh bytes; docs/PERF.md
    "Halving the bytes").  Everything else goes through
    :func:`working_dtype`, so f8 requests still demote to f4 when x64
    is off.  Storage dtype only: compute (weights, FFT butterflies,
    readout results) stays f32 — callers re-widen immediately
    (NBK701/702 contracts, docs/LINT.md)."""
    if str(dt).lower() in ('bf16', 'bfloat16'):
        import jax.numpy as jnp
        return np.dtype(jnp.bfloat16)
    return working_dtype(dt)


def is_narrow_float(dt):
    """True when ``dt`` is a sub-f32 float storage dtype (bfloat16 or
    float16) — the predicate behind every 'compute wide, store narrow'
    branch in pmesh/ops.paint."""
    dt = np.dtype(dt)
    return dt.kind in 'fV' and dt.itemsize == 2


def as_numpy(arr):
    """Fetch a jax array to host numpy.

    Complex arrays are moved as real/imag pairs: the axon TPU runtime
    does not implement complex-dtype host transfers (a failed attempt
    poisons the process), while in-graph complex math is fine.
    """
    arr = jax.numpy.asarray(arr)
    if jax.numpy.iscomplexobj(arr):
        return np.asarray(arr.real) + 1j * np.asarray(arr.imag)
    return np.asarray(arr)


def to_device_complex(arr_np, sharding=None):
    """Place a host complex array on device via a real/imag pair
    (inverse of :func:`as_numpy` for complex inputs)."""
    re = jax.device_put(np.ascontiguousarray(arr_np.real), sharding)
    im = jax.device_put(np.ascontiguousarray(arr_np.imag), sharding)
    return jax.lax.complex(re, im)


class JSONEncoder(json.JSONEncoder):
    """JSON encoder handling numpy scalars/arrays and complex values,
    mirroring the reference's persistence format (nbodykit/utils.py:381):
    arrays become {'__dtype__': ..., '__shape__': ..., '__data__': ...}.
    """

    def default(self, obj):
        if isinstance(obj, jax.Array):
            obj = as_numpy(obj)
        if isinstance(obj, np.generic):
            obj = obj.item()
        if isinstance(obj, complex):
            return {'__complex__': [obj.real, obj.imag]}
        if isinstance(obj, np.ndarray):
            if obj.dtype.kind == 'c':
                data = np.stack([obj.real, obj.imag], axis=-1).tolist()
            elif obj.dtype.kind == 'V':  # structured
                data = {name: self.default(np.ascontiguousarray(obj[name]))
                        for name in obj.dtype.names}
            else:
                data = obj.tolist()
            return {'__dtype__': obj.dtype.str if obj.dtype.kind != 'V'
                    else [list(x) for x in obj.dtype.descr],
                    '__shape__': list(obj.shape),
                    '__data__': data}
        if isinstance(obj, (bool, int, float, str)) or obj is None:
            return obj
        try:
            return json.JSONEncoder.default(self, obj)
        except TypeError:
            return str(obj)


def json_object_hook(value):
    """Decoder hook inverting :class:`JSONEncoder`."""
    if '__complex__' in value:
        re, im = value['__complex__']
        return complex(re, im)
    if '__dtype__' in value:
        dtype = value['__dtype__']
        shape = tuple(value['__shape__'])
        data = value['__data__']
        if isinstance(dtype, list):  # structured
            fields = []
            for f in (tuple(x) for x in dtype):
                # reference files may carry (name, type, shape) triples
                # (nbodykit/utils.py:441-448 accepts both arities)
                if len(f) == 3:
                    fields.append((str(f[0]), str(f[1]), tuple(f[2])))
                else:
                    fields.append((str(f[0]), str(f[1])))
            dtype = np.dtype(fields)
            if isinstance(data, dict):
                # our column-oriented layout
                arr = np.empty(shape, dtype=dtype)
                for name in dtype.names:
                    arr[name] = json_object_hook(data[name]) \
                        if isinstance(data[name], dict) else data[name]
                return arr
            # reference row-oriented layout: nested lists down to the
            # record level, each record a list of field values
            # (written by nbodykit/utils.py JSONEncoder, decoded at
            # utils.py:450-461) — np.array needs tuples at that level
            def _rows_to_tuples(d, depth):
                if depth > 0:
                    return [_rows_to_tuples(i, depth - 1) for i in d]
                return tuple(d)
            return np.array(_rows_to_tuples(data, len(shape)),
                            dtype=dtype)
        dt = np.dtype(str(dtype))
        if dt.kind == 'c':
            a = np.asarray(data, dtype='f8')
            return (a[..., 0] + 1j * a[..., 1]).astype(dt).reshape(shape)
        return np.asarray(data, dtype=dt).reshape(shape)
    return value


class JSONDecoder(json.JSONDecoder):
    def __init__(self, *args, **kwargs):
        kwargs['object_hook'] = json_object_hook
        json.JSONDecoder.__init__(self, *args, **kwargs)


def attrs_to_dict(attrs, prefix=''):
    """Flatten an attrs dict with a prefix (reference analog used when
    saving meta-data to file headers)."""
    return {prefix + k: v for k, v in attrs.items()}


def is_structured_array(arr):
    """True if ``arr`` is a numpy structured array (reference
    utils.py helper)."""
    return getattr(getattr(arr, 'dtype', None), 'names', None) is not None


def split_size_3d(s):
    """Split ``s`` into (a, b, c) with a*b*c == s and a <= b <= c —
    the 3-D process-grid factorization (reference utils.py:84-113),
    used here to shape subvolume domain grids."""
    a = int(s ** (1.0 / 3)) + 1
    while a > 1 and s % a:
        a -= 1
    rest = s // a
    b = int(rest ** 0.5) + 1
    while b > 1 and rest % b:
        b -= 1
    c = rest // b
    return tuple(sorted((a, b, c)))


def get_data_bounds(data, comm=None, selection=None):
    """Global (min, max) of an array along the first axis (reference
    utils.py:23). Columns are global device arrays, so this is a plain
    reduction (jit-fused; no chunking needed)."""
    import jax.numpy as jnp
    arr = jnp.asarray(data)
    if selection is not None:
        sel = jnp.asarray(selection, bool)
        if jnp.issubdtype(arr.dtype, jnp.integer):
            big = jnp.asarray(jnp.iinfo(arr.dtype).max, arr.dtype)
            small = jnp.asarray(jnp.iinfo(arr.dtype).min, arr.dtype)
        else:
            big, small = (jnp.asarray(np.inf, arr.dtype),
                          jnp.asarray(-np.inf, arr.dtype))
        mask = sel[:, None] if arr.ndim > 1 else sel
        lo = jnp.where(mask, arr, big)
        hi = jnp.where(mask, arr, small)
        return (np.asarray(lo.min(axis=0)), np.asarray(hi.max(axis=0)))
    return (np.asarray(arr.min(axis=0)), np.asarray(arr.max(axis=0)))


def GatherArray(data, comm=None, root=0):
    """Materialize a (possibly device-sharded) array on the host
    (reference utils.py:128 gathers rank-local pieces to root; columns
    here are global device arrays, so the gather is a device-to-host
    transfer — complex-safe via :func:`as_numpy`)."""
    return as_numpy(data)


def ScatterArray(data, comm=None, root=0, counts=None):
    """Distribute a host array onto the active device mesh, sharded on
    its leading axis (reference utils.py:249 scatters from root; here
    the inverse of :func:`GatherArray`)."""
    import jax.numpy as jnp
    from .parallel.runtime import CurrentMesh, shard_leading
    if counts is not None:
        raise ValueError("explicit per-device counts are not "
                         "supported: global arrays shard evenly")
    arr = jnp.asarray(data)
    mesh = CurrentMesh.get()
    if mesh is not None and len(mesh.devices) > 1:
        arr = shard_leading(mesh, arr)
    return arr


class captured_output(object):
    """Context manager capturing Python-level stdout/stderr (reference
    utils.py:513 captures C-level output via wurlitzer for its C
    extensions; the compute here is in-process XLA, so Python streams
    are the relevant ones). Yields (stdout, stderr) StringIO."""

    def __enter__(self):
        import io as _io
        import sys
        self._sys = sys
        self._old = (sys.stdout, sys.stderr)
        self.stdout = _io.StringIO()
        self.stderr = _io.StringIO()
        sys.stdout, sys.stderr = self.stdout, self.stderr
        return self.stdout, self.stderr

    def __exit__(self, *exc):
        self._sys.stdout, self._sys.stderr = self._old
        return False
