"""The streaming sharded ingestion plane: catalogs as data.

Real surveys arrive as files, not seeds.  This package turns a file
path into a painted device mesh without ever materializing the catalog
on any host:

- :mod:`.rules` — the partition-rule tree mapping named catalog
  columns onto PartitionSpecs of the live device mesh;
- :mod:`.stream` — chunked reader -> double-buffered ``device_put``
  -> paint, with ``ingest.*`` spans/counters, chunk-boundary
  checkpoints, and the :class:`DataRef` request pointer;
- :mod:`.cache` — the content-addressed on-device catalog cache
  (sha256 over column bytes + partition layout, LRU eviction priced
  through ``memory_plan``), so N requests against one survey pay
  ingestion once.

See docs/INGEST.md for the rule grammar, cache keying and the overlap
model.
"""

from .cache import (CatalogCache, CatalogEntry, fold_digest,
                    layout_token)
from .rules import (DEFAULT_RULES, ROWS, make_shard_and_gather_fns,
                    match_partition_rules, partition_specs,
                    resolve_partition_spec)
from .stream import (ArraySource, DataRef, IngestError, ingest_catalog,
                     paint_cached, paint_chunks, probe_ref,
                     resolve_chunk_rows)

__all__ = [
    'ArraySource', 'CatalogCache', 'CatalogEntry', 'DataRef',
    'DEFAULT_RULES', 'IngestError', 'ROWS', 'fold_digest',
    'ingest_catalog', 'layout_token', 'make_shard_and_gather_fns',
    'match_partition_rules', 'paint_cached', 'paint_chunks',
    'partition_specs', 'probe_ref', 'resolve_chunk_rows',
    'resolve_partition_spec',
]
