"""The content-addressed on-device catalog cache.

N requests against one survey must pay ingestion ONCE: after a cold
ingest the sharded column chunks stay resident on the device mesh,
keyed by a content address — sha256 over the catalog's column bytes
AND the partition layout (columns, dtypes, chunk_rows, device count,
spec templates).  Two requests whose bytes or layout differ can never
collide; two requests that agree get the same device arrays back and
go straight to paint.

Lookups are two-level, the git-index discipline:

- the **fingerprint** (realpath, size, mtime_ns, columns, layout) is
  the O(1) stat-cheap front door — a changed file bumps size/mtime
  and misses;
- the **content digest** is the entry's identity, computed
  incrementally per chunk during the cold ingest (a Merkle fold over
  per-chunk sha256s — resumable across chunk-boundary checkpoints),
  so a hit never re-reads the file.

Eviction is LRU, priced through :func:`nbodykit_tpu.pmesh.memory_plan`:
the caller passes a ``fits(resident_bytes)`` predicate built from the
incoming request's plan (``catalog_bytes=resident + incoming``), and
the cache evicts least-recently-used entries until the predicate holds
(or a hard ``budget_bytes`` cap is honored, whichever binds first).
Counters: ``ingest.cache.hits`` / ``.misses`` / ``.evictions``;
``ingest.cache.bytes`` gauges residency — the doctor WARNs on thrash
(evictions > hits).
"""

import hashlib
import json
import threading
from collections import OrderedDict

from ..diagnostics import counter, gauge


def layout_token(columns, dtypes, chunk_rows, ndevices, templates):
    """The canonical partition-layout string hashed into the content
    address: what the device arrays LOOK like, independent of which
    request asked."""
    return json.dumps({
        'columns': list(columns),
        'dtypes': [str(d) for d in dtypes],
        'chunk_rows': int(chunk_rows),
        'ndevices': int(ndevices),
        'specs': {k: list(map(str, v)) for k, v in
                  sorted(templates.items())},
    }, sort_keys=True)


def fold_digest(layout, chunk_digests):
    """The content address: sha256 over the layout token plus the
    ordered per-chunk column-byte digests (Merkle fold — a resumed
    ingest carries the completed chunks' digests in its checkpoint
    and continues the fold without re-reading them)."""
    h = hashlib.sha256(layout.encode())
    for d in chunk_digests:
        h.update(bytes.fromhex(d) if isinstance(d, str) else d)
    return h.hexdigest()


class CatalogEntry(object):
    """One resident catalog: the sharded per-chunk device arrays plus
    the identity that admitted them."""

    __slots__ = ('digest', 'layout', 'chunks', 'nrows', 'nbytes',
                 'chunk_rows')

    def __init__(self, digest, layout, chunks, nrows, chunk_rows):
        self.digest = digest
        self.layout = layout
        # [(pos_dev, mass_dev, nvalid)] or, with a mapped Velocity
        # column, [(pos_dev, mass_dev, nvalid, vel_dev)] — resident
        # bytes price every device array in the chunk either way
        self.chunks = list(chunks)
        self.nrows = int(nrows)
        self.chunk_rows = int(chunk_rows)
        self.nbytes = int(sum(
            sum(int(getattr(a, 'nbytes', 0)) for a in c)
            for c in self.chunks))


class CatalogCache(object):
    """LRU map fingerprint -> :class:`CatalogEntry` (device-resident).

    ``budget_bytes`` is an optional hard cap on summed entry bytes;
    the per-request ``fits`` predicate passed to :meth:`ensure_room`
    carries the memory_plan pricing.  Thread-safe: serve workers share
    one cache per sub-mesh.
    """

    def __init__(self, budget_bytes=None):
        self.budget_bytes = None if budget_bytes is None \
            else int(budget_bytes)
        self._lock = threading.Lock()
        self._entries = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def resident_bytes(self):
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def lookup(self, fingerprint):
        """The resident entry for a fingerprint (LRU-touched), or
        None.  Every call counts as a hit or a miss."""
        with self._lock:
            entry = self._entries.get(fingerprint)
            if entry is not None:
                self._entries.move_to_end(fingerprint)
                self.hits += 1
            else:
                self.misses += 1
        counter('ingest.cache.hits' if entry is not None
                else 'ingest.cache.misses').add(1)
        return entry

    def ensure_room(self, incoming_bytes, fits=None):
        """Evict LRU entries until ``incoming_bytes`` more fit: under
        the hard cap (when set) AND under ``fits(resident + incoming)``
        (when given — the memory_plan predicate).  Returns the number
        evicted.  An empty cache that still does not fit is the
        caller's admission problem, not an eviction loop."""
        evicted = 0
        with self._lock:
            while self._entries:
                resident = sum(e.nbytes for e in self._entries.values())
                over_cap = (self.budget_bytes is not None
                            and resident + incoming_bytes
                            > self.budget_bytes)
                over_plan = (fits is not None
                             and not fits(resident + incoming_bytes))
                if not (over_cap or over_plan):
                    break
                self._entries.popitem(last=False)
                self.evictions += 1
                evicted += 1
        if evicted:
            counter('ingest.cache.evictions').add(evicted)
            gauge('ingest.cache.bytes').set(self.resident_bytes)
        return evicted

    def put(self, fingerprint, entry, fits=None):
        """Insert (evicting for room first); returns ``entry``."""
        self.ensure_room(entry.nbytes, fits=fits)
        with self._lock:
            self._entries[fingerprint] = entry
            self._entries.move_to_end(fingerprint)
            resident = sum(e.nbytes for e in self._entries.values())
        gauge('ingest.cache.bytes').set(resident)
        return entry

    def clear(self):
        with self._lock:
            self._entries.clear()
        gauge('ingest.cache.bytes').set(0)

    def stats(self):
        with self._lock:
            return {'entries': len(self._entries),
                    'resident_bytes': sum(e.nbytes for e in
                                          self._entries.values()),
                    'hits': self.hits, 'misses': self.misses,
                    'evictions': self.evictions}
