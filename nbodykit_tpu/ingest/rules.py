"""The partition-rule tree: named catalog columns -> PartitionSpecs.

A real survey catalog arrives as NAMED columns (``Position``,
``Velocity``, ``Weight``, ``Selection`` ...), and every column has one
correct placement on the live device mesh: particle-indexed columns
shard their row axis over the mesh's leading axes ('dev' on the slab
mesh, ('x', 'y') flattened on a pencil), per-catalog scalars replicate.
The mapping is a RULE TREE — ordered (regex, spec-template) pairs
resolved by ``re.search`` against the column name, first match wins —
the exact ``match_partition_rules`` idiom of the LLaMA/EasyLM JAX
loaders (SNIPPETS.md [2]), with :func:`make_shard_and_gather_fns`
building the concrete ``device_put`` / host-gather callables per column
(SNIPPETS.md [3]).

The spec templates are mesh-agnostic TOKENS (``'rows'`` / ``None``),
resolved against the live mesh only inside
:func:`resolve_partition_spec` — a rule tree written once serves both
the 1-D slab mesh and any (Px, Py) pencil factorization.
"""

import re

import numpy as np

ROWS = 'rows'

# the default catalog rule tree, in priority order.  Every column a
# reader can deliver must match some rule; the terminal catch-all
# shards any unrecognized per-particle column by rows (the only safe
# default for a column with one entry per catalog row).
DEFAULT_RULES = (
    # vector per-particle columns: rows sharded, components replicated
    (r'(Position|Velocity|Displacement|GadgetVelocity'
     r'|InitialPosition)$', (ROWS, None)),
    # scalar per-particle columns
    (r'(Weight|Mass|Value|Selection|ID)$', (ROWS,)),
    # anything else delivered per-row: shard the leading axis, keep
    # trailing axes (if any) replicated
    (r'.', (ROWS, Ellipsis)),
)


def match_partition_rules(rules, columns):
    """Resolve ``{name: array-like}`` (or ``{name: ndim}``) against an
    ordered rule tree; returns ``{name: spec-template}``.

    ``rules`` is a sequence of ``(pattern, template)`` pairs; the first
    pattern with ``re.search(pattern, name)`` wins (the SNIPPETS.md [2]
    contract, including its failure mode: a name no rule matches is a
    ``ValueError``, never a silent default).
    """
    out = {}
    for name, val in columns.items():
        ndim = val if isinstance(val, int) else np.ndim(val)
        for pattern, template in rules:
            if re.search(pattern, name):
                out[name] = _fit_template(template, ndim)
                break
        else:
            raise ValueError(
                'column %r matches no partition rule' % name)
    return out


def _fit_template(template, ndim):
    """Concretize a spec template for an ``ndim``-dimensional column:
    ``Ellipsis`` expands to replicated trailing axes, and a template
    longer than the column is truncated (a scalar template on a 0-d
    attr is empty)."""
    spec = []
    for tok in template:
        if tok is Ellipsis:
            spec.extend([None] * (ndim - len(spec)))
            break
        spec.append(tok)
    return tuple(spec[:ndim])


def resolve_partition_spec(template, mesh):
    """The concrete ``PartitionSpec`` for a template on a live mesh:
    ``'rows'`` becomes the mesh's leading axis name(s)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.runtime import leading_axes
    axes = []
    for tok in template:
        if tok == ROWS:
            axes.append(leading_axes(mesh) if mesh is not None
                        else None)
        else:
            axes.append(tok)
    return P(*axes)


def partition_specs(columns, mesh, rules=DEFAULT_RULES):
    """``{name: PartitionSpec}`` for a set of named columns on the
    live mesh — the rule tree resolved end to end."""
    templates = match_partition_rules(rules, columns)
    return {name: resolve_partition_spec(t, mesh)
            for name, t in templates.items()}


def make_shard_and_gather_fns(specs, mesh):
    """Per-column ``(shard_fns, gather_fns)`` for resolved specs.

    ``shard_fns[name](host_array)`` places the column on the mesh under
    its spec (row-sharded columns must arrive padded to a multiple of
    the mesh size — :func:`nbodykit_tpu.ingest.stream.pad_rows` is the
    chunk pipeline's padder); ``gather_fns[name](device_array)`` pulls
    it back to one host ndarray.  On ``mesh=None`` both are
    (near-)identity, so single-device callers share the code path.
    """
    import jax

    shard_fns, gather_fns = {}, {}
    for name, spec in specs.items():
        if mesh is None:
            shard_fns[name] = jax.numpy.asarray
        else:
            from jax.sharding import NamedSharding
            sharding = NamedSharding(mesh, spec)

            def _shard(x, _s=sharding):
                return jax.device_put(x, _s)
            shard_fns[name] = _shard
        gather_fns[name] = np.asarray
    return shard_fns, gather_fns
