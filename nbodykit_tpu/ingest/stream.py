"""Chunked host-to-device catalog streaming with overlapped paint.

The binding cost of serving a real survey is moving its bytes onto the
device mesh.  This module makes that cost a PIPELINE, not a staging
area:

- the io reader delivers bounded column chunks
  (:meth:`~nbodykit_tpu.io.base.FileType.read_chunks` — this process's
  row range split into ``chunk_rows`` windows), so the host never
  materializes the catalog;
- each chunk is padded to the device count, placed under its
  partition-rule spec (:mod:`.rules`) with an async ``device_put``,
  and the PREVIOUS chunk is painted while the transfer flies — the
  double buffer that hides H2D behind the deposit
  (``ingest_overlap`` option; the serialized transfer-then-paint path
  stays selectable for A/B measurement);
- chunk boundaries are checkpointable
  (:class:`~nbodykit_tpu.resilience.CheckpointStore`): a killed ingest
  resumes by re-transferring — never re-PAINTING — the completed
  chunks, validated against the checkpointed per-chunk digests;
- the per-chunk sha256s fold into the content address that keys the
  on-device :class:`~nbodykit_tpu.ingest.cache.CatalogCache`, so the
  next request against the same survey skips the file and the wire
  entirely and goes straight to paint.

Bit-identity contract: the painted mesh is defined by the CHUNKED
deposit order (chunk 0's scatter, then chunk 1's scatter merged via
``paint(out=...)``, ...).  The cold streamed path, the cache-hit path
(:func:`paint_cached` replays the stored chunks) and a whole-resident
catalog painted through :func:`paint_chunks` at the same ``chunk_rows``
all execute the identical op sequence on identical values — the tests
assert equality to the bit.

Observability: ``ingest.stream`` / ``ingest.h2d`` /
``ingest.paint_cached`` spans (the ``ingest`` critical-path phase in
``diagnostics/analyze.py``), ``ingest.rows`` / ``.bytes`` / ``.chunks``
/ ``.resumed_chunks`` counters, and an ``ingest.host_bytes`` gauge
whose high-water mark is the proof the host stayed bounded.
"""

import hashlib
import os
import time

import numpy as np

from ..diagnostics import counter, gauge, span
from ..io.base import FileType
from .cache import CatalogEntry, fold_digest, layout_token
from .rules import (DEFAULT_RULES, make_shard_and_gather_fns,
                    match_partition_rules, resolve_partition_spec)

# formats a serialized data_ref may name (FileStack composes
# programmatically and is not addressable by one path + format token)
FORMATS = {
    'binary': 'BinaryFile',
    'csv': 'CSVFile',
    'bigfile': 'BigFile',
    'hdf': 'HDFFile',
    'fits': 'FITSFile',
    'tpm': 'TPMBinaryFile',
    'gadget1': 'Gadget1File',
}

DEFAULT_COLUMNS = {'Position': 'Position'}


class IngestError(Exception):
    """A structured ingestion failure: ``code`` is machine-readable
    (``unreadable_data_ref`` / ``unknown_format`` / ``empty_catalog``
    / ``checkpoint_mismatch``), ``detail`` is for humans."""

    def __init__(self, code, detail, **extra):
        super(IngestError, self).__init__('%s: %s' % (code, detail))
        self.code = code
        self.detail = detail
        self.extra = dict(extra)

    def to_reason(self):
        out = {'code': self.code, 'detail': self.detail}
        out.update(self.extra)
        return out


class DataRef(object):
    """A serializable pointer to an on-disk catalog: path + format +
    the logical->file column map (``{'Position': 'pos', 'Weight':
    'Mass'}``) + reader keyword options.  This is what an
    :class:`~nbodykit_tpu.serve.AnalysisRequest` carries instead of a
    ``seed`` — a few hundred bytes however large the survey."""

    __slots__ = ('path', 'format', 'columns', 'options')

    def __init__(self, path, format, columns=None, options=None):
        self.path = str(path)
        self.format = str(format)
        if self.format not in FORMATS:
            raise IngestError(
                'unknown_format',
                'format %r is not one of %s'
                % (self.format, sorted(FORMATS)), path=self.path)
        self.columns = dict(columns or DEFAULT_COLUMNS)
        if 'Position' not in self.columns:
            raise IngestError(
                'unknown_format',
                "column map must bind 'Position'", path=self.path)
        self.options = dict(options or {})

    def open(self):
        """The reader instance, or a structured
        ``unreadable_data_ref`` failure — never a bare OSError."""
        from .. import io as nbio
        cls = getattr(nbio, FORMATS[self.format])
        try:
            f = cls(self.path, **self.options)
        except Exception as e:
            raise IngestError(
                'unreadable_data_ref',
                '%s: %s' % (type(e).__name__, str(e)[:300]),
                path=self.path, format=self.format)
        missing = [c for c in self.columns.values()
                   if c not in f.dtype.names]
        if missing:
            raise IngestError(
                'unreadable_data_ref',
                'file lacks mapped column(s) %s (has %s)'
                % (missing, list(f.dtype.names)), path=self.path,
                format=self.format)
        return f

    def fingerprint(self, layout):
        """The stat-cheap cache front door: realpath + size + mtime_ns
        + column map + partition layout.  A rewritten file changes
        size/mtime and misses; content identity is re-established by
        the digest computed during the cold ingest."""
        try:
            st = os.stat(self.path)
        except OSError as e:
            raise IngestError('unreadable_data_ref', str(e),
                              path=self.path)
        return (os.path.realpath(self.path), int(st.st_size),
                int(st.st_mtime_ns),
                tuple(sorted(self.columns.items())),
                hashlib.sha256(layout.encode()).hexdigest())

    def to_dict(self):
        return {'path': self.path, 'format': self.format,
                'columns': dict(self.columns),
                'options': dict(self.options)}

    @classmethod
    def from_dict(cls, d):
        if isinstance(d, DataRef):
            return d
        d = dict(d)
        return cls(d['path'], d['format'], d.get('columns'),
                   d.get('options'))


class ArraySource(FileType):
    """An in-memory FileType over named host arrays — the whole-load
    reference the bit-identity tests stream against, and the tuner's
    disk-free trial source.  Same ``read``/``read_chunks`` contract as
    every on-disk reader."""

    def __init__(self, columns):
        names = list(columns)
        arrays = {k: np.asarray(v) for k, v in columns.items()}
        n = {len(a) for a in arrays.values()}
        if len(n) != 1:
            raise ValueError('columns disagree on length: %s'
                             % sorted(n))
        self.size = n.pop()
        self.dtype = np.dtype([(k, arrays[k].dtype,
                                arrays[k].shape[1:]) for k in names])
        self._data = arrays

    def read(self, columns, start, stop, step=1):
        out = self._empty(columns, len(range(start, stop, step)))
        for c in columns:
            out[c] = self._data[c][start:stop:step]
        return out


def _open_source(ref):
    """(reader, logical->file column map) for a DataRef, a dict form
    of one, or a bare FileType (in-memory trials)."""
    if isinstance(ref, FileType):
        cols = {'Position': 'Position'}
        for c in ('Weight', 'Velocity', 'Selection'):
            if c in (ref.dtype.names or ()):
                cols[c] = c
        return ref, cols
    ref = DataRef.from_dict(ref)
    return ref.open(), dict(ref.columns)


def probe_ref(ref):
    """Admission's cheap look: row count and ingested bytes-per-row
    for the mapped columns (what throughput and memory are priced
    against).  Raises :class:`IngestError` on an unreadable ref."""
    f, cols = _open_source(ref)
    row_bytes = sum(int(f.dtype[c].itemsize) for c in cols.values())
    return {'nrows': int(f.size), 'row_bytes': row_bytes,
            'total_bytes': int(f.size) * row_bytes,
            'columns': cols}


def resolve_chunk_rows(npart=None, nproc=1, chunk_rows=None):
    """The concrete streaming window: an explicit value wins, then the
    ``ingest_chunk_rows`` option (``'auto'`` consults the tune cache
    keyed by the part-count shape class, falling back to the cold
    default)."""
    if chunk_rows is not None:
        return max(int(chunk_rows), 1)
    from .. import _global_options
    v = _global_options['ingest_chunk_rows']
    if not isinstance(v, bool) and isinstance(v, (int, float)):
        return max(int(v), 1)
    from ..tune.resolve import resolve_ingest_chunk_rows
    return resolve_ingest_chunk_rows(npart=npart, nproc=nproc)


def _mesh_of(pm):
    return getattr(pm, 'comm', None)


def _catalog_layout(f, cols, chunk_rows, mesh, rules=DEFAULT_RULES):
    """(layout token, shard fns) for the mapped columns on the live
    mesh — the rule tree resolved once per ingest."""
    from ..parallel.runtime import mesh_size
    logical = {'Position': 2}
    if 'Weight' in cols:
        logical['Weight'] = 1
    if 'Velocity' in cols:
        logical['Velocity'] = 2
    if 'Selection' in cols:
        logical['Selection'] = 1
    templates = match_partition_rules(rules, logical)
    specs = {k: resolve_partition_spec(t, mesh)
             for k, t in templates.items()}
    shard_fns, _ = make_shard_and_gather_fns(specs, mesh)
    layout = layout_token(
        sorted(logical), [f.dtype[cols[c]].base for c in
                          sorted(logical) if c in cols],
        chunk_rows, mesh_size(mesh), templates)
    return layout, shard_fns


class _HostMeter(object):
    """High-water accounting of live host chunk bytes — the evidence
    the catalog is never host-resident.  The double buffer holds at
    most two chunks."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.live = 0
        self.peak = 0

    def add(self, nbytes):
        with self._lock:
            self.live += int(nbytes)
            self.peak = max(self.peak, self.live)
            live = self.live
        gauge('ingest.host_bytes').set(live)

    def drop(self, nbytes):
        with self._lock:
            self.live -= int(nbytes)
            live = self.live
        gauge('ingest.host_bytes').set(live)


def _put_chunk(chunk, cols, shard_fns, ndev, pos_dtype):
    """Pad a host chunk to the device count and place it under the
    partition specs.  Padding slots carry mass 0 — inert in the
    deposit (pmesh.paint's documented contract).

    A mapped ``Selection`` column multiplies into the effective
    deposit mass on the host (FKP-style: a 0/1 mask or a completeness
    weight scales each particle's contribution before it ever reaches
    the device), so selection never forces the whole-resident catalog
    path.  A mapped ``Velocity`` column is sharded alongside Position
    and rides the chunk as a 4th element — resident for RSD-style
    consumers, invisible to :func:`paint_chunks`."""
    import jax.numpy as jnp
    n = len(chunk)
    pad = (-n) % max(ndev, 1)
    pos = np.ascontiguousarray(chunk[cols['Position']], dtype=pos_dtype)
    if 'Weight' in cols:
        mass = np.ascontiguousarray(chunk[cols['Weight']],
                                    dtype=pos_dtype)
    else:
        mass = np.ones(n, dtype=pos_dtype)
    if 'Selection' in cols:
        mass = mass * np.ascontiguousarray(
            chunk[cols['Selection']]).astype(pos_dtype)
    vel = None
    if 'Velocity' in cols:
        vel = np.ascontiguousarray(chunk[cols['Velocity']],
                                   dtype=pos_dtype)
    if pad:
        pos = np.concatenate(
            [pos, np.zeros((pad, 3), dtype=pos_dtype)])
        mass = np.concatenate([mass, np.zeros(pad, dtype=pos_dtype)])
        if vel is not None:
            vel = np.concatenate(
                [vel, np.zeros((pad, 3), dtype=pos_dtype)])
    nbytes = pos.nbytes + mass.nbytes \
        + (vel.nbytes if vel is not None else 0)
    with span('ingest.h2d', rows=n, bytes=nbytes):
        pos_dev = shard_fns['Position'](pos)
        mass_dev = shard_fns.get('Weight', jnp.asarray)(mass)
        if vel is None:
            return pos_dev, mass_dev, n
        vel_dev = shard_fns.get('Velocity',
                                shard_fns['Position'])(vel)
    return pos_dev, mass_dev, n, vel_dev


def _chunk_digest(chunk, cols):
    h = hashlib.sha256()
    for c in sorted(cols):
        h.update(np.ascontiguousarray(chunk[cols[c]]).tobytes())
    return h.hexdigest()


def paint_chunks(pm, chunks, resampler=None, out=None):
    """The canonical chunked deposit: paint each (pos, mass) chunk
    into the accumulator in order.  EVERY path to a painted ingest
    mesh goes through this op sequence — that is the bit-identity
    contract.  Chunks are ``(pos, mass, n)`` or ``(pos, mass, n,
    vel)`` — a resident Velocity column rides along untouched."""
    for chunk in chunks:
        out = pm.paint(chunk[0], chunk[1], resampler=resampler,
                       out=out)
    return out


def paint_cached(pm, entry, resampler=None):
    """The cache-hit path: replay the stored chunks straight into
    paint — no file, no wire."""
    with span('ingest.paint_cached', chunks=len(entry.chunks),
              rows=entry.nrows):
        out = paint_chunks(pm, entry.chunks, resampler=resampler)
    return out


def host_chunks(source, cols, chunk_rows, rank=0, nranks=1):
    """This worker's host chunk stream via the uniform reader
    interface (:meth:`FileType.read_chunks`)."""
    file_cols = [cols[c] for c in sorted(cols)]
    return source.read_chunks(file_cols, chunk_rows, rank=rank,
                              nranks=nranks)


def ingest_catalog(ref, pm, resampler=None, chunk_rows=None,
                   overlap=None, cache=None, fits=None,
                   checkpoint=None, ckpt_key=None, ckpt_every=0,
                   rules=DEFAULT_RULES):
    """File -> painted mesh, streaming.  Returns
    ``(field, entry, stats)``.

    On a cache hit the stored chunks replay straight into paint
    (``stats['cache_hit']`` True, zero bytes read).  Cold, the chunk
    loop double-buffers: ``device_put`` of chunk *i+1* is dispatched
    before the paint of chunk *i* is awaited (``overlap``; default the
    ``ingest_overlap`` option), per-chunk digests fold into the
    content address, and — with a ``checkpoint`` store — the painted
    accumulator is saved every ``ckpt_every`` chunk boundaries so a
    kill resumes by re-transferring, never re-painting, finished
    chunks.  ``fits(resident_bytes)`` is the memory_plan eviction
    predicate forwarded to the cache.
    """
    import jax
    import jax.numpy as jnp

    from .. import _global_options
    from ..parallel.runtime import mesh_size, process_count, \
        process_index
    from ..resilience.faults import fault_point

    t0 = time.perf_counter()
    f, cols = _open_source(ref)
    if f.size == 0:
        raise IngestError('empty_catalog', 'catalog has zero rows',
                          path=getattr(ref, 'path', '<memory>'))
    mesh = _mesh_of(pm)
    ndev = mesh_size(mesh)
    nproc = max(ndev, 1)
    chunk_rows = resolve_chunk_rows(npart=f.size, nproc=nproc,
                                    chunk_rows=chunk_rows)
    if overlap is None:
        overlap = bool(_global_options['ingest_overlap'])
    layout, shard_fns = _catalog_layout(f, cols, chunk_rows, mesh,
                                        rules=rules)
    pos_dtype = np.dtype('f8') \
        if f.dtype[cols['Position']].base == np.dtype('f8') \
        else np.dtype('f4')

    fingerprint = None
    if isinstance(ref, (DataRef, dict)):
        fingerprint = DataRef.from_dict(ref).fingerprint(layout)
    elif cache is not None:
        fingerprint = ('memory', id(f), int(f.size),
                       hashlib.sha256(layout.encode()).hexdigest())

    stats = {'rows': 0, 'bytes': 0, 'chunks': 0,
             'chunk_rows': chunk_rows, 'overlap': bool(overlap),
             'cache_hit': False, 'resumed_chunks': 0,
             'host_peak_bytes': 0}
    if cache is not None:
        entry = cache.lookup(fingerprint)
        if entry is not None:
            field = paint_cached(pm, entry, resampler=resampler)
            stats.update(cache_hit=True, rows=entry.nrows,
                         chunks=len(entry.chunks),
                         chunk_rows=entry.chunk_rows,
                         seconds=time.perf_counter() - t0)
            return field, entry, stats

    # ---- cold path: stream, hash, (optionally) resume -------------------
    key = ckpt_key or ('ingest-%s' % (
        hashlib.sha256(layout.encode()).hexdigest()[:12]
        if fingerprint is None else
        hashlib.sha256(repr(fingerprint).encode()).hexdigest()[:12]))
    layout_id = hashlib.sha256(layout.encode()).hexdigest()
    painted = 0
    digests = []
    acc = None
    if checkpoint is not None:
        got = checkpoint.load(key)
        if got is not None:
            state, arrays = got
            if state.get('layout') == layout_id \
                    and state.get('chunk_rows') == chunk_rows:
                painted = int(state['painted'])
                digests = list(state['digests'])
                host_field = np.asarray(arrays['field'],
                                        dtype='f4').astype(
                    np.dtype('f4'))
                fld = jnp.asarray(host_field, pm.dtype)
                acc = jax.device_put(fld, pm.sharding()) \
                    if mesh is not None else fld
                stats['resumed_chunks'] = painted
                counter('ingest.resumed_chunks').add(painted)

    meter = _HostMeter()
    rank, nranks = process_index(), process_count()
    pending = None          # (pos_dev, mass_dev, nvalid, host_bytes)
    stored = []
    i = 0
    with span('ingest.stream', rows=int(f.size),
              chunk_rows=chunk_rows, overlap=bool(overlap),
              ndevices=nproc):
        for chunk in host_chunks(f, cols, chunk_rows, rank=rank,
                                 nranks=nranks):
            hb = int(chunk.nbytes)
            meter.add(hb)
            d = _chunk_digest(chunk, cols)
            if i < painted:
                # resumed: the paint is checkpointed; re-transfer for
                # the cache and VERIFY the bytes are the same catalog
                if d != digests[i]:
                    raise IngestError(
                        'checkpoint_mismatch',
                        'chunk %d bytes changed since the checkpoint'
                        % i, chunk=i)
            else:
                digests.append(d)
            dev = _put_chunk(chunk, cols, shard_fns, nproc, pos_dtype)
            meter.drop(hb)   # device owns the bytes now
            del chunk
            counter('ingest.chunks').add(1)
            counter('ingest.rows').add(dev[2])
            counter('ingest.bytes').add(hb)
            stats['rows'] += dev[2]
            stats['bytes'] += hb
            stats['chunks'] += 1
            if not overlap:
                jax.block_until_ready(dev[:2])
            if pending is not None:
                pi = i - 1
                if pi >= painted:
                    acc = paint_chunks(pm, [pending[:-1]],
                                       resampler=resampler, out=acc)
                    if not overlap:
                        jax.block_until_ready(acc)
                    acc, painted = _maybe_ckpt(
                        checkpoint, key, layout_id, chunk_rows,
                        pi + 1, digests, acc, ckpt_every, pm, mesh,
                        painted)
                stored.append(pending[:-1])
                fault_point('ingest.chunk')
            pending = dev + (hb,)
            i += 1
        if pending is not None:
            if i - 1 >= painted:
                acc = paint_chunks(pm, [pending[:-1]],
                                   resampler=resampler, out=acc)
            stored.append(pending[:-1])
            fault_point('ingest.chunk')
        jax.block_until_ready(acc)
    if acc is None:
        raise IngestError('empty_catalog',
                          'no rows on this worker rank',
                          path=getattr(ref, 'path', '<memory>'))
    if checkpoint is not None:
        checkpoint.delete(key)

    digest = fold_digest(layout, digests)
    entry = CatalogEntry(digest, layout, stored, stats['rows'],
                         chunk_rows)
    if cache is not None:
        cache.put(fingerprint, entry, fits=fits)
    stats['host_peak_bytes'] = meter.peak
    stats['digest'] = digest
    stats['seconds'] = time.perf_counter() - t0
    return acc, entry, stats


def _maybe_ckpt(checkpoint, key, layout_id, chunk_rows, painted_now,
                digests, acc, ckpt_every, pm, mesh, painted_before):
    """Save the accumulator at a chunk boundary (and return it
    re-placed, since np.asarray gathered it)."""
    if not checkpoint or not ckpt_every \
            or painted_now % ckpt_every or painted_now <= painted_before:
        return acc, painted_before
    import jax
    import jax.numpy as jnp
    host = np.asarray(acc, dtype='f4')
    checkpoint.save(key, {'layout': layout_id,
                          'chunk_rows': int(chunk_rows),
                          'painted': int(painted_now),
                          'digests': list(digests[:painted_now])},
                    arrays={'field': host})
    fld = jnp.asarray(host, pm.dtype)
    acc = jax.device_put(fld, pm.sharding()) if mesh is not None \
        else fld
    return acc, painted_before
