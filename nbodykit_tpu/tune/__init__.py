"""nbodykit_tpu.tune — measured autotuning with a persistent
per-platform performance database.

Round 5's verdict made the case: every kernel/knob choice in the
stack (``paint_method``, ``paint_chunk_size``, ``fft_chunk_bytes``,
mxu order/deposit engines, exchange slack) was a static guess, and
the hand-picked flagship MXU paint **lost to the plain scatter on
real hardware at every measured scale**.  The reference gets away
with fixed C kernels; a TPU-native stack cannot — the winning kernel
flips with mesh size, particle density and backend (the regime
dependence the mass-assignment literature predicts for deposit cost:
Jing 2005; Cui et al. 2008, PAPERS.md).  So choices are now
*measured*, cached, and carried between runs:

- :mod:`.space` — declarative search spaces per op (paint kernel ×
  chunk size × order/deposit engine; FFT chunk bytes; exchange
  slack), with deterministic candidate plans;
- :mod:`.trial` — warmup + timed reps per candidate under the
  resilience :class:`~nbodykit_tpu.resilience.Supervisor`, so a
  tunnel death or HBM OOM marks the *candidate* infeasible instead
  of killing the tune run; every trial is a ``tune.*`` span +
  counter;
- :mod:`.cache` — the persistent, content-keyed database
  (``TUNE_CACHE.json``, atomic tmp+rename), keyed by (platform,
  device kind, device count, op, shape class, dtype), with
  nearest-shape-class fallback and staleness stamps;
- :mod:`.resolve` — dispatch-time resolution:
  ``set_options(paint_method='auto')`` / ``fft_chunk_bytes='auto'``
  consult the cache; a cold cache falls back to today's defaults
  with **zero trial overhead** (trials only ever run offline, via
  ``nbodykit-tpu-tune`` / ``python -m nbodykit_tpu.tune``).

Cache location: the ``tune_cache`` option (seeded from
``$NBKIT_TUNE_CACHE``), defaulting to the committed repo-root
``TUNE_CACHE.json``.  Doctor posture: the ``tune`` verdict line WARNs
on entries measured on a different platform/device kind than the
current one or older than 30 days.  Full guide: docs/TUNE.md.
"""

from .cache import (STALE_DAYS, TUNABLE_OPTIONS, TuneCache,  # noqa: F401
                    cache_path, cache_summary, canonical_dtype,
                    class_coords, class_distance, default_cache_path,
                    device_signature, entry_age_days, entry_key,
                    make_key, reset_cache_memo, shape_class,
                    validate_cache)
from .space import (Candidate, SearchSpace, default_spaces,  # noqa: F401
                    exchange_space, fft_space, paint_space)
from .trial import plan_spaces, run_space  # noqa: F401
from .resolve import (FALLBACKS, effective_int_option,  # noqa: F401
                      resolve_exchange_slack, resolve_fft_chunk_bytes,
                      resolve_paint, resolve_paint_deposit,
                      tuned_snapshot)
