"""Measured micro-trials: run every candidate, record the winner.

Each candidate runs warmup + timed reps *under the resilience
Supervisor* (:mod:`..resilience`): a tunnel death gets a bounded
retry, and an HBM OOM (``RESOURCE_EXHAUSTED``) — or any other raised
error — marks the **candidate** infeasible instead of killing the tune
run; the next candidate still gets measured.  Infeasibility is data:
it lands in the cache entry (and the doctor's posture line) so the
next round knows a kernel refused to run at that shape, not just that
it was slow.

Every trial is a ``tune.trial`` span plus ``tune.trials`` /
``tune.infeasible`` counters (:mod:`..diagnostics`), and the
Supervisor's fault point (``tune.trial.attempt``, fired before every
attempt) makes the infeasible path deterministically testable:
``NBKIT_FAULTS='tune.trial.attempt@1:resource_exhausted'`` condemns
the first attempted candidate on the CPU mesh (docs/RESILIENCE.md).

Trial *plans* are deterministic — candidates, order, reps and seeds
are pure functions of the requested contexts — so two invocations of
``nbodykit-tpu-tune`` at the same shapes measure the same programs.
"""

import time

from .cache import (TuneCache, canonical_dtype, device_signature,
                    make_key, utcnow)

DEFAULT_REPS = 2


def _mesh_nproc():
    from ..parallel.runtime import CurrentMesh, mesh_size
    return mesh_size(CurrentMesh.resolve(None))


def plan_spaces(pairs, reps=DEFAULT_REPS, signature=None):
    """The deterministic trial plan for ``(space, ctx)`` pairs: one
    record per pair with the cache key and the candidate names, in
    execution order.  Pure bookkeeping — builds no arrays, runs
    nothing."""
    sig = signature or device_signature(count=_mesh_nproc())
    plan = []
    for space, ctx in pairs:
        sclass = space.shape_class(ctx)
        dtype = canonical_dtype(ctx.get('dtype', 'f4'))
        plan.append({
            'op': space.op,
            'key': make_key(sig[0], sig[1], sig[2], space.op, sclass,
                            dtype),
            'shape_class': sclass,
            'context': {k: ctx[k] for k in sorted(ctx)},
            'reps': int(reps),
            'candidates': [c.name for c in space.candidates(ctx)],
        })
    return plan


def run_space(space, ctx, cache=None, reps=DEFAULT_REPS, policy=None,
              signature=None, log=None):
    """Measure every candidate of ``space`` at ``ctx`` and commit the
    winner to ``cache``.  Returns the cache entry (committed whenever
    at least one candidate was feasible; an all-infeasible entry is
    committed too, with ``winner: null`` — resolution skips it but the
    doctor reports it)."""
    from .. import set_options
    from ..diagnostics import counter, span
    from ..resilience import RetryPolicy, Supervisor, classify_error

    cache = cache if cache is not None else TuneCache()
    sig = signature or device_signature(count=_mesh_nproc())
    sclass = space.shape_class(ctx)
    dtype = canonical_dtype(ctx.get('dtype', 'f4'))
    reps = int(reps)
    trials = {}

    with span('tune.space', op=space.op, shape_class=sclass,
              platform=sig[0], device_count=sig[2]):
        for cand in space.candidates(ctx):
            sup = Supervisor('tune.trial',
                             policy=policy or RetryPolicy(
                                 max_retries=1, base_s=0.05,
                                 max_s=0.2))
            rec = {'options': dict(cand.options)}
            t_span = time.perf_counter()
            with span('tune.trial', op=space.op, candidate=cand.name,
                      shape_class=sclass):
                try:
                    with set_options(**cand.options):
                        once = space.make_runner(ctx)
                        sup.run(once)                 # warmup/compile
                        rec['warm_s'] = round(
                            time.perf_counter() - t_span, 6)
                        t0 = time.perf_counter()
                        for _ in range(reps):
                            sup.run(once)
                        rec['wall_s'] = round(
                            (time.perf_counter() - t0) / reps, 6)
                        rec['reps'] = reps
                    counter('tune.trials').add(1)
                except Exception as e:
                    rec['infeasible'] = classify_error(e)
                    rec['error'] = str(e)[:200]
                    counter('tune.infeasible').add(1)
            retr = [e for e in sup.events if e['kind'] == 'retries']
            if retr:
                rec['retries'] = len(retr)
            trials[cand.name] = rec
            if log is not None:
                log('%s/%s %s: %s'
                    % (space.op, sclass, cand.name,
                       '%.4f s' % rec['wall_s'] if 'wall_s' in rec
                       else 'INFEASIBLE (%s)' % rec['infeasible']))

    feasible = {name: rec for name, rec in trials.items()
                if 'wall_s' in rec}
    winner_name = min(feasible, key=lambda k: feasible[k]['wall_s']) \
        if feasible else None
    entry = {
        'platform': sig[0], 'device_kind': sig[1],
        'device_count': sig[2], 'op': space.op, 'shape_class': sclass,
        'dtype': dtype,
        'context': {k: ctx[k] for k in sorted(ctx)},
        'winner_name': winner_name,
        'winner': {k: v for k, v in
                   trials[winner_name]['options'].items()
                   if k in space.provides} if winner_name else None,
        'trials': trials,
        'infeasible': sorted(name for name, rec in trials.items()
                             if 'infeasible' in rec),
        'measured_at': utcnow(),
    }
    cache.put(entry)
    counter('tune.entries_committed').add(1)
    return entry
