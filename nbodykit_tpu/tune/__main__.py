"""Tune CLI: populate, inspect and validate the performance database.

    nbodykit-tpu-tune                         (== python -m nbodykit_tpu.tune)
        Run the default trial plan on the current backend (paint at
        two shape classes, the FFT chunk ladder, the exchange slack
        when a multi-device mesh is up, the ingest chunk-rows ladder)
        and commit the winners to TUNE_CACHE.json.

    nbodykit-tpu-tune --dry-run
        Print the deterministic trial plan (cache keys + candidates)
        WITHOUT building arrays or touching a device.  Bounded and
        cheap — the smoke gate runs it.

    nbodykit-tpu-tune --validate
        Schema-check the committed cache and print its posture
        summary; exit 1 on a malformed file (the smoke gate).

    Options: --ops paint,fft,exchange,ingest,bspec
    · --paint-shapes 64x1e4,128x1e5
    · --fft-nmesh 64,128 · --pencil PXxPY (fft decomp factorization)
    · --reps N · --cache PATH · --devices N (CPU: force N virtual
    devices and tune on that mesh).

The committed repo-root TUNE_CACHE.json is produced by exactly this
command on the 8-device CPU mesh; the on-chip run (same command over
the axon tunnel) overwrites the TPU-keyed entries without touching
the CPU ones — keys carry the platform, so the two coexist.
"""

import argparse
import json
import sys


def _parse_paint_shapes(text):
    """'64x1e4,128x1e5' -> [(64, 10000), (128, 100000)]."""
    shapes = []
    for part in str(text).split(','):
        part = part.strip()
        if not part:
            continue
        nmesh, _, npart = part.partition('x')
        shapes.append((int(nmesh), int(float(npart))))
    return shapes


def _contexts(args, spaces, nproc):
    """The deterministic (space, ctx) list for this invocation."""
    ops = [o.strip() for o in args.ops.split(',') if o.strip()]
    unknown = sorted(set(ops) - set(spaces))
    if unknown:
        raise SystemExit('unknown op(s): %s (choose from %s)'
                         % (','.join(unknown), ','.join(sorted(spaces))))
    pairs = []
    if 'paint' in ops:
        for nmesh, npart in _parse_paint_shapes(args.paint_shapes):
            pairs.append((spaces['paint'],
                          {'nmesh': nmesh, 'npart': npart,
                           'dtype': 'f4', 'resampler': 'cic',
                           'seed': 7}))
    if 'fft' in ops:
        # multi-device ffts also race fft_decomp; the ctx records the
        # (Px, Py) factorization the pencil candidate runs with
        # (--pencil override, else the near-square default), and the
        # entry is keyed under it (cache.shape_class)
        mesh_shape = None
        if nproc > 1:
            if args.pencil:
                px, _, py = args.pencil.lower().partition('x')
                mesh_shape = (int(px), int(py))
                if mesh_shape[0] * mesh_shape[1] != nproc:
                    raise SystemExit(
                        '--pencil %s does not cover %d devices'
                        % (args.pencil, nproc))
            else:
                from ..parallel.runtime import default_pencil_factor
                mesh_shape = default_pencil_factor(nproc)
        for nmesh in [int(x) for x in args.fft_nmesh.split(',') if x]:
            ctx = {'nmesh': nmesh, 'dtype': 'f4', 'seed': 7,
                   'nproc': nproc}
            if mesh_shape is not None:
                ctx['mesh_shape'] = list(mesh_shape)
            pairs.append((spaces['fft'], ctx))
    if 'exchange' in ops and nproc > 1:
        for _, npart in _parse_paint_shapes(args.paint_shapes)[-1:]:
            pairs.append((spaces['exchange'],
                          {'npart': npart, 'dtype': 'f4', 'seed': 7}))
    if 'ingest' in ops:
        # the streaming window ladder, one entry per part-count class
        # (the knob is keyed by npart alone — shape_class(npart=...))
        for nmesh, npart in _parse_paint_shapes(args.paint_shapes):
            pairs.append((spaces['ingest'],
                          {'nmesh': nmesh, 'npart': npart,
                           'dtype': 'f4', 'seed': 7}))
    if 'bspec' in ops:
        # the FFT/direct bispectrum crossover, one entry per shape
        # class (the same NMESHxNPART grid as paint: the crossover
        # moves with both the mesh the FFT path would need and the
        # particle count the direct path sums over)
        for nmesh, npart in _parse_paint_shapes(args.paint_shapes):
            pairs.append((spaces['bspec'],
                          {'nmesh': nmesh, 'npart': npart,
                           'nbins': 3, 'dtype': 'f4', 'seed': 7}))
    return pairs


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='nbodykit-tpu-tune', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--ops', default='paint,fft,exchange,ingest,bspec',
                    help='comma list of ops to tune (default: all)')
    ap.add_argument('--paint-shapes', default='64x1e4,128x1e5',
                    help="paint trial shapes as NMESHxNPART, comma-"
                         "separated (default: 64x1e4,128x1e5)")
    ap.add_argument('--fft-nmesh', default='64,128',
                    help='FFT trial mesh sizes (default: 64,128)')
    ap.add_argument('--pencil', default=None,
                    help="fft decomp trials: (Px, Py) factorization "
                         "as 'PXxPY' (default: the near-square "
                         "factorization of the device count)")
    ap.add_argument('--reps', type=int, default=2,
                    help='timed reps per candidate (default 2)')
    ap.add_argument('--cache', default=None,
                    help='cache file (default: the tune_cache option '
                         '/ $NBKIT_TUNE_CACHE / repo TUNE_CACHE.json)')
    ap.add_argument('--devices', type=int, default=None,
                    help='CPU only: force N virtual devices and tune '
                         'on that mesh (e.g. 8 for the committed '
                         'cache)')
    ap.add_argument('--dry-run', action='store_true',
                    help='print the deterministic trial plan and exit')
    ap.add_argument('--validate', action='store_true',
                    help='schema-check the cache file; exit 1 on a '
                         'malformed one')
    args = ap.parse_args(argv)

    from .cache import (TuneCache, cache_summary, device_signature,
                        validate_cache)

    cache = TuneCache(args.cache)

    if args.validate:
        problems = validate_cache(cache.path)
        if problems:
            print('TUNE_CACHE INVALID: %s' % cache.path)
            for p in problems:
                print('  - %s' % p)
            return 1
        summary = cache_summary(cache.path)
        if summary is None:
            print('tune cache OK: %s absent (cold cache — dispatch '
                  'falls back to defaults)' % cache.path)
        else:
            print('tune cache OK: %(entries)d entr%(ies)s, '
                  '%(stale)d stale (>%(days).0f d), %(inf)d '
                  'infeasible candidate(s), platforms %(plat)s'
                  % {'entries': summary['entries'],
                     'ies': 'y' if summary['entries'] == 1 else 'ies',
                     'stale': summary['stale'],
                     'days': summary['stale_days'],
                     'inf': summary['infeasible'],
                     'plat': ','.join(summary['platforms']) or '-'})
        return 0

    from .space import default_spaces
    from .trial import plan_spaces, run_space

    if args.dry_run:
        # no arrays, no mesh: plan against the process-visible devices
        # (or the forced count), purely for display
        sig = device_signature(count=args.devices)
        spaces = default_spaces()
        nproc = args.devices if args.devices else sig[2]
        plan = plan_spaces(_contexts(args, spaces, nproc),
                           reps=args.reps, signature=sig)
        print(json.dumps({'cache': cache.path, 'signature': list(sig),
                          'plan': plan}, indent=1))
        return 0

    # live run: bring up the mesh, then walk the plan.  The device
    # count must be forced BEFORE anything initializes a backend
    # (jax.default_backend()/jax.devices() lock it in), so the CPU
    # check reads the requested platform, not the live backend
    import os
    import jax
    if args.devices:
        plats = '%s %s' % (os.environ.get('JAX_PLATFORMS', ''),
                           getattr(jax.config, 'jax_platforms', '')
                           or '')
        if 'cpu' in plats:
            from .._jax_compat import set_cpu_devices
            set_cpu_devices(int(args.devices))
    from ..parallel.runtime import cpu_mesh, tpu_mesh, use_mesh
    from ..utils import is_mxu_backend
    mesh = tpu_mesh() if is_mxu_backend() else cpu_mesh()
    spaces = default_spaces()
    with use_mesh(mesh):
        from ..parallel.runtime import mesh_size
        nproc = mesh_size(mesh)
        pairs = _contexts(args, spaces, nproc)
        entries = []
        for space, ctx in pairs:
            entry = run_space(space, ctx, cache=cache, reps=args.reps,
                              log=lambda msg: print('[tune] ' + msg,
                                                    flush=True))
            entries.append(entry)
            print('[tune] committed %s/%s: winner=%s'
                  % (entry['op'], entry['shape_class'],
                     entry['winner_name']), flush=True)
    print(json.dumps({
        'cache': cache.path,
        'entries': len(entries),
        'winners': {'%s/%s' % (e['op'], e['shape_class']):
                    e['winner_name'] for e in entries},
        'infeasible': sum(len(e['infeasible']) for e in entries),
    }))
    return 0


def main_tune(argv=None):
    """Entry point for the ``nbodykit-tpu-tune`` console script."""
    return main(sys.argv[1:] if argv is None else argv)


if __name__ == '__main__':
    sys.exit(main())
