"""Declarative search spaces: which knob settings compete, per op.

A :class:`SearchSpace` names an op (the cache key's ``op`` field), the
options its winner provides, a deterministic candidate list for a
trial context, and a runner factory that builds the measured callable.
The candidate list is a pure function of the context — no RNG, no
clock — so a trial *plan* is reproducible byte-for-byte and can be
printed (``nbodykit-tpu-tune --dry-run``) without touching a device.

The spaces below cover the knobs round 5 proved are regime-dependent
guesses (VERDICT.md: the hand-picked MXU paint lost to the plain
scatter on real hardware at every measured scale):

- **paint** — kernel (``scatter`` / ``sort`` / ``segsum`` /
  ``streams`` / ``mxu``) × scatter chunk size × one-sort ordering
  engine (``radix`` vs ``argsort``, segsum and mxu) × stream count
  (``streams``: k ∈ {2, 4, 8}, each admitted only if
  ``pmesh.memory_plan`` keeps its k replica meshes inside the
  0.85×HBM budget at the trial shape) × mxu deposit engine (``xla``
  vs ``pallas`` — MXU backends where the Pallas kernel provably
  lowers, :func:`~nbodykit_tpu.ops.paint_pallas.
  pallas_deposit_lowers`) × mesh storage dtype (``mesh_dtype``:
  ``f4`` vs ``bf16`` half-storage with two-sum compensated merges —
  ISSUE 13, accuracy-gated by tests/test_precision.py);
- **fft** — the single-device ``fft_chunk_bytes`` dispatch target
  (one-shot in-jit vs slab-chunked vs eager lowmem), and on
  multi-device contexts the ``fft_decomp`` knob (slab's one P-way
  all_to_all vs the pencil path's two smaller transposes over a 2-D
  mesh); fft entries are keyed by the (Px, Py) factorization the
  pencil candidate runs with, so a winner measured on 4x2 never
  answers an 8x1 question;
- **exchange** — the counted-capacity slack of the particle
  ``all_to_all`` (multi-device contexts only).
"""

from .cache import shape_class


class Candidate(object):
    """One competitor: a name plus the ``set_options`` overrides that
    select it."""

    def __init__(self, name, options):
        self.name = str(name)
        self.options = dict(options)

    def __repr__(self):
        return 'Candidate(%r, %r)' % (self.name, self.options)


class SearchSpace(object):
    """Competing configurations of one op.

    Parameters
    ----------
    op : str — cache-key op name ('paint', 'fft', 'exchange').
    provides : tuple of option names the winner carries into the cache
        (a winner never writes options its trials did not vary).
    candidates : callable(ctx) -> list of :class:`Candidate`, pure in
        ctx.
    make_runner : callable(ctx) -> zero-arg callable running + syncing
        one trial iteration.  Called *inside* each candidate's
        ``set_options`` block, so option reads inside the runner see
        the candidate's values.
    """

    def __init__(self, op, provides, candidates, make_runner):
        self.op = str(op)
        self.provides = tuple(provides)
        self._candidates = candidates
        self.make_runner = make_runner

    def candidates(self, ctx):
        return list(self._candidates(ctx))

    def shape_class(self, ctx):
        # a ctx carrying 'mesh_shape' (the (Px, Py) factorization its
        # trials run with — the fft space on a multi-device mesh) keys
        # its entry under that factorization: decomp winners must not
        # travel across device-mesh shapes (cache.class_distance)
        return shape_class(nmesh=ctx.get('nmesh'),
                           npart=ctx.get('npart'),
                           mesh_shape=ctx.get('mesh_shape'))


def _sync(out):
    """Force completion via a scalar device->host transfer (the same
    real synchronization point bench.py uses: block_until_ready does
    not reliably wait under the axon tunnel)."""
    import jax
    import jax.numpy as jnp
    leaf = jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()
    if leaf.size == 0:
        jax.block_until_ready(out)
        return 0.0
    leaf = leaf[0]
    if jnp.iscomplexobj(leaf):
        leaf = jnp.abs(leaf)
    return float(leaf)


def _trial_positions(ctx):
    """Deterministic uniform positions for a trial (seeded from ctx;
    the plan stays reproducible)."""
    import jax
    import jax.numpy as jnp
    from ..parallel.runtime import CurrentMesh, shard_leading
    box = float(ctx.get('box', 1000.0))
    pos = jax.random.uniform(jax.random.key(int(ctx.get('seed', 7))),
                             (int(ctx['npart']), 3), jnp.float32,
                             0.0, box)
    mesh = CurrentMesh.resolve(None)
    if mesh is not None:
        pos = shard_leading(mesh, pos)
    _sync(pos)
    return pos


# ---------------------------------------------------------------------------
# paint

def _paint_candidates(ctx):
    from ..utils import is_mxu_backend
    chunk = 1024 * 1024 * 16
    cands = [
        Candidate('scatter', {'paint_method': 'scatter'}),
        Candidate('scatter-chunk4m', {'paint_method': 'scatter',
                                      'paint_chunk_size':
                                      1024 * 1024 * 4}),
        Candidate('sort', {'paint_method': 'sort'}),
        Candidate('segsum-argsort', {'paint_method': 'segsum',
                                     'paint_order': 'argsort'}),
        Candidate('segsum-radix', {'paint_method': 'segsum',
                                   'paint_order': 'radix'}),
    ]
    # offset-stream scatter: k replica meshes are k full mesh units of
    # HBM, so each stream count must prove — via the same NBK5xx
    # symbolic-peak model the lint budget gate uses — that the staged
    # ladder still fits before it may compete. memory_plan is pure
    # arithmetic with deterministic defaults (ndevices=1, 16 GB HBM),
    # so the candidate list stays a pure function of ctx.
    from ..pmesh import memory_plan
    for k in (2, 4, 8):
        plan = memory_plan(int(ctx['nmesh']), int(ctx['npart']),
                           dtype=ctx.get('dtype', 'f4'),
                           paint_method='streams', paint_streams=k)
        if plan['fits']:
            cands.append(Candidate('streams%d' % k,
                                   {'paint_method': 'streams',
                                    'paint_streams': k}))
    cands.extend([
        Candidate('mxu-argsort-xla', {'paint_method': 'mxu',
                                      'paint_order': 'argsort',
                                      'paint_deposit': 'xla'}),
        Candidate('mxu-radix-xla', {'paint_method': 'mxu',
                                    'paint_order': 'radix',
                                    'paint_deposit': 'xla'}),
    ])
    # half-storage mesh candidates (ISSUE 13): bf16 replica/field
    # buffers halve the HBM traffic of the scatter-bound paint; the
    # two-sum merge keeps the accuracy inside the tests/test_precision
    # budget, and memory_plan prices the halved meshes so streams
    # counts that only fit at 2 bytes/cell may compete here too
    cands.append(Candidate('scatter-bf16', {'paint_method': 'scatter',
                                            'mesh_dtype': 'bf16'}))
    for k in (4, 8):
        plan = memory_plan(int(ctx['nmesh']), int(ctx['npart']),
                           dtype='bf16', paint_method='streams',
                           paint_streams=k)
        if plan['fits']:
            cands.append(Candidate('streams%d-bf16' % k,
                                   {'paint_method': 'streams',
                                    'paint_streams': k,
                                    'mesh_dtype': 'bf16'}))
    for c in cands:
        c.options.setdefault('paint_chunk_size', chunk)
        # cold default = today's behavior: every candidate that did
        # not ask for bf16 races (and would win as) full-width f4
        c.options.setdefault('mesh_dtype', 'f4')
    if is_mxu_backend():
        # the Pallas VMEM deposit is interpreted (≈100x slow) off-MXU:
        # off-chip it would only ever lose, so it does not compete
        # there — and even on-MXU it competes only where the kernel
        # actually LOWERS (a remote-compile tunnel can reject Mosaic
        # custom calls; the probe is a cached trace+lower, no compile)
        from ..ops.paint_pallas import pallas_deposit_lowers
        if pallas_deposit_lowers():
            cands.append(Candidate('mxu-radix-pallas',
                                   {'paint_method': 'mxu',
                                    'paint_order': 'radix',
                                    'paint_deposit': 'pallas',
                                    'paint_chunk_size': chunk}))
    return cands


def registered_paint_candidates(nmesh, npart, dtype='f4'):
    """The paint candidate list for a shape, as the tuner would build
    it — the enumeration bench.py ``--paint-all``, the smoke gate and
    tests/test_paint_kernels.py iterate so 'every registered
    candidate' means exactly the competitors of a real trial."""
    return _paint_candidates({'nmesh': int(nmesh), 'npart': int(npart),
                              'dtype': dtype})


def _paint_runner(ctx):
    from .. import _global_options
    from ..pmesh import ParticleMesh
    # built inside the candidate's set_options block: a mesh_dtype
    # the candidate carries (e.g. 'bf16') overrides the ctx dtype so
    # the trial actually runs the half-storage pipeline
    mdt = _global_options['mesh_dtype']
    dtype = ctx.get('dtype', 'f4') if mdt in (None, 'auto') else mdt
    pm = ParticleMesh(Nmesh=int(ctx['nmesh']),
                      BoxSize=float(ctx.get('box', 1000.0)),
                      dtype=dtype)
    pos = _trial_positions(ctx)
    resampler = ctx.get('resampler', 'cic')

    def once():
        return _sync(pm.paint(pos, 1.0, resampler=resampler))
    return once


def paint_space():
    return SearchSpace('paint',
                       ('paint_method', 'paint_order', 'paint_deposit',
                        'paint_chunk_size', 'paint_streams',
                        'mesh_dtype'),
                       _paint_candidates, _paint_runner)


# ---------------------------------------------------------------------------
# fft

def _fft_candidates(ctx):
    # the real dispatch ladder: one-shot in-jit, then ever-smaller
    # slab-chunked / lowmem passes (parallel/dfft.py)
    cands = [Candidate('chunk2g', {'fft_chunk_bytes': 2 ** 31}),
             Candidate('chunk256m', {'fft_chunk_bytes': 2 ** 28}),
             Candidate('chunk64m', {'fft_chunk_bytes': 2 ** 26})]
    for c in cands:
        c.options.setdefault('fft_decomp', 'slab')
    # multi-device contexts also race the decomposition itself: the
    # pencil path (two smaller transposes over a 2-D mesh) vs slab's
    # one P-way all_to_all. The factorization comes from the ctx (the
    # CLI stamps the one the transform would run with) so the entry's
    # shape class — and therefore the winner's reach — carries it.
    # The a2a wire format races alongside (a2a_compress).
    nproc = int(ctx.get('nproc', 1))
    if nproc > 1:
        # compressed-wire candidates (ISSUE 13): the transposes are
        # THE slab/pencil cost, so the a2a payload format races too —
        # bf16 planes (half the bytes, re-widened on receipt) and
        # int16 quantized planes with per-shard scales.  Single-device
        # contexts have no collective, so the knob never races there.
        cands.append(Candidate('slab-a2a-bf16',
                               {'fft_decomp': 'slab',
                                'fft_chunk_bytes': 2 ** 31,
                                'a2a_compress': 'bf16'}))
        cands.append(Candidate('slab-a2a-int16',
                               {'fft_decomp': 'slab',
                                'fft_chunk_bytes': 2 ** 31,
                                'a2a_compress': 'int16'}))
    if nproc > 1 and ctx.get('mesh_shape'):
        px, py = ctx['mesh_shape']
        cands.append(Candidate(
            'pencil%dx%d' % (px, py),
            {'fft_decomp': 'pencil', 'fft_pencil': '%dx%d' % (px, py),
             'fft_chunk_bytes': 2 ** 31}))
        cands.append(Candidate(
            'pencil%dx%d-a2a-bf16' % (px, py),
            {'fft_decomp': 'pencil', 'fft_pencil': '%dx%d' % (px, py),
             'fft_chunk_bytes': 2 ** 31, 'a2a_compress': 'bf16'}))
    for c in cands:
        # cold default = today's behavior: uncompressed payloads
        c.options.setdefault('a2a_compress', 'none')
    return cands


def _fft_runner(ctx):
    import jax
    import jax.numpy as jnp
    from ..pmesh import ParticleMesh
    pm = ParticleMesh(Nmesh=int(ctx['nmesh']),
                      BoxSize=float(ctx.get('box', 1000.0)),
                      dtype=ctx.get('dtype', 'f4'))
    x = jax.random.uniform(jax.random.key(int(ctx.get('seed', 7))),
                           pm.shape_real, jnp.float32)
    x = jnp.asarray(x, pm.dtype)
    if pm.comm is not None:
        x = jax.device_put(x, pm.sharding())
    _sync(x)

    def once():
        return _sync(pm.r2c(x))
    return once


def fft_space():
    return SearchSpace('fft',
                       ('fft_chunk_bytes', 'fft_decomp', 'fft_pencil',
                        'a2a_compress'),
                       _fft_candidates, _fft_runner)


# ---------------------------------------------------------------------------
# exchange

def _exchange_candidates(ctx):
    return [Candidate('slack1.05', {'exchange_slack': 1.05}),
            Candidate('slack1.25', {'exchange_slack': 1.25}),
            Candidate('slack2.0', {'exchange_slack': 2.0})]


def _exchange_runner(ctx):
    from .. import _global_options
    from ..parallel.exchange import auto_capacity, exchange_by_dest
    from ..parallel.runtime import CurrentMesh, mesh_size
    mesh = CurrentMesh.resolve(None)
    nproc = mesh_size(mesh)
    if nproc <= 1:
        raise ValueError('exchange tuning needs a multi-device mesh '
                         '(nproc=%d)' % nproc)
    import jax
    import jax.numpy as jnp
    from ..parallel.runtime import shard_leading
    n = int(ctx['npart'])
    key = jax.random.key(int(ctx.get('seed', 7)))
    dest = shard_leading(mesh, jax.random.randint(
        key, (n,), 0, nproc, jnp.int32))
    vals = shard_leading(mesh, jax.random.uniform(
        key, (n,), jnp.float32))
    _sync((dest, vals))
    # the candidate's slack sizes the static per-pair buffers — read
    # at runner-build time, inside the candidate's set_options block
    cap = auto_capacity(dest, nproc,
                        slack=float(_global_options['exchange_slack']))

    def once():
        recv, valid, dropped = exchange_by_dest(dest, [vals], mesh, cap)
        return _sync((recv[0], dropped))
    return once


def exchange_space():
    return SearchSpace('exchange', ('exchange_slack',),
                       _exchange_candidates, _exchange_runner)


# ---------------------------------------------------------------------------
# ingest

def _ingest_candidates(ctx):
    # the chunk-rows ladder: windows small enough to keep two host
    # buffers tiny, large enough to amortize per-chunk dispatch.  The
    # ladder is clipped to the trial's particle count (a window larger
    # than the catalog degenerates to whole-load and measures nothing),
    # keyed by the part-count shape class so a 1e6-row winner never
    # answers a 1e9-row question.
    npart = int(ctx['npart'])
    cands = []
    for rows in (32768, 65536, 131072, 262144, 524288, 1048576):
        if rows >= 2 * npart and cands:
            break
        cands.append(Candidate('rows%dk' % (rows // 1024),
                               {'ingest_chunk_rows': rows}))
    return cands


def _ingest_runner(ctx):
    # stream a deterministic in-memory catalog (the same rows every
    # candidate) through the full chunk pipeline — rule-tree sharding,
    # padded device_put, overlapped paint — on the current mesh; the
    # candidate's ingest_chunk_rows is read inside ingest_catalog
    import numpy as np

    from ..ingest.stream import ArraySource, ingest_catalog
    from ..pmesh import ParticleMesh
    box = float(ctx.get('box', 1000.0))
    rng = np.random.RandomState(int(ctx.get('seed', 7)))
    pos = rng.uniform(0.0, box, size=(int(ctx['npart']), 3)) \
        .astype('f4')
    src = ArraySource({'Position': pos})
    from ..parallel.runtime import CurrentMesh
    pm = ParticleMesh(Nmesh=int(ctx.get('nmesh', 64)), BoxSize=box,
                      dtype=ctx.get('dtype', 'f4'),
                      comm=CurrentMesh.resolve(None))

    def once():
        field, _, _ = ingest_catalog(src, pm)
        return _sync(field)
    return once


def ingest_space():
    return SearchSpace('ingest', ('ingest_chunk_rows',),
                       _ingest_candidates, _ingest_runner)


# ---------------------------------------------------------------------------
# bspec — the FFT/direct bispectrum crossover (ISSUE 20)

def _bspec_candidates(ctx):
    """The estimator race: the Scoccimarro FFT path against the
    MXU-shaped direct path at several dense-block tiles.  Which wins
    is a *per-platform, per-shape* property — the direct path's
    O(Npart x Nk) FLOPs beat the FFT's wire time only where the MXU
    can stream them (PAPERS.md 2005.01739) — so the crossover is
    measured here, never guessed.  Direct tiles are clipped to the
    trial's particle count (a tile bigger than the catalog pads to
    waste and measures nothing)."""
    npart = int(ctx['npart'])
    cands = [Candidate('fft', {'bspec_method': 'fft'})]
    for tile in (256, 1024, 4096):
        if tile >= 4 * npart and len(cands) > 1:
            break
        cands.append(Candidate('direct-tile%d' % tile,
                               {'bspec_method': 'direct',
                                'pairblock_tile': tile}))
    return cands


def _bspec_runner(ctx):
    """One bounded bispectrum measurement per candidate: same
    deterministic uniform catalog, same shell count; the candidate's
    ``bspec_method`` / ``pairblock_tile`` are read inside the trial
    through the class's normal resolution path."""
    from .. import _global_options
    from ..parallel.runtime import CurrentMesh
    from ..pmesh import ParticleMesh
    import numpy as np

    box = float(ctx.get('box', 1000.0))
    nbins = int(ctx.get('nbins', 3))
    nmesh = int(ctx.get('nmesh', 64))
    rng = np.random.RandomState(int(ctx.get('seed', 7)))
    npart = int(ctx['npart'])
    pos = rng.uniform(0.0, box, size=(npart, 3))
    w = np.ones(npart)
    mesh = CurrentMesh.resolve(None)

    def once():
        from ..algorithms.bispectrum import (direct_bispectrum,
                                             fft_bispectrum)
        method = _global_options['bspec_method']
        if method == 'direct':
            tile = _global_options['pairblock_tile']
            B, _ = direct_bispectrum(
                pos, w, box, nbins,
                tile=None if tile in (None, 'auto') else int(tile),
                comm=mesh)
        else:
            import jax.numpy as jnp
            pm = ParticleMesh(Nmesh=nmesh, BoxSize=box,
                              dtype=ctx.get('dtype', 'f4'),
                              comm=mesh)
            delta = pm.paint(jnp.asarray(pos, pm.dtype), 1.0)
            B, _ = fft_bispectrum(pm, pm.r2c(delta), nbins)
        return float(np.nansum(B))
    return once


def bspec_space():
    return SearchSpace('bspec', ('bspec_method', 'pairblock_tile'),
                       _bspec_candidates, _bspec_runner)


def default_spaces():
    """``{op: SearchSpace}`` of every built-in space."""
    return {'paint': paint_space(), 'fft': fft_space(),
            'exchange': exchange_space(), 'ingest': ingest_space(),
            'bspec': bspec_space()}
