"""Dispatch-time resolution of ``'auto'`` options through the cache.

``set_options(paint_method='auto')`` / ``fft_chunk_bytes='auto'`` /
``exchange_capacity(..., slack='auto')`` mean "use the measured
winner for this platform/shape if one exists, else today's default".
The contract is:

- **cold cache: zero trial overhead.**  Resolution never runs a
  trial; a miss costs one ``stat`` plus dict lookups and returns the
  same defaults the option would have had before this subsystem
  existed.  Populating the cache is an offline act
  (``nbodykit-tpu-tune``).
- **warm cache: the measured winner wins.**  Exact shape-class hits
  are preferred; a nearest-class fallback (same platform / device
  kind / op / dtype) is used otherwise and flagged as such.
- an explicit (non-``'auto'``) option is never overridden — the cache
  only answers questions it was asked.

Every consulted resolution bumps ``tune.resolve.hit`` /
``tune.resolve.nearest`` / ``tune.resolve.miss`` so a trace shows
which of a run's choices were measured and which were defaults.
"""

from .cache import TuneCache, device_signature, shape_class

# paint kernels jax reverse mode differentiates natively: the scatter
# chain is pure .at[].add / gather jnp ops whose VJP is the existing
# readout.  'sort' (while_loop), 'segsum'/'streams' (argsort buckets,
# replica-mesh fori loops) and 'mxu' (slack-sized buckets with the
# traced return_dropped overflow contract) are NOT — they either
# refuse reverse mode outright or impose contracts a silent custom_vjp
# forward cannot honor.  forward/adjoint.py wraps the GRAD_WRAPPED set
# in explicit custom_vjp pairs (winner kernel forward, readout-based
# analytic backward); anything else demotes via
# resolve_paint(differentiable=True) — the grad-mode fallback the
# resolver knows about (docs/FORWARD.md).
DIFFERENTIABLE_PAINT = frozenset({'scatter'})
GRAD_WRAPPED_PAINT = frozenset({'sort', 'segsum', 'streams'})

# the pre-tuner defaults, used verbatim on a cold cache
FALLBACKS = {
    'paint_method': 'scatter',
    'paint_order': 'auto',          # hardware heuristic (ops/radix.py)
    'paint_deposit': 'xla',
    'paint_chunk_size': 1024 * 1024 * 16,
    'paint_streams': 4,            # replica meshes of the streams kernel
    'fft_chunk_bytes': 2 ** 31,
    'fft_decomp': 'slab',          # cold cache: the proven decomposition
    'fft_pencil': None,            # near-square default (runtime.py)
    'exchange_slack': 1.05,
    'mesh_dtype': 'f4',            # cold cache: full-width mesh storage
    'a2a_compress': 'none',        # cold cache: uncompressed payloads
    'ingest_chunk_rows': 262144,   # cold cache: the streaming window
    'bspec_method': 'fft',         # cold cache: the proven estimator
    'pairblock_tile': 1024,        # direct-path dense block edge
}


def _current(name):
    from .. import _global_options
    try:
        return _global_options[name]
    except KeyError:
        return None


def _consult(op, sclass, dtype, nproc):
    """``(winner_options, source)`` for one cache question; source is
    ``'cache'`` / ``'cache-nearest'`` / ``'default'``."""
    from ..diagnostics import counter
    sig = device_signature(count=nproc)
    entry, match = TuneCache().lookup(sig[0], sig[1], sig[2], op,
                                      sclass, dtype)
    if entry is None:
        counter('tune.resolve.miss').add(1)
        return {}, 'default'
    if match == 'exact':
        counter('tune.resolve.hit').add(1)
        return dict(entry['winner']), 'cache'
    counter('tune.resolve.nearest').add(1)
    return dict(entry['winner']), 'cache-nearest'


def resolve_paint(nmesh, npart, dtype='f4', nproc=1,
                  differentiable=False):
    """The effective paint configuration for one call: current options
    with every ``'auto'`` replaced by the cache winner (or the
    fallback).  Returns the four paint options plus ``source``
    (``'explicit'`` when nothing was ``'auto'``) and, when the cache
    answered, ``winner_name``.

    ``differentiable=True`` is the grad-mode resolution
    (docs/FORWARD.md): a winner whose kernel jax cannot reverse-
    differentiate natively (:data:`DIFFERENTIABLE_PAINT`) is DEMOTED
    to the nearest differentiable candidate ('scatter' — same
    one-chain deposit, natively adjoint via readout) instead of
    tracing into a ``jax.grad`` error deep inside the pipeline.  The
    demotion is never silent: ``source`` becomes ``'grad-fallback'``,
    the original winner stays in ``winner_name``, the
    ``tune.grad_fallback`` counter bumps and a one-line WARN is
    logged.  Explicit (non-'auto') methods demote the same way —
    grad mode is a hard correctness constraint, not a preference."""
    opts = {k: _current(k) for k in
            ('paint_method', 'paint_order', 'paint_deposit',
             'paint_chunk_size', 'paint_streams')}
    # paint_order/'auto' and paint_deposit/'auto' keep their hardware-
    # heuristic meaning unless the METHOD itself asked the tuner:
    # consulting the cache for every default-configured paint would
    # let a committed database silently re-style explicit benchmarks
    asked = (opts['paint_method'] == 'auto'
             or opts['paint_chunk_size'] == 'auto')
    cfg = dict(opts)
    cfg['source'] = 'explicit'
    if asked:
        winner, source = _consult(
            'paint', shape_class(nmesh=nmesh, npart=npart), dtype,
            nproc)
        cfg['source'] = source
        if winner:
            cfg['winner_name'] = winner.get('paint_method')
        # only the options the caller left 'auto' take the winner's
        # value — an explicit paint_order/'radix' next to
        # paint_method='auto' stays explicit
        for key in ('paint_method', 'paint_order', 'paint_deposit',
                    'paint_chunk_size', 'paint_streams'):
            if opts[key] == 'auto':
                cfg[key] = winner.get(key, FALLBACKS[key])
    # concreteness guarantees: the 'auto' sentinel survives only for
    # paint_order (the hardware heuristic in ops/radix dispatch)
    if cfg['paint_method'] == 'auto':
        cfg['paint_method'] = FALLBACKS['paint_method']
    if isinstance(cfg['paint_chunk_size'], bool) or \
            not isinstance(cfg['paint_chunk_size'], (int, float)):
        cfg['paint_chunk_size'] = FALLBACKS['paint_chunk_size']
    cfg['paint_chunk_size'] = int(cfg['paint_chunk_size'])
    if isinstance(cfg['paint_streams'], bool) or \
            not isinstance(cfg['paint_streams'], (int, float)):
        cfg['paint_streams'] = FALLBACKS['paint_streams']
    cfg['paint_streams'] = int(cfg['paint_streams'])
    if differentiable and cfg['paint_method'] not in \
            DIFFERENTIABLE_PAINT:
        from ..diagnostics import counter
        import logging
        demoted = cfg['paint_method']
        cfg.setdefault('winner_name', demoted)
        cfg['paint_method'] = 'scatter'
        cfg['source'] = 'grad-fallback'
        counter('tune.grad_fallback').add(1)
        logging.getLogger('nbodykit_tpu.tune').warning(
            "grad-mode paint resolution: demoting %r (not natively "
            "differentiable) to 'scatter' for this call "
            "(tune.grad_fallback)", demoted)
    return cfg


def resolve_paint_deposit(nmesh=None, npart=None, dtype='f4', nproc=1):
    """The deposit engine for ``deposit='auto'`` in
    :func:`~nbodykit_tpu.ops.paint.paint_local_mxu`: the cache
    winner's ``paint_deposit`` when a measured paint entry exists for
    this platform/shape, else ``'xla'`` (the proven-everywhere
    engine)."""
    winner, _ = _consult('paint',
                         shape_class(nmesh=nmesh, npart=npart)
                         if (nmesh or npart) else 'mesh1',
                         dtype, nproc)
    dep = winner.get('paint_deposit', FALLBACKS['paint_deposit'])
    return FALLBACKS['paint_deposit'] if dep == 'auto' else dep


def resolve_fft_chunk_bytes(shape=None, dtype='f4', nproc=1,
                            mesh_shape=None):
    """Concrete ``fft_chunk_bytes`` when the option is ``'auto'``:
    the cache winner for the nearest measured mesh class, else the
    pre-tuner default (2**31).  ``mesh_shape`` is the (Px, Py) pencil
    factorization when one is in play — it narrows the lookup to
    entries measured under the same factorization (the shape class
    carries it; see cache.py)."""
    v = _current('fft_chunk_bytes')
    if not isinstance(v, bool) and isinstance(v, (int, float)):
        return int(v)
    nmesh = int(max(shape)) if shape else None
    winner, _ = _consult('fft',
                         shape_class(nmesh=nmesh,
                                     mesh_shape=mesh_shape) if nmesh
                         else 'mesh1', dtype, nproc)
    return int(winner.get('fft_chunk_bytes',
                          FALLBACKS['fft_chunk_bytes']))


def resolve_fft_decomp(shape=None, dtype='f4', nproc=1,
                       mesh_shape=None):
    """The measured slab-vs-pencil winner for
    ``set_options(fft_decomp='auto')``: ``('slab'|'pencil',
    (Px, Py) or None)``.

    Consults the cache keyed by (device_count, shape_class) where the
    shape class carries the (Px, Py) factorization the transform WOULD
    run with — so a pencil winner measured on 4x2 can only answer 4x2
    questions (ISSUE 9 satellite: the key must not ignore the device
    mesh shape).  Cold cache → ``('slab', None)`` at zero trial cost.
    """
    nmesh = int(max(shape)) if shape else None
    winner, _ = _consult('fft',
                         shape_class(nmesh=nmesh,
                                     mesh_shape=mesh_shape) if nmesh
                         else 'mesh1', dtype, nproc)
    decomp = winner.get('fft_decomp', FALLBACKS['fft_decomp'])
    if decomp not in ('slab', 'pencil'):
        decomp = FALLBACKS['fft_decomp']
    pencil = winner.get('fft_pencil') or None
    if pencil is not None:
        try:
            px, _, py = str(pencil).lower().partition('x')
            pencil = (int(px), int(py))
            if pencil[0] * pencil[1] != int(nproc):
                pencil = None
        except ValueError:
            pencil = None
    return decomp, pencil


def resolve_mesh_dtype(nmesh=None, npart=None, nproc=1):
    """Concrete mesh STORAGE dtype token for
    ``set_options(mesh_dtype='auto')``: the cache winner's
    ``mesh_dtype`` for the nearest measured paint class (the knob is
    raced inside the paint space — it changes the deposit kernels),
    else ``'f4'`` (today's full-width behavior, the cold-cache
    contract).  A winner may only answer 'f4' or 'bf16'; anything else
    is treated as unmeasured."""
    v = _current('mesh_dtype')
    if v not in (None, 'auto'):
        return str(v)
    winner, _ = _consult('paint',
                         shape_class(nmesh=nmesh, npart=npart)
                         if (nmesh or npart) else 'mesh1', 'f4', nproc)
    dt = winner.get('mesh_dtype', FALLBACKS['mesh_dtype'])
    return dt if dt in ('f4', 'bf16') else FALLBACKS['mesh_dtype']


def resolve_a2a_compress(shape=None, dtype='f4', nproc=1,
                         mesh_shape=None):
    """Concrete FFT all_to_all wire format for
    ``set_options(a2a_compress='auto')``: the cache winner's
    ``a2a_compress`` for the nearest measured fft class (the knob is
    raced inside the fft space, keyed by the same (Px, Py)-aware shape
    class as ``fft_decomp``), else ``'none'`` (uncompressed — the
    cold-cache contract).  Only formats :func:`~nbodykit_tpu.parallel.
    dfft._a2a` implements may win."""
    v = _current('a2a_compress')
    if v not in (None, 'auto'):
        return str(v)
    nmesh = int(max(shape)) if shape else None
    winner, _ = _consult('fft',
                         shape_class(nmesh=nmesh,
                                     mesh_shape=mesh_shape) if nmesh
                         else 'mesh1', dtype, nproc)
    mode = winner.get('a2a_compress', FALLBACKS['a2a_compress'])
    return mode if mode in ('none', 'bf16', 'int16') \
        else FALLBACKS['a2a_compress']


def resolve_exchange_slack(npart=None, nproc=1):
    """Concrete counted-exchange slack for ``slack='auto'``: the cache
    winner for the nearest measured particle class, else 1.05 (the
    pre-tuner default of
    :meth:`~nbodykit_tpu.pmesh.ParticleMesh.exchange_capacity`)."""
    winner, _ = _consult('exchange',
                         shape_class(npart=npart) if npart
                         else 'part1e0', 'f4', nproc)
    return float(winner.get('exchange_slack',
                            FALLBACKS['exchange_slack']))


def resolve_ingest_chunk_rows(npart=None, nproc=1):
    """Concrete streaming window for
    ``set_options(ingest_chunk_rows='auto')``: the cache winner for
    the nearest measured part-count class (the ``ingest`` op raced by
    ``nbodykit-tpu-tune``), else 262144 rows — the cold-cache default
    equal to pre-tuner behavior."""
    v = _current('ingest_chunk_rows')
    if not isinstance(v, bool) and isinstance(v, (int, float)):
        return max(int(v), 1)
    winner, _ = _consult('ingest',
                         shape_class(npart=npart) if npart
                         else 'part1e0', 'f4', nproc)
    rows = winner.get('ingest_chunk_rows',
                      FALLBACKS['ingest_chunk_rows'])
    if isinstance(rows, bool) or not isinstance(rows, (int, float)):
        rows = FALLBACKS['ingest_chunk_rows']
    return max(int(rows), 1)


def resolve_bispectrum(nmesh=None, npart=None, dtype='f4', nproc=1):
    """The effective bispectrum configuration for one call:
    ``{'bspec_method', 'pairblock_tile', 'source'}`` with every
    ``'auto'`` replaced by the ``bspec`` cache winner (or the
    fallback — ``'fft'`` on a cold cache, the zero-trial contract).

    The FFT/direct crossover is a *measured* per-platform property
    (the direct path's dense pairwise blocks win only where the MXU's
    FLOP rate beats the FFT's all_to_all wire time — ISSUE 20), so
    ``'auto'`` asks the cache keyed by the same shape classes the
    ``bspec`` tune space races.  Explicit (non-``'auto'``) options are
    never overridden."""
    method = _current('bspec_method')
    tile = _current('pairblock_tile')
    cfg = {'bspec_method': method, 'pairblock_tile': tile,
           'source': 'explicit'}
    asked = (method in (None, 'auto')) or (tile in (None, 'auto'))
    if asked:
        winner, source = _consult(
            'bspec', shape_class(nmesh=nmesh, npart=npart), dtype,
            nproc)
        cfg['source'] = source
        if winner:
            cfg['winner_name'] = winner.get('bspec_method')
        if method in (None, 'auto'):
            cfg['bspec_method'] = winner.get(
                'bspec_method', FALLBACKS['bspec_method'])
        if tile in (None, 'auto'):
            cfg['pairblock_tile'] = winner.get(
                'pairblock_tile', FALLBACKS['pairblock_tile'])
    # concreteness guarantees
    if cfg['bspec_method'] not in ('fft', 'direct'):
        cfg['bspec_method'] = FALLBACKS['bspec_method']
    if isinstance(cfg['pairblock_tile'], bool) or \
            not isinstance(cfg['pairblock_tile'], (int, float)):
        cfg['pairblock_tile'] = FALLBACKS['pairblock_tile']
    cfg['pairblock_tile'] = max(int(cfg['pairblock_tile']), 8)
    return cfg


def effective_int_option(option):
    """A concrete integer for a possibly-``'auto'`` option — the value
    the resilience ladder halves from
    (:func:`~nbodykit_tpu.resilience.supervise.default_ladder`)."""
    v = _current(option)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        if option == 'fft_chunk_bytes':
            return resolve_fft_chunk_bytes()
        return int(FALLBACKS[option])
    return int(v)


def tuned_snapshot(nmesh=None, npart=None, dtype='f4', nproc=1):
    """What a bench record stamps next to its measurement: the
    effective paint configuration and FFT chunk target this
    measurement actually ran with, plus where each came from
    ('explicit' / 'default' / 'cache' / 'cache-nearest') and the cache
    file consulted."""
    paint = resolve_paint(nmesh=nmesh, npart=npart, dtype=dtype,
                          nproc=nproc)
    fft_v = _current('fft_chunk_bytes')
    fft_auto = not isinstance(fft_v, (int, float)) \
        or isinstance(fft_v, bool)
    from ..parallel.dfft import resolve_decomp
    decomp, pxpy = resolve_decomp(
        nproc, shape=(nmesh,) * 3 if nmesh else None, dtype=dtype)
    _bspec = resolve_bispectrum(nmesh=nmesh, npart=npart, dtype=dtype,
                                nproc=nproc)
    return {
        'paint_method': paint['paint_method'],
        'paint_order': paint['paint_order'],
        'paint_deposit': paint['paint_deposit'],
        'paint_chunk_size': paint['paint_chunk_size'],
        'paint_streams': paint['paint_streams'],
        'paint_source': paint['source'],
        'fft_chunk_bytes': resolve_fft_chunk_bytes(
            shape=(nmesh,) * 3 if nmesh else None, dtype=dtype,
            nproc=nproc,
            mesh_shape=pxpy if decomp == 'pencil' else None),
        'fft_source': 'auto' if fft_auto else 'explicit',
        # the resolved decomposition and device-mesh shape this
        # measurement actually ran with (BENCH_r07+ attributability)
        'fft_decomp': decomp,
        'fft_pencil': ('%dx%d' % pxpy
                       if (pxpy and decomp == 'pencil') else None),
        'fft_decomp_source': (
            'auto' if _current('fft_decomp') == 'auto' else 'explicit'),
        # the precision posture this measurement ran with (ISSUE 13:
        # compressed-candidate numbers must be attributable)
        'mesh_dtype': resolve_mesh_dtype(nmesh=nmesh, npart=npart,
                                         nproc=nproc),
        'a2a_compress': resolve_a2a_compress(
            shape=(nmesh,) * 3 if nmesh else None, dtype=dtype,
            nproc=nproc,
            mesh_shape=pxpy if decomp == 'pencil' else None),
        # the streaming-ingestion window this measurement ran with
        # (ISSUE 14: ingest GB/s numbers must be attributable)
        'ingest_chunk_rows': resolve_ingest_chunk_rows(npart=npart,
                                                       nproc=nproc),
        'ingest_source': (
            'auto' if _current('ingest_chunk_rows') == 'auto'
            else 'explicit'),
        # the bispectrum estimator + direct-path tile this measurement
        # would dispatch with (ISSUE 20: the fft/direct crossover is a
        # measured per-platform property)
        'bspec_method': _bspec['bspec_method'],
        'pairblock_tile': _bspec['pairblock_tile'],
        'bspec_source': _bspec['source'],
        'cache': TuneCache().path,
    }
