"""The persistent per-platform performance database (``TUNE_CACHE.json``).

One JSON file holds every measured winner, content-keyed by

    (platform, device kind, device count, op, shape class, dtype)

so a number measured on a v5e chip can never silently steer a CPU run
(and vice versa — the round-5 failure mode was exactly a hand-picked
kernel choice that lost on the real hardware).  Writes are atomic
(tmp + ``os.replace``, the same discipline as
:mod:`..resilience.checkpoint`), reads are mtime-cached so dispatch-time
lookups cost one ``stat`` plus dict lookups.

Shape classes bucket (Nmesh, Npart) logarithmically — ``mesh512-part1e7``
— because the kernel ranking flips with regime, not with the exact
count (Jing 2005; Cui et al. 2008, PAPERS.md).  A lookup that misses its
exact class falls back to the *nearest* measured class of the same
(platform, device kind, op, dtype), preferring the same device count;
the match kind is reported so callers (and the doctor) can tell a
measured answer from an extrapolated one.

Every entry carries ``measured_at``; :func:`entry_age_days` feeds the
doctor's staleness verdict (entries older than 30 days WARN — a tuned
choice is evidence, and evidence goes stale).
"""

import json
import math
import os
import re
import threading
import time

import numpy as np

# options a winner config may legitimately carry (anything else in a
# committed cache is a validation error, not silently applied)
TUNABLE_OPTIONS = ('paint_method', 'paint_order', 'paint_deposit',
                   'paint_chunk_size', 'paint_bucket_slack',
                   'paint_streams', 'fft_chunk_bytes', 'fft_decomp',
                   'fft_pencil', 'exchange_slack', 'mesh_dtype',
                   'a2a_compress', 'ingest_chunk_rows')

STALE_DAYS = 30.0

_ENTRY_REQUIRED = ('platform', 'device_kind', 'device_count', 'op',
                   'shape_class', 'dtype', 'measured_at')

_CLASS_RE = re.compile(
    r'^mesh(\d+)(?:-part1e(\d+))?(?:-g\d+x\d+)?$'
    r'|^part1e(\d+)(?:-g\d+x\d+)?$')
_FACTOR_RE = re.compile(r'-g(\d+)x(\d+)$')


def utcnow():
    return time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())


# ---------------------------------------------------------------------------
# shape classes

def shape_class(nmesh=None, npart=None, mesh_shape=None):
    """The logarithmic shape bucket for (nmesh, npart):
    ``mesh512-part1e7`` / ``mesh512`` / ``part1e7``.  Nmesh buckets to
    the nearest power of two, Npart to the nearest decade.

    ``mesh_shape`` is the (Px, Py) device-mesh factorization when the
    op's ranking depends on it (the fft decomp knob): it appends
    ``-g4x2``-style suffix, making classes measured under different
    factorizations mutually incomparable (:func:`class_distance`) — a
    pencil winner measured on a 4x2 mesh must never be replayed onto
    8x1, where the two transposes have entirely different shapes.
    """
    parts = []
    if nmesh:
        parts.append('mesh%d' % (1 << max(0, int(round(
            math.log2(float(nmesh)))))))
    if npart:
        parts.append('part1e%d' % max(0, int(round(
            math.log10(float(npart))))))
    if not parts:
        raise ValueError('shape_class needs nmesh and/or npart')
    if mesh_shape is not None:
        px, py = mesh_shape
        parts.append('g%dx%d' % (int(px), int(py)))
    return '-'.join(parts)


def class_coords(sclass):
    """``(log2 nmesh, log10 npart)`` (either may be None) for a shape
    class string, or None when it does not parse."""
    m = _CLASS_RE.match(str(sclass))
    if not m:
        return None
    mesh, part, part_only = m.groups()
    lm = math.log2(int(mesh)) if mesh else None
    lp = float(part if part is not None else part_only) \
        if (part is not None or part_only is not None) else None
    return (lm, lp)


def class_factorization(sclass):
    """The (Px, Py) device-mesh factorization suffix of a shape class
    (``mesh256-g4x2`` -> (4, 2)), or None when absent."""
    m = _FACTOR_RE.search(str(sclass))
    if not m:
        return None
    return (int(m.group(1)), int(m.group(2)))


def class_distance(a, b):
    """Log-space distance between two shape classes; None when either
    does not parse or they describe different axes (a mesh-only class
    is not comparable to a part-only one, and classes keyed under
    different device-mesh factorizations are mutually incomparable)."""
    ca, cb = class_coords(a), class_coords(b)
    if ca is None or cb is None:
        return None
    if class_factorization(a) != class_factorization(b):
        return None
    d = 0.0
    for xa, xb in zip(ca, cb):
        if (xa is None) != (xb is None):
            return None
        if xa is not None:
            d += (xa - xb) ** 2
    return math.sqrt(d)


def canonical_dtype(dtype):
    """Canonical dtype name for a cache key.  Complex dtypes map to
    their real base (``c8`` -> ``float32``): the FFT chunk target for a
    field is a property of its real footprint, and the tuner measures
    the forward r2c.  The ``'bf16'`` storage token (which ``np.dtype``
    cannot parse) keys as ``bfloat16``."""
    if str(dtype).lower() in ('bf16', 'bfloat16'):
        return 'bfloat16'
    dt = np.dtype(dtype)
    if dt.kind == 'c':
        dt = np.dtype('f4' if dt.itemsize == 8 else 'f8')
    return dt.name


# ---------------------------------------------------------------------------
# device signature

def device_signature(count=None):
    """``(platform, device_kind, device_count)`` of the running
    backend.  ``count`` overrides the device count with the size of
    the mesh the op actually runs on (a paint on a 1-device
    ``ParticleMesh`` in an 8-device process is a 1-device paint)."""
    try:
        import jax
        devs = jax.devices()
        d = devs[0]
        plat = str(d.platform)
        kind = str(getattr(d, 'device_kind', plat))
        n = len(devs)
    except Exception:
        plat, kind, n = 'unknown', 'unknown', 1
    if count is not None:
        n = int(count)
    return (plat, kind, n)


def make_key(platform, device_kind, device_count, op, sclass, dtype):
    return '|'.join([str(platform), str(device_kind),
                     str(int(device_count)), str(op), str(sclass),
                     canonical_dtype(dtype)])


def entry_key(entry):
    return make_key(entry['platform'], entry['device_kind'],
                    entry['device_count'], entry['op'],
                    entry['shape_class'], entry['dtype'])


def entry_age_days(entry, now=None):
    """Days since the entry's measurement, or None without a parseable
    stamp."""
    from ..diagnostics.regress import parse_utc
    ts = parse_utc(entry.get('measured_at'))
    if ts is None:
        return None
    return ((time.time() if now is None else now) - ts) / 86400.0


# ---------------------------------------------------------------------------
# default location

def default_cache_path():
    """The committed repo-root ``TUNE_CACHE.json`` when running from a
    checkout, else a ``TUNE_CACHE.json`` next to the installed package."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, 'TUNE_CACHE.json')


def cache_path():
    """The active cache path: the ``tune_cache`` option (seeded from
    ``$NBKIT_TUNE_CACHE``) when set, else :func:`default_cache_path`."""
    try:
        from .. import _global_options
        configured = _global_options['tune_cache']
    except (ImportError, KeyError):
        configured = None
    return str(configured) if configured else default_cache_path()


# mtime-cached loads: dispatch-time resolution costs one stat.  The
# memo is hit concurrently by every serve worker thread resolving
# tuned options per request (nbodykit_tpu.serve), so reads and
# writes go through one lock — a dict half-updated by a racing
# loader must never be visible.
_loaded = {}            # path -> (mtime_ns, size, entries)
_loaded_lock = threading.Lock()


def _load_entries(path):
    try:
        st = os.stat(path)
    except OSError:
        return {}
    tag = (st.st_mtime_ns, st.st_size)
    with _loaded_lock:
        hit = _loaded.get(path)
        if hit is not None and hit[0] == tag:
            return hit[1]
    # parse outside the lock (a slow disk must not serialize every
    # dispatch); concurrent loaders may parse twice, last one wins —
    # both parsed the same (mtime, size) snapshot
    try:
        with open(path) as f:
            entries = dict(json.load(f).get('entries') or {})
    except (OSError, ValueError):
        entries = {}
    with _loaded_lock:
        _loaded[path] = (tag, entries)
    return entries


def reset_cache_memo():
    """Drop the mtime memo (test isolation)."""
    with _loaded_lock:
        _loaded.clear()


class TuneCache(object):
    """The performance database over one JSON file (default:
    :func:`cache_path`)."""

    def __init__(self, path=None):
        self.path = str(path) if path else cache_path()

    def entries(self):
        """``{key: entry}`` of every committed record (mtime-cached)."""
        return _load_entries(self.path)

    def get(self, platform, device_kind, device_count, op, sclass,
            dtype):
        return self.entries().get(make_key(
            platform, device_kind, device_count, op, sclass, dtype))

    def lookup(self, platform, device_kind, device_count, op, sclass,
               dtype):
        """``(entry, match)`` with match ``'exact'`` / ``'nearest'``,
        or ``(None, 'miss')``.  Nearest fallback searches the same
        (platform, device kind, op, dtype) for the closest shape
        class, preferring entries measured at the same device count;
        winner-less entries (everything infeasible) never match."""
        dtype = canonical_dtype(dtype)
        exact = self.get(platform, device_kind, device_count, op,
                         sclass, dtype)
        if exact is not None and exact.get('winner'):
            return exact, 'exact'
        same_sig = [e for e in self.entries().values()
                    if e.get('platform') == platform
                    and e.get('device_kind') == device_kind
                    and e.get('op') == op
                    and e.get('dtype') == dtype
                    and e.get('winner')]
        if not same_sig:
            return None, 'miss'
        same_count = [e for e in same_sig
                      if int(e.get('device_count', -1))
                      == int(device_count)]
        best, best_d = None, None
        for e in (same_count or same_sig):
            d = class_distance(sclass, e.get('shape_class'))
            if d is None:
                continue
            if best is None or d < best_d:
                best, best_d = e, d
        if best is None:
            return None, 'miss'
        return best, 'nearest'

    def put(self, entry):
        """Merge one entry (keyed by :func:`entry_key`) and commit the
        whole file atomically (tmp + rename).  Returns the key."""
        from ..diagnostics.trace import atomic_write
        entry = dict(entry)
        entry.setdefault('measured_at', utcnow())
        key = entry_key(entry)
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        if not isinstance(data.get('entries'), dict):
            data = {'version': 1, 'entries': {}}
        data['version'] = 1
        data['entries'][key] = entry
        atomic_write(self.path,
                     json.dumps(data, indent=1, sort_keys=True))
        with _loaded_lock:
            _loaded.pop(self.path, None)
        return key


def validate_cache(path):
    """Schema problems of a committed cache file, as a list of strings
    (empty == valid).  A missing file is valid (cold cache); garbage
    or mis-keyed entries are not — the smoke gate runs this so a
    broken committed database cannot silently steer dispatch."""
    problems = []
    if not os.path.exists(path):
        return problems
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ['unreadable: %s' % e]
    entries = data.get('entries')
    if not isinstance(entries, dict):
        return ['no "entries" mapping']
    for key, entry in sorted(entries.items()):
        if not isinstance(entry, dict):
            problems.append('%s: entry is not an object' % key)
            continue
        missing = [k for k in _ENTRY_REQUIRED if entry.get(k) is None]
        if missing:
            problems.append('%s: missing %s' % (key, ','.join(missing)))
            continue
        try:
            want = entry_key(entry)
        except (KeyError, TypeError, ValueError) as e:
            problems.append('%s: unkeyable entry (%s)' % (key, e))
            continue
        if want != key:
            problems.append('%s: key does not match entry fields (%s)'
                            % (key, want))
        if class_coords(entry['shape_class']) is None:
            problems.append('%s: unparseable shape_class %r'
                            % (key, entry['shape_class']))
        winner = entry.get('winner')
        if winner is not None:
            if not isinstance(winner, dict):
                problems.append('%s: winner is not an options mapping'
                                % key)
            else:
                unknown = sorted(set(winner) - set(TUNABLE_OPTIONS))
                if unknown:
                    problems.append('%s: winner carries non-tunable '
                                    'option(s) %s'
                                    % (key, ','.join(unknown)))
        if not isinstance(entry.get('trials', {}), dict):
            problems.append('%s: trials is not a mapping' % key)
    return problems


def cache_summary(path, now=None, stale_days=STALE_DAYS):
    """Posture summary for the doctor / regression tracker: entry
    count, stale count, infeasible-candidate count, the set of
    platform/device-kind signatures present.  ``None`` when the file
    does not exist; an ``error`` key when it is malformed."""
    if not os.path.exists(path):
        return None
    problems = validate_cache(path)
    if problems:
        return {'path': path, 'error': '; '.join(problems[:3]),
                'problems': len(problems)}
    entries = _load_entries(path)
    stale = infeasible = 0
    platforms, ops = set(), {}
    for entry in entries.values():
        age = entry_age_days(entry, now=now)
        if age is None or age > stale_days:
            stale += 1
        infeasible += len(entry.get('infeasible') or [])
        platforms.add('%s/%s' % (entry.get('platform'),
                                 entry.get('device_kind')))
        ops[entry.get('op')] = ops.get(entry.get('op'), 0) + 1
    return {'path': path, 'entries': len(entries), 'stale': stale,
            'infeasible': infeasible, 'platforms': sorted(platforms),
            'ops': ops, 'stale_days': stale_days}
