"""Column transforms: stacking, concatenation, sky geometry.

Reference: ``nbodykit/transform.py`` (dask-lazy column math). Here
columns are jnp arrays, so these are jnp functions; the sky-coordinate
conversions mirror the reference's conventions (:110-489).
"""

import numpy as np
import jax.numpy as jnp


def StackColumns(*cols):
    """Stack 1-D columns into an (N, ncols) array (reference
    transform.py:5)."""
    cols = [jnp.asarray(c) for c in cols]
    return jnp.stack(cols, axis=-1)


def ConcatenateSources(*sources, **kwargs):
    """Concatenate catalogs along the particle axis (reference
    transform.py:29)."""
    from .source.catalog.array import ArrayCatalog
    columns = kwargs.get('columns', None)
    if columns is None:
        columns = sources[0].columns
        for s in sources[1:]:
            columns = [c for c in columns if c in s.columns]
    else:
        if isinstance(columns, str):
            columns = [columns]
        for c in columns:
            for s in sources:
                if c not in s.columns:
                    raise ValueError(
                        "cannot concatenate column %r: not in every "
                        "source (available: %s)" % (c, s.columns))
    data = {c: jnp.concatenate([s[c] for s in sources], axis=0)
            for c in columns}
    attrs = {}
    for s in sources:
        attrs.update(s.attrs)
    return ArrayCatalog(data, comm=sources[0].comm, **attrs)


def ConstantArray(value, size, chunks=None):
    """A constant column (reference transform.py:89)."""
    return jnp.broadcast_to(jnp.asarray(value), (size,) +
                            np.shape(np.asarray(value))).reshape(
        (size,) + np.shape(np.asarray(value)))


# ICRS -> galactic rotation (J2000; the standard IAU matrix used by
# astropy's Galactic frame): v_gal = _ICRS_TO_GAL @ v_icrs
_ICRS_TO_GAL = np.array([
    [-0.0548755604162154, -0.8734370902348850, -0.4838350155487132],
    [+0.4941094278755837, -0.4448296299600112, +0.7469822444972189],
    [-0.8676661490190047, -0.1980763734312015, +0.4559837761750669]])


def _check_frame(frame):
    if frame not in ('icrs', 'galactic'):
        raise ValueError("frame must be 'icrs' or 'galactic', got %r"
                         % (frame,))


def CartesianToEquatorial(pos, observer=[0, 0, 0], frame='icrs'):
    """Cartesian -> (lon, lat) degrees in the requested frame
    (reference transform.py:110; frame='galactic' applies the standard
    ICRS->galactic rotation the reference gets from astropy)."""
    _check_frame(frame)
    pos = jnp.asarray(pos) - jnp.asarray(observer, dtype=jnp.asarray(pos).dtype)
    if frame == 'galactic':
        pos = pos @ jnp.asarray(_ICRS_TO_GAL.T, dtype=pos.dtype)
    s = jnp.hypot(pos[..., 0], pos[..., 1])
    lon = jnp.degrees(jnp.arctan2(pos[..., 1], pos[..., 0])) % 360.0
    lat = jnp.degrees(jnp.arctan2(pos[..., 2], s))
    return lon, lat


def SkyToUnitSphere(ra, dec, degrees=True):
    """(RA, Dec) -> unit vectors (reference transform.py:266)."""
    ra = jnp.asarray(ra)
    dec = jnp.asarray(dec)
    if degrees:
        ra = jnp.radians(ra)
        dec = jnp.radians(dec)
    x = jnp.cos(dec) * jnp.cos(ra)
    y = jnp.cos(dec) * jnp.sin(ra)
    z = jnp.sin(dec)
    return jnp.stack([x, y, z], axis=-1)


def SkyToCartesian(ra, dec, redshift, cosmo, observer=[0, 0, 0],
                   degrees=True, frame='icrs'):
    """(lon, lat, z) -> comoving Cartesian, in Mpc/h (reference
    transform.py:331). ``frame='galactic'`` interprets (lon, lat) as
    galactic coordinates and returns ICRS-aligned Cartesian."""
    _check_frame(frame)
    pos = SkyToUnitSphere(ra, dec, degrees=degrees)
    if frame == 'galactic':
        pos = pos @ jnp.asarray(_ICRS_TO_GAL, dtype=pos.dtype)
    r = jnp.asarray(cosmo.comoving_distance(np.asarray(redshift)))
    return r[..., None] * pos + jnp.asarray(observer,
                                            dtype=pos.dtype)


def CartesianToSky(pos, cosmo, velocity=None, observer=[0, 0, 0],
                   zmax=100.0, frame='icrs'):
    """Cartesian -> (RA, Dec, z[, z_rsd]) (reference transform.py:179).

    Redshift is inverted from the comoving distance on an interpolation
    grid out to ``zmax``.
    """
    _check_frame(frame)
    pos = jnp.asarray(pos) - jnp.asarray(observer, dtype=jnp.asarray(pos).dtype)
    ra, dec = CartesianToEquatorial(pos, frame=frame)
    r = jnp.sqrt((pos ** 2).sum(axis=-1))

    zgrid = np.concatenate([[0.0], np.logspace(-8, np.log10(zmax), 1024)])
    rgrid = np.asarray(cosmo.comoving_distance(zgrid))
    z = jnp.interp(r, jnp.asarray(rgrid), jnp.asarray(zgrid))

    if velocity is not None:
        # the returned z is the OBSERVED redshift including the
        # line-of-sight peculiar velocity (reference transform.py:
        # 238-241 folds vpec into z; it does not add a 4th output)
        velocity = jnp.asarray(velocity)
        rhat = pos / jnp.where(r == 0, 1.0, r)[..., None]
        vpec = (velocity * rhat).sum(axis=-1)
        z = z + vpec / 299792.458 * (1 + z)
    return ra, dec, z


def VectorProjection(vector, direction):
    """Project ``vector`` onto ``direction`` (reference
    transform.py:489)."""
    vector = jnp.asarray(vector)
    direction = jnp.asarray(direction, dtype=vector.dtype)
    direction = direction / jnp.sqrt(
        (direction ** 2).sum(axis=-1, keepdims=True))
    amp = (vector * direction).sum(axis=-1, keepdims=True)
    return amp * direction


# ---------------------------------------------------------------------------
# halo property transforms (reference transform.py:376-487, there via
# halotools; implemented analytically here)
# ---------------------------------------------------------------------------

def HaloRadius(mass, cosmo, redshift, mdef='vir'):
    """Spherical-overdensity radius (Mpc/h) for halo masses (M_sun/h)."""
    from .source.catalog.halos import halo_mass_definition
    rho = halo_mass_definition(mdef, cosmo, redshift)
    mass = jnp.asarray(mass)
    return (3.0 * mass / (4 * np.pi * rho)) ** (1.0 / 3)


def HaloConcentration(mass, cosmo, redshift, mdef='vir'):
    """Dutton & Maccio 2014 concentration-mass relation."""
    mass = jnp.asarray(mass)
    z = redshift
    b = -0.097 + 0.024 * z
    a = 0.537 + (1.025 - 0.537) * np.exp(-0.718 * z ** 1.08)
    return 10.0 ** (a + b * jnp.log10(mass / 1e12))


def HaloVelocityDispersion(mass, cosmo, redshift, mdef='vir'):
    """Virial velocity dispersion, km/s: sigma^2 ~ G M / (2 R)."""
    G = 4.302e-9  # Mpc (km/s)^2 / M_sun (with h's cancelling)
    R = HaloRadius(mass, cosmo, redshift, mdef)
    return jnp.sqrt(G * jnp.asarray(mass) / (2.0 * R))
