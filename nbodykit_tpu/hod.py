"""HOD: halo occupation distribution models and mock population.

Reference: ``nbodykit/hod.py:3-195`` + halo population in
``source/catalog/halos.py:202-270`` (there delegated to halotools).
Implemented natively: the Zheng et al. 2007 occupation functions plus
NFW satellite profile sampling with jax RNG — population is a
vectorized, device-count-invariant program.
"""

import numpy as np
import jax
import jax.numpy as jnp
from scipy import special

from .source.catalog.array import ArrayCatalog
from .utils import as_numpy


class PopulatedHaloCatalog(ArrayCatalog):
    """The galaxy catalog produced by HOD population (reference
    source/catalog/halos.py PopulatedHaloCatalog): an ArrayCatalog
    that remembers the ``model`` that made it."""

    def __init__(self, data, model=None, comm=None, **attrs):
        ArrayCatalog.__init__(self, data, comm=comm, **attrs)
        self.model = model


class Zheng07Model(object):
    """The 5-parameter Zheng07 HOD:

    <N_cen>(M) = 1/2 [1 + erf((logM - logMmin)/sigma_logM)]
    <N_sat>(M) = <N_cen> ((M - M0)/M1)^alpha  for M > M0

    Parameters match the conventional names (logMmin, sigma_logM,
    logM0, logM1, alpha); reference surface: hod.py:53.
    """

    def __init__(self, logMmin=13.031, sigma_logM=0.38, logM0=13.27,
                 logM1=14.08, alpha=0.76):
        self.params = dict(logMmin=logMmin, sigma_logM=sigma_logM,
                           logM0=logM0, logM1=logM1, alpha=alpha)

    def mean_ncen(self, M):
        p = self.params
        logM = np.log10(M)
        return 0.5 * (1 + special.erf(
            (logM - p['logMmin']) / p['sigma_logM']))

    def mean_nsat(self, M):
        p = self.params
        M0 = 10 ** p['logM0']
        M1 = 10 ** p['logM1']
        base = np.clip((M - M0) / M1, 0, None)
        return self.mean_ncen(M) * base ** p['alpha']


class Leauthaud11Model(object):
    """The Leauthaud et al. 2011 stellar-mass-threshold HOD
    (arXiv:1103.2077 eqs. 2-8, built on the Behroozi et al. 2010
    stellar-to-halo-mass relation, arXiv:1001.0015 eq. 21). The
    reference exposes this model as a halotools factory
    (``nbodykit/hod.py:191``); here the occupation functions are
    implemented directly.

    Centrals: the probability a halo of mass ``Mh`` hosts a galaxy
    above the stellar threshold, a lognormal-scatter erf of the SHMR:

        <Ncen>(Mh) = 1/2 [1 - erf((log10 m*_t - log10 f_SHMR(Mh))
                                  / (sqrt(2) sigma_logM*))]

    Satellites: a power law modulated by the central occupation:

        <Nsat>(Mh) = <Ncen>(Mh) (Mh/Msat)^alpha exp(-Mcut/Mh)
        Msat = 1e12 Bsat (Mh_t/1e12)^betasat,
        Mcut = 1e12 Bcut (Mh_t/1e12)^betacut,  Mh_t = f_SHMR^-1(m*_t)

    Defaults are the Leauthaud et al. 2012 SIG_MOD1 z~0.37 best fit
    (the same values halotools ships as the 'leauthaud11' defaults).
    Masses in Msun/h units; ``threshold`` is log10 of the stellar
    threshold.
    """

    def __init__(self, threshold=10.5, smhm_m0=10.72, smhm_m1=12.35,
                 smhm_beta=0.43, smhm_delta=0.56, smhm_gamma=1.54,
                 scatter=0.2, alphasat=1.0, bsat=10.62, betasat=0.859,
                 bcut=1.47, betacut=-0.13):
        self.params = dict(
            threshold=threshold, smhm_m0=smhm_m0, smhm_m1=smhm_m1,
            smhm_beta=smhm_beta, smhm_delta=smhm_delta,
            smhm_gamma=smhm_gamma, scatter=scatter, alphasat=alphasat,
            bsat=bsat, betasat=betasat, bcut=bcut, betacut=betacut)
        # Behroozi10 gives log10 Mh(m*) in closed form; tabulate it on
        # a dense stellar-mass grid and interpolate the inverse
        self._logms_grid = np.linspace(7.0, 12.8, 2048)
        self._logmh_grid = self._log_mhalo(self._logms_grid)
        p = self.params
        self._log_mh_thresh = float(self._log_mhalo(
            np.atleast_1d(p['threshold']))[0])
        mh_t12 = 10.0 ** (self._log_mh_thresh - 12.0)
        self._Msat = 1e12 * p['bsat'] * mh_t12 ** p['betasat']
        self._Mcut = 1e12 * p['bcut'] * mh_t12 ** p['betacut']

    def _log_mhalo(self, log_mstar):
        """Behroozi et al. 2010 eq. 21: log10 Mh as a function of
        log10 m* (the mean relation f_SHMR^-1)."""
        p = self.params
        r = 10.0 ** (log_mstar - p['smhm_m0'])  # m*/M*,0
        return (p['smhm_m1'] + p['smhm_beta'] * (log_mstar - p['smhm_m0'])
                + r ** p['smhm_delta'] / (1.0 + r ** (-p['smhm_gamma']))
                - 0.5)

    def _log_mstar(self, M):
        """f_SHMR(Mh): numerical inverse of the (monotone) SHMR."""
        logM = np.log10(np.clip(np.asarray(M, dtype='f8'), 1.0, None))
        return np.interp(logM, self._logmh_grid, self._logms_grid)

    def mean_ncen(self, M):
        p = self.params
        arg = (p['threshold'] - self._log_mstar(M)) \
            / (np.sqrt(2.0) * p['scatter'])
        return 0.5 * (1.0 - special.erf(arg))

    def mean_nsat(self, M):
        p = self.params
        M = np.asarray(M, dtype='f8')
        return (self.mean_ncen(M) * (M / self._Msat) ** p['alphasat']
                * np.exp(-self._Mcut / np.clip(M, 1.0, None)))


def _decorate(base, strength, percentile, split, upper=None):
    """Decorated-HOD perturbation (Hearin et al. 2016,
    arXiv:1512.03050): halos above the ``split`` percentile of the
    secondary property get ``base + strength * dmax`` and those below
    are compensated so the mass-binned mean is preserved exactly.
    ``dmax`` is the largest upper-branch perturbation keeping BOTH
    branches inside [0, upper] (the compensating lower-branch shift is
    ``-dmax * (1-split)/split``, so its own floor/ceiling bounds dmax
    too — without that, any split != 0.5 lets the clip break the
    mean)."""
    base = np.asarray(base, dtype='f8')
    frac_hi = 1.0 - split
    ratio = frac_hi / max(split, 1e-12)  # |delta_lo| = ratio*|delta_hi|
    if upper is None:
        up_room = np.inf
    else:
        up_room = upper - base
    if strength >= 0:
        # high branch rises (needs headroom), low branch falls
        # (needs floor): delta_hi <= min(up_room, base/ratio)
        dmax = np.minimum(up_room, base / max(ratio, 1e-12))
    else:
        # high branch falls, low branch rises
        dmax = np.minimum(base, up_room / max(ratio, 1e-12))
    delta_hi = strength * dmax
    delta_lo = -delta_hi * ratio
    out = np.where(np.asarray(percentile) >= split,
                   base + delta_hi, base + delta_lo)
    return np.clip(out, 0.0, upper)


class Hearin15Model(Leauthaud11Model):
    """Assembly-biased (decorated) Leauthaud11 HOD (Hearin & Watson
    2015 / Hearin et al. 2016 decorated-HOD framework; the reference's
    'hearin15' halotools factory, ``nbodykit/hod.py:192``): occupations
    additionally depend on the halo's concentration percentile at
    fixed mass. ``assembias_strength`` in [-1, 1] scales the maximal
    mean-preserving perturbation for centrals
    (``assembias_strength_sat`` for satellites, defaulting to the
    same value); ``split`` is the percentile boundary."""

    uses_assembly_bias = True

    def __init__(self, threshold=10.5, split=0.5, assembias_strength=0.5,
                 assembias_strength_sat=None, **kwargs):
        super().__init__(threshold=threshold, **kwargs)
        for name, val in [('assembias_strength', assembias_strength),
                          ('assembias_strength_sat',
                           assembias_strength_sat)]:
            if val is not None and not -1.0 <= val <= 1.0:
                # beyond +-1 the perturbation exceeds the bound dmax
                # was computed for and the clip would silently shift
                # the mass-binned mean
                raise ValueError("%s must lie in [-1, 1], got %r"
                                 % (name, val))
        if not 0.0 < split < 1.0:
            raise ValueError("split must lie in (0, 1), got %r" % split)
        self.params.update(
            split=split, assembias_strength=assembias_strength,
            assembias_strength_sat=(
                assembias_strength if assembias_strength_sat is None
                else assembias_strength_sat))

    def mean_ncen(self, M, percentile=None):
        base = super().mean_ncen(M)
        if percentile is None:
            return base
        p = self.params
        return _decorate(base, p['assembias_strength'], percentile,
                         p['split'], upper=1.0)

    def mean_nsat(self, M, percentile=None):
        base = super().mean_nsat(M)  # undecorated (percentile-free)
        if percentile is None:
            return base
        p = self.params
        return _decorate(base, p['assembias_strength_sat'], percentile,
                         p['split'], upper=None)


def mass_binned_percentile(M, secondary, nbins=20):
    """Rank-percentile of ``secondary`` among halos of similar mass
    (the conditioning variable of decorated-HOD assembly bias): log-M
    is split into ``nbins`` equal-count bins and each halo gets its
    secondary-property rank within its bin, in [0, 1)."""
    M = np.asarray(M, dtype='f8')
    sec = np.asarray(secondary, dtype='f8')
    order = np.argsort(np.argsort(M, kind='stable'), kind='stable')
    # equal-count mass bins via the rank of M
    b = (order * nbins) // max(len(M), 1)
    pct = np.zeros(len(M), dtype='f8')
    for bi in np.unique(b):
        sel = b == bi
        r = np.argsort(np.argsort(sec[sel], kind='stable'),
                       kind='stable')
        pct[sel] = (r + 0.5) / sel.sum()
    return pct


def _sample_nfw_radius(key, conc, n):
    """Draw scaled NFW radii r/rvir by inverse-CDF interpolation:
    m(x) = ln(1+cx) - cx/(1+cx), normalized at x=1."""
    x_grid = np.logspace(-3, 0, 256)

    def m(x, c):
        cx = c * x
        return np.log(1 + cx) - cx / (1 + cx)

    conc_np = np.asarray(conc)
    u = jax.random.uniform(key, (n,))
    # per-halo inverse CDF: vectorized via common x grid
    mgrid = m(x_grid[None, :], conc_np[:, None])
    mgrid = mgrid / mgrid[:, -1:]
    # vectorized per-row inverse CDF: bracket u in each row, then
    # linear-interpolate between the bracketing grid points
    u_np = np.asarray(u)
    j = (mgrid < u_np[:, None]).sum(axis=1)
    j = np.clip(j, 1, len(x_grid) - 1)
    rows = np.arange(n)
    m_lo = mgrid[rows, j - 1]
    m_hi = mgrid[rows, j]
    t = np.where(m_hi > m_lo, (u_np - m_lo) / np.where(
        m_hi > m_lo, m_hi - m_lo, 1.0), 0.0)
    out = x_grid[j - 1] + t * (x_grid[j] - x_grid[j - 1])
    return jnp.asarray(out)


class HODModel(object):
    """Populate a halo catalog with galaxies under an occupation model
    (reference HODModel/HODModelFactory, hod.py:3,122)."""

    def __init__(self, occupation=None, seed=None):
        self.occupation = occupation or Zheng07Model()
        self.seed = seed if seed is not None else \
            np.random.randint(0, 2 ** 31 - 1)

    def populate(self, halos, seed=None):
        """Return an ArrayCatalog of galaxies with Position, Velocity,
        and gal_type (0 = central, 1 = satellite)."""
        seed = self.seed if seed is None else seed
        key = jax.random.key(seed)
        k_cen, k_sat, k_rad, k_dir, k_vel = jax.random.split(key, 5)

        M = as_numpy(halos['Mass'])
        pos = as_numpy(halos['Position'])
        vel = as_numpy(halos['Velocity']) if 'Velocity' in halos \
            else np.zeros_like(pos)
        try:
            rvir = as_numpy(halos['Radius'])
        except Exception:
            rvir = 0.3 * (M / 1e13) ** (1.0 / 3)
        conc = None
        if 'Concentration' in halos:
            try:
                conc = as_numpy(halos['Concentration'])
            except Exception:
                conc = None
        has_conc = conc is not None
        if conc is None:
            # deterministic mass-scaling stand-in (NFW radii only —
            # never fed to the assembly-bias percentile below)
            conc = 7.0 * (M / 1e13) ** -0.1

        if getattr(self.occupation, 'uses_assembly_bias', False) \
                and has_conc:
            # decorated HOD: occupations also see the concentration
            # percentile at fixed mass (only with a REAL secondary
            # column — the deterministic mass-scaling fallback below
            # would degenerate the percentile into a mass rank and
            # fake an assembly-bias signal)
            pct = mass_binned_percentile(M, conc)
            ncen_mean = self.occupation.mean_ncen(M, percentile=pct)
            nsat_mean = self.occupation.mean_nsat(M, percentile=pct)
        else:
            if getattr(self.occupation, 'uses_assembly_bias', False):
                import warnings
                warnings.warn(
                    "assembly-biased occupation requested but the halo "
                    "catalog has no 'Concentration' column; populating "
                    "with the undecorated occupations")
            ncen_mean = self.occupation.mean_ncen(M)
            nsat_mean = self.occupation.mean_nsat(M)

        has_cen = np.asarray(
            jax.random.uniform(k_cen, (len(M),))) < ncen_mean
        nsat = np.asarray(jax.random.poisson(
            k_sat, jnp.asarray(nsat_mean)))
        nsat = nsat * has_cen  # satellites require a central

        # centrals
        cen_pos = pos[has_cen]
        cen_vel = vel[has_cen]

        # satellites: repeat halos, sample NFW radii + isotropic dirs
        idx = np.repeat(np.arange(len(M)), nsat)
        ntot_sat = len(idx)
        if ntot_sat > 0:
            x = np.asarray(_sample_nfw_radius(
                k_rad, conc[idx], ntot_sat))
            dirs = np.array(jax.random.normal(k_dir, (ntot_sat, 3)))
            dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
            sat_pos = pos[idx] + (x * rvir[idx])[:, None] * dirs
            # virial-scaled random velocities
            sigv = 100.0 * np.sqrt(M[idx] / 1e13)  # km/s scaling
            sat_vel = vel[idx] + sigv[:, None] * np.asarray(
                jax.random.normal(k_vel, (ntot_sat, 3)))
        else:
            sat_pos = np.empty((0, 3))
            sat_vel = np.empty((0, 3))

        gal_pos = np.concatenate([cen_pos, sat_pos])
        gal_vel = np.concatenate([cen_vel, sat_vel])
        gal_type = np.concatenate([np.zeros(len(cen_pos), dtype='i4'),
                                   np.ones(len(sat_pos), dtype='i4')])
        halo_mass = np.concatenate([M[has_cen], M[idx]]) \
            if ntot_sat else M[has_cen]

        if 'BoxSize' in halos.attrs:
            box = np.ones(3) * np.asarray(halos.attrs['BoxSize'])
            gal_pos = np.mod(gal_pos, box)

        cat = PopulatedHaloCatalog(
            {'Position': gal_pos, 'Velocity': gal_vel,
             'gal_type': gal_type, 'HaloMass': halo_mass},
            model=self, comm=halos.comm, **halos.attrs)
        cat.attrs['seed'] = seed
        cat.attrs.update(self.occupation.params)
        return cat

    def __call__(self, halos, seed=None):
        return self.populate(halos, seed=seed)


def HODModelFactory(occupation=None, **kwargs):
    """Build an HODModel (reference hod.py:122 parity shim)."""
    return HODModel(occupation=occupation, **kwargs)
