"""HOD: halo occupation distribution models and mock population.

Reference: ``nbodykit/hod.py:3-195`` + halo population in
``source/catalog/halos.py:202-270`` (there delegated to halotools).
Implemented natively: the Zheng et al. 2007 occupation functions plus
NFW satellite profile sampling with jax RNG — population is a
vectorized, device-count-invariant program.
"""

import numpy as np
import jax
import jax.numpy as jnp
from scipy import special

from .source.catalog.array import ArrayCatalog
from .utils import as_numpy


class Zheng07Model(object):
    """The 5-parameter Zheng07 HOD:

    <N_cen>(M) = 1/2 [1 + erf((logM - logMmin)/sigma_logM)]
    <N_sat>(M) = <N_cen> ((M - M0)/M1)^alpha  for M > M0

    Parameters match the conventional names (logMmin, sigma_logM,
    logM0, logM1, alpha); reference surface: hod.py:53.
    """

    def __init__(self, logMmin=13.031, sigma_logM=0.38, logM0=13.27,
                 logM1=14.08, alpha=0.76):
        self.params = dict(logMmin=logMmin, sigma_logM=sigma_logM,
                           logM0=logM0, logM1=logM1, alpha=alpha)

    def mean_ncen(self, M):
        p = self.params
        logM = np.log10(M)
        return 0.5 * (1 + special.erf(
            (logM - p['logMmin']) / p['sigma_logM']))

    def mean_nsat(self, M):
        p = self.params
        M0 = 10 ** p['logM0']
        M1 = 10 ** p['logM1']
        base = np.clip((M - M0) / M1, 0, None)
        return self.mean_ncen(M) * base ** p['alpha']


def _sample_nfw_radius(key, conc, n):
    """Draw scaled NFW radii r/rvir by inverse-CDF interpolation:
    m(x) = ln(1+cx) - cx/(1+cx), normalized at x=1."""
    x_grid = np.logspace(-3, 0, 256)

    def m(x, c):
        cx = c * x
        return np.log(1 + cx) - cx / (1 + cx)

    conc_np = np.asarray(conc)
    u = jax.random.uniform(key, (n,))
    # per-halo inverse CDF: vectorized via common x grid
    mgrid = m(x_grid[None, :], conc_np[:, None])
    mgrid = mgrid / mgrid[:, -1:]
    # vectorized per-row inverse CDF: bracket u in each row, then
    # linear-interpolate between the bracketing grid points
    u_np = np.asarray(u)
    j = (mgrid < u_np[:, None]).sum(axis=1)
    j = np.clip(j, 1, len(x_grid) - 1)
    rows = np.arange(n)
    m_lo = mgrid[rows, j - 1]
    m_hi = mgrid[rows, j]
    t = np.where(m_hi > m_lo, (u_np - m_lo) / np.where(
        m_hi > m_lo, m_hi - m_lo, 1.0), 0.0)
    out = x_grid[j - 1] + t * (x_grid[j] - x_grid[j - 1])
    return jnp.asarray(out)


class HODModel(object):
    """Populate a halo catalog with galaxies under an occupation model
    (reference HODModel/HODModelFactory, hod.py:3,122)."""

    def __init__(self, occupation=None, seed=None):
        self.occupation = occupation or Zheng07Model()
        self.seed = seed if seed is not None else \
            np.random.randint(0, 2 ** 31 - 1)

    def populate(self, halos, seed=None):
        """Return an ArrayCatalog of galaxies with Position, Velocity,
        and gal_type (0 = central, 1 = satellite)."""
        seed = self.seed if seed is None else seed
        key = jax.random.key(seed)
        k_cen, k_sat, k_rad, k_dir, k_vel = jax.random.split(key, 5)

        M = as_numpy(halos['Mass'])
        pos = as_numpy(halos['Position'])
        vel = as_numpy(halos['Velocity']) if 'Velocity' in halos \
            else np.zeros_like(pos)
        try:
            rvir = as_numpy(halos['Radius'])
        except Exception:
            rvir = 0.3 * (M / 1e13) ** (1.0 / 3)
        try:
            conc = as_numpy(halos['Concentration'])
        except Exception:
            conc = 7.0 * (M / 1e13) ** -0.1

        ncen_mean = self.occupation.mean_ncen(M)
        nsat_mean = self.occupation.mean_nsat(M)

        has_cen = np.asarray(
            jax.random.uniform(k_cen, (len(M),))) < ncen_mean
        nsat = np.asarray(jax.random.poisson(
            k_sat, jnp.asarray(nsat_mean)))
        nsat = nsat * has_cen  # satellites require a central

        # centrals
        cen_pos = pos[has_cen]
        cen_vel = vel[has_cen]

        # satellites: repeat halos, sample NFW radii + isotropic dirs
        idx = np.repeat(np.arange(len(M)), nsat)
        ntot_sat = len(idx)
        if ntot_sat > 0:
            x = np.asarray(_sample_nfw_radius(
                k_rad, conc[idx], ntot_sat))
            dirs = np.array(jax.random.normal(k_dir, (ntot_sat, 3)))
            dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
            sat_pos = pos[idx] + (x * rvir[idx])[:, None] * dirs
            # virial-scaled random velocities
            sigv = 100.0 * np.sqrt(M[idx] / 1e13)  # km/s scaling
            sat_vel = vel[idx] + sigv[:, None] * np.asarray(
                jax.random.normal(k_vel, (ntot_sat, 3)))
        else:
            sat_pos = np.empty((0, 3))
            sat_vel = np.empty((0, 3))

        gal_pos = np.concatenate([cen_pos, sat_pos])
        gal_vel = np.concatenate([cen_vel, sat_vel])
        gal_type = np.concatenate([np.zeros(len(cen_pos), dtype='i4'),
                                   np.ones(len(sat_pos), dtype='i4')])
        halo_mass = np.concatenate([M[has_cen], M[idx]]) \
            if ntot_sat else M[has_cen]

        if 'BoxSize' in halos.attrs:
            box = np.ones(3) * np.asarray(halos.attrs['BoxSize'])
            gal_pos = np.mod(gal_pos, box)

        cat = ArrayCatalog(
            {'Position': gal_pos, 'Velocity': gal_vel,
             'gal_type': gal_type, 'HaloMass': halo_mass},
            comm=halos.comm, **halos.attrs)
        cat.attrs['seed'] = seed
        cat.attrs.update(self.occupation.params)
        return cat

    def __call__(self, halos, seed=None):
        return self.populate(halos, seed=seed)


def HODModelFactory(occupation=None, **kwargs):
    """Build an HODModel (reference hod.py:122 parity shim)."""
    return HODModel(occupation=occupation, **kwargs)
