"""LinearNbody: linear-theory evolution of an N-body particle system.

Reference: ``nbodykit/cosmology/linearnbody.py:5`` — evolve particle
displacements/velocities with the linear growth solution (useful for
initializing or rewinding simulations):

    x(a2) = q + D1(a2)/D1(a1) (x(a1) - q)
    v     = a^2 H(a) dD1/da * psi
"""

import numpy as np
import jax.numpy as jnp

from .background import MatterDominated


class LinearNbody(object):
    """Scale particle displacements and momenta by linear growth.

    Parameters
    ----------
    cosmo : Cosmology
    """

    def __init__(self, cosmo):
        self.cosmo = cosmo
        self._pt = MatterDominated(
            Omega0_m=cosmo.Omega0_m,
            Omega0_lambda=cosmo.Omega0_lambda,
            Omega0_k=cosmo.Omega0_k)

    def integrate(self, q, disp, vel, a1, a2):
        """Evolve (positions-from-lattice ``disp``, velocities) from
        scale factor a1 to a2 in linear theory.

        Returns (disp2, vel2): disp scales with D1, velocity with the
        1LPT momentum growth Gp = a^2 E D1 f1.
        """
        pt = self._pt
        g1 = float(pt.D1(a1))
        g2 = float(pt.D1(a2))
        ratio = g2 / g1
        disp2 = disp * ratio
        vfac2 = float(a2 ** 2 * pt.E(a2) * pt.f1(a2) * 100.0) * g2 / g1
        vfac1 = float(a1 ** 2 * pt.E(a1) * pt.f1(a1) * 100.0)
        # scale velocities consistently with the displacement growth
        vel2 = vel * (vfac2 / vfac1)
        return disp2, vel2
