"""Correlation-function utilities: pk<->xi transforms and the
CorrelationFunction wrapper.

Reference: ``nbodykit/cosmology/correlation.py`` (pk_to_xi :39,
xi_to_pk :8, CorrelationFunction :70), there built on mcfit; here on
:mod:`nbodykit_tpu.ops.fftlog`.
"""

import numpy as np
from scipy import interpolate

from ..ops.fftlog import pk_to_xi_fftlog, xi_to_pk_fftlog


def pk_to_xi(k, Pk, ell=0, extrap=True):
    """Return a spline xi_l(r) from samples of P(k).

    Parameters mirror the reference's pk_to_xi: log-spaced k recommended;
    with ``extrap`` the input is power-law extended before transforming.
    """
    k = np.asarray(k, dtype='f8')
    Pk = np.asarray(Pk, dtype='f8')
    if extrap:
        k, Pk = _extend_loglog(k, Pk)
    r, xi = pk_to_xi_fftlog(k, Pk, ell=ell)
    sel = (r > 1e-3) & (r < 1e4)
    return interpolate.InterpolatedUnivariateSpline(r[sel], xi[sel], k=3)


def xi_to_pk(r, xi, ell=0, extrap=False):
    """Return a spline P_l(k) from samples of xi(r)."""
    r = np.asarray(r, dtype='f8')
    xi = np.asarray(xi, dtype='f8')
    if extrap:
        r, xi = _extend_loglog(r, xi)
    k, pk = xi_to_pk_fftlog(r, xi, ell=ell)
    sel = (k > 1e-5) & (k < 1e3)
    return interpolate.InterpolatedUnivariateSpline(k[sel], pk[sel], k=3)


def _extend_loglog(x, y, nlo=128, nhi=128):
    """Power-law extrapolation of (x, y) at both log ends."""
    lx, ly = np.log(x), np.log(np.abs(y) + 1e-300)
    slo = (ly[1] - ly[0]) / (lx[1] - lx[0])
    shi = (ly[-1] - ly[-2]) / (lx[-1] - lx[-2])
    shi = min(shi, -1.01)  # force decay on the high end
    dx = lx[1] - lx[0]
    xlo = np.exp(lx[0] + dx * np.arange(-nlo, 0))
    xhi = np.exp(lx[-1] + dx * np.arange(1, nhi + 1))
    ylo = y[0] * (xlo / x[0]) ** slo
    yhi = y[-1] * (xhi / x[-1]) ** shi
    return (np.concatenate([xlo, x, xhi]),
            np.concatenate([ylo, y, yhi]))


class CorrelationFunction(object):
    """xi(r) computed from any power-spectrum callable (reference
    correlation.py:70)."""

    def __init__(self, power, kmin=1e-5, kmax=1e2, nk=2048):
        self.power = power
        self.attrs = dict(getattr(power, 'attrs', {}))
        k = np.logspace(np.log10(kmin), np.log10(kmax), nk)
        self._spline = pk_to_xi(k, np.asarray(power(k)))
        if hasattr(power, 'redshift'):
            self.redshift = power.redshift

    def __call__(self, r):
        return self._spline(np.asarray(r, dtype='f8'))
