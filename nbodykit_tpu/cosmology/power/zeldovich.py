"""Zel'dovich-approximation power spectrum.

Reference surface: ``nbodykit/cosmology/power/zeldovich.py:27``
(ZeldovichPower), which evaluates the standard ZA resummation via a
tower of mcfit/FFTLog integrals (:186-238). Implemented here from the
published formulation (e.g. Vlah, White & Aviles 2015):

  X(q) = 1/(2 pi^2) int dk P_L(k) [2/3 - 2 j1(kq)/(kq)]
  Y(q) = 1/(2 pi^2) int dk P_L(k) [-2 j0(kq) + 6 j1(kq)/(kq)]

  P_ZA(k) = 4 pi int dq q^2 [ e^{-k^2 (X+Y)/2}
              sum_n (k Y(q) / q)^n j_n(kq)  -  e^{-k^2 sigma_psi^2} j0(kq) ]

where sigma_psi^2 = X(inf)/2 is the one-axis displacement dispersion;
the subtraction removes the unclustered (q -> inf) plateau. The n-sum is
truncated adaptively (the reference uses a similar truncation).

Validated by the low-k limit P_ZA -> P_L (tests/test_cosmology.py).
"""

import numpy as np
from scipy.special import spherical_jn
from scipy import interpolate

from .linear import LinearPower


class ZeldovichPower(object):
    """P_ZA(k) at a fixed redshift.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    transfer : transfer for the underlying LinearPower
    nmax : maximum order in the Bessel tower (default 32)
    """

    def __init__(self, cosmo, redshift, transfer='CLASS', nmax=32):
        self.cosmo = cosmo
        self.redshift = float(redshift)
        self.linear = LinearPower(cosmo, redshift, transfer=transfer)
        self.nmax = int(nmax)
        self.attrs = dict(self.linear.attrs)
        self._tables()

    def _tables(self):
        # k-grid for the linear power integrals
        lnk = np.linspace(np.log(1e-5), np.log(1e3), 2 ** 12)
        k = np.exp(lnk)
        P = self.linear(k)

        # q-grid for X, Y
        q = np.logspace(-2, 4, 1024)
        kq = np.outer(q, k)
        j0 = spherical_jn(0, kq)
        with np.errstate(invalid='ignore', divide='ignore'):
            j1_over = np.where(kq > 1e-8, spherical_jn(1, kq) / kq,
                               1.0 / 3.0)
        pref = 1.0 / (2 * np.pi ** 2)
        # integrate in dlnk: dk = k dlnk
        X = pref * np.trapezoid(P * k * (2.0 / 3 - 2 * j1_over), lnk,
                                axis=-1)
        Y = pref * np.trapezoid(P * k * (-2 * j0 + 6 * j1_over), lnk,
                                axis=-1)
        self.sigma_psi2 = pref * np.trapezoid(P * k / 3.0, lnk)
        # re-sample X, Y onto a fine *linear* q grid: the final integral
        # carries j_n(kq) oscillations that a log grid undersamples at
        # large q (X, Y themselves are smooth in log q)
        Xs = interpolate.InterpolatedUnivariateSpline(q, X, k=3)
        Ys = interpolate.InterpolatedUnivariateSpline(q, Y, k=3)
        qlin = np.linspace(1e-3, 2000.0, 1 << 16)
        self._q = qlin
        self._X = Xs(qlin)
        self._Y = Ys(qlin)

    def __call__(self, k):
        k = np.atleast_1d(np.asarray(k, dtype='f8'))
        q, X, Y = self._q, self._X, self._Y
        out = np.zeros_like(k)
        for i, kk in enumerate(k):
            if kk <= 0:
                continue
            damp = np.exp(-0.5 * kk ** 2 * (X + Y))
            plateau = np.exp(-kk ** 2 * self.sigma_psi2)
            kq = kk * q
            # n = 0 term with the plateau subtraction
            integ = (damp - plateau) * spherical_jn(0, kq)
            # higher-order tower
            fac = np.ones_like(q)
            kY_over_q = kk * Y / q
            for n in range(1, self.nmax + 1):
                fac = fac * kY_over_q
                term = damp * fac * spherical_jn(n, kq)
                integ = integ + term
                if np.max(np.abs(term * q ** 2)) < 1e-10 * max(
                        1e-30, np.max(np.abs(integ * q ** 2))):
                    break
            out[i] = 4 * np.pi * np.trapezoid(integ * q ** 2, q)
        return out if out.shape != (1,) else out[0]

    @property
    def sigma8(self):
        return self.linear.sigma8
