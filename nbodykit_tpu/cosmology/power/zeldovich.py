"""Zel'dovich-approximation power spectrum.

Reference surface: ``nbodykit/cosmology/power/zeldovich.py:27``
(ZeldovichPower), which evaluates the standard ZA resummation via a
tower of mcfit/FFTLog integrals (:186-238). Implemented here from the
published formulation (e.g. Vlah, White & Aviles 2015):

  X(q) = 1/(2 pi^2) int dk P_L(k) [2/3 - 2 j1(kq)/(kq)]
  Y(q) = 1/(2 pi^2) int dk P_L(k) [-2 j0(kq) + 6 j1(kq)/(kq)]

  P_ZA(k) = 4 pi int dq q^2 [ e^{-k^2 (X+Y)/2}
              sum_n (k Y(q) / q)^n j_n(kq)  -  e^{-k^2 sigma_psi^2} j0(kq) ]

where sigma_psi^2 = X(inf)/2 is the one-axis displacement dispersion;
the subtraction removes the unclustered (q -> inf) plateau. The n-sum is
truncated adaptively (the reference uses a similar truncation).

Validated by the low-k limit P_ZA -> P_L (tests/test_cosmology.py).
"""

import numpy as np
from scipy.special import spherical_jn
from scipy import interpolate

from .linear import LinearPower


class ZeldovichPower(object):
    """P_ZA(k) at a fixed redshift.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    transfer : transfer for the underlying LinearPower
    nmax : maximum order in the Bessel tower (default 32)
    """

    def __init__(self, cosmo, redshift, transfer='CLASS', nmax=32):
        self.cosmo = cosmo
        self.redshift = float(redshift)
        self.linear = LinearPower(cosmo, redshift, transfer=transfer)
        self.nmax = int(nmax)
        self.attrs = dict(self.linear.attrs)
        self._tables()

    def _tables(self):
        # k-grid for the linear power integrals
        lnk = np.linspace(np.log(1e-5), np.log(1e3), 2 ** 12)
        k = np.exp(lnk)
        P = self.linear(k)

        # q-grid for X, Y (smooth in log q; wide range so the final
        # transform can reach q ~ 1/k for low k)
        q = np.logspace(-2, 5, 1536)
        kq = np.outer(q, k)
        j0 = spherical_jn(0, kq)
        with np.errstate(invalid='ignore', divide='ignore'):
            j1_over = np.where(kq > 1e-8, spherical_jn(1, kq) / kq,
                               1.0 / 3.0)
        pref = 1.0 / (2 * np.pi ** 2)
        # integrate in dlnk: dk = k dlnk
        X = pref * np.trapezoid(P * k * (2.0 / 3 - 2 * j1_over), lnk,
                                axis=-1)
        Y = pref * np.trapezoid(P * k * (-2 * j0 + 6 * j1_over), lnk,
                                axis=-1)
        self.sigma_psi2 = pref * np.trapezoid(P * k / 3.0, lnk)
        # X, Y splines; the evaluation grid is built per k (linear
        # spacing resolving the j_n(kq) period; a fixed extent q_t is
        # enough because the q > q_t remainder is handled analytically)
        self._Xs = interpolate.InterpolatedUnivariateSpline(q, X, k=3)
        self._Ys = interpolate.InterpolatedUnivariateSpline(q, Y, k=3)

        # analytic linearized transform: expanding to first order in
        # the displacement correlators,
        #   (damp - plateau) j0 ~ plateau (-k^2/2) DW j0,
        #   damp (kY/q) j1    ~ plateau (kY/q) j1,
        # the ALL-q integrals evaluate in closed form via
        # Weber-Schafheitlin:
        #   n=0: plateau [P_L(k) - 2 k^2 int_k^inf P_L/k'^3 dk']
        #   n=1: plateau [        + 2 k^2 int_k^inf P_L/k'^3 dk']
        # so the linearized total is exactly plateau * P_L(k).  The
        # evaluation therefore combines the nonlinear-minus-linearized
        # integrand on (0, q_t] (whose slowly-decaying tails cancel)
        # with plateau * P_L(k).
        self._Plin_spl = interpolate.InterpolatedUnivariateSpline(
            lnk, P, k=3)

    _q_t = 4000.0

    def _qgrid(self, kk):
        period = 2 * np.pi / kk
        dq = min(period / 16.0, 1.5)
        n = min(int(self._q_t / dq), 1 << 19)
        return np.linspace(dq, self._q_t, n)

    def __call__(self, k):
        k = np.atleast_1d(np.asarray(k, dtype='f8'))
        out = np.zeros_like(k)
        for i, kk in enumerate(k):
            if kk <= 0:
                continue
            q = self._qgrid(kk)
            X = self._Xs(q)
            Y = self._Ys(q)
            DW = X + Y - 2.0 * self.sigma_psi2
            damp = np.exp(-0.5 * kk ** 2 * (X + Y))
            plateau = np.exp(-kk ** 2 * self.sigma_psi2)
            kq = kk * q
            j0 = spherical_jn(0, kq)
            # n = 0 and n = 1 minus their linearized versions
            lin0 = plateau * (-0.5 * kk * kk) * DW
            integ = (damp - plateau - lin0) * j0
            kY_over_q = kk * Y / q
            integ = integ + (damp - plateau) * kY_over_q \
                * spherical_jn(1, kq)
            # higher-order tower (support entirely within q_t)
            fac = kY_over_q.copy()
            for n in range(2, self.nmax + 1):
                fac = fac * kY_over_q
                term = damp * fac * spherical_jn(n, kq)
                integ = integ + term
                if np.max(np.abs(term * q ** 2)) < 1e-10 * max(
                        1e-30, np.max(np.abs(integ * q ** 2))):
                    break
            out[i] = 4 * np.pi * np.trapezoid(integ * q ** 2, q) \
                + plateau * float(self._Plin_spl(np.log(kk)))
        return out if out.shape != (1,) else out[0]

    @property
    def sigma8(self):
        return self.linear.sigma8
