from .linear import LinearPower, EHPower, NoWiggleEHPower
from .halofit import HalofitPower
from .zeldovich import ZeldovichPower

__all__ = ['LinearPower', 'EHPower', 'NoWiggleEHPower', 'HalofitPower',
           'ZeldovichPower']
