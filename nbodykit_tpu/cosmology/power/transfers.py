"""Matter transfer functions.

Reference: ``nbodykit/cosmology/power/transfers.py`` — CLASS (:8),
EisensteinHu (:73), NoWiggleEisensteinHu (:184). The CLASS transfer is
served by the in-repo Einstein-Boltzmann engine
(``cosmology/boltzmann.py``); the analytic Eisenstein & Hu 1998
(astro-ph/9709112) forms are implemented from the published paper.

All transfers are normalized to T -> 1 as k -> 0 and take k in h/Mpc.
"""

import numpy as np

available = ['CLASS', 'EisensteinHu', 'NoWiggleEisensteinHu']

# minimum k value representing k -> 0 (reference transfers.py:6)
KMIN = 1e-8


class CLASS(object):
    """The linear matter transfer from the Boltzmann engine:
    ``T(k) = sqrt(P_lin(k)/k^ns)`` normalized to unity at low k at
    z = 0 (reference transfers.py:9-73)."""

    def __init__(self, cosmo, redshift):
        self.cosmo = cosmo
        self._norm = 1.0
        self.redshift = 0
        self._norm = 1.0 / self(KMIN)
        self.redshift = redshift

    def __call__(self, k):
        k = np.asarray(k, dtype='f8')
        scalar = k.ndim == 0
        k = np.atleast_1d(k)
        nonzero = k > 0
        # P in (Mpc/h)^3 -> Mpc^3; primordial in 1/Mpc units
        linearP = self.cosmo.get_pklin(
            np.maximum(k, KMIN), self.redshift) / self.cosmo.h ** 3
        primordialP = (np.maximum(k, KMIN) * self.cosmo.h) \
            ** self.cosmo.n_s
        Tk = np.ones(k.shape)
        D = self.cosmo.scale_independent_growth_factor(self.redshift)
        Tk[~nonzero] = 1.0 * D
        Tk[nonzero] = self._norm * np.sqrt(
            np.maximum(linearP / primordialP, 0.0))[nonzero]
        return Tk[0] if scalar else Tk


class EisensteinHu(object):
    """Full Eisenstein & Hu 1998 transfer function with BAO wiggles."""

    def __init__(self, cosmo, redshift=0):
        self.cosmo = cosmo
        self.redshift = redshift

        h = cosmo.h
        Ob = cosmo.Omega0_b
        Om = cosmo.Omega0_b + cosmo.Omega0_cdm  # baryons + CDM
        self.Obh2 = Ob * h ** 2
        self.Omh2 = Om * h ** 2
        self.f_baryon = Ob / Om
        self.theta_cmb = cosmo.T0_cmb / 2.7

        # redshift and wavenumber of equality (EH98 eqs. 2-3)
        self.z_eq = 2.5e4 * self.Omh2 * self.theta_cmb ** -4
        self.k_eq = 0.0746 * self.Omh2 * self.theta_cmb ** -2  # 1/Mpc

        # drag epoch (eq. 4)
        b1 = 0.313 * self.Omh2 ** -0.419 * (1 + 0.607 * self.Omh2 ** 0.674)
        b2 = 0.238 * self.Omh2 ** 0.223
        self.z_drag = (1291 * self.Omh2 ** 0.251
                       / (1. + 0.659 * self.Omh2 ** 0.828)
                       * (1. + b1 * self.Obh2 ** b2))

        # sound horizon at drag (eqs. 5-6)
        self.r_drag = 31.5 * self.Obh2 * self.theta_cmb ** -4 \
            * (1000. / (1 + self.z_drag))
        self.r_eq = 31.5 * self.Obh2 * self.theta_cmb ** -4 \
            * (1000. / self.z_eq)
        self.sound_horizon = (2. / (3. * self.k_eq)
                              * np.sqrt(6. / self.r_eq)
                              * np.log((np.sqrt(1 + self.r_drag)
                                        + np.sqrt(self.r_drag + self.r_eq))
                                       / (1 + np.sqrt(self.r_eq))))
        # Silk damping (eq. 7)
        self.k_silk = (1.6 * self.Obh2 ** 0.52 * self.Omh2 ** 0.73
                       * (1 + (10.4 * self.Omh2) ** -0.95))  # 1/Mpc

        # CDM suppression (eqs. 11-12)
        a1 = (46.9 * self.Omh2) ** 0.670 \
            * (1 + (32.1 * self.Omh2) ** -0.532)
        a2 = (12.0 * self.Omh2) ** 0.424 \
            * (1 + (45.0 * self.Omh2) ** -0.582)
        self.alpha_c = a1 ** (-self.f_baryon) \
            * a2 ** (-self.f_baryon ** 3)
        b1c = 0.944 / (1 + (458 * self.Omh2) ** -0.708)
        b2c = (0.395 * self.Omh2) ** -0.0266
        self.beta_c = 1. / (1 + b1c * ((1 - self.f_baryon) ** b2c - 1))

        # baryon parameters (eqs. 14-15, 23-24)
        y = (1 + self.z_eq) / (1 + self.z_drag)
        Gy = y * (-6 * np.sqrt(1 + y)
                  + (2 + 3 * y) * np.log((np.sqrt(1 + y) + 1)
                                         / (np.sqrt(1 + y) - 1)))
        self.alpha_b = 2.07 * self.k_eq * self.sound_horizon \
            * (1 + self.r_drag) ** -0.75 * Gy
        self.beta_b = (0.5 + self.f_baryon
                       + (3 - 2 * self.f_baryon)
                       * np.sqrt((17.2 * self.Omh2) ** 2 + 1))
        self.beta_node = 8.41 * self.Omh2 ** 0.435

    def __call__(self, k):
        """T(k), k in h/Mpc."""
        k = np.asarray(k, dtype='f8') * self.cosmo.h  # to 1/Mpc
        out = np.ones_like(k)
        valid = k > 0
        kv = np.where(valid, k, 1.0)

        q = kv / (13.41 * self.k_eq)
        ks = kv * self.sound_horizon

        # CDM part (eqs. 17-20)
        def T0(q, alpha, beta):
            C = 14.2 / alpha + 386. / (1 + 69.9 * q ** 1.08)
            return (np.log(np.e + 1.8 * beta * q)
                    / (np.log(np.e + 1.8 * beta * q) + C * q * q))

        f = 1. / (1 + (ks / 5.4) ** 4)
        Tc = f * T0(q, 1.0, self.beta_c) \
            + (1 - f) * T0(q, self.alpha_c, self.beta_c)

        # baryon part (eq. 21)
        s_tilde = self.sound_horizon \
            / (1 + (self.beta_node / ks) ** 3) ** (1. / 3)
        with np.errstate(invalid='ignore'):
            j0 = np.sinc(kv * s_tilde / np.pi)
        Tb = (T0(q, 1.0, 1.0) / (1 + (ks / 5.2) ** 2)
              + self.alpha_b / (1 + (self.beta_b / ks) ** 3)
              * np.exp(-(kv / self.k_silk) ** 1.4)) * j0

        T = self.f_baryon * Tb + (1 - self.f_baryon) * Tc
        out = np.where(valid, T, 1.0)
        # reference transfers.py:182: growth applied inside the transfer
        return out * self.cosmo.scale_independent_growth_factor(
            self.redshift)


class NoWiggleEisensteinHu(object):
    """EH98 'no-wiggle' shape-only transfer (their section 4.2)."""

    def __init__(self, cosmo, redshift=0):
        self.cosmo = cosmo
        self.redshift = redshift
        h = cosmo.h
        Ob = cosmo.Omega0_b
        Om = cosmo.Omega0_b + cosmo.Omega0_cdm
        self.Obh2 = Ob * h ** 2
        self.Omh2 = Om * h ** 2
        self.f_baryon = Ob / Om
        self.theta_cmb = cosmo.T0_cmb / 2.7

        # approximate sound horizon (eq. 26), Mpc
        self.sound_horizon = (44.5 * np.log(9.83 / self.Omh2)
                              / np.sqrt(1 + 10 * self.Obh2 ** 0.75))
        # alpha_gamma (eq. 31)
        self.alpha_gamma = (1 - 0.328 * np.log(431 * self.Omh2)
                            * self.f_baryon
                            + 0.38 * np.log(22.3 * self.Omh2)
                            * self.f_baryon ** 2)

    def __call__(self, k):
        k = np.asarray(k, dtype='f8') * self.cosmo.h
        out = np.ones_like(k)
        valid = k > 0
        kv = np.where(valid, k, 1.0)
        ks = kv * self.sound_horizon / self.cosmo.h  # note: s in Mpc
        # effective shape (eqs. 28-30)
        gamma_eff = self.Omh2 / self.cosmo.h * (
            self.alpha_gamma + (1 - self.alpha_gamma)
            / (1 + (0.43 * kv * self.sound_horizon) ** 4))
        q = kv / self.cosmo.h * self.theta_cmb ** 2 / gamma_eff
        L0 = np.log(2 * np.e + 1.8 * q)
        C0 = 14.2 + 731.0 / (1 + 62.5 * q)
        T = L0 / (L0 + C0 * q * q)
        # reference transfers.py:255: growth applied inside the transfer
        return np.where(valid, T, 1.0) \
            * self.cosmo.scale_independent_growth_factor(self.redshift)


