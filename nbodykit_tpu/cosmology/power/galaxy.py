"""Galaxy power spectrum with primordial non-Gaussianity.

Reference: ``nbodykit/cosmology/power/galaxy.py:6`` (FNLGalaxyPower):
P_g(k) = (b1 + 2 f_NL (b1 - p) delta_c / alpha(k))^2 P_lin(k), with
alpha(k) = 2 k^2 T(k) D(z) c^2 / (3 Omega_m H0^2) relating density and
potential.
"""

import numpy as np

from .linear import LinearPower
from .transfers import EisensteinHu

DELTA_C = 1.686
C_KMS = 299792.458


class FNLGalaxyPower(object):
    """Biased galaxy power with scale-dependent fNL bias.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    b1 : linear bias
    fnl : local-type f_NL
    p : 1 (mass-selected) to 1.6 (recent mergers)
    """

    def __init__(self, cosmo, redshift, b1=2.0, fnl=0.0, p=1.0,
                 transfer='CLASS'):
        self.cosmo = cosmo
        self.redshift = float(redshift)
        self.b1 = b1
        self.fnl = fnl
        self.p = p
        self.linear = LinearPower(cosmo, redshift, transfer=transfer)
        self._transfer = self.linear._transfer
        self.attrs = dict(self.linear.attrs)
        self.attrs.update(b1=b1, fnl=fnl, p=p)

    def alpha(self, k):
        """The density-potential conversion alpha(k); growth normalized
        so D(a) = a in matter domination (the g(z) convention)."""
        k = np.asarray(k, dtype='f8')
        c = self.cosmo
        # the transfer classes apply D(redshift) internally
        # (transfers.py:144,187), so only the matter-domination
        # renormalization Dmd = D(z_md) (1+z_md) remains here
        z_md = 50.0
        Dmd = c.scale_independent_growth_factor(z_md) * (1 + z_md)
        g = Dmd
        T = self._transfer(k)
        H0 = 100.0  # h km/s/Mpc
        with np.errstate(divide='ignore'):
            out = 2.0 * k ** 2 * T * g * C_KMS ** 2 \
                / (3.0 * c.Omega0_m * H0 ** 2)
        return out

    def bias_k(self, k):
        """Total scale-dependent bias b(k)."""
        if self.fnl == 0:
            return self.b1 * np.ones_like(np.asarray(k, dtype='f8'))
        with np.errstate(divide='ignore'):
            db = (2.0 * self.fnl * (self.b1 - self.p) * DELTA_C
                  / self.alpha(k))
        return self.b1 + db

    def __call__(self, k):
        k = np.asarray(k, dtype='f8')
        return self.bias_k(k) ** 2 * self.linear(k)

    @property
    def sigma8(self):
        return self.linear.sigma8
