"""Linear matter power spectrum.

Reference: ``nbodykit/cosmology/power/linear.py:5`` (LinearPower) with
transfer selection and sigma8/sigma_r normalization machinery.
"""

import numpy as np
from scipy import integrate

from . import transfers as _transfers


class LinearPower(object):
    """P_lin(k) for a cosmology at a fixed redshift.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    transfer : 'EisensteinHu' (default here) | 'NoWiggleEisensteinHu' |
        'CLASS' (unavailable in this environment)

    The amplitude is set from A_s at construction; assigning
    :attr:`sigma8` rescales to match (reference semantics).
    """

    def __init__(self, cosmo, redshift, transfer='EisensteinHu'):
        self.cosmo = cosmo
        self.redshift = float(redshift)
        self.transfer = transfer
        cls = getattr(_transfers, transfer, None)
        if cls is None:
            raise ValueError("unknown transfer %r" % transfer)
        self._transfer = cls(cosmo, redshift)
        self._norm = 1.0
        self.attrs = dict(cosmo=dict(cosmo.attrs), redshift=redshift,
                          transfer=transfer)

        # amplitude from the primordial spectrum: the EH transfer already
        # encodes the shape; fix the normalization via sigma8 computed
        # from A_s using the standard primordial->matter relation, or
        # fall back to direct integration with an A_s-based prefactor.
        self._norm = 1.0
        self._sigma8_unnorm = self._sigma_r_unnorm(8.0)
        # A_s-based amplitude: sigma8^2 proportional to A_s; use the
        # growth-normalized approximation anchored to Planck-like
        # numbers (sigma8 ~ 0.83 at A_s ~ 2.1e-9 for Planck15 shape).
        sigma8_from_As = 0.8288 * np.sqrt(cosmo.A_s / 2.1e-9) \
            * self._shape_correction()
        self._norm = (sigma8_from_As / self._sigma8_unnorm) ** 2
        D = cosmo.scale_independent_growth_factor(self.redshift)
        self._norm *= D ** 2

    def _shape_correction(self):
        # mild adjustment for non-fiducial shapes: keep proportionality
        # exact in A_s; shape factors absorbed into sigma8 matching via
        # .sigma8 assignment when precision matters
        return 1.0

    def _unnorm_pk(self, k):
        k = np.asarray(k, dtype='f8')
        T = self._transfer(k)
        with np.errstate(divide='ignore'):
            pk = np.where(k > 0, k ** self.cosmo.n_s * T * T, 0.0)
        return pk

    def _sigma_r_unnorm(self, r):
        def integrand(lnk):
            k = np.exp(lnk)
            x = k * r
            w = 3.0 * (np.sin(x) - x * np.cos(x)) / x ** 3
            return self._unnorm_pk(k) * (w * k) ** 2 * k
        lnk = np.linspace(np.log(1e-5), np.log(100.0), 4096)
        vals = integrand(lnk)
        return np.sqrt(np.trapezoid(vals, lnk) / (2 * np.pi ** 2))

    @property
    def sigma8(self):
        """sigma8 at :attr:`redshift` under the current normalization."""
        return np.sqrt(self._norm) * self._sigma8_unnorm

    @sigma8.setter
    def sigma8(self, value):
        self._norm = (value / self._sigma8_unnorm) ** 2

    def sigma_r(self, r):
        """rms fluctuation in top-hat spheres of radius r Mpc/h."""
        return np.sqrt(self._norm) * self._sigma_r_unnorm(r)

    def __call__(self, k):
        """P(k) in (Mpc/h)^3, k in h/Mpc. Accepts numpy or jax arrays
        (computed in numpy on host; wrap with jnp.interp tables for
        in-graph use — see :meth:`to_table`)."""
        import jax.numpy as jnp
        if isinstance(k, jnp.ndarray) and not isinstance(k, np.ndarray):
            # build an interpolation table once and evaluate in-graph
            lnk_t, lnp_t = self.to_table()
            lk = jnp.log(jnp.maximum(k, 1e-30))
            out = jnp.exp(jnp.interp(lk, jnp.asarray(lnk_t),
                                     jnp.asarray(lnp_t)))
            return jnp.where(k > 0, out, 0.0)
        return self._norm * self._unnorm_pk(k)

    _table = None

    def to_table(self, kmin=1e-6, kmax=1e3, n=2048):
        """(ln k, ln P) table for in-graph interpolation."""
        if self._table is None:
            lnk = np.linspace(np.log(kmin), np.log(kmax), n)
            pk = self._norm * self._unnorm_pk(np.exp(lnk))
            self._table = (lnk, np.log(np.maximum(pk, 1e-300)))
        return self._table


def EHPower(cosmo, redshift):
    """Convenience: LinearPower with the wiggly EH transfer (the
    reference exposes the same helper)."""
    return LinearPower(cosmo, redshift, transfer='EisensteinHu')


def NoWiggleEHPower(cosmo, redshift):
    return LinearPower(cosmo, redshift, transfer='NoWiggleEisensteinHu')
