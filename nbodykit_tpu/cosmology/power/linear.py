"""Linear matter power spectrum.

Reference: ``nbodykit/cosmology/power/linear.py:5`` (LinearPower):
transfer selection ('CLASS' | 'EisensteinHu' | 'NoWiggleEisensteinHu'),
sigma8 normalization at z=0, assignable ``sigma8``/``redshift``.

Normalization:

- ``transfer='CLASS'``: the amplitude is ``cosmo.sigma8`` (computed
  from A_s by the Boltzmann engine), exactly the reference's scheme
  (``linear.py:57-63``: ``_norm = (sigma8/sigma_r(8, z=0))^2``).
- EH transfers: the reference still normalizes with the CLASS sigma8;
  here the EH path stays Boltzmann-free by computing the amplitude
  analytically from A_s via the exact matter-era relation
  ``delta_m(k) = (2/5) (k^2/(Omega_m H0^2)) T(k) D_md(z)`` with
  ``D_md`` the matter+Lambda growth normalized to ``a`` in matter
  domination.  This agrees with the Boltzmann sigma8 to within the
  EH transfer accuracy (a few percent).
"""

import numpy as np

from . import transfers as _transfers


class LinearPower(object):
    """P_lin(k) for a cosmology at a fixed redshift.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    transfer : 'CLASS' (default) | 'EisensteinHu' |
        'NoWiggleEisensteinHu'
    """

    def __init__(self, cosmo, redshift, transfer='CLASS'):
        if transfer not in _transfers.available:
            raise ValueError("'transfer' should be one of %s"
                             % _transfers.available)
        self.cosmo = cosmo
        self.transfer = transfer
        self._transfer = getattr(_transfers, transfer)(cosmo, redshift)
        # EH fallback for k beyond the CLASS table range
        self._fallback = _transfers.EisensteinHu(cosmo, redshift)
        self.attrs = dict(cosmo=dict(cosmo.attrs)
                          if hasattr(cosmo, 'attrs') else {},
                          redshift=redshift, transfer=transfer)

        self._norm = 1.0
        self._z = 0.0
        self._set_redshift(0.0)
        if transfer == 'CLASS':
            self._sigma8 = cosmo.sigma8
        else:
            self._sigma8 = self._As_sigma8()
        self._norm = (self._sigma8 / self.sigma_r(8.0)) ** 2
        self._set_redshift(redshift)
        self.attrs['sigma8'] = self._sigma8

    # -- A_s-based amplitude for the Boltzmann-free EH path ---------------

    def _As_sigma8(self):
        """sigma8 from A_s via the analytic matter-era normalization."""
        c = self.cosmo
        from ..background import MatterDominated
        md = MatterDominated(Omega0_m=c.Omega0_m,
                             Omega0_lambda=c.Omega0_lambda,
                             Omega0_k=c.Omega0_k)
        # D normalized to a in matter domination: D1 has D(1)=1, so
        # D_md(1) = a_early / D1(a_early)
        g0 = float(1e-3 / md.D1(1e-3))
        H0 = 1.0 / 2997.92458                # h/Mpc
        k_pivot = getattr(c, 'k_pivot', 0.05)

        from ..boltzmann import tophat_sigma
        k = np.exp(np.linspace(np.log(1e-5), np.log(20.0), 4096))
        T = self._fallback(k)
        prim = c.A_s * (k * c.h / k_pivot) ** (c.n_s - 1.0)
        delta = 0.4 * (k * k / (c.Omega0_m * H0 * H0)) * T * g0
        # k in h/Mpc throughout -> P directly in (Mpc/h)^3
        pk = 2 * np.pi ** 2 / k ** 3 * prim * delta ** 2
        return tophat_sigma(k, pk, 8.0)

    # -- redshift / sigma8 surgery (reference semantics) ------------------

    def _set_redshift(self, z):
        self._z = float(z)
        self._transfer.redshift = self._z
        self._fallback.redshift = self._z

    @property
    def redshift(self):
        return self._z

    @redshift.setter
    def redshift(self, value):
        self._set_redshift(value)
        self.attrs['redshift'] = value
        self._table = None

    @property
    def sigma8(self):
        """The z=0 amplitude; assigning rescales the spectrum."""
        return self._sigma8

    @sigma8.setter
    def sigma8(self, value):
        self._norm *= (value / self._sigma8) ** 2
        self._sigma8 = value
        self.attrs['sigma8'] = value
        self._table = None

    # -- evaluation --------------------------------------------------------

    def _unnorm_pk(self, k, z):
        """k^ns T(k, z)^2 with EH fallback beyond the table range."""
        k = np.asarray(k, dtype='f8')
        save = self._z
        if z != save:
            self._set_redshift(z)
        try:
            if self.transfer == 'CLASS':
                kmax = getattr(self.cosmo, 'P_k_max', np.inf)
                T = np.where(k < 0.999 * kmax, self._transfer(k),
                             np.nan)
                bad = ~np.isfinite(T)
                if np.any(bad):
                    # continuity-matched EH fallback at high k
                    kj = 0.999 * kmax
                    ratio = self._transfer(kj) / self._fallback(kj)
                    T = np.where(bad, self._fallback(k) * ratio, T)
            else:
                T = self._transfer(k)
        finally:
            if z != save:
                self._set_redshift(save)
        with np.errstate(divide='ignore'):
            return np.where(k > 0, k ** self.cosmo.n_s * T * T, 0.0)

    def sigma_r(self, r, kmin=1e-5, kmax=1e1):
        """rms fluctuation in top-hat spheres of radius r Mpc/h at
        :attr:`redshift` (reference linear.py sigma_r)."""
        from ..boltzmann import tophat_sigma
        k = np.exp(np.linspace(np.log(kmin), np.log(kmax), 2048))
        return tophat_sigma(k, self._norm * self._unnorm_pk(k, self._z),
                            r)

    def velocity_dispersion(self, kmin=1e-5, kmax=10.0):
        """1D linear velocity dispersion sigma_v in Mpc/h:
        sigma_v^2 = (1/6 pi^2) int P(k) dk (reference linear.py
        velocity_dispersion)."""
        lnk = np.linspace(np.log(kmin), np.log(kmax), 2048)
        k = np.exp(lnk)
        pk = self._norm * self._unnorm_pk(k, self._z)
        val = np.trapezoid(pk * k, lnk) / (6 * np.pi ** 2)
        return float(np.sqrt(val))

    def __call__(self, k):
        """P(k) in (Mpc/h)^3, k in h/Mpc. Accepts numpy or jax arrays
        (jax arrays are evaluated via an interpolation table)."""
        import jax.numpy as jnp
        if isinstance(k, jnp.ndarray) and not isinstance(k, np.ndarray):
            lnk_t, lnp_t = self.to_table()
            lk = jnp.log(jnp.maximum(k, 1e-30))
            out = jnp.exp(jnp.interp(lk, jnp.asarray(lnk_t),
                                     jnp.asarray(lnp_t)))
            return jnp.where(k > 0, out, 0.0)
        return self._norm * self._unnorm_pk(k, self._z)

    _table = None

    def to_table(self, kmin=1e-6, kmax=1e3, n=2048):
        """(ln k, ln P) table for in-graph interpolation."""
        if self._table is None:
            lnk = np.linspace(np.log(kmin), np.log(kmax), n)
            pk = self._norm * self._unnorm_pk(np.exp(lnk), self._z)
            self._table = (lnk, np.log(np.maximum(pk, 1e-300)))
        return self._table


def EHPower(cosmo, redshift):
    """Deprecated alias: LinearPower with the wiggly EH transfer
    (reference linear.py:200)."""
    import warnings
    warnings.warn("EHPower is deprecated; use "
                  "LinearPower(transfer='EisensteinHu')", FutureWarning)
    return LinearPower(cosmo, redshift, transfer='EisensteinHu')


def NoWiggleEHPower(cosmo, redshift):
    import warnings
    warnings.warn("NoWiggleEHPower is deprecated; use "
                  "LinearPower(transfer='NoWiggleEisensteinHu')",
                  FutureWarning)
    return LinearPower(cosmo, redshift, transfer='NoWiggleEisensteinHu')
