"""Nonlinear matter power via the Halofit fitting formula.

Reference surface: ``nbodykit/cosmology/power/halofit.py:3``
(HalofitPower). Implemented from the published formulas: Smith et al.
2003 (astro-ph/0207664) with the Takahashi et al. 2012 (1208.2701)
revision (the same variant CLASS/CAMB use).
"""

import numpy as np
from scipy import optimize

from .linear import LinearPower


class HalofitPower(object):
    """P_nl(k) at a fixed redshift from a LinearPower via halofit.

    Parameters
    ----------
    cosmo : Cosmology
    redshift : float
    linear : optional LinearPower to reuse (else built with the default
        transfer)
    """

    def __init__(self, cosmo, redshift, linear=None):
        self.cosmo = cosmo
        self.redshift = float(redshift)
        self.linear = linear if linear is not None else \
            LinearPower(cosmo, redshift)
        self.attrs = dict(self.linear.attrs)

        # integral quantities of the linear spectrum with a Gaussian
        # window: sigma^2(R) = int dlnk Delta^2_L(k) e^{-k^2 R^2}
        lnk = np.linspace(np.log(1e-5), np.log(1e3), 2 ** 12)
        k = np.exp(lnk)
        D2 = self.linear(k) * k ** 3 / (2 * np.pi ** 2)

        def sigma2(R):
            return np.trapezoid(D2 * np.exp(-(k * R) ** 2), lnk)

        # nonlinear scale: sigma(1/ksigma) == 1
        try:
            lnR = optimize.brentq(
                lambda lr: np.log(sigma2(np.exp(lr))), np.log(1e-4),
                np.log(1e3))
        except ValueError:
            # sigma^2 < 1 everywhere: fully linear regime
            self._linear_only = True
            return
        self._linear_only = False
        R = np.exp(lnR)
        self.ksigma = 1.0 / R

        # effective index and curvature at the nonlinear scale
        eps = 1e-3
        lns = np.log([sigma2(R * np.exp(-eps)), sigma2(R),
                      sigma2(R * np.exp(eps))])
        dlns = (lns[2] - lns[0]) / (2 * eps)
        d2lns = (lns[2] - 2 * lns[1] + lns[0]) / eps ** 2
        self.neff = -3.0 - dlns
        self.C = -d2lns

        om = cosmo.Omega_m(redshift)
        ol = 1.0 - om  # flat approximation for the fit's Omega_L(z)
        w = cosmo.w0_fld
        n, C = self.neff, self.C

        # Takahashi 2012 coefficients (their eqs. A6-A13)
        self.an = 10 ** (1.5222 + 2.8553 * n + 2.3706 * n ** 2
                         + 0.9903 * n ** 3 + 0.2250 * n ** 4
                         - 0.6038 * C + 0.1749 * ol * (1 + w))
        self.bn = 10 ** (-0.5642 + 0.5864 * n + 0.5716 * n ** 2
                         - 1.5474 * C + 0.2279 * ol * (1 + w))
        self.cn = 10 ** (0.3698 + 2.0404 * n + 0.8161 * n ** 2
                         + 0.5869 * C)
        self.gamman = 0.1971 - 0.0843 * n + 0.8460 * C
        self.alphan = abs(6.0835 + 1.3373 * n - 0.1959 * n ** 2
                          - 5.5274 * C)
        self.betan = (2.0379 - 0.7354 * n + 0.3157 * n ** 2
                      + 1.2490 * n ** 3 + 0.3980 * n ** 4 - 0.1682 * C)
        self.mun = 0.0
        self.nun = 10 ** (5.2105 + 3.6902 * n)
        f1 = om ** -0.0307
        f2 = om ** -0.0585
        f3 = om ** 0.0743
        self.f1, self.f2, self.f3 = f1, f2, f3

    def __call__(self, k):
        k = np.asarray(k, dtype='f8')
        PL = self.linear(k)
        if self._linear_only:
            return PL
        D2L = PL * k ** 3 / (2 * np.pi ** 2)
        y = k / self.ksigma

        # two-halo (quasi-linear) term
        fy = y / 4.0 + y ** 2 / 8.0
        D2Q = D2L * ((1 + D2L) ** self.betan
                     / (1 + self.alphan * D2L)) * np.exp(-fy)

        # one-halo term
        with np.errstate(divide='ignore', invalid='ignore'):
            D2Hp = (self.an * y ** (3 * self.f1)
                    / (1 + self.bn * y ** self.f2
                       + (self.cn * self.f3 * y) ** (3 - self.gamman)))
            D2H = D2Hp / (1 + self.mun / y + self.nun / y ** 2)
        D2H = np.where(y > 0, D2H, 0.0)

        D2NL = D2Q + D2H
        with np.errstate(divide='ignore', invalid='ignore'):
            out = np.where(k > 0, D2NL * (2 * np.pi ** 2) / k ** 3, 0.0)
        return out

    @property
    def sigma8(self):
        return self.linear.sigma8
